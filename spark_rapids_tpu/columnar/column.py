"""Device-resident columnar vectors — the TPU analog of the reference's
GpuColumnVector (sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:40)
over ai.rapids.cudf.ColumnVector.

Design (TPU-first, NOT a cuDF translation):
  * XLA requires static shapes, so every column is padded to a *capacity
    bucket* (powers of two, >= 128 to match TPU lane width). The logical row
    count rides next to the data as a device scalar so that filters/joins that
    change row counts do NOT change array shapes and therefore do NOT trigger
    recompilation. This replaces cuDF's exact-length device buffers.
  * Validity is a dense bool array (not a bitmask): TPUs are vector machines,
    predication via bool arrays fuses into elementwise ops for free, and XLA
    packs bools on device. Rows at index >= num_rows are always invalid.
  * Strings/binary use Arrow-style (offsets, bytes) twin arrays with the byte
    buffer padded to its own bucket. There is no ragged tensor support in XLA;
    all varlen kernels are written against this encoding.
  * Columns are registered pytrees, so whole query pipelines (chains of
    operators) jit end-to-end and XLA fuses across operator boundaries —
    something the reference could never do across separate cuDF calls.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (
    ArrayType, BinaryType, BooleanType, DataType, DecimalType, NullType,
    Schema, StringType, StructField, StructType, from_arrow, to_arrow,
)

#: minimum capacity bucket — one TPU lane row
MIN_BUCKET = 128

#: host-build mode (ISSUE 10): inside `host_build()` every constructor
#: lane keeps its buffers as numpy instead of uploading them one
#: jnp.asarray at a time, so the packed upload engine
#: (columnar/upload.py) can ship the whole batch as ONE transfer
_BUILD_TLS = threading.local()


def _dev(x):
    """Constructor-lane leaf placement: device by default, numpy under
    host_build() (the packed-upload staging mode)."""
    if getattr(_BUILD_TLS, "host", False):
        return x if isinstance(x, np.ndarray) else np.asarray(x)
    return jnp.asarray(x)


@contextmanager
def host_build():
    """Build columns with numpy-resident buffers (no per-buffer device
    uploads); promote the finished batch through columnar/upload.py."""
    prev = getattr(_BUILD_TLS, "host", False)
    _BUILD_TLS.host = True
    try:
        yield
    finally:
        _BUILD_TLS.host = prev


def _pad_tail(arr, extra: int):
    """Zero-pad the leading axis by `extra` slots, staying numpy for
    numpy inputs (host-built columns must not silently hop to device)."""
    pad = [(0, extra)] + [(0, 0)] * (arr.ndim - 1)
    if isinstance(arr, np.ndarray):
        return np.pad(arr, pad)
    return jnp.pad(arr, pad)


def _extend_offsets(off, extra: int):
    """Repeat the final offset `extra` times (zero-length padding rows),
    numpy-in numpy-out."""
    if isinstance(off, np.ndarray):
        return np.concatenate([off, np.full(extra, off[-1], off.dtype)])
    return jnp.concatenate(
        [off, jnp.broadcast_to(off[-1], (extra,))])


def _logical_to_physical(dtype: DataType):
    """Value converter for host ingestion: accept the *logical* Python
    values Spark's rows carry (datetime.date, datetime.datetime,
    decimal.Decimal) alongside the raw physical encodings (int days /
    micros / unscaled)."""
    import datetime as _dt
    import decimal as _dec

    from ..types import DateType, DecimalType, TimestampNTZType, TimestampType
    if isinstance(dtype, DateType):
        epoch = _dt.date(1970, 1, 1)
        return lambda v: (v - epoch).days if isinstance(v, _dt.date) \
            and not isinstance(v, _dt.datetime) else v
    if isinstance(dtype, (TimestampType, TimestampNTZType)):
        epoch = _dt.datetime(1970, 1, 1)
        one_us = _dt.timedelta(microseconds=1)
        ntz = isinstance(dtype, TimestampNTZType)

        def conv_ts(v):
            if not isinstance(v, _dt.datetime):
                return v
            if v.tzinfo is not None:
                # NTZ keeps the wall clock; TIMESTAMP converts the instant
                v = v.replace(tzinfo=None) if ntz \
                    else v.astimezone(_dt.timezone.utc).replace(tzinfo=None)
            return (v - epoch) // one_us
        return conv_ts
    if isinstance(dtype, DecimalType):
        scale = dtype.scale
        return lambda v: int(v.scaleb(scale).to_integral_value(
            rounding=_dec.ROUND_HALF_UP)) \
            if isinstance(v, _dec.Decimal) else v
    return lambda v: v


def bucket_capacity(n: int) -> int:
    """Round row/byte counts up to a shape bucket to bound XLA recompiles.

    Replaces the reference's exact-size allocations; the 1 GiB target batch
    size of the reference (RapidsConf.scala:559 batchSizeBytes) becomes a
    target *padded* bucket here.
    """
    if n <= MIN_BUCKET:
        return MIN_BUCKET
    return 1 << (int(n - 1).bit_length())


def _pad_np(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    if arr.shape[0] == capacity:
        return arr
    out = np.full((capacity,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


class Column:
    """Fixed-width device column: data (capacity,) + validity (capacity,) bool."""

    __slots__ = ("data", "validity", "dtype")

    def __init__(self, data, validity, dtype: DataType):
        self.data = data
        self.validity = validity
        self.dtype = dtype

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_numpy(values: np.ndarray, dtype: DataType,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        n = values.shape[0]
        cap = capacity or bucket_capacity(n)
        if validity is None:
            validity = np.ones(n, dtype=np.bool_)
        data = _pad_np(np.ascontiguousarray(values, dtype=dtype.jnp_dtype), cap)
        valid = _pad_np(validity.astype(np.bool_), cap, fill=False)
        return Column(_dev(data), _dev(valid), dtype)

    @staticmethod
    def from_pylist(values: Sequence, dtype: DataType,
                    capacity: Optional[int] = None) -> "Column":
        n = len(values)
        validity = np.array([v is not None for v in values], dtype=np.bool_)
        fill = np.zeros((), dtype=dtype.jnp_dtype).item()
        conv = _logical_to_physical(dtype)
        dense = np.array([fill if v is None else conv(v) for v in values],
                         dtype=dtype.jnp_dtype)
        return Column.from_numpy(dense, dtype, validity, capacity)

    # -- shape -------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def with_capacity(self, capacity: int) -> "Column":
        """Grow (never shrink) the padding bucket."""
        cap = self.capacity
        if capacity == cap:
            return self
        assert capacity > cap, (capacity, cap)
        extra = capacity - cap
        return Column(_pad_tail(self.data, extra),
                      _pad_tail(self.validity, extra), self.dtype)

    # -- host materialization (test/debug surface) -------------------------
    def to_pylist(self, num_rows: int) -> List:
        data = np.asarray(self.data[:num_rows])
        valid = np.asarray(self.validity[:num_rows])
        return [data[i].item() if valid[i] else None for i in range(num_rows)]

    def __repr__(self):
        return f"Column({self.dtype!r}, cap={self.capacity})"


class StringColumn(Column):
    """Varlen column: uint8 byte buffer + int32 offsets (Arrow layout).

    offsets has shape (capacity+1,); for rows >= num_rows offsets repeat so
    lengths are zero. The byte buffer is padded to its own bucket.
    """

    __slots__ = ("offsets",)

    def __init__(self, data, offsets, validity, dtype: DataType = StringType()):
        super().__init__(data, validity, dtype)
        self.offsets = offsets

    @staticmethod
    def from_pylist(values: Sequence[Optional[str]],
                    capacity: Optional[int] = None,
                    dtype: DataType = StringType()) -> "StringColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        raw = [b"" if v is None else (v.encode("utf-8") if isinstance(v, str) else bytes(v))
               for v in values]
        lengths = np.array([len(b) for b in raw], dtype=np.int32)
        offsets = np.zeros(cap + 1, dtype=np.int32)
        np.cumsum(lengths, out=offsets[1 : n + 1])
        offsets[n + 1 :] = offsets[n]
        total = int(offsets[n])
        byte_cap = bucket_capacity(max(total, 1))
        data = np.zeros(byte_cap, dtype=np.uint8)
        if total:
            data[:total] = np.frombuffer(b"".join(raw), dtype=np.uint8)
        validity = _pad_np(np.array([v is not None for v in values], dtype=np.bool_),
                           cap, fill=False)
        return StringColumn(_dev(data), _dev(offsets), _dev(validity), dtype)

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def byte_capacity(self) -> int:
        return int(self.data.shape[0])

    def with_capacity(self, capacity: int) -> "StringColumn":
        cap = self.capacity
        if capacity == cap:
            return self
        assert capacity > cap
        extra = capacity - cap
        offsets = _extend_offsets(self.offsets, extra)
        validity = _pad_tail(self.validity, extra)
        return StringColumn(self.data, offsets, validity, self.dtype)

    def with_byte_capacity(self, byte_capacity: int) -> "StringColumn":
        """Grow (never shrink) the byte-buffer bucket."""
        if byte_capacity == self.byte_capacity:
            return self
        assert byte_capacity > self.byte_capacity
        data = _pad_tail(self.data, byte_capacity - self.byte_capacity)
        return StringColumn(data, self.offsets, self.validity, self.dtype)

    def to_pylist(self, num_rows: int) -> List[Optional[str]]:
        data = np.asarray(self.data)
        offsets = np.asarray(self.offsets)
        valid = np.asarray(self.validity)
        out: List[Optional[str]] = []
        binary = isinstance(self.dtype, BinaryType)
        for i in range(num_rows):
            if not valid[i]:
                out.append(None)
            else:
                b = data[offsets[i] : offsets[i + 1]].tobytes()
                out.append(b if binary else b.decode("utf-8"))
        return out

    def __repr__(self):
        return f"StringColumn(cap={self.capacity}, bytes={self.byte_capacity})"


class StructColumn(Column):
    """Struct column: children stored side by side; no data buffer of its own."""

    __slots__ = ("children",)

    def __init__(self, children: Tuple[Column, ...], validity, dtype: StructType):
        super().__init__(None, validity, dtype)
        self.children = tuple(children)

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    def with_capacity(self, capacity: int) -> "StructColumn":
        """Grow (never shrink) the padding bucket, recursing into the
        children; type(self)(...) keeps Decimal128Column intact."""
        cap = self.capacity
        if capacity == cap:
            return self
        assert capacity > cap, (capacity, cap)
        return type(self)(tuple(c.with_capacity(capacity)
                                for c in self.children),
                          _pad_tail(self.validity, capacity - cap),
                          self.dtype)

    @staticmethod
    def from_pylist(values: Sequence, dtype: StructType,
                    capacity: Optional[int] = None) -> "StructColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = _pad_np(np.array([v is not None for v in values],
                                    np.bool_), cap, False)
        kids = []
        for f in dtype.fields:
            fv = [None if v is None else
                  (v.get(f.name) if isinstance(v, dict)
                   else getattr(v, f.name)) for v in values]
            kids.append(build_column(fv, f.data_type, cap))
        return StructColumn(tuple(kids), _dev(validity), dtype)

    def to_pylist(self, num_rows: int) -> List:
        valid = np.asarray(self.validity[:num_rows])
        kids = [c.to_pylist(num_rows) for c in self.children]
        names = [f.name for f in self.dtype.fields]
        return [
            {n: k[i] for n, k in zip(names, kids)} if valid[i] else None
            for i in range(num_rows)
        ]


class Decimal128Column(StructColumn):
    """DECIMAL(p>18): 128-bit unscaled value as two int64 limb children
    (hi with the sign, lo reinterpreted unsigned). Subclasses
    StructColumn so every structural path (gather/sanitize/transfer/
    serialize) recurses into the limbs unchanged; reconstruction sites
    rebuild via type(col)(...) so the class is preserved.
    Reference analog: cuDF decimal128 under DecimalUtil.scala."""

    def __init__(self, children, validity, dtype: DecimalType):
        assert len(children) == 2
        super().__init__(children, validity, dtype)

    @property
    def hi(self) -> Column:
        return self.children[0]

    @property
    def lo(self) -> Column:
        return self.children[1]

    @staticmethod
    def from_limbs(hi, lo, validity, dtype: DecimalType
                   ) -> "Decimal128Column":
        from ..types import LONG
        return Decimal128Column(
            (Column(hi, validity, LONG), Column(lo, validity, LONG)),
            validity, dtype)

    @staticmethod
    def from_pylist(values: Sequence, dtype: DecimalType,
                    capacity: Optional[int] = None) -> "Decimal128Column":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        conv = _logical_to_physical(dtype)
        validity = np.array([v is not None for v in values], np.bool_)
        his = np.zeros(n, np.int64)
        los = np.zeros(n, np.int64)
        for i, v in enumerate(values):
            if v is None:
                continue
            u = int(conv(v)) & ((1 << 128) - 1)
            lo = u & ((1 << 64) - 1)
            hi = u >> 64
            los[i] = lo - (1 << 64) if lo >= (1 << 63) else lo
            his[i] = hi - (1 << 64) if hi >= (1 << 63) else hi
        vpad = _dev(_pad_np(validity, cap, False))
        from ..types import LONG
        return Decimal128Column(
            (Column(_dev(_pad_np(his, cap)), vpad, LONG),
             Column(_dev(_pad_np(los, cap)), vpad, LONG)),
            vpad, dtype)

    def to_pylist(self, num_rows: int) -> List:
        """Unscaled 128-bit ints (arbitrary-precision Python ints)."""
        hi = np.asarray(self.hi.data[:num_rows])
        lo = np.asarray(self.lo.data[:num_rows])
        valid = np.asarray(self.validity[:num_rows])
        out: List = []
        for i in range(num_rows):
            if not valid[i]:
                out.append(None)
                continue
            u = ((int(hi[i]) & ((1 << 64) - 1)) << 64) \
                | (int(lo[i]) & ((1 << 64) - 1))
            out.append(u - (1 << 128) if u >= (1 << 127) else u)
        return out


class ArrayColumn(Column):
    """List column: int32 offsets into a child column."""

    __slots__ = ("offsets", "child")

    def __init__(self, child: Column, offsets, validity, dtype: ArrayType):
        super().__init__(None, validity, dtype)
        self.child = child
        self.offsets = offsets

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def child_capacity(self) -> int:
        return self.child.capacity

    @staticmethod
    def from_pylist(values: Sequence, dtype: ArrayType,
                    capacity: Optional[int] = None) -> "ArrayColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = _pad_np(np.array([v is not None for v in values],
                                    np.bool_), cap, False)
        lengths = np.array([0 if v is None else len(v) for v in values],
                           np.int32)
        off = np.zeros(cap + 1, np.int32)
        np.cumsum(lengths, out=off[1:n + 1])
        off[n + 1:] = off[n] if n else 0
        flat = [x for v in values if v is not None for x in v]
        child = build_column(flat, dtype.element_type)
        return ArrayColumn(child, _dev(off), _dev(validity), dtype)

    def to_pylist(self, num_rows: int) -> List:
        offsets = np.asarray(self.offsets)
        valid = np.asarray(self.validity[:num_rows])
        child_n = int(offsets[num_rows]) if num_rows else 0
        kid = self.child.to_pylist(child_n)
        return [
            kid[offsets[i] : offsets[i + 1]] if valid[i] else None
            for i in range(num_rows)
        ]


class MapColumn(Column):
    """Map column: int32 offsets + parallel keys/values child columns
    (the cuDF lists-of-structs layout with the struct unzipped — keys and
    values as SEPARATE columns vectorize lookups without interleaving).
    Reference analog: cuDF LIST<STRUCT<K,V>> under GpuCreateMap /
    GpuGetMapValue (collectionOperations.scala, GpuMapUtils)."""

    __slots__ = ("offsets", "keys", "values")

    def __init__(self, keys: Column, values: Column, offsets, validity,
                 dtype):
        super().__init__(None, validity, dtype)
        self.keys = keys
        self.values = values
        self.offsets = offsets

    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def entry_capacity(self) -> int:
        return self.keys.capacity

    @staticmethod
    def from_pylist(values: Sequence, dtype,
                    capacity: Optional[int] = None) -> "MapColumn":
        n = len(values)
        cap = capacity or bucket_capacity(n)
        validity = _pad_np(np.array([v is not None for v in values],
                                    np.bool_), cap, False)
        lengths = np.array([0 if v is None else len(v) for v in values],
                           np.int32)
        off = np.zeros(cap + 1, np.int32)
        np.cumsum(lengths, out=off[1:n + 1])
        off[n + 1:] = off[n] if n else 0
        items = [(k, x) for v in values if v is not None
                 for k, x in (v.items() if isinstance(v, dict) else v)]
        keys = build_column([k for k, _ in items], dtype.key_type)
        vals = build_column([x for _, x in items], dtype.value_type)
        # keys and values index in lockstep by construction
        assert keys.capacity == vals.capacity
        return MapColumn(keys, vals, _dev(off), _dev(validity), dtype)

    def with_capacity(self, capacity: int) -> "MapColumn":
        cap = self.capacity
        if capacity == cap:
            return self
        assert capacity > cap, (capacity, cap)
        extra = capacity - cap
        offsets = _extend_offsets(self.offsets, extra)
        validity = _pad_tail(self.validity, extra)
        return MapColumn(self.keys, self.values, offsets, validity,
                         self.dtype)

    def to_pylist(self, num_rows: int) -> List:
        offsets = np.asarray(self.offsets)
        valid = np.asarray(self.validity[:num_rows])
        entry_n = int(offsets[num_rows]) if num_rows else 0
        ks = self.keys.to_pylist(entry_n)
        vs = self.values.to_pylist(entry_n)
        out = []
        for i in range(num_rows):
            if not valid[i]:
                out.append(None)
                continue
            d = {}
            for k, v in zip(ks[offsets[i]: offsets[i + 1]],
                            vs[offsets[i]: offsets[i + 1]]):
                if k not in d:  # FIRST duplicate key wins, like map_get
                    d[k] = v
            out.append(d)
        return out


def build_column(values: Sequence, dtype: DataType,
                 capacity: Optional[int] = None) -> Column:
    """Host-list → column of the right class for any supported type,
    recursing through nested arrays/structs/maps."""
    from ..types import MapType
    if isinstance(dtype, DecimalType) and dtype.precision > 18:
        return Decimal128Column.from_pylist(values, dtype, capacity)
    if isinstance(dtype, ArrayType):
        return ArrayColumn.from_pylist(values, dtype, capacity)
    if isinstance(dtype, MapType):
        return MapColumn.from_pylist(values, dtype, capacity)
    if isinstance(dtype, StructType):
        return StructColumn.from_pylist(values, dtype, capacity)
    if isinstance(dtype, StringType) or dtype.jnp_dtype is None:
        return StringColumn.from_pylist(values, capacity, dtype=dtype)
    return Column.from_pylist(values, dtype, capacity)


# --- pytree registration: columns flow through jit/shard_map -------------

def _column_flatten(c: Column):
    return (c.data, c.validity), c.dtype


def _column_unflatten(dtype, children):
    data, validity = children
    return Column(data, validity, dtype)


def _string_flatten(c: StringColumn):
    return (c.data, c.offsets, c.validity), c.dtype


def _string_unflatten(dtype, children):
    data, offsets, validity = children
    return StringColumn(data, offsets, validity, dtype)


def _struct_flatten(c: StructColumn):
    return (c.children, c.validity), c.dtype


def _struct_unflatten(dtype, children):
    kids, validity = children
    return StructColumn(tuple(kids), validity, dtype)


def _array_flatten(c: ArrayColumn):
    return (c.child, c.offsets, c.validity), c.dtype


def _array_unflatten(dtype, children):
    child, offsets, validity = children
    return ArrayColumn(child, offsets, validity, dtype)


def _map_flatten(c: MapColumn):
    return (c.keys, c.values, c.offsets, c.validity), c.dtype


def _map_unflatten(dtype, children):
    keys, values, offsets, validity = children
    return MapColumn(keys, values, offsets, validity, dtype)


def _dec128_unflatten(dtype, children):
    kids, validity = children
    return Decimal128Column(tuple(kids), validity, dtype)


jax.tree_util.register_pytree_node(Column, _column_flatten, _column_unflatten)
jax.tree_util.register_pytree_node(StringColumn, _string_flatten, _string_unflatten)
jax.tree_util.register_pytree_node(StructColumn, _struct_flatten, _struct_unflatten)
jax.tree_util.register_pytree_node(ArrayColumn, _array_flatten, _array_unflatten)
jax.tree_util.register_pytree_node(MapColumn, _map_flatten, _map_unflatten)
jax.tree_util.register_pytree_node(Decimal128Column, _struct_flatten,
                                   _dec128_unflatten)


def _string_from_arrow_buffers(arr, dt: DataType, n: int) -> StringColumn:
    """Arrow string/binary array -> device column straight from the Arrow
    (validity bitmap, offsets, bytes) buffers — no per-value Python loop
    (review finding r1: `to_pylist` dominated string-heavy scans).

    ISSUE 10 satellite: offsets and data each copy out of the Arrow
    snapshot exactly ONCE, straight into their padded buffers (the old
    lane materialized offsets twice — astype then rebase — before the
    padded copy, a host-side double-copy on every string scan batch)."""
    import pyarrow as pa

    if pa.types.is_large_string(arr.type):
        arr = arr.cast(pa.string())
    elif pa.types.is_large_binary(arr.type):
        arr = arr.cast(pa.binary())
    bufs = arr.buffers()
    # ONE zero-copy snapshot of the Arrow offsets; the single copy below
    # lands them in the padded buffer, where the rebase runs in place
    off_all = np.frombuffer(bufs[1], dtype=np.int32)
    cap = bucket_capacity(n)
    off_padded = np.empty(cap + 1, dtype=np.int32)
    off_padded[: n + 1] = off_all[arr.offset: arr.offset + n + 1]
    base = int(off_padded[0]) if n else 0
    if base:
        off_padded[: n + 1] -= base
    total = int(off_padded[n]) if n else 0
    off_padded[n + 1:] = total
    byte_cap = bucket_capacity(max(total, 1))
    data = np.zeros(byte_cap, dtype=np.uint8)
    if total:
        # ONE copy out of the shared bytes snapshot (frombuffer is a view)
        data[:total] = np.frombuffer(bufs[2], dtype=np.uint8,
                                     count=total, offset=base)
    if bufs[0] is None:
        validity = np.ones(n, dtype=np.bool_)
    else:
        bits = np.frombuffer(bufs[0], dtype=np.uint8)
        validity = np.unpackbits(bits, bitorder="little")[
            arr.offset: arr.offset + n].astype(np.bool_)
    # Arrow permits null slots with non-zero spans; the engine's length
    # kernels promise 0 for nulls — rebuild through the slow path in that
    # (rare in practice) case
    if n and not validity.all():
        lens_np = np.diff(off_padded[: n + 1])
        if (lens_np[~validity] != 0).any():
            return StringColumn.from_pylist(arr.to_pylist(), dtype=dt)
    return StringColumn(_dev(data), _dev(off_padded),
                        _dev(_pad_np(validity, cap, False)), dt)


def column_from_arrow(arr, dtype: Optional[DataType] = None) -> Column:
    """pyarrow Array/ChunkedArray -> device column."""
    import pyarrow as pa

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type):
        # ISSUE 18: keep Parquet dictionary columns encoded (a
        # DictionaryColumn code lane + payload) instead of eagerly
        # decoding to full width; conf off or unencodable shapes
        # (non-string values, nulls in the dictionary) decode eagerly.
        from ..config import SCAN_ENCODED, active_conf
        if active_conf().get(SCAN_ENCODED):
            from .encoded import dictionary_from_arrow
            dt = dtype or from_arrow(arr.type.value_type)
            if isinstance(dt, (StringType, BinaryType)):
                enc = dictionary_from_arrow(arr, dt)
                if enc is not None:
                    return enc
        arr = arr.dictionary_decode()
    dt = dtype or from_arrow(arr.type)
    n = len(arr)
    if isinstance(dt, (StringType, BinaryType)):
        return _string_from_arrow_buffers(arr, dt, n)
    if isinstance(dt, StructType):
        validity = np.asarray(arr.is_valid())
        kids = tuple(column_from_arrow(arr.field(i), f.data_type)
                     for i, f in enumerate(dt.fields))
        cap = bucket_capacity(n)
        return StructColumn(kids, _dev(_pad_np(validity, cap, False)), dt)
    if isinstance(dt, ArrayType):
        validity = np.asarray(arr.is_valid())
        offsets = np.asarray(arr.offsets, dtype=np.int32)
        cap = bucket_capacity(n)
        off = np.zeros(cap + 1, dtype=np.int32)
        off[: n + 1] = offsets
        off[n + 1 :] = offsets[n] if n else 0
        child = column_from_arrow(arr.values, dt.element_type)
        return ArrayColumn(child, _dev(off),
                           _dev(_pad_np(validity, cap, False)), dt)
    from ..types import MapType as _MapType
    if isinstance(dt, _MapType):
        validity = np.asarray(arr.is_valid())
        offsets = np.asarray(arr.offsets, dtype=np.int32)
        cap = bucket_capacity(n)
        off = np.zeros(cap + 1, dtype=np.int32)
        off[: n + 1] = offsets
        off[n + 1:] = offsets[n] if n else 0
        keys = column_from_arrow(arr.keys, dt.key_type)
        vals = column_from_arrow(arr.items, dt.value_type)
        assert keys.capacity == vals.capacity  # same entry count
        return MapColumn(keys, vals, _dev(off),
                         _dev(_pad_np(validity, cap, False)), dt)
    if isinstance(dt, NullType):
        cap = bucket_capacity(max(n, 1))
        return Column(_dev(np.zeros(cap, np.int8)),
                      _dev(np.zeros(cap, np.bool_)), dt)
    if isinstance(dt, DecimalType):
        pylist = arr.to_pylist()
        if dt.precision > 18:
            return Decimal128Column.from_pylist(pylist, dt)
        unscaled = np.array(
            [0 if v is None else int(round(v.scaleb(dt.scale)))
             for v in pylist], dtype=np.int64)
        validity = np.array([v is not None for v in pylist], dtype=np.bool_)
        return Column.from_numpy(unscaled, dt, validity)
    if isinstance(dt, BooleanType):
        validity = np.asarray(arr.is_valid())
        dense = np.asarray(arr.fill_null(False), dtype=np.bool_)
        return Column.from_numpy(dense, dt, validity)
    validity = np.asarray(arr.is_valid())
    dense = np.asarray(arr.fill_null(0))
    return Column.from_numpy(dense.astype(dt.jnp_dtype), dt, validity)


def column_to_arrow(col: Column, num_rows: int):
    """Device column -> pyarrow array (host materialization)."""
    import pyarrow as pa

    dt = col.dtype
    if isinstance(dt, DecimalType):
        # both tiers (int64 and two-limb) surface unscaled Python ints
        vals = col.to_pylist(num_rows)
        import decimal as _d
        scaled = [None if v is None else _d.Decimal(v).scaleb(-dt.scale) for v in vals]
        return pa.array(scaled, type=to_arrow(dt))
    return pa.array(col.to_pylist(num_rows), type=to_arrow(dt))
