"""Dictionary-encoded string columns (ISSUE 18): keep Parquet
dictionary columns compressed from scan to output, materialize late.

"GPU Acceleration of SQL Analytics on Compressed Data" (PAPERS.md)
shows predicates and join keys can be evaluated directly on dictionary
codes; Theseus makes data movement the first-class design axis. The
engine analog: a `DictionaryColumn` carries a device-resident i32 code
lane plus the per-batch dictionary payload (Arrow (offsets, bytes)
layout, bucket-padded like every other buffer), so

  * the packed H2D upload ships codes + dictionary instead of the
    decoded width (typically a >=2x byte shrink on string-heavy scans),
  * HBM and the spill catalog hold the encoded bytes for the whole
    query (the column is a registered pytree; the catalog spills any
    pytree),
  * equality / IN / null predicates compare i32 codes on device after
    translating the literal through the dictionary ONCE per program
    (expr/predicates.py), and hash joins hash the dictionary once then
    gather precomputed hashes by code (ops/hashing.py),
  * decode happens at ONE chokepoint — `materialize_column` — routed
    through the gather engine (ops/gather.py: a dictionary decode IS a
    row gather of the dictionary by the code lane), only at seams that
    genuinely need full values (operator boundaries whose consumer
    cannot take encoded input, and output collection).

Null/inactive rows use the sentinel code `NULL_CODE` (-1), matching
the engine's -1 invalid-index gather idiom: an unmasked gather of the
dictionary by raw codes yields invalid rows for nulls, never garbage.

The column deliberately carries `data=None` (the StructColumn
precedent): any kernel that was not taught the encoded layout crashes
loudly on `.data` instead of silently misreading codes as values —
the materialize-at-boundary walk in exec/base.py exists so that crash
is unreachable in planned queries.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .column import (Column, StringColumn, _dev, _pad_np, bucket_capacity)
from ..types import BinaryType, DataType, StringType

__all__ = [
    "NULL_CODE", "DictionaryColumn", "dictionary_from_arrow", "dict_take",
    "dictionary_hashes", "row_byte_lanes", "bytes_equal_rows",
    "encoded_equal_literal", "materialize_column", "materialize_batch",
    "batch_has_encoded", "encoded_sig", "note_scan_batch", "counters",
]

#: sentinel code for null/inactive rows — out of range for every
#: dictionary, so unmasked gathers yield invalid rows (the -1 idiom)
NULL_CODE = -1


# ---------------------------------------------------------------------------
# process counters (bench.py embeds per-record deltas via _delta_since;
# the encoded_scan event and the advisor rule read the same totals)
# ---------------------------------------------------------------------------

_COUNTER_LOCK = threading.Lock()
_COUNTERS = {
    "cols_encoded": 0,          # DictionaryColumns built at scan seams
    "codes_bytes": 0,           # code-lane bytes (codes + validity)
    "dict_bytes": 0,            # dictionary payload bytes (offsets + data)
    "decoded_bytes_avoided": 0,  # eager-decode bytes the lane did NOT build
    "materializations": 0,      # late decodes through the gather engine
    "materialized_bytes": 0,    # decoded bytes actually produced late
    "code_space_predicates": 0,  # predicates evaluated on i32 codes
    "dict_hash_tables": 0,      # per-dictionary murmur3 precomputes
    "scan_string_bytes": 0,     # plain (decoded) string bytes built at scan
}


def _note(**deltas) -> None:
    with _COUNTER_LOCK:
        for k, v in deltas.items():
            _COUNTERS[k] += v


def counters() -> Dict[str, int]:
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


# ---------------------------------------------------------------------------
# the column
# ---------------------------------------------------------------------------


class DictionaryColumn(Column):
    """Encoded varlen column: int32 codes into a per-batch dictionary.

    codes    — int32 (capacity,); NULL_CODE for null/inactive rows
    validity — bool (capacity,)
    dict_offsets / dict_data — the dictionary's Arrow (offsets, bytes)
        twin arrays, bucket-padded like a StringColumn's; padded
        dictionary slots are zero-length entries no valid code refers to
    """

    __slots__ = ("codes", "dict_data", "dict_offsets")

    def __init__(self, codes, dict_data, dict_offsets, validity,
                 dtype: DataType = StringType()):
        super().__init__(None, validity, dtype)
        self.codes = codes
        self.dict_data = dict_data
        self.dict_offsets = dict_offsets

    # -- shape -------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.validity.shape[0])

    @property
    def dict_capacity(self) -> int:
        return int(self.dict_offsets.shape[0]) - 1

    @property
    def dict_byte_capacity(self) -> int:
        return int(self.dict_data.shape[0])

    def dict_view(self) -> StringColumn:
        """The dictionary itself as a StringColumn (every entry valid —
        padded slots are zero-length and unreferenced)."""
        return StringColumn(self.dict_data, self.dict_offsets,
                            jnp.ones((self.dict_capacity,), jnp.bool_),
                            self.dtype)

    def with_capacity(self, capacity: int) -> "DictionaryColumn":
        cap = self.capacity
        if capacity == cap:
            return self
        assert capacity > cap, (capacity, cap)
        extra = capacity - cap
        if isinstance(self.codes, np.ndarray):
            codes = np.concatenate(
                [self.codes, np.full(extra, NULL_CODE, self.codes.dtype)])
            validity = np.concatenate(
                [self.validity, np.zeros(extra, self.validity.dtype)])
        else:
            codes = jnp.concatenate(
                [self.codes, jnp.full((extra,), NULL_CODE, self.codes.dtype)])
            validity = jnp.concatenate(
                [self.validity, jnp.zeros((extra,), self.validity.dtype)])
        return DictionaryColumn(codes, self.dict_data, self.dict_offsets,
                                validity, self.dtype)

    # -- host materialization (test/debug surface) -------------------------
    def to_pylist(self, num_rows: int) -> List:
        codes = np.asarray(self.codes[:num_rows])
        valid = np.asarray(self.validity[:num_rows])
        data = np.asarray(self.dict_data)
        off = np.asarray(self.dict_offsets)
        binary = isinstance(self.dtype, BinaryType)
        out: List = []
        for i in range(num_rows):
            c = int(codes[i])
            if not valid[i] or c < 0 or c >= self.dict_capacity:
                out.append(None)
                continue
            b = data[off[c]: off[c + 1]].tobytes()
            out.append(b if binary else b.decode("utf-8"))
        return out

    def __repr__(self):
        return (f"DictionaryColumn(cap={self.capacity}, "
                f"dict={self.dict_capacity}x{self.dict_byte_capacity}B)")


def _dict_flatten(c: DictionaryColumn):
    return (c.codes, c.dict_data, c.dict_offsets, c.validity), c.dtype


def _dict_unflatten(dtype, children):
    codes, dict_data, dict_offsets, validity = children
    return DictionaryColumn(codes, dict_data, dict_offsets, validity, dtype)


jax.tree_util.register_pytree_node(DictionaryColumn, _dict_flatten,
                                   _dict_unflatten)


# ---------------------------------------------------------------------------
# scan construction (io/parquet.py requests Arrow dictionary arrays;
# columnar/column.column_from_arrow routes them here)
# ---------------------------------------------------------------------------


def dictionary_from_arrow(arr, dt: DataType) -> Optional[DictionaryColumn]:
    """pyarrow DictionaryArray -> encoded column, or None when the
    array is not an encodable shape (non-string values, nulls inside
    the dictionary itself) — the caller then decodes eagerly."""
    import pyarrow as pa

    dic = arr.dictionary
    if not (pa.types.is_string(dic.type) or pa.types.is_large_string(dic.type)
            or pa.types.is_binary(dic.type)
            or pa.types.is_large_binary(dic.type)):
        return None
    if dic.null_count:
        return None
    n = len(arr)
    validity = np.asarray(arr.is_valid())
    idx = arr.indices
    if idx.null_count:
        idx = idx.fill_null(0)
    codes = np.asarray(idx).astype(np.int32, copy=True)
    np.putmask(codes, ~validity, NULL_CODE)
    cap = bucket_capacity(n)
    from .column import _string_from_arrow_buffers
    view = _string_from_arrow_buffers(dic, dt, len(dic))
    col = DictionaryColumn(
        _dev(_pad_np(codes, cap, fill=NULL_CODE)),
        view.data, view.offsets,
        _dev(_pad_np(validity.astype(np.bool_), cap, fill=False)), dt)
    return col


# ---------------------------------------------------------------------------
# code-indexed gather of a per-dictionary precomputed table — the
# `dict_gather` measured-tier lane (kern_bench family; the Pallas side
# reuses the ops/pallas_gather DMA row-gather with the table as a
# one-lane matrix)
# ---------------------------------------------------------------------------


def dict_take(table, codes):
    """out[i] = table[clip(codes[i])] for a per-dictionary table
    (precomputed hashes, a literal's hit mask). Tier-selected between
    the XLA take and the Pallas DMA gather; accounted on the gather
    engine (a code-indexed take IS a row gather)."""
    n = int(table.shape[0])
    rows = int(codes.shape[0])
    safe = jnp.clip(codes, 0, n - 1)
    use_pallas = False
    if rows and n:
        from ..ops.pallas_tier import fused_tier_enabled
        use_pallas = fused_tier_enabled("dict_gather", (rows, n))
    from ..ops import gather as gather_engine
    gather_engine.record(1, pallas=use_pallas,
                         nbytes=rows * int(np.dtype(table.dtype).itemsize))
    if use_pallas:
        from ..ops.pallas_gather import dma_row_gather
        from ..ops.pallas_kernels import on_tpu
        mat = table.astype(jnp.uint32).reshape(n, 1)
        out = dma_row_gather(mat, safe, interpret=not on_tpu())[:, 0]
        return out.astype(table.dtype)
    return table[safe]


def dictionary_hashes(col: DictionaryColumn, seed: int):
    """murmur3 over the dictionary entries ONCE (uint32 (dict_cap,)) —
    the join-hash precompute: per-row hashes are then one dict_take of
    this table by the code lane instead of a re-hash per row."""
    from ..ops.hashing import murmur3_string
    _note(dict_hash_tables=1)
    view = col.dict_view()
    h0 = jnp.full((col.dict_capacity,), jnp.uint32(seed))
    return murmur3_string(view, h0)


# ---------------------------------------------------------------------------
# encoded comparisons
# ---------------------------------------------------------------------------


def row_byte_lanes(col):
    """(lengths, starts, data, byte_capacity) per-row byte views for a
    StringColumn or a DictionaryColumn — the shared shape every
    byte-wise kernel (hashing, join verify) consumes, so encoded
    columns compare/hash without materializing."""
    if isinstance(col, DictionaryColumn):
        dlens = col.dict_offsets[1:] - col.dict_offsets[:-1]
        safe = jnp.clip(col.codes, 0, col.dict_capacity - 1)
        lengths = jnp.where(col.validity, dlens[safe], 0)
        starts = col.dict_offsets[:-1][safe]
        return lengths, starts, col.dict_data, col.dict_byte_capacity
    from ..ops.strings import string_lengths
    return string_lengths(col), col.offsets[:-1], col.data, col.byte_capacity


def _bytes_equal_spans(la, sa, da, lb, sb, db):
    """Byte equality of (start, length) spans a vs b over their flat
    buffers: bool per row. O(max common length) vectorized byte steps,
    the string_compare_cols loop shape."""
    len_eq = la == lb
    max_len = jnp.max(jnp.where(len_eq, la, 0))
    da_cap = int(da.shape[0])
    db_cap = int(db.shape[0])

    def cond(carry):
        j, ok = carry
        return j < max_len

    def body(carry):
        j, ok = carry
        ba = da[jnp.clip(sa + j, 0, da_cap - 1)]
        bb = db[jnp.clip(sb + j, 0, db_cap - 1)]
        ok = ok & ((j >= la) | (ba == bb))
        return j + jnp.int32(1), ok

    _, ok = jax.lax.while_loop(cond, body, (jnp.int32(0), len_eq))
    return ok


def bytes_equal_rows(a, b):
    """Row-wise byte equality between two varlen columns (string or
    dictionary, any mix): bool (capacity,), ignoring validity — callers
    AND validity in."""
    la, sa, da, _bca = row_byte_lanes(a)
    lb, sb, db, _bcb = row_byte_lanes(b)
    return _bytes_equal_spans(la, sa, da, lb, sb, db)


def _span_lanes_at(col, idx):
    """(lengths, starts, validity) of col[idx] as spans into col's
    ORIGINAL byte buffer — no gathered byte materialization. Negative /
    out-of-range idx rows come back invalid with length 0."""
    lengths, starts, data, _bc = row_byte_lanes(col)
    cap = int(lengths.shape[0])
    in_range = (idx >= 0) & (idx < cap)
    safe = jnp.where(in_range, idx, 0)
    valid = col.validity[safe] & in_range
    return jnp.where(valid, lengths[safe], 0), starts[safe], data, valid


def bytes_equal_at(a, a_idx, b, b_idx):
    """Candidate-level varlen key verify (join): byte equality of
    a[a_idx] vs b[b_idx] ANDed with both rows' validity, comparing
    through spans into the ORIGINAL buffers. A materialized candidate
    gather cannot do this soundly: its byte bucket is sized for the
    base batch, and a join fan-out overflows it (rows past the bucket
    silently truncate)."""
    la, sa, da, va = _span_lanes_at(a, a_idx)
    lb, sb, db, vb = _span_lanes_at(b, b_idx)
    return _bytes_equal_spans(la, sa, da, lb, sb, db) & va & vb


def encoded_equal_literal(col: DictionaryColumn, value) -> Column:
    """EqualTo(dictionary column, string literal) in code space: compare
    the literal against the dictionary ONCE (per traced program — jit
    caching makes that once per (batch shape, dict shape)), then the
    per-row answer is a dict_take of the hit lane by the code lane.
    Returns a BOOLEAN Column with Spark's 3VL (null rows stay null)."""
    from ..types import BOOLEAN
    cap = col.capacity
    _note(code_space_predicates=1)
    if value is None:
        zeros = jnp.zeros((cap,), jnp.bool_)
        return Column(zeros, zeros, BOOLEAN)
    raw = value.encode("utf-8") if isinstance(value, str) else bytes(value)
    m = len(raw)
    dlens = col.dict_offsets[1:] - col.dict_offsets[:-1]
    if m == 0:
        hit = dlens == 0
    else:
        lit = jnp.asarray(np.frombuffer(raw, np.uint8))
        starts = col.dict_offsets[:-1]
        pos = starts[:, None] + jnp.arange(m, dtype=jnp.int32)[None, :]
        entry = col.dict_data[jnp.clip(pos, 0, col.dict_byte_capacity - 1)]
        hit = (dlens == m) & jnp.all(entry == lit[None, :], axis=1)
    row_hit = dict_take(hit, col.codes)
    return Column(jnp.where(col.validity, row_hit, False),
                  col.validity, BOOLEAN)


# ---------------------------------------------------------------------------
# late materialization — the ONE decode chokepoint
# ---------------------------------------------------------------------------


def decoded_byte_bucket(col: DictionaryColumn) -> int:
    """Byte bucket a full decode of `col` needs (host sync — the
    materialize seams are host-level by design, so the decoded buffer
    is sized tight instead of to a static worst case)."""
    dlens = col.dict_offsets[1:] - col.dict_offsets[:-1]
    safe = jnp.clip(col.codes, 0, col.dict_capacity - 1)
    total = jnp.sum(jnp.where(col.validity, dlens[safe], 0))
    return bucket_capacity(max(int(total), 1))


def materialize_column(col, fault_key: Optional[str] = None,
                       seam: str = "boundary"):
    """Decode a DictionaryColumn to a full-width StringColumn through
    the gather engine (a dictionary decode IS a row gather of the
    dictionary by the code lane: NULL_CODE rows come out invalid via
    the standard -1 gather masking). Non-encoded columns pass through.
    Host-level only — this is the late-materialization seam, routed
    through the `device.dispatch` chaos fault point like every other
    host->device dispatch boundary."""
    if not isinstance(col, DictionaryColumn):
        return col
    from .. import faults
    faults.check("device.dispatch", key=fault_key)
    byte_cap = decoded_byte_bucket(col)
    from ..ops.basic import gather_column
    out = gather_column(col.dict_view(), col.codes,
                        out_valid=col.validity,
                        out_byte_capacity=byte_cap)
    _note(materializations=1, materialized_bytes=byte_cap)
    return out


def batch_has_encoded(batch) -> bool:
    return any(isinstance(c, DictionaryColumn) for c in batch.columns)


def encoded_sig(columns: Sequence) -> tuple:
    """Per-lane encoded-ness marker folded into stage-compiler program
    keys so cached programs never cross representations."""
    return tuple(isinstance(c, DictionaryColumn) for c in columns)


def materialize_batch(batch, fault_key: Optional[str] = None,
                      seam: str = "boundary"):
    """Materialize every encoded column of a batch (identity when none
    are encoded) — the operator-boundary / output-collection seam."""
    if not batch_has_encoded(batch):
        return batch
    cols = [materialize_column(c, fault_key=fault_key, seam=seam)
            for c in batch.columns]
    out = batch.with_columns(cols, batch.schema)
    from ..obs import events as obs_events
    if obs_events.active_bus() is not None:
        obs_events.emit("encoded_materialize", seam=seam,
                        cols=sum(1 for c in batch.columns
                                 if isinstance(c, DictionaryColumn)))
    return out


# ---------------------------------------------------------------------------
# scan-seam accounting (the `encoded_scan` event + advisor evidence)
# ---------------------------------------------------------------------------


def _decoded_nbytes_estimate(col: DictionaryColumn) -> int:
    """Bytes the eager-decode lane would have built for this column
    (string data bucket + offsets + validity) — all numpy at the scan
    seam (pre-upload), so this is a pure host computation."""
    codes = np.asarray(col.codes)
    off = np.asarray(col.dict_offsets)
    valid = np.asarray(col.validity)
    dlens = off[1:] - off[:-1]
    safe = np.clip(codes, 0, col.dict_capacity - 1)
    total = int(np.where(valid, dlens[safe], 0).sum())
    cap = col.capacity
    return bucket_capacity(max(total, 1)) + (cap + 1) * 4 + cap


def note_scan_batch(columns: Sequence) -> None:
    """Account a scan-built batch: encoded lanes bump the counters the
    encoded_scan event / bench attribution / advisor rule read; plain
    string lanes bump scan_string_bytes (the advisor's evidence that a
    conf-off scan is shipping decoded width)."""
    enc = [c for c in columns if isinstance(c, DictionaryColumn)]
    plain = sum(c.data.nbytes + c.offsets.nbytes for c in columns
                if isinstance(c, StringColumn))
    if plain:
        _note(scan_string_bytes=int(plain))
    if not enc:
        return
    codes_bytes = sum(c.codes.nbytes + c.validity.nbytes for c in enc)
    dict_bytes = sum(c.dict_data.nbytes + c.dict_offsets.nbytes for c in enc)
    avoided = 0
    for c in enc:
        est = _decoded_nbytes_estimate(c)
        have = c.codes.nbytes + c.validity.nbytes \
            + c.dict_data.nbytes + c.dict_offsets.nbytes
        avoided += max(est - have, 0)
    _note(cols_encoded=len(enc), codes_bytes=int(codes_bytes),
          dict_bytes=int(dict_bytes), decoded_bytes_avoided=int(avoided))
    from ..obs import events as obs_events
    if obs_events.active_bus() is None:
        return
    with _COUNTER_LOCK:
        mats = _COUNTERS["materializations"]
    obs_events.emit("encoded_scan", cols_encoded=len(enc),
                    codes_bytes=int(codes_bytes), dict_bytes=int(dict_bytes),
                    decoded_bytes_avoided=int(avoided),
                    materializations=mats)
