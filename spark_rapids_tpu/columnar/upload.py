"""Packed host->device batch upload — the ingest mirror of the packed
D2H fetch (columnar/transfer.py).

Every ingest seam used to promote a decoded host batch buffer-by-buffer:
one `jnp.asarray` per data/validity/offsets array per column, ~3 x
n_columns host->device round trips per batch. On a remote-attached TPU
each transfer pays full link latency, exactly the failure mode the
packed D2H fetch killed for the device->host direction. The reference
never ships a table that way either: host-side concat results land as
ONE contiguous buffer and cross PCIe in one copy (JCudfSerialization /
HostConcatResult, SURVEY §2.5).

This module provides the mirror:

  1. a host-side packer that lays the batch (row count + per-column
     blocks, the SAME block layout as the D2H format in transfer.py,
     f64 staged as double-double float32 pairs on TPU) into ONE
     contiguous uint8 staging buffer drawn from a reusable,
     capacity-bucketed staging pool (the pinned-host-memory analog:
     conf-capped idle bytes, grow-on-miss, LRU-trimmed) so steady-state
     uploads do zero host allocation;
  2. ONE `jax.device_put` per batch — the single transfer, routed
     through the `device.dispatch` chaos fault point with the batch's
     work-item key;
  3. ONE jitted device unpack program per capacity-shape bucket (the
     static layout spec keys the trace, like `_pack_jit`) that slices /
     bitcasts the buffer back into column arrays — byte-identical to
     the per-buffer lane for every column family.

Wired at the three ingest seams: `SourceScanExec` batch upload
(`ColumnarBatch.from_arrow`), the shuffle-read deserializer's device
promotion (`shuffle/serializer.deserialize_batch` +
`HostShuffleExchangeExec._read_partition`), and spill unspill
(`memory/catalog._unspill_locked` via `upload_leaves`). Gated by
`spark.rapids.tpu.transfer.packedUpload.enabled` (default on); column
trees the packer does not recognize keep the per-buffer lane.

CPU backends may make `device_put` a ZERO-COPY alias of the staging
buffer (PJRT kImmutableZeroCopy) — a PER-BUFFER, alignment-dependent
decision, so every upload checks its own transfer: an aliased buffer
is single-use (discarded; the device owns its bytes for the arrays'
lifetime), a copied one returns to the pool through a non-blocking
release-when-ready gate on the transfer (no upload path ever blocks on
the device — the unspill seam runs under the catalog lock).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .column import (ArrayColumn, Column, Decimal128Column, MapColumn,
                     StringColumn, StructColumn)
from .encoded import DictionaryColumn
from . import transfer as _transfer

__all__ = [
    "StagingPool", "staging_pool", "reset_staging_pool", "counters",
    "to_device_batch", "packed_upload_batch", "promote_batch",
    "promote_stream", "upload_leaves", "metric_sink", "pack_host_batch",
]


# ---------------------------------------------------------------------------
# process counters (bench.py embeds per-record deltas, the chaos-delta
# pattern; the structural-transfer test and the conftest tripwire read
# them too)
# ---------------------------------------------------------------------------

_COUNTER_LOCK = threading.Lock()
_COUNTERS = {"uploads": 0, "packed": 0, "per_buffer": 0, "transfers": 0,
             "bytes": 0, "pack_ns": 0, "pool_hits": 0, "pool_misses": 0}


def _note(**deltas) -> None:
    with _COUNTER_LOCK:
        for k, v in deltas.items():
            _COUNTERS[k] += v


def counters() -> Dict[str, int]:
    with _COUNTER_LOCK:
        return dict(_COUNTERS)


# ---------------------------------------------------------------------------
# staging-buffer pool
# ---------------------------------------------------------------------------

def _byte_bucket(n: int) -> int:
    """Round a staging size up to a power-of-two bucket (>= 256 bytes)
    so reuse hits across batches of similar shape and the device unpack
    traces once per bucket, not once per exact byte size."""
    if n <= 256:
        return 256
    return 1 << int(n - 1).bit_length()


class StagingPool:
    """Reusable host staging buffers for packed uploads — the
    pinned-host-memory pool analog. acquire() pops the bucket's most
    recently returned buffer (LIFO: cache-warm) or allocates on miss;
    release() returns it and trims the LEAST recently used idle buffers
    past the `packedUpload.poolBytes` cap. In-flight (acquired) bytes
    are tracked but never capped; the conftest tripwire asserts they
    return to zero at module boundaries."""

    def __init__(self):
        self._lock = threading.Lock()
        #: bucket size -> [(tick, buf)] appended in tick order; reuse
        #: pops the tail (newest), trim pops the head (oldest)
        self._free: Dict[int, List[Tuple[int, np.ndarray]]] = {}
        #: buffers whose device consumers may still read them —
        #: returned to _free by the (non-blocking) sweep once every
        #: tracked device array reports ready
        self._pending: List[Tuple[np.ndarray, list]] = []
        self._tick = 0
        self._pooled = 0
        self._outstanding = 0
        self.hits = 0
        self.misses = 0
        self.trims = 0

    def release_when_ready(self, buf: np.ndarray, arrays) -> None:
        """Return `buf` to the pool once every device array in `arrays`
        reports ready — WITHOUT blocking the caller (review r2: the
        unspill seam runs under the catalog's most contended lock; a
        blocking device sync there stalls every admitted query).
        Sweeps happen on later acquire()/stats() calls; `settle()`
        flushes synchronously."""
        leaves = [a for a in jax.tree_util.tree_leaves(arrays)
                  if hasattr(a, "is_ready")]
        if not leaves:
            self.release(buf)
            return
        with self._lock:
            self._pending.append((buf, leaves))
        self._sweep()

    def _sweep(self, block: bool = False) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        still = []
        for buf, leaves in pending:
            if block:
                jax.block_until_ready(leaves)
            if all(a.is_ready() for a in leaves):
                self.release(buf)
            else:
                still.append((buf, leaves))
        if still:
            with self._lock:
                self._pending.extend(still)

    def settle(self) -> None:
        """Blocking flush of deferred releases (tests / tripwires)."""
        self._sweep(block=True)

    def acquire(self, nbytes: int) -> np.ndarray:
        self._sweep()  # reclaim any deferred buffers that landed
        bucket = _byte_bucket(nbytes)
        with self._lock:
            lst = self._free.get(bucket)
            if lst:
                _t, buf = lst.pop()
                self._pooled -= bucket
                self._outstanding += bucket
                self.hits += 1
                _note(pool_hits=1)
                return buf
            self.misses += 1
            self._outstanding += bucket
        _note(pool_misses=1)
        return np.empty(bucket, np.uint8)

    def release(self, buf: np.ndarray) -> None:
        bucket = int(buf.shape[0])
        from ..config import UPLOAD_POOL_BYTES, active_conf
        cap = max(int(active_conf().get(UPLOAD_POOL_BYTES)), 0)
        with self._lock:
            self._outstanding -= bucket
            self._tick += 1
            self._free.setdefault(bucket, []).append((self._tick, buf))
            self._pooled += bucket
            while self._pooled > cap:
                oldest = None
                for b, lst in self._free.items():
                    if lst and (oldest is None
                                or lst[0][0] < self._free[oldest][0][0]):
                        oldest = b
                if oldest is None:  # pragma: no cover — pooled>0 => found
                    break
                self._free[oldest].pop(0)
                self._pooled -= oldest
                self.trims += 1

    def discard(self, buf: np.ndarray) -> None:
        """Drop an acquired buffer without pooling it (the upload error
        path: on a zero-copy backend a half-dispatched program may still
        alias it, so it must never be handed out again)."""
        with self._lock:
            self._outstanding -= int(buf.shape[0])

    def outstanding_bytes(self) -> int:
        self._sweep()
        with self._lock:
            return self._outstanding

    def pooled_bytes(self) -> int:
        with self._lock:
            return self._pooled

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"pooled_bytes": self._pooled,
                    "outstanding_bytes": self._outstanding,
                    "hits": self.hits, "misses": self.misses,
                    "trims": self.trims}

    def presize(self, target_bytes: int, pool_cap: int) -> int:
        """Pre-populate one idle buffer per power-of-two bucket from
        256B up to the bucket of `target_bytes` (ISSUE 14 satellite —
        the PR 10 recorded TODO): steady-state scans pack batches at or
        under batchSizeBytes, so with the ladder pre-sized their
        acquires are all HITS and the miss counter stays at zero
        (asserted in tests/test_upload.py). Cumulative pre-sized bytes
        respect `pool_cap` (the poolBytes conf) — a 1GiB default
        batch-size target under the 256MiB default pool cap pre-sizes
        the ladder up to the cap, never past it. np.empty buffers are
        lazily paged, so an unused rung costs address space, not RSS.
        Idempotent per bucket: rungs that already have an idle or
        in-flight buffer are skipped. Returns bytes pre-allocated."""
        top = _byte_bucket(max(int(target_bytes), 256))
        added = 0
        bucket = 256
        while bucket <= top:
            with self._lock:
                have = bool(self._free.get(bucket))
                room = self._pooled + bucket <= pool_cap
            if not have and room:
                buf = np.empty(bucket, np.uint8)
                with self._lock:
                    self._tick += 1
                    self._free.setdefault(bucket, []).append(
                        (self._tick, buf))
                    self._pooled += bucket
                added += bucket
            bucket <<= 1
        return added


_POOL: Optional[StagingPool] = None
_POOL_LOCK = threading.Lock()
#: (target, cap) the process pool was last pre-sized for — configure()
#: re-presizes only when the sizing inputs actually changed
_PRESIZED_FOR: Optional[Tuple[int, int]] = None


def staging_pool() -> StagingPool:
    global _POOL
    if _POOL is None:
        with _POOL_LOCK:
            if _POOL is None:
                _POOL = StagingPool()
    return _POOL


def reset_staging_pool() -> StagingPool:
    global _POOL, _PRESIZED_FOR
    with _POOL_LOCK:
        _POOL = StagingPool()
        _PRESIZED_FOR = None
    return _POOL


def configure(conf=None) -> None:
    """Session-configure hook (ISSUE 14 satellite): pre-size the
    staging pool's bucket ladder from spark.rapids.sql.batchSizeBytes
    so steady-state scan uploads hit pre-allocated buffers instead of
    growing on miss. Cheap and idempotent per (batchSizeBytes,
    poolBytes) pair; packedUpload.poolBytes=0 (pooling off) skips."""
    global _PRESIZED_FOR
    from ..config import (BATCH_SIZE_BYTES, UPLOAD_PACKED,
                          UPLOAD_POOL_BYTES, active_conf)
    conf = conf if conf is not None else active_conf()
    if not conf.get(UPLOAD_PACKED):
        return
    cap = max(int(conf.get(UPLOAD_POOL_BYTES)), 0)
    if cap <= 0:
        return
    target = int(conf.get(BATCH_SIZE_BYTES))
    key = (target, cap)
    with _POOL_LOCK:
        if _PRESIZED_FOR == key:
            return
        _PRESIZED_FOR = key
    staging_pool().presize(target, cap)


#: cpu-family backends can make device_put a zero-copy ALIAS of the
#: host buffer — a PER-BUFFER decision in PJRT (alignment-dependent),
#: so each upload must check ITS OWN transfer (found live: a
#: process-wide probe misclassified runs whose malloc alignment
#: differed from the probe's, and pooled reuse then rewrote bytes that
#: aliased live device arrays — intermittent cross-thread corruption)
_CPU_FAMILY: Optional[bool] = None


def _cpu_family_backend() -> bool:
    global _CPU_FAMILY
    if _CPU_FAMILY is None:
        _CPU_FAMILY = jax.default_backend() == "cpu"
    return _CPU_FAMILY


def _put_aliased(dev, buf: np.ndarray) -> bool:
    """True when `dev` zero-copy-aliases the staging buffer `buf`."""
    try:
        return dev.unsafe_buffer_pointer() == buf.ctypes.data
    except Exception:  # noqa: BLE001 — sharded/odd arrays: play safe
        return True


# ---------------------------------------------------------------------------
# layout spec — one hashable description per column, sizing the host
# pack and keying the jitted device unpack (trace per capacity bucket)
# ---------------------------------------------------------------------------

def _col_spec(col: Column):
    if isinstance(col, DictionaryColumn):
        return ("dict", col.dtype, col.capacity, col.dict_capacity,
                col.dict_byte_capacity)
    if isinstance(col, StringColumn):
        return ("str", col.dtype, col.capacity, col.byte_capacity)
    if isinstance(col, Decimal128Column):
        return ("dec128", col.dtype, col.capacity,
                tuple(_col_spec(k) for k in col.children))
    if isinstance(col, StructColumn):
        return ("struct", col.dtype, col.capacity,
                tuple(_col_spec(k) for k in col.children))
    if isinstance(col, ArrayColumn):
        return ("array", col.dtype, col.capacity, _col_spec(col.child))
    if isinstance(col, MapColumn):
        return ("map", col.dtype, col.capacity, _col_spec(col.keys),
                _col_spec(col.values))
    return ("fix", col.dtype, str(np.dtype(col.data.dtype)), col.capacity)


def _spec_nbytes(spec) -> int:
    kind = spec[0]
    if kind == "dict":
        _, _dt, cap, dict_cap, dict_byte_cap = spec
        # codes + validity + dictionary (offsets, bytes)
        return cap * 4 + cap + (dict_cap + 1) * 4 + dict_byte_cap
    if kind == "str":
        _, _dt, cap, byte_cap = spec
        return (cap + 1) * 4 + byte_cap + cap
    if kind in ("struct", "dec128"):
        return spec[2] + sum(_spec_nbytes(s) for s in spec[3])
    if kind == "array":
        return (spec[2] + 1) * 4 + spec[2] + _spec_nbytes(spec[3])
    if kind == "map":
        return (spec[2] + 1) * 4 + spec[2] + _spec_nbytes(spec[3]) \
            + _spec_nbytes(spec[4])
    _, _dt, np_dtype, cap = spec
    return cap * np.dtype(np_dtype).itemsize + cap  # data + validity


def _packable_leaf(a) -> bool:
    return isinstance(a, np.ndarray) and a.ndim == 1


def _packable_column(col) -> bool:
    """True when the packer knows this column's class and every buffer
    is host-resident — anything else keeps the per-buffer lane."""
    if isinstance(col, DictionaryColumn):
        return _packable_leaf(col.codes) and _packable_leaf(col.validity) \
            and _packable_leaf(col.dict_offsets) \
            and _packable_leaf(col.dict_data)
    if isinstance(col, StringColumn):
        return _packable_leaf(col.data) and _packable_leaf(col.offsets) \
            and _packable_leaf(col.validity)
    if isinstance(col, StructColumn):  # incl. Decimal128Column
        return _packable_leaf(col.validity) \
            and all(_packable_column(k) for k in col.children)
    if isinstance(col, ArrayColumn):
        return _packable_leaf(col.offsets) and _packable_leaf(col.validity) \
            and _packable_column(col.child)
    if isinstance(col, MapColumn):
        return _packable_leaf(col.offsets) and _packable_leaf(col.validity) \
            and _packable_column(col.keys) and _packable_column(col.values)
    if type(col) is Column:
        return _packable_leaf(col.data) and _packable_leaf(col.validity)
    return False


# ---------------------------------------------------------------------------
# host-side pack (mirrors transfer._pack_column's block order exactly:
# pack_host_batch(cols, n) is byte-identical to
# np.asarray(transfer._pack_jit(device_batch)) — property-tested)
# ---------------------------------------------------------------------------

def _host_bytes(arr: np.ndarray, dd: bool) -> np.ndarray:
    """One numpy leaf as its wire bytes — the host mirror of
    transfer._bytes_of."""
    a = np.ascontiguousarray(arr)
    if a.dtype == np.bool_:
        return a.view(np.uint8)
    if a.dtype == np.float64 and dd:
        hi = a.astype(np.float32)
        lo = (a - hi.astype(np.float64)).astype(np.float32)
        pair = np.empty((a.shape[0], 2), np.float32)
        pair[:, 0] = hi
        pair[:, 1] = lo
        return pair.reshape(-1).view(np.uint8)
    return a.reshape(-1).view(np.uint8)


def _put_block(buf: np.ndarray, pos: int, block: np.ndarray) -> int:
    n = block.shape[0]
    buf[pos: pos + n] = block
    return pos + n


def _pack_host_column(col: Column, buf: np.ndarray, pos: int,
                      dd: bool) -> int:
    if isinstance(col, DictionaryColumn):
        pos = _put_block(buf, pos, _host_bytes(col.codes, dd))
        pos = _put_block(buf, pos, _host_bytes(col.dict_offsets, dd))
        pos = _put_block(buf, pos, _host_bytes(col.dict_data, dd))
        return _put_block(buf, pos, _host_bytes(col.validity, dd))
    if isinstance(col, StringColumn):
        pos = _put_block(buf, pos, _host_bytes(col.offsets, dd))
        pos = _put_block(buf, pos, _host_bytes(col.data, dd))
        return _put_block(buf, pos, _host_bytes(col.validity, dd))
    if isinstance(col, StructColumn):  # incl. Decimal128Column
        pos = _put_block(buf, pos, _host_bytes(col.validity, dd))
        for k in col.children:
            pos = _pack_host_column(k, buf, pos, dd)
        return pos
    if isinstance(col, ArrayColumn):
        pos = _put_block(buf, pos, _host_bytes(col.offsets, dd))
        pos = _put_block(buf, pos, _host_bytes(col.validity, dd))
        return _pack_host_column(col.child, buf, pos, dd)
    if isinstance(col, MapColumn):
        pos = _put_block(buf, pos, _host_bytes(col.offsets, dd))
        pos = _put_block(buf, pos, _host_bytes(col.validity, dd))
        pos = _pack_host_column(col.keys, buf, pos, dd)
        return _pack_host_column(col.values, buf, pos, dd)
    pos = _put_block(buf, pos, _host_bytes(col.data, dd))
    return _put_block(buf, pos, _host_bytes(col.validity, dd))


def pack_host_batch(cols: Sequence[Column], n: int,
                    pool: Optional[StagingPool] = None,
                    specs: Optional[tuple] = None
                    ) -> Tuple[np.ndarray, int]:
    """Lay (row count + columns) into one pooled staging buffer.
    Returns (buffer, used_bytes); the buffer is bucket-sized (>= used)
    and the device unpack ignores the tail. Caller must release() or
    discard() the buffer back to the pool. `specs` lets a caller that
    already built the layout specs (the unpack needs them too) skip a
    second tree walk."""
    dd = _transfer._dd_split()
    if specs is None:
        specs = tuple(_col_spec(c) for c in cols)
    total = 4 + sum(_spec_nbytes(s) for s in specs)
    pool = pool or staging_pool()
    buf = pool.acquire(total)
    buf[:4] = np.array([n], dtype="<i4").view(np.uint8)
    pos = 4
    for col in cols:
        pos = _pack_host_column(col, buf, pos, dd)
    assert pos == total, (pos, total)
    return buf, total


# ---------------------------------------------------------------------------
# device-side unpack (ONE jitted program per (buffer bucket, layout))
# ---------------------------------------------------------------------------

def _dev_cast(raw, np_dtype: np.dtype, count: int, dd: bool):
    """uint8 wire block -> device array of `count` elements — the
    device mirror of the host views in transfer._unpack_column."""
    if np_dtype == np.bool_:
        return raw.astype(jnp.bool_)
    if np_dtype == np.float64 and dd:
        pair = jax.lax.bitcast_convert_type(
            raw.reshape(count * 2, 4), jnp.float32).reshape(count, 2)
        return pair[:, 0].astype(jnp.float64) \
            + pair[:, 1].astype(jnp.float64)
    size = np_dtype.itemsize
    if size == 1:
        return jax.lax.bitcast_convert_type(raw, np_dtype)
    if size == 8:
        # stage through uint32 pairs: TPU's X64 rewriting pass has no
        # direct 8->64 bitcast (the exact inverse of _bytes_of)
        u32 = jax.lax.bitcast_convert_type(
            raw.reshape(count * 2, 4), jnp.uint32)
        return jax.lax.bitcast_convert_type(
            u32.reshape(count, 2), np_dtype)
    return jax.lax.bitcast_convert_type(
        raw.reshape(count, size), np_dtype)


def _unpack_dev_column(spec, buf, pos: int, dd: bool):
    kind = spec[0]
    if kind == "dict":
        _, dt, cap, dict_cap, dict_byte_cap = spec
        codes = _dev_cast(buf[pos: pos + cap * 4], np.dtype(np.int32),
                          cap, dd)
        pos += cap * 4
        off = _dev_cast(buf[pos: pos + (dict_cap + 1) * 4],
                        np.dtype(np.int32), dict_cap + 1, dd)
        pos += (dict_cap + 1) * 4
        data = buf[pos: pos + dict_byte_cap]
        pos += dict_byte_cap
        v = buf[pos: pos + cap].astype(jnp.bool_)
        pos += cap
        return DictionaryColumn(codes, data, off, v, dt), pos
    if kind == "str":
        _, dt, cap, byte_cap = spec
        off = _dev_cast(buf[pos: pos + (cap + 1) * 4], np.dtype(np.int32),
                        cap + 1, dd)
        pos += (cap + 1) * 4
        data = buf[pos: pos + byte_cap]
        pos += byte_cap
        v = buf[pos: pos + cap].astype(jnp.bool_)
        pos += cap
        return StringColumn(data, off, v, dt), pos
    if kind in ("struct", "dec128"):
        dt, cap = spec[1], spec[2]
        v = buf[pos: pos + cap].astype(jnp.bool_)
        pos += cap
        kids = []
        for s in spec[3]:
            kid, pos = _unpack_dev_column(s, buf, pos, dd)
            kids.append(kid)
        cls = Decimal128Column if kind == "dec128" else StructColumn
        return cls(tuple(kids), v, dt), pos
    if kind == "array":
        dt, cap = spec[1], spec[2]
        off = _dev_cast(buf[pos: pos + (cap + 1) * 4], np.dtype(np.int32),
                        cap + 1, dd)
        pos += (cap + 1) * 4
        v = buf[pos: pos + cap].astype(jnp.bool_)
        pos += cap
        kid, pos = _unpack_dev_column(spec[3], buf, pos, dd)
        return ArrayColumn(kid, off, v, dt), pos
    if kind == "map":
        dt, cap = spec[1], spec[2]
        off = _dev_cast(buf[pos: pos + (cap + 1) * 4], np.dtype(np.int32),
                        cap + 1, dd)
        pos += (cap + 1) * 4
        v = buf[pos: pos + cap].astype(jnp.bool_)
        pos += cap
        keys, pos = _unpack_dev_column(spec[3], buf, pos, dd)
        vals, pos = _unpack_dev_column(spec[4], buf, pos, dd)
        return MapColumn(keys, vals, off, v, dt), pos
    _, dt, np_dtype, cap = spec
    np_dtype = np.dtype(np_dtype)
    nbytes = cap * np_dtype.itemsize
    data = _dev_cast(buf[pos: pos + nbytes], np_dtype, cap, dd)
    pos += nbytes
    v = buf[pos: pos + cap].astype(jnp.bool_)
    pos += cap
    return Column(data, v, dt), pos


def _unpack_batch_impl(buf, specs, dd: bool):
    num_rows = jax.lax.bitcast_convert_type(
        buf[:4].reshape(1, 4), jnp.int32)[0]
    pos = 4
    cols = []
    for s in specs:
        col, pos = _unpack_dev_column(s, buf, pos, dd)
        cols.append(col)
    return num_rows, tuple(cols)


from ..obs.dispatch import instrument as _instrument

_unpack_batch_jit = _instrument(_unpack_batch_impl,
                                label="upload.unpack_batch",
                                static_argnums=(1, 2))


def _unpack_leaves_impl(buf, specs, dd: bool):
    pos = 0
    out = []
    for np_dtype, shape in specs:
        np_dtype = np.dtype(np_dtype)
        count = int(np.prod(shape)) if shape else 1
        # dd staging is size-preserving: 2 x f32 == f64's 8 bytes
        nbytes = count * np_dtype.itemsize
        flat = _dev_cast(buf[pos: pos + nbytes], np_dtype, count, dd)
        pos += nbytes
        out.append(flat.reshape(shape))
    return tuple(out)


_unpack_leaves_jit = _instrument(_unpack_leaves_impl,
                                 label="upload.unpack_leaves",
                                 static_argnums=(1, 2))


# ---------------------------------------------------------------------------
# metric attribution (thread-local sink: the scan seam's uploads happen
# deep inside source.batches(), on the pipeline producer thread)
# ---------------------------------------------------------------------------

_TLS = threading.local()


@contextmanager
def metric_sink(num_metric, time_metric):
    """Attribute uploads inside the with-block to an exec's
    (numUploads, uploadPackTimeNs) metric pair."""
    prev = getattr(_TLS, "sink", None)
    _TLS.sink = (num_metric, time_metric)
    try:
        yield
    finally:
        _TLS.sink = prev


def _record(lane: str, seam: str, nbytes: int, rows: int, n_cols: int,
            transfers: int, pack_ns: int) -> None:
    _note(uploads=1, transfers=transfers, bytes=nbytes, pack_ns=pack_ns,
          **({"packed": 1} if lane == "packed" else {"per_buffer": 1}))
    sink = getattr(_TLS, "sink", None)
    if sink is not None:
        sink[0].add(1)
        sink[1].add(pack_ns)
    from ..obs import events as obs_events
    obs_events.emit("upload", lane=lane, seam=seam, bytes=nbytes,
                    rows=rows, cols=n_cols, transfers=transfers,
                    pack_ns=pack_ns)


# ---------------------------------------------------------------------------
# upload lanes
# ---------------------------------------------------------------------------

def _one_transfer(buf: np.ndarray, fault_key: Optional[str]):
    """The single host->device copy, routed through the
    `device.dispatch` chaos fault point with the batch's work-item key
    so seeded injection covers this lane (ISSUE 10 satellite)."""
    from .. import faults
    faults.check("device.dispatch", key=fault_key)
    return jax.device_put(buf)


def _finish_staging(pool: StagingPool, buf: np.ndarray, dev) -> None:
    """Hand the staging buffer back once it is safe to mutate again —
    `dev` readiness is the sufficient gate in every case (a ready
    device copy means the host bytes were consumed; an alias is never
    safe at all).

    CPU backend, aliased put (PJRT zero-copy — per-buffer, alignment
    dependent): `dev` references `buf`'s bytes for its whole lifetime,
    so the buffer can NEVER be rewritten — staging is single-use
    (discard; jaxlib keeps the ndarray alive for the aliasing device
    buffer). Pooling buys nothing for such puts anyway: no copy
    happened, there is nothing to amortize. Found live: 8 concurrent
    upload lanes with pooled reuse intermittently read each other's
    bytes through aliasing; single-use staging (and, independently,
    serialized uploads) are both clean.

    Copied put (CPU non-aliased, or any real accelerator's DMA): reuse
    is safe once the transfer consumed the host bytes — gate the
    release on `dev` readiness WITHOUT blocking (review r2: the
    unspill seam runs under the catalog's most contended lock; waiting
    out a remote-link DMA there stalls every admitted query). The
    deferred gate keeps the device u8 buffer alive until the next pool
    sweep — one batch-sized buffer, untracked by the HBM budget,
    bounded by upload cadence."""
    if _cpu_family_backend() and _put_aliased(dev, buf):
        pool.discard(buf)
    else:
        pool.release_when_ready(buf, dev)


def packed_upload_batch(cols: Sequence[Column], n: int, schema,
                        fault_key: Optional[str] = None,
                        seam: str = "other"):
    """The packed lane, unconditionally: ONE staging pack, ONE
    device_put, ONE jitted unpack. Callers outside tests/bench should
    use to_device_batch (conf-gated, with the per-buffer fallback)."""
    from .batch import ColumnarBatch
    t0 = time.perf_counter_ns()
    dd = _transfer._dd_split()
    specs = tuple(_col_spec(c) for c in cols)
    pool = staging_pool()
    buf, total = pack_host_batch(cols, n, pool, specs=specs)
    try:
        # ship only the used bytes, not the pool bucket: the bucket can
        # be ~2x the payload, and on a remote-attached link that halves
        # effective ingest bandwidth (the specs fix `total`, so the
        # unpack still traces once per layout — the view adds no keys)
        dev = _one_transfer(buf[:total], fault_key)
        num_rows, out_cols = _unpack_batch_jit(dev, specs, dd)
    except BaseException:
        pool.discard(buf)
        raise
    _finish_staging(pool, buf, dev)
    del dev
    _record("packed", seam, total, n, len(cols), 1,
            time.perf_counter_ns() - t0)
    return ColumnarBatch(list(out_cols), num_rows, schema, host_rows=n)


def _per_buffer_batch(cols: Sequence[Column], n: int, schema,
                      seam: str, fault_key: Optional[str] = None):
    """The fallback lane: one transfer per host leaf (exactly the
    pre-ISSUE-10 behavior), counted so the structural tests can pin the
    difference."""
    from .batch import ColumnarBatch
    t0 = time.perf_counter_ns()
    from .. import faults
    faults.check("device.dispatch", key=fault_key)
    leaves, treedef = jax.tree_util.tree_flatten(list(cols))
    transfers = 0
    nbytes = 0
    dev_leaves = []
    for leaf in leaves:
        if isinstance(leaf, np.ndarray):
            transfers += 1
            nbytes += leaf.nbytes
            dev_leaves.append(jnp.asarray(leaf))
        else:
            # already on device, or an unregistered-pytree column that
            # flattened as one opaque leaf — pass through untouched
            # (exactly the pre-ISSUE-10 behavior for such trees)
            dev_leaves.append(leaf)
    out_cols = jax.tree_util.tree_unflatten(treedef, dev_leaves)
    batch = ColumnarBatch(out_cols, n, schema)  # +1: the row-count scalar
    _record("per_buffer", seam, nbytes, n, len(cols), transfers + 1,
            time.perf_counter_ns() - t0)
    return batch


def to_device_batch(cols: Sequence[Column], n: int, schema,
                    fault_key: Optional[str] = None, seam: str = "other"):
    """Promote host-built columns to a device ColumnarBatch on the lane
    the conf selects: packed (one transfer) when enabled and every
    column is packable, per-buffer otherwise."""
    from ..config import UPLOAD_PACKED, active_conf
    if active_conf().get(UPLOAD_PACKED) \
            and all(_packable_column(c) for c in cols):
        return packed_upload_batch(cols, n, schema, fault_key, seam)
    return _per_buffer_batch(cols, n, schema, seam, fault_key)


def promote_batch(batch, fault_key: Optional[str] = None,
                  seam: str = "other"):
    """Device-promote a host-backed ColumnarBatch (numpy leaves);
    batches already on device pass through untouched."""
    leaves = jax.tree_util.tree_leaves(list(batch.columns))
    if not any(isinstance(x, np.ndarray) for x in leaves):
        return batch
    return to_device_batch(list(batch.columns), batch.num_rows_host,
                           batch.schema, fault_key, seam)


def promote_stream(it, key_prefix: str = "", seam: str = "other",
                   num_metric=None, time_metric=None):
    """Wrap a host-batch iterator with device promotion — the
    shuffle-read seam: decode stays on the reader pool, the ONE upload
    per batch runs here (on the pipeline producer thread), attributed
    to the wired exec's metric pair and keyed per batch ordinal so
    seeded chaos placement is thread-schedule independent."""
    try:
        for i, b in enumerate(it):
            key = f"{key_prefix}:{i}" if key_prefix else None
            if num_metric is not None:
                # promote INSIDE the sink, yield OUTSIDE it: a
                # generator suspends at yield with thread-locals
                # intact, and a sink left bound across the suspension
                # would swallow whatever uploads the consuming thread
                # does between pulls (e.g. an unspill)
                with metric_sink(num_metric, time_metric):
                    out = promote_batch(b, fault_key=key, seam=seam)
                yield out
            else:
                yield promote_batch(b, fault_key=key, seam=seam)
    finally:
        # closing this wrapper must close the wrapped stream too — a
        # for-loop abandons its iterator without closing it, and the
        # engine's teardown discipline is synchronous (ISSUE 6)
        close = getattr(it, "close", None)
        if close is not None:
            close()


def upload_leaves(host_leaves: Sequence[np.ndarray],
                  fault_key: Optional[str] = None,
                  seam: str = "unspill") -> List:
    """Promote a flat list of numpy leaves (a spilled pytree) with ONE
    transfer — the unspill seam. Falls back to per-leaf jnp.asarray
    when the conf gates packing off or a leaf is not a plain numpy
    array."""
    from ..config import UPLOAD_PACKED, active_conf
    leaves = list(host_leaves)
    packable = active_conf().get(UPLOAD_PACKED) and leaves \
        and all(isinstance(a, np.ndarray) for a in leaves)
    t0 = time.perf_counter_ns()
    if not packable:
        from .. import faults
        faults.check("device.dispatch", key=fault_key)
        out = [jnp.asarray(a) for a in leaves]
        _record("per_buffer", seam,
                sum(a.nbytes for a in leaves
                    if isinstance(a, np.ndarray)),
                0, len(leaves), len(leaves), time.perf_counter_ns() - t0)
        return out
    dd = _transfer._dd_split()
    specs = tuple((str(a.dtype), tuple(a.shape)) for a in leaves)
    # dd staging is size-preserving (a (hi, lo) float32 pair is exactly
    # f64's 8 bytes), so plain nbytes sizes every leaf
    total = sum(a.nbytes for a in leaves)
    pool = staging_pool()
    buf = pool.acquire(max(total, 1))
    pos = 0
    for a in leaves:
        block = _host_bytes(a.reshape(-1), dd)
        buf[pos: pos + block.shape[0]] = block
        pos += block.shape[0]
    assert pos == total, (pos, total)
    try:
        dev = _one_transfer(buf[:total], fault_key)  # used bytes only
        out = _unpack_leaves_jit(dev, specs, dd)
    except BaseException:
        pool.discard(buf)
        raise
    _finish_staging(pool, buf, dev)
    del dev
    _record("packed", seam, total, 0, len(leaves), 1,
            time.perf_counter_ns() - t0)
    return list(out)
