"""Dump/debug tooling — the reference's DumpUtils.scala (dump the batches
feeding a failing operator to parquet so the bug reproduces offline) and
GpuCoreDumpHandler.scala:38 (ship crash diagnostics to durable storage).

`dump_batch` writes one batch as parquet + a metadata sidecar;
`dump_on_error` wraps an operator drive and dumps every input batch seen
before the failure, plus a generated repro script, into a timestamped
directory under spark.rapids.sql.debug.dumpPath.
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Iterator, List, Optional


def dump_batch(batch, path: str) -> str:
    """One batch → parquet + .meta.json (reference
    DumpUtils.dumpToParquetFile)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    import pyarrow.parquet as pq
    table = batch.to_arrow()
    pq.write_table(table, path)
    meta = {
        "num_rows": batch.num_rows_host,
        "capacity": batch.capacity,
        "schema": [(f.name, f.data_type.simple_name())
                   for f in batch.schema.fields],
        "device_size_bytes": batch.device_size_bytes(),
    }
    with open(path + ".meta.json", "w") as f:
        json.dump(meta, f, indent=2)
    return path


class dump_on_error:
    """Context manager around an operator drive: on exception, dump the
    batches registered via observe() plus the traceback and a repro
    script. Conf-gated by spark.rapids.sql.debug.dumpPath (empty = off),
    like the reference's dump-on-failure hooks."""

    def __init__(self, op_name: str, conf=None):
        from ..config import DEBUG_DUMP_PATH, active_conf
        c = conf or active_conf()
        self.root = c.get(DEBUG_DUMP_PATH)
        self.op_name = op_name
        self._batches: List = []

    @property
    def enabled(self) -> bool:
        return bool(self.root)

    def observe(self, batch):
        if self.enabled:
            self._batches.append(batch)
        return batch

    def observe_iter(self, it: Iterator) -> Iterator:
        for b in it:
            yield self.observe(b)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is None or not self.enabled:
            return False
        stamp = time.strftime("%Y%m%d-%H%M%S")
        out = os.path.join(self.root, f"{self.op_name}-{stamp}")
        os.makedirs(out, exist_ok=True)
        with open(os.path.join(out, "error.txt"), "w") as f:
            f.write("".join(traceback.format_exception(exc_type, exc, tb)))
        for i, b in enumerate(self._batches):
            try:
                dump_batch(b, os.path.join(out, f"input-{i:04d}.parquet"))
            except Exception as dump_exc:  # noqa: BLE001 best-effort dump
                with open(os.path.join(out, f"input-{i:04d}.FAILED"),
                          "w") as f:
                    f.write(repr(dump_exc))
        with open(os.path.join(out, "repro.py"), "w") as f:
            f.write(_REPRO_TEMPLATE.format(op=self.op_name))
        return False  # never swallow the error


_REPRO_TEMPLATE = '''\
"""Auto-generated repro for a failed {op} drive (reference DumpUtils).

Loads the dumped input batches; re-apply the failing operator manually.
"""
import glob
import jax
jax.config.update("jax_platforms", "cpu")
from spark_rapids_tpu.columnar.batch import ColumnarBatch
import pyarrow.parquet as pq

batches = []
for p in sorted(glob.glob(__file__.replace("repro.py", "input-*.parquet"))):
    batches.append(ColumnarBatch.from_arrow(pq.read_table(p)))
print(f"loaded {{len(batches)}} input batches for {op}")
'''
