from .tracing import annotate_op, profile_trace  # noqa: F401
