from .tracing import annotate_op, profile_trace  # noqa: F401


def __getattr__(name: str):
    if name == "op_span":  # delegate to tracing's lazy hook (one shim)
        from .tracing import op_span
        return op_span
    raise AttributeError(name)
