"""Tracing/profiling — the engine's xprof surface (reference analog:
the NVTX ranges + profiler integration in GpuExec/RapidsConf
spark.rapids.profile.*; SURVEY §5).

Two layers:
  * `annotate_op(name)` — a jax.profiler.TraceAnnotation around each
    operator's per-batch device work, so xprof timelines show
    engine-level operator names (ProjectExec, AggregateExec, ...) over
    the XLA ops they launched — the TPU equivalent of the reference's
    NVTX ranges in Nsight.
  * `profile_trace(out_dir)` — capture a full profiler trace of a code
    region to `out_dir` for TensorBoard/xprof, gated by
    spark.rapids.tpu.profile.enabled + .dir so production configs can
    switch it on without code changes (reference profile.* confs).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def annotate_op(name: str) -> Iterator[None]:
    """Named trace annotation (no-op cost when no trace is active)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax profiler trace around the body. With out_dir=None,
    reads spark.rapids.tpu.profile.{enabled,dir}; a disabled conf makes
    this a no-op so call sites can wrap unconditionally."""
    from ..config import PROFILE_DIR, PROFILE_ENABLED, active_conf
    conf = active_conf()
    if out_dir is None:
        if not conf.get(PROFILE_ENABLED):
            yield
            return
        out_dir = conf.get(PROFILE_DIR) or "/tmp/spark_rapids_tpu_trace"
    import jax
    with jax.profiler.trace(out_dir):
        yield
