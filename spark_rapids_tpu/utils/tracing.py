"""Tracing/profiling — the engine's xprof surface (reference analog:
the NVTX ranges + profiler integration in GpuExec/RapidsConf
spark.rapids.profile.*; SURVEY §5).

Three layers:
  * `annotate_op(name)` — a jax.profiler.TraceAnnotation around each
    operator's per-batch device work, so xprof timelines show
    engine-level operator names (ProjectExec, AggregateExec, ...) over
    the XLA ops they launched — the TPU equivalent of the reference's
    NVTX ranges in Nsight.
  * `op_span(name, metric=None, ...)` (re-exported from obs/span.py) —
    the NvtxWithMetrics analog: the same TraceAnnotation plus TpuMetric
    ns accumulation plus a structured event record when the
    spark.rapids.tpu.eventLog confs are on. New metric-scoped call
    sites should use this instead of pairing annotate_op with
    ns_timer by hand.
  * `profile_trace(out_dir)` — capture a full profiler trace of a code
    region to `out_dir` for TensorBoard/xprof, gated by
    spark.rapids.tpu.profile.enabled + .dir so production configs can
    switch it on without code changes (reference profile.* confs).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


def __getattr__(name: str):
    # lazy: obs.span imports annotate_op from here, so the re-export
    # cannot be a top-level import
    if name == "op_span":
        from ..obs.span import op_span
        return op_span
    raise AttributeError(name)


@contextlib.contextmanager
def annotate_op(name: str) -> Iterator[None]:
    """Named trace annotation (no-op cost when no trace is active)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax profiler trace around the body. With out_dir=None,
    reads spark.rapids.tpu.profile.{enabled,dir}; a disabled conf makes
    this a no-op so call sites can wrap unconditionally."""
    from ..config import PROFILE_DIR, PROFILE_ENABLED, active_conf
    conf = active_conf()
    if out_dir is None:
        if not conf.get(PROFILE_ENABLED):
            yield
            return
        out_dir = conf.get(PROFILE_DIR) or "/tmp/spark_rapids_tpu_trace"
    import jax
    with jax.profiler.trace(out_dir):
        yield
