"""Logical plan nodes — the engine's Catalyst analog. The reference plugs
into Spark's physical plans; standalone, this engine carries its own small
logical algebra that the override layer (overrides.py) wraps, tags and
converts to TpuExec trees, preserving the reference's architecture
(GpuOverrides.scala wrap/tag/convert over SparkPlan)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..expr.aggexprs import AggregateFunction
from ..expr.core import Expression, output_name, resolve
from ..types import LongType, Schema, StructField


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def schema(self) -> Schema:
        raise NotImplementedError(type(self).__name__)

    def node_name(self) -> str:
        return type(self).__name__.removeprefix("Logical")

    def describe(self) -> str:
        return self.node_name()

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.describe()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)


class LogicalScan(LogicalPlan):
    """In-memory or datasource scan. `source` is any object with
    `.schema` and `.batches()` (io/ readers provide these)."""

    def __init__(self, source):
        self.source = source

    @property
    def schema(self) -> Schema:
        return self.source.schema

    def describe(self):
        return f"Scan {type(self.source).__name__}"


class LogicalRange(LogicalPlan):
    def __init__(self, start: int, end: int, step: int = 1, name: str = "id"):
        self.start, self.end, self.step, self.name = start, end, step, name

    @property
    def schema(self) -> Schema:
        return Schema((StructField(self.name, LongType(), False),))

    def describe(self):
        return f"Range({self.start}, {self.end}, {self.step})"


class LogicalProject(LogicalPlan):
    def __init__(self, exprs: Sequence[Expression], child: LogicalPlan):
        self.exprs = list(exprs)
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..exec.basic import projection_schema
        return projection_schema(self.exprs, self.children[0].schema)

    def describe(self):
        return f"Project [{', '.join(map(repr, self.exprs))}]"


class LogicalFilter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def describe(self):
        return f"Filter [{self.condition!r}]"


class LogicalAggregate(LogicalPlan):
    def __init__(self, group_exprs: Sequence[Expression],
                 aggregates: Sequence[Tuple[AggregateFunction, str]],
                 child: LogicalPlan):
        self.group_exprs = list(group_exprs)
        self.aggregates = list(aggregates)
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..exec.aggregate import AggregateExec
        from ..exec.basic import InMemoryScanExec
        probe = AggregateExec(self.group_exprs, self.aggregates,
                              InMemoryScanExec([], self.children[0].schema))
        return probe.output_schema

    def describe(self):
        aggs = ", ".join(f"{fn!r} AS {n}" for fn, n in self.aggregates)
        return f"Aggregate keys={self.group_exprs!r} [{aggs}]"


class LogicalJoin(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = "inner",
                 condition: Optional[Expression] = None):
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition
        self.children = (left, right)

    @property
    def schema(self) -> Schema:
        from ..exec.basic import InMemoryScanExec
        from ..exec.joins import HashJoinExec, NestedLoopJoinExec
        l = InMemoryScanExec([], self.children[0].schema)
        r = InMemoryScanExec([], self.children[1].schema)
        if not self.left_keys and self.join_type in ("inner", "cross",
                                                     "left_outer"):
            return NestedLoopJoinExec(l, r, self.join_type,
                                      self.condition).output_schema
        return HashJoinExec(l, r, self.left_keys, self.right_keys,
                            self.join_type,
                            condition=self.condition).output_schema

    def describe(self):
        return (f"Join {self.join_type} lkeys={self.left_keys!r} "
                f"rkeys={self.right_keys!r}")


class LogicalSort(LogicalPlan):
    def __init__(self, orders: Sequence, child: LogicalPlan,
                 limit: Optional[int] = None, offset: int = 0):
        self.orders = list(orders)
        self.limit = limit
        self.offset = offset
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def describe(self):
        return f"Sort {self.orders!r} limit={self.limit} offset={self.offset}"


class LogicalLimit(LogicalPlan):
    def __init__(self, limit: int, child: LogicalPlan, offset: int = 0):
        self.limit = limit
        self.offset = offset
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def describe(self):
        return f"Limit {self.limit} offset={self.offset}"


class LogicalUnion(LogicalPlan):
    def __init__(self, *children: LogicalPlan):
        self.children = tuple(children)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema


class LogicalExpand(LogicalPlan):
    def __init__(self, projections: Sequence[Sequence[Expression]],
                 child: LogicalPlan):
        self.projections = [list(p) for p in projections]
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..exec.basic import projection_schema
        return projection_schema(self.projections[0],
                                 self.children[0].schema)


class LogicalGenerate(LogicalPlan):
    """explode/posexplode of an array expression (reference
    GpuGenerateExec.scala:829)."""

    def __init__(self, generator: Expression, child: LogicalPlan,
                 outer: bool = False, position: bool = False,
                 elem_name: str = "col", pos_name: str = "pos"):
        self.generator = generator
        self.outer = outer
        self.position = position
        self.elem_name = elem_name
        self.pos_name = pos_name
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..types import ArrayType, IntegerType, MapType
        bound = resolve(self.generator, self.children[0].schema)
        arr_t = bound.data_type
        if not isinstance(arr_t, (ArrayType, MapType)):
            raise TypeError(
                f"explode needs an ARRAY or MAP input, got "
                f"{arr_t.simple_name()}")
        fields = list(self.children[0].schema.fields)
        if self.position:
            fields.append(StructField(self.pos_name, IntegerType(),
                                      self.outer))
        if isinstance(arr_t, MapType):
            # explode(map) emits (key, value) pairs like Spark
            fields.append(StructField("key", arr_t.key_type,
                                      self.outer))
            fields.append(StructField("value", arr_t.value_type, True))
        else:
            fields.append(StructField(self.elem_name, arr_t.element_type,
                                      True))
        return Schema(tuple(fields))

    def describe(self):
        kind = "posexplode" if self.position else "explode"
        return f"Generate {kind}{'_outer' if self.outer else ''}" \
               f"({self.generator!r})"


class LogicalWindow(LogicalPlan):
    def __init__(self, window_exprs, child: LogicalPlan):
        self.window_exprs = list(window_exprs)
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..exec.basic import InMemoryScanExec
        from ..exec.window import WindowExec
        probe = WindowExec(self.window_exprs,
                           InMemoryScanExec([], self.children[0].schema))
        return probe.output_schema

    def describe(self):
        return "Window [" + ", ".join(
            f"{we!r} AS {n}" for we, n in self.window_exprs) + "]"


class LogicalRepartition(LogicalPlan):
    """Explicit repartition (Spark df.repartition/coalesce(1); reference
    GpuRoundRobinPartitioning / GpuSinglePartitioning exchanges)."""

    def __init__(self, n_partitions: int, child: LogicalPlan,
                 mode: str = "roundrobin"):
        self.n_partitions = n_partitions
        self.mode = mode  # roundrobin | single
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def describe(self):
        return f"Repartition[{self.mode}, n={self.n_partitions}]"


class LogicalSample(LogicalPlan):
    """Bernoulli row sample (Spark df.sample; reference GpuSampleExec /
    GpuPoissonSampler, basicPhysicalOperators sampling)."""

    def __init__(self, fraction: float, seed: int, child: LogicalPlan):
        assert 0.0 <= fraction <= 1.0, fraction
        self.fraction = fraction
        self.seed = seed
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def describe(self):
        return f"Sample[fraction={self.fraction}, seed={self.seed}]"


class LogicalGroupedMapInPandas(LogicalPlan):
    """df.groupBy(keys).applyInPandas(fn, schema) — reference
    GpuFlatMapGroupsInPandasExec.scala:79."""

    def __init__(self, keys, fn, out_schema: Schema, child: LogicalPlan):
        self.keys = list(keys)
        self.fn = fn
        self.out_schema = out_schema
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.out_schema

    def describe(self):
        return f"GroupedMapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class LogicalAggregateInPandas(LogicalPlan):
    """df.groupBy(keys).agg(pandas_udf...) — reference
    GpuAggregateInPandasExec.scala."""

    def __init__(self, keys, key_names, aggs, child: LogicalPlan):
        self.keys = list(keys)
        self.key_names = list(key_names)
        self.aggs = list(aggs)  # (fn, name, result type, [input exprs])
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..expr.core import resolve
        from ..types import StructField
        child = self.children[0].schema
        fields = [StructField(n, resolve(k, child).data_type)
                  for n, k in zip(self.key_names, self.keys)]
        fields += [StructField(name, rt)
                   for _, name, rt, _ in self.aggs]
        return Schema(tuple(fields))

    def describe(self):
        return f"AggregateInPandas[{len(self.aggs)} aggs]"


class LogicalMapInBatch(LogicalPlan):
    """df.mapInPandas(fn, schema) — reference GpuMapInBatchExec.scala."""

    def __init__(self, fn, out_schema: Schema, child: LogicalPlan):
        self.fn = fn
        self.out_schema = out_schema
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        return self.out_schema

    def describe(self):
        return f"MapInPandas[{getattr(self.fn, '__name__', 'fn')}]"


class LogicalCoGroupedMapInPandas(LogicalPlan):
    """cogroup(...).applyInPandas(fn, schema) — reference
    GpuFlatMapCoGroupsInPandasExec.scala."""

    def __init__(self, left_keys, right_keys, fn, out_schema: Schema,
                 left: LogicalPlan, right: LogicalPlan):
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self.out_schema = out_schema
        self.children = (left, right)

    @property
    def schema(self) -> Schema:
        return self.out_schema

    def describe(self):
        return "CoGroupedMapInPandas"


class LogicalWindowInPandas(LogicalPlan):
    """Whole-partition pandas window UDF — reference
    GpuWindowInPandasExecBase.scala."""

    def __init__(self, part_exprs, wins, child: LogicalPlan):
        self.part_exprs = list(part_exprs)
        self.wins = list(wins)  # (fn, name, result type, [input exprs])
        self.children = (child,)

    @property
    def schema(self) -> Schema:
        from ..types import StructField
        fields = list(self.children[0].schema.fields)
        for _, name, rt, _ in self.wins:
            fields.append(StructField(name, rt))
        return Schema(tuple(fields))

    def describe(self):
        return f"WindowInPandas[{len(self.wins)} fns]"
