"""Cost-based optimizer — the reference's CostBasedOptimizer.scala:1-60
(optional pass deciding GPU-vs-CPU placement per section from operator
cost estimates; off by default via spark.rapids.sql.optimizer.enabled,
same as the reference).

TPU cost shape: a device operator pays a fixed program-dispatch cost
(tens of microseconds — dominated by host→device launch and the XLA
runtime) plus a tiny per-row cost at HBM bandwidth; the host row engine
pays a large per-row interpreter cost but no dispatch. Row↔columnar
transitions at host/device boundaries cost per-row transfer. For tiny
inputs the dispatch dominates and the host engine wins — exactly the
sections the reference's CBO keeps on CPU.

The pass runs over the tagged PlanMeta tree and may flip device-eligible
Project/Filter nodes (the operators with a host implementation,
exec/fallback.py) to host placement when the modeled host cost is lower.
"""

from __future__ import annotations

from typing import Optional

from . import logical as L

# model constants (microseconds); coarse on purpose — the decision only
# needs to be right in the regimes where the two engines differ by 10x+
DEVICE_DISPATCH_US = 150.0     # one XLA program launch
DEVICE_ROW_US = 0.00002        # ~50 GB/s effective over ~1KB rows
HOST_ROW_US = 1.0              # Python row interpreter
TRANSITION_ROW_US = 0.5        # to_pylist / from_pydict per row, per side


def estimate_rows(plan: L.LogicalPlan) -> Optional[int]:
    """Crude row-count estimate threaded from scan statistics (Spark
    sizeInBytes statistics analog; None = unknown)."""
    from .overrides import estimate_plan_size
    if isinstance(plan, L.LogicalRange):
        if plan.step > 0:
            return max(0, (plan.end - plan.start + plan.step - 1)
                       // plan.step)
        return max(0, (plan.start - plan.end - plan.step - 1)
                   // -plan.step)
    if isinstance(plan, L.LogicalScan):
        est = getattr(plan.source, "estimated_num_rows", None)
        if est is not None:
            n = est() if callable(est) else est
            if n is not None:
                return int(n)
        size = estimate_plan_size(plan)
        if size is None:
            return None
        width = max(8, 8 * len(plan.schema.fields))
        return max(1, size // width)
    if isinstance(plan, L.LogicalFilter):
        base = estimate_rows(plan.children[0])
        return None if base is None else max(1, int(base * 0.5))
    if isinstance(plan, (L.LogicalProject, L.LogicalSort, L.LogicalSample,
                         L.LogicalRepartition)):
        return estimate_rows(plan.children[0])
    if isinstance(plan, L.LogicalLimit):
        base = estimate_rows(plan.children[0])
        return plan.limit if base is None else min(plan.limit, base)
    if isinstance(plan, L.LogicalUnion):
        parts = [estimate_rows(c) for c in plan.children]
        if any(p is None for p in parts):
            return None
        return sum(parts)
    return None


def device_cost_us(rows: int) -> float:
    return DEVICE_DISPATCH_US + rows * DEVICE_ROW_US


def host_cost_us(rows: int, needs_transitions: bool) -> float:
    cost = rows * HOST_ROW_US
    if needs_transitions:
        cost += 2 * rows * TRANSITION_ROW_US
    return cost


class CostBasedOptimizer:
    """Optional placement pass (reference Optimizer trait /
    CostBasedOptimizer). Mutates PlanMeta.host_fallback."""

    def __init__(self, conf):
        self.conf = conf

    def optimize(self, meta) -> None:
        from ..exec.fallback import supports_host_eval
        for c in meta.children:
            self.optimize(c)
        p = meta.plan
        if not isinstance(p, (L.LogicalProject, L.LogicalFilter)):
            return
        if meta.host_fallback or not meta.can_run_on_tpu:
            return  # already decided by capability tagging
        exprs = list(p.exprs) if isinstance(p, L.LogicalProject) \
            else [p.condition]
        if not all(supports_host_eval(e) for e in exprs):
            return
        rows = estimate_rows(p)
        if rows is None:
            return
        # a host node between device nodes pays both transitions; a host
        # node whose child is already host-placed shares the boundary
        child_on_host = meta.children and meta.children[0].host_fallback
        dev = device_cost_us(rows)
        host = host_cost_us(rows, needs_transitions=not child_on_host)
        if host < dev:
            meta.host_fallback = True
            meta.cost_note = (
                f"cost optimizer: host {host:.0f}us < device {dev:.0f}us "
                f"for ~{rows} rows (reference CostBasedOptimizer)")
