"""TypeSig — per-operator type-support signatures (reference
TypeChecks.scala:168 TypeSig / :1456 ExprChecks; drives both tagging and
the generated supported-ops documentation)."""

from __future__ import annotations

from typing import FrozenSet, Optional

from ..types import (
    ArrayType, BinaryType, BooleanType, ByteType, DataType, DateType,
    DecimalType, DoubleType, FloatType, IntegerType, LongType, MapType,
    NullType, ShortType, StringType, StructType, TimestampNTZType,
    TimestampType,
)

_ALL_TAGS = {
    "BOOLEAN": BooleanType, "BYTE": ByteType, "SHORT": ShortType,
    "INT": IntegerType, "LONG": LongType, "FLOAT": FloatType,
    "DOUBLE": DoubleType, "DATE": DateType, "TIMESTAMP": TimestampType,
    "TIMESTAMP_NTZ": TimestampNTZType, "STRING": StringType,
    "BINARY": BinaryType, "NULL": NullType, "DECIMAL": DecimalType,
    "ARRAY": ArrayType, "MAP": MapType, "STRUCT": StructType,
}


class TypeSig:
    """An immutable set of supported type tags with set algebra."""

    def __init__(self, tags: FrozenSet[str]):
        self.tags = frozenset(tags)

    @staticmethod
    def of(*names: str) -> "TypeSig":
        for n in names:
            assert n in _ALL_TAGS, n
        return TypeSig(frozenset(names))

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.tags | other.tags)

    def __sub__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.tags - other.tags)

    def supports(self, dt: DataType) -> bool:
        for tag in self.tags:
            if isinstance(dt, _ALL_TAGS[tag]):
                return True
        return False

    def reason_not_supported(self, dt: DataType) -> Optional[str]:
        if self.supports(dt):
            return None
        return (f"{dt.simple_name()} is not supported "
                f"(supported: {', '.join(sorted(self.tags))})")

    def __repr__(self):
        return f"TypeSig({'+'.join(sorted(self.tags))})"


BOOLEAN = TypeSig.of("BOOLEAN")
integral = TypeSig.of("BYTE", "SHORT", "INT", "LONG")
fp = TypeSig.of("FLOAT", "DOUBLE")
numeric = integral + fp
decimal = TypeSig.of("DECIMAL")
numeric_and_decimal = numeric + decimal
datetime = TypeSig.of("DATE", "TIMESTAMP", "TIMESTAMP_NTZ")
stringlike = TypeSig.of("STRING", "BINARY")
nulltype = TypeSig.of("NULL")
comparable = numeric_and_decimal + datetime + stringlike + BOOLEAN + nulltype
orderable = comparable
#: everything current kernels handle for pass-through (gather/concat/sort
#: payloads). ARRAY/MAP/STRUCT restricted until nested gather lands.
commonly_supported = comparable
all_types = TypeSig(frozenset(_ALL_TAGS))
nested = TypeSig.of("ARRAY", "MAP", "STRUCT")
