"""Override rule tables + plan conversion — the reference's
GpuOverrides.scala (rule tables :919/:3838, wrapAndTagPlan :4421,
doConvertPlan :4427) and GpuTransitionOverrides (coalesce insertion :322).

Standalone difference: the reference falls back to Spark's CPU operators
node-by-node; this engine has no host engine underneath, so an
unsupported node raises PlanNotSupported carrying the full explain report
(the same text the reference logs as "will not run on GPU because ...").
A host-fallback operator tier can slot in here later without touching the
tagging machinery.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from ..config import RapidsConf, active_conf
from ..exec.aggregate import AggregateExec
from ..exec.base import TpuExec
from ..exec.basic import (
    ExpandExec, FilterExec, GlobalLimitExec, ProjectExec, RangeExec,
    SourceScanExec, UnionExec,
)
from ..exec.coalesce import CoalesceBatchesExec
from ..exec.joins import HashJoinExec, NestedLoopJoinExec
from ..exec.sort import SortExec, TopNExec
from ..exec.window import WindowExec
from ..expr import arithmetic, cast, collectionexprs, conditional, \
    datetimeexprs, hashexprs, math as emath, predicates, stringexprs
from ..expr.core import (
    Alias, BoundReference, Expression, Literal, UnresolvedAttribute, resolve,
)
from . import logical as L
from .meta import BaseMeta, ExprMeta, ExprRule
from .typesig import (
    BOOLEAN, TypeSig, comparable, commonly_supported, fp, integral,
    numeric, numeric_and_decimal, orderable, stringlike,
)


class PlanNotSupported(Exception):
    def __init__(self, report: str):
        super().__init__(
            "plan cannot run on TPU:\n" + report)
        self.report = report


# ---------------------------------------------------------------------------
# expression rule table (reference: 218 expr[...] rules; grows with kernels)
# ---------------------------------------------------------------------------

_EXPR_RULES: Optional[Dict[Type[Expression], ExprRule]] = None


def _r(rules, cls, desc, input_sig=commonly_supported,
       output_sig=commonly_supported, tag_fn=None):
    rules[cls] = ExprRule(cls, desc, input_sig, output_sig, tag_fn)


def expression_rules() -> Dict[Type[Expression], ExprRule]:
    global _EXPR_RULES
    if _EXPR_RULES is not None:
        return _EXPR_RULES
    rules: Dict[Type[Expression], ExprRule] = {}
    num = numeric_and_decimal
    # leaves: pass through whatever the column holds — the consuming
    # expression's input signature is what gates support
    from .typesig import all_types
    _r(rules, Literal, "literal value")
    _r(rules, BoundReference, "column reference", all_types, all_types)
    _r(rules, UnresolvedAttribute, "column reference", all_types, all_types)
    _r(rules, Alias, "named expression", all_types, all_types)
    # arithmetic. decimal128 coverage (ops/decimal128.py): add/sub for
    # any precision, multiply only from <=18-digit inputs (a d128 input
    # would need a 256-bit intermediate); div/mod past 18 digits need
    # 128/64 long division — all tagged off-device at plan time.
    def _tag_decimal128(meta):
        from ..types import DecimalType as _Dec
        e = meta.expr
        try:
            out_t = e.data_type
            in_ts = [c.data_type for c in e.children]
        except (TypeError, NotImplementedError):
            return
        name = type(e).__name__
        if not (isinstance(out_t, _Dec)
                or any(isinstance(t, _Dec) for t in in_ts)):
            return
        big_in = any(isinstance(t, _Dec) and t.precision > 18
                     for t in in_ts)
        if name == "Multiply" and big_in:
            meta.will_not_work_on_tpu(
                "decimal multiply with >18-digit inputs needs a 256-bit "
                "intermediate")
        if name in ("Divide", "IntegralDivide", "Remainder", "Pmod") \
                and big_in:
            meta.will_not_work_on_tpu(
                f"decimal {name.lower()} with >18-digit inputs has no "
                "device kernel")

    for c in (arithmetic.Add, arithmetic.Subtract, arithmetic.Multiply):
        _r(rules, c, f"{c.__name__.lower()}", num, num,
           tag_fn=_tag_decimal128)
    _r(rules, arithmetic.Divide, "division", num, fp + TypeSig.of("DECIMAL"),
       tag_fn=_tag_decimal128)
    _r(rules, arithmetic.IntegralDivide, "integral division", num, integral,
       tag_fn=_tag_decimal128)
    _r(rules, arithmetic.Remainder, "remainder", num, num,
       tag_fn=_tag_decimal128)
    _r(rules, arithmetic.Pmod, "positive modulo", num, num,
       tag_fn=_tag_decimal128)
    _r(rules, arithmetic.UnaryMinus, "negation", num, num)
    _r(rules, arithmetic.Abs, "absolute value", num, num)
    _r(rules, arithmetic.Least, "least of arguments", orderable, orderable)
    _r(rules, arithmetic.Greatest, "greatest of arguments", orderable, orderable)
    # predicates
    for c in (predicates.EqualTo, predicates.EqualNullSafe,
              predicates.LessThan, predicates.LessThanOrEqual,
              predicates.GreaterThan, predicates.GreaterThanOrEqual):
        _r(rules, c, "comparison", comparable, BOOLEAN)
    for c in (predicates.And, predicates.Or, predicates.Not):
        _r(rules, c, "boolean logic", BOOLEAN, BOOLEAN)
    _r(rules, predicates.IsNull, "null check", commonly_supported, BOOLEAN)
    _r(rules, predicates.IsNotNull, "non-null check", commonly_supported, BOOLEAN)
    _r(rules, predicates.In, "IN list", comparable, BOOLEAN)
    # conditional
    _r(rules, conditional.If, "if/else", commonly_supported)
    _r(rules, conditional.CaseWhen, "case/when", commonly_supported)
    _r(rules, conditional.Coalesce, "first non-null", commonly_supported)
    _r(rules, conditional.IsNaN, "NaN check", fp, BOOLEAN)
    _r(rules, conditional.NaNvl, "NaN replacement", fp, fp)
    # cast — combos without a device kernel are tagged off-device at plan
    # time instead of raising inside the compiled projection (reference
    # GpuCast tags unsupported from/to pairs off-GPU the same way). The
    # host row tier covers some of them (float/double/timestamp→string);
    # the rest fail loudly at plan time.
    def _tag_cast(meta):
        from ..types import (DecimalType as _Dec, DoubleType as _Dbl,
                             FloatType as _Flt, StringType as _Str,
                             TimestampType as _Ts)
        c = meta.expr
        try:
            src = c.children[0].data_type
            dst = c.data_type
        except (TypeError, NotImplementedError):
            return  # unresolved; re-checked post-bind
        off = (isinstance(dst, _Str)
               and isinstance(src, (_Flt, _Dbl, _Ts))) \
            or (isinstance(src, _Str) and isinstance(dst, (_Ts, _Dec))) \
            or (isinstance(src, _Dec) and src.precision > 18) \
            or (isinstance(dst, _Dec) and dst.precision > 18)
        if off:
            meta.will_not_work_on_tpu(
                f"cast {src.simple_name()} -> {dst.simple_name()} has no "
                "device kernel")

    _r(rules, cast.Cast, "type cast", tag_fn=_tag_cast)
    # datetime
    dtsig = TypeSig.of("DATE", "TIMESTAMP", "TIMESTAMP_NTZ")
    for c in (datetimeexprs.Year, datetimeexprs.Month,
              datetimeexprs.DayOfMonth, datetimeexprs.DayOfWeek,
              datetimeexprs.DayOfYear, datetimeexprs.Quarter):
        _r(rules, c, "date part extraction", dtsig, integral)
    for c in (datetimeexprs.Hour, datetimeexprs.Minute,
              datetimeexprs.Second):
        _r(rules, c, "time part extraction",
           TypeSig.of("TIMESTAMP", "TIMESTAMP_NTZ"), integral)
    _r(rules, datetimeexprs.DateAdd, "date_add/date_sub",
       dtsig + integral, dtsig)
    _r(rules, datetimeexprs.DateDiff, "datediff", dtsig, integral)
    _r(rules, datetimeexprs.AddMonths, "add_months", dtsig + integral, dtsig)
    _r(rules, datetimeexprs.LastDay, "last_day", dtsig, dtsig)
    _r(rules, datetimeexprs.TruncDate, "trunc", dtsig, dtsig)

    def _tag_timezone(meta):
        """Resolve the zone at PLAN time: unknown zones tag the expression
        off the device instead of failing mid-kernel (reference
        GpuTimeZoneDB load-or-fallback, TimeZoneDB.scala:61)."""
        import struct as _struct

        from ..ops.timezone import timezone_db
        try:
            timezone_db().tables(meta.expr.tz)
        except (ValueError, OSError, AssertionError, IndexError,
                TypeError, _struct.error) as e:
            # unknown zone OR corrupt/truncated tzdata file: tag off the
            # device either way instead of crashing planning
            meta.will_not_work_on_tpu(f"timezone: {e}")

    tssig = TypeSig.of("TIMESTAMP", "TIMESTAMP_NTZ")
    _r(rules, datetimeexprs.FromUTCTimestamp,
       "UTC → zone wall clock (device tz transition tables)", tssig, tssig,
       tag_fn=_tag_timezone)
    _r(rules, datetimeexprs.ToUTCTimestamp,
       "zone wall clock → UTC (device tz transition tables)", tssig, tssig,
       tag_fn=_tag_timezone)
    # math: each Spark expression registers its own rule (the reference
    # table is per-expression, GpuOverrides.scala:919); all share the
    # UnaryMath device kernel family (expr/math.py)
    for c in (emath.Sqrt, emath.Exp, emath.Expm1, emath.Log, emath.Log2,
              emath.Log10, emath.Log1p, emath.Sin, emath.Cos, emath.Tan,
              emath.Asin, emath.Acos, emath.Atan, emath.Sinh, emath.Cosh,
              emath.Tanh, emath.Asinh, emath.Acosh, emath.Atanh,
              emath.Cbrt, emath.ToDegrees, emath.ToRadians, emath.Signum,
              emath.Rint, emath.Pow, emath.Floor, emath.Ceil, emath.Round,
              emath.BRound):
        _r(rules, c, f"math function {c.__name__.lower()}", num, num)
    _r(rules, emath.UnaryMath, "math function (family base)", num, num)
    # hash
    _r(rules, hashexprs.Murmur3Hash, "murmur3 hash", commonly_supported, integral)
    _r(rules, hashexprs.XxHash64, "xxhash64", commonly_supported, integral)
    # strings
    _r(rules, stringexprs.Length, "string length", stringlike, integral)
    _r(rules, stringexprs.Upper, "uppercase (ASCII)", stringlike, stringlike)
    _r(rules, stringexprs.Lower, "lowercase (ASCII)", stringlike, stringlike)
    _r(rules, stringexprs.Substring, "substring", stringlike, stringlike)
    _r(rules, stringexprs.StartsWith, "prefix match", stringlike, BOOLEAN)
    _r(rules, stringexprs.EndsWith, "suffix match", stringlike, BOOLEAN)
    _r(rules, stringexprs.Contains, "substring match", stringlike, BOOLEAN)
    for c, d in ((stringexprs.StringTrim, "trim"),
                 (stringexprs.StringTrimLeft, "ltrim"),
                 (stringexprs.StringTrimRight, "rtrim"),
                 (stringexprs.StringLPad, "lpad"),
                 (stringexprs.StringRPad, "rpad"),
                 (stringexprs.StringRepeat, "repeat"),
                 (stringexprs.Reverse, "reverse (byte order)"),
                 (stringexprs.InitCap, "initcap"),
                 (stringexprs.StringReplace, "literal replace"),
                 (stringexprs.Concat, "string concatenation"),
                 (stringexprs.ConcatWs, "concat with separator"),
                 (stringexprs.StringTranslate, "character translation"),
                 (stringexprs.Left, "left substring"),
                 (stringexprs.Right, "right substring")):
        _r(rules, c, d, stringlike, stringlike)
    _r(rules, stringexprs.StringLocate, "substring position", stringlike,
       integral)
    _r(rules, stringexprs.Ascii, "first byte code", stringlike, integral)
    _r(rules, stringexprs.Chr, "code point to string", integral, stringlike)
    _r(rules, stringexprs.OctetLength, "byte length", stringlike, integral)
    _r(rules, stringexprs.BitLength, "bit length", stringlike, integral)
    def _tag_regex(meta):
        """Transpile at tag time; unsupported constructs tag the
        expression off the TPU instead of throwing (reference
        RegexParser.scala:687 transpile-or-fallback)."""
        from ..regex import RegexUnsupported
        try:
            meta.expr.program
        except RegexUnsupported as e:
            meta.will_not_work_on_tpu(str(e))

    _r(rules, stringexprs.RLike,
       "regex match (device Glushkov automaton; unsupported constructs "
       "tag off-TPU, reference RegexParser.scala:687)",
       stringlike, BOOLEAN, tag_fn=_tag_regex)
    _r(rules, stringexprs.Like, "SQL LIKE pattern", stringlike, BOOLEAN,
       tag_fn=_tag_regex)
    # bitwise + shifts (device kernels, expr/bitwise.py)
    from ..expr import bitwise as bw
    for c, d in ((bw.BitwiseAnd, "bitwise AND"),
                 (bw.BitwiseOr, "bitwise OR"),
                 (bw.BitwiseXor, "bitwise XOR"),
                 (bw.BitwiseNot, "bitwise NOT")):
        _r(rules, c, d, integral, integral)
    for c, d in ((bw.ShiftLeft, "left shift"),
                 (bw.ShiftRight, "arithmetic right shift"),
                 (bw.ShiftRightUnsigned, "logical right shift")):
        _r(rules, c, d, integral, integral)

    # host-tier families: no device kernel yet — the rule exists so the
    # operator is documented/type-checked, and the tag routes the node
    # through the CPU fallback transitions (reference keeps several of
    # these off-GPU in configurations too)
    def _tag_host_tier(meta):
        meta.will_not_work_on_tpu(
            f"{type(meta.expr).__name__} is a host-tier expression "
            "(runs via CPU fallback; no device kernel)")

    def _tag_device_when_supported(meta):
        # expressions with a partial device kernel expose
        # `device_supported`; unsupported shapes drop to the host tier
        if not getattr(meta.expr, "device_supported", True):
            _tag_host_tier(meta)

    from ..expr.jsonexprs import GetJsonObject, JsonToStructsField
    from ..expr.urlexprs import ParseUrl

    def _tag_get_json(meta):
        # device byte-parallel scanner handles literal wildcard-free
        # paths; '[*]' falls back to the host row tier
        if not meta.expr.device_supported:
            _tag_host_tier(meta)

    _r(rules, GetJsonObject, "JSON path extraction",
       stringlike, stringlike, tag_fn=_tag_get_json)
    _r(rules, JsonToStructsField, "from_json single field (host tier)",
       stringlike, commonly_supported, tag_fn=_tag_host_tier)
    _r(rules, ParseUrl, "URL part extraction", stringlike,
       stringlike, tag_fn=_tag_device_when_supported)
    arrstr = TypeSig.of("ARRAY")

    _r(rules, stringexprs.StringSplit, "string split",
       stringlike, arrstr, tag_fn=_tag_device_when_supported)
    _r(rules, stringexprs.SubstringIndex, "substring_index",
       stringlike, stringlike, tag_fn=_tag_device_when_supported)
    _r(rules, stringexprs.FindInSet, "find_in_set",
       stringlike, integral)
    _r(rules, stringexprs.RegExpExtract, "regex group extract",
       stringlike, stringlike, tag_fn=_tag_device_when_supported)
    _r(rules, stringexprs.RegExpReplace, "regex replace",
       stringlike, stringlike, tag_fn=_tag_device_when_supported)
    _r(rules, stringexprs.FormatNumber,
       "format_number (device digit emission; decimal inputs host tier)",
       numeric, stringlike, tag_fn=_tag_device_when_supported)
    _r(rules, stringexprs.Levenshtein, "edit distance (host tier)",
       stringlike, integral, tag_fn=_tag_host_tier)
    # per-expression input signatures: only types the host evaluators
    # actually handle may reach them
    strbin = stringlike
    stronly = TypeSig.of("STRING")
    for c, d, in_sig in (
            (stringexprs.Base64Encode, "base64 encode", strbin),
            (stringexprs.UnBase64, "base64 decode", strbin),
            (stringexprs.Hex, "hex encode", strbin + integral),
            (stringexprs.Unhex, "hex decode", strbin)):
        _r(rules, c, d, in_sig, strbin)  # device codecs (ops/codecs.py)
    for c, d, in_sig in (
            (stringexprs.Encode, "charset encode", stronly),
            (stringexprs.Decode, "charset decode", strbin)):
        _r(rules, c,
           d + " (device UTF-8/ASCII/Latin-1 byte maps; UTF-16 host tier)",
           in_sig, strbin, tag_fn=_tag_device_when_supported)

    # higher-order functions: literal-leaf lambdas run on device as one
    # flat pass over the child column; others stay host tier
    ce = collectionexprs
    for c, d in ((ce.ArrayTransform, "transform() HOF"),
                 (ce.ArrayFilter, "filter() HOF"),
                 (ce.ArrayExists, "exists() HOF"),
                 (ce.ArrayForAll, "forall() HOF")):
        _r(rules, c, d, commonly_supported + arrstr,
           commonly_supported + arrstr,
           tag_fn=_tag_device_when_supported)
    # r5: segment-kernel device implementations (ops/collection.py);
    # string-element shapes drop to the host tier via device_supported
    for c, d in ((ce.ArrayPosition, "array_position"),
                 (ce.ArrayRemove, "array_remove"),
                 (ce.ArrayDistinct, "array_distinct"),
                 (ce.Slice, "slice"),
                 (ce.Flatten, "flatten"),
                 (ce.ArraysOverlap, "arrays_overlap"),
                 (ce.ArrayRepeat, "array_repeat (literal count)"),
                 (ce.Sequence, "sequence (literal bounds)")):
        _r(rules, c, d, commonly_supported + arrstr,
           commonly_supported + arrstr,
           tag_fn=_tag_device_when_supported)
    # residual host tier with one-line justifications:
    # - aggregate() HOF: arbitrary non-associative lambda fold — no
    #   static-shape device formulation
    # - array_join: per-row varlen string ASSEMBLY (dynamic byte output
    #   composition) — planned with the string-builder substrate
    for c, d in ((ce.ArrayAggregate, "aggregate() HOF"),
                 (ce.ArrayJoin, "array_join")):
        _r(rules, c, d + " (host tier)", commonly_supported,
           commonly_supported, tag_fn=_tag_host_tier)

    from ..expr.zorder import InterleaveBits
    _r(rules, InterleaveBits,
       "z-order bit interleave (device; reference GpuInterleaveBits)",
       integral, integral)

    # null handling / misc
    from ..expr.udf import PythonUDF
    # inputs/outputs limited to the types the host boundary actually
    # converts (DECIMAL/DATE/TIMESTAMP would arrive as raw physical ints)
    udf_io = numeric + BOOLEAN + TypeSig.of("STRING")
    _r(rules, PythonUDF,
       "Python UDF (host round trip via pure_callback; the reference's "
       "Arrow-batched Python worker with XLA as the transport)",
       udf_io, numeric + BOOLEAN)
    _r(rules, conditional.Nvl, "nvl/ifnull")
    _r(rules, conditional.Nvl2, "nvl2")
    _r(rules, conditional.NullIf, "nullif")
    # collections (fixed-width + string elements; deeper nesting tagged off)
    arr = TypeSig.of("ARRAY")
    mapsig = TypeSig.of("MAP")
    _r(rules, collectionexprs.Size, "array/map size", arr + mapsig,
       integral)
    _r(rules, collectionexprs.ArrayContains, "array membership", arr, BOOLEAN)
    _r(rules, collectionexprs.ElementAt, "element access (array/map)",
       arr + mapsig, commonly_supported)
    # maps (reference GpuCreateMap/GpuGetMapValue/GpuMapKeys/GpuMapValues)
    from ..expr import mapexprs
    _r(rules, mapexprs.CreateMap, "map constructor", commonly_supported,
       mapsig)
    _r(rules, mapexprs.GetMapValue, "map value lookup",
       mapsig + commonly_supported, commonly_supported)
    _r(rules, mapexprs.MapKeys, "map_keys", mapsig, arr)
    _r(rules, mapexprs.MapValues, "map_values", mapsig, arr)
    _r(rules, mapexprs.MapContainsKey, "map_contains_key", mapsig,
       BOOLEAN)
    _r(rules, collectionexprs.GetArrayItem, "0-based element access", arr,
       commonly_supported)
    def _fixed_width_elements(meta):
        """Sort/min/max kernels need fixed-width elements (no string sort
        lanes in arrays yet); reject at plan time, not eval time."""
        from ..types import ArrayType
        for c in meta.children:
            try:
                dt = c.expr.data_type
            except TypeError:
                continue
            if isinstance(dt, ArrayType) and not dt.element_type.is_fixed_width:
                meta.will_not_work_on_tpu(
                    f"array<{dt.element_type.simple_name()}> elements are "
                    "not fixed-width (string sort lanes in arrays planned)")

    _r(rules, collectionexprs.SortArray, "in-array sort", arr, arr,
       tag_fn=_fixed_width_elements)
    _r(rules, collectionexprs.ArrayMin, "array minimum", arr,
       numeric_and_decimal, tag_fn=_fixed_width_elements)
    _r(rules, collectionexprs.ArrayMax, "array maximum", arr,
       numeric_and_decimal, tag_fn=_fixed_width_elements)
    # fixed-width inputs only: the interleave constructor has no string
    # element path yet (reject loudly instead of reinterpreting bytes)
    _r(rules, collectionexprs.CreateArray, "array constructor",
       numeric_and_decimal + TypeSig.of("BOOLEAN", "DATE", "TIMESTAMP",
                                        "TIMESTAMP_NTZ"), arr)

    _EXPR_RULES = rules
    return rules


_AGG_WINDOW_RULES = None


def aggregate_window_rules() -> Dict[type, ExprRule]:
    """Aggregate functions and window functions as rules (the reference
    registers each as an expression rule, GpuOverrides.scala aggregate
    exprs). They live in their OWN table: AggregateFunction and
    WindowFunction are not Expression subclasses here (their tagging
    runs at the LogicalAggregate/LogicalWindow plan nodes), so the
    expression table's ExprMeta invariants do not apply — but the
    per-expression docs/typesig surface and the total rule count do."""
    global _AGG_WINDOW_RULES
    if _AGG_WINDOW_RULES is not None:
        return _AGG_WINDOW_RULES
    rules: Dict[type, ExprRule] = {}
    from ..expr import aggexprs as agg
    for c, d in ((agg.Sum, "sum aggregate"),
                 (agg.Count, "count aggregate"),
                 (agg.Min, "min aggregate"),
                 (agg.Max, "max aggregate"),
                 (agg.First, "first aggregate"),
                 (agg.Last, "last aggregate"),
                 (agg.Average, "average aggregate"),
                 (agg.CollectList, "collect_list aggregate"),
                 (agg.CollectSet, "collect_set aggregate"),
                 (agg.Percentile, "percentile aggregate"),
                 (agg.ApproxPercentile,
                  "approx_percentile aggregate (bounded sketch)"),
                 (agg.StddevPop, "stddev_pop aggregate"),
                 (agg.StddevSamp, "stddev_samp aggregate"),
                 (agg.VariancePop, "var_pop aggregate"),
                 (agg.VarianceSamp, "var_samp aggregate")):
        _r(rules, c, d, commonly_supported, commonly_supported)
    from ..expr import windowexprs as win
    for c, d in ((win.RowNumber, "row_number window function"),
                 (win.Rank, "rank window function"),
                 (win.DenseRank, "dense_rank window function"),
                 (win.Lag, "lag window function"),
                 (win.Lead, "lead window function"),
                 (win.FirstValue, "first_value window function"),
                 (win.LastValue, "last_value window function"),
                 (win.WindowAgg, "aggregate over window frame"),
                 (win.WindowExpression, "window expression"),
                 (win.WindowSpec, "window specification"),
                 (win.WindowFrame, "window frame (rows/range bounds)")):
        _r(rules, c, d, commonly_supported, commonly_supported)
    _AGG_WINDOW_RULES = rules
    return rules


# ---------------------------------------------------------------------------
# plan metas
# ---------------------------------------------------------------------------

def extract_pushable_filters(condition: Expression, schema) -> List[tuple]:
    """Split a filter condition into (name, op, literal) conjuncts a scan
    can prune row groups with (the reference's predicate pushdown feeding
    GpuParquetScan). Non-extractable conjuncts simply don't push — the
    Filter stays above the scan either way."""
    out: List[tuple] = []

    def name_of(e) -> Optional[str]:
        if isinstance(e, (UnresolvedAttribute, BoundReference)) \
                and e.name in schema.names:
            return e.name
        return None

    def visit(e: Expression):
        if isinstance(e, predicates.And):
            visit(e.children[0])
            visit(e.children[1])
            return
        ops = {predicates.LessThan: "<", predicates.LessThanOrEqual: "<=",
               predicates.GreaterThan: ">",
               predicates.GreaterThanOrEqual: ">=",
               predicates.EqualTo: "=="}
        op = ops.get(type(e))
        if op is not None:
            l, r = e.children
            if name_of(l) is not None and isinstance(r, Literal) \
                    and r.value is not None:
                out.append((name_of(l), op, r.value))
            elif name_of(r) is not None and isinstance(l, Literal) \
                    and l.value is not None:
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                        "==": "=="}
                out.append((name_of(r), flip[op], l.value))
            return
        if isinstance(e, predicates.IsNull):
            n = name_of(e.children[0])
            if n is not None:
                out.append((n, "is_null", None))
        if isinstance(e, predicates.IsNotNull):
            n = name_of(e.children[0])
            if n is not None:
                out.append((n, "is_not_null", None))

    visit(condition)
    return out


def estimate_plan_size(plan: L.LogicalPlan) -> Optional[int]:
    """Best-effort bytes estimate for broadcast planning (the analog of
    Spark's logical-plan statistics feeding autoBroadcastJoinThreshold).
    None = unknown (never broadcast)."""
    if isinstance(plan, L.LogicalScan):
        est = getattr(plan.source, "estimated_size_bytes", None)
        return est() if callable(est) else None
    if isinstance(plan, L.LogicalRange):
        if plan.step > 0:
            n = max(0, (plan.end - plan.start + plan.step - 1) // plan.step)
        else:
            n = max(0, (plan.start - plan.end - plan.step - 1) // -plan.step)
        return n * 8
    if isinstance(plan, (L.LogicalProject, L.LogicalFilter, L.LogicalLimit,
                         L.LogicalSort)):
        # conservative: assume no reduction (Spark sizes filters the same
        # way without column stats)
        return estimate_plan_size(plan.children[0])
    if isinstance(plan, L.LogicalUnion):
        sizes = [estimate_plan_size(c) for c in plan.children]
        if any(s is None for s in sizes):
            return None
        return sum(sizes)
    if isinstance(plan, L.LogicalAggregate):
        if not plan.group_exprs:
            return 256  # grand aggregate: exactly one tiny row
        # keyed aggregates shrink to the key cardinality — unknown here;
        # returning None routes joins over this subtree to the runtime-
        # measured AdaptiveJoinExec instead of "never broadcast"
        return None
    return None


class PlanMeta(BaseMeta):
    def __init__(self, plan: L.LogicalPlan, conf: RapidsConf):
        super().__init__()
        self.plan = plan
        self.conf = conf
        self.host_fallback = False  # convert this node on the host row engine
        self.children = [PlanMeta(c, conf) for c in plan.children]
        self.expr_metas: List[ExprMeta] = [
            ExprMeta.wrap(e, conf, sch)
            for e, sch in self._expression_pairs()]

    def _expression_pairs(self):
        """(expression, input schema) pairs — the schema lets tagging bind
        column references so type checks see real types."""
        p = self.plan
        child_sch = p.children[0].schema if p.children else None
        if isinstance(p, L.LogicalProject):
            return [(e, child_sch) for e in p.exprs]
        if isinstance(p, L.LogicalFilter):
            return [(p.condition, child_sch)]
        if isinstance(p, L.LogicalAggregate):
            out = [(e, child_sch) for e in p.group_exprs]
            for fn, _ in p.aggregates:
                out.extend((e, child_sch) for e in fn.inputs)
            return out
        if isinstance(p, L.LogicalJoin):
            lsch = p.children[0].schema
            rsch = p.children[1].schema
            out = [(e, lsch) for e in p.left_keys]
            out += [(e, rsch) for e in p.right_keys]
            if p.condition is not None:
                out.append((p.condition, None))  # pair-scope, binds later
            return out
        if isinstance(p, L.LogicalGroupedMapInPandas):
            return [(k, child_sch) for k in p.keys]
        if isinstance(p, L.LogicalAggregateInPandas):
            return [(k, child_sch) for k in p.keys] + [
                (e, child_sch) for _, _, _, ins in p.aggs for e in ins]
        if isinstance(p, L.LogicalMapInBatch):
            return []
        if isinstance(p, L.LogicalCoGroupedMapInPandas):
            return [(k, p.children[0].schema) for k in p.left_keys] + \
                [(k, p.children[1].schema) for k in p.right_keys]
        if isinstance(p, L.LogicalWindowInPandas):
            return [(e, child_sch) for e in p.part_exprs] + [
                (e, child_sch) for _, _, _, ins in p.wins for e in ins]
        if isinstance(p, L.LogicalExpand):
            return [(e, child_sch) for proj in p.projections for e in proj]
        if isinstance(p, L.LogicalGenerate):
            return [(p.generator, child_sch)]
        if isinstance(p, L.LogicalSort):
            out = []
            for o in p.orders:
                e = o[0] if isinstance(o, tuple) else o
                if isinstance(e, Expression):
                    out.append((e, child_sch))
            return out
        if isinstance(p, L.LogicalWindow):
            out = []
            for we, _ in p.window_exprs:
                out.extend((e, child_sch) for e in we.fn.inputs)
                out.extend((e, child_sch) for e in we.spec.partition_by)
                for o in we.spec.order_by:
                    out.append((o[0], child_sch))
            return out
        return []

    def tag_for_tpu(self):
        """Bottom-up tagging (reference RapidsMeta.tagForGpu:291)."""
        for c in self.children:
            c.tag_for_tpu()
            if not c.can_run_on_tpu:
                self.will_not_work_on_tpu("child plan cannot run on TPU")
        if isinstance(self.plan, L.LogicalAggregate):
            # collect_set dedup lanes exist for fixed-width values only
            from ..expr.aggexprs import CollectSet
            from ..expr.core import resolve as _resolve
            for fn, _ in self.plan.aggregates:
                if isinstance(fn, CollectSet) and fn.inputs:
                    try:
                        dt = _resolve(fn.inputs[0],
                                      self.plan.children[0].schema).data_type
                    except (KeyError, TypeError):
                        continue
                    if not dt.is_fixed_width:
                        self.will_not_work_on_tpu(
                            f"collect_set over {dt.simple_name()} needs "
                            "string dedup lanes (planned)")
        # (round 5: decimal128 KEY positions are supported — two-limb
        # order lanes in ops/sort.order_key_lanes, limb equality in the
        # join verify, recursive murmur3 over the limb children — so the
        # former >18-digit key tag-off is gone.)
        if isinstance(self.plan, L.LogicalJoin):
            # joins duplicate payload rows; the duplicating array gather
            # has no string-element byte measurement yet — reject at plan
            # time instead of asserting mid-execution
            from ..types import ArrayType, MapType
            for child in self.plan.children:
                for f in child.schema.fields:
                    if isinstance(f.data_type, ArrayType) \
                            and not f.data_type.element_type.is_fixed_width:
                        self.will_not_work_on_tpu(
                            f"join payload column {f.name!r}: "
                            f"{f.data_type.simple_name()} elements are not "
                            "fixed-width (duplicating gather lacks string "
                            "byte measurement)")
                    if isinstance(f.data_type, MapType):
                        self.will_not_work_on_tpu(
                            f"join payload column {f.name!r}: "
                            "map payloads lack the join-side duplicating "
                            "byte measurement")
        for em in self.expr_metas:
            em.tag_for_tpu()
        if any(not em.can_run_on_tpu for em in self.expr_metas):
            if self._can_host_fallback():
                # reference GpuOverrides.scala:4427 convertToCpu: this
                # node runs on the host row engine; the plan stays viable
                self.host_fallback = True
            else:
                for em in self.expr_metas:
                    if not em.can_run_on_tpu:
                        self.will_not_work_on_tpu(
                            f"expression {type(em.expr).__name__} "
                            "cannot run on TPU")
        name = self.plan.node_name()
        key = f"spark.rapids.sql.exec.{name}"
        if self.host_fallback and \
                str(self.conf._settings.get(key, "true")).lower() == "false":
            # operator disabled entirely — fallback cannot save it either
            self.host_fallback = False
        if str(self.conf._settings.get(key, "true")).lower() == "false":
            self.will_not_work_on_tpu(f"operator {name} disabled by {key}")
        if not self.conf.sql_enabled:
            self.will_not_work_on_tpu(
                "spark.rapids.sql.enabled is false")

    def _can_host_fallback(self) -> bool:
        """True when this node's expressions can all run on the host row
        engine instead (reference convertToCpu; only Project/Filter have
        host operators today)."""
        from ..config import CPU_FALLBACK_ENABLED
        from ..exec.fallback import supports_host_eval
        if not self.conf.get(CPU_FALLBACK_ENABLED):
            return False
        p = self.plan
        if isinstance(p, L.LogicalProject):
            exprs = list(p.exprs)
        elif isinstance(p, L.LogicalFilter):
            exprs = [p.condition]
        else:
            return False
        # resolve against the child schema first: type-based checks
        # (decimal rejection, cast targets) need real column types
        child_schema = p.children[0].schema
        bound = []
        for e in exprs:
            try:
                bound.append(resolve(e, child_schema))
            except (KeyError, TypeError):
                bound.append(e)
        return all(supports_host_eval(e) for e in bound)

    def explain(self, indent: int = 0, lines: Optional[List[str]] = None
                ) -> str:
        """The reference's explain output (GpuOverrides.scala:4764)."""
        lines = [] if lines is None else lines
        mark = "*" if self.can_run_on_tpu else "!"
        if self.host_fallback:
            mark = "~"  # runs, but on the host row engine
        lines.append("  " * indent + f"{mark} {self.plan.describe()}")
        if self.host_fallback:
            note = getattr(self, "cost_note", None) \
                or ("host row engine fallback: expression lacks a "
                    "device kernel")
            lines.append("  " * indent
                         + f"    @ will run on CPU ({note})")
        for r in self._reasons:
            lines.append("  " * indent + f"    @ {r}")
        expr_reasons: List[str] = []
        for em in self.expr_metas:
            em.collect_reasons(expr_reasons)
        for r in expr_reasons:
            lines.append("  " * indent + f"    ! {r}")
        for c in self.children:
            c.explain(indent + 1, lines)
        return "\n".join(lines)

    # -- conversion --------------------------------------------------------
    def _plan_mesh(self):
        """Active multi-device mesh, or None when the plan should stay
        single-partition (no mesh / 1-device mesh / exchange planning
        disabled)."""
        from ..config import SHUFFLE_PLAN_EXCHANGE
        from ..parallel.mesh import active_mesh, mesh_axis_size
        mesh = active_mesh()
        if mesh is None or mesh_axis_size(mesh) <= 1:
            return None
        if not self.conf.get(SHUFFLE_PLAN_EXCHANGE):
            return None
        return mesh

    def _convert_distributed_aggregate(self, p, child: TpuExec, mesh
                                       ) -> TpuExec:
        """partial → shuffle exchange on the group keys → final (reference
        Spark's partial/final split feeding GpuShuffleExchangeExecBase)."""
        from ..exec.exchange import ShuffleExchangeExec
        from ..types import ArrayType
        partial = AggregateExec(p.group_exprs, p.aggregates, child,
                                mode="partial")
        if any(isinstance(f.data_type, ArrayType)
               for f in partial.output_schema.fields):
            # collect_* buffers are list columns; the fixed-width exchange
            # codec cannot carry them yet — stay single-partition
            return AggregateExec(p.group_exprs, p.aggregates, child)
        key_names = partial.output_schema.names[: len(p.group_exprs)]
        part_keys = [UnresolvedAttribute(n) for n in key_names]
        exchange = ShuffleExchangeExec(part_keys, partial, mesh)
        return AggregateExec(p.group_exprs, p.aggregates, exchange,
                             mode="final",
                             input_types=partial._input_types)

    def _host_shuffle_partitions(self) -> int:
        """Partition count for the MULTITHREADED host shuffle, or 1 when
        host-shuffled planning is off (it is the no-mesh fallback: the
        always-works mode of the reference's shuffle manager)."""
        from ..config import SHUFFLE_MODE, SHUFFLE_PARTITIONS
        if self.conf.get(SHUFFLE_MODE).upper() != "MULTITHREADED":
            return 1
        return max(1, self.conf.get(SHUFFLE_PARTITIONS))

    def _convert_host_shuffled_aggregate(self, p, child: TpuExec,
                                         n_parts: int) -> TpuExec:
        """partial → host shuffle exchange → final over partition files
        (device memory bounded per partition; reference MULTITHREADED
        shuffle under partial/final agg)."""
        from ..exec.exchange import HostShuffleExchangeExec
        from ..types import ArrayType
        partial = AggregateExec(p.group_exprs, p.aggregates, child,
                                mode="partial")
        if any(isinstance(f.data_type, ArrayType)
               for f in partial.output_schema.fields):
            # collect_* partial buffers are list columns; the final-mode
            # merge can't consume shuffled list buffers yet (same guard
            # as the mesh path above) — stay single-partition
            return AggregateExec(p.group_exprs, p.aggregates, child)
        key_names = partial.output_schema.names[: len(p.group_exprs)]
        part_keys = [UnresolvedAttribute(n) for n in key_names]
        exchange = HostShuffleExchangeExec(part_keys, partial, n_parts,
                                           self.conf)
        return AggregateExec(p.group_exprs, p.aggregates, exchange,
                             mode="final",
                             input_types=partial._input_types)

    def _convert_range_partitioned_sort(self, p, child: TpuExec,
                                        n_parts: int) -> Optional[TpuExec]:
        """Distributed global sort: range exchange on the first sort key
        (sampled bounds) → per-partition sort, stream in partition order
        (reference GpuRangePartitioner + GpuSortExec over a range
        shuffle). None when the first key isn't a plain column — the
        planner would need a pre-projection (single-partition sort is
        always correct)."""
        from ..exec.exchange import HostShuffleExchangeExec
        from ..exec.sort import PartitionWiseSortExec, resolve_sort_orders
        try:
            orders = resolve_sort_orders(p.orders, child.output_schema)
        except (AssertionError, KeyError, TypeError):
            return None
        first = orders[0]
        exchange = HostShuffleExchangeExec(
            [], child, n_parts, self.conf, partitioning="range",
            range_order=(first.ordinal, first.ascending,
                         first.nulls_first))
        return PartitionWiseSortExec(p.orders, exchange)

    def _convert_host_shuffled_join(self, p, left: TpuExec, right: TpuExec,
                                    n_parts: int) -> Optional[TpuExec]:
        from ..exec.basic import bind_projection
        from ..exec.exchange import (HostShuffleExchangeExec,
                                     ShuffledHashJoinExec)
        lb = bind_projection(p.left_keys, left.output_schema)
        rb = bind_projection(p.right_keys, right.output_schema)
        if any(l.data_type != r.data_type for l, r in zip(lb, rb)):
            return None
        lex = HostShuffleExchangeExec(p.left_keys, left, n_parts, self.conf)
        rex = HostShuffleExchangeExec(p.right_keys, right, n_parts,
                                      self.conf)
        return ShuffledHashJoinExec(lex, rex, p.left_keys, p.right_keys,
                                    p.join_type, condition=p.condition)

    def _convert_distributed_join(self, p, left: TpuExec, right: TpuExec,
                                  mesh) -> Optional[TpuExec]:
        """exchange both sides on the join keys → per-partition shuffled
        hash join (reference GpuShuffledHashJoinExec). Returns None when
        the key partitioning cannot be made consistent (mismatched key
        types hash differently) — caller falls back to the single-partition
        join."""
        from ..exec.basic import bind_projection
        from ..exec.exchange import ShuffledHashJoinExec, ShuffleExchangeExec
        lb = bind_projection(p.left_keys, left.output_schema)
        rb = bind_projection(p.right_keys, right.output_schema)
        if any(l.data_type != r.data_type for l, r in zip(lb, rb)):
            return None
        lex = ShuffleExchangeExec(p.left_keys, left, mesh)
        rex = ShuffleExchangeExec(p.right_keys, right, mesh)
        return ShuffledHashJoinExec(lex, rex, p.left_keys, p.right_keys,
                                    p.join_type, condition=p.condition)

    def _convert_join(self, p, kids) -> TpuExec:
        """Join strategy selection, in the reference's preference order
        (GpuOverrides + Spark's JoinSelection): broadcast when a side's
        estimated size is under the threshold (no data movement for the
        stream side at all), else shuffled hash join over the mesh, else
        the single-partition hash join. Keyless joins go to the
        (broadcast) nested-loop join."""
        from ..config import ADAPTIVE_ENABLED, BROADCAST_SIZE_THRESHOLD
        from ..exec.exchange import BroadcastExchangeExec
        thr = self.conf.get(BROADCAST_SIZE_THRESHOLD)
        # adaptive cap (ISSUE 19): when the runtime replanner is on,
        # its measured-bytes broadcast cap also bounds the ESTIMATE-
        # based decision — an estimate past adaptive.autoBroadcastMax
        # Bytes must not plan a broadcast the replanner would demote
        if thr >= 0 and self.conf.get(ADAPTIVE_ENABLED):
            from ..exec import adaptive
            cap = adaptive.auto_broadcast_max(self.conf)
            if cap >= 0:
                thr = min(thr, cap)
        jt = p.join_type
        size_l = estimate_plan_size(p.children[0])
        size_r = estimate_plan_size(p.children[1])
        can_bcast_r = thr >= 0 and size_r is not None and size_r <= thr \
            and jt in ("inner", "left_outer", "left_semi", "left_anti",
                       "existence", "cross")
        can_bcast_l = thr >= 0 and size_l is not None and size_l <= thr \
            and jt in ("inner", "right_outer")

        if not p.left_keys:
            if can_bcast_r:
                return NestedLoopJoinExec(kids[0],
                                          BroadcastExchangeExec(kids[1]),
                                          jt, p.condition)
            return NestedLoopJoinExec(kids[0], kids[1], jt, p.condition)

        # prefer broadcasting the smaller eligible side
        if can_bcast_r and can_bcast_l and size_l < size_r:
            can_bcast_r = False
        if can_bcast_r:
            return HashJoinExec(kids[0], BroadcastExchangeExec(kids[1]),
                                p.left_keys, p.right_keys, jt,
                                build_side="right", condition=p.condition)
        if can_bcast_l:
            return HashJoinExec(BroadcastExchangeExec(kids[0]), kids[1],
                                p.left_keys, p.right_keys, jt,
                                build_side="left", condition=p.condition)
        mesh = self._plan_mesh()
        if mesh is not None:
            out = self._convert_distributed_join(p, kids[0], kids[1], mesh)
            if out is not None:
                return out
        n_parts = self._host_shuffle_partitions()
        # sub-partitioned join (reference GpuSubPartitionHashJoin.scala
        # :547): a BUILD side too big for device memory splits the join
        # into hash sub-partitions — same-key rows colocate, so the
        # union of per-sub-partition joins is exact. Folded into the
        # host-shuffle partition count so an explicit shuffle.partitions
        # setting can only RAISE the split, never bypass the memory
        # bound; gated on the same MULTITHREADED mode as every other
        # host-shuffle path (_host_shuffle_partitions returns 1
        # otherwise, and the threshold respects that).
        from ..config import JOIN_SUBPARTITION_THRESHOLD, SHUFFLE_MODE
        thr_sub = self.conf.get(JOIN_SUBPARTITION_THRESHOLD)
        if mesh is None and thr_sub >= 0 and size_r is not None \
                and size_r > thr_sub \
                and self.conf.get(SHUFFLE_MODE).upper() == "MULTITHREADED":
            # size from the BUILD side (ShuffledHashJoinExec builds
            # right); cap guards runaway partition-file counts — the
            # reference re-splits recursively instead, so log when the
            # cap leaves sub-builds over the threshold
            k = -(-size_r // max(thr_sub, 1))
            if k > 256:
                import logging
                logging.getLogger("spark_rapids_tpu.plan").warning(
                    "sub-partitioned join capped at 256 partitions; "
                    "build side ~%d bytes still exceeds %d per "
                    "sub-partition", size_r, thr_sub)
                k = 256
            n_parts = max(n_parts, int(k))
        if mesh is None and n_parts > 1:
            out = self._convert_host_shuffled_join(p, kids[0], kids[1],
                                                   n_parts)
            if out is not None:
                return out
        if thr >= 0 and p.left_keys and (size_r is None or size_l is None):
            # UNKNOWN sizes go through the symmetric adaptive join: both
            # sides spillable, runtime build-side choice by MEASURED
            # bytes, sub-partitioning when both sides are huge (reference
            # GpuShuffledSymmetricHashJoinExec:354; sizes come from the exec
            # itself instead of AQE statistics). Known sizes keep the
            # streaming HashJoinExec below — re-measuring them would
            # break the probe-side pipeline for no information.
            from ..exec.joins import AdaptiveJoinExec
            return AdaptiveJoinExec(kids[0], kids[1], p.left_keys,
                                    p.right_keys, p.join_type,
                                    p.condition, self.conf)
        return HashJoinExec(kids[0], kids[1], p.left_keys, p.right_keys,
                            p.join_type, condition=p.condition)

    def _convert_host_node(self, p, child: TpuExec) -> TpuExec:
        """ColumnarToRow → host row operator → RowToColumnar (reference
        transition insertion, GpuTransitionOverrides.scala:50)."""
        from ..exec.fallback import (ColumnarToRowExec, HostFilterExec,
                                     HostProjectExec, RowToColumnarExec)
        rows_in = ColumnarToRowExec(child)
        if isinstance(p, L.LogicalProject):
            host: TpuExec = HostProjectExec(p.exprs, rows_in)
        else:
            host = HostFilterExec(p.condition, rows_in)
        return RowToColumnarExec(host, host.output_schema)

    def convert(self) -> TpuExec:
        p = self.plan
        if isinstance(p, L.LogicalFilter) and not self.host_fallback \
                and isinstance(p.children[0], L.LogicalScan):
            # predicate pushdown: hand simple conjuncts to the source for
            # footer-stats row-group pruning; the Filter stays for
            # exactness (stats prove absence, never presence)
            from ..config import PARQUET_PUSHDOWN_ENABLED
            scan = p.children[0]
            src = scan.source
            if self.conf.get(PARQUET_PUSHDOWN_ENABLED) \
                    and hasattr(src, "with_filters"):
                pushed = extract_pushable_filters(p.condition, scan.schema)
                if pushed:
                    src = src.with_filters(pushed)
            # SourceScanExec streams source.batches() lazily: with
            # pipelining enabled, decode + upload of batch N+1 overlap
            # the device compute of batch N (ISSUE 3)
            scan_exec = CoalesceBatchesExec(
                SourceScanExec(src, scan.schema))
            return FilterExec(p.condition, scan_exec)
        kids = [c.convert() for c in self.children]
        if isinstance(p, L.LogicalScan):
            exec_node: TpuExec = SourceScanExec(p.source, p.schema)
            return CoalesceBatchesExec(exec_node)
        if isinstance(p, L.LogicalRange):
            return RangeExec(p.start, p.end, p.step, name=p.name)
        if isinstance(p, L.LogicalProject):
            if self.host_fallback:
                return self._convert_host_node(p, kids[0])
            return ProjectExec(p.exprs, kids[0])
        if isinstance(p, L.LogicalFilter):
            if self.host_fallback:
                return self._convert_host_node(p, kids[0])
            return FilterExec(p.condition, kids[0])
        if isinstance(p, L.LogicalAggregate):
            mesh = self._plan_mesh()
            if mesh is not None and p.group_exprs:
                return self._convert_distributed_aggregate(p, kids[0], mesh)
            n_parts = self._host_shuffle_partitions()
            if n_parts > 1 and p.group_exprs:
                return self._convert_host_shuffled_aggregate(
                    p, kids[0], n_parts)
            return AggregateExec(p.group_exprs, p.aggregates, kids[0])
        if isinstance(p, L.LogicalSort):
            if p.limit is None:
                n_parts = self._host_shuffle_partitions()
                if n_parts > 1 and self._plan_mesh() is None:
                    out = self._convert_range_partitioned_sort(
                        p, kids[0], n_parts)
                    if out is not None:
                        return out
                return SortExec(p.orders, kids[0])
            return TopNExec(p.limit, p.orders, kids[0], offset=p.offset)
        if isinstance(p, L.LogicalRepartition):
            from ..exec.exchange import HostShuffleExchangeExec
            return HostShuffleExchangeExec(
                [], kids[0], p.n_partitions, self.conf,
                partitioning=p.mode)
        if isinstance(p, L.LogicalSample):
            from ..exec.basic import SampleExec
            return SampleExec(p.fraction, p.seed, kids[0])
        if isinstance(p, L.LogicalLimit):
            return GlobalLimitExec(p.limit, kids[0], offset=p.offset)
        if isinstance(p, L.LogicalUnion):
            return UnionExec(*kids)
        if isinstance(p, L.LogicalExpand):
            return ExpandExec(p.projections, kids[0])
        if isinstance(p, L.LogicalWindow):
            return WindowExec(p.window_exprs, kids[0])
        if isinstance(p, L.LogicalGroupedMapInPandas):
            from ..exec.python_udf import GroupedMapInPandasExec
            return GroupedMapInPandasExec(p.keys, p.fn, p.out_schema,
                                          kids[0])
        if isinstance(p, L.LogicalAggregateInPandas):
            from ..exec.python_udf import AggregateInPandasExec
            return AggregateInPandasExec(p.keys, p.aggs, p.key_names,
                                         kids[0])
        if isinstance(p, L.LogicalMapInBatch):
            from ..exec.python_udf import MapInBatchExec
            return MapInBatchExec(p.fn, p.out_schema, kids[0])
        if isinstance(p, L.LogicalCoGroupedMapInPandas):
            from ..exec.python_udf import CoGroupedMapInPandasExec
            return CoGroupedMapInPandasExec(p.left_keys, p.right_keys,
                                            p.fn, p.out_schema, kids[0],
                                            kids[1])
        if isinstance(p, L.LogicalWindowInPandas):
            from ..exec.python_udf import WindowInPandasExec
            return WindowInPandasExec(p.part_exprs, p.wins, kids[0])
        if isinstance(p, L.LogicalGenerate):
            from ..exec.generate import GenerateExec
            return GenerateExec(p.generator, kids[0], p.outer, p.position,
                                p.elem_name, p.pos_name)
        if isinstance(p, L.LogicalJoin):
            return self._convert_join(p, kids)
        raise PlanNotSupported(f"no conversion for {type(p).__name__}")


class TpuOverrides:
    """Entry point (reference `case class GpuOverrides` apply :4624)."""

    def __init__(self, conf: Optional[RapidsConf] = None):
        self.conf = conf or active_conf()

    def wrap_and_tag(self, plan: L.LogicalPlan) -> PlanMeta:
        meta = PlanMeta(plan, self.conf)
        meta.tag_for_tpu()
        from ..config import OPTIMIZER_ENABLED
        if self.conf.get(OPTIMIZER_ENABLED):
            from .cost import CostBasedOptimizer
            CostBasedOptimizer(self.conf).optimize(meta)
        self._emit_plan_decisions(meta)
        return meta

    @staticmethod
    def _emit_plan_decisions(meta: PlanMeta) -> None:
        """Plan-time why-not records (the reference's "will not run on
        GPU because ..." explain lines, as structured events): one
        `plan_fallback` per host-row-engine node, one `plan_not_on_tpu`
        per tag-off reason. One pointer check when logging is off."""
        from ..obs import events as obs_events
        if obs_events.active_bus() is None:
            return

        def walk(m: PlanMeta):
            node = m.plan.node_name()
            if m.host_fallback:
                reasons: List[str] = []
                for em in m.expr_metas:
                    em.collect_reasons(reasons)
                obs_events.emit("plan_fallback", node=node,
                                reasons=reasons)
            for r in m.reasons:
                obs_events.emit("plan_not_on_tpu", node=node, reason=r)
            for c in m.children:
                walk(c)

        walk(meta)

    def apply(self, plan: L.LogicalPlan) -> TpuExec:
        from ..udf_compiler import maybe_compile_plan_udfs
        plan = maybe_compile_plan_udfs(plan, self.conf)
        meta = self.wrap_and_tag(plan)
        if not self._all_ok(meta):
            raise PlanNotSupported(meta.explain())
        # whole-stage compilation (ISSUE 14): after conversion the
        # stage planner groups whitelisted operator chains into
        # CompiledStageExec nodes (one jitted program per stage per
        # batch) — conf-gated, no-op when stage.fusion is off
        from ..exec.stage_compiler import compile_stages
        return compile_stages(meta.convert(), self.conf)

    def explain(self, plan: L.LogicalPlan) -> str:
        return self.wrap_and_tag(plan).explain()

    @staticmethod
    def _all_ok(meta: PlanMeta) -> bool:
        if not meta.can_run_on_tpu:
            return False
        return all(TpuOverrides._all_ok(c) for c in meta.children)
