"""Planning engine (reference layer L2, SURVEY §2.2): logical plans, the
meta wrap->tag->convert framework, TypeSig checks and the override rule
tables that decide what runs on TPU."""

from .logical import (  # noqa: F401
    LogicalAggregate, LogicalFilter, LogicalJoin, LogicalLimit, LogicalPlan,
    LogicalProject, LogicalRange, LogicalScan, LogicalSort, LogicalUnion,
)
from .overrides import TpuOverrides, PlanNotSupported  # noqa: F401
