"""Meta wrap/tag framework — the reference's RapidsMeta.scala:83 rebuilt:
every plan node and expression is wrapped in a meta object that records
whether (and why not) it can run on TPU, powers the explain output
("will/will not run on TPU because ..."), and performs the conversion.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Type

from ..config import RapidsConf
from ..expr.core import Expression
from .typesig import TypeSig, commonly_supported


class BaseMeta:
    def __init__(self):
        self._reasons: List[str] = []

    def will_not_work_on_tpu(self, reason: str):
        if reason not in self._reasons:
            self._reasons.append(reason)

    @property
    def can_run_on_tpu(self) -> bool:
        return not self._reasons

    @property
    def reasons(self) -> List[str]:
        return list(self._reasons)


class ExprRule:
    """Registry entry for one expression class (reference GpuOverrides
    `expr[...]` rules, GpuOverrides.scala:919)."""

    def __init__(self, cls: Type[Expression], desc: str,
                 input_sig: TypeSig = commonly_supported,
                 output_sig: TypeSig = commonly_supported,
                 tag_fn: Optional[Callable[["ExprMeta"], None]] = None):
        self.cls = cls
        self.desc = desc
        self.input_sig = input_sig
        self.output_sig = output_sig
        self.tag_fn = tag_fn

    @property
    def name(self) -> str:
        return self.cls.__name__


class ExprMeta(BaseMeta):
    def __init__(self, expr: Expression, rule: Optional[ExprRule],
                 conf: RapidsConf, input_schema):
        super().__init__()
        self.expr = expr
        self.rule = rule
        self.conf = conf
        self.input_schema = input_schema
        self.children = [ExprMeta.wrap(c, conf, input_schema)
                         for c in expr.children]

    @staticmethod
    def wrap(expr: Expression, conf: RapidsConf, input_schema) -> "ExprMeta":
        from .overrides import expression_rules
        if input_schema is not None:
            # bind column references so type-signature checks see real
            # types (reference tags over resolved Catalyst expressions)
            from ..expr.core import resolve
            try:
                expr = resolve(expr, input_schema)
            except (KeyError, TypeError):
                pass  # unresolvable here (e.g. join pair scope)
        rules = expression_rules()
        rule = None
        for cls in type(expr).__mro__:
            rule = rules.get(cls)
            if rule is not None:
                break
        return ExprMeta(expr, rule, conf, input_schema)

    def tag_for_tpu(self):
        for c in self.children:
            c.tag_for_tpu()
            if not c.can_run_on_tpu:
                self.will_not_work_on_tpu(
                    f"child {type(c.expr).__name__} cannot run on TPU")
        if self.rule is None:
            self.will_not_work_on_tpu(
                f"no TPU implementation for expression "
                f"{type(self.expr).__name__}")
            return
        key = f"spark.rapids.sql.expression.{self.rule.name}"
        if str(self.conf._settings.get(key, "true")).lower() == "false":
            self.will_not_work_on_tpu(
                f"expression {self.rule.name} disabled by {key}")
        # decimal gating (reference decimalType.enabled)
        from ..config import DECIMAL_ENABLED
        from ..types import DecimalType
        if not self.conf.get(DECIMAL_ENABLED):
            # children tag themselves in the recursion above; only this
            # node's own output type needs checking here
            try:
                is_dec = isinstance(self.expr.data_type, DecimalType)
            except TypeError:
                is_dec = False
            if is_dec:
                self.will_not_work_on_tpu(
                    "decimal disabled by "
                    "spark.rapids.sql.decimalType.enabled")
        # type checks: children output types against the input signature
        for c in self.children:
            try:
                dt = c.expr.data_type
            except TypeError:
                continue  # unresolved; checked post-bind
            reason = self.rule.input_sig.reason_not_supported(dt)
            if reason:
                self.will_not_work_on_tpu(
                    f"input to {self.rule.name}: {reason}")
        try:
            out_dt = self.expr.data_type
            reason = self.rule.output_sig.reason_not_supported(out_dt)
            if reason:
                self.will_not_work_on_tpu(
                    f"output of {self.rule.name}: {reason}")
        except TypeError:
            pass
        if self.rule.tag_fn is not None:
            self.rule.tag_fn(self)

    def collect_reasons(self, out: List[str], prefix: str = ""):
        for r in self._reasons:
            out.append(f"{prefix}{type(self.expr).__name__}: {r}")
        for c in self.children:
            c.collect_reasons(out, prefix)
