"""Concurrent workload governor (ISSUE 7): fair admission with aging,
per-query memory quotas, overload shedding, semaphore grant fairness,
the heartbeat purge satellite, and the tooling surfaces.

Deterministic on single-core CPU, house style: ordering assertions are
driven by registration sequence (threads are started one at a time and
their queue residency is confirmed before the next starts), never by
sleep races; the concurrency acceptance drive compares every lane
against a numpy-derived single-threaded oracle."""

import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu import QueryAdmissionError, QueryCancelledError
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import lifecycle, workload
from spark_rapids_tpu.memory.budget import (memory_budget,
                                            reset_memory_budget)
from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                             reset_buffer_catalog)
from spark_rapids_tpu.memory.retry import TpuRetryOOM
from spark_rapids_tpu.memory.semaphore import reset_tpu_semaphore
from spark_rapids_tpu.obs import events
from spark_rapids_tpu.types import LONG, Schema

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

FAST = {
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
    "spark.rapids.tpu.retry.backoffMs": "1",
}

WL = dict(FAST, **{"spark.rapids.tpu.workload.enabled": "true"})


def _threads():
    return {t for t in threading.enumerate()
            if t.name.startswith(("pipeline-", "spill-writer"))}


@pytest.fixture(autouse=True)
def _workload_isolation():
    """Every test starts with a fresh governor and semaphore, a clean
    lifecycle, injection off, the conf restored, and leaks checked."""
    pre = _threads()
    prev_conf = C.active_conf()
    workload.reset_workload()
    lifecycle.reset_lifecycle()
    faults.install(None)
    yield
    faults.install(None)
    snap = workload.snapshot()
    workload.reset_workload()
    lifecycle.reset_lifecycle()
    reset_tpu_semaphore()
    C.set_active_conf(prev_conf)
    assert snap["queue_depth"] == 0 and snap["admitted"] == 0, snap
    assert _threads() <= pre, "leaked threads"


@pytest.fixture
def spy(monkeypatch):
    rows = []
    real = events.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy_emit)
    return rows


def _kinds(rows, kind):
    return [r for r in rows if r["kind"] == kind]


def _conf(**extra):
    settings = dict(WL)
    settings.update({k: str(v) for k, v in extra.items()})
    return C.RapidsConf(settings)


# ---------------------------------------------------------------------------
# fair admission ordering (unit, no threads)
# ---------------------------------------------------------------------------

def test_pick_next_is_priority_then_fifo_with_aging():
    """Weighted-fair ordering: interactive before batch, FIFO inside a
    class, and every AGING_EVERY-th grant the OLDEST waiter outright —
    so batch is granted long before the interactive stream drains."""
    m = workload.WorkloadManager()
    order_in = ["batch", "interactive", "batch", "interactive",
                "interactive", "interactive"]
    tickets = [workload.Ticket(p, seq=next(m._seq)) for p in order_in]
    m._queued.extend(tickets)
    order = []
    while m._queued:
        t = m._pick_next()
        m._queued.remove(t)
        m._grants += 1
        order.append(tickets.index(t))
    # hand-derived: seqs 1..6, ranks [1,0,1,0,0,0] —
    #   g0 (grants=0): min (rank, seq) -> seq2; g1 -> seq4; g2 -> seq5;
    #   g3 (aging, grants=3): oldest -> seq1 (the first BATCH arrival,
    #   granted ahead of two younger interactives); g4 -> seq6;
    #   g5 -> seq3 (batch)
    assert order == [1, 3, 4, 0, 5, 2]


def test_all_interactive_keeps_fifo():
    m = workload.WorkloadManager()
    tickets = [workload.Ticket("interactive", seq=next(m._seq))
               for _ in range(5)]
    m._queued.extend(tickets)
    order = []
    while m._queued:
        t = m._pick_next()
        m._queued.remove(t)
        m._grants += 1
        order.append(tickets.index(t))
    assert order == [0, 1, 2, 3, 4]


# ---------------------------------------------------------------------------
# admission / shedding (manager-level)
# ---------------------------------------------------------------------------

def test_direct_admission_and_release(spy):
    m = workload.manager()
    conf = _conf(**{"spark.rapids.tpu.workload.maxConcurrentQueries": 2})
    a = m.admit(conf, None)
    b = m.admit(conf, None)
    assert a.state == "admitted" and b.state == "admitted"
    assert m.admitted_count() == 2 and m.queued_count() == 0
    evs = _kinds(spy, "query_admitted")
    assert len(evs) == 2 and evs[0]["wait_ms"] == 0
    C.set_active_conf(conf)
    m.release(a)
    m.release(b)
    assert m.admitted_count() == 0
    assert a.state == "released" and b.state == "released"
    assert workload.counters()["admitted"] == 2


def test_queue_full_sheds_fast(spy):
    m = workload.manager()
    conf = _conf(**{"spark.rapids.tpu.workload.maxConcurrentQueries": 1,
                    "spark.rapids.tpu.workload.queueDepth": 0})
    a = m.admit(conf, None)
    t0 = time.monotonic()
    with pytest.raises(QueryAdmissionError) as ei:
        m.admit(conf, None)
    assert time.monotonic() - t0 < 2.0, "shed was not fast"
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_ms > 0
    assert faults.classify(ei.value) == "fatal", \
        "a shed query must not burn task-retry attempts"
    evs = _kinds(spy, "query_shed")
    assert len(evs) == 1 and evs[0]["reason"] == "queue_full"
    C.set_active_conf(conf)
    m.release(a)
    assert workload.counters()["shed"] == 1


def test_admission_timeout_sheds(spy):
    m = workload.manager()
    conf = _conf(**{
        "spark.rapids.tpu.workload.maxConcurrentQueries": 1,
        "spark.rapids.tpu.workload.admissionTimeoutMs": 80})
    a = m.admit(conf, None)
    with pytest.raises(QueryAdmissionError) as ei:
        m.admit(conf, None)
    assert ei.value.reason == "timeout"
    assert _kinds(spy, "query_shed")[0]["reason"] == "timeout"
    assert m.queued_count() == 0, "timed-out ticket left in the queue"
    C.set_active_conf(conf)
    m.release(a)


def test_deadline_infeasible_sheds(spy):
    m = workload.manager()
    conf = _conf(**{"spark.rapids.tpu.workload.maxConcurrentQueries": 1})
    a = m.admit(conf, None)
    ctx = lifecycle.QueryContext(timeout_ms=1)
    time.sleep(0.01)  # the whole wall-clock budget is gone
    with pytest.raises(QueryAdmissionError) as ei:
        m.admit(conf, ctx)
    assert ei.value.reason == "deadline_infeasible"
    C.set_active_conf(conf)
    m.release(a)


def test_open_device_breaker_sheds_at_admission(spy):
    """An OPEN device_dispatch breaker means dispatches are currently
    dying: admission sheds instead of feeding the degraded device —
    without consuming the breaker's half-open probe slot."""
    conf = C.RapidsConf(dict(WL, **{
        "spark.rapids.tpu.breaker.enabled": "true",
        "spark.rapids.tpu.breaker.threshold": "1",
        "spark.rapids.tpu.breaker.cooldownMs": "60000"}))
    C.set_active_conf(conf)
    lifecycle.record_domain_failure("device_dispatch")
    assert "device_dispatch" in lifecycle.open_breakers()
    m = workload.manager()
    # the consult must run on the ADMITTING conf: admission happens
    # before collect installs the session conf thread-locally, so a
    # fresh client thread's active_conf knows nothing of the breaker
    C.set_active_conf(C.RapidsConf(dict(FAST)))
    with pytest.raises(QueryAdmissionError) as ei:
        m.admit(conf, None)
    assert ei.value.reason == "breaker_open"
    assert 0 < ei.value.retry_after_ms <= 60000
    assert workload.counters()["shed"] == 1
    C.set_active_conf(conf)
    # the read-only consult must not have half-opened the breaker
    assert lifecycle.health()["breakers"]["device_dispatch"]["state"] \
        == "open"
    # kill-switch parity with breaker_allows: disabling the breaker
    # conf restores admission immediately
    off = C.RapidsConf(dict(WL, **{
        "spark.rapids.tpu.breaker.enabled": "false"}))
    C.set_active_conf(off)
    t = m.admit(off, None)
    m.release(t)


def test_cancel_query_dequeues_queued(spy):
    """cancel_query() on a QUEUED query raises QueryCancelledError with
    admission-wait phase attribution and leaves the queue clean."""
    assert "admission-wait" in lifecycle.CANCEL_PHASES
    m = workload.manager()
    conf = _conf(**{"spark.rapids.tpu.workload.maxConcurrentQueries": 1})
    a = m.admit(conf, None)
    owner = object()
    result = {}

    def queued_query():
        C.set_active_conf(conf)
        with lifecycle.governed(conf, owner=owner) as ctx:
            try:
                with workload.admitted(conf, ctx):
                    result["outcome"] = "admitted"
            except QueryCancelledError as e:
                result["outcome"] = e.phase

    t = threading.Thread(target=queued_query, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while m.queued_count() < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert m.queued_count() == 1, "query never queued"
    assert lifecycle.cancel_owner(owner) == 1
    t.join(timeout=10)
    assert not t.is_alive(), "cancelled queued query never unwound"
    assert result["outcome"] == "admission-wait"
    evs = _kinds(spy, "query_cancelled")
    assert len(evs) == 1 and evs[0]["phase"] == "admission-wait"
    assert m.queued_count() == 0
    C.set_active_conf(conf)
    m.release(a)


# ---------------------------------------------------------------------------
# per-query memory quotas
# ---------------------------------------------------------------------------

def test_quota_rebalances_as_queries_finish():
    m = workload.manager()
    conf = _conf(**{"spark.rapids.tpu.workload.maxConcurrentQueries": 4})
    C.set_active_conf(conf)
    a = m.admit(conf, None)
    assert m.quota_bytes(1000, 0.5) is None, \
        "a lone query gets the whole budget"
    b = m.admit(conf, None)
    assert m.quota_bytes(1000, 0.5) == 500
    c = m.admit(conf, None)
    # fraction floor beats the even split (soft oversubscription)
    assert m.quota_bytes(1000, 0.5) == 500
    assert m.quota_bytes(1000, 0.2) == 333
    m.release(c)
    assert m.quota_bytes(1000, 0.2) == 500
    m.release(b)
    assert m.quota_bytes(1000, 0.2) is None
    m.release(a)


def _governed_with_ticket(conf, ticket):
    """Install a governed context carrying `ticket` on this thread."""
    ctx = lifecycle.QueryContext()
    ctx.workload_ticket = ticket
    lifecycle.adopt_context(ctx)
    return ctx


def test_over_quota_reserve_spills_own_entries_first(spy):
    """The quota contract: under budget pressure an over-share query
    spills ITS OWN catalog entries (quota_spill event) — the
    under-share neighbor's residency is untouched on EVERY tier (the
    host-limit enforcement pass riding the owner-scoped spill must not
    demote a neighbor's host entry to disk either)."""
    import jax.numpy as jnp
    from spark_rapids_tpu.memory.catalog import (
        OUTPUT_FOR_SHUFFLE_PRIORITY, StorageTier)
    conf = C.RapidsConf(dict(WL, **{
        "spark.rapids.tpu.spill.asyncWrite": "false",
        "spark.rapids.tpu.workload.maxConcurrentQueries": "2"}))
    # same settings, 1-byte host soft limit: installed only for B's
    # pressure phase, so A can park an entry on the HOST tier first
    tiny_host = C.RapidsConf(dict(
        conf._settings,
        **{"spark.rapids.memory.host.spillStorageSize": "1"}))
    C.set_active_conf(conf)
    m = workload.manager()
    a = m.admit(conf, None)
    b = m.admit(conf, None)
    try:
        reset_buffer_catalog()
        reset_memory_budget(1 << 20)  # 1 MiB; shares = 512 KiB each
        cat = buffer_catalog()
        _governed_with_ticket(conf, a)
        h_a = cat.add(jnp.zeros(300 * 1024, jnp.uint8))  # A: 300 KiB
        # a second A entry parked on the HOST tier (spilled while the
        # host limit is roomy): bait for an unscoped host-limit pass
        h_a2 = cat.add(jnp.zeros(64 * 1024, jnp.uint8),
                       priority=OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(64 * 1024, owner=a)
        assert cat.tier_of(h_a2) == StorageTier.HOST
        assert a.device_bytes == 300 * 1024
        # B's phase runs with the 1-byte host limit: its own quota
        # spill would demote ANY host entry the enforcement pass sees
        C.set_active_conf(tiny_host)
        _governed_with_ticket(tiny_host, b)
        h_b = cat.add(jnp.zeros(600 * 1024, jnp.uint8))  # B: over share
        assert b.device_bytes == 600 * 1024
        # B reserves 200 KiB more: global pressure + B over quota ->
        # B's own entry spills, A's stays device-resident
        memory_budget().reserve(200 * 1024)
        memory_budget().release(200 * 1024)
        assert cat.tier_of(h_b) != StorageTier.DEVICE, \
            "the offender's entry did not spill"
        assert cat.tier_of(h_a) == StorageTier.DEVICE, \
            "a neighbor's entry was pushed down a tier"
        # the host-limit enforcement riding B's owner-scoped spill must
        # be owner-scoped too: A's parked HOST entry stays HOST even
        # though the limit is 1 byte (B's own spilled entry paid the
        # demotion instead)
        assert cat.tier_of(h_a2) == StorageTier.HOST, \
            "B's quota spill demoted a neighbor's HOST entry to disk"
        assert cat.tier_of(h_b) == StorageTier.DISK
        assert b.device_bytes == 0 and a.device_bytes == 300 * 1024
        evs = _kinds(spy, "quota_spill")
        assert len(evs) == 1
        assert evs[0]["quota"] == 512 * 1024
        assert evs[0]["freed"] == 600 * 1024
        assert workload.counters()["quota_spills"] == 1
        cat.remove(h_a)
        cat.remove(h_b)
    finally:
        lifecycle.adopt_context(None)
        m.release(b)
        m.release(a)
        reset_buffer_catalog()
        reset_memory_budget()


def test_over_quota_with_pinned_entries_raises_own_oom(spy):
    """When the over-share query's entries are all in use (nothing of
    its own to spill), pressure surfaces as ITS TpuRetryOOM — the
    neighbor is still untouched."""
    import jax.numpy as jnp
    conf = C.RapidsConf(dict(WL, **{
        "spark.rapids.tpu.spill.asyncWrite": "false",
        "spark.rapids.tpu.workload.maxConcurrentQueries": "2"}))
    C.set_active_conf(conf)
    m = workload.manager()
    a = m.admit(conf, None)
    b = m.admit(conf, None)
    try:
        reset_buffer_catalog()
        reset_memory_budget(1 << 20)
        cat = buffer_catalog()
        _governed_with_ticket(conf, a)
        h_a = cat.add(jnp.zeros(300 * 1024, jnp.uint8))
        _governed_with_ticket(conf, b)
        h_b = cat.add(jnp.zeros(600 * 1024, jnp.uint8))
        cat.acquire(h_b)  # pinned: unspillable
        with pytest.raises(TpuRetryOOM) as ei:
            memory_budget().reserve(200 * 1024)
        assert "quota" in str(ei.value)
        from spark_rapids_tpu.memory.catalog import StorageTier
        assert cat.tier_of(h_a) == StorageTier.DEVICE, \
            "a neighbor's entry was pushed down a tier"
        cat.release(h_b)
        cat.remove(h_a)
        cat.remove(h_b)
    finally:
        lifecycle.adopt_context(None)
        m.release(b)
        m.release(a)
        reset_buffer_catalog()
        reset_memory_budget()


def test_spill_for_retry_honors_quota_while_over_share():
    """The quota TpuRetryOOM lands in the OOM-retry lane, whose
    between-attempt spill runs spill_for_retry: while the query is
    still over its share, that pass too spills only ITS entries — an
    unfiltered pass would hand the offender the bytes its neighbors
    freed, undoing the reserve-path isolation one frame up."""
    import jax.numpy as jnp
    from spark_rapids_tpu.memory.budget import spill_for_retry
    from spark_rapids_tpu.memory.catalog import StorageTier
    conf = C.RapidsConf(dict(WL, **{
        "spark.rapids.tpu.spill.asyncWrite": "false",
        "spark.rapids.tpu.workload.maxConcurrentQueries": "2"}))
    C.set_active_conf(conf)
    m = workload.manager()
    a = m.admit(conf, None)
    b = m.admit(conf, None)
    try:
        reset_buffer_catalog()
        reset_memory_budget(1 << 20)  # shares = 512 KiB
        cat = buffer_catalog()
        _governed_with_ticket(conf, a)
        h_a = cat.add(jnp.zeros(300 * 1024, jnp.uint8))
        _governed_with_ticket(conf, b)
        h_b = cat.add(jnp.zeros(600 * 1024, jnp.uint8))  # over share
        spill_for_retry()  # B's thread, B over quota
        assert cat.tier_of(h_b) != StorageTier.DEVICE
        assert cat.tier_of(h_a) == StorageTier.DEVICE, \
            "the retry-lane spill stole a neighbor's working set"
        # B is now under share (device_bytes 0): the next pass is the
        # normal global one — A's entry is fair game again
        spill_for_retry()
        assert cat.tier_of(h_a) != StorageTier.DEVICE
        cat.remove(h_a)
        cat.remove(h_b)
    finally:
        lifecycle.adopt_context(None)
        m.release(b)
        m.release(a)
        reset_buffer_catalog()
        reset_memory_budget()


# ---------------------------------------------------------------------------
# semaphore fairness (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

def test_semaphore_grants_priority_then_fifo_with_aging():
    """N waiter threads across two simulated queries + one releaser:
    grants follow (priority, FIFO seq) with the AGING_EVERY-th grant
    going to the oldest waiter — deterministic ordering, never timing.
    Batch waiters are granted (no starvation) even though interactive
    waiters keep arriving behind them."""
    sem = reset_tpu_semaphore(1)
    assert sem.acquire_if_necessary(100)  # grant #1: pool is now empty
    priorities = ["batch", "interactive", "batch", "interactive",
                  "interactive", "interactive"]
    order = []
    threads = []

    def waiter(task_id, prio):
        ctx = lifecycle.QueryContext()
        ctx.workload_ticket = workload.Ticket(prio)
        lifecycle.adopt_context(ctx)
        try:
            assert sem.acquire_if_necessary(task_id)
            order.append(task_id)
            sem.release_if_necessary(task_id)
        finally:
            lifecycle.adopt_context(None)

    for i, prio in enumerate(priorities):
        t = threading.Thread(target=waiter, args=(i + 1, prio),
                             daemon=True)
        t.start()
        threads.append(t)
        # registration order IS the FIFO seq: confirm this waiter is in
        # line before starting the next (state wait, not a sleep race)
        deadline = time.monotonic() + 10
        while len(sem._pool._waiters) < i + 1 \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(sem._pool._waiters) == i + 1

    sem.release_if_necessary(100)
    for t in threads:
        t.join(timeout=15)
        assert not t.is_alive(), "a waiter starved"
    # seqs 2..7 (the releaser's uncontended acquire took seq 1 and
    # grant #1). grants 2,3: (rank, seq) -> tasks 2, 4; grant #4
    # (aging) -> oldest = task 1 (batch); grants 5,6 -> tasks 5, 6;
    # grant 7 -> task 3 (batch)
    assert order == [2, 4, 1, 5, 6, 3]
    assert sem.available == 1


def test_semaphore_waiter_gives_up_cleanly():
    """A cancelled waiter leaves the fair queue; the permit goes to the
    next in line, not to a ghost."""
    sem = reset_tpu_semaphore(1)
    assert sem.acquire_if_necessary(1)
    stop = threading.Event()
    got = []

    def cancelled_waiter():
        assert sem.acquire_if_necessary(2, cancel=stop.is_set) is False

    def patient_waiter():
        assert sem.acquire_if_necessary(3)
        got.append(3)
        sem.release_if_necessary(3)

    t1 = threading.Thread(target=cancelled_waiter, daemon=True)
    t1.start()
    deadline = time.monotonic() + 10
    while len(sem._pool._waiters) < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    t2 = threading.Thread(target=patient_waiter, daemon=True)
    t2.start()
    while len(sem._pool._waiters) < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    stop.set()
    t1.join(timeout=10)
    assert not t1.is_alive()
    sem.release_if_necessary(1)
    t2.join(timeout=10)
    assert not t2.is_alive() and got == [3]
    assert sem.available == 1


# ---------------------------------------------------------------------------
# heartbeat purge (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

def test_heartbeat_purges_long_dead_peers_and_recycles_slots(spy):
    from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager
    m = HeartbeatManager(timeout_s=0.03, purge_timeout_s=0.1)
    m.register("e1")
    m.register("e2")
    slot_e1 = m._peers["e1"].slot
    time.sleep(0.05)
    m.heartbeat("e2")  # e2 stays alive (silent 0.05 < purge 0.1)
    assert m.dead_peers() == ["e1"]  # dead but not yet purged
    time.sleep(0.06)  # e1 now silent ~0.11 > purge_timeout_s
    m.heartbeat("e2")
    # e1 silent past purge_timeout_s: forgotten entirely, its slot free
    assert m.dead_peers() == []
    assert "e1" not in m._peers and m._free_slots == [slot_e1]
    # re-registration after purge is clean (the _register_locked
    # contract): first beat == registration, recycled slot
    peers = m.heartbeat("e1")
    assert [p.executor_id for p in peers] == ["e2"]
    assert m._peers["e1"].slot == slot_e1 and m._free_slots == []
    assert set(m.live_peers()) == {"e1", "e2"}
    # registry stays bounded under churn: slots never exceed the peak
    # concurrent population
    assert m._next_slot == 2
    # a peer whose death was never polled still gets its ONE peer_dead
    # on the purge — and a peer that beats after crossing the purge
    # threshold is NOT purged by its own beat (no inverted transition
    # event for a peer that just proved alive)
    time.sleep(0.11)  # both now silent past purge_timeout_s
    spy.clear()
    m.heartbeat("e1")
    assert {e["executor_id"]
            for e in _kinds(spy, "peer_dead")} == {"e2"}
    assert "e1" in m._peers and "e2" not in m._peers


# ---------------------------------------------------------------------------
# concurrency acceptance drive (tier-1, deterministic)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def storm_files(tmp_path_factory):
    """Per-lane parquet inputs + numpy oracles for the storm drive —
    the PR 3/4 proven forced-spill shape (parquet scan -> filter ->
    join -> agg -> sort holds join/coalesce staging spillable across
    device calls, unlike a from_pydict scan)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("storm_q")
    lanes = []
    for seed in range(8):
        rng = np.random.default_rng(seed)
        n_l, n_o = 2000, 500
        l_key = rng.integers(0, n_o, n_l)
        l_val = rng.random(n_l) * 100.0
        l_flag = rng.integers(0, 4, n_l)
        o_flag = rng.integers(0, 10, n_o)
        lp = str(d / f"lines-{seed}.parquet")
        op = str(d / f"orders-{seed}.parquet")
        pq.write_table(pa.table({
            "l_key": pa.array(l_key, pa.int64()),
            "l_val": pa.array(l_val, pa.float64()),
            "l_flag": pa.array(l_flag, pa.int64())}), lp,
            row_group_size=512)
        pq.write_table(pa.table({
            "o_key": pa.array(np.arange(n_o), pa.int64()),
            "o_flag": pa.array(o_flag, pa.int64())}), op,
            row_group_size=128)
        keep = (l_flag != 0) & (o_flag[l_key] < 5)
        oracle = {}
        for k, v in zip(l_key[keep], l_val[keep]):
            s, c = oracle.get(int(k), (0.0, 0))
            oracle[int(k)] = (s + float(v), c + 1)
        lanes.append((lp, op, oracle))
    return lanes


def _run_storm_query(settings, lane):
    """scan -> filter -> join -> agg -> sort through the session."""
    from spark_rapids_tpu.api.functions import col, lit
    lp, op, _ = lane
    sess = TpuSession(settings)
    lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
    orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                  (F.count(), "cnt"))
    return agg.sort(("rev", False)).collect()


def _assert_matches_oracle(rows, oracle, label):
    """Keys/counts bit-exact, float sums 1e-9-relative: under a
    forced-spill budget OOM-retry SPLIT points depend on thread
    interleaving, so float reduction order may differ — the engine's
    documented improvedFloatOps divergence class."""
    got = {int(k): (rev, int(cnt)) for k, rev, cnt in rows}
    assert set(got) == set(oracle), label
    for k, (rev, cnt) in got.items():
        o_rev, o_cnt = oracle[k]
        assert cnt == o_cnt, (label, k)
        assert abs(rev - o_rev) <= 1e-9 * max(abs(o_rev), 1.0), \
            (label, k)


STORM = dict(WL, **{
    "spark.rapids.tpu.workload.maxConcurrentQueries": "2",
    "spark.rapids.tpu.workload.queueDepth": "8",
    "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
    "spark.rapids.sql.broadcastSizeThreshold": "-1",
    # two admitted lanes share the forced-spill budget: peaks depend
    # on interleaving, so give the OOM lane more attempts (with a real
    # backoff) to wait a neighbor's release out instead of exhausting
    "spark.rapids.sql.retry.maxAttempts": "50",
    "spark.rapids.tpu.retry.backoffMs": "5",
})


# moved to the slow tier by ISSUE 13 budget relief (92s: the 8-lane
# storm acceptance; fairness/quota/shed contracts stay tier-1 as units
# and the queueDepth-exceeded drive)
@pytest.mark.slow
def test_eight_concurrent_queries_match_single_threaded_oracle(
        spy, storm_files):
    """Acceptance criterion: 8 queries from 8 threads under a
    forced-spill device budget with the governor on all complete and
    match the single-threaded oracle; zero leaked threads; budget and
    catalog counters restored after the storm."""
    pre = _threads()
    try:
        reset_buffer_catalog()
        # one lane peaks ~60 KiB of staged spillables; 112 KiB forces
        # the two admitted lanes to spill against each other (probed
        # stable: every lane converges, spill bites every run)
        reset_memory_budget(112 * 1024)
        used_before = memory_budget().used
        entries_before = buffer_catalog().num_entries()
        results = [None] * 8

        def lane(i):
            try:
                results[i] = _run_storm_query(STORM, storm_files[i])
            except BaseException as e:  # noqa: BLE001 — asserted below
                results[i] = e

        threads = [threading.Thread(target=lane, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
            assert not t.is_alive(), "a lane wedged"
        for i in range(8):
            assert not isinstance(results[i], BaseException), results[i]
            _assert_matches_oracle(results[i], storm_files[i][2],
                                   f"lane {i}")
        # the storm actually contended: every lane was admitted, some
        # had to queue behind the 2 slots, none was shed
        cnt = workload.counters()
        assert cnt["admitted"] == 8 and cnt["shed"] == 0
        assert cnt["queued"] >= 1, "no queue residency: no contention"
        assert memory_budget().spill_requests > 0, \
            "budget never hit pressure — the forced-spill drive lost " \
            "its teeth"
        buffer_catalog().drain_writeback()
        assert memory_budget().used == used_before, "leaked budget"
        assert buffer_catalog().num_entries() == entries_before, \
            "leaked catalog entries"
        assert workload.snapshot()["admitted"] == 0
        # the catalog's singleton writer daemon is long-lived by
        # design; stop it so the leak check sees only true leaks
        buffer_catalog().shutdown_writer()
        assert _threads() <= pre, "storm leaked threads"
    finally:
        reset_buffer_catalog()
        reset_memory_budget()


def test_queue_depth_exceeded_sheds_while_survivors_stay_correct(spy):
    """Acceptance criterion: with queueDepth exceeded, shed queries
    raise QueryAdmissionError fast while the admitted survivors finish
    correct. Deterministic: the slot-holder blocks on an event, each
    arrival's queue state is confirmed before the next."""
    release = threading.Event()
    settings = dict(WL, **{
        "spark.rapids.tpu.workload.maxConcurrentQueries": "1",
        "spark.rapids.tpu.workload.queueDepth": "1",
        "spark.rapids.sql.batchSizeBytes": "4k"})
    sess1 = TpuSession(settings)
    m = workload.manager()

    def blocking_fn(it):
        for pdf in it:
            assert release.wait(60), "test driver never released"
            yield pdf

    df1 = sess1.from_pydict({"a": list(range(512))}, Schema.of(a=LONG),
                            batch_rows=128)
    out = {}

    def q1():
        out["q1"] = df1.map_in_pandas(
            blocking_fn, Schema.of(a=LONG)).collect()

    def q2():
        out["q2"] = sorted(
            TpuSession(settings).from_pydict(
                {"z": [1, 2, 3]}, Schema.of(z=LONG))
            .agg((F.sum("z"), "s")).collect())

    t1 = threading.Thread(target=q1, daemon=True)
    t1.start()
    deadline = time.monotonic() + 30
    while m.admitted_count() < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert m.admitted_count() == 1, "q1 never took the slot"
    t2 = threading.Thread(target=q2, daemon=True)
    t2.start()
    while m.queued_count() < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert m.queued_count() == 1, "q2 never queued"
    # the queue is full: the next arrival is shed FAST on this thread
    t0 = time.monotonic()
    with pytest.raises(QueryAdmissionError) as ei:
        TpuSession(settings).from_pydict(
            {"w": [9]}, Schema.of(w=LONG)).agg((F.sum("w"), "s")).collect()
    assert time.monotonic() - t0 < 5.0, "shed was not fast"
    assert ei.value.reason == "queue_full" and ei.value.retry_after_ms > 0
    assert _kinds(spy, "query_shed")[0]["reason"] == "queue_full"
    # survivors complete correct
    release.set()
    t1.join(timeout=60)
    t2.join(timeout=60)
    assert not t1.is_alive() and not t2.is_alive()
    assert sorted(out["q1"]) == [(i,) for i in range(512)]
    assert out["q2"] == [(6,)]
    assert workload.snapshot()["admitted"] == 0
    assert workload.counters()["shed"] == 1


def test_governed_session_health_and_admission_events(spy):
    sess = TpuSession(dict(WL))
    df = sess.from_pydict({"a": [1, 2, 3, 4]}, Schema.of(a=LONG))
    assert df.agg((F.sum("a"), "s")).collect() == [(10,)]
    h = sess.health()
    assert h["workload"]["queue_depth"] == 0
    assert h["workload"]["admitted"] == 0
    assert h["workload"]["counters"]["admitted"] == 1
    evs = _kinds(spy, "query_admitted")
    assert len(evs) == 1 and evs[0]["priority"] == "interactive"
    # priority class is a session/query property
    sess_b = TpuSession(dict(WL, **{
        "spark.rapids.tpu.workload.priority": "batch"}))
    dfb = sess_b.from_pydict({"a": [5]}, Schema.of(a=LONG))
    assert dfb.agg((F.sum("a"), "s")).collect() == [(5,)]
    assert _kinds(spy, "query_admitted")[-1]["priority"] == "batch"


# ---------------------------------------------------------------------------
# tooling: bench flags + profile_report roll-up
# ---------------------------------------------------------------------------

def test_bench_concurrency_flag(monkeypatch):
    import bench
    monkeypatch.setattr(bench, "_CONCURRENCY", 1)
    monkeypatch.setattr(bench, "_attr_prev", {})
    assert bench.maybe_concurrency(["bench.py"]) is None
    # bad argv: the usage-error JSON convention, never a traceback
    with pytest.raises(SystemExit):
        bench.maybe_concurrency(["bench.py", "--concurrency"])
    with pytest.raises(SystemExit):
        bench.maybe_concurrency(["bench.py", "--concurrency", "three"])
    with pytest.raises(SystemExit):
        bench.maybe_concurrency(["bench.py", "--concurrency", "0"])
    assert bench.maybe_concurrency(
        ["bench.py", "--concurrency", "3"]) == 3
    rec = bench.workload_attribution()
    assert rec["concurrency"] == 3
    assert set(rec) >= {"queued", "admitted", "shed", "quota_spills"}
    # deltas, not cumulative totals
    assert bench.workload_attribution()["admitted"] == 0
    # guarded_run admits every iteration through the governor
    seen = {}

    def probe():
        seen["ticket"] = workload.current_ticket() is not None
        return 7

    assert bench.guarded_run(probe) == 7
    assert seen["ticket"] is True
    assert bench.workload_attribution()["admitted"] == 1
    # run_concurrent fans a worker across the lane threads and
    # re-raises the first failure
    assert sorted(bench.run_concurrent(lambda i: i)) == [0, 1, 2]

    def boom(i):
        raise ValueError("lane died")

    with pytest.raises(ValueError):
        bench.run_concurrent(boom)


def test_profile_report_workload_rollup():
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import profile_report
    evs = [
        {"kind": "query_queued", "priority": "batch"},
        {"kind": "query_admitted", "wait_ms": 12},
        {"kind": "query_admitted", "wait_ms": 0},
        {"kind": "query_shed", "reason": "queue_full"},
        {"kind": "query_shed", "reason": "breaker_open"},
        {"kind": "quota_spill", "need": 1, "quota": 2, "freed": 3},
    ]
    report = profile_report.build_report(evs)
    assert "workload admissions: 2 (1 queued, max wait 12ms)" in report
    assert "queries shed: 2 (breaker_open:1, queue_full:1)" in report
    assert "quota spills: 1" in report


# ---------------------------------------------------------------------------
# slow tier: concurrent chaos soak
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_concurrent_chaos_converges(storm_files):
    """4 threads x seeded 5% faults x workload on: the governor
    composes with every recovery lane — per-lane results equal the
    fault-free oracle, zero leaked threads, budget/catalog restored."""
    pre = _threads()
    settings = dict(STORM, **{
        "spark.rapids.tpu.workload.maxConcurrentQueries": "2",
        "spark.rapids.tpu.task.maxAttempts": "20"})
    faults.install(";".join(
        part + ",max=2" for part in
        faults.uniform_spec(0.05, seed=3).split(";")))
    try:
        reset_buffer_catalog()
        reset_memory_budget(112 * 1024)
        used_before = memory_budget().used
        results = [None] * 4

        def lane(i):
            try:
                results[i] = _run_storm_query(settings, storm_files[i])
            except BaseException as e:  # noqa: BLE001 — asserted below
                results[i] = e

        threads = [threading.Thread(target=lane, args=(i,), daemon=True)
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "a chaos lane wedged"
        for i in range(4):
            assert not isinstance(results[i], BaseException), results[i]
            _assert_matches_oracle(results[i], storm_files[i][2],
                                   f"chaos lane {i}")
        buffer_catalog().drain_writeback()
        assert memory_budget().used == used_before
        buffer_catalog().shutdown_writer()
        assert _threads() <= pre, "chaos storm leaked threads"
    finally:
        faults.install(None)
        reset_buffer_catalog()
        reset_memory_budget()
