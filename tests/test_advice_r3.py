"""Regression tests pinning the round-3 advisor fixes (ADVICE r3, fixed in
round 4 — VERDICT r4 asked for these to exist)."""

import decimal as dec

import jax
import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DecimalType, LONG, STRING, ArrayType, MapType, Schema, StructField,
)

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


# --- r3 #1: element_at with a non-literal index must dispatch on the
# child's resolved type (array -> per-row index, map -> key lookup)
def test_element_at_expression_index_on_array():
    s = TpuSession()
    df = s.from_pydict(
        {"a": [[10, 20, 30], [5], None, [7, 8]],
         "i": [2, 1, 1, -1]},
        schema=Schema((StructField("a", ArrayType(LONG)),
                       StructField("i", LONG))))
    got = [r[0] for r in
           df.select(F.element_at(col("a"), col("i")).alias("r")).collect()]
    assert got == [20, 5, None, 8]


def test_element_at_expression_key_on_map():
    s = TpuSession()
    df = s.from_pydict(
        {"m": [{"a": 1, "b": 2}, {"c": 3}, None],
         "k": ["b", "x", "a"]},
        schema=Schema((StructField("m", MapType(STRING, LONG)),
                       StructField("k", STRING))))
    got = [r[0] for r in
           df.select(F.element_at_key(col("m"), col("k")).alias("r"))
           .collect()]
    assert got == [2, None, None]


# --- r3 #3: distributed (partial->exchange->final) decimal sums must agree
# with the single-stage plan on VALUE and RESULT TYPE (Spark: p+10 capped)
@needs_8
@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_decimal_sum_result_type_matches_across_tiers():
    t = DecimalType(7, 2)
    vals = [dec.Decimal(f"{x}.25") for x in range(50)] + [None]
    data = {"k": [i % 3 for i in range(51)], "v": vals}
    sch = Schema((StructField("k", LONG), StructField("v", t)))
    no_bcast = {"spark.rapids.sql.broadcastSizeThreshold": "-1"}

    def run(sess):
        df = sess.from_pydict(data, sch, batch_rows=16)
        q = df.group_by("k").agg((F.sum(F.col("v")), "sv"))
        ex = q._exec()
        rows = sorted(q.collect())
        return rows, ex.output_schema.fields[1].data_type

    rows1, t1 = run(TpuSession(no_bcast))
    rows8, t8 = run(TpuSession(no_bcast, mesh_devices=8))
    assert rows1 == rows8
    assert t1 == t8 == DecimalType(17, 2)  # 7 + 10


# --- r3 #4: sub-partition count k must key off the side that is BUILT
# (right, for non-swappable joins), not min(sizes)
@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_adaptive_k_uses_build_side_for_nonswappable():
    sess = TpuSession(conf={
        "spark.rapids.sql.broadcastSizeThreshold": "1",
        "spark.rapids.sql.join.subPartitionThreshold": "4096",
        "spark.rapids.shuffle.mode": "MULTITHREADED"})
    # LEFT tiny (below threshold), RIGHT huge (above): a left_outer join
    # cannot swap, so the build side is RIGHT and must sub-partition even
    # though min(size_l, size_r) is under the threshold
    left = sess.from_pydict(
        {"k": [1, 2, 3], "x": [10, 20, 30]},
        schema=Schema((StructField("k", LONG), StructField("x", LONG)))
    ).group_by("k").agg((F.sum(F.col("x")), "sx"))
    n = 4000
    right = sess.from_pydict(
        {"k": [i % 800 for i in range(n)], "y": list(range(n))},
        schema=Schema((StructField("k", LONG), StructField("y", LONG)))
    ).group_by("k").agg((F.sum(F.col("y")), "sy"))
    q = left.join(right, on="k", how="left_outer")
    ex = q._exec()
    out = sorted(ex.collect())
    from tests.test_adaptive_join import _find_adaptive
    aj = _find_adaptive(ex)
    assert aj is not None and aj._choice == "subpartition", \
        (aj and aj._choice, aj and aj._measured)
    # values still correct
    oracle = {}
    for i in range(n):
        oracle[i % 800] = oracle.get(i % 800, 0) + i
    assert out == [(k, x * 10, oracle.get(k))
                   for k, x in [(1, 1), (2, 2), (3, 3)]]
