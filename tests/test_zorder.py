"""Z-order tests (reference zorder/ZOrderRules + GpuInterleaveBits +
Delta OPTIMIZE ZORDER BY)."""

import numpy as np

from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.delta import DeltaTable
from spark_rapids_tpu.expr.zorder import InterleaveBits
from spark_rapids_tpu.types import DOUBLE, LONG, Schema, StructField


def _host_interleave(vals, n_keys):
    """Independent oracle: MSB-first round-robin interleave of the
    sign-flipped 64-bit keys, 64//n bits per key."""
    bits_per = 64 // n_keys
    out = 0
    total = n_keys * bits_per
    ranks = [(v & ((1 << 64) - 1)) ^ (1 << 63) for v in vals]
    for b in range(total):
        src_bit = 63 - (b // n_keys)
        dst_bit = total - 1 - b
        bit = (ranks[b % n_keys] >> src_bit) & 1
        out |= bit << dst_bit
    out ^= 1 << 63  # signed-storage flip, mirrors the kernel
    return out - (1 << 64) if out >= (1 << 63) else out


def test_interleave_matches_oracle_and_orders():
    sess = TpuSession()
    sch = Schema((StructField("x", LONG), StructField("y", LONG)))
    rng = np.random.default_rng(0)
    data = {"x": [int(v) for v in rng.integers(-1000, 1000, 64)],
            "y": [int(v) for v in rng.integers(-1000, 1000, 64)]}
    df = sess.from_pydict(data, sch)
    got = [r[0] for r in df.select(
        InterleaveBits(col("x"), col("y")).alias("z")).collect()]
    expect = [_host_interleave([x, y], 2)
              for x, y in zip(data["x"], data["y"])]
    assert got == expect
    # order preservation along each axis (other key fixed)
    one = sess.from_pydict({"x": [-5, 0, 7], "y": [3, 3, 3]}, sch)
    zs = [r[0] for r in one.select(
        InterleaveBits(col("x"), col("y")).alias("z")).collect()]
    assert zs == sorted(zs)


def test_delta_optimize_zorder(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    sch = Schema((StructField("x", LONG), StructField("y", LONG)))
    rng = np.random.default_rng(1)
    # several commits -> several small files
    for _ in range(4):
        sess.from_pydict(
            {"x": [int(v) for v in rng.integers(0, 100, 50)],
             "y": [int(v) for v in rng.integers(0, 100, 50)]},
            sch).write_delta(path, mode="append")
    before = DeltaTable.for_path(sess, path).log.snapshot()
    assert len(before.files) == 4
    rows_before = sorted(sess.read_delta(path).collect())

    removed = DeltaTable.for_path(sess, path).optimize(zorder_by=["x", "y"])
    assert removed == 4
    after = DeltaTable.for_path(sess, path).log.snapshot()
    assert len(after.files) == 1          # compacted
    assert sorted(sess.read_delta(path).collect()) == rows_before
    hist = DeltaTable.for_path(sess, path).history()
    assert hist[-1]["operation"] == "OPTIMIZE"
