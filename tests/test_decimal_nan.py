"""Regression tests for decimal arithmetic rescaling and Spark NaN ordering
(code-review findings on the initial kernel drop)."""

import math

import pytest

from spark_rapids_tpu.types import DOUBLE, DecimalType, LONG, Schema, STRING
from spark_rapids_tpu.columnar import ColumnarBatch, Column
from spark_rapids_tpu.expr import (
    Cast, Divide, EqualTo, EqualNullSafe, Greatest, GreaterThan,
    IntegralDivide, Least, col, lit, resolve,
)


def ev(expr, batch):
    bound = resolve(expr, batch.schema)
    return bound.columnar_eval(batch).to_pylist(batch.num_rows_host)


def dec_batch():
    """a: decimal(10,2) = [1.00, 2.50, 12.34]; b: decimal(10,0) = [2, 3, 4]."""
    import numpy as np
    from spark_rapids_tpu.types import Schema, StructField
    a = Column.from_numpy(np.array([100, 250, 1234], np.int64), DecimalType(10, 2))
    b = Column.from_numpy(np.array([2, 3, 4], np.int64), DecimalType(10, 0))
    schema = Schema((StructField("a", DecimalType(10, 2)),
                     StructField("b", DecimalType(10, 0))))
    return ColumnarBatch([a, b], 3, schema)


def unscaled(expr, batch):
    bound = resolve(expr, batch.schema)
    c = bound.columnar_eval(batch)
    return c.dtype, c.to_pylist(batch.num_rows_host)


def test_decimal_add_rescales():
    b = dec_batch()
    dt, vals = unscaled(col("a") + col("b"), b)
    # 1.00+2 = 3.00 ; 2.50+3 = 5.50 ; 12.34+4 = 16.34 at scale 2
    assert dt.scale == 2
    assert vals == [300, 550, 1634]


def test_decimal_multiply():
    b = dec_batch()
    dt, vals = unscaled(col("a") * col("b"), b)
    # scale s1+s2 = 2: 2.00, 7.50, 49.36
    assert dt.scale == 2
    assert vals == [200, 750, 4936]


def test_decimal_divide():
    b = dec_batch()
    dt, vals = unscaled(col("a") / col("b"), b)
    # Spark result scale: max(6, s1+p2+1) = 13 -> adjusted; 1.00/2 = 0.5
    assert vals[0] == 5 * 10 ** (dt.scale - 1)
    # 2.50/3 = 0.8333... round HALF_UP at result scale
    expect = round((250 / 3) * 10 ** (dt.scale - 2))
    assert abs(vals[1] - expect) <= 1


def test_decimal_integral_divide():
    b = dec_batch()
    assert ev(IntegralDivide(col("a"), col("b")), b) == [0, 0, 3]


def test_nan_equality():
    b = ColumnarBatch.from_pydict(
        {"x": [float("nan"), 1.0, float("nan")],
         "y": [float("nan"), float("nan"), 2.0]},
        Schema.of(x=DOUBLE, y=DOUBLE))
    # Spark: NaN = NaN is TRUE; NaN > everything
    assert ev(EqualTo(col("x"), col("y")), b) == [True, False, False]
    assert ev(GreaterThan(col("x"), col("y")), b) == [False, False, True]
    assert ev(GreaterThan(col("y"), col("x")), b) == [False, True, False]
    assert ev(EqualNullSafe(col("x"), col("y")), b) == [True, False, False]


def test_nan_least_greatest():
    b = ColumnarBatch.from_pydict(
        {"x": [float("nan"), 5.0], "y": [1.0, float("nan")]},
        Schema.of(x=DOUBLE, y=DOUBLE))
    assert ev(Least(col("x"), col("y")), b) == [1.0, 5.0]
    out = ev(Greatest(col("x"), col("y")), b)
    assert math.isnan(out[0]) and math.isnan(out[1])


def test_round_negative_scale_ints():
    from spark_rapids_tpu.expr import Round
    from spark_rapids_tpu.types import INT
    b = ColumnarBatch.from_pydict({"i": [-14, -15, 14, 15, -16]},
                                  Schema.of(i=INT))
    # Spark HALF_UP at -1: -14 -> -10, -15 -> -20 (away from zero), 15 -> 20
    assert ev(Round(col("i"), -1), b) == [-10, -20, 10, 20, -20]


def test_parse_long_min():
    b = ColumnarBatch.from_pydict(
        {"s": ["-9223372036854775808", "9223372036854775807",
               "9223372036854775808", "-9223372036854775809"]},
        Schema.of(s=STRING))
    assert ev(Cast(col("s"), LONG), b) == [-(2**63), 2**63 - 1, None, None]


def test_log1p_domain():
    from spark_rapids_tpu.expr import Log1p
    b = ColumnarBatch.from_pydict({"x": [-2.0, -1.0, 0.0]}, Schema.of(x=DOUBLE))
    assert ev(Log1p(col("x")), b) == [None, None, 0.0]


def test_if_strings_byte_budget():
    """Row-wise string blend where the selection needs bytes from both sides."""
    from spark_rapids_tpu.expr import If
    n = 8
    b = ColumnarBatch.from_pydict(
        {"f": [True, False] * (n // 2),
         "s": ["x" * 40] * n, "t": ["y" * 40] * n},
        Schema.of(f=__import__("spark_rapids_tpu.types", fromlist=["BOOLEAN"]).BOOLEAN,
                  s=STRING, t=STRING))
    out = ev(If(col("f"), col("s"), col("t")), b)
    assert out == ["x" * 40 if i % 2 == 0 else "y" * 40 for i in range(n)]
