"""Bounded approx_percentile sketch (VERDICT r4 item 8; reference
GpuApproximatePercentile.scala:41-76): groups beyond the K-point budget
stay within the rank-accuracy contract; small groups stay exact; buffers
are bounded across multi-batch merges."""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.aggexprs import ApproxPercentile
from spark_rapids_tpu.types import DOUBLE, LONG, Schema, StructField


def _run(data, sch, aggs, batch_rows=None):
    sess = TpuSession()
    df = sess.from_pydict(data, sch, batch_rows=batch_rows)
    return df.group_by("k").agg(*aggs).collect()


def test_small_groups_stay_exact():
    rng = np.random.default_rng(0)
    n = 3000
    ks = rng.integers(0, 5, n).tolist()
    vs = rng.normal(0, 100, n).tolist()
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    # accuracy 2000 -> K=4000 > any group: sketch path but EXACT content
    rows = _run({"k": ks, "v": vs}, sch,
                [(ApproxPercentile(col("v"), 0.5, 2000), "p")],
                batch_rows=512)
    got = dict(rows)
    for key in set(ks):
        grp = sorted(v for k, v in zip(ks, vs) if k == key)
        exact = grp[int(np.ceil(0.5 * len(grp))) - 1]
        assert got[key] == pytest.approx(exact), key


@pytest.mark.parametrize("p", [0.05, 0.5, 0.95])
def test_large_group_within_accuracy_contract(p):
    rng = np.random.default_rng(1)
    n = 60000
    vs = rng.normal(0, 1000, n).tolist()
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    acc = 200  # K=400 << n: the sketch must actually compress
    rows = _run({"k": [1] * n, "v": vs}, sch,
                [(ApproxPercentile(col("v"), p, acc), "p")],
                batch_rows=8192)
    got = rows[0][1]
    srt = sorted(vs)
    # rank-accuracy contract: returned value's rank within n/acc * slack
    # (a few merge levels; contract bound is n/acc per Spark)
    import bisect
    r = bisect.bisect_left(srt, got)
    target = int(np.ceil(p * n)) - 1
    assert abs(r - target) <= 4 * n // acc, (r, target, n // acc)


def test_multi_batch_merge_bounded_and_sane():
    rng = np.random.default_rng(2)
    n = 40000
    ks = (rng.integers(0, 3, n)).tolist()
    vs = rng.uniform(0, 1, n).tolist()
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    rows = _run({"k": ks, "v": vs}, sch,
                [(ApproxPercentile(col("v"), 0.5, 100), "p")],
                batch_rows=2048)  # ~20 partial batches get merged
    got = dict(rows)
    for key in set(ks):
        grp = sorted(v for k, v in zip(ks, vs) if k == key)
        med = grp[len(grp) // 2]
        assert abs(got[key] - med) < 0.08, (key, got[key], med)


def test_with_nulls_and_multiple_percentages():
    vs = [1.0, 2.0, None, 3.0, 4.0, None, 5.0]
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    rows = _run({"k": [1] * 7, "v": vs}, sch,
                [(ApproxPercentile(col("v"), [0.0, 0.5, 1.0]), "p")])
    assert rows[0][1] == [1.0, 3.0, 5.0]


def test_all_null_group_yields_null():
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    rows = _run({"k": [1, 1, 2], "v": [None, None, 7.0]}, sch,
                [(ApproxPercentile(col("v"), 0.5), "p")])
    got = dict(rows)
    assert got[1] is None and got[2] == 7.0


def test_integral_input_returns_input_type():
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rows = _run({"k": [1] * 5, "v": [10, 20, 30, 40, 50]}, sch,
                [(ApproxPercentile(col("v"), 0.5), "p")])
    assert rows[0][1] == 30 and isinstance(rows[0][1], int)
