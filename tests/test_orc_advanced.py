"""ORC stripe-statistics pruning + options (VERDICT r4 item 5; reference
GpuOrcScan.scala:1455-1546). Prove-absence semantics: a stripe is skipped
only when its statistics PROVE no row matches; results always equal the
unpruned read."""

import datetime as dt
import os

import numpy as np
import pyarrow as pa
import pyarrow.orc as paorc
import pytest

from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.io.orc import OrcSource, write_orc


@pytest.fixture(scope="module")
def orc_file(tmp_path_factory):
    # ~8 stripes of 1024 rows each with monotone `a` so min/max prune
    path = str(tmp_path_factory.mktemp("orc") / "t.orc")
    n = 8192
    t = pa.table({
        "a": pa.array(range(n), pa.int64()),
        "d": pa.array([float(i) * 0.5 for i in range(n)], pa.float64()),
        "s": pa.array([f"k{i:06d}" for i in range(n)]),
        "dt": pa.array([dt.date(2020, 1, 1) + dt.timedelta(days=i // 100)
                        for i in range(n)]),
        "nul": pa.array([None if i % 2 else i for i in range(n)],
                        pa.int64()),
    })
    paorc.write_table(t, path, stripe_size=1)
    f = paorc.ORCFile(path)
    assert f.nstripes >= 4, f.nstripes  # the test needs real stripes
    return path, n, f.nstripes


def test_stripe_pruning_int_predicate(orc_file):
    path, n, nstripes = orc_file
    src = OrcSource(path, filters=[("a", "<", 1000)])
    rows = sum(b.num_rows_host for b in src.batches())
    assert src.stripes_pruned > 0
    assert src.stripes_read + src.stripes_pruned == nstripes
    # prove-absence: every matching row survives pruning
    assert rows >= 1000


def test_pruned_scan_equals_full_scan(orc_file):
    path, n, _ = orc_file
    full = OrcSource(path)
    vals_full = sorted(
        v for b in full.batches()
        for v in b.columns[0].to_pylist(b.num_rows_host))
    pruned = OrcSource(path, filters=[("a", ">=", 5000)])
    vals_pruned = sorted(
        v for b in pruned.batches()
        for v in b.columns[0].to_pylist(b.num_rows_host))
    assert pruned.stripes_pruned > 0
    # pruning keeps a superset of matches and a subset of the full scan
    assert set(v for v in vals_full if v >= 5000) <= set(vals_pruned)
    assert set(vals_pruned) <= set(vals_full)


def test_string_and_double_and_date_stats(orc_file):
    path, n, nstripes = orc_file
    assert OrcSource(path, filters=[("s", ">", "k999999")]).stripes_read == 0 \
        or True  # counters update on drive, not construction
    src = OrcSource(path, filters=[("s", ">", "k999999")])
    assert sum(b.num_rows_host for b in src.batches()) == 0
    assert src.stripes_pruned == nstripes
    src2 = OrcSource(path, filters=[("d", "<", 0.0)])
    assert sum(b.num_rows_host for b in src2.batches()) == 0
    assert src2.stripes_pruned == nstripes
    src3 = OrcSource(path,
                     filters=[("dt", ">", dt.date(2021, 1, 1))])
    assert sum(b.num_rows_host for b in src3.batches()) == 0
    assert src3.stripes_pruned == nstripes


def test_null_stats(orc_file):
    path, n, nstripes = orc_file
    # `a` has no nulls anywhere: IS NULL prunes every stripe
    src = OrcSource(path, filters=[("a", "is_null", None)])
    assert sum(b.num_rows_host for b in src.batches()) == 0
    assert src.stripes_pruned == nstripes
    # `nul` has nulls in every stripe: nothing prunable
    src2 = OrcSource(path, filters=[("nul", "is_null", None)])
    assert src2.stripes_pruned == 0 or \
        sum(1 for _ in src2.batches()) >= 0


def test_planner_pushes_filters_to_orc(orc_file, tmp_path):
    path, n, _ = orc_file
    sess = TpuSession()
    df = sess.read_orc(path).filter(col("a") < lit(512))
    got = sorted(r[0] for r in df.select(col("a")).collect())
    assert got == list(range(512))


def test_coalescing_reader_type(orc_file):
    path, n, _ = orc_file
    src = OrcSource(path, reader_type="COALESCING", batch_rows=1 << 14)
    rows = sum(b.num_rows_host for b in src.batches())
    assert rows == n


def test_zlib_file_stats_parse(tmp_path):
    path = str(tmp_path / "z.orc")
    t = pa.table({"x": pa.array(range(4096), pa.int64())})
    paorc.write_table(t, path, stripe_size=1, compression="zlib")
    nstripes = paorc.ORCFile(path).nstripes
    src = OrcSource(path, filters=[("x", ">", 10 ** 9)])
    assert sum(b.num_rows_host for b in src.batches()) == 0
    assert src.stripes_pruned == nstripes  # zlib footers parse fine


def test_unsupported_codec_degrades_to_no_pruning(tmp_path):
    path = str(tmp_path / "zstd.orc")
    t = pa.table({"x": pa.array(range(4096), pa.int64())})
    paorc.write_table(t, path, stripe_size=1, compression="zstd")
    src = OrcSource(path, filters=[("x", ">", 10 ** 9)])
    rows = sum(b.num_rows_host for b in src.batches())
    assert rows == 4096  # nothing pruned; the Filter above stays exact
    assert src.stripes_pruned == 0


def test_column_pruning_and_write_options(tmp_path, orc_file):
    path, n, _ = orc_file
    src = OrcSource(path, columns=["s", "a"])
    assert [f.name for f in src.schema.fields] == ["s", "a"]
    b = next(iter(src.batches()))
    assert len(b.columns) == 2
    # write round trip with options
    sess = TpuSession()
    df = sess.read_orc(path, columns=["a"])
    out = str(tmp_path / "out.orc")
    write_orc(df, out, compression="zlib", stripe_size=64 * 1024)
    back = OrcSource(out)
    assert sum(bb.num_rows_host for bb in back.batches()) == n
