"""Map-type columns: representation, kernels, planner integration."""
import numpy as np

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar import ColumnarBatch, MapColumn
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)
from spark_rapids_tpu.types import (ArrayType, IntegerType, LONG, MapType,
                                    STRING, Schema, StructField,
                                    StructType)

MT = MapType(STRING, LONG)
ROWS = [{"a": 1, "b": 2}, {}, None, {"x": None, "a": 9}]


def _sch(**kw):
    return Schema(tuple(StructField(k, v) for k, v in kw.items()))


def test_map_column_roundtrip():
    b = ColumnarBatch.from_pydict({"m": ROWS}, _sch(m=MT))
    assert b.columns[0].to_pylist(4) == ROWS


def test_nested_array_ingestion():
    at = ArrayType(ArrayType(IntegerType()))
    rows = [[[1], [2, 2]], [], None, [[3, 4]]]
    b = ColumnarBatch.from_pydict({"a": rows}, _sch(a=at))
    assert b.columns[0].to_pylist(4) == rows


def test_array_of_struct_ingestion():
    st = ArrayType(StructType((StructField("x", LONG),
                               StructField("y", STRING))))
    rows = [[{"x": 1, "y": "a"}, None], None, []]
    b = ColumnarBatch.from_pydict({"s": rows}, _sch(s=st))
    assert b.columns[0].to_pylist(3) == rows


def test_map_arrow_roundtrip():
    import pyarrow as pa
    t = pa.table({"m": pa.array(ROWS, pa.map_(pa.string(), pa.int64()))})
    b = ColumnarBatch.from_arrow(t)
    assert isinstance(b.columns[0], MapColumn)
    assert b.to_pydict()["m"] == ROWS
    back = b.to_arrow()
    assert back.column("m").to_pylist() == [
        list(r.items()) if r is not None else None for r in ROWS]


def test_map_shuffle_serialization():
    b = ColumnarBatch.from_pydict({"m": ROWS}, _sch(m=MT))
    rt = deserialize_batch(serialize_batch(b), b.schema)
    assert rt.columns[0].to_pylist(4) == ROWS


def test_map_lookup_and_views():
    sess = TpuSession()
    df = sess.from_pydict({"m": ROWS, "k": ["a", "a", "a", "x"]},
                          schema=_sch(m=MT, k=STRING))
    q = df.select(
        F.element_at(F.col("m"), "a").alias("va"),
        F.get_map_value(F.col("m"), F.col("k")).alias("vk"),
        F.map_keys(F.col("m")).alias("ks"),
        F.map_values(F.col("m")).alias("vs"),
        F.map_contains_key(F.col("m"), "b").alias("hb"),
        F.size(F.col("m")).alias("sz"))
    assert "host" not in q.explain()
    out = q.collect()
    assert out[0] == (1, 1, ["a", "b"], [1, 2], True, 2)
    assert out[1] == (None, None, [], [], False, 0)
    assert out[2] == (None, None, None, None, None, None)
    assert out[3] == (9, None, ["x", "a"], [None, 9], False, 2)


def test_create_map_and_filter():
    sess = TpuSession()
    df = sess.from_pydict({"k1": ["p", "q"], "v1": [1, 2]},
                          schema=_sch(k1=STRING, v1=LONG))
    out = df.select(F.create_map(F.col("k1"), F.col("v1"),
                                 F.lit("z"), F.lit(0)).alias("m")).collect()
    assert out == [({"p": 1, "z": 0},), ({"q": 2, "z": 0},)]
    df2 = sess.from_pydict({"m": [{"a": 1}, {"b": 2}, None],
                            "x": [1, 2, 3]}, _sch(m=MT, x=LONG))
    out2 = df2.where(F.col("x") > F.lit(1)).select(F.col("m")).collect()
    assert out2 == [({"b": 2},), (None,)]


def test_map_explode():
    sess = TpuSession()
    df = sess.from_pydict({"m": [{"a": 1, "b": 2}, {}, None, {"c": 3}]},
                          schema=_sch(m=MT))
    out = df.explode(F.col("m")).collect()
    assert [(r[-2], r[-1]) for r in out] == [("a", 1), ("b", 2), ("c", 3)]


def test_int_key_map():
    mt = MapType(LONG, STRING)
    rows = [{1: "x", 2: "y"}, None, {7: None}]
    sess = TpuSession()
    df = sess.from_pydict({"m": rows}, _sch(m=mt))
    out = df.select(F.element_at(F.col("m"), 2).alias("v"),
                    F.element_at(F.col("m"), 7).alias("w")).collect()
    assert out == [("y", None), (None, None), (None, None)]


def test_map_payload_through_explode():
    # a map PAYLOAD column duplicated by explode must size its entry
    # (and string byte) buckets from measurement, not silently truncate
    sess = TpuSession()
    big = {chr(97 + i) * 3: i for i in range(6)}
    df = sess.from_pydict(
        {"a": [[1, 2, 3, 4], [5, 6, 7, 8]], "m": [big, big]},
        schema=Schema((StructField("a", ArrayType(LONG)),
                       StructField("m", MT))))
    out = df.explode(F.col("a")).collect()
    assert len(out) == 8
    assert all(r[1] == big for r in out)


def test_duplicate_keys_first_wins_everywhere():
    sess = TpuSession()
    df = sess.from_pydict({"v1": [10], "v2": [20]},
                          schema=_sch(v1=LONG, v2=LONG))
    q = df.select(F.create_map(F.lit("a"), F.col("v1"),
                               F.lit("a"), F.col("v2")).alias("m"))
    m_expr = q.select(F.element_at(F.col("m"), "a").alias("v"))
    assert m_expr.collect() == [(10,)]        # lookup: first wins
    assert q.collect() == [({"a": 10},)]      # materialize: first wins


def test_map_contains_key_column():
    sess = TpuSession()
    df = sess.from_pydict({"m": [{"a": 1}, {"b": 2}], "k": ["a", "a"]},
                          schema=_sch(m=MT, k=STRING))
    out = df.select(F.map_contains_key(F.col("m"), F.col("k"))
                    .alias("c")).collect()
    assert out == [(True,), (False,)]


def test_element_at_null_key():
    sess = TpuSession()
    df = sess.from_pydict({"m": [{"a": 1}]}, schema=_sch(m=MT))
    out = df.select(F.get_map_value(F.col("m"), F.lit(None)).alias("v"))
    assert out.collect() == [(None,)]
