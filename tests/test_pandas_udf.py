"""Pandas UDF exec family (VERDICT r4 item 3): grouped map
(applyInPandas), grouped agg, mapInPandas, cogrouped map and
window-in-pandas, vs Python oracles. Reference
execution/python/GpuFlatMapGroupsInPandasExec.scala:79 and siblings."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DOUBLE, LONG, STRING, Schema, StructField,
)


def _df(sess, n=50, batch_rows=16):
    rng = np.random.default_rng(5)
    ks = [["a", "b", "c", None][i] for i in rng.integers(0, 4, n)]
    vs = [int(x) for x in rng.integers(-50, 50, n)]
    vs[3] = None
    data = {"k": ks, "v": vs,
            "d": [float(x) for x in rng.normal(0, 5, n)]}
    sch = Schema((StructField("k", STRING), StructField("v", LONG),
                  StructField("d", DOUBLE)))
    return sess.from_pydict(data, sch, batch_rows=batch_rows), data


def test_apply_in_pandas_grouped_map():
    sess = TpuSession()
    df, data = _df(sess)

    out_sch = Schema((StructField("k", STRING),
                      StructField("v_centered", DOUBLE)))

    def center(g: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({
            "k": g["k"],
            "v_centered": g["v"] - g["v"].mean()})

    got = df.group_by("k").apply_in_pandas(center, out_sch).collect()

    exp = []
    for key in set(data["k"]):
        vs = [v for k, v in zip(data["k"], data["v"]) if k == key]
        mean = np.nanmean([np.nan if v is None else v for v in vs])
        for k, v in zip(data["k"], data["v"]):
            if k == key:
                exp.append((key, None if v is None else v - mean))
    from collections import Counter
    norm = lambda rows: Counter(
        (k, None if v is None or (isinstance(v, float) and np.isnan(v))
         else round(float(v), 9)) for k, v in rows)
    assert norm(got) == norm(exp)


def test_apply_in_pandas_multi_batch_group_and_expr_key():
    # groups span multiple input batches; key is an EXPRESSION
    sess = TpuSession()
    sch = Schema((StructField("x", LONG),))
    df = sess.from_pydict({"x": list(range(40))}, sch, batch_rows=8)

    out_sch = Schema((StructField("parity", LONG),
                      StructField("n", LONG),
                      StructField("s", LONG)))

    def summarize(g):
        return pd.DataFrame({"parity": [int(g["x"].iloc[0] % 2)],
                             "n": [len(g)], "s": [int(g["x"].sum())]})

    got = sorted(df.group_by(col("x") % F.lit(2))
                 .apply_in_pandas(summarize, out_sch).collect())
    evens = [x for x in range(40) if x % 2 == 0]
    odds = [x for x in range(40) if x % 2 == 1]
    assert got == [(0, 20, sum(evens)), (1, 20, sum(odds))]


def test_agg_in_pandas():
    sess = TpuSession()
    df, data = _df(sess)

    def wmean(v: pd.Series, d: pd.Series) -> float:
        w = d.abs() + 1.0
        m = v.notna()
        return float((v[m] * w[m]).sum() / w[m].sum())

    got = dict(df.group_by("k").agg_in_pandas(
        (wmean, "wm", DOUBLE, [col("v"), col("d")])).collect())

    for key in set(data["k"]):
        vs = [(v, d) for k, v, d in
              zip(data["k"], data["v"], data["d"]) if k == key]
        num = sum(v * (abs(d) + 1.0) for v, d in vs if v is not None)
        den = sum(abs(d) + 1.0 for v, d in vs if v is not None)
        assert got[key] == pytest.approx(num / den), key


def test_map_in_pandas_streams_batches():
    sess = TpuSession()
    sch = Schema((StructField("x", LONG),))
    df = sess.from_pydict({"x": list(range(30))}, sch, batch_rows=10)

    out_sch = Schema((StructField("y", LONG),))
    seen = []

    def doubler(frames):
        for pdf in frames:
            seen.append(len(pdf))
            yield pd.DataFrame({"y": pdf["x"] * 2})

    got = sorted(r[0] for r in
                 df.map_in_pandas(doubler, out_sch).collect())
    assert got == [2 * x for x in range(30)]
    # the exec streams per incoming batch (upstream coalescing may merge
    # small scans, so exact batch count is the engine's choice)
    assert sum(seen) == 30 and len(seen) >= 1


def test_cogrouped_apply_in_pandas():
    sess = TpuSession()
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", LONG)))
    left = sess.from_pydict({"k": [1, 1, 2, 3], "v": [10, 11, 20, 30]},
                            lsch)
    right = sess.from_pydict({"k": [1, 2, 2, 4], "w": [5, 6, 7, 8]}, rsch)

    out_sch = Schema((StructField("k", LONG), StructField("lv", LONG),
                      StructField("rw", LONG)))

    def merge(lg, rg):
        k = lg["k"].iloc[0] if len(lg) else rg["k"].iloc[0]
        return pd.DataFrame({
            "k": [int(k)],
            "lv": [int(lg["v"].sum()) if len(lg) else 0],
            "rw": [int(rg["w"].sum()) if len(rg) else 0]})

    got = sorted(left.group_by("k").cogroup(right.group_by("k"))
                 .apply_in_pandas(merge, out_sch).collect())
    assert got == [(1, 21, 5), (2, 20, 13), (3, 30, 0), (4, 0, 8)]


def test_window_in_pandas_broadcast():
    sess = TpuSession()
    df, data = _df(sess, n=30)

    def spread(v: pd.Series) -> float:
        return float(v.max() - v.min())

    rows = df.window_in_pandas("k", (spread, "sp", DOUBLE, col("v"))) \
        .collect()
    exp = {}
    for key in set(data["k"]):
        vs = [v for k, v in zip(data["k"], data["v"])
              if k == key and v is not None]
        exp[key] = float(max(vs) - min(vs))
    assert len(rows) == 30
    for k, v, d, sp in rows:
        assert sp == pytest.approx(exp[k]), k


def test_apply_in_pandas_empty_input():
    sess = TpuSession()
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    df = sess.from_pydict({"k": [], "v": []}, sch)
    out_sch = Schema((StructField("k", LONG), StructField("n", LONG)))
    got = df.group_by("k").apply_in_pandas(
        lambda g: pd.DataFrame({"k": [g["k"].iloc[0]], "n": [len(g)]}),
        out_sch).collect()
    assert got == []


def test_nan_and_null_keys_are_distinct_groups():
    # Spark groups NaN as a real value, distinct from NULL
    sess = TpuSession()
    sch = Schema((StructField("k", DOUBLE), StructField("v", LONG)))
    df = sess.from_pydict(
        {"k": [1.0, float("nan"), None, float("nan"), None, 1.0],
         "v": [1, 2, 3, 4, 5, 6]}, sch)
    out_sch = Schema((StructField("n", LONG), StructField("s", LONG)))
    got = sorted(df.group_by("k").apply_in_pandas(
        lambda g: pd.DataFrame({"n": [len(g)], "s": [int(g["v"].sum())]}),
        out_sch).collect())
    # three groups: 1.0 -> {1,6}, NaN -> {2,4}, NULL -> {3,5}
    assert got == [(2, 6), (2, 7), (2, 8)]
