"""Regression tests for the round-4 advisor findings (ADVICE.md r4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.ops.aggregate import groupby_aggregate
from spark_rapids_tpu.ops.basic import masked_compaction_order
from spark_rapids_tpu.types import (
    DOUBLE, LONG, ArrayType, Schema, StructField,
)


def _group_sums(keys, vals, dtype):
    k = Column.from_pylist(keys, LONG)
    v = Column.from_pylist(vals, dtype, capacity=k.capacity)
    out_keys, results, num_groups = groupby_aggregate(
        [k], [("sum", v)], jnp.int32(len(keys)), k.capacity, 0)
    ng = int(num_groups)
    ks = out_keys[0].to_pylist(ng)
    tag, (data, valid) = results[0]
    assert tag == "raw"
    return dict(zip(ks, np.asarray(data)[:ng].tolist()))


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_float_sum_not_prefix_differenced():
    # ADVICE r4 high: a tiny group sorted after huge groups must not lose
    # its sum to global-cumsum cancellation. Group 0: 1e12-scale; group 1:
    # ten 1e-6 values -> exact sum 1e-5.
    keys = [0] * 200 + [1] * 10
    vals = [1e12] * 200 + [1e-6] * 10
    got = _group_sums(keys, vals, DOUBLE)
    assert got[1] == pytest.approx(1e-5, rel=1e-9)
    assert got[0] == pytest.approx(200e12, rel=1e-12)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_int_sum_prefix_tier_exact():
    # integer sums stay on the cumsum-difference tier and are exact
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 37, 4000).tolist()
    vals = rng.integers(-(2 ** 40), 2 ** 40, 4000).tolist()
    got = _group_sums(keys, vals, LONG)
    exp = {}
    for k, v in zip(keys, vals):
        exp[k] = exp.get(k, 0) + v
    assert {k: int(s) for k, s in got.items()} == exp


def test_masked_compaction_order_tail_fail_safe():
    keep = jnp.asarray([True, False, True, False, True, False, False, False])
    perm, n = masked_compaction_order(keep, jnp.int32(6))
    assert int(n) == 3
    p = np.asarray(perm)
    assert p[:3].tolist() == [0, 2, 4]
    # tail slots are -1, not dropped-row indices
    assert (p[3:] == -1).all()


@pytest.fixture(scope="module")
def adf():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(LONG)),
                  StructField("i", LONG)))
    return s.from_pydict(
        {"a": [[1, 2, 3], [4], None, [5, 6]],
         "i": [0, 1, 0, 2]}, sch)


def test_element_at_literal_zero_raises(adf):
    with pytest.raises(ValueError, match="indices start at 1"):
        adf.select(F.element_at(col("a"), 0).alias("r")).collect()


def test_element_at_col_zero_is_null_documented_deviation(adf):
    # per-row expression index: rows with index 0 yield NULL (documented
    # deviation from Spark's runtime raise, ops/collection.element_at_col)
    out = [r[0] for r in
           adf.select(F.element_at(col("a"), col("i")).alias("r")).collect()]
    assert out == [None, 4, None, 6]


def test_exchange_skewed_partition_streams_in_pieces():
    # ADVICE r3 #2 / VERDICT r4 Weak #6: a skewed shard must NOT be
    # concatenated whole at yield — the exchange streams its staged
    # pieces, and partition-aware consumers take boundaries from
    # execute_partitions()
    import numpy as np
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.types import LONG, STRING, Schema, StructField
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.exchange import HostShuffleExchangeExec
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.expr.core import col

    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    # every row hashes to the same key -> ONE skewed partition, fed in
    # several input batches so several shuffle blocks exist
    batches = [ColumnarBatch.from_pydict(
        {"k": [7] * 64, "v": list(range(i * 64, (i + 1) * 64))}, sch)
        for i in range(4)]
    ex = HostShuffleExchangeExec([col("k")],
                                 InMemoryScanExec(batches, sch), 4,
                                 RapidsConf({}))
    parts = list(ex.execute_partitions())
    assert len(parts) == 4
    sizes = []
    rows = []
    for gen in parts:
        got = list(gen)
        sizes.append(len(got))
        rows.extend(r for b in got for r in b.to_pylist())
    # the skewed partition arrived as MULTIPLE pieces (one per map block)
    assert max(sizes) > 1, sizes
    assert sorted(r[1] for r in rows) == list(range(256))
