"""CPU fallback tests: unsupported-on-device expressions run on the host
row engine behind ColumnarToRow/RowToColumnar transitions instead of
failing the plan (reference: GpuOverrides.scala:4427 convertToCpu +
integration tests' allow_non_gpu marker; SURVEY §2.2 transitions)."""

import numpy as np
import pytest

from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec.fallback import row_eval, supports_host_eval
from spark_rapids_tpu.expr import stringexprs as S
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.plan.overrides import PlanNotSupported
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField


def _schema():
    return Schema((StructField("s", STRING), StructField("v", LONG)))


def _data(n=120):
    rng = np.random.default_rng(0)
    return {
        "s": [None if x % 7 == 0 else ["abc1", "a1b2c3", "xyz", "aa-bb",
                                       "Hello World", ""][int(x) % 6]
              for x in rng.integers(0, 100, n)],
        "v": [None if x % 11 == 0 else int(x)
              for x in rng.integers(-100, 100, n)],
    }


# ---------------------------------------------------------------------------
# host row interpreter semantics
# ---------------------------------------------------------------------------

def test_row_eval_three_valued_logic():
    e = (col("a") > lit(1)) & (col("b") > lit(1))
    from spark_rapids_tpu.expr.core import resolve
    from spark_rapids_tpu.types import Schema, StructField
    sch = Schema((StructField("a", LONG), StructField("b", LONG)))
    b = resolve(e, sch)
    assert row_eval(b, (2, 2)) is True
    assert row_eval(b, (0, None)) is False      # False AND NULL = False
    assert row_eval(b, (2, None)) is None       # True AND NULL = NULL


def test_row_eval_divide_by_zero_is_null():
    from spark_rapids_tpu.expr.arithmetic import Divide
    assert row_eval(Divide(lit(1.0), lit(0.0)), ()) is None


def test_row_eval_in_with_null_items():
    from spark_rapids_tpu.expr.predicates import In
    e = In(lit(5), [1, 2, None])
    assert row_eval(e, ()) is None   # no match + null item → NULL
    e2 = In(lit(2), [1, 2, None])
    assert row_eval(e2, ()) is True


def test_supports_host_eval_rejects_unknown():
    from spark_rapids_tpu.expr.hashexprs import Murmur3Hash
    assert not supports_host_eval(Murmur3Hash([col("s")]))
    assert supports_host_eval(S.RLike(col("s"), r"(a)\1"))


# ---------------------------------------------------------------------------
# planner integration
# ---------------------------------------------------------------------------

def test_backreference_regex_falls_back_to_host():
    """Device regex rejects backreferences; host `re` handles them — the
    plan must sandwich a HostFilterExec between transitions."""
    sess = TpuSession()
    df = sess.from_pydict(_data(), _schema())
    q = df.filter(S.RLike(col("s"), r"(a)\1"))  # 'aa-bb' rows match
    tree = q._exec().tree_string()
    assert "HostFilterExec" in tree
    assert "RowToColumnarExec" in tree and "ColumnarToRowExec" in tree
    got = q.collect()
    expect = [(s, v) for s, v in zip(_data()["s"], _data()["v"])
              if s is not None and "aa" in s]
    assert sorted(got, key=repr) == sorted(expect, key=repr)


def test_disabled_expression_falls_back_project():
    """Disabling a device expression rule (reference
    spark.rapids.sql.expression.* conf) reroutes the projection through
    the host engine with identical results."""
    on = TpuSession()
    off = TpuSession({"spark.rapids.sql.expression.Upper": "false"})
    data, sch = _data(), _schema()

    def q(sess):
        df = sess.from_pydict(data, sch)
        return df.select(S.Upper(col("s")).alias("u"),
                         (col("v") + lit(1)).alias("w"))

    tree_off = q(off)._exec().tree_string()
    assert "HostProjectExec" in tree_off
    tree_on = q(on)._exec().tree_string()
    assert "HostProjectExec" not in tree_on
    assert q(on).collect() == q(off).collect()


def test_fallback_disabled_raises_with_report():
    sess = TpuSession({"spark.rapids.sql.cpuFallback.enabled": "false"})
    df = sess.from_pydict(_data(), _schema())
    with pytest.raises(PlanNotSupported) as ei:
        df.filter(S.RLike(col("s"), r"(a)\1"))._exec()
    assert "cannot run on TPU" in str(ei.value)


def test_explain_marks_host_fallback():
    sess = TpuSession()
    df = sess.from_pydict(_data(), _schema())
    report = df.filter(S.RLike(col("s"), r"(a)\1")).explain()
    assert "will run on CPU" in report


def test_host_engine_mixed_pipeline():
    """Fallback node in the middle: device scan → host filter → device
    aggregate keeps running on device above the transition."""
    from spark_rapids_tpu.api import functions as F
    sess = TpuSession()
    data, sch = _data(200), _schema()
    df = sess.from_pydict(data, sch)
    q = (df.filter(S.RLike(col("s"), r"(a)\1|(b)\2"))
           .group_by("s").agg((F.count(), "c")))
    tree = q._exec().tree_string()
    assert "HostFilterExec" in tree and "AggregateExec" in tree
    got = dict((k, c) for k, c in q.collect())
    import re as _re
    expect = {}
    for s in data["s"]:
        if s is not None and _re.search(r"(a)\1|(b)\2", s):
            expect[s] = expect.get(s, 0) + 1
    assert got == expect
