"""Whole-stage compilation (ISSUE 14): engine-level fusion on/off
equality for q1- and q3-shaped plans (incl. the PR 3 forced-spill
parquet recipe), the dispatch_summary acceptance rates (q3 fused
filter->probe->partial-agg chain <= 1.5 dispatches/output-batch, q1's
chain at 1.0), the plan-fingerprint program cache (a second collect()
of an identical plan compiles ZERO new programs), map-stage fusion,
breaker demotion to per-operator execution, the stage-boundary chaos
fault point, the stage_fused event, and the report/bench surfaces."""

from __future__ import annotations

import json
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import stage_compiler
from spark_rapids_tpu.exec.stage_compiler import (CompiledStageExec,
                                                  compile_stages)
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs import dispatch, events
from spark_rapids_tpu.types import (DoubleType, IntegerType, LongType,
                                    Schema, StructField)

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import profile_report  # noqa: E402

INT, LONG, DOUBLE = IntegerType(), LongType(), DoubleType()

OFF = {"spark.rapids.tpu.stage.fusion.enabled": "false"}


@pytest.fixture(autouse=True)
def _fresh_planes():
    dispatch.reset_dispatch_ledger()
    stage_compiler.reset_stage_counters()
    events.reset_event_bus()
    yield
    dispatch.reset_dispatch_ledger()
    stage_compiler.reset_stage_counters()
    events.reset_event_bus()


def _q1_query(sess, n=3000, batch_rows=None):
    rng = np.random.default_rng(0)
    schema = Schema((StructField("k", INT), StructField("q", LONG),
                     StructField("p", DOUBLE)))
    df = sess.from_pydict({"k": rng.integers(0, 6, n).tolist(),
                           "q": rng.integers(1, 50, n).tolist(),
                           "p": (rng.random(n) * 10).tolist()},
                          schema, batch_rows=batch_rows)
    return (df.filter(col("q") <= lit(40))
              .group_by("k").agg((Sum(col("p")), "s"), (Count(), "c")))


Q3_CONF = {"spark.rapids.sql.broadcastSizeThreshold": "-1",
           "spark.rapids.tpu.agg.speculative.enabled": "false"}


def _q3_query(sess, n=800):
    rng = np.random.default_rng(1)
    osch = Schema((StructField("o", LONG), StructField("d", LONG)))
    lsch = Schema((StructField("o", LONG), StructField("x", DOUBLE)))
    orders = sess.from_pydict(
        {"o": list(range(n)), "d": rng.integers(0, 100, n).tolist()},
        osch)
    lines = sess.from_pydict(
        {"o": [int(v) for v in rng.integers(0, n, 2 * n)],
         "x": (rng.random(2 * n) * 5).tolist()}, lsch)
    return (orders.filter(col("d") < lit(50))
                  .join(lines, on="o")
                  .group_by("o").agg((Sum(col("x")), "rev")))


def _stage_row(sess):
    rows = [r for r in
            sess.last_query_profile().dispatch_summary()["stages"]
            if r["op"] == "CompiledStageExec"]
    assert rows, "no CompiledStageExec in the plan"
    return rows[0]


# -- planner shape -----------------------------------------------------------

def test_q1_plan_compiles_filter_project_agg_chain():
    sess = TpuSession()
    plan = _q1_query(sess)._exec()
    assert isinstance(plan, CompiledStageExec)
    assert plan._kind == "agg"
    ops = [type(o).__name__ for o in plan._absorbed]
    assert ops[0] == "AggregateExec" and "FilterExec" in ops


def test_q3_plan_compiles_join_agg_chain():
    sess = TpuSession(Q3_CONF)
    plan = _q3_query(sess)._exec()
    assert isinstance(plan, CompiledStageExec)
    assert plan._kind == "join_agg"
    ops = [type(o).__name__ for o in plan._absorbed]
    assert ops[0] == "AggregateExec" and ops[-1] == "HashJoinExec"


def test_fusion_off_is_a_noop_rewrite():
    sess = TpuSession(OFF)
    plan = _q1_query(sess)._exec()
    assert not isinstance(plan, CompiledStageExec)
    # and compile_stages itself returns the tree untouched
    assert compile_stages(plan, sess.conf) is plan


def test_bare_group_by_stays_per_operator():
    """A group-by with NO absorbed chain is already one program per
    batch — wrapping it would only rename its profile row."""
    sess = TpuSession()
    schema = Schema((StructField("k", INT), StructField("v", LONG)))
    df = sess.from_pydict({"k": [1, 2, 1], "v": [3, 4, 5]}, schema)
    plan = df.group_by("k").agg((Sum(col("v")), "s"))._exec()
    assert not isinstance(plan, CompiledStageExec)


# -- engine-level equality ---------------------------------------------------

def test_q1_fusion_on_off_byte_identical():
    on = sorted(_q1_query(TpuSession()).collect())
    off = sorted(_q1_query(TpuSession(OFF)).collect())
    assert on == off  # CPU byte-identical (same fold, same programs)


def test_q3_fusion_on_off_byte_identical():
    on = sorted(_q3_query(TpuSession(Q3_CONF)).collect())
    off = sorted(_q3_query(TpuSession(dict(Q3_CONF, **OFF))).collect())
    assert on == off


def test_q3_speculative_tier_on_off_equality():
    """With agg speculation ON the q3 cardinality trips the bucket
    table and the plan re-runs exact — the stage must replay the same
    trip-and-rerun contract."""
    conf = {"spark.rapids.sql.broadcastSizeThreshold": "-1"}
    on = sorted(_q3_query(TpuSession(conf)).collect())
    off = sorted(_q3_query(TpuSession(dict(conf, **OFF))).collect())
    assert on == off


def test_empty_input_corners_match_per_op():
    sess_on, sess_off = TpuSession(), TpuSession(OFF)
    schema = Schema((StructField("k", INT), StructField("v", LONG)))
    for sess, out in ((sess_on, {}), (sess_off, {})):
        df = sess.from_pydict({"k": [1, 2], "v": [3, 4]}, schema)
        # filter removes everything -> keyed agg emits nothing
        keyed = (df.filter(col("v") > lit(100))
                   .group_by("k").agg((Sum(col("v")), "s"))).collect()
        # grand aggregate over empty input still emits one row
        grand = (df.filter(col("v") > lit(100))
                   .agg((Count(), "c"))).collect()
        out["keyed"], out["grand"] = keyed, grand
        if sess is sess_on:
            on = dict(out)
    assert on["keyed"] == keyed == []
    assert on["grand"] == grand == [(0,)]


def _rows_equal_float_tolerant(xs, ys, float_cols=(1,)):
    if len(xs) != len(ys):
        return False
    for x, y in zip(xs, ys):
        for i, (a, b) in enumerate(zip(x, y)):
            if i in float_cols:
                if abs(a - b) > 1e-9 * max(abs(a), abs(b), 1.0):
                    return False
            elif a != b:
                return False
    return True


def test_forced_spill_parquet_equality(tmp_path):
    """The PR 3 forced-spill recipe (scan->filter->join->agg->sort
    parquet shape, 192 KiB budget): the catalog really spills under
    the fused stage, and results match the per-op path (float sums to
    reduction-order tolerance — OOM splits depend on interleaving)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.memory.budget import reset_memory_budget
    from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                                 reset_buffer_catalog)
    rng = np.random.default_rng(3)
    n_l, n_o = 4000, 500
    lp = str(tmp_path / "lines.parquet")
    op = str(tmp_path / "orders.parquet")
    pq.write_table(pa.table({
        "l_key": pa.array(rng.integers(0, n_o, n_l), pa.int64()),
        "l_val": pa.array(rng.random(n_l) * 100.0, pa.float64()),
        "l_flag": pa.array(rng.integers(0, 4, n_l), pa.int64()),
    }), lp, row_group_size=512)
    pq.write_table(pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(rng.integers(0, 10, n_o), pa.int64()),
    }), op, row_group_size=128)

    results, spilled, fused = {}, {}, {}
    try:
        for mode, settings in (("on", {}), ("off", dict(OFF))):
            reset_buffer_catalog()
            reset_memory_budget(192 * 1024)
            sess = TpuSession(dict(
                settings,
                **{"spark.rapids.memory.spillDirectory": str(tmp_path)}))
            lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
            orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
            j = lines.join(orders, left_on=["l_key"],
                           right_on=["o_key"])
            agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                          (F.count(), "cnt"))
            before = stage_compiler.counters()["executions"]
            results[mode] = agg.sort(("rev", False)).collect()
            fused[mode] = stage_compiler.counters()["executions"] - before
            spilled[mode] = buffer_catalog().spilled_device_bytes
    finally:
        reset_buffer_catalog()
        reset_memory_budget()
    assert spilled["on"] > 0 and spilled["off"] > 0  # the budget DID bite
    assert fused["on"] > 0 and fused["off"] == 0  # the stage DID engage
    assert _rows_equal_float_tolerant(results["on"], results["off"])


# -- acceptance: dispatches per output batch ---------------------------------

def test_q1_chain_one_dispatch_per_output_batch():
    """Acceptance (ISSUE 14): the fused q1 chain runs at 1.0
    dispatches/output-batch — the whole filter->project->partial-agg
    chain is ONE program per input batch (vs the 4.0 the PR 13
    baseline measured at 4 input batches/execution)."""
    sess = TpuSession()
    q = _q1_query(sess)
    q.collect()
    row = _stage_row(sess)
    assert row["dispatches_per_batch"] == 1.0, row
    assert row["programs"] >= 1


def test_q3_chain_dispatch_rate_acceptance():
    """Acceptance (ISSUE 14): the fused filter->probe->partial-agg
    chain at <= 1.5 dispatches/output-batch (vs HashJoinExec 3.0 +
    AggregateExec 2.0 in the PR 13 baseline); a WARM execution —
    sizing cache hot, build fused into the first step — is exactly
    1.0."""
    sess = TpuSession(Q3_CONF)
    q = _q3_query(sess)
    q.collect()  # cold: sizing program + fused step
    cold = _stage_row(sess)
    assert cold["dispatches_per_batch"] <= 2.0, cold
    prev = None
    for _ in range(2):
        q.collect()
        row = _stage_row(sess)
        # warm execution (fresh exec instance per collect; the sizing
        # cache is fingerprint-shared): ONE dispatch, ONE output batch
        assert row["dispatches"] == 1 and row["batches"] == 1, row
        assert row["dispatches_per_batch"] == 1.0
        prev = row
    # cumulative over cold + 2 warm executions: (2 + 1 + 1) / 3 <= 1.5
    total_d = cold["dispatches"] + 2 * prev["dispatches"]
    total_b = cold["batches"] + 2 * prev["batches"]
    assert total_d / total_b <= 1.5


# -- acceptance: plan-fingerprint program cache ------------------------------

@pytest.mark.parametrize("conf,build", [({}, _q1_query),
                                        (Q3_CONF, _q3_query)],
                         ids=["q1", "q3"])
def test_second_collect_is_all_cache_hits(conf, build):
    """Acceptance (ISSUE 14): a second collect() of an identical plan
    reports 100% ledger cache hits — ZERO fresh traces (every
    DataFrame.collect() rebuilds its exec tree; the program cache
    hands the rebuilt execs their already-compiled programs)."""
    sess = TpuSession(conf)
    q = build(sess)
    r1 = sorted(q.collect())
    c1 = dispatch.counters()
    assert c1["traces"] > 0  # the first collect really compiled
    r2 = sorted(q.collect())
    c2 = dispatch.counters()
    assert r1 == r2
    assert c2["traces"] == c1["traces"], "second collect re-traced"
    delta = c2["dispatches"] - c1["dispatches"]
    assert delta > 0
    assert c2["cache_hits"] - c1["cache_hits"] == delta  # 100% hits


def test_fingerprints_distinguish_plans_and_conf():
    """Soundness: semantically DIFFERENT plans (another predicate) or a
    different trace-affecting conf (agg bucket slots) never share
    program sites."""
    sess = TpuSession()
    p1 = _q1_query(sess)._exec()
    sess2 = TpuSession()
    p2 = sess2.from_pydict(
        {"k": [1], "q": [2], "p": [3.0]},
        Schema((StructField("k", INT), StructField("q", LONG),
                StructField("p", DOUBLE)))) \
        .filter(col("q") <= lit(7)) \
        .group_by("k").agg((Sum(col("p")), "s"), (Count(), "c"))._exec()
    assert p1.plan_fingerprint() != p2.plan_fingerprint()
    sess3 = TpuSession({"spark.rapids.tpu.agg.bucketSlots": "16"})
    p3 = _q1_query(sess3)._exec()
    assert p1.plan_fingerprint() != p3.plan_fingerprint()
    # identical plan + conf => identical fingerprint (the cache key)
    sess4 = TpuSession()
    p4 = _q1_query(sess4)._exec()
    assert p1.plan_fingerprint() == p4.plan_fingerprint()


def test_fingerprints_are_value_complete_not_repr():
    """Regression (caught live by the full suite): expression __repr__
    omits non-child parameters — trim sets, percentile fractions,
    first()'s ignore_nulls — so repr-keyed fingerprints handed one
    expression another's compiled program (trim(s, "ag ") returned
    plain-trim results). Fingerprints ride semantic_key / the new
    AggregateFunction.semantic_key, which the CSE contract keeps
    value-complete."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.expr.aggexprs import (ApproxPercentile, First,
                                                Percentile)
    from spark_rapids_tpu.types import STRING

    def proj_fp(expr):
        sess = TpuSession()
        df = sess.from_pydict({"s": ["x"]},
                              Schema((StructField("s", STRING),)))
        return df.select(expr.alias("r"))._exec().plan_fingerprint()

    assert proj_fp(F.trim(col("s"))) != proj_fp(F.trim(col("s"), "ag "))

    # aggregate-function parameters distinguish too
    assert Percentile(col("x"), 0.05).semantic_key() != \
        Percentile(col("x"), 0.95).semantic_key()
    assert ApproxPercentile(col("x"), 0.5, 100).semantic_key() != \
        ApproxPercentile(col("x"), 0.5, 200).semantic_key()
    assert First(col("x"), ignore_nulls=True).semantic_key() != \
        First(col("x"), ignore_nulls=False).semantic_key()

    def agg_fp(fn):
        sess = TpuSession()
        df = sess.from_pydict(
            {"k": [1], "x": [2.0]},
            Schema((StructField("k", INT), StructField("x", DOUBLE))))
        return (df.filter(col("x") > lit(0)).group_by("k")
                  .agg((fn, "r"))._exec().plan_fingerprint())

    from spark_rapids_tpu.expr.aggexprs import Last
    assert agg_fp(First(col("x"), ignore_nulls=True)) != \
        agg_fp(First(col("x"), ignore_nulls=False))
    assert agg_fp(First(col("x"))) != agg_fp(Last(col("x")))

    # non-deterministic expressions (UDFs key per-instance) opt OUT
    from spark_rapids_tpu.expr.udf import PythonUDF
    from spark_rapids_tpu.types import LongType as _L
    sess = TpuSession()
    df = sess.from_pydict({"a": [1, 2]},
                          Schema((StructField("a", LONG),)))
    udf = PythonUDF(lambda x: x + 1, _L(), col("a"))
    plan = df.select(udf.alias("r"))._exec()

    def walk(n):
        yield n
        for c in n.children:
            yield from walk(c)
    projs = [n for n in walk(plan)
             if type(n).__name__ in ("ProjectExec", "HostProjectExec")]
    assert all(n.plan_fingerprint() is None for n in projs), \
        "a UDF-bearing projection must opt out of the program cache"


# -- map stages --------------------------------------------------------------

def test_map_stage_fuses_filter_project_chain():
    """filter->project chains feeding a non-fusable consumer compile
    to a map stage: every projection + ONE compaction in one program
    per input batch, results byte-identical to the per-op chain."""
    def q(sess):
        schema = Schema((StructField("a", LONG), StructField("b", LONG)))
        df = sess.from_pydict(
            {"a": list(range(40)), "b": [i * 3 for i in range(40)]},
            schema, batch_rows=16)
        return (df.filter(col("a") > lit(4))
                  .select(col("a"), (col("b") + col("a")).alias("c"))
                  .filter(col("c") > lit(30))
                  .sort(("c", False)))
    sess = TpuSession()
    plan = q(sess)._exec()
    kinds = []

    def walk(n):
        kinds.append((type(n).__name__,
                      getattr(n, "_kind", None)))
        for c in n.children:
            walk(c)
    walk(plan)
    assert ("CompiledStageExec", "map") in kinds, kinds
    on = q(sess).collect()
    off = q(TpuSession(OFF)).collect()
    assert on == off
    row = _stage_row(sess)
    # one program per input batch, one output per input batch
    assert row["dispatches_per_batch"] == 1.0, row


def test_map_stage_expand_fans_out_from_one_program():
    """An expand inside a map chain emits ALL its projections from ONE
    program per input batch (grouping-sets shape)."""
    from spark_rapids_tpu.exec.basic import (ExpandExec, FilterExec,
                                             InMemoryScanExec)
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    schema = Schema((StructField("k", LONG), StructField("v", LONG)))
    batch = ColumnarBatch.from_pydict(
        {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]}, schema)
    def tree():
        scan = InMemoryScanExec([batch], schema)
        expand = ExpandExec([[col("k"), col("v")],
                             [col("k"), (col("v") * lit(2)).alias("v")]],
                            scan)
        return FilterExec(col("v") > lit(15), expand)
    per_op = sorted(r for b in tree().execute() for r in b.to_pylist())
    fused = compile_stages(tree(), TpuSession().conf)
    assert isinstance(fused, CompiledStageExec) and fused._kind == "map"
    got = sorted(r for b in fused.execute() for r in b.to_pylist())
    assert got == per_op
    # 1 input batch -> 2 output batches from ONE dispatch
    assert fused.metrics["numDispatches"].value == 1
    assert fused.metrics["numOutputBatches"].value == 2


# -- governance at the stage boundary ---------------------------------------

def test_breaker_demotes_stage_to_per_op():
    """PR 5 degradation at stage granularity: an OPEN device_dispatch
    breaker demotes the fused stage back to per-operator execution —
    results unchanged, the fallback counter proves the lane."""
    from spark_rapids_tpu.exec import lifecycle
    conf = {"spark.rapids.tpu.breaker.enabled": "true",
            "spark.rapids.tpu.breaker.threshold": "1",
            "spark.rapids.tpu.breaker.cooldownMs": "600000"}
    sess = TpuSession(conf)
    baseline = sorted(_q1_query(sess).collect())
    try:
        lifecycle.record_domain_failure("device_dispatch")
        assert not lifecycle.breaker_allows("device_dispatch")
        before = stage_compiler.counters()
        demoted = sorted(_q1_query(sess).collect())
        after = stage_compiler.counters()
        assert demoted == baseline
        assert after["fallbacks"] > before["fallbacks"]
        assert after["executions"] == before["executions"]
    finally:
        lifecycle.reset_lifecycle()


def test_stage_fault_point_recovers_via_task_retry():
    """Chaos coverage of the new seam: the stage-boundary harness
    draws the device.dispatch fault point with a stage-keyed work item
    — one injected device fault converges through task re-execution."""
    from spark_rapids_tpu import faults
    sess = TpuSession({"spark.rapids.tpu.task.maxAttempts": "5"})
    expect = sorted(_q1_query(sess).collect())
    try:
        faults.install("device.dispatch:prob=1.0,seed=7,kind=device,"
                       "max=1")
        got = sorted(_q1_query(sess).collect())
    finally:
        faults.install("")
    assert got == expect


def test_stage_fused_event_fields(tmp_path):
    bus = events.enable(str(tmp_path), level="MODERATE")
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": "true",
                       "spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    _q1_query(sess).collect()
    log = events.active_bus().path
    events.reset_event_bus()
    recs = [json.loads(ln) for ln in open(log)]
    fused = [r for r in recs if r["kind"] == "stage_fused"]
    assert fused, "no stage_fused event"
    e = fused[0]
    assert e["stage"] == "agg" and e["ops"] >= 2
    assert "AggregateExec" in e["label"]
    assert e["batches"] >= 1 and e["dispatches"] >= 1
    assert e["donated_bytes"] > 0  # the carried state really donates
    # report roll-up renders it; pre-fusion logs stay silent
    s = profile_report.build_summary(recs)
    fs = s["fused_stages"]
    assert fs["executions"] >= 1 and fs["ops_absorbed"] >= 2
    text = profile_report.build_report(recs)
    assert "fused stages:" in text
    old = [{"ts_ns": 1, "kind": "op_close", "query": 1, "op": "X",
            "op_id": 1, "wall_ns": 5, "batches": 1, "rows": 1}]
    assert profile_report.build_summary(old)["fused_stages"][
        "executions"] == 0
    assert "fused stages" not in profile_report.build_report(old)


def test_bench_stage_attribution_deltas():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", Path(__file__).resolve().parents[1] / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._attr_prev.pop("stage", None)
    first = bench.stage_attribution()
    assert set(first) == {"stages_fused", "ops_fused", "dispatches",
                          "cache_hits"}
    sess = TpuSession()
    _q1_query(sess).collect()
    delta = bench.stage_attribution()
    assert delta["stages_fused"] >= 1 and delta["dispatches"] >= 1
    # --stage-fusion argv contract: usage error JSON on bad argv
    with pytest.raises(SystemExit) as ei:
        bench.maybe_stage_fusion(["bench.py", "--stage-fusion",
                                  "maybe"])
    assert ei.value.code == 2
