"""Device regexp_replace/regexp_extract span kernels vs Python re."""
import random
import re

import pytest

from spark_rapids_tpu.columnar.column import StringColumn
from spark_rapids_tpu.regex import RegexUnsupported
from spark_rapids_tpu.regex.spans import (compile_spans,
                                          regexp_extract_device,
                                          regexp_replace_device)


def host_replace(s, pattern, repl):
    if s is None:
        return None
    return re.sub(pattern, repl, s)


def host_extract(s, pattern, idx):
    if s is None:
        return None
    m = re.search(pattern, s)
    if m is None:
        return ""
    g = m.group(idx)
    return g if g is not None else ""


ROWS = ["abc123def456", "", None, "999", "a1b2c3", "no digits here",
        "   spaces  ", "x", "aaa", "12.34.56", "cat and dog", "catdog"]


@pytest.mark.parametrize("pattern,repl", [
    ("[0-9]", "#"),
    ("[0-9]+", "#"),
    ("[0-9]+", ""),
    ("[0-9]+", "NUM"),
    (r"\s+", "_"),
    ("a", "XY"),
    ("cat|dog", "pet"),
    ("[a-c][0-9]", "*"),
    ("a{2}", "Z"),
])
def test_replace_differential(pattern, repl):
    col = StringColumn.from_pylist(ROWS)
    plan = compile_spans(pattern)
    got = regexp_replace_device(col, plan,
                                repl.encode()).to_pylist(len(ROWS))
    assert got == [host_replace(s, pattern, repl) for s in ROWS], pattern


@pytest.mark.parametrize("pattern,idx", [
    ("[0-9]+", 0),
    ("([0-9]+)", 1),
    (r"([a-z])([0-9])", 2),
    (r"([a-z])([0-9])", 1),
    ("cat|dog", 0),
    ("a([0-9])c", 1),
])
def test_extract_differential(pattern, idx):
    col = StringColumn.from_pylist(ROWS)
    plan = compile_spans(pattern)
    got = regexp_extract_device(col, plan, idx).to_pylist(len(ROWS))
    assert got == [host_extract(s, pattern, idx) for s in ROWS], pattern


def test_anchored_spans():
    rows = ["123abc", "abc123", "123", "abc", None]
    col = StringColumn.from_pylist(rows)
    got = regexp_replace_device(col, compile_spans("^[0-9]+"),
                                b"#").to_pylist(len(rows))
    assert got == [host_replace(s, "^[0-9]+", "#") for s in rows]
    got = regexp_replace_device(col, compile_spans("[0-9]+$"),
                                b"#").to_pylist(len(rows))
    assert got == [host_replace(s, "[0-9]+$", "#") for s in rows]


def test_unsupported_shapes_raise():
    for p in ("a+b", "(ab|c)", "a*", "a.*b"):
        with pytest.raises(RegexUnsupported):
            compile_spans(p)
    # group under a repeat: Java keeps the LAST iteration; reject
    plan = compile_spans("([0-9])+") if True else None
    # ([0-9])+ is classplus after group stripping; extract must reject
    with pytest.raises(RegexUnsupported):
        regexp_extract_device(StringColumn.from_pylist(["1"]), plan, 1)


def test_fuzz_differential():
    rng = random.Random(3)
    alphabet = "ab1 2xy."
    rows = [None if rng.random() < 0.1 else
            "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 14)))
            for _ in range(80)]
    col = StringColumn.from_pylist(rows)
    n = len(rows)
    for pattern in ("[0-9]+", "[ab]", "x|y", "[a-z][0-9]", r"\.", " +"):
        plan = compile_spans(pattern)
        got = regexp_replace_device(col, plan, b"<>").to_pylist(n)
        assert got == [host_replace(s, pattern, "<>") for s in rows], \
            pattern
        got = regexp_extract_device(col, plan, 0).to_pylist(n)
        assert got == [host_extract(s, pattern, 0) for s in rows], pattern


def test_planner_routing():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import STRING, Schema, StructField
    sess = TpuSession()
    df = sess.from_pydict(
        {"s": ["a1b22", None, "xyz"]},
        schema=Schema((StructField("s", STRING),)))
    q = df.select(F.regexp_replace(F.col("s"), "[0-9]+", "#").alias("r"),
                  F.regexp_extract(F.col("s"), "([0-9]+)", 1).alias("e"))
    assert "host" not in q.explain()
    assert q.collect() == [("a#b#", "1"), (None, None), ("xyz", "")]
    # variable-length alternation stays host
    q2 = df.select(F.regexp_replace(F.col("s"), "a+|b", "#").alias("r"))
    assert "host" in q2.explain()
    assert [r[0] for r in q2.collect()] == ["#1#22", None, "xyz"]
