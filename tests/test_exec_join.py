"""Join exec tests against a python oracle covering all join types, null
keys, duplicates, hash-collision safety and residual conditions."""

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.exec.joins import (
    HashJoinExec, NestedLoopJoinExec,
)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import INT, LONG, STRING, Schema, StructField

L_SCHEMA = Schema((StructField("lk", INT), StructField("lv", STRING)))
R_SCHEMA = Schema((StructField("rk", INT), StructField("rv", STRING)))

L_DATA = {"lk": [1, 2, 2, None, 5, 7], "lv": ["a", "b", "c", "d", "e", "f"]}
R_DATA = {"rk": [2, 2, 3, None, 5, 5], "rv": ["x", "y", "z", "n", "p", "q"]}


def scan(data, schema, split=0):
    n = len(next(iter(data.values())))
    if split:
        batches = [ColumnarBatch.from_pydict(
            {k: v[s:s + split] for k, v in data.items()}, schema)
            for s in range(0, n, split)]
    else:
        batches = [ColumnarBatch.from_pydict(data, schema)]
    return InMemoryScanExec(batches, schema)


def oracle_join(join_type):
    lrows = list(zip(L_DATA["lk"], L_DATA["lv"]))
    rrows = list(zip(R_DATA["rk"], R_DATA["rv"]))
    out = []
    matched_r = set()
    for lk, lv in lrows:
        matches = [(rk, rv) for rk, rv in rrows
                   if lk is not None and rk == lk]
        for i, (rk, rv) in enumerate(rrows):
            if lk is not None and rk == lk:
                matched_r.add(i)
        if matches:
            if join_type in ("inner", "left_outer", "full_outer"):
                out.extend([(lk, lv, rk, rv) for rk, rv in matches])
            elif join_type == "left_semi":
                out.append((lk, lv))
        else:
            if join_type in ("left_outer", "full_outer"):
                out.append((lk, lv, None, None))
            elif join_type == "left_anti":
                out.append((lk, lv))
    if join_type in ("right_outer", "full_outer"):
        for i, (rk, rv) in enumerate(rrows):
            if i not in matched_r:
                out.append((None, None, rk, rv))
    if join_type == "right_outer":
        inner = oracle_join("inner")
        out = inner + out
    return out


@pytest.mark.parametrize("split", [0, 2])
@pytest.mark.parametrize("jt", ["inner", "left_outer", "right_outer",
                                "full_outer", "left_semi", "left_anti"])
def test_hash_join_types(jt, split):
    plan = HashJoinExec(scan(L_DATA, L_SCHEMA, split),
                        scan(R_DATA, R_SCHEMA),
                        [col("lk")], [col("rk")], join_type=jt)
    got = sorted(plan.collect(), key=repr)
    want = sorted(oracle_join(jt), key=repr)
    assert got == want, f"{jt}: {got} != {want}"


def test_hash_join_build_left():
    plan = HashJoinExec(scan(L_DATA, L_SCHEMA), scan(R_DATA, R_SCHEMA),
                        [col("lk")], [col("rk")], join_type="inner",
                        build_side="left")
    got = sorted(plan.collect(), key=repr)
    assert got == sorted(oracle_join("inner"), key=repr)


def test_left_outer_build_left():
    plan = HashJoinExec(scan(L_DATA, L_SCHEMA), scan(R_DATA, R_SCHEMA),
                        [col("lk")], [col("rk")], join_type="left_outer",
                        build_side="left")
    got = sorted(plan.collect(), key=repr)
    assert got == sorted(oracle_join("left_outer"), key=repr)


def test_join_with_condition():
    # inner join with residual: rv > lv is replaced by int condition
    ldata = {"lk": [1, 1, 2], "lv": ["a", "b", "c"]}
    rdata = {"rk": [1, 1, 2], "rv": ["p", "q", "r"]}
    plan = HashJoinExec(
        scan(ldata, L_SCHEMA), scan(rdata, R_SCHEMA),
        [col("lk")], [col("rk")], join_type="inner",
        condition=(col("lv") == lit("a")))
    got = sorted(plan.collect(), key=repr)
    assert got == [(1, "a", 1, "p"), (1, "a", 1, "q")]


def test_left_outer_condition_unmatched():
    ldata = {"lk": [1, 2], "lv": ["a", "b"]}
    rdata = {"rk": [1, 2], "rv": ["p", "q"]}
    plan = HashJoinExec(
        scan(ldata, L_SCHEMA), scan(rdata, R_SCHEMA),
        [col("lk")], [col("rk")], join_type="left_outer",
        condition=(col("lv") == lit("a")))
    got = sorted(plan.collect(), key=repr)
    assert got == [(1, "a", 1, "p"), (2, "b", None, None)]


def test_string_keys_join():
    lschema = Schema((StructField("lk", STRING), StructField("lv", INT)))
    rschema = Schema((StructField("rk", STRING), StructField("rv", INT)))
    ldata = {"lk": ["aa", "bb", None, "cc"], "lv": [1, 2, 3, 4]}
    rdata = {"rk": ["bb", "cc", "cc", None], "rv": [10, 20, 30, 40]}
    plan = HashJoinExec(scan(ldata, lschema), scan(rdata, rschema),
                        [col("lk")], [col("rk")], join_type="inner")
    got = sorted(plan.collect())
    assert got == [("bb", 2, "bb", 10), ("cc", 4, "cc", 20),
                   ("cc", 4, "cc", 30)]


def test_multi_key_join():
    lschema = Schema((StructField("k1", INT), StructField("k2", STRING),
                      StructField("lv", INT)))
    rschema = Schema((StructField("j1", INT), StructField("j2", STRING),
                      StructField("rv", INT)))
    ldata = {"k1": [1, 1, 2], "k2": ["a", "b", "a"], "lv": [1, 2, 3]}
    rdata = {"j1": [1, 1, 2], "j2": ["a", "a", "b"], "rv": [10, 20, 30]}
    plan = HashJoinExec(scan(ldata, lschema), scan(rdata, rschema),
                        [col("k1"), col("k2")], [col("j1"), col("j2")],
                        join_type="inner")
    got = sorted(plan.collect())
    assert got == [(1, "a", 1, 1, "a", 10), (1, "a", 1, 1, "a", 20)]


def test_existence_join():
    plan = HashJoinExec(scan(L_DATA, L_SCHEMA), scan(R_DATA, R_SCHEMA),
                        [col("lk")], [col("rk")], join_type="existence")
    got = {r[0:2]: r[2] for r in plan.collect()}
    assert got[(2, "b")] is True
    assert got[(1, "a")] is False
    assert got[(None, "d")] is False
    assert got[(5, "e")] is True


def test_empty_build_side():
    empty = InMemoryScanExec([], R_SCHEMA)
    plan = HashJoinExec(scan(L_DATA, L_SCHEMA), empty,
                        [col("lk")], [col("rk")], join_type="left_outer")
    got = sorted(plan.collect(), key=repr)
    assert len(got) == 6
    assert all(r[2] is None and r[3] is None for r in got)


def test_cross_join():
    plan = NestedLoopJoinExec(scan(L_DATA, L_SCHEMA), scan(R_DATA, R_SCHEMA),
                              join_type="cross", chunk_rows=8)
    assert len(plan.collect()) == 36


def test_nested_loop_inner_condition():
    plan = NestedLoopJoinExec(
        scan(L_DATA, L_SCHEMA), scan(R_DATA, R_SCHEMA),
        join_type="inner",
        condition=(col("lk") > col("rk")), chunk_rows=8)
    got = plan.collect()
    want = [(lk, lv, rk, rv)
            for lk, lv in zip(L_DATA["lk"], L_DATA["lv"])
            for rk, rv in zip(R_DATA["rk"], R_DATA["rv"])
            if lk is not None and rk is not None and lk > rk]
    assert sorted(got) == sorted(want)


def test_nested_loop_left_outer():
    plan = NestedLoopJoinExec(
        scan(L_DATA, L_SCHEMA), scan(R_DATA, R_SCHEMA),
        join_type="left_outer",
        condition=(col("lk") > col("rk")), chunk_rows=4)
    got = plan.collect()
    matched = {lk for lk, _ in zip(L_DATA["lk"], L_DATA["lv"])
               if lk is not None and any(rk is not None and lk > rk
                                         for rk in R_DATA["rk"])}
    unmatched_rows = [r for r in got if r[2] is None and r[3] is None]
    assert {r[0] for r in unmatched_rows} == {1, 2, None}
