"""Plugin lifecycle, heartbeats, bounded host alloc, dump tooling tests
(reference: Plugin.scala init/fatal-error suites, heartbeat manager
tests, HostAllocSuite, DumpUtils usage; SURVEY §2.1/§2.4/§2.5/§5)."""

import os
import threading
import time

import pytest

from spark_rapids_tpu.memory.host_alloc import HostAlloc, HostOOM
from spark_rapids_tpu.parallel.heartbeat import (HeartbeatEndpoint,
                                                 HeartbeatManager)
from spark_rapids_tpu.plugin import (FatalDeviceError, TpuDriverPlugin,
                                     TpuExecutorPlugin)


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------

def test_register_returns_existing_peers():
    m = HeartbeatManager()
    assert m.register("e1") == []
    peers = m.register("e2")
    assert [p.executor_id for p in peers] == ["e1"]


def test_heartbeat_delta_updates():
    m = HeartbeatManager()
    m.register("e1")
    time.sleep(0.01)
    assert m.heartbeat("e1") == []
    time.sleep(0.01)
    m.register("e2")  # joined after e1's last beat
    new = m.heartbeat("e1")
    assert [p.executor_id for p in new] == ["e2"]
    assert m.heartbeat("e1") == []  # already delivered


def test_liveness_timeout():
    m = HeartbeatManager(timeout_s=0.05)
    m.register("e1")
    m.register("e2")
    time.sleep(0.08)
    m.heartbeat("e2")
    assert m.dead_peers() == ["e1"]
    assert m.live_peers() == ["e2"]


def test_endpoint_thread_beats_and_discovers():
    m = HeartbeatManager(timeout_s=1.0)
    seen = []
    ep = HeartbeatEndpoint(m, "e1", interval_s=0.02,
                           on_new_peer=lambda p: seen.append(p.executor_id))
    ep.start()
    try:
        m.register("e2")
        deadline = time.monotonic() + 2
        while "e2" not in seen and time.monotonic() < deadline:
            time.sleep(0.01)
        assert "e2" in seen
        assert "e1" in m.live_peers()
    finally:
        ep.stop()


# ---------------------------------------------------------------------------
# plugin lifecycle
# ---------------------------------------------------------------------------

def test_executor_plugin_init_and_peers():
    driver = TpuDriverPlugin().init()
    e1 = TpuExecutorPlugin(executor_id="e1", driver=driver,
                           exit_fn=lambda c: None).init()
    e2 = TpuExecutorPlugin(executor_id="e2", driver=driver,
                           exit_fn=lambda c: None).init()
    try:
        deadline = time.monotonic() + 2
        while "e2" not in e1.peers and time.monotonic() < deadline:
            time.sleep(0.01)
        assert "e2" in e1.peers   # discovered via heartbeat delta
        assert "e1" in e2.peers   # returned at registration
    finally:
        e1.shutdown()
        e2.shutdown()
        driver.shutdown()


def test_fatal_error_exits_executor():
    codes = []
    p = TpuExecutorPlugin(exit_fn=codes.append)
    p.on_fatal_error(FatalDeviceError("device wedged"))
    assert codes == [1]


def test_retryable_oom_is_not_fatal():
    from spark_rapids_tpu.memory.retry import TpuRetryOOM
    codes = []
    p = TpuExecutorPlugin(exit_fn=codes.append)
    p.on_task_failed(TpuRetryOOM("retry me"))
    assert codes == []


# ---------------------------------------------------------------------------
# bounded host alloc
# ---------------------------------------------------------------------------

def test_host_alloc_pinned_preference_and_bounds():
    pool = HostAlloc(limit_bytes=1000, pinned_bytes=400)
    a = pool.alloc(300)             # fits the pinned fast lane
    assert a.pinned
    b = pool.alloc(300)             # pinned lane full -> general lane
    assert not b.pinned
    assert pool.used_bytes == 600
    assert pool.try_alloc(400) is None   # general lane cap is 600
    b.close()
    c = pool.try_alloc(500, prefer_pinned=False)
    assert c is not None and not c.pinned
    a.close()
    c.close()
    assert pool.used_bytes == 0


def test_host_alloc_blocks_until_release():
    pool = HostAlloc(limit_bytes=100, pinned_bytes=0)
    a = pool.alloc(80)
    got = []

    def waiter():
        with pool.alloc(50, timeout_s=5):
            got.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    assert not got          # still blocked
    a.close()
    t.join(timeout=5)
    assert got == [True]


def test_host_alloc_timeout_raises():
    pool = HostAlloc(limit_bytes=100, pinned_bytes=0)
    a = pool.alloc(90)
    with pytest.raises(HostOOM):
        pool.alloc(50, timeout_s=0.05)
    a.close()
    with pytest.raises(HostOOM):
        pool.alloc(101)     # larger than the pool can ever serve


# ---------------------------------------------------------------------------
# dump tooling
# ---------------------------------------------------------------------------

def test_dump_batch_and_dump_on_error(tmp_path):
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.types import LONG, STRING, Schema, StructField
    from spark_rapids_tpu.utils.dump import dump_batch, dump_on_error

    sch = Schema((StructField("k", LONG), StructField("s", STRING)))
    b = ColumnarBatch.from_pydict({"k": [1, None], "s": ["x", None]}, sch)
    p = dump_batch(b, str(tmp_path / "b.parquet"))
    assert os.path.exists(p) and os.path.exists(p + ".meta.json")
    back = ColumnarBatch.from_arrow(
        __import__("pyarrow.parquet", fromlist=["pq"]).read_table(p))
    assert back.to_pylist() == b.to_pylist()

    conf = RapidsConf({"spark.rapids.sql.debug.dumpPath": str(tmp_path)})
    with pytest.raises(RuntimeError, match="boom"):
        with dump_on_error("TestOp", conf) as scope:
            scope.observe(b)
            raise RuntimeError("boom")
    dirs = [d for d in os.listdir(tmp_path) if d.startswith("TestOp-")]
    assert len(dirs) == 1
    files = os.listdir(tmp_path / dirs[0])
    assert "error.txt" in files and "repro.py" in files
    assert any(f.startswith("input-") and f.endswith(".parquet")
               for f in files)


def test_operator_failure_dumps_real_exception(tmp_path):
    """The exec-layer failure hook dumps the failing operator's INPUT
    batches plus the real exception's traceback (reference DumpUtils
    dump-failing-batches wiring)."""
    import glob

    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.exec.base import TpuExec
    from spark_rapids_tpu.types import LONG, Schema, StructField

    sch = Schema((StructField("v", LONG),))
    set_active_conf(RapidsConf(
        {"spark.rapids.sql.debug.dumpPath": str(tmp_path)}))
    try:
        class Src(TpuExec):
            output_schema = sch

            def internal_execute(self):
                yield ColumnarBatch.from_pydict({"v": [1, 2]}, sch)

        class Boom(TpuExec):
            output_schema = sch

            def internal_execute(self):
                for b in self.children[0].execute():
                    raise ValueError("kernel exploded here")
                    yield b  # generator marker (unreachable)

        with pytest.raises(ValueError):
            list(Boom(Src()).execute())
        d = glob.glob(str(tmp_path / "Boom-*"))[0]
        assert "kernel exploded here" in open(os.path.join(d, "error.txt")).read()
        assert glob.glob(os.path.join(d, "input-*.parquet"))
    finally:
        set_active_conf(RapidsConf({}))


def test_host_alloc_unserveable_nonpinned_fast_fails():
    """A non-pinned request larger than the general lane must fail
    immediately, not stall the timeout (the pinned lane is not an
    option for it)."""
    import time as _t
    pool = HostAlloc(limit_bytes=100, pinned_bytes=80)
    t0 = _t.monotonic()
    with pytest.raises(HostOOM):
        pool.alloc(50, prefer_pinned=False, timeout_s=10)
    assert _t.monotonic() - t0 < 1
    a = pool.alloc(50, prefer_pinned=True)   # pinned lane fits it
    a.close()
