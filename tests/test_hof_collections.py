"""Higher-order functions + collection long tail (reference
higherOrderFunctions.scala / collectionOperations.scala; host-tier
through CPU fallback)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.types import (LONG, STRING, ArrayType, Schema,
                                    StructField)

ARR_SCH = Schema((StructField("a", ArrayType(LONG)),
                  StructField("k", LONG)))


def _df(sess):
    return sess.from_pydict(
        {"a": [[1, 2, 3], [], None, [4, None, 6]],
         "k": [10, 20, 30, 40]}, ARR_SCH)


def _run(sess, expr):
    return [r[0] for r in _df(sess).select(expr.alias("o")).collect()]


def test_transform_with_outer_column():
    sess = TpuSession()
    got = _run(sess, F.transform(col("a"), lambda x: x + col("k")))
    assert got == [[11, 12, 13], [], None, [44, None, 46]]


def test_filter_exists_forall():
    sess = TpuSession()
    assert _run(sess, F.filter_(col("a"), lambda x: x > lit(1))) == \
        [[2, 3], [], None, [4, 6]]
    assert _run(sess, F.exists(col("a"), lambda x: x > lit(5))) == \
        [False, False, None, True]
    # forall with a NULL element and no False → NULL (3-valued)
    assert _run(sess, F.forall(col("a"), lambda x: x > lit(0))) == \
        [True, True, None, None]


def test_aggregate_hof():
    sess = TpuSession()
    got = _run(sess, F.aggregate(col("a"), lit(0),
                                 lambda acc, x: acc + x))
    assert got == [6, 0, None, None]  # null element poisons the sum
    got = _run(sess, F.aggregate(col("a"), lit(1),
                                 lambda acc, x: acc * lit(2),
                                 finish=lambda acc: acc + lit(100)))
    assert got == [108, 101, None, 108]


def test_collection_long_tail():
    sess = TpuSession()
    assert _run(sess, F.array_position(col("a"), lit(2))) == \
        [1 + 1, 0, None, 0]
    assert _run(sess, F.array_remove(col("a"), lit(2))) == \
        [[1, 3], [], None, [4, None, 6]]
    assert _run(sess, F.slice(col("a"), lit(2), lit(2))) == \
        [[2, 3], [], None, [None, 6]]
    assert _run(sess, F.arrays_overlap(col("a"), F.array(lit(3), lit(9)))) \
        == [True, False, None, None]
    assert _run(sess, F.array_join(col("a"), ",", "NULL")) == \
        ["1,2,3", "", None, "4,NULL,6"]
    assert _run(sess, F.sequence(lit(1), col("k"), lit(7))) == \
        [[1, 8], [1, 8, 15], [1, 8, 15, 22, 29], [1, 8, 15, 22, 29, 36]]


def test_array_distinct():
    sess = TpuSession()
    sch = Schema((StructField("a", ArrayType(LONG)),))
    df = sess.from_pydict({"a": [[1, 2, 1, None, None, 2], None]}, sch)
    got = [r[0] for r in df.select(
        F.array_distinct(col("a")).alias("o")).collect()]
    assert got == [[1, 2, None], None]


def test_flatten_scalar_semantics():
    """The columnar substrate has no nested-array ingestion yet, so
    flatten is exercised at the host-interpreter level (its planner path
    activates once nested array columns exist)."""
    from spark_rapids_tpu.expr.collectionexprs import Flatten
    from spark_rapids_tpu.expr.core import col as c_
    f = Flatten(c_("a"))
    assert f.host_eval_row([[1, 2], [3]]) == [1, 2, 3]
    assert f.host_eval_row([[1], None]) is None
    assert f.host_eval_row(None) is None


def test_hof_literal_lambda_plans_on_device():
    # literal-leaf lambdas run the device kernel since round 3
    sess = TpuSession()
    q = _df(sess).select(F.transform(col("a"), lambda x: x * 2).alias("o"))
    tree = q._exec().tree_string()
    assert "HostProjectExec" not in tree


def test_hof_outer_column_lambda_stays_on_host():
    # a lambda referencing an outer row column still needs the host tier
    sess = TpuSession()
    q = _df(sess).select(
        F.transform(col("a"), lambda x: x * col("k")).alias("o"))
    assert "HostProjectExec" in q._exec().tree_string()
