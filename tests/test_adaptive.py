"""Adaptive runtime replanner (ISSUE 19): skew-split acceptance drive
(zipf-shaped key, on/off equality, evidence-carrying events),
sub-read fault recovery through the partition-granular lane,
single-build conversion, tiny-partition coalescing, measured broadcast
demotion BEFORE the first OOM retry, OOM-feedback batch right-sizing,
the `adaptive` breaker stand-down, the health() stats surface — and
the slow-tier 8-lane workload storm with one zipf lane (no neighbor
sheds).

House style: every engine drive compares against the adaptive-off run
or a numpy oracle; integer results must be bit-exact (splits and
coalesces regroup the same decoded blocks in the same order)."""

import threading

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import adaptive, lifecycle, workload
from spark_rapids_tpu.memory.budget import reset_memory_budget
from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                             reset_buffer_catalog)
from spark_rapids_tpu.obs import events
from spark_rapids_tpu.obs import stats as runtime_stats
from spark_rapids_tpu.types import LONG, Schema, StructField


@pytest.fixture(autouse=True)
def _isolation():
    # fresh DEFAULT conf per test: the module-scoped drive fixture
    # leaves its last session's conf active (TpuSession installs the
    # constructor conf globally), and a leaked 4 KiB batch target
    # changes every ambient-conf assertion downstream
    prev_conf = C.active_conf()
    C.set_active_conf(C.RapidsConf())
    adaptive.reset_adaptive()
    lifecycle.reset_lifecycle()
    runtime_stats.reset_stats()
    faults.install(None)
    yield
    faults.install(None)
    adaptive.reset_adaptive()
    lifecycle.reset_lifecycle()
    runtime_stats.reset_stats()
    C.set_active_conf(prev_conf)


@pytest.fixture
def spy(monkeypatch):
    rows = []
    real = events.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy_emit)
    return rows


def _kinds(rows, kind):
    return [e for e in rows if e["kind"] == kind]


# ---------------------------------------------------------------------------
# the zipf drive: a key space where one reducer carries ~80% of the rows
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def skew_files(tmp_path_factory):
    """Skewed fact side + tiny dimension side as parquet. Small row
    groups matter: the scan must produce MANY map outputs per exchange
    (a one-batch scan has nothing to split a partition into)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("adaptive_q")
    rng = np.random.default_rng(7)
    n = 2400
    hot = rng.random(n) < 0.8
    k = np.where(hot, 0, rng.integers(0, 64, n)).astype(np.int64)
    v = rng.integers(0, 1000, n).astype(np.int64)
    rk = np.arange(64, dtype=np.int64)
    w = (rk * 10).astype(np.int64)
    lp, rp = str(d / "fact.parquet"), str(d / "dim.parquet")
    pq.write_table(pa.table({"k": pa.array(k, pa.int64()),
                             "v": pa.array(v, pa.int64())}), lp,
                   row_group_size=256)
    pq.write_table(pa.table({"rk": pa.array(rk, pa.int64()),
                             "w": pa.array(w, pa.int64())}), rp,
                   row_group_size=256)
    # numpy oracle: per key, sum(v + w[k]) and count (all-integer: the
    # engine must match bit-exactly, adaptive on or off)
    oracle = {}
    for key in np.unique(k):
        vals = v[k == key] + w[key]
        oracle[int(key)] = (int(vals.sum()), int((k == key).sum()))
    return lp, rp, oracle


#: shuffled-join + agg confs: partitions > 1 so a skew threshold is
#: decidable, tiny batches so the scan yields many map outputs,
#: broadcast off so the join takes the shuffled-hash path
BASE = {
    "spark.rapids.sql.shuffle.partitions": "4",
    "spark.rapids.sql.batchSizeBytes": "4096",
    "spark.rapids.sql.broadcastSizeThreshold": "-1",
    "spark.rapids.tpu.adaptive.skewedPartitionMinBytes": "1024",
    "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes": "-1",
    "spark.rapids.tpu.adaptive.coalesceTargetBytes": "0",
}


def _drive(skew_files, extra):
    """scan -> shuffled join -> group-by agg over the zipf key."""
    from spark_rapids_tpu.api.functions import col
    lp, rp, _ = skew_files
    sess = TpuSession(conf=dict(BASE, **extra))
    fact = sess.read_parquet(lp)
    dim = sess.read_parquet(rp)
    j = fact.join(dim, left_on=["k"], right_on=["rk"])
    agg = (j.select(col("k"), (col("v") + col("w")).alias("x"))
           .group_by("k").agg((F.sum("x"), "sx"), (F.count(), "cnt")))
    return sorted(agg.collect())


def _matches_oracle(rows, oracle):
    assert len(rows) == len(oracle)
    for k, sx, cnt in rows:
        assert (int(sx), int(cnt)) == oracle[int(k)], k


def _counter_delta(after, before):
    return {k: after[k] - before.get(k, 0) for k in after}


@pytest.fixture(scope="module")
def zipf_runs(skew_files):
    """THREE shared engine drives (each costs tens of seconds on a
    single-core host, which is why every consumer of this fixture is
    SLOW-TIER — the tier-1 faces of the same decisions run at the
    exec level below; every assertion reads captured snapshots
    instead of re-driving):

    1. ``off``   — adaptive.enabled=false baseline.
    2. ``on``    — defaults (skew splitting live) WITH one injected
                   sub-read corruption riding the same drive: the
                   inject-once-assert-recovery criterion and the
                   on/off equality criterion are one run — recovery
                   must be invisible in the results.
    3. ``combo`` — splitting off, conversion + coalescing on.
    """
    from spark_rapids_tpu.obs import events as ev_mod
    rows: list = []
    real = ev_mod.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    out = {}
    adaptive.reset_adaptive()
    lifecycle.reset_lifecycle()
    runtime_stats.reset_stats()
    faults.install(None)
    ev_mod.emit = spy_emit
    try:
        out["off"] = _drive(skew_files,
                            {"spark.rapids.tpu.adaptive.enabled":
                             "false"})
        out["counters_off"] = adaptive.counters()
        lc0 = lifecycle.counters()
        c0 = adaptive.counters()
        rows.clear()
        faults.install(
            "shuffle.skew_split:prob=1,seed=3,kind=corrupt,max=1")
        try:
            out["on"] = _drive(skew_files, {})
            out["fired"] = dict(faults.stats())
        finally:
            faults.install(None)
        out["lc_delta"] = _counter_delta(lifecycle.counters(), lc0)
        out["on_delta"] = _counter_delta(adaptive.counters(), c0)
        out["events_on"] = list(rows)
        out["health"] = runtime_stats.health_section()
        c1 = adaptive.counters()
        rows.clear()
        out["combo"] = _drive(skew_files, {
            "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes": "1m",
            "spark.rapids.tpu.adaptive.coalesceTargetBytes": "1m",
            "spark.rapids.tpu.adaptive.skewedPartitionFactor": "0"})
        out["combo_delta"] = _counter_delta(adaptive.counters(), c1)
        out["events_combo"] = list(rows)
    finally:
        ev_mod.emit = real
        faults.install(None)
    return out


@pytest.mark.slow  # engine drive: ~50s/drive on the 1-core host
def test_skew_split_on_off_equality_and_evidence(skew_files, zipf_runs):
    """Acceptance drive: the zipf key triggers map-granular splitting
    of the hot reducer; every sub-read stays under the measured
    threshold; results are bit-identical to adaptive off; zero task
    retries are spent."""
    r = zipf_runs
    _matches_oracle(r["off"], skew_files[2])
    assert r["counters_off"]["consults"] == 0  # off = truly dark
    assert r["on"] == r["off"], "adaptive on changed integer results"
    assert r["on_delta"]["skew_splits"] >= 1
    assert r["on_delta"]["consults"] >= 1
    assert r["lc_delta"]["whole_plan_retries"] == 0
    # evidence-carrying replan events: the split partition, its
    # measured bytes, and sub-reads each bounded by the threshold —
    # no single hash window holds the whole hot key
    splits = [e for e in _kinds(r["events_on"], "adaptive_replan")
              if e["decision"] == "skew_split"]
    assert splits, "split taken but no adaptive_replan evidence"
    for e in splits:
        assert e["bytes"] > e["threshold"] >= e["median_bytes"]
        assert e["subs"] >= 2
        assert e["max_sub_bytes"] <= e["threshold"]
        assert e["exec"] == "HostShuffleExchangeExec"


@pytest.mark.slow
def test_skew_split_sub_read_fault_recovers_one_map(zipf_runs):
    """Inject-once-assert-recovery (ISSUE 19 satellite): the ONE
    corrupted sub-read frame injected into the `on` drive recovered
    through the partition-granular lineage lane — exactly one map
    recompute, zero whole-plan retries, and (asserted above) results
    still bit-exact."""
    r = zipf_runs
    assert r["fired"].get("shuffle.skew_split") == 1, r["fired"]
    assert r["lc_delta"]["partition_recompute"] == 1, \
        "corrupt sub-read must recompute exactly ONE map output"
    assert r["lc_delta"]["whole_plan_retries"] == 0, \
        "sub-read recovery must not burn a whole-plan attempt"
    assert _kinds(r["events_on"], "partition_recompute"), \
        "recovery left no event"


@pytest.mark.slow
def test_single_build_convert_small_measured_build(zipf_runs):
    """The converse decision: a shuffled join whose build side MEASURES
    under autoBroadcastMaxBytes collapses to one single-build probe
    pass (probe-side exchange skipped), results unchanged."""
    r = zipf_runs
    assert r["combo"] == r["off"]
    assert r["combo_delta"]["single_build_converts"] >= 1
    evs = [e for e in _kinds(r["events_combo"], "adaptive_replan")
           if e["decision"] == "single_build_convert"]
    assert evs and all(e["measured_bytes"] <= e["threshold"]
                       for e in evs)


@pytest.mark.slow
def test_partition_coalesce_flat_consumers_only(zipf_runs):
    """Adjacent tiny reducers merge into one read on the flat (agg)
    exchange; the partition-aware join exchanges keep their static
    boundaries. Integer results stay bit-exact."""
    r = zipf_runs
    assert r["combo"] == r["off"]
    assert r["combo_delta"]["partition_coalesces"] >= 1


# ---------------------------------------------------------------------------
# tier-1 faces of the same decisions, at the exec level (sub-second:
# the engine drives above are slow-tier; the suite-budget gate leaves
# no room for ~50s drives in tier-1)
# ---------------------------------------------------------------------------

EXEC_SCHEMA = Schema((StructField("k", LONG), StructField("v", LONG)))


def _hot_key_scan():
    """4 map batches, ~86% of rows on key 7: the hot hash partition
    measures several× the median and spans all four map outputs."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    batches = []
    for i in range(4):
        ks = [7] * 128 + [1, 2, 3, 4, 5, 6, 8, 9] * 2
        vs = list(range(i * 1000, i * 1000 + len(ks)))
        batches.append(ColumnarBatch.from_pydict(
            {"k": ks, "v": vs}, EXEC_SCHEMA))
    return InMemoryScanExec(batches, EXEC_SCHEMA)


def _read_partitions(conf):
    from spark_rapids_tpu.exec.exchange import HostShuffleExchangeExec
    from spark_rapids_tpu.expr.core import col as ecol
    ex = HostShuffleExchangeExec([ecol("k")], _hot_key_scan(), 4, conf)
    return [[r for b in gen for r in b.to_pylist()]
            for gen in ex.execute_partitions()]


def test_skew_split_exec_level_on_off_equality_and_evidence(spy):
    """The tier-1 zipf acceptance face: the hot partition splits into
    map-granular sub-reads, each bounded by the MEASURED threshold (no
    single hash window holds the whole hot key), partition boundaries
    and row order bit-identical to adaptive off."""
    off = _read_partitions(C.RapidsConf(
        {"spark.rapids.tpu.adaptive.enabled": "false"}))
    assert adaptive.counters()["consults"] == 0  # off = truly dark
    on = _read_partitions(C.RapidsConf(
        {"spark.rapids.tpu.adaptive.skewedPartitionMinBytes": "1024"}))
    assert on == off, "skew split changed results or row order"
    assert len(on) == 4, "split must not move partition boundaries"
    c = adaptive.counters()
    assert c["skew_splits"] >= 1 and c["consults"] >= 1
    splits = [e for e in _kinds(spy, "adaptive_replan")
              if e["decision"] == "skew_split"]
    assert splits, "split taken but no adaptive_replan evidence"
    for e in splits:
        assert e["bytes"] > e["threshold"] >= e["median_bytes"]
        assert e["subs"] >= 2
        assert e["max_sub_bytes"] <= e["threshold"]
        assert e["exec"] == "HostShuffleExchangeExec"


def test_skew_split_exec_level_fault_recovers_one_map(spy):
    """Inject-once-assert-recovery at the exec level: one corrupted
    sub-read frame recovers through the partition-granular lineage
    lane — ONE map recompute, zero whole-plan retries, results
    bit-exact."""
    off = _read_partitions(C.RapidsConf(
        {"spark.rapids.tpu.adaptive.enabled": "false"}))
    lc0 = dict(lifecycle.counters())
    faults.install("shuffle.skew_split:prob=1,seed=3,kind=corrupt,max=1")
    try:
        on = _read_partitions(C.RapidsConf(
            {"spark.rapids.tpu.adaptive.skewedPartitionMinBytes":
             "1024"}))
        fired = dict(faults.stats())
    finally:
        faults.install(None)
    assert fired.get("shuffle.skew_split") == 1, fired
    assert on == off, "recovery must be invisible in the results"
    lc1 = lifecycle.counters()
    assert lc1["partition_recompute"] - lc0["partition_recompute"] == 1
    assert lc1["whole_plan_retries"] - lc0["whole_plan_retries"] == 0
    assert _kinds(spy, "partition_recompute"), "recovery left no event"


def test_single_build_convert_tiny_session_join(spy):
    """The converse decision, tier-1 face: a shuffled join whose build
    side MEASURES under the (default 64m) cap collapses to one
    single-build probe pass, evidence event attached, rows correct."""
    sess = TpuSession(conf={
        "spark.rapids.sql.shuffle.partitions": "4",
        "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    left = sess.from_pydict(
        {"k": [1, 2, 3, 4, 2], "x": [10, 20, 30, 40, 21]},
        schema=Schema((StructField("k", LONG), StructField("x", LONG))))
    right = sess.from_pydict(
        {"k": [2, 3, 9], "y": [5, 6, 7]},
        schema=Schema((StructField("k", LONG), StructField("y", LONG))))
    out = sorted(left.join(right, on="k", how="inner").collect())
    assert out == [(2, 20, 5), (2, 21, 5), (3, 30, 6)]
    assert adaptive.counters()["single_build_converts"] >= 1
    evs = [e for e in _kinds(spy, "adaptive_replan")
           if e["decision"] == "single_build_convert"]
    assert evs and all(e["measured_bytes"] <= e["threshold"]
                       for e in evs)


def test_partition_coalesce_exec_level_flat_only(spy):
    """Tiny-partition coalescing, tier-1 face: a flat consumer's 8
    tiny reducers merge into fewer reads (evidence event counts them);
    a partition-AWARE consumer of the same exchange keeps all 8
    boundaries."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.exec.exchange import HostShuffleExchangeExec
    from spark_rapids_tpu.expr.core import col as ecol
    conf = C.RapidsConf(
        {"spark.rapids.tpu.adaptive.coalesceTargetBytes": "1m"})

    def scan():
        return InMemoryScanExec(
            [ColumnarBatch.from_pydict(
                {"k": list(range(64)),
                 "v": list(range(i * 64, (i + 1) * 64))}, EXEC_SCHEMA)
             for i in range(2)], EXEC_SCHEMA)

    ex = HostShuffleExchangeExec([ecol("k")], scan(), 8, conf)
    flat = sorted(r for b in ex.internal_execute()
                  for r in b.to_pylist())
    assert len(flat) == 128
    assert adaptive.counters()["partition_coalesces"] >= 1
    evs = [e for e in _kinds(spy, "adaptive_replan")
           if e["decision"] == "partition_coalesce"]
    assert evs and evs[0]["reads"] < evs[0]["partitions"] == 8
    # partition-aware consumers must see the static boundaries
    ex2 = HostShuffleExchangeExec([ecol("k")], scan(), 8, conf)
    parts = [[r for b in g for r in b.to_pylist()]
             for g in ex2.execute_partitions()]
    assert len(parts) == 8
    assert sorted(r for p in parts for r in p) == flat


# ---------------------------------------------------------------------------
# measured broadcast demotion (the OOM-prevention acceptance criterion)
# ---------------------------------------------------------------------------

def test_broadcast_demote_fires_before_any_oom_retry(spy):
    """A planned single-build join whose build side MEASURES over the
    adaptive cap demotes to the sub-partitioned strategy up front:
    adaptive_demote observed with the measured evidence, ZERO oom_retry
    events, results correct."""
    sess = TpuSession(conf={
        # generous static threshold: the PLAN says single-build
        "spark.rapids.sql.broadcastSizeThreshold": "1g",
        "spark.rapids.shuffle.mode": "MULTITHREADED",
        # ...but the measured build side is over the adaptive cap
        "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes": "1"})
    left = sess.from_pydict(
        {"k": [1, 2, 3, 4, 2], "x": [10, 20, 30, 40, 21]},
        schema=Schema((StructField("k", LONG), StructField("x", LONG))))
    right = sess.from_pydict(
        {"k": [2, 3, 2, 9], "y": [5, 6, 7, 8]},
        schema=Schema((StructField("k", LONG), StructField("y", LONG))))
    # a post-aggregation build side has unknown plan-time size: the
    # join must measure at runtime (AdaptiveJoinExec)
    small = right.group_by("k").agg((F.count(), "n"))
    q = left.join(small, on="k", how="inner")
    assert "AdaptiveJoinExec" in q._exec().tree_string()
    out = sorted(q.collect())
    assert out == [(2, 20, 2), (2, 21, 2), (3, 30, 1)]
    dem = [e for e in _kinds(spy, "adaptive_demote")
           if e["decision"] == "broadcast_demote"]
    assert dem, "measured-oversized build was not demoted"
    assert dem[0]["measured_bytes"] > dem[0]["threshold"]
    assert dem[0]["basis"] == "conf"
    assert dem[0]["planned"] == "build_right"
    assert not _kinds(spy, "oom_retry"), \
        "demotion must preempt the OOM retry lane, not follow it"
    assert adaptive.counters()["broadcast_demotes"] >= 1


def test_demote_cap_quota_basis_takes_tighter_bound():
    """With the workload governor admitting this query, the demote cap
    is the TIGHTER of the conf cap and the ticket's quota share."""
    conf = C.RapidsConf({
        "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes": "64m"})
    assert adaptive.demote_cap(conf) == (64 * 1024 * 1024, "conf")
    off = C.RapidsConf({
        "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes": "-1"})
    assert adaptive.demote_cap(off) is None


# ---------------------------------------------------------------------------
# OOM-feedback batch right-sizing
# ---------------------------------------------------------------------------

def test_note_oom_split_halves_governed_batch_target(spy):
    """Inside a governed query an OOM split halves the context's batch
    target down to the 4 KiB floor; outside any context it is a no-op
    and the override reads None."""
    assert adaptive.batch_target_override() is None
    adaptive.note_oom_split()  # no governed query: must not throw
    conf = C.RapidsConf({"spark.rapids.sql.batchSizeBytes": "32k"})
    C.set_active_conf(conf)
    with lifecycle.governed(conf) as ctx:
        adaptive.note_oom_split()
        assert ctx.adaptive_batch_target == 16 * 1024
        assert adaptive.batch_target_override() == 16 * 1024
        for _ in range(10):
            adaptive.note_oom_split()
        assert ctx.adaptive_batch_target == adaptive.MIN_BATCH_TARGET
    assert adaptive.batch_target_override() is None
    evs = [e for e in _kinds(spy, "adaptive_replan")
           if e["decision"] == "batch_right_size"]
    assert evs and evs[0]["prev_target"] == 32 * 1024 \
        and evs[0]["new_target"] == 16 * 1024
    assert adaptive.counters()["batch_right_sizes"] >= 3


def test_coalesce_exec_honors_shrunken_target():
    """CoalesceBatchesExec consumes the governed override: the same
    4-batch input coalesces to 1 batch normally but stays 4 when an
    earlier OOM split shrank the target below a batch's size."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.exec.coalesce import CoalesceBatchesExec
    schema = Schema((StructField("a", LONG),))

    def scan():
        return InMemoryScanExec(
            [ColumnarBatch.from_pydict({"a": [i, i + 1]}, schema)
             for i in range(0, 8, 2)], schema)

    assert len(list(CoalesceBatchesExec(scan()).execute())) == 1
    conf = C.active_conf()
    with lifecycle.governed(conf) as ctx:
        ctx.adaptive_batch_target = 1
        assert len(list(CoalesceBatchesExec(scan()).execute())) == 4


# ---------------------------------------------------------------------------
# the `adaptive` breaker: a misfiring lane demotes to the static plan
# ---------------------------------------------------------------------------

def test_open_adaptive_breaker_stands_lane_down(spy):
    conf = C.RapidsConf({
        "spark.rapids.tpu.breaker.enabled": "true",
        "spark.rapids.tpu.breaker.threshold": "2",
        "spark.rapids.tpu.breaker.windowMs": "60000",
        "spark.rapids.tpu.breaker.cooldownMs": "60000"})
    C.set_active_conf(conf)
    assert adaptive.consult(conf, op="X", op_id=1) is True
    # two consult-path errors open the domain...
    adaptive.note_error(op="X", op_id=1, error="boom")
    adaptive.note_error(op="X", op_id=1, error="boom")
    assert "adaptive" in lifecycle.open_breakers()
    # ...and every later consult refuses, counted and visible
    c0 = adaptive.counters()
    assert adaptive.consult(conf, op="X", op_id=1) is False
    c1 = adaptive.counters()
    assert c1["breaker_demotions"] - c0["breaker_demotions"] == 1
    assert c1["errors"] >= 2
    dem = _kinds(spy, "adaptive_demote")
    assert any(e.get("reason") == "breaker_open" for e in dem)
    assert any(e.get("reason") == "error" for e in dem)
    lifecycle.reset_lifecycle()


def test_adaptive_domain_registered():
    assert "adaptive" in lifecycle.BREAKER_DOMAINS
    assert set(adaptive.DECISIONS) == set(adaptive._DECISION_COUNTER)


# ---------------------------------------------------------------------------
# health() stats surface
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_health_stats_section(zipf_runs):
    # content: the section captured right after the `on` drive
    st = zipf_runs["health"]
    assert st["recent_exchanges"], "no per-exchange roll-up retained"
    last = st["recent_exchanges"][-1]
    assert {"op", "partitions", "maps", "bytes", "max", "median",
            "ratio"} <= set(last)
    assert st["last_skew_ratio"] >= 1.0
    assert st["adaptive"]["consults"] >= 1
    assert st["adaptive"]["skew_splits"] >= 1


def test_health_stats_surface():
    """TpuSession.health() carries the runtime-stats section (keys
    present even before any query ran; content is pinned by the
    slow-tier drive above and the exec-level split test's counters)."""
    live = TpuSession(conf=dict(BASE)).health()["stats"]
    assert {"recent_exchanges", "last_skew_ratio", "adaptive"} \
        <= set(live)
    assert set(adaptive.counters()) <= set(live["adaptive"])


# ---------------------------------------------------------------------------
# slow tier: the PR 6 storm with one adversarial zipf lane
# ---------------------------------------------------------------------------

FAST = {
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
    "spark.rapids.tpu.retry.backoffMs": "1",
}

STORM = dict(FAST, **{
    "spark.rapids.tpu.workload.enabled": "true",
    "spark.rapids.tpu.workload.maxConcurrentQueries": "2",
    "spark.rapids.tpu.workload.queueDepth": "8",
    "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
    "spark.rapids.sql.broadcastSizeThreshold": "-1",
    "spark.rapids.sql.retry.maxAttempts": "50",
    "spark.rapids.tpu.retry.backoffMs": "5",
})

#: the adversarial lane: same query shape, zipf key + a partitioned
#: shuffle so the skew shield has a split to take. Conversion OFF on
#: this lane — the tiny dim side would otherwise single-build-convert
#: the join and delete the skewed exchange before a split can happen
#: (the shield's preferred move, but this storm pins the SPLIT path)
ZIPF_LANE = {
    "spark.rapids.sql.shuffle.partitions": "4",
    "spark.rapids.tpu.adaptive.skewedPartitionMinBytes": "1024",
    "spark.rapids.tpu.adaptive.autoBroadcastMaxBytes": "-1",
}


@pytest.fixture(scope="module")
def storm_files(tmp_path_factory):
    """8 lanes of the PR 6 storm drive; lane 0's join key is zipf-
    shaped (~80% of fact rows on one key) instead of uniform."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("adaptive_storm")
    lanes = []
    for seed in range(8):
        rng = np.random.default_rng(seed)
        n_l, n_o = 2000, 500
        if seed == 0:
            hot = rng.random(n_l) < 0.8
            l_key = np.where(hot, 0,
                             rng.integers(0, n_o, n_l)).astype(np.int64)
        else:
            l_key = rng.integers(0, n_o, n_l)
        l_val = rng.random(n_l) * 100.0
        l_flag = rng.integers(0, 4, n_l)
        o_flag = rng.integers(0, 10, n_o)
        lp = str(d / f"lines-{seed}.parquet")
        op = str(d / f"orders-{seed}.parquet")
        pq.write_table(pa.table({
            "l_key": pa.array(l_key, pa.int64()),
            "l_val": pa.array(l_val, pa.float64()),
            "l_flag": pa.array(l_flag, pa.int64())}), lp,
            row_group_size=512)
        pq.write_table(pa.table({
            "o_key": pa.array(np.arange(n_o), pa.int64()),
            "o_flag": pa.array(o_flag, pa.int64())}), op,
            row_group_size=128)
        keep = (l_flag != 0) & (o_flag[l_key] < 5)
        oracle = {}
        for k, v in zip(l_key[keep], l_val[keep]):
            s, c = oracle.get(int(k), (0.0, 0))
            oracle[int(k)] = (s + float(v), c + 1)
        lanes.append((lp, op, oracle))
    return lanes


def _run_storm_query(settings, lane):
    from spark_rapids_tpu.api.functions import col, lit
    lp, op, _ = lane
    sess = TpuSession(settings)
    lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
    orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                  (F.count(), "cnt"))
    return agg.sort(("rev", False)).collect()


def _assert_matches_oracle(rows, oracle, label):
    got = {int(k): (rev, int(cnt)) for k, rev, cnt in rows}
    assert set(got) == set(oracle), label
    for k, (rev, cnt) in got.items():
        o_rev, o_cnt = oracle[k]
        assert cnt == o_cnt, (label, k)
        assert abs(rev - o_rev) <= 1e-9 * max(abs(o_rev), 1.0), \
            (label, k)


@pytest.mark.slow  # minute-scale: the 8-lane storm under forced spill
def test_storm_with_zipf_lane_no_neighbor_sheds(storm_files):
    """Acceptance: the PR 6 8-lane storm with one adversarial zipf
    lane — every lane (including the skewed one) matches its oracle,
    the zipf lane's skew triggered splits, and NO neighbor was shed or
    wedged by the adversarial shape."""
    try:
        reset_buffer_catalog()
        reset_memory_budget(112 * 1024)
        workload.reset_workload()
        c0 = adaptive.counters()
        results = [None] * 8

        def lane(i):
            settings = dict(STORM, **ZIPF_LANE) if i == 0 else STORM
            try:
                results[i] = _run_storm_query(settings, storm_files[i])
            except BaseException as e:  # noqa: BLE001 — asserted below
                results[i] = e

        threads = [threading.Thread(target=lane, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
            assert not t.is_alive(), "a lane wedged"
        for i in range(8):
            assert not isinstance(results[i], BaseException), results[i]
            _assert_matches_oracle(results[i], storm_files[i][2],
                                   f"lane {i}")
        cnt = workload.counters()
        assert cnt["admitted"] == 8 and cnt["shed"] == 0, \
            "the zipf lane must not shed a neighbor"
        c1 = adaptive.counters()
        assert c1["skew_splits"] - c0["skew_splits"] >= 1, \
            "the adversarial lane never engaged the skew shield"
        buffer_catalog().drain_writeback()
        assert workload.snapshot()["admitted"] == 0
    finally:
        workload.reset_workload()
        reset_buffer_catalog()
        reset_memory_budget()
