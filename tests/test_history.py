"""Query history store (ISSUE 17 tentpole piece 2): one JSONL capsule
per governed collect behind spark.rapids.tpu.history.{enabled,dir,
maxBytes} — default off = one pointer check; capsule schema; rotation;
configure() lifecycle semantics — plus the event-log
rotation-under-concurrent-emission regression (satellite)."""

import glob
import json
import os
import threading

import numpy as np
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs import events, history
from spark_rapids_tpu.obs.phase import PHASES
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField


@pytest.fixture(autouse=True)
def _history_isolation():
    yield
    history.reset_history()
    events.reset_event_bus()
    TpuSession()  # restore the default active conf


def _q1_query(sess, n=3000):
    rng = np.random.default_rng(0)
    schema = Schema((StructField("returnflag", INT),
                     StructField("quantity", LONG),
                     StructField("extendedprice", DOUBLE),
                     StructField("discount", DOUBLE)))
    df = sess.from_pydict(
        {"returnflag": rng.integers(0, 4, n).tolist(),
         "quantity": rng.integers(1, 51, n).tolist(),
         "extendedprice": (rng.random(n) * 1000).tolist(),
         "discount": (rng.random(n) * 0.1).tolist()}, schema)
    return (df.filter(col("quantity") <= lit(45))
              .select(col("returnflag"), col("quantity"),
                      (col("extendedprice") * (lit(1.0) - col("discount")))
                      .alias("disc_price"))
              .group_by("returnflag")
              .agg((Sum(col("quantity")), "sum_qty"),
                   (Sum(col("disc_price")), "sum_disc"), (Count(), "cnt")))


def _read_capsules(d):
    """Rotated-set order: the base file holds the OLDEST records, then
    .1.jsonl, .2.jsonl, ... ascending (the event-log convention)."""
    def key(path):
        stem = path.rsplit(".jsonl", 1)[0]
        suffix = stem.rsplit(".", 1)[-1]
        return int(suffix) if suffix.isdigit() else 0
    out = []
    for path in sorted(glob.glob(str(d / "history-*.jsonl*")), key=key):
        with open(path) as f:
            for ln in f:
                if ln.strip():
                    out.append(json.loads(ln))
    return out


# ---------------------------------------------------------------------------
# disabled mode (the default): one pointer check per collect
# ---------------------------------------------------------------------------

def test_disabled_default_writes_nothing(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.history.dir": str(tmp_path)})
    assert history.active_store() is None   # the one pointer a collect pays
    rows = _q1_query(sess).collect()
    assert rows
    assert glob.glob(str(tmp_path / "*")) == []


# ---------------------------------------------------------------------------
# the capsule
# ---------------------------------------------------------------------------

def test_collect_appends_one_self_describing_capsule(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.history.enabled": "true",
                       "spark.rapids.tpu.history.dir": str(tmp_path)})
    assert history.active_store() is not None
    rows = _q1_query(sess).collect()
    assert len(rows) == 4
    (cap,) = _read_capsules(tmp_path)
    assert cap["ok"] is True
    assert cap["query"] is not None
    assert cap["attempts"] == 1
    assert cap["priority"] == "interactive"
    assert cap["wall_ns"] > 0
    assert cap["mesh_devices"] >= 1
    assert isinstance(cap["ts_ms"], int)
    # the plan fingerprint is the diff join key — stable hex digest
    assert isinstance(cap["fingerprint"], str) and len(cap["fingerprint"]) == 40
    # the phase ledger rides the capsule and stays closed
    assert set(cap["phases"]) == set(PHASES)
    assert sum(cap["phases"].values()) == cap["wall_ns"]
    # essential metrics + counter-family deltas (total.* sums span
    # every operator, so rows >= the 4 result rows)
    assert cap["rows"] >= 4 and cap["batches"] >= 1
    assert cap["sem_wait_ns"] >= 0 and cap["spill_bytes"] == 0
    assert cap["dispatch"]["dispatches"] > 0
    for fam in ("shuffle", "ici", "upload", "workload"):
        assert fam in cap
    # a second collect of the SAME plan shape appends a second capsule
    # with the SAME fingerprint (the aggregation key)
    _q1_query(sess).collect()
    caps = _read_capsules(tmp_path)
    assert len(caps) == 2
    assert caps[0]["fingerprint"] == caps[1]["fingerprint"]


def test_failed_query_capsule_keeps_its_own_fingerprint(tmp_path):
    """A query that dies MID-execution still harvested its own plan, so
    its capsule carries its OWN fingerprint (joining the healthy runs
    of the same shape in the aggregation) with ok=False and closed
    phase books."""
    sess = TpuSession({
        "spark.rapids.tpu.history.enabled": "true",
        "spark.rapids.tpu.history.dir": str(tmp_path),
        "spark.rapids.tpu.task.maxAttempts": "1",
        "spark.rapids.tpu.task.retryBackoffMs": "1",
    })
    _q1_query(sess).collect()  # a healthy run of the same plan shape
    from spark_rapids_tpu import faults
    try:
        faults.install(
            "device.dispatch:prob=1,seed=11,kind=device,max=99")
        with pytest.raises(Exception):
            _q1_query(sess).collect()
    finally:
        faults.install(None)
    caps = _read_capsules(tmp_path)
    assert len(caps) == 2
    ok_cap, bad_cap = caps
    assert ok_cap["ok"] is True and ok_cap["fingerprint"]
    assert bad_cap["ok"] is False
    assert bad_cap["fingerprint"] == ok_cap["fingerprint"]
    assert bad_cap["wall_ns"] > 0
    assert sum(bad_cap["phases"].values()) == bad_cap["wall_ns"]


def test_shed_query_capsule_has_no_stale_plan(tmp_path):
    """A query that dies BEFORE its plan exists (admission shed) must
    not write the PREVIOUS query's fingerprint/metrics into its
    capsule: ok=False, fingerprint None, wall still measured."""
    from spark_rapids_tpu import QueryAdmissionError
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec import workload
    settings = {
        "spark.rapids.tpu.history.enabled": "true",
        "spark.rapids.tpu.history.dir": str(tmp_path),
        "spark.rapids.tpu.workload.enabled": "true",
        "spark.rapids.tpu.workload.maxConcurrentQueries": "1",
        "spark.rapids.tpu.workload.queueDepth": "0",
    }
    sess = TpuSession(settings)
    _q1_query(sess).collect()  # seeds _last_query_profile
    m = workload.manager()
    ticket = m.admit(RapidsConf(settings), None)  # occupy the one slot
    try:
        with pytest.raises(QueryAdmissionError):
            _q1_query(sess).collect()
    finally:
        m.release(ticket)
        workload.reset_workload()
    caps = _read_capsules(tmp_path)
    assert len(caps) == 2
    ok_cap, shed_cap = caps
    assert ok_cap["ok"] is True and ok_cap["fingerprint"]
    assert shed_cap["ok"] is False
    assert shed_cap["fingerprint"] is None   # never the stale plan's
    assert shed_cap["rows"] == 0 and shed_cap["batches"] == 0


# ---------------------------------------------------------------------------
# rotation + write-never-raises
# ---------------------------------------------------------------------------

def test_capsule_rotation_past_max_bytes(tmp_path):
    store = history.enable(str(tmp_path), max_bytes=512)
    try:
        for i in range(40):
            store.append({"i": i, "pad": "x" * 64})
        assert store.records == 40
    finally:
        history.reset_history()
    files = glob.glob(str(tmp_path / "history-*.jsonl*"))
    assert len(files) > 1, "512-byte cap never rotated"
    caps = _read_capsules(tmp_path)
    assert [c["i"] for c in caps] == list(range(40))  # ordered, lossless


def test_write_failure_warns_once_and_self_uninstalls(tmp_path, caplog):
    store = history.enable(str(tmp_path))
    store.append({"i": 0})
    # kill the sink out from under the store: next append must not raise
    store._file.close()  # noqa: SLF001 — simulating a dead file handle
    with caplog.at_level("WARNING", logger="spark_rapids_tpu.obs"):
        store.append({"i": 1})
    assert history.active_store() is None   # self-uninstalled
    assert any("history" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# configure() lifecycle (the event-bus conf semantics)
# ---------------------------------------------------------------------------

def test_configure_unset_keeps_explicit_false_tears_down(tmp_path):
    TpuSession({"spark.rapids.tpu.history.enabled": "true",
                "spark.rapids.tpu.history.dir": str(tmp_path)})
    store = history.active_store()
    assert store is not None
    TpuSession()  # history.enabled UNSET: another session's store lives on
    assert history.active_store() is store
    TpuSession({"spark.rapids.tpu.history.enabled": "false"})  # explicit
    assert history.active_store() is None


# ---------------------------------------------------------------------------
# counter deltas + worst-skew summarization (unit)
# ---------------------------------------------------------------------------

def test_counters_delta_numeric_only():
    before = {"shuffle": {"bytes": 100, "frames": 2, "flag": True}}
    after = {"shuffle": {"bytes": 350, "frames": 5, "flag": True},
             "ici": {"rounds": 3}}
    d = history.counters_delta(before, after)
    assert d["shuffle"] == {"bytes": 250, "frames": 3}  # bools skipped
    assert d["ici"] == {"rounds": 3}


def test_build_capsule_tolerates_missing_surfaces():
    """A capsule from a query with no stats, no summary, no phases
    still self-describes (every schema field present)."""
    cap = history.build_capsule(
        query_id=7, fingerprint=None, ok=False, priority="batch",
        attempts=3, wall_ns=123, phases=None, stats=None, summary=None,
        deltas={"dispatch": {"dispatches": 1}})
    for field in ("ts_ms", "query", "fingerprint", "ok", "priority",
                  "attempts", "wall_ns", "mesh_devices", "phases",
                  "rows", "batches", "sem_wait_ns", "spill_bytes",
                  "skew"):
        assert field in cap
    assert cap["query"] == 7 and cap["attempts"] == 3
    assert cap["phases"] is None and cap["skew"] is None
    assert cap["dispatch"] == {"dispatches": 1}
    json.dumps(cap)  # JSONL-serializable as-is


# ---------------------------------------------------------------------------
# satellite: event-log rotation under concurrent emission
# ---------------------------------------------------------------------------

def test_eventlog_rotation_under_concurrent_emission(tmp_path):
    """N threads hammering a small-maxBytes bus: rotation must lose no
    records and tear no lines (every line of every rotated member
    parses, and the full id set survives)."""
    bus = events.enable(str(tmp_path), max_bytes=4096)
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def hammer(tid):
        barrier.wait()
        for i in range(per_thread):
            # an unregistered kind defaults to MODERATE — kept at the
            # bus's default level on every thread
            events.emit("hammer", tid=tid, i=i, pad="y" * 40)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events.reset_event_bus()
    files = glob.glob(str(tmp_path / "events-*.jsonl*"))
    assert len(files) > 1, "4KB cap never rotated under the storm"
    seen = set()
    for path in files:
        with open(path) as f:
            for ln in f:
                assert ln.endswith("\n"), f"torn line in {path}"
                rec = json.loads(ln)   # no partial lines
                if rec["kind"] == "hammer":
                    seen.add((rec["tid"], rec["i"]))
    assert seen == {(t, i) for t in range(n_threads)
                    for i in range(per_thread)}, "lost records"
