"""IO layer tests: parquet/CSV/JSON read & parquet write round-trips
(reference parquet/csv/json integration suites, SURVEY §4)."""

import json
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)


@pytest.fixture
def sess():
    return TpuSession()


@pytest.fixture
def pq_dir(tmp_path):
    t1 = pa.table({"k": ["a", "b", None, "a"], "v": [1, 2, 3, 4],
                   "d": [1.5, None, 2.5, 3.5]})
    t2 = pa.table({"k": ["c", "b"], "v": [5, 6], "d": [4.5, 5.5]})
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(t1, d / "part-0.parquet", row_group_size=2)
    pq.write_table(t2, d / "part-1.parquet")
    return str(d)


def test_parquet_read_directory(sess, pq_dir):
    df = sess.read_parquet(pq_dir)
    assert set(df.columns) == {"k", "v", "d"}
    got = sorted(df.collect(), key=repr)
    assert len(got) == 6
    assert ("a", 1, 1.5) in got and ("c", 5, 4.5) in got \
        and (None, 3, 2.5) in got


def test_parquet_query_pipeline(sess, pq_dir):
    got = (sess.read_parquet(pq_dir)
           .filter(F.col("v") > 1)
           .group_by("k").agg((F.sum("v"), "s"))
           .sort("k").collect())
    assert got == [(None, 3), ("a", 4), ("b", 8), ("c", 5)]


def test_parquet_roundtrip_write(sess, pq_dir, tmp_path):
    out = str(tmp_path / "out.parquet")
    sess.read_parquet(pq_dir).filter(F.col("v") <= 4).write_parquet(out)
    back = sess.read_parquet(out)
    assert back.count() == 4


def test_parquet_partitioned_write(sess, pq_dir, tmp_path):
    out = str(tmp_path / "parted")
    sess.read_parquet(pq_dir).filter(
        F.col("k") == F.lit("b")).write_parquet(out, partition_by=["k"])
    assert os.path.isdir(os.path.join(out, "k=b"))


def test_csv_read(sess, tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("a,b,c\n1,x,1.5\n2,y,2.5\n3,,3.5\n")
    df = sess.read_csv(str(p))
    got = df.collect()
    assert got == [(1, "x", 1.5), (2, "y", 2.5), (3, None, 3.5)]


def test_csv_read_with_schema(sess, tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("a,b\n1,10\n2,20\n")
    schema = Schema((StructField("a", LONG), StructField("b", DOUBLE)))
    df = sess.read_csv(str(p), schema=schema)
    assert df.collect() == [(1, 10.0), (2, 20.0)]
    assert df.schema.fields[1].data_type.simple_name() == "double"


def test_json_read(sess, tmp_path):
    p = tmp_path / "t.jsonl"
    rows = [{"a": 1, "s": "x"}, {"a": 2, "s": None}, {"a": 3, "s": "z"}]
    p.write_text("\n".join(json.dumps(r) for r in rows))
    df = sess.read_json(str(p))
    assert df.collect() == [(1, "x"), (2, None), (3, "z")]


def test_multifile_order_preserved(sess, tmp_path):
    d = tmp_path / "many"
    d.mkdir()
    for i in range(5):
        pq.write_table(pa.table({"i": [i * 10 + j for j in range(3)]}),
                       d / f"f{i}.parquet")
    got = [r[0] for r in sess.read_parquet(str(d)).collect()]
    assert got == sorted(got)
    assert len(got) == 15
