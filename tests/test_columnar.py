"""Columnar substrate tests: round-trips, padding invariants, gather/compact/
concat kernels (the engine's copy_if/gather — reference cuDF L6 analog)."""

import numpy as np
import jax.numpy as jnp
import pytest

from spark_rapids_tpu.types import (
    BOOLEAN, DOUBLE, INT, LONG, STRING, Schema,
)
from spark_rapids_tpu.columnar import Column, ColumnarBatch, StringColumn
from spark_rapids_tpu.ops.basic import (
    compact_columns, concat_columns, gather_column, slice_rows,
)


def make_batch():
    return ColumnarBatch.from_pydict(
        {
            "a": [1, 2, None, 4, 5],
            "b": [1.5, None, 3.5, -0.0, 2.25],
            "s": ["apple", None, "banana", "", "cherry"],
        },
        Schema.of(a=INT, b=DOUBLE, s=STRING),
    )


def test_roundtrip():
    b = make_batch()
    assert b.num_rows_host == 5
    assert b.capacity == 128
    d = b.to_pydict()
    assert d["a"] == [1, 2, None, 4, 5]
    assert d["b"] == [1.5, None, 3.5, -0.0, 2.25]
    assert d["s"] == ["apple", None, "banana", "", "cherry"]


def test_arrow_roundtrip():
    import pyarrow as pa
    t = pa.table({
        "x": pa.array([10, None, 30], pa.int64()),
        "y": pa.array(["a", "bb", None], pa.string()),
    })
    b = ColumnarBatch.from_arrow(t)
    t2 = b.to_arrow()
    assert t2.column("x").to_pylist() == [10, None, 30]
    assert t2.column("y").to_pylist() == ["a", "bb", None]


def test_gather_fixed():
    b = make_batch()
    idx = jnp.asarray(np.array([4, 0, 2] + [0] * 125, np.int32))
    valid = jnp.asarray(np.array([True] * 3 + [False] * 125))
    g = gather_column(b.column("a"), idx, valid)
    assert g.to_pylist(3) == [5, 1, None]


def test_gather_string():
    b = make_batch()
    idx = jnp.asarray(np.array([2, 0, 3, 1] + [0] * 124, np.int32))
    valid = jnp.asarray(np.array([True] * 4 + [False] * 124))
    g = gather_column(b.column("s"), idx, valid)
    assert g.to_pylist(4) == ["banana", "apple", "", None]


def test_compact():
    b = make_batch()
    keep = jnp.asarray(np.array([True, False, True, False, True] + [False] * 123))
    cols, n = compact_columns(b.columns, keep, b.num_rows)
    assert int(n) == 3
    assert cols[0].to_pylist(3) == [1, None, 5]
    assert cols[2].to_pylist(3) == ["apple", "banana", "cherry"]


def test_concat():
    a = Column.from_pylist([1, None, 3], INT)
    b = Column.from_pylist([7, 8], INT)
    out = concat_columns(a, b, jnp.int32(3), jnp.int32(2), 256)
    assert out.to_pylist(5) == [1, None, 3, 7, 8]


def test_concat_string():
    a = StringColumn.from_pylist(["xx", None])
    b = StringColumn.from_pylist(["yyy", "z", ""])
    out = concat_columns(a, b, jnp.int32(2), jnp.int32(3), 256)
    assert out.to_pylist(5) == ["xx", None, "yyy", "z", ""]


def test_slice():
    c = Column.from_pylist([1, 2, 3, 4, 5, 6], LONG)
    s = slice_rows(c, jnp.int32(2), jnp.int32(3), 128)
    assert s.to_pylist(3) == [3, 4, 5]


def test_bucketing():
    from spark_rapids_tpu.columnar import bucket_capacity
    assert bucket_capacity(1) == 128
    assert bucket_capacity(128) == 128
    assert bucket_capacity(129) == 256
    assert bucket_capacity(1000) == 1024
