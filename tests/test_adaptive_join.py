"""AdaptiveJoinExec: runtime-measured build side (AQE-lite, r2 item 10)."""
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec.joins import AdaptiveJoinExec
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField


def _find_adaptive(e):
    from spark_rapids_tpu.exec.joins import AdaptiveJoinExec
    if isinstance(e, AdaptiveJoinExec):
        return e
    for c in e.children:
        got = _find_adaptive(c)
        if got is not None:
            return got
    return None


def _sess_dfs(sess):
    left = sess.from_pydict(
        {"k": [1, 2, 3, 4, 2], "x": [10, 20, 30, 40, 21]},
        schema=Schema((StructField("k", LONG), StructField("x", LONG))))
    right = sess.from_pydict(
        {"k": [2, 3, 2, 9], "g": ["p", "q", "r", "z"]},
        schema=Schema((StructField("k", LONG), StructField("g", STRING))))
    return left, right


def test_post_aggregation_build_goes_adaptive():
    # a keyed aggregate has unknown plan-time size: the join over it must
    # pick its strategy at runtime instead of never broadcasting.
    # broadcast threshold 0 keeps the (known-size) left side from being
    # broadcast, isolating the adaptive path... threshold must stay >= 0
    # for adaptive planning, so use 1 byte
    sess = TpuSession(conf={
        "spark.rapids.sql.broadcastSizeThreshold": "1"})
    left, right = _sess_dfs(sess)
    small = right.group_by("k").agg((F.count(), "n"))
    q = left.join(small, on="k", how="inner")
    tree = q._exec().tree_string()
    assert "AdaptiveJoinExec" in tree, tree
    out = sorted(q.collect())
    assert out == [(2, 20, 2), (2, 21, 2), (3, 30, 1)]


def test_adaptive_join_measures_and_runs():
    sess = TpuSession()
    left, right = _sess_dfs(sess)
    agg = right.group_by("k").agg((F.count(), "n"))
    q = left.join(agg, on="k", how="left_outer")
    ex = q._exec()
    out = sorted(q.collect(), key=lambda r: (r[0], r[1]))
    assert out == [(1, 10, None), (2, 20, 2), (2, 21, 2), (3, 30, 1),
                   (4, 40, None)]


@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_symmetric_build_side_choice():
    # inner join, left much smaller: the runtime measurement must build
    # LEFT (semantics-preserving swap). Post-aggregation sides make the
    # plan-time sizes unknown, which is what routes to the adaptive exec
    # (known sizes keep the streaming join).
    sess = TpuSession(conf={
        "spark.rapids.sql.broadcastSizeThreshold": "1"})
    left = sess.from_pydict(
        {"k": [2, 3], "x": [20, 30]},
        schema=Schema((StructField("k", LONG), StructField("x", LONG)))
    ).group_by("k").agg((F.sum(F.col("x")), "sx"))
    right = sess.from_pydict(
        {"k": list(range(600)), "y": list(range(600))},
        schema=Schema((StructField("k", LONG), StructField("y", LONG)))
    ).group_by("k").agg((F.sum(F.col("y")), "sy"))
    q = left.join(right, on="k", how="inner")
    ex = q._exec()
    assert "AdaptiveJoinExec" in ex.tree_string()
    out = sorted(ex.collect())
    assert out == [(2, 20, 2), (3, 30, 3)]
    aj = _find_adaptive(ex)
    assert aj is not None and aj._choice == "build_left", aj._choice


@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_symmetric_both_huge_subpartitions_with_spill():
    # both sides over the (tiny, forced) sub-partition threshold: the
    # adaptive join must route through sub-partitioned exchanges
    sess = TpuSession(conf={
        "spark.rapids.sql.broadcastSizeThreshold": "1",
        "spark.rapids.sql.join.subPartitionThreshold": "4096",
        "spark.rapids.shuffle.mode": "MULTITHREADED"})
    n = 3000
    # aggregates make both sides' sizes UNKNOWN at plan time, so the
    # runtime-measuring adaptive exec owns the decision
    left = sess.from_pydict(
        {"k": [i % 500 for i in range(n)], "x": list(range(n))},
        schema=Schema((StructField("k", LONG), StructField("x", LONG)))
    ).group_by("k").agg((F.sum(F.col("x")), "sx"))
    right = sess.from_pydict(
        {"k": [i % 500 for i in range(n)], "y": list(range(n))},
        schema=Schema((StructField("k", LONG), StructField("y", LONG)))
    ).group_by("k").agg((F.sum(F.col("y")), "sy"))
    q = left.join(right, on="k", how="inner")
    ex = q._exec()
    out = ex.collect()
    assert len(out) == 500
    aj = _find_adaptive(ex)
    assert aj is not None and aj._choice == "subpartition",         (aj and aj._choice, aj and aj._measured)
