"""UDF compiler tests — Python bytecode → device expression tree
(reference udf-compiler: CatalystExpressionBuilder.scala:45,
OpcodeSuite.scala is the test model: compile, run, compare against the
interpreted function)."""

import sys

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, udf
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.types import (DOUBLE, LONG, STRING, Schema,
                                    StructField)
from spark_rapids_tpu.udf_compiler import UdfCompileError, compile_udf


def _run(expr, data, sch):
    sess = TpuSession()
    df = sess.from_pydict(data, sch)
    return [r[0] for r in df.select(expr.alias("out")).collect()]


NUM_SCH = Schema((StructField("x", LONG), StructField("y", LONG)))
STR_SCH = Schema((StructField("s", STRING),))

#: the compiler targets the 3.11+ specialized opcode set (BINARY_OP,
#: RESUME, ...); 3.10 bytecode still emits BINARY_MULTIPLY & co., which
#: it deliberately does not translate — gate those cases, don't fail
#: every 3.10 run (ISSUE 4 satellite: tier-1 fully green)
py311 = pytest.mark.skipif(
    sys.version_info < (3, 11),
    reason="udf compiler targets Python 3.11+ opcodes; this case's "
           "3.10 bytecode uses legacy opcodes it does not translate")


@py311
def test_compile_arithmetic_straight_line():
    e = compile_udf(lambda x, y: (x + y) * 2 - x, [col("x"), col("y")])
    got = _run(e, {"x": [1, 2, None], "y": [10, 20, 30]}, NUM_SCH)
    assert got == [21, 42, None]


def test_compile_comparison_and_ternary():
    e = compile_udf(lambda x, y: x if x > y else y, [col("x"), col("y")])
    got = _run(e, {"x": [1, 5, 3], "y": [2, 4, 3]}, NUM_SCH)
    assert got == [2, 5, 3]


@py311
def test_compile_boolean_shortcircuit():
    fn = lambda x, y: (x > 0) and (y > 0)  # noqa: E731
    e = compile_udf(fn, [col("x"), col("y")])
    got = _run(e, {"x": [1, 1, -1], "y": [1, -1, 1]}, NUM_SCH)
    assert got == [True, False, False]


def test_compile_none_checks():
    fn = lambda x, y: -1 if x is None else x  # noqa: E731
    e = compile_udf(fn, [col("x"), col("y")])
    got = _run(e, {"x": [1, None, 3], "y": [0, 0, 0]}, NUM_SCH)
    assert got == [1, -1, 3]


@py311
def test_compile_string_methods():
    fn = lambda s: s.strip().upper() if s.startswith("a") else s.lower()  # noqa: E731
    e = compile_udf(fn, [col("s")])
    got = _run(e, {"s": ["abc  ", "XYZ", "a", None]}, STR_SCH)
    assert got == ["ABC", "xyz", "A", None]


@py311
def test_compile_builtins():
    e = compile_udf(lambda x, y: min(abs(x), y) + max(x, y),
                    [col("x"), col("y")])
    got = _run(e, {"x": [-5, 2], "y": [3, 10]}, NUM_SCH)
    assert got == [(min(5, 3) + max(-5, 3)), (min(2, 10) + max(2, 10))]


@py311
def test_compile_closure_capture():
    k = 7
    e = compile_udf(lambda x, y: x + k, [col("x"), col("y")])
    got = _run(e, {"x": [1, 2], "y": [0, 0]}, NUM_SCH)
    assert got == [8, 9]


@py311
def test_compile_local_assignment():
    def fn(x, y):
        t = x * 2
        return t + y
    e = compile_udf(fn, [col("x"), col("y")])
    got = _run(e, {"x": [3], "y": [4]}, NUM_SCH)
    assert got == [10]


def test_loops_rejected():
    def fn(x, y):
        acc = 0
        for i in range(3):
            acc += x
        return acc
    with pytest.raises(UdfCompileError):
        compile_udf(fn, [col("x"), col("y")])


def test_unknown_call_rejected():
    import os
    with pytest.raises(UdfCompileError):
        compile_udf(lambda x, y: os.getpid() + x, [col("x"), col("y")])


def test_planner_rewrite_replaces_callback():
    """With the compiler conf on, a callback PythonUDF in a projection
    becomes a fused device expression (no pure_callback in the plan);
    with it off, the callback path remains — results identical."""
    data = {"x": [1.0, 2.0, -3.0], "y": [2.0, 0.5, 1.0]}
    sch = Schema((StructField("x", DOUBLE), StructField("y", DOUBLE)))
    f = udf(lambda a, b: a * b + 1.0, return_type=DOUBLE)

    def q(sess):
        df = sess.from_pydict(data, sch)
        return df.select(f(col("x"), col("y")).alias("r"))

    on = TpuSession({"spark.rapids.sql.udfCompiler.enabled": "true"})
    off = TpuSession()
    tree_on = q(on)._exec().tree_string()
    tree_off = q(off)._exec().tree_string()
    assert "PythonUDF" not in tree_on or "udf" not in tree_on.lower() \
        or tree_on != tree_off
    assert q(on).collect() == q(off).collect() == \
        [(3.0,), (2.0,), (-2.0,)]


def test_planner_rewrite_keeps_uncompilable_udfs():
    """A UDF the compiler cannot handle keeps the host-callback path and
    still runs (reference: fall back to the JVM UDF)."""
    import math as pymath
    data = {"x": [1.0, 4.0], "y": [1.0, 1.0]}
    sch = Schema((StructField("x", DOUBLE), StructField("y", DOUBLE)))
    f = udf(lambda a, b: pymath.gamma(a) + b, return_type=DOUBLE)
    sess = TpuSession({"spark.rapids.sql.udfCompiler.enabled": "true"})
    df = sess.from_pydict(data, sch)
    got = df.select(f(col("x"), col("y")).alias("r")).collect()
    assert got == [(pymath.gamma(1.0) + 1.0,), (pymath.gamma(4.0) + 1.0,)]
