"""Two-limb decimal128: kernels, columns, arithmetic, exact sums."""
import decimal as dec
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.columnar.column import Decimal128Column
from spark_rapids_tpu.expr.core import col, resolve
from spark_rapids_tpu.ops import decimal128 as D
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)
from spark_rapids_tpu.types import (DecimalType, STRING, Schema,
                                    StructField)


def _pair(vals):
    h, l = [], []
    for v in vals:
        u = v & ((1 << 128) - 1)
        lo = u & ((1 << 64) - 1)
        hi = u >> 64
        l.append(lo - (1 << 64) if lo >= (1 << 63) else lo)
        h.append(hi - (1 << 64) if hi >= (1 << 63) else hi)
    return (jnp.asarray(np.array(h, np.int64)),
            jnp.asarray(np.array(l, np.int64)))


def _unpair(h, l):
    out = []
    for hi, lo in zip(np.asarray(h).tolist(), np.asarray(l).tolist()):
        u = ((hi & ((1 << 64) - 1)) << 64) | (lo & ((1 << 64) - 1))
        out.append(u - (1 << 128) if u >= (1 << 127) else u)
    return out


def test_kernel_add_mul_rescale():
    rng = random.Random(5)
    a = [rng.randint(-10**30, 10**30) for _ in range(40)] + [0, 1, -1]
    b = [rng.randint(-10**30, 10**30) for _ in range(40)] + [5, -7, 1]
    ha, la = _pair(a)
    hb, lb = _pair(b)
    gh, gl = D.add128(ha, la, hb, lb)
    assert _unpair(gh, gl) == [
        (x + y + 2**127) % 2**128 - 2**127 for x, y in zip(a, b)]
    xs = [rng.randint(-(10**18), 10**18) for _ in range(40)]
    ys = [rng.randint(-(10**18), 10**18) for _ in range(40)]
    mh, ml = D.mul_i64_i64(jnp.asarray(np.array(xs, np.int64)),
                           jnp.asarray(np.array(ys, np.int64)))
    assert _unpair(mh, ml) == [x * y for x, y in zip(xs, ys)]
    vv = [rng.randint(-10**25, 10**25) for _ in range(30)] + [449, 450,
                                                              -450, -25]
    h, l = _pair(vv)
    rh, rl, ov = D.rescale(h, l, 6, 2)
    exp = [int(dec.Decimal(v).scaleb(-4).quantize(
        dec.Decimal(1), rounding=dec.ROUND_HALF_UP)) for v in vv]
    assert _unpair(rh, rl) == exp
    assert not bool(jnp.any(ov))


def test_limb_sum_recombination():
    rng = random.Random(7)
    vals = [rng.randint(-10**30, 10**30) for _ in range(500)]
    h, l = _pair(vals)
    sums = [jnp.sum(lane) for lane in D.limb16_lanes(h, l)]
    rh, rl = D.combine_limb_sums([s[None] for s in sums])
    assert _unpair(rh, rl)[0] == sum(vals)


def test_column_roundtrip_and_serialize():
    t = DecimalType(30, 4)
    vals = [dec.Decimal("123456789012345678901234.5678"),
            dec.Decimal("-1.0001"), None, dec.Decimal("0")]
    sch = Schema((StructField("d", t),))
    b = ColumnarBatch.from_pydict({"d": vals}, sch)
    assert isinstance(b.columns[0], Decimal128Column)
    unscaled = [None if v is None else int(v.scaleb(4)) for v in vals]
    assert b.columns[0].to_pylist(4) == unscaled
    rt = deserialize_batch(serialize_batch(b), sch)
    assert rt.columns[0].to_pylist(4) == unscaled
    # arrow roundtrip
    back = b.to_arrow().column("d").to_pylist()
    assert back == vals


def test_multiply_into_decimal128_exact():
    t = DecimalType(12, 2)
    a = [dec.Decimal("12345678.90"), dec.Decimal("-0.05"), None,
         dec.Decimal("9999999999.99")]
    b = [dec.Decimal("2.50"), dec.Decimal("3.00"), dec.Decimal("1.00"),
         dec.Decimal("9999999999.99")]
    sch = Schema((StructField("a", t), StructField("b", t)))
    batch = ColumnarBatch.from_pydict({"a": a, "b": b}, sch)
    mul = resolve(col("a") * col("b"), sch)
    assert mul.data_type == DecimalType(25, 4)
    out = mul.columnar_eval(batch)
    exp = [None if x is None or y is None else
           int((x * y).scaleb(4)) for x, y in zip(a, b)]
    assert out.to_pylist(4) == exp


def test_group_by_decimal_sums_match_decimal_oracle():
    t = DecimalType(12, 2)
    sess = TpuSession()
    rng = random.Random(3)
    n = 60
    keys = [rng.choice("ABC") for _ in range(n)]
    q = [None if rng.random() < 0.1 else
         dec.Decimal(rng.randint(0, 10**12 - 1)).scaleb(-2)
         for _ in range(n)]
    p = [dec.Decimal(rng.randint(-(10**12) + 1, 10**12 - 1)).scaleb(-2)
         for _ in range(n)]
    df = sess.from_pydict(
        {"k": keys, "q": q, "p": p},
        schema=Schema((StructField("k", STRING), StructField("q", t),
                       StructField("p", t))))
    out = sorted(df.group_by("k").agg(
        (F.sum(F.col("q")), "sq"),
        (F.sum(F.col("q") * F.col("p")), "spq")).collect())
    import collections
    o_sq = collections.defaultdict(dec.Decimal)
    o_spq = collections.defaultdict(dec.Decimal)
    for k, qq, pp in zip(keys, q, p):
        if qq is not None:
            o_sq[k] += qq
            o_spq[k] += qq * pp
    exp = sorted((k, int(o_sq[k].scaleb(2)), int(o_spq[k].scaleb(4)))
                 for k in o_sq)
    assert out == exp


def test_grand_aggregate_decimal_sum():
    t = DecimalType(15, 3)
    sess = TpuSession()
    vals = [dec.Decimal("999999999999.999"), dec.Decimal("0.001"), None,
            dec.Decimal("-5.500")]
    df = sess.from_pydict({"v": vals},
                          schema=Schema((StructField("v", t),)))
    out = df.agg((F.sum(F.col("v")), "s")).collect()
    assert out == [(int(dec.Decimal("999999999994.500").scaleb(3)),)]


def test_sum_overflow_past_result_precision_is_null():
    # DECIMAL(35,0): sum type DECIMAL(38,0) = 10^38 bound; twelve values
    # of 9e34 total 1.08e36 (fits), but 9e34 * 1200 = 1.08e38 overflows
    t = DecimalType(35, 0)
    sess = TpuSession()
    small = sess.from_pydict({"v": [dec.Decimal(9 * 10 ** 34)] * 12},
                             schema=Schema((StructField("v", t),)))
    assert small.agg((F.sum(F.col("v")), "s")).collect() == \
        [(12 * 9 * 10 ** 34,)]
    big = sess.from_pydict({"v": [dec.Decimal(9 * 10 ** 34)] * 1200},
                           schema=Schema((StructField("v", t),)))
    assert big.agg((F.sum(F.col("v")), "s")).collect() == [(None,)]


def test_sum_overflow_past_128_bits_is_null_not_aliased():
    # the 192-bit checked combine: a true sum past 2^127 must NOT wrap
    # mod 2^128 back into range — it saturates and evaluates to NULL
    t = DecimalType(38, 0)
    v = dec.Decimal(85070591730234615865843651857942052864)  # 2^126
    sess = TpuSession()
    df = sess.from_pydict({"v": [v] * 4},
                          schema=Schema((StructField("v", t),)))
    assert df.agg((F.sum(F.col("v")), "s")).collect() == [(None,)]


def test_divide_into_decimal128_exact():
    sess = TpuSession()
    t = DecimalType(12, 2)
    a = [dec.Decimal("1.00"), dec.Decimal("2.50"),
         dec.Decimal("9999999999.99"), None, dec.Decimal("-7.00")]
    b = [dec.Decimal("2.00"), dec.Decimal("3.00"),
         dec.Decimal("0.03"), dec.Decimal("1.00"), dec.Decimal("0.00")]
    df = sess.from_pydict(
        {"a": a, "b": b},
        schema=Schema((StructField("a", t), StructField("b", t))))
    q = df.select((F.col("a") / F.col("b")).alias("d"))
    out_t = resolve(col("a") / col("b"),
                    Schema((StructField("a", t), StructField("b", t)))
                    ).data_type
    assert out_t.precision > 18  # genuinely the two-limb path
    got = [r[0] for r in q.collect()]
    ctx = dec.Context(prec=60)
    exp = []
    for x, y in zip(a, b):
        if x is None or y is None or y == 0:
            exp.append(None)
            continue
        v = ctx.divide(x, y).quantize(
            dec.Decimal(1).scaleb(-out_t.scale),
            rounding=dec.ROUND_HALF_UP, context=ctx)
        exp.append(int(v.scaleb(out_t.scale)))
    assert got == exp, (got, exp, out_t)
