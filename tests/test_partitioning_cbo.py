"""Partitioning modes (roundrobin/single/range), repartition/sample API,
distributed range sort, and the cost-based optimizer (reference:
GpuRoundRobinPartitioning / GpuSinglePartitioning / GpuRangePartitioner /
GpuSampleExec / CostBasedOptimizer.scala; SURVEY §2.5 #29, §2.2 #7,
§2.3 Sample)."""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.types import DOUBLE, LONG, STRING, Schema, StructField


def _sorted(rows):
    return sorted(rows, key=repr)


SCH = Schema((StructField("k", LONG), StructField("s", STRING)))


def _data(n=400):
    rng = np.random.default_rng(0)
    return {"k": [int(x) for x in rng.integers(-100, 100, n)],
            "s": [None if x % 7 == 0 else f"v{x}"
                  for x in rng.integers(0, 60, n)]}


def test_repartition_roundrobin_preserves_rows():
    sess = TpuSession()
    df = sess.from_pydict(_data(), SCH, batch_rows=64)
    out = df.repartition(4)
    tree = out._exec().tree_string()
    assert "HostShuffleExchangeExec" in tree
    assert _sorted(out.collect()) == _sorted(df.collect())


def test_coalesce_single_partition():
    sess = TpuSession()
    df = sess.from_pydict(_data(100), SCH, batch_rows=16)
    out = df.coalesce(1)
    exec_node = out._exec()
    batches = list(exec_node.execute())
    assert len(batches) == 1  # single partitioning: one output batch
    assert _sorted(r for b in [batches[0].to_pylist()] for r in b) == \
        _sorted(df.collect())


def test_sample_reproducible_and_fractional():
    sess = TpuSession()
    df = sess.from_pydict(_data(2000), SCH, batch_rows=256)
    s1 = df.sample(0.3, seed=7).collect()
    s2 = df.sample(0.3, seed=7).collect()
    assert s1 == s2                      # same seed → same rows
    s3 = df.sample(0.3, seed=8).collect()
    assert s1 != s3                      # different seed → different draw
    frac = len(s1) / 2000
    assert 0.2 < frac < 0.4              # ~Bernoulli(0.3)
    assert df.sample(0.0).collect() == []
    assert _sorted(df.sample(1.0).collect()) == _sorted(df.collect())


def test_range_partitioned_global_sort():
    sess = TpuSession({"spark.rapids.sql.shuffle.partitions": "4",
                       "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    data = _data(600)
    df = sess.from_pydict(data, SCH, batch_rows=64)
    q = df.sort("k")
    tree = q._exec().tree_string()
    assert "PartitionWiseSortExec" in tree
    assert "HostShuffleExchangeExec" in tree
    got = [r[0] for r in q.collect()]
    assert got == sorted(data["k"])
    # descending too
    got_d = [r[0] for r in df.sort(("k", False)).collect()]
    assert got_d == sorted(data["k"], reverse=True)


def test_range_sort_with_string_key_and_nulls():
    sess = TpuSession({"spark.rapids.sql.shuffle.partitions": "3"})
    data = _data(300)
    df = sess.from_pydict(data, SCH, batch_rows=64)
    got = [r[1] for r in df.sort("s").collect()]
    expect = sorted(data["s"], key=lambda v: (v is not None, v))
    assert got == expect  # nulls first (Spark asc default)


def test_cbo_places_tiny_section_on_host():
    on = TpuSession({"spark.rapids.sql.optimizer.enabled": "true"})
    off = TpuSession()
    data = {"k": [1, 2, 3], "s": ["a", "b", "c"]}

    def q(sess):
        df = sess.from_pydict(data, SCH)
        return df.select((col("k") + lit(1)).alias("k2"))

    tree_on = q(on)._exec().tree_string()
    tree_off = q(off)._exec().tree_string()
    assert "HostProjectExec" in tree_on       # 3 rows: dispatch dominates
    assert "HostProjectExec" not in tree_off  # default: stays on device
    assert q(on).collect() == q(off).collect() == [(2,), (3,), (4,)]
    assert "cost optimizer" in q(on).explain()


def test_cbo_keeps_large_section_on_device():
    on = TpuSession({"spark.rapids.sql.optimizer.enabled": "true"})
    df = on.from_pydict(_data(100000 // 250), SCH)  # 400 rows > breakeven
    big = on.from_pydict(
        {"k": list(range(5000)), "s": ["x"] * 5000}, SCH)
    tree = big.select((col("k") + lit(1)).alias("k2"))._exec().tree_string()
    assert "HostProjectExec" not in tree


@pytest.mark.slow  # ~11s: nightly tier (round-7 budget move, redundant tier-1 coverage)
def test_subpartitioned_join_for_big_build_side():
    """Both sides over the sub-partition threshold: the planner splits
    the join into hash sub-partitions through the host shuffle
    (reference GpuSubPartitionHashJoin.scala:547) — results identical to
    the in-memory join."""
    rng = np.random.default_rng(9)
    n = 800
    ldata = {"k": [int(x) for x in rng.integers(0, 40, n)],
             "v": [int(x) for x in rng.integers(0, 100, n)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 40, n)],
             "w": [int(x) for x in rng.integers(0, 100, n)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", LONG)))

    def q(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=128)
        r = sess.from_pydict(rdata, rsch, batch_rows=128)
        return l.join(r, on="k")

    sub = TpuSession({
        # tiny threshold: both sides "exceed device memory"
        "spark.rapids.sql.join.subPartitionThreshold": "1024",
        "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    plain = TpuSession({
        "spark.rapids.sql.join.subPartitionThreshold": "-1",
        "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    tree = q(sub)._exec().tree_string()
    assert "ShuffledHashJoinExec" in tree
    assert "HostShuffleExchangeExec" in tree
    assert _sorted(q(sub).collect()) == _sorted(q(plain).collect())
