"""Giant-partition two-pass windows (VERDICT r4 item 7; reference
GpuUnboundedToUnboundedAggWindowExec.scala:1155): when one partition
exceeds the chunk budget and every window expression is a whole-partition
aggregate, the exec carries tiny agg state + spillable pieces instead of
concatenating the partition, and pass 2 emits the pieces with broadcast
finals.

Marked `slow`: giant-partition shapes are minute-scale on one core;
bounded-window semantics stay gated in tier-1 (test_window.py,
test_window_range.py)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.exec.sort import SortExec
from spark_rapids_tpu.exec.window import WindowExec
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.expr.windowexprs import (
    RowNumber, WindowAgg, WindowFrame, window,
)
from spark_rapids_tpu.types import DOUBLE, LONG, STRING, Schema, StructField

SCHEMA = Schema((StructField("p", STRING), StructField("v", LONG),
                 StructField("d", DOUBLE)))


def _scan(data, batch_rows):
    n = len(data["p"])
    batches = [ColumnarBatch.from_pydict(
        {k: v[s:s + batch_rows] for k, v in data.items()}, SCHEMA)
        for s in range(0, n, batch_rows)]
    return InMemoryScanExec(batches, SCHEMA)


def _data(n_giant=900, n_small=40):
    rng = np.random.default_rng(3)
    parts = ["giant"] * n_giant + ["small"] * n_small
    vals = rng.integers(-100, 100, n_giant + n_small).tolist()
    vals[5] = None
    ds = rng.normal(0, 10, n_giant + n_small).tolist()
    return {"p": parts, "v": vals, "d": ds}


def _oracle(data, op):
    out = {}
    for p in set(data["p"]):
        vs = [v for q, v in zip(data["p"], data["v"])
              if q == p and v is not None]
        if op == "sum":
            out[p] = sum(vs)
        elif op == "count":
            out[p] = len(vs)
        elif op == "min":
            out[p] = min(vs)
        elif op == "max":
            out[p] = max(vs)
        elif op == "avg":
            out[p] = sum(vs) / len(vs)
    return out


@pytest.fixture()
def small_chunks(monkeypatch):
    # force the sorter to emit many small chunks so the giant partition
    # spans chunk boundaries
    monkeypatch.setattr(SortExec, "MERGE_FAN_IN", 2)


def test_two_pass_engages_and_matches_oracle(small_chunks):
    data = _data()
    spec = window(partition_by=["p"])
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s"),
                       (WindowAgg("count", col("v")).over(spec), "c"),
                       (WindowAgg("min", col("v")).over(spec), "mn"),
                       (WindowAgg("max", col("v")).over(spec), "mx"),
                       (WindowAgg("avg", col("v")).over(spec), "av")],
                      _scan(data, batch_rows=64))
    plan.TWO_PASS_THRESHOLD_ROWS = 128
    batches = list(plan.execute())
    # structural: the giant partition was NOT concatenated — output arrives
    # as multiple pieces (peak device memory stays ~chunk-sized)
    assert len(batches) > 2, len(batches)
    rows = [r for b in batches for r in b.to_pylist()]
    assert len(rows) == len(data["p"])
    sums, counts = _oracle(data, "sum"), _oracle(data, "count")
    mns, mxs, avs = (_oracle(data, "min"), _oracle(data, "max"),
                     _oracle(data, "avg"))
    for p, v, d, s, c, mn, mx, av in rows:
        assert s == sums[p] and c == counts[p], (p, s, c)
        assert mn == mns[p] and mx == mxs[p]
        assert av == pytest.approx(avs[p])


def test_two_pass_unbounded_rows_frame_qualifies(small_chunks):
    data = _data(400, 10)
    spec = window(partition_by=["p"], order_by=["v"],
                  frame=WindowFrame.rows(None, None))
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                      _scan(data, batch_rows=64))
    plan.TWO_PASS_THRESHOLD_ROWS = 128
    batches = list(plan.execute())
    assert len(batches) > 1
    rows = [r for b in batches for r in b.to_pylist()]
    sums = _oracle(data, "sum")
    assert all(r[3] == sums[r[0]] for r in rows)


def test_mixed_exprs_fall_back_to_concat(small_chunks):
    # row_number disqualifies two-pass: the exec must still be correct
    # (single concatenated window for the giant partition)
    data = _data(300, 8)
    spec = window(partition_by=["p"], order_by=["v"])
    plan = WindowExec([(RowNumber().over(spec), "rn"),
                       (WindowAgg("sum", col("v")).over(spec), "s")],
                      _scan(data, batch_rows=64))
    plan.TWO_PASS_THRESHOLD_ROWS = 128
    rows = [r for b in plan.execute() for r in b.to_pylist()]
    assert len(rows) == len(data["p"])
    by_p = {}
    for r in sorted(rows, key=lambda r: (r[0], r[3])):
        by_p.setdefault(r[0], []).append(r[3])
    assert by_p["giant"] == list(range(1, 301))


def test_small_partitions_untouched(small_chunks):
    # nothing crosses the threshold: normal chunked path
    data = {"p": ["a", "b", "a", "b"], "v": [1, 2, 3, 4],
            "d": [0.0, 0.0, 0.0, 0.0]}
    spec = window(partition_by=["p"])
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                      _scan(data, batch_rows=2))
    rows = sorted(r[:2] + (r[3],) for b in plan.execute()
                  for r in b.to_pylist())
    assert rows == [("a", 1, 4), ("a", 3, 4), ("b", 2, 6), ("b", 4, 6)]
