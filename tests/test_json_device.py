"""Device get_json_object (ops/json_device.py) vs the host row tier."""
import json
import random

import pytest

from spark_rapids_tpu.columnar.column import StringColumn
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.expr.jsonexprs import GetJsonObject, parse_json_path
from spark_rapids_tpu.ops.json_device import json_extract


def _diff(docs, path):
    steps = parse_json_path(path)
    assert steps is not None
    expr = GetJsonObject(col("x"), path)
    host = [expr.host_eval_row(d) for d in docs]
    sc = StringColumn.from_pylist(docs)
    dev = json_extract(sc, steps).to_pylist(len(docs))
    assert dev == host, (path, [
        (d, h, v) for d, h, v in zip(docs, host, dev) if h != v])


DOCS = [
    '{"a": 1}',
    '{"a": {"b": "x"}}',
    '{"a": [1, 2, 3]}',
    '{"a": "hello"}',
    '{"a": null}',
    '{"b": 2}',
    None,
    'not json {',
    '{"a": 1.5, "b": [true, false]}',
    '{"a": {"b": {"c": [10, {"d": "deep"}]}}}',
    '{"a": "line\\nbreak \\"quoted\\" tab\\t"}',
    '{"a": "\\u00e9\\u4e2d\\ud83d\\ude00"}',
    '{"a": [ { "x" : 1 } , {"x": 2} ]}',
    '{"aa": 1, "a": 2}',
    '[]',
    '{"a": []}',
    '{"a": ""}',
    '{ "a" : 7 }',
    '{"a,b": 1, "a": "c,d"}',
    '{"a": true}',
    '[5, 6, 7]',
    '"bare"',
    '42',
]


@pytest.mark.parametrize("path", [
    "$.a", "$.a.b", "$.a[0]", "$.a[1]", "$.a[2]", "$.a.b.c[1].d",
    "$", "$['a']", "$[0]", "$[2]", "$.missing",
])
def test_device_matches_host(path):
    _diff(DOCS, path)


def test_fuzz_differential():
    rng = random.Random(7)

    def gen_value(depth):
        kinds = ["int", "float", "str", "bool", "null"]
        if depth < 3:
            kinds += ["obj", "arr", "obj", "arr"]
        k = rng.choice(kinds)
        if k == "int":
            return rng.randint(-1000, 1000)
        if k == "float":
            return round(rng.uniform(-10, 10), 3)
        if k == "str":
            return "".join(rng.choice("abc XY\"\\\n\té中")
                           for _ in range(rng.randint(0, 6)))
        if k == "bool":
            return rng.random() < 0.5
        if k == "null":
            return None
        if k == "obj":
            return {rng.choice(["a", "b", "cc", "d e"]): gen_value(depth + 1)
                    for _ in range(rng.randint(0, 3))}
        return [gen_value(depth + 1) for _ in range(rng.randint(0, 3))]

    docs = []
    for _ in range(120):
        v = gen_value(0)
        # pretty or compact, random whitespace style
        txt = json.dumps(v, indent=rng.choice([None, None, 1]))
        docs.append(txt)
    docs += [None, "", "{", "[1,]"][:2]  # null + empty only (see module doc)
    for path in ["$.a", "$.a.b", "$.b[0]", "$[1]", "$.cc", "$['d e'].a",
                 "$.a[0].b"]:
        _diff(docs, path)


def test_number_raw_text_divergence_documented():
    # device returns raw scalar text; host normalizes via json.dumps.
    # Both agree on canonical numbers (covered above); this documents the
    # divergence case stays device-side raw.
    sc = StringColumn.from_pylist(['{"a": 1.00}'])
    out = json_extract(sc, ["a"]).to_pylist(1)
    assert out == ["1.00"]


def test_planner_routes_literal_path_to_device():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import STRING, Schema, StructField
    sess = TpuSession()
    df = sess.from_pydict(
        {"j": ['{"a": 1}', '{"a": {"b": 2}}', None]},
        schema=Schema((StructField("j", STRING),)))
    q = df.select(F.get_json_object(F.col("j"), "$.a").alias("r"))
    assert "host" not in q.explain()
    assert [r[0] for r in q.collect()] == ["1", '{"b":2}', None]


def test_planner_keeps_wildcard_on_host():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import STRING, Schema, StructField
    sess = TpuSession()
    df = sess.from_pydict(
        {"j": ['{"a": [1, 2]}']},
        schema=Schema((StructField("j", STRING),)))
    q = df.select(F.get_json_object(F.col("j"), "$.a[*]").alias("r"))
    assert "host" in q.explain()
    assert [r[0] for r in q.collect()] == ["[1,2]"]
