"""Timezone DB + datetime rebase tests. Oracles are independent host
implementations: Python zoneinfo (IANA rules, fold=0) for zone shifts and
pure-python JDN formulas cross-checked against datetime for rebase
(reference analogs: TimeZoneSuite / RebaseDateTimeSuite; SURVEY §2.9/§2.11
TimeZoneDB.scala:61, datetimeRebaseUtils.scala)."""

import datetime as dt
from zoneinfo import ZoneInfo

import numpy as np
import pytest

from spark_rapids_tpu.ops.rebase import (rebase_gregorian_to_julian_days,
                                         rebase_julian_to_gregorian_days,
                                         rebase_julian_to_gregorian_micros)
from spark_rapids_tpu.ops.timezone import (local_to_utc, timezone_db,
                                           utc_to_local)

UTC = dt.timezone.utc
EPOCH = dt.datetime(1970, 1, 1, tzinfo=UTC)
MICROS = 1_000_000


def _utc_micros(y, mo, d, h=0, mi=0, s=0):
    return int((dt.datetime(y, mo, d, h, mi, s, tzinfo=UTC) - EPOCH)
               .total_seconds()) * MICROS


ZONES = ["America/Los_Angeles", "Europe/Berlin", "Asia/Kolkata",
         "Australia/Sydney", "+05:30", "UTC"]


@pytest.mark.parametrize("tz", ZONES)
def test_utc_to_local_matches_zoneinfo(tz):
    zone = ZoneInfo(tz) if "/" in tz or tz == "UTC" else None
    instants = []
    rng = np.random.default_rng(0)
    for y in (1950, 1969, 1987, 2001, 2015, 2023, 2035):
        for _ in range(8):
            instants.append(_utc_micros(y, int(rng.integers(1, 13)),
                                        int(rng.integers(1, 28)),
                                        int(rng.integers(0, 24)),
                                        int(rng.integers(0, 60))))
    # DST boundary minutes for the US zone (2am PST/PDT transitions 2023)
    instants += [_utc_micros(2023, 3, 12, 9, 59), _utc_micros(2023, 3, 12, 10, 1),
                 _utc_micros(2023, 11, 5, 8, 59), _utc_micros(2023, 11, 5, 9, 1)]
    arr = np.array(instants, np.int64)
    got = np.asarray(utc_to_local(arr, tz))
    for ts, g in zip(instants, got):
        when = EPOCH + dt.timedelta(microseconds=ts)
        if zone is not None:
            off = when.astimezone(zone).utcoffset()
        else:
            off = dt.timedelta(hours=5, minutes=30)
        assert g == ts + int(off.total_seconds()) * MICROS, (tz, when)


@pytest.mark.parametrize("tz", ["America/Los_Angeles", "Europe/Berlin",
                                "Asia/Kolkata"])
def test_local_to_utc_roundtrip_unambiguous(tz):
    zone = ZoneInfo(tz)
    rng = np.random.default_rng(1)
    walls = []
    for y in (1975, 1999, 2020, 2024):
        for _ in range(10):
            # mid-month noon: never in a DST gap/overlap
            walls.append(dt.datetime(y, int(rng.integers(1, 13)), 15, 12,
                                     int(rng.integers(0, 60))))
    arr = np.array([int((w - dt.datetime(1970, 1, 1)).total_seconds())
                    * MICROS for w in walls], np.int64)
    got = np.asarray(local_to_utc(arr, tz))
    for w, g in zip(walls, got):
        expect = int(w.replace(tzinfo=zone, fold=0)
                     .astimezone(UTC).timestamp()) * MICROS
        assert g == expect, (tz, w)


def test_dst_overlap_uses_earlier_offset():
    # 2023-11-05 01:30 in LA happens twice; fold=0 = PDT (UTC-7)
    wall = int((dt.datetime(2023, 11, 5, 1, 30)
                - dt.datetime(1970, 1, 1)).total_seconds()) * MICROS
    got = int(np.asarray(local_to_utc(np.array([wall], np.int64),
                                      "America/Los_Angeles"))[0])
    expect = int(dt.datetime(2023, 11, 5, 1, 30,
                             tzinfo=ZoneInfo("America/Los_Angeles"),
                             fold=0).astimezone(UTC).timestamp()) * MICROS
    assert got == expect


def test_unknown_timezone_rejected():
    with pytest.raises((ValueError, OSError)):
        timezone_db().tables("Not/AZone")


def test_fixed_offset_zones():
    arr = np.array([0, 10**15], np.int64)
    assert list(np.asarray(utc_to_local(arr, "+05:30"))) == \
        [int(5.5 * 3600) * MICROS, 10**15 + int(5.5 * 3600) * MICROS]
    assert list(np.asarray(utc_to_local(arr, "UTC"))) == [0, 10**15]


# ---------------------------------------------------------------------------
# rebase
# ---------------------------------------------------------------------------

def _days(y, m, d):
    return (dt.date(y, m, d) - dt.date(1970, 1, 1)).days


def test_rebase_identity_after_cutover():
    days = np.array([_days(1582, 10, 15), _days(1600, 1, 1), 0,
                     _days(2024, 6, 1)], np.int64)
    out = np.asarray(rebase_julian_to_gregorian_days(days))
    assert (out == days).all()


def test_rebase_known_shifts():
    """Rebase preserves the WALL DATE (Y-M-D), not the instant: hybrid
    day for Julian 1582-10-04 (cutover-1) maps to proleptic Gregorian
    '1582-10-04', 10 days earlier as a day number (Spark
    RebaseDateTimeSuite semantics)."""
    cut = _days(1582, 10, 15)
    out = int(np.asarray(rebase_julian_to_gregorian_days(
        np.array([cut - 1], np.int64)))[0])
    assert out == _days(1582, 10, 4)  # same wall date, -10 day number

    # 1000-01-01 Julian = 1000-01-06 proleptic Gregorian (shift +5... check
    # via formulas): use the module's own host formulas as the oracle and
    # verify the DEVICE table path agrees day-by-day around breakpoints
    from spark_rapids_tpu.ops.rebase import _hybrid_to_proleptic
    probe = []
    for y in (100, 500, 900, 1100, 1500, 1582):
        probe.extend(range(_days(2000, 1, 1) - (2000 - y) * 365 - 20,
                           _days(2000, 1, 1) - (2000 - y) * 365 + 20))
    arr = np.array(sorted(probe), np.int64)
    got = np.asarray(rebase_julian_to_gregorian_days(arr))
    expect = np.array([_hybrid_to_proleptic(int(d)) for d in arr], np.int64)
    assert (got == expect).all()


def test_rebase_roundtrip():
    rng = np.random.default_rng(2)
    days = rng.integers(-500000, 20000, 500).astype(np.int64)
    fwd = np.asarray(rebase_julian_to_gregorian_days(days))
    back = np.asarray(rebase_gregorian_to_julian_days(fwd))
    assert (back == days).all()


def test_rebase_micros_preserves_time_of_day():
    base_day = _days(1500, 6, 1) - 9  # hybrid-era day
    micros = np.array([base_day * 86_400_000_000 + 12 * 3_600_000_000 + 123,
                       base_day * 86_400_000_000], np.int64)
    out = np.asarray(rebase_julian_to_gregorian_micros(micros))
    assert out[0] - out[1] == 12 * 3_600_000_000 + 123
    assert out[1] % 86_400_000_000 == 0


# ---------------------------------------------------------------------------
# expression + planner integration
# ---------------------------------------------------------------------------

def test_from_utc_timestamp_through_session():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import TIMESTAMP, Schema, StructField
    sess = TpuSession()
    vals = [_utc_micros(2023, 3, 12, 9, 59), _utc_micros(2023, 7, 1, 0, 0),
            None]
    sch = Schema((StructField("ts", TIMESTAMP),))
    df = sess.from_pydict({"ts": vals}, sch)
    rows = df.select(F.from_utc_timestamp(col("ts"), "America/Los_Angeles")
                     .alias("lts")).collect()
    zone = ZoneInfo("America/Los_Angeles")
    for v, (got,) in zip(vals, rows):
        if v is None:
            assert got is None
            continue
        when = EPOCH + dt.timedelta(microseconds=v)
        off = when.astimezone(zone).utcoffset()
        assert got == v + int(off.total_seconds()) * MICROS


def test_unknown_zone_tags_off_device():
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.datetimeexprs import FromUTCTimestamp
    from spark_rapids_tpu.plan.overrides import PlanNotSupported
    from spark_rapids_tpu.types import TIMESTAMP, Schema, StructField
    sess = TpuSession({"spark.rapids.sql.cpuFallback.enabled": "false"})
    sch = Schema((StructField("ts", TIMESTAMP),))
    df = sess.from_pydict({"ts": [0]}, sch)
    with pytest.raises(PlanNotSupported, match="timezone"):
        df.select(FromUTCTimestamp(col("ts"), "Mars/Olympus").alias("x")
                  )._exec()


def test_parquet_legacy_rebase_mode(tmp_path):
    """LEGACY datetimeRebaseModeInRead rebases DATE columns on scan
    (reference GpuParquetScan rebase handling)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.ops.rebase import _hybrid_to_proleptic

    hybrid_days = [-150000, -141428, -141427, 0, 19000]
    table = pa.table({"d": pa.array(hybrid_days, pa.int32()).cast(
        pa.date32())})
    path = str(tmp_path / "legacy.parquet")
    pq.write_table(table, path)

    legacy = TpuSession({
        "spark.rapids.sql.format.parquet.datetimeRebaseModeInRead":
            "LEGACY"})
    rows = [r[0] for r in legacy.read_parquet(path).collect()]
    assert rows == [_hybrid_to_proleptic(d) for d in hybrid_days]

    corrected = TpuSession()
    rows2 = [r[0] for r in corrected.read_parquet(path).collect()]
    assert rows2 == hybrid_days
