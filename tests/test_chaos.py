"""Chaos-hardened execution (ISSUE 4): the seeded fault-injection
surface, the three recovery lanes (IO retry -> OOM retry -> task
re-execution), integrity-checked spill/shuffle, the watchdogs on the
PR 3 async seams, and the end-to-end chaos soak.

Deterministic on single-core CPU: every injection is driven by a seeded
plan (prob=1 + max=N for the "inject once, assert recovery" tests),
never by wall-clock or RNG state. The 100-query soak is `slow`-marked;
tier-1 runs a 3-seed mini soak of the same shape."""

import glob
import os
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.pipeline import pipelined
from spark_rapids_tpu.exec.task_retry import (task_attempt,
                                              with_task_retry)
from spark_rapids_tpu.io.multifile import threaded_chunks
from spark_rapids_tpu.io.retrying import io_retry_recoveries, with_io_retry
from spark_rapids_tpu.memory import retry as mretry
from spark_rapids_tpu.memory.budget import (memory_budget,
                                            reset_memory_budget)
from spark_rapids_tpu.memory.catalog import (StorageTier, buffer_catalog,
                                             reset_buffer_catalog)
from spark_rapids_tpu.obs import events
from spark_rapids_tpu.types import LONG, Schema, StructField

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

#: a real XLA runtime error is not importable on every backend build —
#: the taxonomy matches by type NAME, which is exactly what we fake
XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})

#: fast-backoff settings every chaos test runs under (the defaults
#: sleep 50-100ms per retry — pointless in a deterministic suite)
FAST = {
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
    "spark.rapids.tpu.retry.backoffMs": "1",
}


def _threads():
    return {t for t in threading.enumerate()
            if t.name.startswith(("pipeline-", "spill-writer"))}


@pytest.fixture(autouse=True)
def _chaos_isolation():
    """Every test starts with injection off, restores the active conf,
    and leaks zero NEW pipeline-*/spill-writer threads."""
    pre = _threads()
    prev_conf = C.active_conf()
    faults.install(None)
    yield
    faults.install(None)
    C.set_active_conf(prev_conf)
    assert _threads() <= pre, "leaked robustness threads"


@pytest.fixture
def spy(monkeypatch):
    """Capture every events.emit() call (all modules import the events
    MODULE and resolve .emit at call time, so one patch sees them all
    — including emits from pool/writer threads)."""
    rows = []
    real = events.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy_emit)
    return rows


def _kinds(rows, kind):
    return [r for r in rows if r["kind"] == kind]


@pytest.fixture
def fast_conf():
    conf = C.RapidsConf(dict(FAST))
    C.set_active_conf(conf)
    return conf


@pytest.fixture
def spill_env(tmp_path):
    """Forced-spill catalog sandbox (same shape as test_pipeline's)."""

    def setup(async_write, host_limit="4g", budget=512 * 1024, **extra):
        settings = dict(FAST)
        settings.update({
            "spark.rapids.tpu.spill.asyncWrite": async_write,
            "spark.rapids.memory.host.spillStorageSize": host_limit,
            "spark.rapids.memory.spillDirectory": str(tmp_path),
        })
        settings.update(extra)
        C.set_active_conf(C.RapidsConf(settings))
        reset_memory_budget(budget)
        return reset_buffer_catalog()

    yield setup
    reset_buffer_catalog()
    reset_memory_budget()


def _batch(n, seed=0):
    return ColumnarBatch.from_pydict(
        {"a": list(range(seed, seed + n))}, Schema.of(a=LONG))


def _spillable(n=256, seed=0):
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    return SpillableBatch.from_batch(_batch(n, seed))


# ---------------------------------------------------------------------------
# the injection plan: grammar, determinism, off-by-default
# ---------------------------------------------------------------------------

def test_parse_grammar_and_defaults():
    plan = faults.parse_faults(
        "spill.d2h_copy:prob=0.25,seed=7,kind=device,max=3;"
        "shuffle.decode:kind=corrupt")
    assert set(plan.specs) == {"spill.d2h_copy", "shuffle.decode"}
    s = plan.specs["spill.d2h_copy"]
    assert (s.prob, s.seed, s.kind, s.max_injections) == (0.25, 7,
                                                          "device", 3)
    d = plan.specs["shuffle.decode"]
    assert (d.prob, d.seed, d.kind, d.max_injections) == (1.0, 0,
                                                          "corrupt", None)
    assert faults.parse_faults("") is None
    assert faults.parse_faults("   ") is None


def test_parse_rejects_typos_loudly():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.parse_faults("spill.d2h_cpoy:prob=1")
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_faults("spill.d2h_copy:kind=oom")
    with pytest.raises(ValueError, match="unknown fault option"):
        faults.parse_faults("spill.d2h_copy:probb=1")


def test_decisions_replay_exactly_under_one_seed():
    spec = "device.dispatch:prob=0.3,seed=11,kind=device"
    plan_a, plan_b = faults.parse_faults(spec), faults.parse_faults(spec)
    a = [plan_a.decide("device.dispatch") is not None for _ in range(200)]
    b = [plan_b.decide("device.dispatch") is not None for _ in range(200)]
    assert a == b
    assert 20 < sum(a) < 120  # prob=0.3 actually bites, is not prob=1
    plan_c = faults.parse_faults(
        "device.dispatch:prob=0.3,seed=12,kind=device")
    other = [plan_c.decide("device.dispatch") is not None
             for _ in range(200)]
    assert a != other  # the seed is load-bearing


def test_max_caps_total_injections():
    plan = faults.parse_faults("device.dispatch:prob=1,seed=0,"
                               "kind=device,max=2")
    fired = sum(plan.decide("device.dispatch") is not None
                for _ in range(50))
    assert fired == 2
    assert plan.stats() == {"device.dispatch": 2}


def test_corrupt_flips_exactly_one_byte_and_skips_data_free_sites():
    faults.install("shuffle.decode:prob=1,seed=5,kind=corrupt")
    data = bytes(range(200))
    out = faults.apply("shuffle.decode", data)
    assert len(out) == len(data)
    assert sum(x != y for x, y in zip(out, data)) == 1
    # a data-free site treats an armed corrupt kind as a no-op
    faults.check("shuffle.decode")
    assert faults.apply("shuffle.decode", b"") == b""


def test_off_by_default_and_conf_gating():
    assert faults.active_plan() is None
    data = b"untouched"
    assert faults.apply("spill.disk_write", data) is data  # pointer check
    faults.check("device.dispatch")  # no-op, no raise
    assert faults.stats() == {}
    # a conf that does not mention the key leaves the plan alone ...
    faults.install("device.dispatch:prob=1,seed=0,kind=device,max=1")
    faults.configure(C.RapidsConf({}))
    assert faults.active_plan() is not None
    # ... an explicit empty value clears it
    faults.configure(C.RapidsConf({"spark.rapids.tpu.test.faults": ""}))
    assert faults.active_plan() is None


def test_configure_keeps_armed_plan_across_reexecutions():
    """A task RE-EXECUTION reconfigures faults on its way back through
    _exec: the same spec string must keep the SAME plan (call counters,
    max budgets), or every retry would replay exactly the faults that
    killed the previous attempt and recovery could never converge."""
    spec = "device.dispatch:prob=1,seed=0,kind=device,max=1"
    conf = C.RapidsConf({"spark.rapids.tpu.test.faults": spec})
    plan = faults.configure(conf)
    assert plan.decide("device.dispatch") is not None  # budget spent
    again = faults.configure(conf)
    assert again is plan  # SAME plan object, budget still spent
    assert again.decide("device.dispatch") is None
    # a DIFFERENT spec re-arms from scratch
    fresh = faults.configure(C.RapidsConf(
        {"spark.rapids.tpu.test.faults":
         "device.dispatch:prob=1,seed=1,kind=device,max=1"}))
    assert fresh is not plan
    assert fresh.decide("device.dispatch") is not None


def test_uniform_spec_arms_every_registered_point():
    plan = faults.parse_faults(faults.uniform_spec(0.05, seed=9))
    assert set(plan.specs) == set(faults.FAULT_POINTS)
    assert all(s.prob == 0.05 and s.seed == 9 for s in plan.specs.values())


def test_classify_taxonomy():
    assert faults.classify(mretry.TpuRetryOOM("x")) == "oom"
    assert faults.classify(
        XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "oom"
    assert faults.classify(XlaRuntimeError("INTERNAL: device reset")) \
        == "task"
    assert faults.classify(faults.InjectedDeviceError("p")) == "task"
    assert faults.classify(faults.InjectedIOError("p")) == "task"
    assert faults.classify(faults.IntegrityError("crc")) == "task"
    assert faults.classify(ValueError("bug")) == "fatal"
    assert faults.classify(FileNotFoundError("gone")) == "fatal"


# ---------------------------------------------------------------------------
# recovery lane 1: bounded IO retry
# ---------------------------------------------------------------------------

def test_io_retry_recovers_and_emits(fast_conf, spy):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) <= 2:
            raise OSError("transient mount hiccup")
        return 42

    before = io_retry_recoveries()
    assert with_io_retry(flaky, "unit", conf=fast_conf) == 42
    assert len(calls) == 3
    assert io_retry_recoveries() == before + 1
    evs = _kinds(spy, "io_retry")
    assert [e["attempt"] for e in evs] == [1, 2]
    assert all(e["max_attempts"] == 4 and e["backoff_ns"] > 0
               for e in evs)  # io.retries default 3 -> 4 attempts


def test_io_retry_non_transient_fails_immediately(fast_conf):
    calls = []

    def gone():
        calls.append(1)
        raise FileNotFoundError("no such file")

    with pytest.raises(FileNotFoundError):
        with_io_retry(gone, "unit", conf=fast_conf)
    assert len(calls) == 1


def test_io_retry_exhausts_and_surfaces_original(fast_conf):
    conf = C.RapidsConf(dict(FAST, **{"spark.rapids.tpu.io.retries": "2"}))
    calls = []

    def always():
        calls.append(1)
        raise OSError("persistently flaky")

    with pytest.raises(OSError, match="persistently flaky"):
        with_io_retry(always, "unit", conf=conf)
    assert len(calls) == 3  # 1 + 2 retries
    zero = C.RapidsConf({"spark.rapids.tpu.io.retries": "0"})
    calls.clear()
    with pytest.raises(OSError):
        with_io_retry(always, "unit", conf=zero)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# recovery lane 2: OOM retry backoff (satellite) + XLA classification
# ---------------------------------------------------------------------------

class _Item:
    def close(self):
        pass


def test_oom_retry_sleeps_with_backoff_and_tagged_events(
        fast_conf, spy, monkeypatch):
    """CHANGES PR 3 round-5: the retry loop used to spin through all 10
    attempts in microseconds. Now each TpuRetryOOM attempt sleeps a
    capped exponential backoff and the event carries the surface."""
    sleeps = []
    monkeypatch.setattr(mretry.time, "sleep", sleeps.append)
    mretry.register_task(7)
    try:
        mretry.force_retry_oom(2)
        calls = []

        def fn(item):
            mretry.oom_guard()
            calls.append(1)
            return 99

        assert mretry.with_retry_no_split(_Item(), fn) == 99
        assert len(calls) == 1  # two injected OOMs, then success
        evs = _kinds(spy, "oom_retry")
        assert [e["attempt"] for e in evs] == [1, 2]
        assert all(e["oom"] == "retry" and e["max_attempts"] >= 2
                   and e["backoff_ns"] > 0 for e in evs)
        assert len(sleeps) == 2 and all(s > 0 for s in sleeps)
        # backoffMs=0 restores immediate re-spin
        C.set_active_conf(C.RapidsConf(
            {"spark.rapids.tpu.retry.backoffMs": "0"}))
        sleeps.clear()
        mretry.force_retry_oom(1)
        assert mretry.with_retry_no_split(_Item(), fn) == 99
        assert sleeps == []
    finally:
        mretry.unregister_task()


def test_xla_resource_exhausted_rides_the_oom_lane(fast_conf, spy):
    """An XlaRuntimeError whose status is RESOURCE_EXHAUSTED is an OOM
    in runtime-error clothing: with_retry recovers it by spill+retry at
    the guarded section instead of failing the task; any other XLA
    error re-raises for the task layer."""
    mretry.register_task(3)
    try:
        calls = []

        def fn(item):
            calls.append(1)
            if len(calls) == 1:
                raise XlaRuntimeError(
                    "RESOURCE_EXHAUSTED: out of memory allocating 1g")
            return 5

        assert mretry.with_retry_no_split(_Item(), fn) == 5
        assert len(calls) == 2
        assert [e["attempt"] for e in _kinds(spy, "oom_retry")] == [1]

        def hard(item):
            raise XlaRuntimeError("INTERNAL: device reset")

        with pytest.raises(XlaRuntimeError, match="INTERNAL"):
            mretry.with_retry_no_split(_Item(), hard)
    finally:
        mretry.unregister_task()


# ---------------------------------------------------------------------------
# recovery lane 3: task re-execution
# ---------------------------------------------------------------------------

def test_task_retry_recovers_transient_and_numbers_attempts(
        fast_conf, spy):
    seen = []

    def run(attempt):
        seen.append((attempt, task_attempt()))
        if attempt < 3:
            raise faults.TpuTaskRetryError("injected transient")
        return "done"

    assert with_task_retry(run, conf=fast_conf, label="unit") == "done"
    assert seen == [(1, 1), (2, 2), (3, 3)]
    assert task_attempt() == 1  # thread-local restored
    evs = _kinds(spy, "task_retry")
    assert [e["attempt"] for e in evs] == [1, 2]
    assert all(e["label"] == "unit" and e["max_attempts"] == 3
               and e["backoff_ns"] > 0 for e in evs)


def test_task_retry_fatal_and_exhaustion(fast_conf):
    calls = []

    def fatal(attempt):
        calls.append(1)
        raise ValueError("a real bug, not a fault")

    with pytest.raises(ValueError):
        with_task_retry(fatal, conf=fast_conf)
    assert len(calls) == 1  # fatal = no re-execution

    conf = C.RapidsConf(dict(FAST,
                             **{"spark.rapids.tpu.task.maxAttempts": "2"}))
    calls.clear()

    def always(attempt):
        calls.append(1)
        raise faults.InjectedDeviceError("device.dispatch")

    with pytest.raises(faults.InjectedDeviceError):
        with_task_retry(always, conf=conf)
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# fault points: spill
# ---------------------------------------------------------------------------

def test_spill_injection_placement_replays_across_runs(spill_env, spy):
    """ISSUE 7 satellite: the spill sites now pass the catalog entry's
    deterministic registration ordinal as the fault work-item key, so
    injection PLACEMENT — which entry's write draws the fault, not just
    how many — replays under any processing order. Two runs spill the
    same 12 entries in OPPOSITE priority order (the single-core proxy
    for a thread-scheduling permutation), and a third hands the writes
    to the async writer THREAD: all three fire on the same entries."""
    import jax.numpy as jnp

    def run(async_write, ascending):
        cat = spill_env(async_write, host_limit="1")
        spy.clear()
        handles = []
        for i in range(12):
            prio = i if ascending else -i
            handles.append(cat.add(jnp.arange(64, dtype=jnp.int64),
                                   priority=prio))
        faults.install("spill.disk_write:prob=0.5,seed=0,kind=io")
        cat.synchronous_spill(None)  # device -> host -> (1B limit) disk
        cat.drain_writeback()
        faults.install(None)
        placed = {(r["point"], r["key"]) for r in spy
                  if r["kind"] == "fault_inject"}
        for h in handles:
            cat.remove(h)
        return placed

    a = run("false", ascending=True)
    b = run("false", ascending=False)  # reversed spill order
    c = run("true", ascending=True)    # writes on the writer thread
    assert a == b == c, "injection placement moved with scheduling"
    # teeth: a proper subset fired, and every draw carried an entry key
    assert 0 < len(a) < 12
    assert all(k and k.startswith("spill:") for _p, k in a)


def test_point_spill_d2h_sync_restores_entry_and_budget(spill_env, spy):
    cat = spill_env(False)
    sb = _spillable()
    used = memory_budget().used
    faults.install("spill.d2h_copy:prob=1,seed=1,kind=device,max=1")
    with pytest.raises(faults.TpuTaskRetryError, match="spill copy"):
        cat.synchronous_spill(None)
    assert cat.tier_of(sb._handle) == StorageTier.DEVICE
    assert memory_budget().used == used  # nothing physically moved
    assert cat.spilled_device_bytes == 0  # the hop never happened
    assert _kinds(spy, "fault_inject") and _kinds(spy, "spill_error")
    cat.synchronous_spill(None)  # max=1 consumed: the retry lands
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    assert sb.get_batch().to_pydict()["a"][:3] == [0, 1, 2]
    sb.release()
    sb.close()


def test_point_spill_d2h_async_recovers_silently(spill_env, spy):
    cat = spill_env(True)
    sb = _spillable()
    used = memory_budget().used
    faults.install("spill.d2h_copy:prob=1,seed=1,kind=device,max=1")
    cat.synchronous_spill(None)
    cat.drain_writeback()
    # the writer put the entry back on DEVICE intact: no task died, the
    # budget was never released, the hop was un-counted
    assert cat.tier_of(sb._handle) == StorageTier.DEVICE
    assert memory_budget().used == used
    assert cat.spilled_device_bytes == 0
    errs = _kinds(spy, "spill_error")
    assert errs and errs[0]["sync"] is False
    cat.synchronous_spill(None)  # and the next spill goes through
    cat.drain_writeback()
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    assert sb.get_batch().to_pydict()["a"][:3] == [0, 1, 2]
    sb.release()
    sb.close()


def test_point_spill_disk_write_io_stays_on_host(spill_env, spy,
                                                 tmp_path):
    cat = spill_env(False, host_limit="1k")
    sb = _spillable()
    faults.install("spill.disk_write:prob=1,seed=1,kind=io,max=1")
    cat.synchronous_spill(None)  # device -> host -> (1k limit) -> disk
    # the disk write died: the host copy is intact, the entry stays on
    # HOST (over its soft limit), no partial file survives
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    assert not list(tmp_path.glob("spill-*.npz"))
    errs = _kinds(spy, "spill_error")
    assert errs and errs[0]["stage"] == "disk_write"
    assert sb.get_batch().to_pydict()["a"][:3] == [0, 1, 2]
    sb.release()
    sb.close()


def test_host_limit_pass_continues_past_failed_disk_write(spill_env):
    """A sync disk-write failure leaves the entry on HOST and must NOT
    count as freed host bytes: the enforcement pass goes on to the next
    candidate instead of stopping early with the limit still blown."""
    # two ~2KiB entries over a 3KiB limit: spilling EITHER satisfies it,
    # so a miscounted failure would end the pass with zero on disk
    cat = spill_env(False, host_limit="3k")
    sb1, sb2 = _spillable(seed=0), _spillable(seed=1000)
    faults.install("spill.disk_write:prob=1,seed=1,kind=io,max=1")
    cat.synchronous_spill(None)
    assert cat.tier_of(sb1._handle) == StorageTier.HOST  # write died
    assert cat.tier_of(sb2._handle) == StorageTier.DISK  # pass went on
    assert sb1.get_batch().to_pydict()["a"][:2] == [0, 1]
    assert sb2.get_batch().to_pydict()["a"][:2] == [1000, 1001]
    sb1.release(), sb2.release()
    sb1.close(), sb2.close()


def test_point_spill_disk_write_corrupt_quarantined(spill_env, spy,
                                                    tmp_path):
    from spark_rapids_tpu.memory.catalog import SpillFileCorruption
    cat = spill_env(False, host_limit="1k")
    sb = _spillable()
    faults.install("spill.disk_write:prob=1,seed=1,kind=corrupt,max=1")
    cat.synchronous_spill(None)
    assert cat.tier_of(sb._handle) == StorageTier.DISK
    faults.install(None)
    with pytest.raises(SpillFileCorruption, match="checksum mismatch"):
        sb.get_batch()
    # the evidence is quarantined, never fed downstream; the failure is
    # task-transient (recovery = recompute from the sources)
    assert classify_is_task(SpillFileCorruption("x"))
    assert list(tmp_path.glob("spill-*.npz.quarantined"))
    assert not list(tmp_path.glob("spill-*.npz"))
    evs = _kinds(spy, "integrity_fail")
    assert evs and evs[0]["what"] == "spill_file"
    sb.close()  # remove() cleans the quarantined file too
    assert not list(tmp_path.glob("spill-*"))


def classify_is_task(exc):
    return faults.classify(exc) == "task"


def test_point_spill_disk_read_is_task_transient(spill_env, spy):
    cat = spill_env(False, host_limit="1k")
    sb = _spillable()
    cat.synchronous_spill(None)
    assert cat.tier_of(sb._handle) == StorageTier.DISK
    faults.install("spill.disk_read:prob=1,seed=1,kind=io,max=1")
    with pytest.raises(faults.TpuTaskRetryError, match="unreadable"):
        sb.get_batch()
    errs = _kinds(spy, "spill_error")
    assert errs and errs[-1]["stage"] == "disk_read"
    # max=1 consumed: the re-read (what a task retry would do) succeeds
    assert sb.get_batch().to_pydict()["a"][:3] == [0, 1, 2]
    sb.release()
    sb.close()


# ---------------------------------------------------------------------------
# fault points: shuffle (+ commit protocol)
# ---------------------------------------------------------------------------

SCH = Schema((StructField("k", LONG), StructField("v", LONG)))


def _shuffle_fixture(n_rows=64):
    from spark_rapids_tpu.shuffle.manager import (HostShuffleWriter,
                                                  partition_batch_host,
                                                  shuffle_manager)
    b = ColumnarBatch.from_pydict(
        {"k": [i % 2 for i in range(n_rows)],
         "v": list(range(n_rows))}, SCH)
    mgr = shuffle_manager()
    handle = mgr.register(2, SCH)
    parts = partition_batch_host(b, np.array([i % 2 for i in range(n_rows)]),
                                 2)
    HostShuffleWriter(handle, 0, mgr).write([[p] for p in parts])
    rows = b.to_pylist()
    return mgr, handle, rows


def test_point_shuffle_fetch_retries_transparently(fast_conf, spy):
    from spark_rapids_tpu.shuffle.manager import HostShuffleReader
    mgr, handle, rows = _shuffle_fixture()
    try:
        faults.install("shuffle.fetch:prob=1,seed=1,kind=io,max=1")
        r = HostShuffleReader(handle, mgr, conf=fast_conf)
        got = [row for p in range(2) for b in r.read_partition(p)
               for row in b.to_pylist()]
        assert sorted(got) == sorted(rows)  # recovered, nothing lost
        evs = _kinds(spy, "io_retry")
        assert evs and evs[0]["what"] == "shuffle.fetch"
    finally:
        mgr.unregister(handle)


def test_point_shuffle_decode_corrupt_quarantined(fast_conf, spy):
    from spark_rapids_tpu.shuffle.manager import HostShuffleReader
    mgr, handle, rows = _shuffle_fixture()
    try:
        faults.install("shuffle.decode:prob=1,seed=1,kind=corrupt,max=1")
        r = HostShuffleReader(handle, mgr, conf=fast_conf)
        with pytest.raises(faults.IntegrityError, match="corrupt shuffle"):
            for p in range(2):
                list(r.read_partition(p))
        evs = _kinds(spy, "integrity_fail")
        assert evs and evs[0]["what"] == "shuffle_block"
        # max=1 consumed: the recompute's re-read decodes clean
        r2 = HostShuffleReader(handle, mgr, conf=fast_conf)
        got = [row for p in range(2) for b in r2.read_partition(p)
               for row in b.to_pylist()]
        assert sorted(got) == sorted(rows)
    finally:
        mgr.unregister(handle)


def test_shuffle_commit_protocol_attempt_isolation(fast_conf, spy,
                                                   monkeypatch):
    """A task attempt that dies mid-commit leaves no visible shard and
    no droppings; the retry attempt writes under its own tag and
    commits atomically — the reader sees exactly one copy."""
    from spark_rapids_tpu.shuffle.manager import (HostShuffleReader,
                                                  HostShuffleWriter,
                                                  partition_batch_host,
                                                  shuffle_manager)
    b = ColumnarBatch.from_pydict({"k": [0, 1], "v": [10, 11]}, SCH)
    mgr = shuffle_manager()
    handle = mgr.register(2, SCH)
    parts = partition_batch_host(b, np.array([0, 1]), 2)
    data_path = mgr.map_data_path(handle.shuffle_id, 0)
    shuffle_dir = os.path.dirname(data_path)
    real_replace = os.replace
    state = {"fail_attempt_1": True}

    def flaky_replace(src, dst, *a, **kw):
        if state["fail_attempt_1"] and ".attempt-1.tmp" in str(src):
            state["fail_attempt_1"] = False
            raise faults.InjectedIOError("shuffle.commit")
        return real_replace(src, dst, *a, **kw)

    monkeypatch.setattr(os, "replace", flaky_replace)
    try:
        def run(attempt):
            assert task_attempt() == attempt  # the writer tags with this
            HostShuffleWriter(handle, 0, mgr).write([[p] for p in parts])
            return attempt

        assert with_task_retry(run, conf=fast_conf) == 2
        # attempt 1 died at its data rename: both temp files were
        # cleaned, nothing committed, nothing registered twice
        droppings = glob.glob(os.path.join(shuffle_dir, "*.tmp"))
        assert droppings == []
        assert handle.map_outputs == [data_path]
        assert os.path.exists(data_path)
        assert os.path.exists(data_path + ".index")
        r = HostShuffleReader(handle, mgr, conf=fast_conf)
        got = [row for p in range(2) for bb in r.read_partition(p)
               for row in bb.to_pylist()]
        assert sorted(got) == [(0, 10), (1, 11)]  # exactly one copy
    finally:
        mgr.unregister(handle)


def test_shuffle_failed_write_leaves_nothing_visible(monkeypatch):
    from spark_rapids_tpu.shuffle.manager import (HostShuffleWriter,
                                                  partition_batch_host,
                                                  shuffle_manager)
    b = ColumnarBatch.from_pydict({"k": [0, 1], "v": [1, 2]}, SCH)
    mgr = shuffle_manager()
    handle = mgr.register(2, SCH)
    parts = partition_batch_host(b, np.array([0, 1]), 2)
    data_path = mgr.map_data_path(handle.shuffle_id, 0)
    shuffle_dir = os.path.dirname(data_path)
    real_replace = os.replace

    def dying_replace(src, dst, *a, **kw):
        if ".attempt-" in str(src):
            raise faults.InjectedIOError("shuffle.commit")
        return real_replace(src, dst, *a, **kw)

    monkeypatch.setattr(os, "replace", dying_replace)
    try:
        with pytest.raises(faults.InjectedIOError):
            HostShuffleWriter(handle, 0, mgr).write([[p] for p in parts])
        assert not os.path.exists(data_path)
        assert not os.path.exists(data_path + ".index")
        assert glob.glob(os.path.join(shuffle_dir, "*.tmp")) == []
        assert handle.map_outputs == []
    finally:
        mgr.unregister(handle)


# ---------------------------------------------------------------------------
# fault points: io.multifile_read, device.dispatch, pipeline.produce
# ---------------------------------------------------------------------------

def test_point_multifile_read_retries_on_the_pool(fast_conf, spy):
    faults.install("io.multifile_read:prob=1,seed=2,kind=io,max=1")
    tasks = [lambda i=i: i * 10 for i in range(6)]
    before = io_retry_recoveries()
    assert list(threaded_chunks(tasks, num_threads=3)) == [
        0, 10, 20, 30, 40, 50]  # in order, nothing lost
    assert io_retry_recoveries() == before + 1
    evs = _kinds(spy, "io_retry")
    assert evs and evs[0]["what"] == "multifile_read"


def test_point_device_dispatch_recovers_via_task_retry(fast_conf, spy):
    mretry.register_task(5)
    try:
        faults.install("device.dispatch:prob=1,seed=1,kind=device,max=1")

        def run(attempt):
            def fn(item):
                mretry.oom_guard()  # the guarded section
                return attempt
            return mretry.with_retry_no_split(_Item(), fn)

        # the injected device error is NOT an OOM: with_retry re-raises
        # it and the task layer re-executes from the sources
        assert with_task_retry(run, conf=fast_conf) == 2
        assert len(_kinds(spy, "task_retry")) == 1
        assert _kinds(spy, "fault_inject")[0]["point"] == "device.dispatch"
    finally:
        mretry.unregister_task()


def test_producer_threads_inherit_task_attempt(fast_conf):
    """Pipeline producer threads adopt the consumer's task-attempt
    thread-local (like conf/query-id/speculation context): an exchange
    WRITE driven from a producer must tag its shuffle temp files with
    the real attempt, or attempt 2 would reuse attempt 1's temp names
    and a detached (pipeline_stuck) attempt-1 producer could tear
    them."""
    seen = []

    def run(attempt):
        def src():
            seen.append((attempt, task_attempt()))  # producer thread
            yield 1

        stage = pipelined(src(), depth=1)
        try:
            list(stage)
        finally:
            stage.close()
        if attempt == 1:
            raise faults.TpuTaskRetryError("force a second attempt")
        return attempt

    assert with_task_retry(run, conf=fast_conf) == 2
    assert seen == [(1, 1), (2, 2)]
    # outside any retry scope, a fresh producer sees the default
    seen.clear()
    stage = pipelined(iter([1]), depth=1)
    try:
        list(stage)
    finally:
        stage.close()
    assert task_attempt() == 1


def test_point_pipeline_produce_recovers_via_task_retry(fast_conf, spy):
    faults.install("pipeline.produce:prob=1,seed=3,kind=io,max=1")

    def run(attempt):
        stage = pipelined(iter(range(20)), depth=2)
        try:
            return list(stage)
        finally:
            stage.close()

    assert with_task_retry(run, conf=fast_conf) == list(range(20))
    assert len(_kinds(spy, "task_retry")) == 1


# ---------------------------------------------------------------------------
# watchdogs
# ---------------------------------------------------------------------------

def test_pipeline_close_watchdog_emits_stuck(spy):
    """A producer wedged beyond cancellation's reach (blocking C call)
    must not hang query teardown: close() gives up after the conf
    timeout, emits pipeline_stuck, and detaches the daemon thread."""
    C.set_active_conf(C.RapidsConf(
        {"spark.rapids.tpu.pipeline.closeTimeoutMs": "150"}))
    release = threading.Event()

    def wedged():
        release.wait(5.0)  # blocking call close() cannot interrupt
        yield 1

    stage = pipelined(wedged(), depth=2)
    t0 = time.monotonic()
    stage.close()  # must return despite the wedged producer
    assert time.monotonic() - t0 < 3.0
    assert stage.stuck is True
    evs = _kinds(spy, "pipeline_stuck")
    assert evs and evs[0]["timeout_ms"] == 150
    # let the wedge resolve so the daemon thread exits before the
    # hygiene fixture looks
    release.set()
    stage._thread.join(5.0)
    assert not stage._thread.is_alive()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_spill_writer_death_detected_and_queue_drained(spill_env, spy,
                                                       monkeypatch):
    """A writer thread killed by something harsher than the per-job
    except must not wedge spilling: the stranded queue is drained
    synchronously, spill_writer_dead is emitted, and the next spill
    spawns a fresh writer."""
    cat = spill_env(True)
    sb1, sb2 = _spillable(seed=0), _spillable(seed=1000)
    real_run = cat._run_writeback
    state = {"poison": True}

    def poisoned(kind, entry, path):
        real_run(kind, entry, path)  # the job's bytes land first
        if state["poison"]:
            state["poison"] = False
            raise SystemExit("injected writer death")  # BaseException:
            # escapes the writer loop's per-job except and kills it

    monkeypatch.setattr(cat, "_run_writeback", poisoned)
    cat.synchronous_spill(None)  # queues two to_host jobs
    writer = cat._writer
    assert writer is not None
    writer.join(10.0)
    assert not writer.is_alive()  # the poison killed it
    # the watchdog drains the stranded job synchronously: every hop's
    # completion event still sets, so no acquire can hang
    cat.drain_writeback()
    assert _kinds(spy, "spill_writer_dead")
    assert sb1.get_batch().to_pydict()["a"][:2] == [0, 1]
    assert sb2.get_batch().to_pydict()["a"][:2] == [1000, 1001]
    sb1.release(), sb2.release()
    # and the NEXT spill detects the dead writer at enqueue and starts
    # a fresh one
    cat.synchronous_spill(None)
    cat.drain_writeback()
    assert cat._writer is not None and cat._writer.is_alive()
    assert cat.tier_of(sb1._handle) == StorageTier.HOST
    sb1.close(), sb2.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_acquire_of_stranded_hop_does_not_hang(spill_env, monkeypatch):
    """acquire() of an entry whose writeback was stranded by a writer
    death recovers via the bounded-wait watchdog instead of parking
    forever."""
    cat = spill_env(True)
    sb = _spillable()
    real_run = cat._run_writeback

    def poisoned(kind, entry, path):
        real_run(kind, entry, path)
        raise SystemExit("injected writer death")

    monkeypatch.setattr(cat, "_run_writeback", poisoned)
    cat.synchronous_spill(None)
    cat._writer.join(10.0)
    monkeypatch.setattr(cat, "_run_writeback", real_run)
    done = {}

    def get():
        done["batch"] = sb.get_batch().to_pydict()["a"][:2]

    t = threading.Thread(target=get, daemon=True)
    t.start()
    t.join(15.0)
    assert not t.is_alive(), "acquire hung on a dead writer's hop"
    assert done["batch"] == [0, 1]
    sb.release()
    sb.close()


# ---------------------------------------------------------------------------
# end-to-end: session-level recovery + the chaos soak
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def q_files(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("chaos_q")
    rng = np.random.default_rng(17)
    n_l, n_o = 3000, 400
    lines = pa.table({
        "l_key": pa.array(rng.integers(0, n_o, n_l), pa.int64()),
        "l_val": pa.array(rng.random(n_l) * 100.0, pa.float64()),
        "l_flag": pa.array(rng.integers(0, 4, n_l), pa.int64()),
    })
    orders = pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(rng.integers(0, 10, n_o), pa.int64()),
    })
    lp, op = str(d / "lines.parquet"), str(d / "orders.parquet")
    pq.write_table(lines, lp, row_group_size=512)
    pq.write_table(orders, op, row_group_size=128)
    return lp, op, _oracle(lines, orders)


def _oracle(lines, orders):
    """key -> (rev, cnt) for the _drive_query shape, computed outside
    the engine (float sums to reduction-order tolerance)."""
    lk = np.asarray(lines["l_key"])
    lv = np.asarray(lines["l_val"])
    lf = np.asarray(lines["l_flag"])
    of = np.asarray(orders["o_flag"])
    keep = (lf != 0) & (of[lk] < 5)
    out = {}
    for k in np.unique(lk[keep]):
        vals = lv[keep & (lk == k)]
        out[int(k)] = (float(vals.sum()), int(len(vals)))
    return out


def _matches_oracle(rows, oracle):
    if len(rows) != len(oracle):
        return False
    for k, rev, cnt in rows:
        erev, ecnt = oracle.get(k, (None, None))
        if cnt != ecnt or abs(rev - erev) > 1e-9 * max(abs(erev), 1.0):
            return False
    revs = [r[1] for r in rows]
    return revs == sorted(revs, reverse=True)  # the sort survived too


def _drive_query(lp, op, settings):
    """scan -> filter -> join -> agg -> sort through the session."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col, lit
    from spark_rapids_tpu.api.session import TpuSession
    sess = TpuSession(settings)
    lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
    orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                  (F.count(), "cnt"))
    return agg.sort(("rev", False)).collect()


#: chaos session settings: fast deterministic backoffs, enough task
#: attempts to outlast the capped injection budget (8 points x max=2
#: task-lane faults worst case)
CHAOS = dict(FAST, **{"spark.rapids.tpu.task.maxAttempts": "20"})


def _rows_equal_float_tolerant(xs, ys, float_cols=(1,)):
    """Exact on keys/counts, 1e-9-relative on float sums: task retries
    and OOM splits change float reduction order (the documented
    improvedFloatOps divergence class); integers stay bit-exact."""
    if len(xs) != len(ys):
        return False
    for x, y in zip(xs, ys):
        for i, (a, b) in enumerate(zip(x, y)):
            if i in float_cols:
                if abs(a - b) > 1e-9 * max(abs(a), abs(b), 1.0):
                    return False
            elif a != b:
                return False
    return True


def _capped_spec(prob, seed, max_per_point=2):
    """Every point at `prob` with a per-point injection cap: total
    task-lane faults are bounded, so a bounded-attempt run provably
    converges while still injecting at the target rate."""
    return ";".join(part + f",max={max_per_point}"
                    for part in faults.uniform_spec(prob, seed).split(";"))


def _soak_once(q_files, seed, baseline, budget=None):
    lp, op, _ = q_files
    pre_threads = _threads()
    if budget is not None:
        reset_buffer_catalog()
        reset_memory_budget(budget)
    used_before = memory_budget().used
    entries_before = buffer_catalog().num_entries()
    try:
        settings = dict(CHAOS)
        settings["spark.rapids.tpu.test.faults"] = _capped_spec(0.05, seed)
        got = _drive_query(lp, op, settings)
        assert _rows_equal_float_tolerant(got, baseline), \
            f"seed {seed}: chaos run diverged from fault-free results"
        # hygiene: no leaked threads, budget + catalog back to baseline
        assert _threads() <= pre_threads, f"seed {seed}: leaked threads"
        buffer_catalog().drain_writeback()
        assert memory_budget().used == used_before, \
            f"seed {seed}: budget counter leaked"
        assert buffer_catalog().num_entries() == entries_before, \
            f"seed {seed}: catalog entries leaked"
    finally:
        faults.install(None)
        if budget is not None:
            reset_buffer_catalog()
            reset_memory_budget()


@pytest.fixture(scope="module")
def spill_q_files(tmp_path_factory):
    """A join input big enough that, under a 128KiB budget with a 1KiB
    host limit, the adaptive join's staged (spillable) build batches
    cascade to DISK and are re-read at probe time — the measured,
    deterministic (pipeline off) disk round-trip the spill-corruption
    criterion needs."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("chaos_spill_q")
    rng = np.random.default_rng(17)
    n_l, n_o = 8000, 400
    lines = pa.table({
        "l_key": pa.array(rng.integers(0, n_o, n_l), pa.int64()),
        "l_val": pa.array(rng.random(n_l) * 100.0, pa.float64()),
        "l_flag": pa.array(rng.integers(0, 4, n_l), pa.int64()),
    })
    orders = pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(rng.integers(0, 10, n_o), pa.int64()),
    })
    lp, op = str(d / "lines.parquet"), str(d / "orders.parquet")
    pq.write_table(lines, lp, row_group_size=512)
    pq.write_table(orders, op, row_group_size=128)
    return lp, op, _oracle(lines, orders)


def test_e2e_spill_corruption_recovered_by_recompute(spill_q_files,
                                                     spy, tmp_path):
    """Acceptance criterion: a corrupted spill file is detected by
    checksum at read, quarantined with integrity_fail, and the query
    still returns correct results via recompute (task re-execution —
    attempt 2 reuses the SAME armed plan, whose max=1 budget is spent,
    so the rewrite is clean)."""
    lp, op, oracle = spill_q_files
    prev = C.active_conf()
    try:
        reset_buffer_catalog()
        reset_memory_budget(128 * 1024)
        settings = dict(CHAOS)
        settings.update({
            # deterministic forced disk round-trip (see spill_q_files)
            "spark.rapids.memory.host.spillStorageSize": "1k",
            "spark.rapids.memory.spillDirectory": str(tmp_path),
            "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
            "spark.rapids.sql.broadcastSizeThreshold": "-1",
            "spark.rapids.tpu.pipeline.enabled": "false",
            # ISSUE 14: the deterministic disk RE-READ depends on the
            # per-op plan's exact allocation order (the documented
            # narrow window); the fused stage holds less live memory
            # and the corrupted file is never unspilled. The recovery
            # lane UNDER fusion is covered by test_stage_compiler's
            # forced-spill + chaos tests; this test pins the per-op
            # choreography that actually re-reads the corrupt file.
            "spark.rapids.tpu.stage.fusion.enabled": "false",
            "spark.rapids.tpu.test.faults":
                "spill.disk_write:prob=1,seed=4,kind=corrupt,max=1",
        })
        got = _drive_query(lp, op, settings)
        assert _kinds(spy, "integrity_fail"), \
            "the corruption was never read back — test lost its teeth"
        assert _kinds(spy, "task_retry")  # recovery was recompute
        assert _matches_oracle(got, oracle)
    finally:
        C.set_active_conf(prev)
        faults.install(None)
        reset_buffer_catalog()
        reset_memory_budget()


@pytest.mark.slow
def test_e2e_shuffle_corruption_recovered_by_recompute(q_files, spy):
    """Same criterion for a shuffle block: host-shuffled join/agg, one
    corrupted frame at decode, correct results — since ISSUE 6 via the
    PARTITION-GRANULAR lane: the exchange's captured lineage recomputes
    the one damaged map output in place, and no task attempt is spent
    (tests/test_lifecycle.py covers the same contract at tier-1 on a
    smaller plan; the conf-off fallback to the whole-plan lane is
    tier-1 there too). `slow`: the host-shuffled plan costs ~26s on the
    1-core box and the 870s tier-1 gate is the binding constraint."""
    lp, op, oracle = q_files
    settings = dict(CHAOS, **{
        "spark.rapids.sql.shuffle.partitions": "3",
        "spark.rapids.sql.broadcastSizeThreshold": "-1",
        "spark.rapids.tpu.test.faults":
            "shuffle.decode:prob=1,seed=6,kind=corrupt,max=1",
    })
    got = _drive_query(lp, op, settings)
    assert _matches_oracle(got, oracle)
    evs = _kinds(spy, "integrity_fail")
    assert evs and evs[0]["what"] == "shuffle_block"
    assert _kinds(spy, "partition_recompute"), \
        "the partition-granular lane did not engage"
    assert not _kinds(spy, "task_retry"), \
        "recovery escalated to the whole-plan lane"


@pytest.mark.slow
def test_chaos_mini_soak(q_files):
    """Nightly slice of the soak: 3 seeds at ~5% across every point —
    one of them under a spill-forcing budget — each bit-identical
    (float-order tolerant) to the fault-free run, with thread and
    budget hygiene asserted per query. (`slow` with the 100-query soak:
    tier-1 keeps the per-point injection tests and the two end-to-end
    corruption-recovery drives, which exercise the same task-retry
    path; the suite's 870s gate is the binding constraint.)"""
    lp, op, _ = q_files
    baseline = _drive_query(lp, op, dict(CHAOS))
    for seed in (1, 2):
        _soak_once(q_files, seed, baseline)
    _soak_once(q_files, 3, baseline, budget=192 * 1024)


@pytest.mark.slow
def test_chaos_soak_100_queries(q_files):
    """The full acceptance soak: 100 seeded end-to-end queries at ~5%
    injected fault rate (every registered point armed), every one equal
    to the fault-free run, zero leaked threads, budget counters back to
    baseline. Replay any failing seed with the spec string the
    assertion message names."""
    lp, op, _ = q_files
    baseline = _drive_query(lp, op, dict(CHAOS))
    for seed in range(100):
        _soak_once(q_files, seed, baseline,
                   budget=192 * 1024 if seed % 10 == 0 else None)


def test_profile_report_robustness_rollup():
    """The event-log CLI rolls up what a chaos run absorbed and at
    which recovery layer (tools/profile_report.py)."""
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import profile_report
    evs = [
        {"kind": "fault_inject", "point": "shuffle.decode"},
        {"kind": "fault_inject", "point": "shuffle.decode"},
        {"kind": "fault_inject", "point": "device.dispatch"},
        {"kind": "io_retry", "what": "shuffle.fetch", "attempt": 1},
        {"kind": "task_retry", "attempt": 1},
        {"kind": "integrity_fail", "what": "shuffle_block"},
        {"kind": "pipeline_stuck", "stage": "scan"},
        {"kind": "spill_writer_dead", "pending": 1},
    ]
    report = profile_report.build_report(evs)
    assert "injected faults: 3 (device.dispatch:1, shuffle.decode:2)" \
        in report
    assert "io retries: 1" in report
    assert "task re-executions: 1" in report
    assert "integrity quarantines: 1" in report
    assert "watchdog trips: 2" in report


# ---------------------------------------------------------------------------
# bench --fault-rate wiring
# ---------------------------------------------------------------------------

def test_bench_fault_rate_smoke(fast_conf, monkeypatch):
    import bench
    monkeypatch.setattr(bench, "_FAULT_RATE", None)
    assert bench.maybe_enable_faults(["bench.py"]) is None
    assert bench.chaos_attribution() is None
    rate = bench.maybe_enable_faults(["bench.py", "--fault-rate", "0.05"])
    assert rate == 0.05
    plan = faults.active_plan()
    assert plan is not None and set(plan.specs) == set(faults.FAULT_POINTS)
    rec = bench.chaos_attribution()
    assert rec["fault_rate"] == 0.05
    assert set(rec) >= {"points_hit", "injections", "recoveries",
                        "task_retries"}
    assert set(rec["recoveries"]) == {"io_retry", "task_retry"}
    # guarded_run absorbs a transient fault like a bench lane would
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise faults.InjectedDeviceError("device.dispatch")
        return 11

    assert bench.guarded_run(flaky) == 11
    assert len(calls) == 2
