"""Bounded RANGE window frames vs a Python oracle (VERDICT r4 item 7;
reference window/GpuWindowExpression.scala:111-179)."""

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.exec.window import WindowExec
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.expr.windowexprs import (
    WindowAgg, WindowFrame, window,
)
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)


def scan(data, schema):
    return InMemoryScanExec([ColumnarBatch.from_pydict(data, schema)],
                            schema)


def range_oracle(parts, keys, vals, prec, foll, op, ascending=True):
    """Per input row: op over vals of rows in the same partition whose key
    is within the value range; null-key rows frame the partition's null
    run; null vals skipped."""
    out = []
    for i in range(len(parts)):
        if keys[i] is None:
            # Spark: a null-key row frames the partition's null run for
            # bounded sides; an UNBOUNDED side extends past it (with
            # nulls-first ascending, UNBOUNDED FOLLOWING reaches every
            # valid row, UNBOUNDED PRECEDING adds nothing)
            in_frame = [j for j in range(len(parts))
                        if parts[j] == parts[i]
                        and (keys[j] is None or foll is None)]
        else:
            sgn = 1 if ascending else -1
            lo_v = None if prec is None else keys[i] - sgn * prec
            hi_v = None if foll is None else keys[i] + sgn * foll
            if not ascending:
                lo_v, hi_v = hi_v, lo_v
            # an UNBOUNDED side reaches the partition edge, including the
            # null run parked there (nulls first when ascending — Spark
            # default null ordering)
            nulls_reachable = (prec is None if ascending
                               else foll is None)
            in_frame = [
                j for j in range(len(parts))
                if parts[j] == parts[i]
                and ((keys[j] is None and nulls_reachable)
                     or (keys[j] is not None
                         and (lo_v is None or keys[j] >= lo_v)
                         and (hi_v is None or keys[j] <= hi_v)))]
        got = [vals[j] for j in in_frame if vals[j] is not None]
        if op == "count":
            out.append(len(got))
        elif not got:
            out.append(None)
        elif op == "sum":
            out.append(sum(got))
        elif op == "min":
            out.append(min(got))
        elif op == "max":
            out.append(max(got))
        elif op == "avg":
            out.append(sum(got) / len(got))
    return out


PARTS = ["a", "a", "a", "a", "b", "b", "b", "a", "b", "a"]
KEYS = [1, 3, 3, 7, 2, 4, 10, None, None, 12]
VALS = [10, 20, None, 40, 5, 15, 25, 99, 7, 60]
SCHEMA = Schema((StructField("p", STRING), StructField("k", LONG),
                 StructField("v", LONG)))


def _run(op, prec, foll, ascending=True, keys=KEYS, vals=VALS,
         key_type=LONG, val_type=LONG):
    sch = Schema((StructField("p", STRING), StructField("k", key_type),
                  StructField("v", val_type)))
    data = {"p": PARTS, "k": keys, "v": vals}
    spec = window(partition_by=["p"], order_by=[("k", ascending)],
                  frame=WindowFrame.range(prec, foll))
    plan = WindowExec([(WindowAgg(op, col("v")).over(spec), "w")],
                      scan(data, sch))
    got = plan.collect()
    # output is partition-sorted; map back via (p, k, v) multiset keys
    exp = range_oracle(PARTS, keys, vals, prec, foll, op, ascending)
    exp_rows = sorted(zip(PARTS, [("z" if k is None else k) for k in keys],
                          [(None, v) for v in vals], exp),
                      key=lambda r: (r[0], str(r[1])))
    got_rows = sorted([(r[0], "z" if r[1] is None else r[1],
                        (None, r[2]), r[3]) for r in got],
                      key=lambda r: (r[0], str(r[1])))
    for g, e in zip(got_rows, exp_rows):
        assert g[0] == e[0] and g[1] == e[1], (g, e)
        if isinstance(e[3], float):
            assert g[3] == pytest.approx(e[3])
        else:
            assert g[3] == e[3], (g, e)


@pytest.mark.parametrize("op", [
    "sum",
    # count rides the same machinery (ISSUE 13 budget relief): nightly
    pytest.param("count", marks=pytest.mark.slow),
    # min/max/avg ride the same range-frame machinery (~25s): nightly
    pytest.param("min", marks=pytest.mark.slow),
    pytest.param("max", marks=pytest.mark.slow),
    pytest.param("avg", marks=pytest.mark.slow),
])
def test_range_bounded_ops(op):
    _run(op, 2, 2)


@pytest.mark.parametrize("prec,foll", [
    (0, 0),        # CURRENT ROW..CURRENT ROW with ties
    # ISSUE 13 budget relief: the bounded shapes (0,0)/(1,1) and the
    # effectively-unbounded (1e12,1e12) stay tier-1; the rest of the
    # mixed-bound lattice is nightly
    pytest.param(None, 2, marks=pytest.mark.slow),
    pytest.param(2, None, marks=pytest.mark.slow),  # 2 PREC..UNB FOLL
    pytest.param(5, 0, marks=pytest.mark.slow),
    pytest.param(0, 5, marks=pytest.mark.slow),
    (1, 1), (10 ** 12, 10 ** 12),
    # 1 FOLLOWING..3 FOLLOWING (exclusive of current)
    pytest.param(-1, 3, marks=pytest.mark.slow),
])
def test_range_sum_bound_shapes(prec, foll):
    _run("sum", prec, foll)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_range_descending_order():
    _run("sum", 2, 2, ascending=False)
    _run("min", 3, 0, ascending=False)


@pytest.mark.slow  # ~8s; float range keys nightly, float-sum cancellation kept (round-7 budget move)
def test_range_float_keys():
    keys = [0.5, 1.25, 1.25, 3.0, -2.0, 0.0, 9.5, None, None, 12.75]
    _run("sum", 1.0, 1.0, keys=keys, key_type=DOUBLE)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_range_empty_frames_yield_null_sum_zero_count():
    # frame strictly in the future past the last key: empty for the max key
    parts = ["a", "a", "a"]
    keys = [1, 2, 10]
    vals = [1, 2, 4]
    sch = SCHEMA
    data = {"p": parts, "k": keys, "v": vals}
    spec = window(partition_by=["p"], order_by=["k"],
                  frame=WindowFrame.range(-1, 2))  # (k+1)..(k+2)
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s"),
                       (WindowAgg("count", col("v")).over(spec), "c")],
                      scan(data, sch))
    got = sorted(plan.collect())
    # k=1 -> frame keys in [2,3] -> {2}; k=2 -> [3,4] -> empty;
    # k=10 -> [11,12] -> empty
    assert got == [("a", 1, 1, 2, 1), ("a", 2, 2, None, 0),
                   ("a", 10, 4, None, 0)]


def test_range_rejects_multiple_order_keys():
    sch = Schema((StructField("p", STRING), StructField("k", LONG),
                  StructField("k2", LONG), StructField("v", LONG)))
    data = {"p": ["a"], "k": [1], "k2": [2], "v": [3]}
    spec = window(partition_by=["p"], order_by=["k", "k2"],
                  frame=WindowFrame.range(1, 1))
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "w")],
                      scan(data, sch))
    with pytest.raises(AssertionError, match="RANGE"):
        plan.collect()


def test_range_float_sum_no_cross_partition_cancellation():
    # tiny partition sorted after a 1e12-scale partition: its windowed
    # sums must not collapse to 0.0 (segment-local prefix, ADVICE r4)
    parts = ["a"] * 50 + ["b"] * 5
    keys = list(range(50)) + list(range(5))
    vals = [1e12] * 50 + [1e-6] * 5
    sch = Schema((StructField("p", STRING), StructField("k", LONG),
                  StructField("v", DOUBLE)))
    spec = window(partition_by=["p"], order_by=["k"],
                  frame=WindowFrame.range(1, 1))
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                      scan({"p": parts, "k": keys, "v": vals}, sch))
    got_b = [r[3] for r in plan.collect() if r[0] == "b"]
    exp = [2e-6, 3e-6, 3e-6, 3e-6, 2e-6]
    for g, e in zip(got_b, exp):
        assert g == pytest.approx(e, rel=1e-9), (g, e)


def test_rows_float_sum_no_cross_partition_cancellation():
    parts = ["a"] * 50 + ["b"] * 5
    keys = list(range(55))
    vals = [1e12] * 50 + [1e-6] * 5
    sch = Schema((StructField("p", STRING), StructField("k", LONG),
                  StructField("v", DOUBLE)))
    spec = window(partition_by=["p"], order_by=["k"],
                  frame=WindowFrame.rows(1, 1))
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                      scan({"p": parts, "k": keys, "v": vals}, sch))
    got_b = [r[3] for r in plan.collect() if r[0] == "b"]
    exp = [2e-6, 3e-6, 3e-6, 3e-6, 2e-6]
    for g, e in zip(got_b, exp):
        assert g == pytest.approx(e, rel=1e-9), (g, e)
