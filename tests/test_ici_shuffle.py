"""ICI-native device-resident shuffle lane (ISSUE 16): on a mesh whose
axis size equals the partition count, the host shuffle exchange runs
map-side partition split + packed all-to-all + reduce-side unpack
entirely on device — zero host serialize frames, zero per-batch
D2H/H2D. The host serialize/LZ4 path stays as the degradation tier.

Covers: byte-identical results vs the host lane across column families
(strings, nulls, decimal128, empty partitions), the structural
zero-host-serialize claim, slot-cap negotiation, spillability of staged
exchange shards (origin-tagged catalog entries), the roundrobin cursor,
injected-fault fallback (whole-stream and mid-stream hybrid drain) and
ICI-lane eligibility gating."""

import decimal

import numpy as np
import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar import upload
from spark_rapids_tpu.memory.catalog import buffer_catalog
from spark_rapids_tpu.parallel.exchange import negotiate_slot_cap
from spark_rapids_tpu.shuffle import manager as shuffle_mgr
from spark_rapids_tpu.types import (DOUBLE, LONG, STRING, ArrayType,
                                    DecimalType, Schema, StructField)

N_DEV = 8  # tests/conftest.py forces 8 virtual CPU devices


def _conf(ici: bool, extra=None):
    conf = {
        # planExchange=false keeps the mesh for collectives while the
        # planner still places the HOST shuffle exchange — the exec the
        # ICI lane lives in
        "spark.rapids.sql.shuffle.partitions": str(N_DEV),
        "spark.rapids.tpu.shuffle.planExchange": "false",
        "spark.rapids.sql.broadcastSizeThreshold": "-1",
        "spark.rapids.tpu.shuffle.ici.enabled": str(ici).lower(),
    }
    if extra:
        conf.update(extra)
    return conf


def _ici_session(extra=None):
    return TpuSession(_conf(True, extra), mesh_devices=N_DEV)


def _host_session(extra=None):
    return TpuSession(_conf(False, extra), mesh_devices=N_DEV)


def _sorted(rows):
    return sorted(rows, key=repr)


def _find_exchange(plan):
    from spark_rapids_tpu.exec.exchange import HostShuffleExchangeExec
    if isinstance(plan, HostShuffleExchangeExec):
        return plan
    for attr in ("child", "left", "right"):
        c = getattr(plan, attr, None)
        if c is not None:
            found = _find_exchange(c)
            if found is not None:
                return found
    for c in getattr(plan, "children", ()) or ():
        found = _find_exchange(c)
        if found is not None:
            return found
    return None


# ---------------------------------------------------------------------------
# slot-cap negotiation (parallel/exchange.py promoted primitive)
# ---------------------------------------------------------------------------

def test_negotiate_slot_cap():
    from spark_rapids_tpu.columnar.column import bucket_capacity
    # measured load rounds up to its capacity bucket...
    assert negotiate_slot_cap(100, 1024) == bucket_capacity(100)
    # ...but never past the full-capacity worst case
    assert negotiate_slot_cap(5000, 1024) == 1024
    # empty rounds still get a 1-slot grid (all_to_all needs a shape)
    assert negotiate_slot_cap(0, 1024) >= 1
    # the running high-water hint floors the cap so later smaller
    # rounds reuse the SAME compiled step (shape stability)
    small = negotiate_slot_cap(3, 1024)
    assert negotiate_slot_cap(3, 1024, hint=100) \
        == negotiate_slot_cap(100, 1024) >= small


# ---------------------------------------------------------------------------
# equality drive: ICI vs host lane, byte-identical per-partition order
# ---------------------------------------------------------------------------

def _rich_data(n=300):
    rng = np.random.default_rng(16)
    return {
        "k": [int(x) for x in rng.integers(0, 20, n)],
        "v": [None if x % 11 == 0 else int(x)
              for x in rng.integers(-(10 ** 12), 10 ** 12, n)],
        "s": [None if x % 5 == 0 else ("värde-%d" % x) * (x % 4)
              for x in range(n)],
        "d": [None if x % 7 == 0 else float(x) * 0.5 for x in range(n)],
        "dec": [None if x % 6 == 0
                else decimal.Decimal(int(x) * 123456789).scaleb(-2)
                for x in rng.integers(0, 10 ** 6, n)],
    }


def _rich_schema():
    return Schema((StructField("k", LONG), StructField("v", LONG),
                   StructField("s", STRING), StructField("d", DOUBLE),
                   StructField("dec", DecimalType(30, 2))))


def test_ici_repartition_matches_host_exactly():
    """Round-robin repartition of string/null/decimal128 payloads: the
    ICI lane's output rows EQUAL the host lane's in order, not just as
    multisets — the one-map-batch-per-device round grouping preserves
    per-partition row order."""
    data, sch = _rich_data(), _rich_schema()

    def q(sess):
        return sess.from_pydict(data, sch, batch_rows=64) \
            .repartition(N_DEV).collect()

    host = q(_host_session())
    i0 = shuffle_mgr.ici_counters()
    ici = q(_ici_session())
    i1 = shuffle_mgr.ici_counters()
    assert i1["rounds"] > i0["rounds"], "ICI lane did not engage"
    assert ici == host


@pytest.mark.slow  # ~90s: two fresh sessions compile the 8-way shuffled
# join pipeline; string/decimal exchange equality stays tier-1 via the
# repartition drive, and the driver's dryrun third leg keeps a join-path
# ICI check in every round
def test_ici_join_agg_matches_host():
    """Hash-partitioned shuffled join + aggregation over the mesh:
    ICI and host lanes agree, with string payloads through the join
    exchange."""
    rng = np.random.default_rng(7)
    ldata = {"k": [int(x) for x in rng.integers(0, 20, 300)],
             "v": [int(x) for x in rng.integers(0, 50, 300)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 20, 200)],
             "w": [["a", "bb", None, "dddd"][int(x)]
                   for x in rng.integers(0, 4, 200)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", STRING)))

    def q(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=64)
        r = sess.from_pydict(rdata, rsch, batch_rows=64)
        return l.join(r, on="k").group_by("k").agg(
            (F.sum(col("v")), "sv"), (F.count(), "c")).collect()

    host = q(_host_session())
    i0 = shuffle_mgr.ici_counters()
    ici = q(_ici_session())
    i1 = shuffle_mgr.ici_counters()
    assert i1["rounds"] > i0["rounds"]
    assert i1["fallbacks"] == i0["fallbacks"]
    assert _sorted(ici) == _sorted(host)


def test_ici_empty_partitions():
    """Keys confined to two values on an 8-way mesh: most partitions
    receive nothing, the compaction still yields exact results and the
    empty partitions drain as empty batches."""
    data = {"k": [0, 1] * 40, "v": list(range(80))}
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))

    def q(sess):
        return sess.from_pydict(data, sch, batch_rows=16) \
            .group_by("k").agg((F.sum(col("v")), "sv"),
                               (F.count(), "c")).collect()

    host = q(_host_session())
    ici = q(_ici_session())
    assert _sorted(ici) == _sorted(host)
    assert len(ici) == 2


# ---------------------------------------------------------------------------
# the structural claim: map output never leaves HBM
# ---------------------------------------------------------------------------

def test_ici_zero_host_serialize_frames():
    """On the ICI lane the host serializer writes ZERO frames and the
    upload engine runs ZERO shuffle-read ingests — the exchanged bytes
    moved device-to-device (shuffle/manager + columnar/upload counter
    deltas are the structural witnesses)."""
    data, sch = _rich_data(), _rich_schema()
    sess = _ici_session()
    df = sess.from_pydict(data, sch, batch_rows=64).repartition(N_DEV)
    tree = df._exec().tree_string()
    assert "HostShuffleExchangeExec" in tree, tree

    c0 = shuffle_mgr.counters()
    i0 = shuffle_mgr.ici_counters()
    u0 = upload.counters()
    rows = df.collect()
    c1 = shuffle_mgr.counters()
    i1 = shuffle_mgr.ici_counters()
    u1 = upload.counters()

    assert len(rows) == len(data["k"])
    assert c1["frames"] == c0["frames"], \
        "host serialize frames on the ICI lane"
    assert c1["bytes"] == c0["bytes"]
    assert u1["uploads"] == u0["uploads"], \
        "shuffle-read h2d ingest on the ICI lane"
    assert i1["rounds"] > i0["rounds"]
    assert i1["bytes"] > i0["bytes"]
    assert i1["fallbacks"] == i0["fallbacks"]


# ---------------------------------------------------------------------------
# staged shards are real catalog citizens: origin tag + forced spill
# ---------------------------------------------------------------------------

def test_ici_staged_shards_spill_and_recover():
    """Staged exchange shards are origin-tagged spillable catalog
    entries: mid-drain they show under bytes_by_origin(), a forced
    full spill pushes them off-device, and the remaining partitions
    unspill to the exact host-lane rows."""
    data, sch = _rich_data(), _rich_schema()
    host = _host_session().from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV).collect()

    sess = _ici_session()
    plan = sess.from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV)._exec()
    it = plan.execute()
    first = next(it)  # all rounds ran; later partitions still staged
    org = buffer_catalog().bytes_by_origin()
    assert "ici_exchange" in org, org
    dev_b, host_b = org["ici_exchange"]
    assert dev_b + host_b > 0
    buffer_catalog().synchronous_spill(None)  # steal everything
    batches = [first] + list(it)
    rows = [tuple(r) for b in batches for r in b.to_pylist()]
    assert rows == [tuple(r) for r in host]


# ---------------------------------------------------------------------------
# degradation: injected collective fault -> host serialize lane
# ---------------------------------------------------------------------------

def _fault_guard():
    faults.install(None)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.install(None)
    yield
    faults.install(None)


def test_ici_fault_falls_back_to_host():
    """A seeded device fault at the collective dispatch
    (shuffle.ici_exchange) opens the round's degradation path: the
    stream finishes on the host serialize lane with exact results and
    one recorded fallback."""
    data, sch = _rich_data(), _rich_schema()

    def q(sess):
        return sess.from_pydict(data, sch, batch_rows=64) \
            .repartition(N_DEV).collect()

    host = q(_host_session())
    i0 = shuffle_mgr.ici_counters()
    faults.install("shuffle.ici_exchange:prob=1,seed=3,kind=device,max=1")
    try:
        ici = q(_ici_session())
    finally:
        faults.install(None)
    i1 = shuffle_mgr.ici_counters()
    assert i1["fallbacks"] - i0["fallbacks"] >= 1
    assert ici == host


def test_ici_midstream_fault_hybrid_drain():
    """A fault AFTER successful rounds exercises the hybrid drain:
    staged ICI pieces (earlier map batches) chain before the host
    lane's partition streams, preserving exact row order. Driven
    deterministically at the exec seam — a transient raise on round 1
    of a multi-round stream."""
    rng = np.random.default_rng(11)
    data = {"k": [int(x) for x in rng.integers(0, 9, 1200)],
            "v": [int(x) for x in rng.integers(-40, 40, 1200)]}
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    # small coalesce target keeps the 64-row scan batches from merging
    # into one exchange input (19 map batches -> a 3-round stream)
    extra = {"spark.rapids.sql.batchSizeBytes": "4096"}
    host = _host_session(extra).from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV).collect()

    sess = _ici_session(extra)
    plan = sess.from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV)._exec()
    ex = _find_exchange(plan)
    assert ex is not None
    orig = ex._ici_exchange_round

    def flaky(batches, rr_offs, round_idx):
        if round_idx >= 1:
            raise faults.InjectedDeviceError("shuffle.ici_exchange")
        return orig(batches, rr_offs, round_idx)

    ex._ici_exchange_round = flaky
    i0 = shuffle_mgr.ici_counters()
    rows = [tuple(r) for b in plan.execute() for r in b.to_pylist()]
    i1 = shuffle_mgr.ici_counters()
    assert i1["rounds"] - i0["rounds"] == 1  # round 0 succeeded on ICI
    assert i1["fallbacks"] - i0["fallbacks"] == 1
    assert rows == [tuple(r) for r in host]


# ---------------------------------------------------------------------------
# review hardening: seam narrowness, abandonment cleanup, stats unity,
# mesh-keyed step cache
# ---------------------------------------------------------------------------

def _midstream_plan(seed=11):
    """The 3-round shuffle stream the hybrid-drain tests share: 19 map
    batches through an 8-way repartition."""
    rng = np.random.default_rng(seed)
    data = {"k": [int(x) for x in rng.integers(0, 9, 1200)],
            "v": [int(x) for x in rng.integers(-40, 40, 1200)]}
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    extra = {"spark.rapids.sql.batchSizeBytes": "4096"}
    sess = _ici_session(extra)
    return sess.from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV)._exec()


def _ici_origin_bytes():
    org = buffer_catalog().bytes_by_origin()
    return sum(org.get("ici_exchange", (0, 0)))


def test_ici_child_stream_error_propagates():
    """A transient error raised by the CHILD stream (not the collective
    dispatch) must NOT be swallowed into the degradation seam: the
    raised generator is finalized, so a host-lane fallback would
    silently drop every unconsumed child batch and return partial
    results. It propagates to the task-retry layer instead — no
    fallback recorded, staged shards torn down."""
    plan = _midstream_plan()
    ex = _find_exchange(plan)
    assert ex is not None
    orig = ex.child.execute

    def flaky_child():
        for i, b in enumerate(orig()):
            if i >= N_DEV + 1:  # past round 0: shards already staged
                raise faults.InjectedDeviceError("upstream.compute")
            yield b

    ex.child.execute = flaky_child
    base = _ici_origin_bytes()
    i0 = shuffle_mgr.ici_counters()
    with pytest.raises(faults.InjectedDeviceError):
        list(plan.execute())
    i1 = shuffle_mgr.ici_counters()
    assert i1["fallbacks"] == i0["fallbacks"], \
        "child-stream error misattributed to the ICI collective"
    assert i1["rounds"] - i0["rounds"] == 1  # round 0 had succeeded
    assert _ici_origin_bytes() == base, "staged shards leaked on raise"


def test_ici_abandoned_partition_generators_release_staged_entries():
    """A consumer that abandons the outer partition stream — or never
    starts a yielded partition generator (never-started generators run
    no finally, even on close) — must not leak the staged shards'
    catalog entries: the weakref finalizers + the outer finally close
    every undrained piece."""
    import gc
    data, sch = _rich_data(), _rich_schema()
    sess = _ici_session()
    plan = sess.from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV)._exec()
    ex = _find_exchange(plan)
    assert ex is not None
    base = _ici_origin_bytes()
    outer = ex.execute_partitions()
    g0 = next(outer)  # never started
    g1 = next(outer)
    next(g1)          # partially drained, then abandoned
    assert _ici_origin_bytes() > base, "staged entries live mid-drain"
    del g0, g1
    outer.close()     # partitions 2..7 never handed out
    del outer
    gc.collect()
    assert _ici_origin_bytes() == base, \
        "abandoned partition streams leaked staged catalog entries"


def test_ici_hybrid_drain_single_exchange_stats(tmp_path):
    """One execution emits ONE exchange_stats record even when it
    crosses both lanes (ICI rounds + host remainder after a mid-stream
    fault): the recorder rides into the host fallback instead of each
    lane emitting its own partial roll-up."""
    import glob
    import json

    from spark_rapids_tpu.obs import events
    plan = _midstream_plan()
    ex = _find_exchange(plan)
    orig = ex._ici_exchange_round

    def flaky(batches, rr_offs, round_idx):
        if round_idx >= 1:
            raise faults.InjectedDeviceError("shuffle.ici_exchange")
        return orig(batches, rr_offs, round_idx)

    ex._ici_exchange_round = flaky
    events.enable(str(tmp_path), "MODERATE")
    try:
        rows = [r for b in plan.execute() for r in b.to_pylist()]
    finally:
        events.reset_event_bus()
    assert len(rows) == 1200
    recs = []
    for f in glob.glob(str(tmp_path / "events-*.jsonl")):
        with open(f) as fh:
            recs.extend(json.loads(ln) for ln in fh if ln.strip())
    stats = [r for r in recs if r["kind"] == "exchange_stats"]
    assert len(stats) == 1, stats
    # the single record spans BOTH lanes: every map batch (ICI round 0
    # replays nothing; its 8 maps + the host lane's 11) and every row
    assert stats[0]["maps"] == 19
    assert stats[0]["rows"] == 1200


def test_ici_stats_per_map_batch_granularity(tmp_path):
    """The pure ICI lane records one map per MAP BATCH (the host
    lane's granularity), not one per collective round — skew roll-ups
    across lanes stay comparable."""
    import glob
    import json

    from spark_rapids_tpu.obs import events
    plan = _midstream_plan(seed=13)
    ex = _find_exchange(plan)
    i0 = shuffle_mgr.ici_counters()
    events.enable(str(tmp_path), "MODERATE")
    try:
        rows = [r for b in plan.execute() for r in b.to_pylist()]
    finally:
        events.reset_event_bus()
    i1 = shuffle_mgr.ici_counters()
    assert len(rows) == 1200
    rounds = i1["rounds"] - i0["rounds"]
    assert rounds >= 2
    recs = []
    for f in glob.glob(str(tmp_path / "events-*.jsonl")):
        with open(f) as fh:
            recs.extend(json.loads(ln) for ln in fh if ln.strip())
    stats = [r for r in recs if r["kind"] == "exchange_stats"]
    assert len(stats) == 1, stats
    from spark_rapids_tpu.exec.base import NUM_INPUT_BATCHES
    n_maps = ex.metrics[NUM_INPUT_BATCHES].value
    assert stats[0]["maps"] == n_maps > rounds


def test_ici_step_cache_keys_on_mesh_identity():
    """The compiled exchange step closes over the mesh it was built
    under: a session that installs a DIFFERENT mesh later (same axis
    size, different device order) must miss the step cache and get a
    fresh step bound to the new mesh, not a collective over the stale
    one."""
    import jax
    from jax.sharding import Mesh

    from spark_rapids_tpu.parallel.mesh import DATA_AXIS
    data, sch = _rich_data(80), _rich_schema()
    sess = _ici_session()
    plan = sess.from_pydict(data, sch, batch_rows=64) \
        .repartition(N_DEV)._exec()
    ex = _find_exchange(plan)
    list(plan.execute())
    assert ex._ici_steps, "ICI lane did not build a step"
    cap, slot_cap, width = next(iter(ex._ici_steps))[:3]
    n0 = len(ex._ici_steps)
    # same mesh identity -> cache hit
    ex._get_ici_step(cap, slot_cap, width)
    assert len(ex._ici_steps) == n0
    # reversed device order = a different mesh -> cache miss
    devs = list(jax.devices())[:N_DEV]
    ex._ici_mesh = Mesh(np.array(devs[::-1]), (DATA_AXIS,))
    ex._get_ici_step(cap, slot_cap, width)
    assert len(ex._ici_steps) == n0 + 1


# ---------------------------------------------------------------------------
# eligibility gating
# ---------------------------------------------------------------------------

def test_ici_requires_mesh_matching_partitions():
    """Partition count != mesh axis size -> the exchange silently keeps
    the host lane (no rounds, frames move)."""
    data = {"k": [int(x) for x in range(100)],
            "v": [int(x) for x in range(100)]}
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    sess = TpuSession(_conf(True, {
        "spark.rapids.sql.shuffle.partitions": "4"}), mesh_devices=N_DEV)
    i0 = shuffle_mgr.ici_counters()
    c0 = shuffle_mgr.counters()
    got = sess.from_pydict(data, sch, batch_rows=32) \
        .group_by("k").agg((F.count(), "c")).collect()
    i1 = shuffle_mgr.ici_counters()
    c1 = shuffle_mgr.counters()
    assert len(got) == 100
    assert i1["rounds"] == i0["rounds"]
    assert c1["frames"] > c0["frames"], "host lane should have run"


def test_ici_skips_nested_payloads():
    """Array payloads have no packed collective representation — the
    eligibility gate keeps such schemas on the host lane instead of
    dispatching a collective that cannot carry them."""
    data = {"k": [int(x) for x in range(60)],
            "a": [[int(x), int(x) + 1] for x in range(60)]}
    sch = Schema((StructField("k", LONG),
                  StructField("a", ArrayType(LONG))))

    def q(sess):
        return sess.from_pydict(data, sch, batch_rows=16) \
            .repartition(N_DEV).collect()

    host = q(_host_session())
    i0 = shuffle_mgr.ici_counters()
    ici = q(_ici_session())
    i1 = shuffle_mgr.ici_counters()
    assert i1["rounds"] == i0["rounds"], \
        "nested payload must not take the collective lane"
    assert ici == host
