"""Delta Lake tests: log replay, checkpoints, partitioned writes, file
skipping via stats, time travel, DELETE/UPDATE/MERGE, optimistic
concurrency (reference: delta-lake module suites + integration
delta_lake_*.py; SURVEY §2.7)."""

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.delta import DeltaLog, DeltaTable
from spark_rapids_tpu.delta.log import DeltaConcurrentModificationException
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.types import (DOUBLE, LONG, STRING, Schema,
                                    StructField)


def _sorted(rows):
    return sorted(rows, key=repr)


SCH = Schema((StructField("k", LONG), StructField("v", DOUBLE),
              StructField("s", STRING)))


def _df(sess, ks, vs=None, ss=None):
    n = len(ks)
    return sess.from_pydict({
        "k": ks,
        "v": vs if vs is not None else [float(x) for x in range(n)],
        "s": ss if ss is not None else [f"s{x}" for x in range(n)],
    }, SCH)


def test_write_read_roundtrip(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    df = _df(sess, [1, 2, 3], [1.0, None, 3.0], ["a", "b", None])
    df.write_delta(path)
    got = sess.read_delta(path).collect()
    assert _sorted(got) == _sorted(df.collect())
    # log structure exists
    assert os.path.exists(os.path.join(path, "_delta_log",
                                       f"{0:020d}.json"))


def test_append_and_overwrite(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1]).write_delta(path)
    _df(sess, [2]).write_delta(path, mode="append")
    assert sorted(r[0] for r in sess.read_delta(path).collect()) == [1, 2]
    _df(sess, [9]).write_delta(path, mode="overwrite")
    assert [r[0] for r in sess.read_delta(path).collect()] == [9]


def test_time_travel(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1]).write_delta(path)
    _df(sess, [2]).write_delta(path, mode="append")
    assert [r[0] for r in sess.read_delta(path, version=0).collect()] == [1]
    assert sorted(r[0] for r in sess.read_delta(path, version=1)
                  .collect()) == [1, 2]


def test_partitioned_write_and_pruning(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1, 1, 2, 2, 3], [0.0, 1.0, 2.0, 3.0, 4.0],
        ["a", "b", "c", "d", "e"]).write_delta(path, partition_by=["k"])
    # hive-style layout
    assert os.path.isdir(os.path.join(path, "k=1"))
    df = sess.read_delta(path)
    got = df.filter(col("k") == lit(2)).collect()
    assert _sorted([(r[1], r[2]) for r in got]) == [(2.0, "c"), (3.0, "d")]
    # pruning is observable through the source stats
    from spark_rapids_tpu.delta.table import DeltaSource
    log = DeltaLog(path)
    src = DeltaSource(log, log.snapshot(), sess.conf,
                      filters=[("k", "==", 2)])
    files = src.files_after_skipping()
    assert len(files) == 1 and src.scan_stats["files_pruned"] == 2


def test_stats_file_skipping_non_partition(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    # two files with disjoint k ranges (two commits → two files)
    _df(sess, [1, 2, 3]).write_delta(path)
    _df(sess, [100, 200]).write_delta(path, mode="append")
    from spark_rapids_tpu.delta.table import DeltaSource
    log = DeltaLog(path)
    src = DeltaSource(log, log.snapshot(), sess.conf,
                      filters=[("k", ">", 50)])
    files = src.files_after_skipping()
    assert len(files) == 1
    assert src.scan_stats["files_pruned"] == 1
    # stats recorded in the add action
    snap = log.snapshot()
    stats = [f.parsed_stats() for f in snap.files]
    assert all(s and "minValues" in s and "numRecords" in s for s in stats)


def test_delete(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1, 2, 3, 4], [1.0, 2.0, 3.0, 4.0]).write_delta(path)
    n = DeltaTable.for_path(sess, path).delete(col("k") >= lit(3))
    assert n == 2
    assert sorted(r[0] for r in sess.read_delta(path).collect()) == [1, 2]
    # old version still readable (time travel across DML)
    assert len(sess.read_delta(path, version=0).collect()) == 4


def test_update(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1, 2, 3], [1.0, 2.0, 3.0]).write_delta(path)
    n = DeltaTable.for_path(sess, path).update(
        {"v": col("v") * lit(10.0)}, col("k") > lit(1))
    assert n == 2
    got = {r[0]: r[1] for r in sess.read_delta(path).collect()}
    assert got == {1: 1.0, 2: 20.0, 3: 30.0}


def test_merge_upsert(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1, 2, 3], [1.0, 2.0, 3.0], ["a", "b", "c"]).write_delta(path)
    source = sess.from_pydict(
        {"k": [2, 3, 4], "v": [20.0, 30.0, 40.0], "s": ["B", "C", "D"]},
        SCH)
    stats = (DeltaTable.for_path(sess, path)
             .merge(source, on=["k"])
             .when_matched_update({"v": col("__s_v"), "s": col("__s_s")})
             .when_not_matched_insert()
             .execute())
    assert stats["updated"] == 2 and stats["inserted"] == 1
    got = {r[0]: (r[1], r[2]) for r in sess.read_delta(path).collect()}
    assert got == {1: (1.0, "a"), 2: (20.0, "B"), 3: (30.0, "C"),
                   4: (40.0, "D")}


def test_merge_delete(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1, 2, 3]).write_delta(path)
    source = sess.from_pydict({"k": [2], "v": [0.0], "s": ["x"]}, SCH)
    stats = (DeltaTable.for_path(sess, path)
             .merge(source, on=["k"]).when_matched_delete().execute())
    assert stats["deleted"] == 1
    assert sorted(r[0] for r in sess.read_delta(path).collect()) == [1, 3]


def test_merge_ambiguous_source_rejected(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1]).write_delta(path)
    source = sess.from_pydict({"k": [1, 1], "v": [0.0, 1.0],
                               "s": ["x", "y"]}, SCH)
    with pytest.raises(ValueError, match="multiple source rows"):
        (DeltaTable.for_path(sess, path).merge(source, on=["k"])
         .when_matched_update({"v": col("__s_v")}).execute())


def test_concurrent_commit_conflict(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1]).write_delta(path)
    log = DeltaLog(path)
    v = log.latest_version() + 1
    log.commit([DeltaLog.commit_info("WRITE")], v)
    with pytest.raises(DeltaConcurrentModificationException):
        log.commit([DeltaLog.commit_info("WRITE")], v)


def test_checkpoint_roundtrip(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [0]).write_delta(path)
    for i in range(1, 12):
        _df(sess, [i]).write_delta(path, mode="append")
    log = DeltaLog(path)
    assert log.last_checkpoint() == 10
    # snapshot built from checkpoint + tail commits
    got = sorted(r[0] for r in sess.read_delta(path).collect())
    assert got == list(range(12))


def test_history(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t")
    _df(sess, [1]).write_delta(path)
    DeltaTable.for_path(sess, path).delete(col("k") == lit(1))
    hist = DeltaTable.for_path(sess, path).history()
    assert [h["operation"] for h in hist] == ["WRITE", "DELETE"]
