"""Asynchronous pipelined execution (ISSUE 3): the bounded stage
boundary's fault paths, thread hygiene and context propagation, the
cross-thread re-entrant admission semaphore, background spill
writeback, and engine-level on/off equality. Deterministic on
single-core CPU: ordering and thread hygiene are asserted, never
timing."""

import threading
import time
import traceback

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.exec.pipeline import (PipelinedIterator, _SyncStage,
                                            pipeline_depth, pipelined)


def _pipeline_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("pipeline-")]


@pytest.fixture(autouse=True)
def no_leaked_threads():
    """Every test in this file must leave zero pipeline threads."""
    assert not _pipeline_threads()
    yield
    assert not _pipeline_threads()


# -- the primitive ----------------------------------------------------------

def test_fifo_ordering():
    stage = pipelined(iter(range(500)), depth=3)
    try:
        assert list(stage) == list(range(500))
    finally:
        stage.close()


def test_depth_zero_is_synchronous():
    stage = pipelined(iter([1, 2, 3]), depth=0)
    assert isinstance(stage, _SyncStage)
    assert list(stage) == [1, 2, 3]
    stage.close()


def test_enabled_false_degrades_to_sync():
    conf = C.RapidsConf({"spark.rapids.tpu.pipeline.enabled": False})
    assert pipeline_depth(conf) == 0
    assert isinstance(pipelined(iter([]), conf=conf), _SyncStage)
    conf_on = C.RapidsConf({"spark.rapids.tpu.pipeline.depth": "5"})
    assert pipeline_depth(conf_on) == 5


def test_producer_error_surfaces_at_consumer_with_traceback():
    """Items produced before the error arrive first (queue drained),
    then the error re-raises at the consumer with the producer's
    original traceback; the thread is joined."""
    def boom():
        yield 10
        yield 20
        raise ValueError("injected producer failure")

    stage = pipelined(boom(), depth=2)
    got = []
    with pytest.raises(ValueError, match="injected producer failure") as ei:
        for x in stage:
            got.append(x)
    assert got == [10, 20]
    tb = "".join(traceback.format_tb(ei.value.__traceback__))
    assert "boom" in tb  # original producer frames preserved
    stage.close()
    assert not _pipeline_threads()  # joined, not abandoned


def test_consumer_abandons_early_producer_unblocks():
    """A consumer walking away (limit/short-circuit) must unblock a
    producer stuck on the full queue and join it; the source iterator's
    finally runs."""
    state = {"produced": 0, "closed": False}

    def endless():
        try:
            while True:
                state["produced"] += 1
                yield state["produced"]
        finally:
            state["closed"] = True

    stage = pipelined(endless(), depth=2)
    assert next(stage) == 1
    stage.close()  # producer is blocked on the full queue right now
    assert not _pipeline_threads()
    assert state["closed"]  # source generator finalized
    # bounded prefetch: the producer never ran unboundedly ahead
    assert state["produced"] <= 2 + 2 + 1  # depth + in-flight slack


def test_close_is_idempotent_and_next_after_close_stops():
    stage = pipelined(iter(range(10)), depth=2)
    assert next(stage) == 0
    stage.close()
    stage.close()
    with pytest.raises(StopIteration):
        next(stage)


def test_producer_inherits_conf_query_id_and_speculation_scope():
    from spark_rapids_tpu.exec.speculation import (current_scope,
                                                   speculation_scope)
    from spark_rapids_tpu.obs import events as obs_events

    conf = C.RapidsConf({"spark.rapids.tpu.pipeline.depth": "3"})
    C.set_active_conf(conf)
    try:
        with obs_events.query_scope() as qid:
            with speculation_scope() as scope:
                seen = {}

                def probe():
                    seen["conf"] = C.active_conf()
                    seen["qid"] = obs_events.current_query_id()
                    seen["scope"] = current_scope()
                    yield 1

                stage = pipelined(probe(), depth=2)
                try:
                    assert list(stage) == [1]
                finally:
                    stage.close()
                assert seen["conf"] is conf
                assert seen["qid"] == qid
                assert seen["scope"] is scope
    finally:
        C.set_active_conf(C.RapidsConf())


def test_pipeline_events_emitted(tmp_path):
    import json

    from spark_rapids_tpu.obs import events as obs_events
    obs_events.enable(str(tmp_path), "MODERATE")
    try:
        stage = pipelined(iter(range(5)), depth=2, label="evt-test")
        assert list(stage) == list(range(5))
        stage.close()
    finally:
        obs_events.reset_event_bus()
    recs = [json.loads(ln) for f in tmp_path.glob("*.jsonl")
            for ln in f.read_text().splitlines()]
    kinds = {r["kind"] for r in recs if r.get("stage") == "evt-test"}
    assert kinds == {"pipeline_wait", "pipeline_full"}
    wait = [r for r in recs if r["kind"] == "pipeline_wait"
            and r["stage"] == "evt-test"]
    assert len(wait) == 1 and wait[0]["batches"] == 5
    assert wait[0]["wait_ns"] >= 0


def test_non_operator_stage_stays_out_of_event_log(tmp_path):
    """emit_events=False (tools/pipeline_bench driven in-process by
    bench.py): the synthetic stage's deliberate sleep-stalls must not
    land in an active engine event log, where profile_report's
    'pipeline stages' roll-up would misattribute them to real
    boundaries."""
    import json
    import sys
    from pathlib import Path

    from spark_rapids_tpu.obs import events as obs_events
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import pipeline_bench  # noqa: E402
    obs_events.enable(str(tmp_path), "MODERATE")
    try:
        out = pipeline_bench.run_bench(items=3, produce_s=0.001,
                                       consume_s=0.001, depth=2)
    finally:
        obs_events.reset_event_bus()
    assert out["items"] == 3
    recs = [json.loads(ln) for f in tmp_path.glob("*.jsonl")
            for ln in f.read_text().splitlines()]
    assert not [r for r in recs
                if r["kind"].startswith("pipeline_")]  # log uncontaminated


# -- cross-thread re-entrant semaphore --------------------------------------

def test_semaphore_shared_permit_across_threads():
    """Two threads racing a task's FIRST acquire take ONE permit; the
    re-entrant call from a second thread is free."""
    from spark_rapids_tpu.memory.semaphore import reset_tpu_semaphore
    sem = reset_tpu_semaphore(1)
    done = []

    def worker():
        assert sem.acquire_if_necessary(42)
        done.append(threading.current_thread().name)

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
    assert len(done) == 3          # nobody deadlocked on a 1-permit sem
    assert sem.available == 0      # exactly one permit taken
    sem.release_if_necessary(42)
    assert sem.available == 1
    reset_tpu_semaphore()


def test_semaphore_cancellable_wait():
    from spark_rapids_tpu.memory.semaphore import reset_tpu_semaphore
    sem = reset_tpu_semaphore(1)
    assert sem.acquire_if_necessary(1)
    stop = threading.Event()
    out = []
    t = threading.Thread(
        target=lambda: out.append(
            sem.acquire_if_necessary(2, cancel=stop.is_set)))
    t.start()
    stop.set()
    t.join(5)
    assert out == [False]
    assert not sem.held_by(2)      # no permit, no stale holder record
    sem.release_if_necessary(1)
    assert sem.acquire_if_necessary(2)  # task 2 can acquire normally now
    sem.release_if_necessary(2)
    reset_tpu_semaphore()


def test_semaphore_release_during_blocked_first_acquire_leaks_no_permit():
    """release_if_necessary (task end) while another thread's FIRST
    acquire for that task is still blocked for a permit: the
    late-landing acquire must hand its permit straight back and report
    failure — keeping it would leak the permit forever (the ended task
    never releases again)."""
    from spark_rapids_tpu.memory.semaphore import reset_tpu_semaphore
    sem = reset_tpu_semaphore(1)
    assert sem.acquire_if_necessary(1)   # exhaust the only permit
    out = []
    t = threading.Thread(
        target=lambda: out.append(sem.acquire_if_necessary(2)))
    t.start()
    for _ in range(500):                 # until t's first acquire is in
        with sem._lock:                  # flight (registered, blocked)
            if sem._holders.get(2) is not None:
                break
        time.sleep(0.01)
    else:
        pytest.fail("first acquire never registered")
    sem.release_if_necessary(2)          # task 2 ends while t is blocked
    sem.release_if_necessary(1)          # a permit frees up; t's acquire
    t.join(5)                            # lands and must give it back
    assert out == [False]
    assert sem.available == 1            # nothing leaked
    assert not sem.held_by(2)
    assert sem.acquire_if_necessary(2)   # fresh lifecycle still works
    sem.release_if_necessary(2)
    reset_tpu_semaphore()


def test_semaphore_abandoned_waiters_do_not_reacquire():
    """Waiters parked BEHIND a task's blocked first acquire when
    release_if_necessary (task end) lands must not re-race a fresh
    acquire for the dead task — the owner's hand-back alone is not
    enough: a re-racing waiter would install a new hold and take a
    permit nobody ever releases."""
    from spark_rapids_tpu.memory.semaphore import reset_tpu_semaphore
    sem = reset_tpu_semaphore(1)
    assert sem.acquire_if_necessary(1)   # exhaust the only permit
    out = []
    threads = [threading.Thread(
        target=lambda: out.append(sem.acquire_if_necessary(2)))
        for _ in range(3)]               # 1 first-acquire owner + 2 waiters
    for t in threads:
        t.start()
    for _ in range(500):                 # until the first acquire is in
        with sem._lock:                  # flight (registered, blocked)
            if sem._holders.get(2) is not None:
                break
        time.sleep(0.01)
    else:
        pytest.fail("first acquire never registered")
    sem.release_if_necessary(2)          # task 2 ends while all blocked
    sem.release_if_necessary(1)          # a permit frees up
    for t in threads:
        t.join(5)
    assert out == [False, False, False]  # nobody acquired for the dead task
    assert sem.available == 1            # nothing leaked
    assert not sem.held_by(2)
    assert sem.acquire_if_necessary(2)   # fresh lifecycle still works
    sem.release_if_necessary(2)
    reset_tpu_semaphore()


# -- background spill writeback ---------------------------------------------

@pytest.fixture
def spill_env(tmp_path):
    from spark_rapids_tpu.memory.budget import reset_memory_budget
    from spark_rapids_tpu.memory.catalog import reset_buffer_catalog
    prev_conf = C.active_conf()

    def setup(async_write, host_limit="4g"):
        C.set_active_conf(C.RapidsConf({
            "spark.rapids.tpu.spill.asyncWrite": async_write,
            "spark.rapids.memory.host.spillStorageSize": host_limit,
            "spark.rapids.memory.spillDirectory": str(tmp_path),
        }))
        reset_memory_budget(512 * 1024)
        return reset_buffer_catalog()

    yield setup
    C.set_active_conf(prev_conf)
    reset_buffer_catalog()
    reset_memory_budget()


def _batch(n, seed=0):
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.types import LONG, Schema
    return ColumnarBatch.from_pydict(
        {"a": list(range(seed, seed + n))}, Schema.of(a=LONG))


def test_async_writeback_host_hop_roundtrip(spill_env):
    from spark_rapids_tpu.memory.catalog import StorageTier
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True)
    sb = SpillableBatch.from_batch(_batch(128))
    cat.synchronous_spill(None)
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    # acquire blocks until the in-flight device->host copy lands, then
    # promotes back — identical data, async or not
    assert sb.get_batch().to_pydict()["a"][:3] == [0, 1, 2]
    assert cat.tier_of(sb._handle) == StorageTier.DEVICE
    sb.release()
    sb.close()


def test_async_writeback_disk_hop_is_durable(spill_env, tmp_path):
    from spark_rapids_tpu.memory.catalog import StorageTier
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True, host_limit="1k")
    sb = SpillableBatch.from_batch(_batch(128))
    cat.synchronous_spill(None)  # device -> host -> (1k limit) -> disk
    assert cat.tier_of(sb._handle) == StorageTier.DISK
    cat.drain_writeback()
    assert list(tmp_path.glob("spill-*.npz"))  # written + fsync'd
    assert sb.get_batch().to_pydict()["a"][5] == 5
    sb.release()
    sb.close()


def test_remove_during_inflight_writeback_leaks_nothing(spill_env,
                                                        tmp_path):
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True, host_limit="1k")
    sb = SpillableBatch.from_batch(_batch(256))
    cat.synchronous_spill(None)
    sb.close()  # remove while to_host/to_disk jobs may still be queued
    cat.drain_writeback()
    assert cat.num_entries() == 0
    assert not list(tmp_path.glob("spill-*.npz"))  # file discarded


def test_sync_vs_async_spill_same_data(spill_env):
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    out = {}
    for mode in (False, True):
        cat = spill_env(mode, host_limit="1k")
        sb = SpillableBatch.from_batch(_batch(200, seed=7))
        cat.synchronous_spill(None)
        out[mode] = sb.get_batch().to_pydict()["a"]
        sb.release()
        sb.close()
    assert out[True] == out[False] == list(range(7, 207))


def test_spill_events_out_collects_own_hops(spill_env):
    """synchronous_spill(events_out=...) hands back the completion
    events of exactly the device->host copies IT queued; once those are
    set the spilled bytes are out of the budget — the surface
    budget.reserve uses to avoid draining the whole writer queue under
    pressure."""
    from spark_rapids_tpu.memory.budget import memory_budget
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True)
    sbs = [SpillableBatch.from_batch(_batch(256, seed=i)) for i in range(4)]
    assert memory_budget().used > 0
    events = []
    freed = cat.synchronous_spill(None, events_out=events)
    assert freed > 0 and len(events) == 4
    for ev in events:
        assert ev.wait(5)
    assert memory_budget().used == 0  # every copy landed -> bytes freed
    for sb in sbs:
        sb.close()


def test_spill_for_retry_waits_out_async_writebacks(spill_env):
    """Between OOM retries spill_for_retry must leave the budget
    actually freed, not just hand hops to the writer: the TpuRetryOOM
    that triggered it can come from reserve(wait_for_writeback=False)
    (unspill under the catalog lock — cannot drain itself), whose
    pressure only clears when the writer lands the copies. A
    non-waiting spill_for_retry lets the retry loop spin through all
    its attempts in microseconds while the bytes it needs are still
    queued behind the writer, failing a query asyncWrite=false would
    have completed."""
    from spark_rapids_tpu.memory.budget import (memory_budget,
                                                spill_for_retry)
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    spill_env(True)
    sbs = [SpillableBatch.from_batch(_batch(256, seed=i)) for i in range(4)]
    assert memory_budget().used > 0
    spill_for_retry()
    assert memory_budget().used == 0   # copies LANDED before returning
    for sb in sbs:
        sb.close()


def test_failed_async_host_hop_restores_entry_and_counters(spill_env,
                                                           monkeypatch):
    """A d2h copy failure on the writer puts the entry back on DEVICE
    intact AND un-counts the spill, so a retried (healthy) spill of the
    same entry is reported exactly once."""
    import jax
    from spark_rapids_tpu.memory.catalog import StorageTier
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True)
    sb = SpillableBatch.from_batch(_batch(64))
    real_device_get = jax.device_get

    def boom(x):
        raise RuntimeError("injected d2h failure")

    monkeypatch.setattr(jax, "device_get", boom)
    cat.synchronous_spill(None)
    cat.drain_writeback()
    monkeypatch.setattr(jax, "device_get", real_device_get)
    assert cat.tier_of(sb._handle) == StorageTier.DEVICE
    assert cat.spilled_device_bytes == 0       # the hop never happened
    cat.synchronous_spill(None)                # retry, now healthy
    cat.drain_writeback()
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    assert cat.spilled_device_bytes == cat.size_of(sb._handle)
    assert sb.get_batch().to_pydict()["a"][:3] == [0, 1, 2]
    sb.release()
    sb.close()


def test_failed_async_disk_hop_restores_counters(spill_env, monkeypatch):
    """A disk-write failure keeps the entry on HOST (partial file
    dropped) and un-counts the host->disk hop; a later healthy pass
    counts it exactly once."""
    from spark_rapids_tpu.memory import catalog as cat_mod
    from spark_rapids_tpu.memory.catalog import StorageTier
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True, host_limit="1k")
    sb = SpillableBatch.from_batch(_batch(128))
    real_write = cat_mod._write_npz

    def boom(path, host_leaves):
        raise OSError("injected disk-full")

    monkeypatch.setattr(cat_mod, "_write_npz", boom)
    cat.synchronous_spill(None)   # device -> host -> (1k limit) -> disk
    cat.drain_writeback()
    monkeypatch.setattr(cat_mod, "_write_npz", real_write)
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    assert cat.spilled_host_bytes == 0         # the disk hop never landed
    assert cat.spilled_device_bytes == cat.size_of(sb._handle)
    cat.synchronous_spill(None)                # host limit re-enforced
    cat.drain_writeback()
    assert cat.tier_of(sb._handle) == StorageTier.DISK
    assert cat.spilled_host_bytes == cat.size_of(sb._handle)
    assert sb.get_batch().to_pydict()["a"][5] == 5
    sb.release()
    sb.close()


# -- engine-level equality --------------------------------------------------

@pytest.fixture(scope="module")
def q_files(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("pipe_q")
    rng = np.random.default_rng(3)
    n_l, n_o = 4000, 500
    lines = pa.table({
        "l_key": pa.array(rng.integers(0, n_o, n_l), pa.int64()),
        "l_val": pa.array(rng.random(n_l) * 100.0, pa.float64()),
        "l_flag": pa.array(rng.integers(0, 4, n_l), pa.int64()),
    })
    orders = pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(rng.integers(0, 10, n_o), pa.int64()),
    })
    lp, op = str(d / "lines.parquet"), str(d / "orders.parquet")
    pq.write_table(lines, lp, row_group_size=512)
    pq.write_table(orders, op, row_group_size=128)
    return lp, op


def _drive_query(lp, op, settings):
    """scan -> filter -> join -> agg -> sort through the session."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col, lit
    from spark_rapids_tpu.api.session import TpuSession
    sess = TpuSession(settings)
    lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
    orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                  (F.count(), "cnt"))
    return agg.sort(("rev", False)).collect()


def test_cache_materialization_under_one_permit_no_deadlock():
    """A cached relation materializes by driving a full child plan —
    whose own SourceScanExec needs an admission permit — from inside
    the outer scan's producer. With concurrentGpuTasks=1 that nested
    acquire deadlocked until the producer learned to pre-materialize
    exec-driving sources BEFORE taking its permit."""
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.memory.semaphore import reset_tpu_semaphore
    from spark_rapids_tpu.types import LONG, Schema, StructField
    reset_tpu_semaphore(1)
    try:
        sess = TpuSession()
        sch = Schema((StructField("k", LONG),))
        df = sess.from_pydict({"k": list(range(200))}, sch, batch_rows=64)
        cached = df.filter(col("k") < 150).cache()
        out = {}
        done = threading.Event()

        def drive():  # a deadlock must fail the test, not hang the suite
            out["rows"] = cached.collect()
            done.set()

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        assert done.wait(60), "cache materialization deadlocked"
        t.join(5)
        assert len(out["rows"]) == 150
        assert not _pipeline_threads()
    finally:
        reset_tpu_semaphore()


def test_host_shuffle_limit_short_circuit_thread_hygiene():
    """A LIMIT that abandons host-shuffle partition streams mid-read
    must join the pipelined readers BEFORE the shuffle files are
    unregistered (part_stream closes its inner reader first) and leak
    no pipeline threads."""
    from spark_rapids_tpu.api.functions import col  # noqa: F401
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import LONG, Schema, StructField
    rng = np.random.default_rng(9)
    ldata = {"k": [int(x) for x in rng.integers(0, 10, 200)],
             "v": [int(x) for x in rng.integers(0, 50, 200)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 10, 150)],
             "w": [int(x) for x in rng.integers(0, 9, 150)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", LONG)))
    sess = TpuSession({
        "spark.rapids.sql.shuffle.partitions": "3",
        "spark.rapids.sql.broadcastSizeThreshold": "-1",
    })
    left = sess.from_pydict(ldata, lsch, batch_rows=32)
    right = sess.from_pydict(rdata, rsch, batch_rows=32)
    out = left.join(right, on="k").limit(5).collect()
    assert len(out) == 5
    assert not _pipeline_threads()


def test_engine_equality_pipeline_on_off(q_files):
    lp, op = q_files
    on = _drive_query(lp, op, {"spark.rapids.tpu.pipeline.enabled": True})
    off = _drive_query(lp, op, {"spark.rapids.tpu.pipeline.enabled": False})
    assert on == off
    assert len(on) > 0
    assert not _pipeline_threads()


def _rows_equal_float_tolerant(xs, ys, float_cols=(1,)):
    """Exact on keys/counts, 1e-9-relative on float sums: under a
    forced-spill budget the OOM-retry SPLIT points depend on thread
    interleaving, so float reduction order may differ between runs —
    the engine's documented improvedFloatOps divergence class. Integer
    results must still match bit-exactly."""
    if len(xs) != len(ys):
        return False
    for x, y in zip(xs, ys):
        for i, (a, b) in enumerate(zip(x, y)):
            if i in float_cols:
                if abs(a - b) > 1e-9 * max(abs(a), abs(b), 1.0):
                    return False
            elif a != b:
                return False
    return True


# moved to the slow tier by ISSUE 13 budget relief (11s: async-spill
# equality also exercised by the forced-spill recipes in
# test_partition_split/test_upload; pipeline on/off equality stays)
@pytest.mark.slow
def test_engine_equality_async_spill_on_off(q_files, tmp_path):
    """Forced-spill budget: the whole query runs under a budget small
    enough that coalesce/join staging spills; results are identical
    with background writeback on and off (float sums to reduction-order
    tolerance — see _rows_equal_float_tolerant)."""
    from spark_rapids_tpu.memory.budget import reset_memory_budget
    from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                                 reset_buffer_catalog)
    lp, op = q_files
    prev = C.active_conf()
    results = {}
    spilled = {}
    try:
        for mode in (True, False):
            reset_buffer_catalog()
            reset_memory_budget(192 * 1024)  # fits one batch, not the query
            results[mode] = _drive_query(lp, op, {
                "spark.rapids.tpu.spill.asyncWrite": mode,
                "spark.rapids.memory.spillDirectory": str(tmp_path),
            })
            spilled[mode] = buffer_catalog().spilled_device_bytes
    finally:
        C.set_active_conf(prev)
        reset_buffer_catalog()
        reset_memory_budget()
    assert _rows_equal_float_tolerant(results[True], results[False])
    assert spilled[True] > 0 and spilled[False] > 0  # the budget DID bite


# -- shared multi-file decode pool ------------------------------------------

def test_threaded_chunks_shared_pool_and_conf_window():
    """ISSUE 3 satellite: one process-wide decode pool (sized by
    multiThreadedRead.numThreads, grow-only) instead of a pool per
    call, and a conf-driven fetch-ahead window."""
    from spark_rapids_tpu.io import multifile

    p1 = multifile.shared_read_pool(4)
    assert multifile.shared_read_pool(2) is p1   # smaller ask reuses
    assert multifile.shared_read_pool(4) is p1

    # in-order emission with a small explicit window
    tasks = [lambda i=i: i for i in range(20)]
    assert list(multifile.threaded_chunks(tasks, 4, window=3)) \
        == list(range(20))

    # repeated drives don't multiply pool threads (the old per-call
    # ThreadPoolExecutor did)
    for _ in range(5):
        list(multifile.threaded_chunks(tasks, 4, window=4))
    decode_threads = [t for t in threading.enumerate()
                      if t.name.startswith("multifile-read")]
    assert len(decode_threads) <= 8

    conf = C.RapidsConf(
        {"spark.rapids.sql.multiThreadedRead.fetchAheadWindow": "5"})
    assert multifile.fetch_ahead_window(4, conf) == 5
    assert multifile.fetch_ahead_window(4, C.RapidsConf()) == 8  # 2 x n


def test_sync_stage_close_closes_source_generator():
    state = {"closed": False}

    def gen():
        try:
            yield 1
            yield 2
        finally:
            state["closed"] = True

    stage = pipelined(gen(), depth=0)  # synchronous degradation
    assert next(stage) == 1
    stage.close()
    assert state["closed"]


def test_cancelled_is_false_outside_producer_threads():
    from spark_rapids_tpu.exec.pipeline import cancelled
    assert cancelled() is False


def test_wall_metric_accumulates_on_finish():
    from spark_rapids_tpu.exec.base import TpuMetric
    wall = TpuMetric("pipelineWallNs")
    stage = pipelined(iter(range(3)), depth=2, wall_metric=wall)
    try:
        assert list(stage) == [0, 1, 2]
    finally:
        stage.close()
    assert wall.value > 0
    assert stage.wall_ns >= stage.wait_ns  # wall bounds the stall


def test_no_events_when_bus_disabled(tmp_path):
    from spark_rapids_tpu.obs import events as obs_events
    obs_events.reset_event_bus()
    stage = pipelined(iter(range(3)), depth=2, label="no-bus")
    assert list(stage) == [0, 1, 2]
    stage.close()
    assert not list(tmp_path.iterdir())  # nothing written anywhere


def test_nested_stage_abandonment_propagates_cancellation():
    """An outer stage's producer may itself be blocked pulling from an
    INNER stage (planner stacks become nested stages): abandoning the
    outer one must still tear everything down — the inner consumer
    polls its thread's cancel event, and the unwinding source generator
    closes the inner stage."""
    inner_state = {"closed": False}

    def inner_src():
        try:
            while True:
                yield 1
        finally:
            inner_state["closed"] = True

    def outer_src(inner):
        try:
            for x in inner:
                yield x
        finally:
            inner.close()

    inner = pipelined(inner_src(), depth=1, label="inner")
    outer = pipelined(outer_src(inner), depth=1, label="outer")
    assert next(outer) == 1
    outer.close()
    assert not _pipeline_threads()
    assert inner_state["closed"]


def test_scan_producer_releases_permit_between_batches():
    """SourceScanExec holds the admission permit only around one
    batch's decode+upload — a scan idling on its full prefetch queue
    must not starve other tasks of the semaphore."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.basic import SourceScanExec
    from spark_rapids_tpu.memory.semaphore import (reset_tpu_semaphore,
                                                   tpu_semaphore)
    from spark_rapids_tpu.types import LONG, Schema

    sem = reset_tpu_semaphore(1)
    schema = Schema.of(a=LONG)
    produced = threading.Event()

    class Src:
        def batches(self):
            for i in range(3):
                yield ColumnarBatch.from_pydict({"a": [i]}, schema)
                produced.set()

    scan = SourceScanExec(Src(), schema)
    it = scan.execute()
    first = next(it)
    assert first.num_rows_host == 1
    produced.wait(5)
    # with depth=2 the producer has prefetched ahead and is now idle:
    # another task must be able to take the single permit
    deadline = 100
    while sem.available == 0 and deadline:
        threading.Event().wait(0.02)
        deadline -= 1
    assert sem.available == 1
    assert tpu_semaphore().acquire_if_necessary(999)
    tpu_semaphore().release_if_necessary(999)
    it.close()
    assert not _pipeline_threads()
    reset_tpu_semaphore()


# -- review hardening: cancellation vs end-of-stream, race-loser wait -------

def test_cancelled_consumer_raises_error_not_end_of_stream():
    """A consumer running on a closed outer stage's producer thread
    must see StageCancelled, NOT a bare StopIteration — downstream code
    that materializes its input (CachedRelation) would otherwise treat
    the truncated stream as complete."""
    from spark_rapids_tpu.exec import pipeline as P
    cancel = threading.Event()
    cancel.set()

    def src():
        yield 1
        yield 2
        # park until THIS stage is closed (the producer-side cancel),
        # so the consumer deterministically finds the queue empty
        while not P.cancelled():
            time.sleep(0.005)

    stage = pipelined(src(), depth=1, label="inner")
    got, err = [], []

    def consume():
        P._tls.cancel_event = cancel
        try:
            for x in stage:
                got.append(x)
        except BaseException as e:  # noqa: BLE001 — asserting the type
            err.append(e)
        finally:
            P._tls.cancel_event = None
            stage.close()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(30)
    assert not t.is_alive()
    assert got == [1, 2][:len(got)]  # a strict prefix, never junk
    assert len(err) == 1 and isinstance(err[0], P.StageCancelled)
    assert not _pipeline_threads()


def test_cancel_does_not_truncate_cached_relation():
    """Regression: the cancel cut used to raise StopIteration, so
    CachedRelation._materialize caching on a cancelled producer thread
    stored the PARTIAL stream as the complete relation — every later
    scan of the cached DataFrame silently returned truncated results."""
    from spark_rapids_tpu.exec import pipeline as P
    from spark_rapids_tpu.exec.cache import CachedRelation
    from spark_rapids_tpu.types import LONG, Schema
    sch = Schema.of(a=LONG)
    cancel = threading.Event()
    cancel.set()

    def src():
        yield _batch(4)
        yield _batch(4, seed=4)
        while not P.cancelled():
            time.sleep(0.005)

    class ChildExec:
        def execute(self):
            stage = pipelined(src(), depth=1, label="inner")
            try:
                yield from stage
            finally:
                stage.close()

    rel = CachedRelation(lambda: ChildExec(), sch)
    err = []

    def drive():
        P._tls.cancel_event = cancel
        try:
            rel.ensure_materialized()
        except BaseException as e:  # noqa: BLE001 — asserting the type
            err.append(e)
        finally:
            P._tls.cancel_event = None

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    t.join(30)
    assert not t.is_alive()
    assert len(err) == 1 and isinstance(err[0], P.StageCancelled)
    assert not rel.is_materialized  # never cache a truncated stream
    assert not _pipeline_threads()


def test_semaphore_race_loser_records_wait_time(monkeypatch):
    """The thread that LOSES a task's first-acquire race parks in the
    waiter loop; its blocked time must land in total_wait_ns (and emit
    a semaphore_acquire event) just like the winner's does."""
    from spark_rapids_tpu.memory import semaphore as S
    from spark_rapids_tpu.obs import events as obs_events
    calls = []
    monkeypatch.setattr(obs_events, "emit",
                        lambda kind, **kw: calls.append((kind, kw)))
    sem = S.reset_tpu_semaphore(1)
    in_wait = threading.Event()

    class SpyEvent(threading.Event):
        def wait(self, timeout=None):
            in_wait.set()  # the loser reached the waiter loop
            return super().wait(timeout)

    # hand-install task 7's in-flight first acquire (what a racing
    # winner holds), so this thread deterministically loses the race
    hold = S._TaskHold()
    hold.ready = SpyEvent()
    sem._holders[7] = hold
    out = []
    t = threading.Thread(
        target=lambda: out.append(sem.acquire_if_necessary(7)),
        daemon=True)
    t.start()
    assert in_wait.wait(10)
    with sem._lock:  # the winner's acquire lands
        hold.count = 1
    hold.ready.set()
    t.join(10)
    assert out == [True]
    assert sem.total_wait_ns > 0
    assert any(k == "semaphore_acquire" and kw["wait_ns"] > 0
               for k, kw in calls)
    S.reset_tpu_semaphore()


def test_unspill_failure_drops_failing_piece(monkeypatch):
    """A staged shuffle piece whose host->device promotion fails must
    still be closed (its catalog entry dropped) — not just the
    unreached tail of the partition."""
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.types import LONG, Schema
    sch = Schema.of(a=LONG)

    class FakePiece:
        def __init__(self, fail=False):
            self.fail = fail
            self.closed = False

        def get_batch(self):
            if self.fail:
                raise RuntimeError("promotion failed")
            return _batch(4)

        def release(self):
            pass

        def close(self):
            self.closed = True

    ok, bad, tail = FakePiece(), FakePiece(fail=True), FakePiece()
    ex = ShuffleExchangeExec([], InMemoryScanExec([], sch))
    it = ex._drain_partition([ok, bad, tail], sch)
    assert next(it).num_rows_host == 4
    with pytest.raises(RuntimeError, match="promotion failed"):
        list(it)
    assert ok.closed and bad.closed and tail.closed
    assert not _pipeline_threads()


def test_writer_shutdown_then_spill_starts_fresh_writer(spill_env):
    """shutdown_writer detaches the queue under the catalog lock; a
    spill after (or racing) the detach starts a FRESH writer instead of
    enqueueing onto a queue whose writer already exited — that hop's
    completion event would never fire and acquire() would hang."""
    from spark_rapids_tpu.memory.catalog import StorageTier
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    cat = spill_env(True)
    sb = SpillableBatch.from_batch(_batch(64))
    cat.synchronous_spill(None)
    cat.drain_writeback()
    cat.shutdown_writer()
    sb2 = SpillableBatch.from_batch(_batch(64, seed=100))
    cat.synchronous_spill(None)  # must revive the writer
    done = threading.Event()
    out = {}

    def fetch():  # a hang must fail the test, not wedge the suite
        out["batch"] = sb2.get_batch()
        done.set()

    t = threading.Thread(target=fetch, daemon=True)
    t.start()
    assert done.wait(60), "acquire hung on a dead writer queue"
    assert out["batch"].to_pydict()["a"][:2] == [100, 101]
    sb2.release()
    sb2.close()
    sb.close()
