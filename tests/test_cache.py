"""In-memory table cache tests (reference ParquetCachedBatchSerializer /
GpuInMemoryTableScanExec, cache_test.py in integration tests)."""

import numpy as np
import pytest


def _sorted(rows):
    return sorted(rows, key=repr)

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField


def _df(sess, n=300):
    rng = np.random.default_rng(0)
    data = {"k": [int(x) for x in rng.integers(0, 10, n)],
            "s": [None if x % 5 == 0 else f"row-{x}"
                  for x in rng.integers(0, 50, n)]}
    sch = Schema((StructField("k", LONG), StructField("s", STRING)))
    return sess.from_pydict(data, sch, batch_rows=64)


def test_cache_roundtrip_and_single_materialization():
    sess = TpuSession()
    base = _df(sess).filter(col("k") < 7)
    cached = base.cache()
    rel = cached._cached_relation
    assert not rel.is_materialized
    first = cached.collect()
    assert rel.is_materialized
    assert _sorted(first) == _sorted(base.collect())
    frames_before = rel.compressed_bytes
    # second action re-reads the cache (no re-materialization)
    again = cached.group_by("k").agg((F.count(), "c")).collect()
    assert rel.compressed_bytes == frames_before
    expect = {}
    for k, _ in first:
        expect[k] = expect.get(k, 0) + 1
    assert dict(again) == expect


@pytest.mark.slow  # ~6s; compression detail nightly, roundtrip kept tier-1 (round-7 budget move)
def test_cache_is_compressed():
    sess = TpuSession()
    cached = _df(sess, 2000).cache()
    cached.collect()
    rel = cached._cached_relation
    assert 0 < rel.compressed_bytes < rel.raw_bytes


def test_unpersist_then_recompute():
    sess = TpuSession()
    cached = _df(sess).cache()
    r1 = cached.collect()
    cached.unpersist()
    assert not cached._cached_relation.is_materialized
    assert _sorted(cached.collect()) == _sorted(r1)
