"""Straggler & stall shield (ISSUE 20): progress-watchdog units
(fire/re-arm, the retry-seam verdict consumed at the next cancellation
checkpoint, cancel), the deterministic `delay` fault kind, speculative
shuffle sub-reads (bound floor, first-result-wins race, slot denial,
both-fail error identity, and the e2e speculation-win drive under
injected delay with ZERO whole-plan retries), the dispatch hang bound
(timed_call + breaker domain override + the ledger chokepoint), and
dead-peer map-output invalidation through the partition-granular
recompute lane.

Deterministic on single-core CPU: stalls are real frozen contexts with
generous multiples of tiny windows; the injected straggler is the
seeded `kind=delay` plan (max=1 — the `spec:`-salted duplicate draws
from an exhausted budget, so the duplicate is provably fast)."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from spark_rapids_tpu import QueryCancelledError
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import lifecycle, speculation_shield
from spark_rapids_tpu.exec.speculation_shield import (ProgressWatchdog,
                                                      ReadSpeculation,
                                                      dispatch_domain,
                                                      current_dispatch_domain,
                                                      timed_call,
                                                      watchdog_for)
from spark_rapids_tpu.faults import (DispatchTimeoutError,
                                     QueryStalledError,
                                     TpuTaskRetryError)
from spark_rapids_tpu.obs import events
from spark_rapids_tpu.parallel import heartbeat
from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager
from spark_rapids_tpu.shuffle.manager import (HostShuffleReader,
                                              HostShuffleWriter,
                                              partition_batch_host,
                                              shuffle_manager)
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.types import LONG, Schema

FAST = {
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
    "spark.rapids.tpu.retry.backoffMs": "1",
}


@pytest.fixture(autouse=True)
def _isolation():
    """Every test starts with zeroed shield counters, no heartbeat
    manager, no injection, no governed contexts, the conf restored."""
    prev = C.active_conf()
    faults.install(None)
    lifecycle.reset_lifecycle()
    speculation_shield.reset_shield()
    heartbeat.install(None)
    yield
    faults.install(None)
    lifecycle.reset_lifecycle()
    speculation_shield.reset_shield()
    heartbeat.install(None)
    C.set_active_conf(prev)


@pytest.fixture
def spy(monkeypatch):
    rows = []
    real = events.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy_emit)
    return rows


def _kinds(rows, kind):
    return [r for r in rows if r["kind"] == kind]


def _wait_for(pred, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return pred()


# ---------------------------------------------------------------------------
# progress watchdog
# ---------------------------------------------------------------------------

def test_watchdog_disabled_by_default():
    ctx = lifecycle.QueryContext()
    assert watchdog_for(ctx, C.active_conf()) is None
    conf = C.RapidsConf({"spark.rapids.tpu.stall.timeoutMs": "0"})
    assert watchdog_for(ctx, conf) is None


def test_watchdog_fires_and_rearms_on_a_frozen_seam(spy):
    """A context advancing no batches/rows for the window fires ONE
    query_stalled per episode and re-arms — a query frozen for several
    windows reports several episodes, not a storm per poll."""
    ctx = lifecycle.QueryContext()
    ctx.current_op = "HashAggregateExec"
    dog = ProgressWatchdog(ctx, 50, "report")
    dog.start()
    try:
        assert _wait_for(lambda: len(_kinds(spy, "query_stalled")) >= 2)
    finally:
        dog.stop()
    evs = _kinds(spy, "query_stalled")
    assert len(evs) >= 2
    first = evs[0]
    assert first["action"] == "report"
    assert first["seam"] == "HashAggregateExec"
    assert first["attempt"] == 1
    assert first["stalled_ms"] >= 50 and first["timeout_ms"] == 50
    # the poll cadence is ~4x the window: episodes must be ~one per
    # window, not one per poll tick
    assert speculation_shield.counters()["stalls"] == len(evs)
    # report action never touches the query
    assert not ctx.cancelled() and not ctx.stall_retry


def test_watchdog_stays_quiet_while_progress_flows(spy):
    ctx = lifecycle.QueryContext()
    ctx.root_op_id = 7
    dog = ProgressWatchdog(ctx, 60, "report")
    dog.start()
    try:
        end = time.monotonic() + 0.3
        while time.monotonic() < end:
            ctx.note_batch("ScanExec", 7, 10)  # root output: progress
            time.sleep(0.01)
    finally:
        dog.stop()
    assert _kinds(spy, "query_stalled") == []
    assert speculation_shield.counters()["stalls"] == 0


def test_watchdog_retry_seam_fails_the_attempt_transiently(spy):
    """stall.action=retry-seam: the watchdog flags the attempt; the
    NEXT cancellation checkpoint raises QueryStalledError — a
    task-lane (transient) error consumed once, so the retried attempt
    starts clean."""
    ctx = lifecycle.QueryContext()
    dog = ProgressWatchdog(ctx, 50, "retry-seam")
    dog.start()
    try:
        assert _wait_for(lambda: ctx.stall_retry)
    finally:
        dog.stop()
    with pytest.raises(QueryStalledError) as ei:
        ctx.check("compute")
    assert isinstance(ei.value, TpuTaskRetryError)  # task-retry lane
    ctx.check("compute")  # the flag was consumed: attempt runs clean
    assert speculation_shield.counters()["stall_retries"] >= 1
    assert _kinds(spy, "query_stalled")[0]["action"] == "retry-seam"


def test_watchdog_cancel_action_cancels_through_the_token(spy):
    ctx = lifecycle.QueryContext()
    dog = ProgressWatchdog(ctx, 50, "cancel")
    dog.start()
    try:
        assert _wait_for(ctx.cancelled)
    finally:
        dog.stop()
    assert ctx.reason == "stalled"
    with pytest.raises(QueryCancelledError):
        ctx.check("compute")
    assert speculation_shield.counters()["stall_cancels"] >= 1


# ---------------------------------------------------------------------------
# the deterministic `delay` fault kind (the injected straggler)
# ---------------------------------------------------------------------------

def test_delay_kind_sleeps_without_failing():
    faults.install("shuffle.fetch:prob=1,seed=3,kind=delay,ms=60,max=1")
    t0 = time.monotonic()
    assert faults.apply("shuffle.fetch", b"abc", key="k") == b"abc"
    assert time.monotonic() - t0 >= 0.055  # slept, data untouched
    t1 = time.monotonic()
    faults.apply("shuffle.fetch", b"abc", key="k2")  # budget exhausted
    assert time.monotonic() - t1 < 0.05
    assert faults.active_plan().stats()["shuffle.fetch"] == 1


def test_delay_kind_requires_positive_ms():
    with pytest.raises(ValueError):
        faults.parse_faults("shuffle.fetch:prob=1,seed=1,kind=delay")


# ---------------------------------------------------------------------------
# speculative sub-reads: policy units
# ---------------------------------------------------------------------------

def test_bound_floor_and_measured_growth():
    spec = ReadSpeculation(3.0, 100, 2)
    assert spec.bound_ms("fetch") == 100  # cold histogram: the floor
    for _ in range(64):
        with spec._lock:
            spec._hists["fetch"].add(400)
    assert spec.bound_ms("fetch") > 100  # p95 x multiplier took over
    assert spec.bound_ms("decode") == 100  # stages measure separately


def test_fast_primary_never_speculates(spy):
    spec = ReadSpeculation(3.0, 50, 2)
    with ThreadPoolExecutor(1) as pool:
        out = spec.resolve("fetch", pool.submit(lambda: "ok"),
                           launch=lambda: pytest.fail("speculated"),
                           key="m0:0")
    assert out == "ok"
    assert speculation_shield.counters()["spec_launched"] == 0
    assert _kinds(spy, "speculative_fetch") == []


def test_straggling_primary_races_one_duplicate_spec_wins(spy):
    release = threading.Event()
    spec = ReadSpeculation(3.0, 20, 2)
    with ThreadPoolExecutor(2) as pool:
        primary = pool.submit(release.wait, 10)
        try:
            out = spec.resolve(
                "fetch", primary,
                launch=lambda: pool.submit(lambda: "dup"), key="m0:0")
        finally:
            release.set()
    assert out == "dup"
    c = speculation_shield.counters()
    assert c["spec_launched"] == 1 and c["spec_wins"] == 1
    assert c["spec_wait_ns"] > 0  # post-bound exposure accrued
    (ev,) = _kinds(spy, "speculative_fetch")
    assert ev["winner"] == "spec" and ev["stage"] == "fetch"
    assert ev["key"] == "m0:0" and ev["bound_ms"] >= 20


def test_denied_straggler_waits_out_its_primary(spy):
    assert speculation_shield._take_slot(1)  # occupy the only slot
    try:
        spec = ReadSpeculation(3.0, 10, 1)
        with ThreadPoolExecutor(1) as pool:
            primary = pool.submit(lambda: (time.sleep(0.1), "slow")[1])
            out = spec.resolve("fetch", primary,
                               launch=lambda: pytest.fail("no slot"),
                               key="m0:0")
    finally:
        speculation_shield._release_slot()
    assert out == "slow"
    c = speculation_shield.counters()
    assert c["spec_denied"] == 1 and c["spec_launched"] == 0
    assert c["spec_wait_ns"] > 0  # denial still measures the exposure
    assert _kinds(spy, "speculative_fetch") == []


def test_both_attempts_failing_surfaces_the_primary_error(spy):
    def slow_boom():
        time.sleep(0.05)
        raise ValueError("primary fault identity")

    def fast_boom():
        raise RuntimeError("duplicate fault")

    spec = ReadSpeculation(3.0, 10, 2)
    with ThreadPoolExecutor(2) as pool:
        with pytest.raises(ValueError, match="primary fault identity"):
            spec.resolve("fetch", pool.submit(slow_boom),
                         launch=lambda: pool.submit(fast_boom),
                         key="m0:0")
    (ev,) = _kinds(spy, "speculative_fetch")
    assert ev["winner"] == "none"
    c = speculation_shield.counters()
    assert c["spec_wins"] == 0 and c["spec_primary_wins"] == 0


# ---------------------------------------------------------------------------
# e2e: the speculation-win drive (acceptance criterion)
# ---------------------------------------------------------------------------

def _shuffle_query_data():
    rng = np.random.default_rng(7)
    data = {"k": [int(x) for x in rng.integers(0, 50, 2000)],
            "v": [int(x) for x in rng.integers(0, 1000, 2000)]}
    oracle = {}
    for k, v in zip(data["k"], data["v"]):
        oracle[k] = oracle.get(k, 0) + v
    return data, sorted(oracle.items())


def test_injected_straggler_loses_the_race_zero_plan_retries(spy):
    """ISSUE 20 acceptance: a seeded `delay` straggler on ONE shuffle
    fetch is raced by a speculative duplicate (the `spec:` salt draws
    from the exhausted max=1 budget, so the duplicate is provably
    fast), results equal the numpy oracle, and the whole-plan retry
    lane never fires."""
    data, oracle = _shuffle_query_data()
    settings = dict(FAST, **{
        "spark.rapids.sql.shuffle.partitions": "3",
        "spark.rapids.sql.broadcastSizeThreshold": "-1",
        "spark.rapids.tpu.test.faults":
            "shuffle.fetch:prob=1,seed=1,kind=delay,ms=400,max=1",
        "spark.rapids.tpu.shuffle.speculation.enabled": "true",
        "spark.rapids.tpu.shuffle.speculation.minMs": "50",
    })
    sess = TpuSession(settings)
    df = sess.from_pydict(data, Schema.of(k=LONG, v=LONG),
                          batch_rows=500)
    got = sorted(df.group_by("k").agg((F.sum("v"), "s")).collect())
    assert got == oracle
    c = speculation_shield.counters()
    assert c["spec_wins"] >= 1, "the duplicate never won the race"
    wins = [e for e in _kinds(spy, "speculative_fetch")
            if e["winner"] == "spec"]
    assert wins and wins[0]["stage"] == "fetch"
    assert _kinds(spy, "task_retry") == [], \
        "a straggler must not burn a whole-plan attempt"
    assert _kinds(spy, "query_stalled") == []


# ---------------------------------------------------------------------------
# dispatch hang bound
# ---------------------------------------------------------------------------

def test_timed_call_passthrough_and_error_relay():
    assert timed_call(lambda: 7, 1000, "device_dispatch", "x") == 7
    with pytest.raises(KeyError):
        timed_call(lambda: {}["missing"], 1000, "device_dispatch", "x")
    assert speculation_shield.counters()["dispatch_timeouts"] == 0


def test_timed_call_timeout_classifies_transient_and_trips_breaker(spy):
    """A wedged call past the bound raises DispatchTimeoutError (task
    lane), emits dispatch_timeout with its domain, and records a
    breaker-domain failure — with breaker.threshold=1 the domain
    opens."""
    C.set_active_conf(C.RapidsConf({
        "spark.rapids.tpu.breaker.enabled": "true",
        "spark.rapids.tpu.breaker.threshold": "1",
        "spark.rapids.tpu.breaker.windowMs": "60000",
        "spark.rapids.tpu.breaker.cooldownMs": "60000",
    }))
    wedged = threading.Event()
    with pytest.raises(DispatchTimeoutError) as ei:
        timed_call(lambda: wedged.wait(10), 50, "ici_exchange", "a2a")
    wedged.set()  # unpark the abandoned helper
    assert isinstance(ei.value, TpuTaskRetryError)
    (ev,) = _kinds(spy, "dispatch_timeout")
    assert ev["domain"] == "ici_exchange" and ev["timeout_ms"] == 50
    assert speculation_shield.counters()["dispatch_timeouts"] == 1
    assert "ici_exchange" in lifecycle.open_breakers()


def test_dispatch_domain_override_nests_and_restores():
    assert current_dispatch_domain() == "device_dispatch"
    with dispatch_domain("ici_exchange"):
        assert current_dispatch_domain() == "ici_exchange"
        with dispatch_domain("device_dispatch"):
            assert current_dispatch_domain() == "device_dispatch"
        assert current_dispatch_domain() == "ici_exchange"
    assert current_dispatch_domain() == "device_dispatch"


def test_hang_bounded_dispatch_lane_runs_queries_correctly():
    """dispatch.timeoutMs > 0 reroutes every ledger-chokepoint dispatch
    through the watchdog helper (dispatch + block_until_ready on the
    helper thread): results are unchanged and no bound trips."""
    from spark_rapids_tpu.obs import dispatch as obs_dispatch
    before = obs_dispatch.counters()["dispatches"]
    sess = TpuSession({"spark.rapids.tpu.dispatch.timeoutMs": "30000"})
    df = sess.from_pydict({"a": list(range(100))}, Schema.of(a=LONG))
    (row,) = df.agg((F.sum("a"), "s")).collect()
    assert row == (sum(range(100)),)
    assert obs_dispatch.counters()["dispatches"] > before, \
        "the timed lane never dispatched — the bound was not exercised"
    assert speculation_shield.counters()["dispatch_timeouts"] == 0


# ---------------------------------------------------------------------------
# dead-peer map-output invalidation
# ---------------------------------------------------------------------------

SCH = Schema.of(k=LONG, v=LONG)


def _write_two_maps(mgr, n_rows=64):
    handle = mgr.register(2, SCH)
    rows = []
    for map_id in range(2):
        b = ColumnarBatch.from_pydict(
            {"k": [i % 2 for i in range(n_rows)],
             "v": [map_id * 1000 + i for i in range(n_rows)]}, SCH)
        parts = partition_batch_host(
            b, np.array([i % 2 for i in range(n_rows)]), 2)
        HostShuffleWriter(handle, map_id, mgr).write([[p] for p in parts])
        rows += b.to_pylist()
    return handle, rows


def test_dead_peer_invalidates_outputs_and_recomputes_once(spy):
    """The peer_dead transition invalidates the peer's bound map
    outputs (exactly once), the next read re-executes lineage through
    the partition-granular lane (trigger=dead_peer), the lineage-less
    output falls back to its committed bytes, and the slot stays
    blacklisted until the peer re-registers."""
    mgr = shuffle_manager()
    handle, rows = _write_two_maps(mgr)
    with_lineage, without_lineage = handle.map_outputs
    saved = {p: (open(p, "rb").read(), open(p + ".index", "rb").read())
             for p in (with_lineage,)}
    recomputes = []

    def recompute():
        recomputes.append(1)
        data, idx = saved[with_lineage]
        with open(with_lineage, "wb") as f:
            f.write(data)
        with open(with_lineage + ".index", "wb") as f:
            f.write(idx)

    handle.lineage[with_lineage] = recompute
    mgr.bind_peer_output("exec-1", handle, with_lineage)
    mgr.bind_peer_output("exec-1", handle, without_lineage)
    try:
        m = HeartbeatManager(timeout_s=0.05)
        heartbeat.install(m)  # wires on_peer_dead to the shield
        m.register("exec-1")
        time.sleep(0.08)
        assert m.dead_peers() == ["exec-1"]
        # the transition hook ran: both outputs marked, exactly once
        evs = _kinds(spy, "map_output_invalidated")
        assert {e["map_path"] for e in evs} == {
            p.rsplit("/", 1)[-1] for p in handle.map_outputs}
        assert {e["has_lineage"] for e in evs} == {True, False}
        assert handle.invalidated == set(handle.map_outputs)
        c = speculation_shield.counters()
        assert c["peer_invalidations"] == 1
        assert c["outputs_invalidated"] == 2
        assert m.blacklisted_slots() == {"exec-1": 0}
        assert heartbeat.health_section()["dead"] == ["exec-1"]
        # a second poll is not a second transition
        m.dead_peers()
        assert speculation_shield.counters()["outputs_invalidated"] == 2
        # the read consumes the markers: lineage recomputes in place,
        # the lineage-less output reads its committed bytes as-is
        r = HostShuffleReader(handle, mgr)
        got = [row for p in range(2) for b in r.read_partition(p)
               for row in b.to_pylist()]
        assert sorted(got, key=repr) == sorted(rows, key=repr)
        assert recomputes == [1]
        recs = _kinds(spy, "partition_recompute")
        assert len(recs) == 1 and recs[0]["trigger"] == "dead_peer"
        assert not handle.invalidated  # all markers consumed
        assert _kinds(spy, "task_retry") == []
        # re-registration clears the blacklist (the peer is back)
        m.register("exec-1")
        assert m.blacklisted_slots() == {}
    finally:
        mgr.unregister(handle)


def test_invalidation_conf_gate_off_leaves_outputs_trusted(spy):
    C.set_active_conf(C.RapidsConf({
        "spark.rapids.tpu.shuffle.deadPeerInvalidation.enabled":
            "false"}))
    mgr = shuffle_manager()
    handle, rows = _write_two_maps(mgr)
    mgr.bind_peer_output("exec-9", handle, handle.map_outputs[0])
    try:
        m = HeartbeatManager(timeout_s=0.05)
        heartbeat.install(m)
        m.register("exec-9")
        time.sleep(0.08)
        assert m.dead_peers() == ["exec-9"]
        assert handle.invalidated == set()
        assert _kinds(spy, "map_output_invalidated") == []
        assert speculation_shield.counters()["peer_invalidations"] == 0
    finally:
        mgr.unregister(handle)


def test_session_health_reports_peer_section():
    out = TpuSession({}).health()["peers"]
    assert out == {"enabled": False, "live": [], "dead": [],
                   "purged": 0, "blacklisted_slots": {}}
    m = HeartbeatManager()
    heartbeat.install(m)
    m.register("e1")
    out = TpuSession({}).health()["peers"]
    assert out["enabled"] is True and out["live"] == ["e1"]
    assert out["dead"] == [] and out["blacklisted_slots"] == {}
