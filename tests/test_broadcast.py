"""Broadcast exchange + broadcast hash join planning tests (reference
GpuBroadcastExchangeExec.scala:352, GpuBroadcastHashJoinExecBase,
Spark JoinSelection's autoBroadcastJoinThreshold)."""

import numpy as np
import pytest

import jax

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _sorted(rows):
    return sorted(rows, key=lambda r: tuple((x is None, x) for x in r))


LSCH = Schema((StructField("k", LONG), StructField("lv", LONG)))
RSCH = Schema((StructField("k", LONG), StructField("rv", STRING)))


def _frames(sess, nl=200, nr=10):
    rng = np.random.default_rng(11)
    l = sess.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 20, nl)],
         "lv": [int(x) for x in rng.integers(0, 1000, nl)]},
        LSCH, batch_rows=64)
    r = sess.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 20, nr)],
         "rv": [f"r{i}" for i in range(nr)]}, RSCH)
    return l, r


def test_small_build_side_plans_broadcast():
    sess = TpuSession()
    l, r = _frames(sess)
    tree = l.join(r, on="k")._exec().tree_string()
    assert "BroadcastExchangeExec" in tree
    assert "build=right" in tree


@needs_8
def test_broadcast_beats_shuffle_when_small():
    """With a mesh active, a small build side must still broadcast (no
    exchange of the big stream side)."""
    sess = TpuSession(mesh_devices=8)
    l, r = _frames(sess)
    tree = l.join(r, on="k")._exec().tree_string()
    assert "BroadcastExchangeExec" in tree
    assert "ShuffleExchangeExec" not in tree


@needs_8
def test_large_build_side_shuffles():
    sess = TpuSession({"spark.rapids.sql.broadcastSizeThreshold": "1"},
                      mesh_devices=8)
    l, r = _frames(sess)
    tree = l.join(r, on="k")._exec().tree_string()
    assert "BroadcastExchangeExec" not in tree
    assert "ShuffledHashJoinExec" in tree


def test_broadcast_disabled():
    sess = TpuSession({"spark.rapids.sql.broadcastSizeThreshold": "-1"})
    l, r = _frames(sess)
    tree = l.join(r, on="k")._exec().tree_string()
    assert "BroadcastExchangeExec" not in tree


def test_broadcast_left_for_right_outer():
    sess = TpuSession()
    rng = np.random.default_rng(3)
    small = sess.from_pydict({"k": [1, 2], "lv": [10, 20]}, LSCH)
    big = sess.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 5, 300)],
         "rv": [f"r{i}" for i in range(300)]}, RSCH, batch_rows=64)
    tree = small.join(big, on="k", how="right_outer")._exec().tree_string()
    assert "BroadcastExchangeExec" in tree
    assert "build=left" in tree


@pytest.mark.parametrize("how", ["inner", "left_outer", "left_semi",
                                 "left_anti"])
def test_broadcast_join_results_match(how):
    bcast = TpuSession()
    plain = TpuSession({"spark.rapids.sql.broadcastSizeThreshold": "-1"})

    def run(sess):
        l, r = _frames(sess)
        return _sorted(l.join(r, on="k", how=how).collect())

    assert run(bcast) == run(plain)


def test_broadcast_materializes_once():
    sess = TpuSession()
    l, r = _frames(sess)
    exec_tree = l.join(r, on="k")._exec()

    def find(node):
        from spark_rapids_tpu.exec.exchange import BroadcastExchangeExec
        if isinstance(node, BroadcastExchangeExec):
            return node
        for c in node.children:
            got = find(c)
            if got is not None:
                return got
        return None

    bx = find(exec_tree)
    assert bx is not None
    first = bx.materialize()
    assert bx.materialize() is first


def test_broadcast_nested_loop_join():
    sess = TpuSession()
    l = sess.from_pydict({"k": [1, 2, 3], "lv": [10, 20, 30]}, LSCH)
    r = sess.from_pydict({"k": [7, 8], "rv": ["a", "b"]}, RSCH)
    df = l.join(r.select(col("k").alias("k2"), col("rv")), how="cross")
    tree = df._exec().tree_string()
    assert "BroadcastExchangeExec" in tree
    assert len(df.collect()) == 6
