"""Parquet row-group pruning, coalescing reader, zero-copy string
ingestion (reference GpuParquetScan.scala:1860 predicate pushdown,
GpuMultiFileReader.scala:830 COALESCING)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.io.parquet import ParquetSource


@pytest.fixture(scope="module")
def sorted_file(tmp_path_factory):
    """10 row groups of 100 rows each, k ascending 0..999 (so min/max
    stats segment cleanly)."""
    path = str(tmp_path_factory.mktemp("pq") / "sorted.parquet")
    t = pa.table({"k": pa.array(range(1000), pa.int64()),
                  "s": pa.array([f"val_{i:04d}" for i in range(1000)])})
    pq.write_table(t, path, row_group_size=100)
    return path


def test_pruning_counts_row_groups(sorted_file):
    src = ParquetSource(sorted_file, filters=[("k", ">=", 700)])
    rows = sum(b.num_rows_host for b in src.batches())
    assert src.row_groups_pruned == 7
    assert src.row_groups_read == 3
    assert rows == 300  # groups are read whole; the Filter trims exactly


def test_pruning_equality_and_ranges(sorted_file):
    src = ParquetSource(sorted_file, filters=[("k", "==", 250)])
    list(src.batches())
    assert src.row_groups_read == 1
    src = ParquetSource(sorted_file, filters=[("k", "<", 100)])
    list(src.batches())
    assert src.row_groups_read == 1
    src = ParquetSource(sorted_file,
                        filters=[("k", ">=", 100), ("k", "<", 300)])
    list(src.batches())
    assert src.row_groups_read == 2


def test_pruning_never_wrong(sorted_file):
    """Pruned scan + Filter gives exactly the unpruned answer."""
    sess = TpuSession()
    df = sess.read_parquet(sorted_file).filter(col("k") >= 700)
    got = sorted(df.collect())
    assert got == [(k, f"val_{k:04d}") for k in range(700, 1000)]


def test_pushdown_through_planner(sorted_file):
    sess = TpuSession()
    df = sess.read_parquet(sorted_file)
    src = df._plan.source
    out = df.filter((col("k") >= 850) & (col("s") != "zz")).collect()
    assert sorted(r[0] for r in out) == list(range(850, 1000))
    # planner pushed (k >= 850); the != conjunct stays filter-only
    assert src.row_groups_pruned == 8
    assert src.row_groups_read == 2


def test_pushdown_disabled_conf(sorted_file):
    sess = TpuSession(
        {"spark.rapids.sql.format.parquet.filterPushdown.enabled": False})
    df = sess.read_parquet(sorted_file)
    src = df._plan.source
    df.filter(col("k") >= 850).collect()
    assert src.row_groups_pruned == 0


def test_coalescing_reader(sorted_file):
    multi = ParquetSource(sorted_file, reader_type="MULTITHREADED")
    coal = ParquetSource(sorted_file, reader_type="COALESCING")
    mb = list(multi.batches())
    cb = list(coal.batches())
    assert len(cb) < len(mb)  # 10 row groups stitched into one upload
    flat = lambda bs: [r for b in bs for r in b.to_pylist()]
    assert sorted(flat(cb)) == sorted(flat(mb))


def test_string_ingestion_zero_copy_paths(sorted_file):
    """Arrow-buffer ingestion: nulls, slices, empty strings, multibyte."""
    from spark_rapids_tpu.columnar.column import column_from_arrow
    vals = ["", "abc", None, "é中", "x" * 50, None, "tail"]
    arr = pa.array(vals, pa.string())
    c = column_from_arrow(arr)
    assert c.to_pylist(len(vals)) == vals
    # sliced array (non-zero offset)
    sl = arr.slice(2, 4)
    c2 = column_from_arrow(sl)
    assert c2.to_pylist(4) == vals[2:6]
    # large_string
    c3 = column_from_arrow(arr.cast(pa.large_string()))
    assert c3.to_pylist(len(vals)) == vals
    # chunked
    ch = pa.chunked_array([arr, arr])
    c4 = column_from_arrow(ch)
    assert c4.to_pylist(2 * len(vals)) == vals + vals


def test_roundtrip_via_session(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "rt.parquet")
    data = {"a": [1, 2, None, 4], "s": ["x", None, "zz", ""]}
    from spark_rapids_tpu.types import LONG, STRING, Schema, StructField
    sch = Schema((StructField("a", LONG), StructField("s", STRING)))
    sess.from_pydict(data, sch).write_parquet(path)
    got = sess.read_parquet(path).collect()
    assert got == list(zip(data["a"], data["s"]))
