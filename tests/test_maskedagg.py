"""Masked-bucket aggregation kernel + speculative execution + whole-stage
fusion (ops/maskedagg.py, exec/speculation.py, exec/aggregate.py).

Oracle pattern mirrors the reference's CPU-vs-GPU equality testing
(SparkQueryCompareTestSuite.scala): every result is checked against an
independent numpy/python aggregation of the same data.
"""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec, ProjectExec
from spark_rapids_tpu.exec.speculation import speculation_scope
from spark_rapids_tpu.expr.aggexprs import (
    Average, Count, First, Last, Max, Min, Sum,
)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, Schema, StructField,
)


def _oracle_groupby(keys, vals):
    out = {}
    for k, v in zip(keys, vals):
        e = out.setdefault(k, [0, 0, None, None])
        e[1] += 1
        if v is not None:
            e[0] += v
            e[2] = v if e[2] is None else min(e[2], v)
            e[3] = v if e[3] is None else max(e[3], v)
    return out


def _run_agg(keys, vals, key_type=LONG, batches=1):
    sch = Schema((StructField("k", key_type), StructField("v", LONG)))
    n = len(keys)
    per = max(1, n // batches)
    bs = []
    for i in range(0, n, per):
        bs.append(ColumnarBatch.from_pydict(
            {"k": keys[i:i + per], "v": vals[i:i + per]}, sch))
    plan = AggregateExec(
        [col("k")],
        [(Sum(col("v")), "s"), (Count(), "c"),
         (Min(col("v")), "mn"), (Max(col("v")), "mx")],
        InMemoryScanExec(bs, sch))
    rows = plan.collect()
    return {r[0]: (r[1], r[2], r[3], r[4]) for r in rows}


def _check(keys, vals, **kw):
    got = _run_agg(keys, vals, **kw)
    want = _oracle_groupby(keys, vals)
    assert set(got) == set(want), (set(got), set(want))
    for k, (s, c2, mn, mx) in want.items():
        gs, gc, gmn, gmx = got[k]
        assert gc == c2, (k, got[k], want[k])
        assert gs == (s if c2 and any(
            v is not None for kk, v in zip(keys, vals) if kk == k) else gs)
        assert gmn == mn and gmx == mx, (k, got[k], want[k])


def test_low_cardinality():
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 5, 500).tolist()
    vals = rng.integers(-100, 100, 500).tolist()
    _check(keys, vals)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_high_cardinality_falls_back_exact():
    # cardinality >> bucketSlots * bucketRounds: fast path must flag and
    # the plan re-run must still be exact
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 400, 2000).tolist()
    vals = rng.integers(-50, 50, 2000).tolist()
    _check(keys, vals)


def test_null_keys_and_values():
    keys = [1, None, 2, None, 1, 2, None, 3]
    vals = [10, 20, None, 40, 50, 60, None, None]
    got = _run_agg(keys, vals)
    assert got[None] == (60, 3, 20, 40)
    assert got[1] == (60, 2, 10, 50)
    assert got[2] == (60, 2, 60, 60)
    assert got[3][1] == 1 and got[3][0] is None  # all-null group sum


def test_multi_batch_merge():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 7, 999).tolist()
    vals = rng.integers(0, 9, 999).tolist()
    _check(keys, vals, batches=7)


def test_float_keys_nan_normalization():
    sch = Schema((StructField("k", DOUBLE), StructField("v", LONG)))
    keys = [1.5, float("nan"), -0.0, 0.0, float("nan"), 1.5]
    vals = [1, 2, 3, 4, 5, 6]
    b = ColumnarBatch.from_pydict({"k": keys, "v": vals}, sch)
    plan = AggregateExec([col("k")], [(Sum(col("v")), "s")],
                         InMemoryScanExec([b], sch))
    rows = plan.collect()
    got = {}
    for k, s in rows:
        key = "nan" if (k is not None and k != k) else k
        got[key] = s
    # Spark: all NaNs one group; -0.0 == 0.0
    assert got["nan"] == 7
    assert got[0.0] == 7
    assert got[1.5] == 7
    assert len(rows) == 3


def test_speculation_scope_trips_and_rerun_matches():
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 500, 3000).tolist()
    vals = rng.integers(0, 100, 3000).tolist()
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    b = ColumnarBatch.from_pydict({"k": keys, "v": vals}, sch)
    plan = AggregateExec([col("k")], [(Sum(col("v")), "s")],
                         InMemoryScanExec([b], sch))
    with speculation_scope() as scope:
        list(plan.execute())
        assert scope.tripped()  # 500 distinct > 32*2 slots
    # collect() transparently re-runs exact
    want = {}
    for k, v in zip(keys, vals):
        want[k] = want.get(k, 0) + v
    got = dict(plan.collect())
    assert got == want


@pytest.mark.slow  # ~5s; fusion equality nightly, pallas_fused equality kept tier-1 (round-7 budget move)
def test_fused_filter_project_agg_matches_unfused():
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    rng = np.random.default_rng(5)
    n = 4096
    k = rng.integers(0, 6, n).tolist()
    q = rng.integers(1, 51, n).tolist()
    p = (rng.random(n) * 100).tolist()
    sch = Schema((StructField("k", INT), StructField("q", LONG),
                  StructField("p", DOUBLE)))

    def build():
        b = ColumnarBatch.from_pydict({"k": k, "q": q, "p": p}, sch)
        scan = InMemoryScanExec([b], sch)
        filt = FilterExec(col("q") <= lit(40), scan)
        proj = ProjectExec([col("k"), col("q"),
                            (col("p") * lit(2.0)).alias("p2")], filt)
        return AggregateExec(
            [col("k")],
            [(Sum(col("q")), "sq"), (Sum(col("p2")), "sp"),
             (Count(), "c"), (Average(col("p2")), "avg")], proj)

    fused = build()
    assert fused._fused_steps, "fusion did not engage"
    got = {r[0]: r[1:] for r in fused.collect()}

    set_active_conf(RapidsConf({"spark.rapids.tpu.fusion.enabled": False}))
    try:
        unfused = build()
        assert not unfused._fused_steps
        want = {r[0]: r[1:] for r in unfused.collect()}
    finally:
        set_active_conf(RapidsConf())

    assert set(got) == set(want)
    for key in want:
        assert got[key][0] == want[key][0]  # exact int sum
        assert got[key][2] == want[key][2]  # count
        assert abs(got[key][1] - want[key][1]) < 1e-9 * max(
            1.0, abs(want[key][1]))
        assert abs(got[key][3] - want[key][3]) < 1e-9 * max(
            1.0, abs(want[key][3]))


def test_fused_count_star_with_filter_mask():
    sch = Schema((StructField("v", LONG),))
    b = ColumnarBatch.from_pydict({"v": list(range(100))}, sch)
    plan = AggregateExec(
        [], [(Count(), "c")],
        FilterExec(col("v") < lit(37), InMemoryScanExec([b], sch)))
    assert plan.collect() == [(37,)]


def test_grand_aggregate_over_large_batch():
    # count(*) with no input columns must not be capped by any bucket
    sch = Schema((StructField("v", LONG),))
    n = 1000
    b = ColumnarBatch.from_pydict({"v": list(range(n))}, sch)
    plan = AggregateExec([], [(Count(), "c"), (Sum(col("v")), "s")],
                         InMemoryScanExec([b], sch))
    assert plan.collect() == [(n, n * (n - 1) // 2)]


def test_first_last_in_masked_path():
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    b = ColumnarBatch.from_pydict(
        {"k": [1, 1, 2, 2, 1], "v": [None, 10, 20, None, 30]}, sch)
    plan = AggregateExec(
        [col("k")], [(First(col("v"), ignore_nulls=True), "f"),
         (Last(col("v"), ignore_nulls=True), "l")],
        InMemoryScanExec([b], sch))
    got = {r[0]: r[1:] for r in plan.collect()}
    assert got[1] == (10, 30)
    assert got[2] == (20, 20)


@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_more_than_16_key_columns():
    # beyond the 16-column packed-stats code word: the per-column boolean
    # reductions path must kick in, not an assert/overflow
    n_keys = 17
    sch = Schema(tuple(StructField(f"k{i}", LONG) for i in range(n_keys))
                 + (StructField("v", LONG),))
    data = {f"k{i}": [1, 1, 2, None] for i in range(n_keys)}
    data["v"] = [10, 20, 30, 40]
    b = ColumnarBatch.from_pydict(data, sch)
    plan = AggregateExec(
        [col(f"k{i}") for i in range(n_keys)],
        [(Sum(col("v")), "s")], InMemoryScanExec([b], sch))
    got = sorted(plan.collect(), key=lambda r: (r[0] is None, r[0] or 0))
    assert [r[-1] for r in got] == [30, 30, 40]
