"""Dedicated tests for the `output_grouped_by` grouped-output contract
(ISSUE 1 satellite, VERDICT r5 Weak #2): the inner join's key-grouped
emission hint flows through projections into the aggregate's sort-skip
(pre_grouped) tier — a WRONG hint silently mis-aggregates, so the edge
cases must be pinned:

- a computed alias REUSING a key name must drop the hint (the projected
  column no longer carries the join key's grouping);
- duplicate output names must drop the hint (the name no longer
  identifies one column);
- grouping by a SUBSET of the join keys must NOT take the sort-skip
  tier (joint-tuple contiguity does not imply per-key contiguity) yet
  still aggregate correctly;
- a bare rename / duplication of a key keeps the hint and the sort-skip
  tier stays bit-correct.

Path under test: exec/joins.HashJoinExec.output_grouped_by ->
exec/basic.ProjectExec.output_grouped_by ->
exec/aggregate.AggregateExec._input_pre_grouped.
"""

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import InMemoryScanExec, ProjectExec
from spark_rapids_tpu.exec.joins import HashJoinExec
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField

L_SCHEMA = Schema((StructField("lk", LONG), StructField("lk2", INT),
                   StructField("v", DOUBLE)))
R_SCHEMA = Schema((StructField("rk", LONG), StructField("rk2", INT),
                   StructField("w", LONG)))


def _data(n_l=360, n_r=120, dom=30, dom2=3, seed=0):
    rng = np.random.default_rng(seed)
    l = {"lk": rng.integers(0, dom, n_l).tolist(),
         "lk2": rng.integers(0, dom2, n_l).tolist(),
         "v": (rng.random(n_l) * 10).round(6).tolist()}
    r = {"rk": rng.integers(0, dom, n_r).tolist(),
         "rk2": rng.integers(0, dom2, n_r).tolist(),
         "w": rng.integers(0, 100, n_r).tolist()}
    return l, r


def _scans(l, r):
    lb = ColumnarBatch.from_pydict(l, L_SCHEMA)
    rb = ColumnarBatch.from_pydict(r, R_SCHEMA)
    return (InMemoryScanExec([lb], L_SCHEMA),
            InMemoryScanExec([rb], R_SCHEMA))


def _oracle(l, r, keys, one_key_join=True):
    """numpy oracle of join-then-group-by: {key tuple: (sum v, count)}."""
    out = {}
    for i in range(len(l["lk"])):
        for j in range(len(r["rk"])):
            if l["lk"][i] != r["rk"][j]:
                continue
            if not one_key_join and l["lk2"][i] != r["rk2"][j]:
                continue
            row = {"lk": l["lk"][i], "lk2": l["lk2"][i], "v": l["v"][i],
                   "rk": r["rk"][j], "rk2": r["rk2"][j], "w": r["w"][j]}
            k = tuple(row[x] for x in keys)
            s, c = out.get(k, (0.0, 0))
            out[k] = (s + row["v"], c + 1)
    return out


def _check(agg, l, r, keys, one_key_join=True):
    got = {}
    for row in agg.collect():
        got[tuple(row[:len(keys)])] = (row[len(keys)], row[len(keys) + 1])
    exp = _oracle(l, r, keys, one_key_join)
    assert set(got) == set(exp)
    for k in exp:
        assert abs(got[k][0] - exp[k][0]) <= 1e-9 * max(abs(exp[k][0]), 1)
        assert got[k][1] == exp[k][1]


def _agg(child, keys):
    return AggregateExec([col(k) for k in keys],
                         [(Sum(col("v")), "s"), (Count(), "c")], child)


def test_single_key_join_hint_and_sort_skip_correct():
    l, r = _data()
    ls, rs = _scans(l, r)
    join = HashJoinExec(ls, rs, [col("lk")], [col("rk")], "inner")
    hint = join.output_grouped_by
    assert hint == (frozenset({"lk", "rk"}),)
    agg = _agg(join, ["lk"])
    assert agg._pre_grouped  # the sort-skip tier engages...
    _check(agg, l, r, ["lk"])  # ...and is bit-correct


# moved to the slow tier by ISSUE 13 budget relief (6s: hint-drop
# variant; the join-hint + sort-skip contract single stays tier-1)
@pytest.mark.slow
def test_computed_alias_reusing_key_name_drops_hint():
    """project (lk + 1) AS lk: the output column named 'lk' is NOT the
    join key anymore — the hint must vanish and the aggregate must use
    its sorting tier (pre_grouped False) with correct results."""
    l, r = _data(seed=1)
    ls, rs = _scans(l, r)
    join = HashJoinExec(ls, rs, [col("lk")], [col("rk")], "inner")
    proj = ProjectExec([(col("lk") + lit(1)).alias("lk"), col("v")], join)
    assert proj.output_grouped_by is None
    agg = _agg(proj, ["lk"])
    assert not agg._pre_grouped
    got = {row[0]: (row[1], row[2]) for row in agg.collect()}
    exp_raw = _oracle(l, r, ["lk"])
    exp = {k[0] + 1: v for k, v in exp_raw.items()}
    assert set(got) == set(exp)
    for k in exp:
        assert got[k][1] == exp[k][1]
        assert abs(got[k][0] - exp[k][0]) <= 1e-9 * max(abs(exp[k][0]), 1)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_duplicate_output_names_cannot_reach_the_hint():
    """Both join key columns named 'k': the duplicate-name hazard the
    hint guards against (out_names.count(n) == 1 in joins.py) cannot
    materialize as a schema — the engine rejects duplicate names at the
    Schema level, so a raw same-name join fails loudly instead of
    emitting an ambiguous hint; the session surface reaches the same
    shape via the USING-join rename, where the hint stays precise and
    the sort-skip aggregation stays correct."""
    l, r = _data(seed=2)
    l_schema = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    r_schema = Schema((StructField("k", LONG), StructField("w", LONG)))
    lb = ColumnarBatch.from_pydict({"k": l["lk"], "v": l["v"]}, l_schema)
    rb = ColumnarBatch.from_pydict({"k": r["rk"], "w": r["w"]}, r_schema)
    join = HashJoinExec(InMemoryScanExec([lb], l_schema),
                        InMemoryScanExec([rb], r_schema),
                        [col("k")], [col("k")], "inner")
    with pytest.raises(AssertionError, match="duplicate column names"):
        join.output_schema  # noqa: B018 — the access IS the assertion

    # the session-level USING join renames the right key before joining;
    # the surviving single 'k' keeps the grouping contract end to end
    from spark_rapids_tpu.api.session import TpuSession
    sess = TpuSession()
    df_l = sess.from_pydict({"k": l["lk"], "v": l["v"]}, l_schema)
    df_r = sess.from_pydict({"k": r["rk"], "w": r["w"]}, r_schema)
    j = df_l.join(df_r, on="k", how="inner")
    got = {}
    for row in (j.group_by("k")
                 .agg((Sum(col("v")), "s"), (Count(), "c")).collect()):
        got[row[0]] = (row[1], row[2])
    exp = {k[0]: v for k, v in _oracle(l, r, ["lk"]).items()}
    assert set(got) == set(exp)
    for k in exp:
        assert got[k][1] == exp[k][1]
        assert abs(got[k][0] - exp[k][0]) <= 1e-9 * max(abs(exp[k][0]), 1)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_subset_of_keys_grouping_skips_sort_skip_but_stays_correct():
    """Two-key join emits (lk,lk2)-tuple-grouped batches; grouping by lk
    ALONE must not claim pre_grouped (tuple contiguity does not give
    per-key contiguity), and grouping by both keys may."""
    l, r = _data(seed=3)
    ls, rs = _scans(l, r)
    join = HashJoinExec(ls, rs, [col("lk"), col("lk2")],
                        [col("rk"), col("rk2")], "inner")
    assert join.output_grouped_by == (frozenset({"lk", "rk"}),
                                      frozenset({"lk2", "rk2"}))
    sub = _agg(join, ["lk"])
    assert not sub._pre_grouped
    _check(sub, l, r, ["lk"], one_key_join=False)

    ls2, rs2 = _scans(l, r)
    join2 = HashJoinExec(ls2, rs2, [col("lk"), col("lk2")],
                         [col("rk"), col("rk2")], "inner")
    full = _agg(join2, ["lk", "lk2"])
    assert full._pre_grouped
    _check(full, l, r, ["lk", "lk2"], one_key_join=False)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_bare_rename_keeps_hint_through_projection():
    """SELECT lk AS g, lk, v: the grouping class maps to {g, lk}; a
    group-by on the rename keeps the sort-skip tier and stays correct."""
    l, r = _data(seed=4)
    ls, rs = _scans(l, r)
    join = HashJoinExec(ls, rs, [col("lk")], [col("rk")], "inner")
    proj = ProjectExec([col("lk").alias("g"), col("lk"), col("v")], join)
    assert proj.output_grouped_by == (frozenset({"g", "lk"}),)
    agg = _agg(proj, ["g"])
    assert agg._pre_grouped
    got = {row[0]: (row[1], row[2]) for row in agg.collect()}
    exp = {k[0]: v for k, v in _oracle(l, r, ["lk"]).items()}
    assert set(got) == set(exp)
    for k in exp:
        assert got[k][1] == exp[k][1]
        assert abs(got[k][0] - exp[k][0]) <= 1e-9 * max(abs(exp[k][0]), 1)


def test_grouping_class_vanishing_from_projection_drops_hint():
    """exec/basic.py: a projection that drops every name of a grouping
    class (here: neither lk nor rk survives) must return None."""
    l, r = _data(seed=5)
    ls, rs = _scans(l, r)
    join = HashJoinExec(ls, rs, [col("lk"), col("lk2")],
                        [col("rk"), col("rk2")], "inner")
    proj = ProjectExec([col("lk2"), col("v")], join)  # class {lk,rk} gone
    assert proj.output_grouped_by is None
