"""Test fixture: run the engine on a virtual 8-device CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (the reference's analog is
the NUM_LOCAL_EXECS pseudo-cluster, run_pyspark_from_build.sh:138).

The axon sitecustomize pins jax_platforms=axon (real TPU tunnel); tests
override it back to CPU *after* jax import — env vars alone are not enough.
CPU also gives correctly-rounded f64, the reference oracle for Spark
semantics; TPU f64 is double-float emulated (documented divergence, like the
reference's docs/compatibility.md floating-point section).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_fault_plan():
    """A chaos plan (spark.rapids.tpu.test.faults) leaked by one module
    would silently inject faults into every later suite — disarm at
    module boundaries and fail the offender loudly (ISSUE 4)."""
    from spark_rapids_tpu import faults
    faults.install(None)
    yield
    leaked = faults.active_plan()
    faults.install(None)
    assert leaked is None, (
        f"module leaked an armed fault plan: {leaked.spec_string!r}")
