"""Test fixture: run the engine on a virtual 8-device CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (the reference's analog is
the NUM_LOCAL_EXECS pseudo-cluster, run_pyspark_from_build.sh:138).

The axon sitecustomize pins jax_platforms=axon (real TPU tunnel); tests
override it back to CPU *after* jax import — env vars alone are not enough.
CPU also gives correctly-rounded f64, the reference oracle for Spark
semantics; TPU f64 is double-float emulated (documented divergence, like the
reference's docs/compatibility.md floating-point section).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_fault_plan():
    """A chaos plan (spark.rapids.tpu.test.faults) leaked by one module
    would silently inject faults into every later suite — disarm at
    module boundaries and fail the offender loudly (ISSUE 4)."""
    from spark_rapids_tpu import faults
    faults.install(None)
    yield
    leaked = faults.active_plan()
    faults.install(None)
    assert leaked is None, (
        f"module leaked an armed fault plan: {leaked.spec_string!r}")


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_workload_state():
    """Workload-governor hygiene (ISSUE 7, mirroring the lifecycle
    tripwire): a query left queued or admitted at a module boundary
    means some admitted() scope never released its ticket — later
    suites would inherit a phantom tenant whose quota share shrinks
    everyone else's. Reset at module boundaries and fail the offender
    loudly."""
    from spark_rapids_tpu.exec import workload
    workload.reset_workload()
    yield
    snap = workload.snapshot()
    workload.reset_workload()
    assert snap["queue_depth"] == 0 and snap["admitted"] == 0, (
        f"module leaked workload state: {snap['queue_depth']} queued, "
        f"{snap['admitted']} admitted")


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_staging_buffers():
    """Packed-upload staging-pool hygiene (ISSUE 10, mirroring the
    lifecycle/workload tripwires): an upload that fails to release its
    staging buffer leaks host memory forever (the pool can only reuse
    what comes back) — assert in-flight bytes return to the zero
    baseline at module boundaries and fail the offender loudly. Idle
    (pooled) buffers are the pool working as designed and may persist."""
    from spark_rapids_tpu.columnar import upload
    yield
    pool = upload.staging_pool()
    pool.settle()  # flush deferred (release-when-ready) buffers
    leaked = pool.outstanding_bytes()
    if leaked:
        upload.reset_staging_pool()
    assert leaked == 0, (
        f"module leaked {leaked} bytes of in-flight upload staging "
        f"buffers (acquire without release/discard)")


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_telemetry_state():
    """Telemetry-plane hygiene (ISSUE 11, mirroring the lifecycle/
    workload tripwires): a registry left enabled keeps a
    `telemetry-*` sampler thread alive into every later suite — reset
    at module boundaries and fail the offender loudly if its exporter
    thread survives the reset."""
    import threading

    from spark_rapids_tpu.obs import stats as runtime_stats
    from spark_rapids_tpu.obs import telemetry
    telemetry.reset_telemetry()
    runtime_stats.reset_stats()
    yield
    telemetry.reset_telemetry()
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("telemetry-") and t.is_alive()]
    assert not leaked, (
        f"module leaked telemetry exporter thread(s): {leaked}")


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_history_state():
    """Phase/history-plane hygiene (ISSUE 17, the telemetry pattern): a
    module that enabled the query-history store must not leave later
    suites appending capsules to its tmpdir (the file handle would
    outlive the tmpdir fixture), and the process-global phase counters
    must not bleed across modules' bench-delta assertions — reset both
    at module boundaries."""
    from spark_rapids_tpu.obs import history
    from spark_rapids_tpu.obs import phase
    history.reset_history()
    phase.reset_phase_counters()
    yield
    history.reset_history()
    phase.reset_phase_counters()


@pytest.fixture(scope="module", autouse=True)
def _dispatch_ledger_reset():
    """Dispatch-plane hygiene (ISSUE 13): a module that disabled the
    ledger (dispatch.ledger.enabled=false session) must not leave the
    default-on plane dark for every later suite, and a module's
    program records must not bleed into another's dispatch_summary
    assertions — reset to a fresh default-enabled ledger at module
    boundaries."""
    from spark_rapids_tpu.obs import dispatch
    dispatch.reset_dispatch_ledger()
    yield
    dispatch.reset_dispatch_ledger()


@pytest.fixture(scope="module", autouse=True)
def _stage_compiler_reset():
    """Whole-stage-compilation hygiene (ISSUE 14): the plan-fingerprint
    program-site cache (cleared with the ledger above, but only at
    reset points) and the stage counters/size caches are process-wide —
    a module asserting fresh-trace behavior or per-lane stage deltas
    must not inherit another module's warm caches."""
    from spark_rapids_tpu.exec import stage_compiler
    stage_compiler.reset_stage_counters()
    yield
    stage_compiler.reset_stage_counters()


@pytest.fixture(scope="module", autouse=True)
def _adaptive_counters_reset():
    """Adaptive-replanner hygiene (ISSUE 19, the dispatch pattern): the
    decision counters are process-wide and several suites assert exact
    deltas (skew splits taken, demotions observed) — zero them at
    module boundaries so one module's replans don't bleed into
    another's assertions."""
    from spark_rapids_tpu.exec import adaptive
    adaptive.reset_adaptive()
    yield
    adaptive.reset_adaptive()


@pytest.fixture(scope="module", autouse=True)
def _speculation_shield_reset():
    """Straggler-shield hygiene (ISSUE 20, the adaptive pattern): the
    shield counters (stalls, spec wins/denials, dispatch timeouts,
    peer invalidations) are process-wide and asserted as deltas, and a
    heartbeat manager left installed would keep routing peer_dead
    transitions into later suites — zero both at module boundaries."""
    from spark_rapids_tpu.exec import speculation_shield
    from spark_rapids_tpu.parallel import heartbeat
    speculation_shield.reset_shield()
    heartbeat.install(None)
    yield
    speculation_shield.reset_shield()
    heartbeat.install(None)


@pytest.fixture(scope="module", autouse=True)
def _no_leaked_lifecycle_state():
    """Lifecycle-governor hygiene (ISSUE 6, same pattern as the leaked
    fault plan): a breaker left open would silently demote a kernel
    tier for every later suite, and a QueryContext left registered
    means some query never unwound its governed scope — reset at module
    boundaries and fail the offender loudly."""
    from spark_rapids_tpu.exec import lifecycle
    lifecycle.reset_lifecycle()
    yield
    leaked_queries = lifecycle.active_query_ids()
    leaked_breakers = lifecycle.open_breakers()
    lifecycle.reset_lifecycle()
    assert not leaked_queries, (
        f"module leaked registered query contexts: {leaked_queries}")
    assert not leaked_breakers, (
        f"module left circuit breakers open: {leaked_breakers}")
