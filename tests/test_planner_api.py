"""Planning engine + DataFrame API tests: tagging, explain reporting,
conf-driven disables (reference GpuOverrides explain/tag semantics) and
end-to-end query execution through the session surface."""

import math

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import Expression, col, lit
from spark_rapids_tpu.plan.overrides import PlanNotSupported, TpuOverrides
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)

SCHEMA = Schema((StructField("k", STRING), StructField("v", INT),
                 StructField("d", DOUBLE)))
DATA = {
    "k": ["b", "a", None, "b", "a", "c"],
    "v": [3, 1, 7, None, 5, 2],
    "d": [1.5, 2.5, 0.5, 3.5, None, 4.5],
}


def session(**conf):
    return TpuSession(conf)


def df(sess=None, batch_rows=None):
    sess = sess or session()
    return sess.from_pydict(DATA, SCHEMA, batch_rows=batch_rows)


def test_select_filter_collect():
    got = (df().filter(col("v") > 1)
               .select(col("k"), (col("v") * 2).alias("v2"))
               .collect())
    assert sorted(got, key=repr) == sorted(
        [("b", 6), (None, 14), ("a", 10), ("c", 4)], key=repr)


def test_with_column_and_count():
    d = df().with_column("vv", col("v") + col("v"))
    assert d.columns == ["k", "d", "vv"] or "vv" in d.columns
    assert df().count() == 6


def test_groupby_agg_api():
    got = (df(batch_rows=2).group_by("k")
           .agg((F.sum("v"), "s"), (F.count(), "c"))
           .sort("k").collect())
    assert got == [(None, 7, 1), ("a", 6, 2), ("b", 3, 2), ("c", 2, 1)]


def test_join_api():
    s = session()
    other = s.from_pydict({"k2": ["a", "b"], "w": [10, 20]},
                          Schema((StructField("k2", STRING),
                                  StructField("w", INT))))
    got = (df(s).join(other, left_on=col("k"), right_on=col("k2"))
           .select("k", "v", "w").sort("k", "v").collect())
    assert got == [("a", 1, 10), ("a", 5, 10), ("b", None, 20),
                   ("b", 3, 20)]


def test_sort_limit_pushdown_topn():
    d = df().sort(("v", False)).limit(2)
    got = d.collect()
    assert [r[1] for r in got] == [7, 5]


def test_distinct():
    s = session()
    d = s.from_pydict({"x": [1, 2, 1, 3, 2]},
                      Schema((StructField("x", INT),)))
    assert sorted(r[0] for r in d.distinct().collect()) == [1, 2, 3]


def test_union_api():
    assert df().union(df()).count() == 12


def test_range():
    got = session().range(10).collect()
    assert [r[0] for r in got] == list(range(10))


def test_explain_marks_supported():
    report = df().filter(col("v") > 1).select(col("v") + 1).explain()
    assert "* Project" in report
    assert "* Filter" in report
    assert "* Scan" in report
    assert "!" not in report.replace("!=", "")


def test_explain_reports_unsupported_expression():
    class FancyExpr(Expression):
        def __init__(self, child):
            self.children = (child,)
        @property
        def data_type(self):
            return self.children[0].data_type
        def with_children(self, cs):
            return FancyExpr(cs[0])

    d = df().select(FancyExpr(col("v")))
    report = d.explain()
    assert "no TPU implementation for expression FancyExpr" in report
    with pytest.raises(PlanNotSupported) as exc:
        d.collect()
    assert "FancyExpr" in str(exc.value)


def test_conf_disable_expression():
    """Disabling a device expression moves the node to the host row
    engine (the reference's convertToCpu per-operator fallback); with
    fallback off it fails the plan with the explain report."""
    s = session(**{"spark.rapids.sql.expression.Add": "false"})
    d = s.from_pydict(DATA, SCHEMA).select(col("v") + 1)
    assert "will run on CPU" in d.explain()
    tree = d._exec().tree_string()
    assert "HostProjectExec" in tree
    assert [r[0] for r in d.collect()] == \
        [None if v is None else v + 1 for v in DATA["v"]]
    strict = session(**{"spark.rapids.sql.expression.Add": "false",
                        "spark.rapids.sql.cpuFallback.enabled": "false"})
    d2 = strict.from_pydict(DATA, SCHEMA).select(col("v") + 1)
    assert "disabled by spark.rapids.sql.expression.Add" in d2.explain()
    with pytest.raises(PlanNotSupported):
        d2.collect()


def test_conf_disable_exec():
    s = session(**{"spark.rapids.sql.exec.Sort": "false"})
    d = s.from_pydict(DATA, SCHEMA).sort("v")
    with pytest.raises(PlanNotSupported):
        d.collect()


def test_sql_enabled_off():
    s = session(**{"spark.rapids.sql.enabled": "false"})
    with pytest.raises(PlanNotSupported):
        s.from_pydict(DATA, SCHEMA).collect()


def test_to_arrow_roundtrip():
    t = df().filter(col("v") > 1).to_arrow()
    assert t.num_rows == 4
    assert set(t.column_names) == {"k", "v", "d"}


def test_string_functions_api():
    got = (df().filter(F.col("k").is_not_null() if hasattr(F.col("k"), "is_not_null")
                       else ~F.col("k").__eq__(lit(None)))
           if False else
           df().select(F.upper(F.col("k")).alias("u"),
                       F.length(F.col("k")).alias("l"))).collect()
    assert ("B", 1) in got and (None, None) in got


def test_sorted_limit_with_offset():
    # review regression: offset must survive the sort+limit TopN collapse
    s = session()
    d = s.from_pydict({"a": [5, 3, 1, 4, 2]},
                      Schema((StructField("a", INT),)))
    got = d.sort("a").limit(2, offset=1).collect()
    assert got == [(2,), (3,)]
