"""Query lifecycle governor (ISSUE 6): deadlines + cooperative
cancellation (thread hygiene asserted), partition-granular shuffle
recovery vs the whole-plan fallback, degradation circuit breakers, the
heartbeat deadlock fix, and the tooling roll-ups.

Deterministic on single-core CPU: cancellations are either self-induced
(a pandas UDF cancels its own session mid-stream) or deadline-driven
against an artificially stalled producer; breaker transitions use
injected device faults and tiny cooldowns; shuffle corruption is the
PR 4 seeded injection plan."""

import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu import QueryCancelledError
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import lifecycle
from spark_rapids_tpu.exec.task_retry import with_task_retry
from spark_rapids_tpu.memory.budget import (memory_budget,
                                            reset_memory_budget)
from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                             reset_buffer_catalog)
from spark_rapids_tpu.obs import events
from spark_rapids_tpu.types import LONG, Schema

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

FAST = {
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
    "spark.rapids.tpu.retry.backoffMs": "1",
}


def _threads():
    return {t for t in threading.enumerate()
            if t.name.startswith(("pipeline-", "spill-writer"))}


@pytest.fixture(autouse=True)
def _lifecycle_isolation():
    """Every test starts with a clean governor (no breakers, no
    contexts), injection off, the conf restored, and zero NEW
    pipeline-*/spill-writer threads leaked."""
    pre = _threads()
    prev_conf = C.active_conf()
    lifecycle.reset_lifecycle()
    faults.install(None)
    yield
    faults.install(None)
    lifecycle.reset_lifecycle()
    C.set_active_conf(prev_conf)
    assert _threads() <= pre, "leaked threads"


@pytest.fixture
def spy(monkeypatch):
    rows = []
    real = events.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy_emit)
    return rows


def _kinds(rows, kind):
    return [r for r in rows if r["kind"] == kind]


# ---------------------------------------------------------------------------
# QueryContext unit contracts
# ---------------------------------------------------------------------------

def test_context_deadline_and_tick_cadence(spy):
    ctx = lifecycle.QueryContext(timeout_ms=0, check_every=3)
    ctx.tick(); ctx.tick(); ctx.tick()  # healthy: no raise
    ctx.cancel("user")
    ctx.tick(); ctx.tick()  # below the check cadence: still no raise
    with pytest.raises(QueryCancelledError) as ei:
        ctx.tick()
    assert ei.value.phase == "compute" and ei.value.reason == "user"
    # the event is emitted exactly once, by the first checker
    with pytest.raises(QueryCancelledError):
        ctx.check("sem-wait")
    evs = _kinds(spy, "query_cancelled")
    assert len(evs) == 1 and evs[0]["phase"] == "compute"

    expired = lifecycle.QueryContext(timeout_ms=10, check_every=1)
    time.sleep(0.02)
    with pytest.raises(QueryCancelledError) as ei:
        expired.check("spill-wait")
    assert ei.value.reason == "timeout"
    assert ei.value.phase in lifecycle.CANCEL_PHASES


def test_governed_registry_and_cancel_owner():
    owner = object()
    assert lifecycle.cancel_owner(owner) == 0  # nothing running
    with lifecycle.governed(C.RapidsConf({}), owner=owner) as ctx:
        assert ctx.ctx_id in lifecycle.active_query_ids()
        assert lifecycle.current_context() is ctx
        assert lifecycle.cancel_owner(owner) == 1
        assert ctx.cancelled() and ctx.reason == "user"
        # an unrelated owner's cancel does not touch it
        assert lifecycle.cancel_owner(object()) == 0
    assert lifecycle.active_query_ids() == []
    assert lifecycle.current_context() is None


def test_check_current_is_noop_without_context():
    lifecycle.check_current("compute")  # must not raise
    assert not lifecycle.current_cancelled()


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------

BREAKER = dict(FAST, **{
    "spark.rapids.tpu.breaker.enabled": "true",
    "spark.rapids.tpu.breaker.threshold": "2",
    "spark.rapids.tpu.breaker.cooldownMs": "120",
    "spark.rapids.tpu.task.maxAttempts": "6",
    "spark.rapids.tpu.pallas.fusedTier": "on",
})


def test_breaker_disabled_by_default_records_nothing():
    C.set_active_conf(C.RapidsConf(dict(FAST)))
    for _ in range(5):
        lifecycle.record_domain_failure("pallas_fused")
    assert lifecycle.open_breakers() == []
    assert lifecycle.breaker_allows("pallas_fused")


def test_breaker_demotes_fused_tier_and_rearms_after_cooldown(spy):
    """Acceptance criterion: N injected device failures demote the
    fused-Pallas domain to XLA (fused_tier_enabled answers False with
    reason 'circuit breaker open'); after the cooldown the half-open
    probe re-engages and a successful attempt closes the breaker."""
    from spark_rapids_tpu.ops.pallas_tier import (family_may_engage,
                                                  fused_tier_enabled)
    conf = C.RapidsConf(dict(BREAKER))
    C.set_active_conf(conf)
    engagements = []

    def flaky(attempt):
        engagements.append(fused_tier_enabled("scan_agg", (1024,)))
        if attempt <= 2:
            raise faults.InjectedDeviceError("device.dispatch")
        return "ok"

    assert with_task_retry(flaky, conf=conf) == "ok"
    # attempts 1+2 engaged and failed -> breaker opens -> attempt 3
    # runs demoted on the XLA safe path
    assert engagements == [True, True, False]
    opens = _kinds(spy, "breaker_open")
    assert {e["domain"] for e in opens} == {"pallas_fused",
                                            "device_dispatch"}
    assert any(e["safe_path"] for e in opens)
    assert set(lifecycle.open_breakers()) == {"device_dispatch",
                                              "pallas_fused"}
    assert not family_may_engage("scan_agg")
    h = lifecycle.health()
    assert h["breakers"]["pallas_fused"]["state"] == "open"
    assert h["breakers"]["pallas_fused"]["trips"] == 1

    # demoted inside the cooldown window
    assert not fused_tier_enabled("scan_agg", (1024,))

    # cooldown -> half-open probe -> success closes and re-arms
    time.sleep(0.15)
    assert with_task_retry(
        lambda a: fused_tier_enabled("scan_agg", (1024,)),
        conf=conf) is True
    assert lifecycle.open_breakers() == []
    assert [e["domain"] for e in _kinds(spy, "breaker_half_open")
            if e["domain"] == "pallas_fused"] == ["pallas_fused"]
    assert [e["domain"] for e in _kinds(spy, "breaker_close")].count(
        "pallas_fused") == 1
    assert fused_tier_enabled("scan_agg", (1024,))


def test_breaker_reopens_on_failed_probe(spy):
    conf = C.RapidsConf(dict(BREAKER))
    C.set_active_conf(conf)
    from spark_rapids_tpu.ops.pallas_tier import fused_tier_enabled

    def flaky(attempt):
        engaged = fused_tier_enabled("scan_agg", (512,))
        if engaged:  # fails every time the fused tier engages
            raise faults.InjectedDeviceError("device.dispatch")
        return "xla"

    assert with_task_retry(flaky, conf=conf) == "xla"
    assert "pallas_fused" in lifecycle.open_breakers()
    time.sleep(0.15)
    # half-open probe engages, fails again -> re-open (trips == 2)
    assert with_task_retry(flaky, conf=conf) == "xla"
    assert lifecycle.health()["breakers"]["pallas_fused"]["trips"] == 2
    assert "pallas_fused" in lifecycle.open_breakers()


def test_breaker_half_open_single_probe_and_kill_switch(spy):
    """Review r4: half_open lets exactly ONE probe through (concurrent
    consults stay demoted while it is in flight), and the
    breaker.enabled kill-switch restores the accelerated path
    immediately, recorded state notwithstanding."""
    conf = C.RapidsConf(dict(BREAKER, **{
        "spark.rapids.tpu.breaker.threshold": "1",
        "spark.rapids.tpu.breaker.cooldownMs": "60"}))
    C.set_active_conf(conf)
    lifecycle.record_domain_failure("pallas_join")
    assert not lifecycle.breaker_allows("pallas_join")  # open
    time.sleep(0.08)
    assert lifecycle.breaker_allows("pallas_join")       # the probe
    assert not lifecycle.breaker_allows("pallas_join"), \
        "a second consult engaged while the probe was in flight"
    lifecycle.record_domain_success("pallas_join")       # probe passed
    assert lifecycle.breaker_allows("pallas_join")
    assert lifecycle.open_breakers() == []
    # kill-switch: an open breaker must not outlive the conf
    lifecycle.record_domain_failure("pallas_join")
    assert not lifecycle.breaker_allows("pallas_join")
    C.set_active_conf(C.RapidsConf(dict(FAST, **{
        "spark.rapids.tpu.breaker.enabled": "false"})))
    assert lifecycle.breaker_allows("pallas_join")


def test_breaker_counts_the_exhausted_final_attempt(spy):
    """Review r2: the FINAL failing attempt (the strongest persistence
    signal) must count toward the breaker before with_task_retry
    re-raises — with maxAttempts=1 it is the only signal there is."""
    from spark_rapids_tpu.ops.pallas_tier import fused_tier_enabled
    conf = C.RapidsConf(dict(BREAKER, **{
        "spark.rapids.tpu.task.maxAttempts": "1",
        "spark.rapids.tpu.breaker.threshold": "1"}))
    C.set_active_conf(conf)

    def doomed(attempt):
        assert fused_tier_enabled("scan_agg", (256,))
        raise faults.InjectedDeviceError("device.dispatch")

    with pytest.raises(faults.InjectedDeviceError):
        with_task_retry(doomed, conf=conf)
    assert "pallas_fused" in lifecycle.open_breakers()
    assert _kinds(spy, "breaker_open")


def test_cancelled_producer_never_reads_as_clean_end():
    """Review r2: a pipeline producer that stops on lifecycle
    cancellation must carry the cancellation to its consumer — a clean
    _END would let a truncated stream read as normal completion (silent
    wrong results)."""
    from spark_rapids_tpu.exec.pipeline import pipelined
    C.set_active_conf(C.RapidsConf(dict(FAST)))
    with lifecycle.governed(C.RapidsConf(dict(FAST))) as ctx:
        def src():
            yield 1
            ctx.cancel("user")  # lands between producer steps
            yield 2
            yield 3

        stage = pipelined(src(), depth=1, emit_events=False)
        got = []
        try:
            with pytest.raises(QueryCancelledError):
                for x in stage:
                    got.append(x)
        finally:
            stage.close()
        assert 3 not in got, "producer ran past the cancellation"


def test_breaker_session_health_surface(spy):
    """Session-level: a query whose guarded dispatch dies twice still
    succeeds via task retry, and health() surfaces the opened
    device_dispatch breaker."""
    settings = dict(BREAKER)
    # long cooldown: the breaker must still be OPEN when the successful
    # third attempt lands (a short one would legitimately half-open and
    # close it mid-query — compile time alone outlasts 120ms)
    settings["spark.rapids.tpu.breaker.cooldownMs"] = "60000"
    settings["spark.rapids.tpu.test.faults"] = \
        "device.dispatch:prob=1,seed=3,kind=device,max=2"
    sess = TpuSession(settings)
    df = sess.from_pydict({"a": list(range(64))}, Schema.of(a=LONG))
    out = df.agg((F.sum("a"), "s")).collect()
    assert out == [(sum(range(64)),)]
    h = sess.health()
    assert h["breakers"]["device_dispatch"]["state"] == "open"
    assert h["counters"]["breaker_open"] >= 1
    assert h["counters"]["whole_plan_retries"] >= 2
    assert _kinds(spy, "breaker_open")


# ---------------------------------------------------------------------------
# cooperative cancellation through the session (thread hygiene)
# ---------------------------------------------------------------------------

def _cancel_after(sess, k):
    """A mapInPandas fn that cancels its own session after k batches —
    a deterministic mid-query cancellation trigger."""
    seen = {"n": 0}

    def fn(it):
        for pdf in it:
            seen["n"] += 1
            if seen["n"] == k:
                assert sess.cancel_query() == 1
            yield pdf

    return fn


def _assert_clean_and_rerunnable(sess, df, spy, pre_threads):
    """Shared post-cancellation contract: the event fired, no
    robustness threads leaked, and the SAME session runs the next query
    clean (no poisoned semaphore/catalog state)."""
    evs = _kinds(spy, "query_cancelled")
    assert len(evs) == 1 and evs[0]["phase"] in lifecycle.CANCEL_PHASES
    assert _threads() <= pre_threads, "cancellation leaked threads"
    assert lifecycle.active_query_ids() == []
    follow = sess.from_pydict({"z": [1, 2, 3]}, Schema.of(z=LONG))
    assert follow.agg((F.sum("z"), "s")).collect() == [(6,)]


def test_cancel_mid_scan(spy):
    pre = _threads()
    # small coalesce target: the scan's batches must NOT collapse into
    # one, or there is no "mid"-scan left to cancel in
    sess = TpuSession(dict(FAST, **{
        "spark.rapids.tpu.query.cancelCheckBatches": "1",
        "spark.rapids.sql.batchSizeBytes": "4k"}))
    df = sess.from_pydict({"a": list(range(5000))}, Schema.of(a=LONG),
                          batch_rows=250)
    out_schema = Schema.of(a=LONG)
    with pytest.raises(QueryCancelledError) as ei:
        df.map_in_pandas(_cancel_after(sess, 2), out_schema).collect()
    assert ei.value.reason == "user"
    _assert_clean_and_rerunnable(sess, df, spy, pre)


def test_cancel_mid_shuffle_read(spy):
    """Cancellation lands while host-shuffle partition streams are
    still pending: the unwind must close the pipelined shuffle readers
    and unregister the handle."""
    pre = _threads()
    sess = TpuSession(dict(FAST, **{
        "spark.rapids.tpu.query.cancelCheckBatches": "1",
        "spark.rapids.sql.shuffle.partitions": "3",
        "spark.rapids.sql.broadcastSizeThreshold": "-1"}))
    rng = np.random.default_rng(5)
    df = sess.from_pydict(
        {"k": [int(x) for x in rng.integers(0, 40, 1200)],
         "v": [int(x) for x in rng.integers(0, 100, 1200)]},
        Schema.of(k=LONG, v=LONG), batch_rows=300)
    agg = df.group_by("k").agg((F.sum("v"), "s"))
    out_schema = Schema.of(k=LONG, s=LONG)
    with pytest.raises(QueryCancelledError):
        agg.map_in_pandas(_cancel_after(sess, 1), out_schema).collect()
    _assert_clean_and_rerunnable(sess, df, spy, pre)


def test_cancel_mid_spill_writeback(spy):
    """Cancellation under a spill-forcing budget with the async writer
    active: the unwind settles in-flight writebacks, catalog entries
    and the budget counter."""
    pre = _threads()
    prev_cat_entries = None
    try:
        reset_buffer_catalog()
        reset_memory_budget(192 * 1024)
        sess = TpuSession(dict(FAST, **{
            "spark.rapids.tpu.query.cancelCheckBatches": "1",
            "spark.rapids.tpu.spill.asyncWrite": "true",
            "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
            "spark.rapids.sql.broadcastSizeThreshold": "-1"}))
        used_before = memory_budget().used
        prev_cat_entries = buffer_catalog().num_entries()
        rng = np.random.default_rng(9)
        n_l, n_o = 6000, 300
        lines = sess.from_pydict(
            {"l_key": [int(x) for x in rng.integers(0, n_o, n_l)],
             "l_val": [int(x) for x in rng.integers(0, 100, n_l)]},
            Schema.of(l_key=LONG, l_val=LONG), batch_rows=1500)
        orders = sess.from_pydict(
            {"o_key": list(range(n_o))}, Schema.of(o_key=LONG))
        j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
        out_schema = Schema.of(l_key=LONG, l_val=LONG, o_key=LONG)
        with pytest.raises(QueryCancelledError):
            j.map_in_pandas(_cancel_after(sess, 1), out_schema).collect()
        buffer_catalog().drain_writeback()
        assert memory_budget().used == used_before, \
            "cancellation leaked budget"
        assert buffer_catalog().num_entries() == prev_cat_entries, \
            "cancellation leaked catalog entries"
        _assert_clean_and_rerunnable(sess, j, spy, pre)
    finally:
        reset_buffer_catalog()
        reset_memory_budget()


class _StallingSource:
    """batches() sleeps before every batch after the first — an
    artificially stalled producer for the deadline acceptance test."""

    def __init__(self, batches, schema, stall_s):
        self._batches = batches
        self.schema = schema
        self.stall_s = stall_s

    def batches(self):
        for i, b in enumerate(self._batches):
            if i >= 1:
                time.sleep(self.stall_s)
            yield b

    def estimated_size_bytes(self):
        return sum(b.device_size_bytes() for b in self._batches)

    def estimated_num_rows(self):
        return sum(b.num_rows_host for b in self._batches)


def test_deadline_bounds_stalled_producer(spy):
    """Acceptance criterion: a stalled producer query returns
    QueryCancelledError within timeoutMs + slack (the slack covers one
    producer step + the stage join) with zero leaked threads."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.plan import logical as L
    pre = _threads()
    schema = Schema.of(a=LONG)
    batches = [ColumnarBatch.from_pydict({"a": [i] * 64}, schema)
               for i in range(6)]
    sess = TpuSession(dict(FAST, **{
        "spark.rapids.tpu.query.timeoutMs": "300",
        "spark.rapids.tpu.query.cancelCheckBatches": "1"}))
    df = sess._df(L.LogicalScan(_StallingSource(batches, schema, 1.2)))
    t0 = time.monotonic()
    with pytest.raises(QueryCancelledError) as ei:
        df.filter(col("a") >= lit(0)).collect()
    wall = time.monotonic() - t0
    assert ei.value.reason == "timeout"
    # timeoutMs + slack: one 1.2s producer step may be in flight when
    # the deadline fires and the unwind joins it; 8s is generous slack
    # for a loaded 1-core box against the 7.2s un-cancelled runtime
    assert 0.3 <= wall < 8.0, wall
    evs = _kinds(spy, "query_cancelled")
    assert len(evs) == 1 and evs[0]["reason"] == "timeout"
    assert evs[0]["phase"] in lifecycle.CANCEL_PHASES
    assert _threads() <= pre, "deadline expiry leaked threads"
    # the same session runs the next query clean — with the deadline
    # lifted first: the 300ms budget governs EVERY collect on this
    # session, and a fresh plan's cold jit compile alone can outlast it
    # (a single-test run has no warm caches), which would measure cache
    # temperature instead of state hygiene
    sess.conf = C.RapidsConf(dict(FAST))
    ok = sess.from_pydict({"z": [4, 5]}, Schema.of(z=LONG))
    assert ok.agg((F.sum("z"), "s")).collect() == [(9,)]


def test_deadline_spans_task_retry_attempts(spy):
    """The deadline bounds the query's TOTAL wall-clock: a query whose
    attempts keep dying transiently stops retrying once the deadline
    passes (phase task-retry), instead of burning all maxAttempts."""
    conf = C.RapidsConf(dict(FAST, **{
        "spark.rapids.tpu.task.maxAttempts": "50",
        "spark.rapids.tpu.task.retryBackoffMs": "30"}))
    calls = []

    def always_transient(attempt):
        calls.append(attempt)
        raise faults.InjectedDeviceError("device.dispatch")

    with lifecycle.governed(conf, timeout_ms=120):
        with pytest.raises(QueryCancelledError) as ei:
            with_task_retry(always_transient, conf=conf)
    assert ei.value.phase == "task-retry"
    assert len(calls) < 50, "deadline did not bound the retry loop"


# ---------------------------------------------------------------------------
# partition-granular recovery
# ---------------------------------------------------------------------------

def _shuffle_query_data():
    rng = np.random.default_rng(7)
    data = {"k": [int(x) for x in rng.integers(0, 50, 2000)],
            "v": [int(x) for x in rng.integers(0, 1000, 2000)]}
    oracle = {}
    for k, v in zip(data["k"], data["v"]):
        oracle[k] = oracle.get(k, 0) + v
    return data, sorted(oracle.items())


SHUFFLED = dict(FAST, **{
    "spark.rapids.sql.shuffle.partitions": "3",
    "spark.rapids.sql.broadcastSizeThreshold": "-1",
})


def _drive_shuffled_agg(settings, data):
    sess = TpuSession(settings)
    df = sess.from_pydict(data, Schema.of(k=LONG, v=LONG),
                          batch_rows=500)
    return sorted(df.group_by("k").agg((F.sum("v"), "s")).collect())


def test_shuffle_corruption_recomputes_one_map_output(spy):
    """Acceptance criterion: one corrupted committed shuffle block
    mid-query recomputes ONE map output (the producing sub-plan), not
    the query — asserted via event counts — with results equal to the
    fault-free run (numpy oracle)."""
    data, oracle = _shuffle_query_data()
    settings = dict(SHUFFLED)
    settings["spark.rapids.tpu.test.faults"] = \
        "shuffle.decode:prob=1,seed=6,kind=corrupt,max=1"
    got = _drive_shuffled_agg(settings, data)
    assert got == oracle
    assert len(_kinds(spy, "integrity_fail")) == 1, \
        "the corruption was never read back — test lost its teeth"
    recs = _kinds(spy, "partition_recompute")
    assert len(recs) == 1
    assert recs[0]["map_path"].startswith("shuffle_")
    assert _kinds(spy, "task_retry") == [], \
        "recovery escalated to the whole-plan lane"
    assert lifecycle.counters()["partition_recompute"] == 1


# moved to the slow tier by ISSUE 13 budget relief (21s: conf-off
# fallback variant of the same recovery e2e)
@pytest.mark.slow
def test_shuffle_corruption_whole_plan_fallback_when_disabled(spy):
    """With partitionRecovery off, the same corruption takes the PR 4
    whole-plan lane — and the task_retry event now names the lane and
    the shuffle-block provenance."""
    data, oracle = _shuffle_query_data()
    settings = dict(SHUFFLED)
    settings["spark.rapids.tpu.task.partitionRecovery.enabled"] = "false"
    settings["spark.rapids.tpu.test.faults"] = \
        "shuffle.decode:prob=1,seed=6,kind=corrupt,max=1"
    got = _drive_shuffled_agg(settings, data)
    assert got == oracle
    assert _kinds(spy, "partition_recompute") == []
    evs = _kinds(spy, "task_retry")
    assert evs and evs[0]["lane"] == "whole_plan"
    assert evs[0]["provenance"]["kind"] == "shuffle_block"
    assert "map_path" in evs[0]["provenance"]


# moved to the slow tier by ISSUE 13 budget relief (23s: second-
# corruption fallback variant; the primary one-map-recompute lane
# stays tier-1)
@pytest.mark.slow
def test_repeated_corruption_of_one_map_output_falls_back(spy):
    """max=2 decode corruption hits the original block AND its
    recovered re-decode: the second failure of the same map output must
    not recompute forever — it surfaces with provenance and the
    whole-plan lane converges."""
    data, oracle = _shuffle_query_data()
    settings = dict(SHUFFLED)
    settings["spark.rapids.tpu.test.faults"] = \
        "shuffle.decode:prob=1,seed=6,kind=corrupt,max=2"
    got = _drive_shuffled_agg(settings, data)
    assert got == oracle
    assert len(_kinds(spy, "partition_recompute")) == 1  # one attempt
    evs = _kinds(spy, "task_retry")
    assert evs and evs[0]["lane"] == "whole_plan"
    assert evs[0]["provenance"]["kind"] == "shuffle_block"


def test_spill_quarantine_provenance_is_ambiguous(spy):
    """A quarantined spill file carries spill provenance (no lineage —
    intermediate state), so the task-retry event documents WHY the
    whole-plan lane ran."""
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    import tempfile
    prev = C.active_conf()
    try:
        reset_buffer_catalog()
        with tempfile.TemporaryDirectory() as d:
            C.set_active_conf(C.RapidsConf(dict(FAST, **{
                "spark.rapids.tpu.spill.asyncWrite": "false",
                "spark.rapids.memory.host.spillStorageSize": "1",
                "spark.rapids.memory.spillDirectory": d})))
            faults.install(
                "spill.disk_write:prob=1,seed=4,kind=corrupt,max=1")
            sb = SpillableBatch.from_batch(ColumnarBatch.from_pydict(
                {"a": list(range(256))}, Schema.of(a=LONG)))
            buffer_catalog().synchronous_spill(None)
            with pytest.raises(faults.IntegrityError) as ei:
                sb.get_batch()
            assert ei.value.provenance["kind"] == "spill_file"
            sb.close()
    finally:
        faults.install(None)
        C.set_active_conf(prev)
        reset_buffer_catalog()


# ---------------------------------------------------------------------------
# heartbeat satellite: deadlock fix + liveness events
# ---------------------------------------------------------------------------

def test_heartbeat_of_unknown_executor_does_not_deadlock():
    """Regression (ISSUE 6 satellite): heartbeat() used to call
    register() while holding the non-reentrant lock — an unregistered
    executor's first beat hung forever. Watchdog-timed thread proves
    the fix."""
    from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager
    m = HeartbeatManager(timeout_s=5.0)
    m.register("e1")
    result = {}

    def beat():
        result["peers"] = m.heartbeat("never-registered")

    t = threading.Thread(target=beat, daemon=True)
    t.start()
    t.join(timeout=10.0)
    assert not t.is_alive(), \
        "heartbeat() deadlocked on an unregistered executor"
    # first beat == registration: the reply carries the known peers
    assert [p.executor_id for p in result["peers"]] == ["e1"]
    assert set(m.live_peers()) == {"e1", "never-registered"}


def test_peer_dead_event_per_transition(spy):
    from spark_rapids_tpu.parallel.heartbeat import HeartbeatManager
    m = HeartbeatManager(timeout_s=0.05)
    m.register("e1")
    m.register("e2")
    time.sleep(0.1)
    m.heartbeat("e2")  # e2 beats back to life
    assert m.dead_peers() == ["e1"]
    evs = _kinds(spy, "peer_dead")
    assert len(evs) == 1 and evs[0]["executor_id"] == "e1"
    assert evs[0]["silent_ms"] >= 50 and evs[0]["timeout_ms"] == 50
    m.dead_peers()  # still dead: no second event
    assert len(_kinds(spy, "peer_dead")) == 1
    m.heartbeat("e1")  # back alive -> transition re-arms
    time.sleep(0.1)
    assert "e1" in m.dead_peers()  # (e2 died again too by now)
    e1_evs = [e for e in _kinds(spy, "peer_dead")
              if e["executor_id"] == "e1"]
    assert len(e1_evs) == 2


# ---------------------------------------------------------------------------
# task_retry settle-error satellite
# ---------------------------------------------------------------------------

def test_settle_failure_between_attempts_is_observable(spy, monkeypatch):
    conf = C.RapidsConf(dict(FAST))
    C.set_active_conf(conf)
    cat = buffer_catalog()

    def wedged():
        raise RuntimeError("catalog wedged between attempts")

    monkeypatch.setattr(cat, "drain_writeback", wedged)
    calls = []

    def flaky(attempt):
        calls.append(attempt)
        if attempt == 1:
            raise faults.InjectedDeviceError("device.dispatch")
        return "ok"

    assert with_task_retry(flaky, conf=conf) == "ok"
    evs = _kinds(spy, "task_retry_settle_error")
    assert len(evs) == 1
    assert "catalog wedged" in evs[0]["error"]


# ---------------------------------------------------------------------------
# tooling: profile_report roll-up + bench wiring
# ---------------------------------------------------------------------------

def test_profile_report_lifecycle_rollup():
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    import profile_report
    evs = [
        {"kind": "query_cancelled", "phase": "sem-wait"},
        {"kind": "query_cancelled", "phase": "compute"},
        {"kind": "query_cancelled", "phase": "compute"},
        {"kind": "breaker_open", "domain": "pallas_fused"},
        {"kind": "breaker_half_open", "domain": "pallas_fused"},
        {"kind": "breaker_close", "domain": "pallas_fused"},
        {"kind": "partition_recompute", "partition": 1},
        {"kind": "task_retry", "attempt": 1},
    ]
    report = profile_report.build_report(evs)
    assert "query cancellations: 3 (compute:2, sem-wait:1)" in report
    assert "breaker trips: 1 open, 1 half-open, 1 close" in report
    assert ("recovery lanes: 1 partition-granular recompute(s), "
            "1 whole-plan re-execution(s)") in report


def test_bench_query_timeout_flag(monkeypatch):
    import bench
    monkeypatch.setattr(bench, "_QUERY_TIMEOUT_MS", None)
    monkeypatch.setattr(bench, "_attr_prev", {})
    assert bench.maybe_query_timeout(["bench.py"]) is None
    with pytest.raises(SystemExit):
        bench.maybe_query_timeout(["bench.py", "--query-timeout-ms"])
    assert bench.maybe_query_timeout(
        ["bench.py", "--query-timeout-ms", "5000"]) == 5000
    rec = bench.lifecycle_attribution()
    assert rec["query_timeout_ms"] == 5000
    assert set(rec) >= {"cancelled", "partition_recompute",
                        "breaker_open", "whole_plan_retries"}
    # deltas, not cumulative totals
    assert bench.lifecycle_attribution()["cancelled"] == 0
    # guarded_run runs the lane under a governed deadline
    seen = {}

    def probe():
        ctx = lifecycle.current_context()
        seen["deadline"] = ctx is not None and ctx.deadline is not None
        return 7

    assert bench.guarded_run(probe) == 7
    assert seen["deadline"] is True


# ---------------------------------------------------------------------------
# slow tier: bounded per-query wall-clock under chaos
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_bounded_wall_clock_under_chaos():
    """5 seeded chaos queries (every point armed at 5%, capped) under a
    2-minute deadline each: all equal to the fault-free run AND each
    attempt chain bounded in wall-clock — the --query-timeout-ms
    contract the nightly bench soak relies on."""
    data, oracle = _shuffle_query_data()
    base = dict(SHUFFLED, **{
        "spark.rapids.tpu.task.maxAttempts": "20",
        "spark.rapids.tpu.query.timeoutMs": "120000"})
    for seed in range(5):
        settings = dict(base)
        settings["spark.rapids.tpu.test.faults"] = ";".join(
            part + ",max=2" for part in
            faults.uniform_spec(0.05, seed).split(";"))
        t0 = time.monotonic()
        got = _drive_shuffled_agg(settings, data)
        wall = time.monotonic() - t0
        faults.install(None)
        assert got == oracle, f"seed {seed} diverged"
        assert wall < 120.0, f"seed {seed} blew the deadline: {wall}"
