"""Structured event pipeline (ISSUE 2 tentpole): JSONL schema, span
nesting/attribution, disabled-mode zero-emission, metric reconciliation
against last_query_metrics(), and the profile_report CLI."""

import glob
import json
import os
import re
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec.base import TpuMetric
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs import events, op_span
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import profile_report  # noqa: E402


@pytest.fixture(autouse=True)
def _bus_isolation():
    """Every test leaves the process bus off (other suites rely on the
    disabled-mode fast path)."""
    yield
    events.reset_event_bus()
    TpuSession()


def _q1_query(sess, n=4000):
    """The q1-shaped bench pipeline: filter -> derived projection ->
    group-by aggregate (acceptance criterion shape)."""
    rng = np.random.default_rng(0)
    schema = Schema((StructField("returnflag", INT),
                     StructField("quantity", LONG),
                     StructField("extendedprice", DOUBLE),
                     StructField("discount", DOUBLE)))
    df = sess.from_pydict(
        {"returnflag": rng.integers(0, 4, n).tolist(),
         "quantity": rng.integers(1, 51, n).tolist(),
         "extendedprice": (rng.random(n) * 1000).tolist(),
         "discount": (rng.random(n) * 0.1).tolist()}, schema)
    return (df.filter(col("quantity") <= lit(45))
              .select(col("returnflag"), col("quantity"),
                      (col("extendedprice") * (lit(1.0) - col("discount")))
                      .alias("disc_price"))
              .group_by("returnflag")
              .agg((Sum(col("quantity")), "sum_qty"),
                   (Sum(col("disc_price")), "sum_disc"), (Count(), "cnt")))


def _enabled_session(tmp_path, level="DEBUG"):
    return TpuSession({"spark.rapids.tpu.eventLog.enabled": True,
                       "spark.rapids.tpu.eventLog.dir": str(tmp_path),
                       "spark.rapids.tpu.eventLog.level": level})


def _read_log(tmp_path):
    files = glob.glob(str(tmp_path / "events-*.jsonl"))
    assert len(files) == 1, files
    with open(files[0]) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_disabled_mode_emits_nothing(tmp_path):
    """Conf off (default): no bus, no files — even with a dir set."""
    sess = TpuSession({"spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    assert events.active_bus() is None
    rows = _q1_query(sess).collect()
    assert rows
    assert glob.glob(str(tmp_path / "*")) == []
    events.emit("spill", bytes=1)  # cold-path emit is a no-op too
    assert glob.glob(str(tmp_path / "*")) == []


def test_jsonl_schema_and_reconciliation(tmp_path):
    """The acceptance criterion: a q1-shaped query writes a parseable
    JSONL log whose op_close span times and row counts reconcile with
    last_query_metrics() totals."""
    sess = _enabled_session(tmp_path)
    rows = _q1_query(sess).collect()
    assert len(rows) == 4
    recs = _read_log(tmp_path)
    kinds = {r["kind"] for r in recs}
    assert {"query_start", "query_end", "op_open", "op_batch",
            "op_close"} <= kinds
    for r in recs:  # every record is self-describing
        assert isinstance(r["ts_ns"], int)
        assert isinstance(r["kind"], str)
        assert "query" in r
    (qid,) = {r["query"] for r in recs if r["kind"] == "op_close"}
    closes = [r for r in recs if r["kind"] == "op_close"]
    for r in closes:
        assert r["wall_ns"] >= 0 and r["batches"] >= 0 and r["rows"] >= 0
        assert r["op_id"] is not None
    # op_batch wall times sum to <= their op_close (close adds nothing)
    for r in closes:
        steps = [b for b in recs if b["kind"] == "op_batch"
                 and b["op_id"] == r["op_id"]]
        assert len(steps) == r["batches"]
        assert sum(b["wall_ns"] for b in steps) <= r["wall_ns"] * 1.01 + 1
    # row counts reconcile with the session metric roll-up, per operator
    m = sess.last_query_metrics()
    metric_rows = {}
    for path, v in m.items():
        if path.startswith("ops.") and path.endswith(".numOutputRows"):
            label = path[: -len(".numOutputRows")].split("/")[-1]
            label = label.removeprefix("ops.")
            label = re.sub(r"\[\d+\]$", "", label)  # sibling ordinal
            metric_rows[label] = metric_rows.get(label, 0) + v
    close_rows = {}
    for r in closes:
        close_rows[r["op"]] = close_rows.get(r["op"], 0) + r["rows"]
    for op, n in close_rows.items():
        assert metric_rows.get(op, 0) == n, (op, n, metric_rows)
    # the end event closes the query the spans ran under
    end = [r for r in recs if r["kind"] == "query_end"]
    assert end and end[-1]["ok"] and end[-1]["query"] == qid


def test_event_level_filters_span_records(tmp_path):
    """eventLog.level=ESSENTIAL keeps the query begin/end/phase-ledger
    records only (query_phases joined the essential set in ISSUE 17)."""
    sess = _enabled_session(tmp_path, level="ESSENTIAL")
    _q1_query(sess).collect()
    kinds = {r["kind"] for r in _read_log(tmp_path)}
    assert kinds == {"query_start", "query_end", "query_phases"}


def test_span_nesting_and_attribution(tmp_path):
    """op_span is the NvtxWithMetrics analog: nested spans all record,
    each bumps its metric, and every record carries the enclosing query
    id."""
    events.enable(str(tmp_path), "DEBUG")
    outer_m = TpuMetric("opTime")
    inner_m = TpuMetric("opTime")
    with events.query_scope(77):
        with op_span("outer", outer_m, detail="a"):
            with op_span("inner", inner_m):
                pass
    assert outer_m.value >= inner_m.value > 0
    recs = _read_log(tmp_path)
    spans = {r["op"]: r for r in recs if r["kind"] == "span"}
    assert set(spans) == {"outer", "inner"}
    assert all(r["query"] == 77 and r["ok"] for r in spans.values())
    assert spans["outer"]["detail"] == "a"
    # inner closes first (nesting), and its wall time is contained
    assert spans["inner"]["ts_ns"] <= spans["outer"]["ts_ns"]
    assert spans["inner"]["wall_ns"] <= spans["outer"]["wall_ns"]


def test_span_records_failure_and_still_bumps_metric(tmp_path):
    events.enable(str(tmp_path), "DEBUG")
    m = TpuMetric("opTime")
    with pytest.raises(ValueError):
        with op_span("boom", m):
            raise ValueError("x")
    assert m.value > 0
    (rec,) = _read_log(tmp_path)
    assert rec["op"] == "boom" and rec["ok"] is False


def test_memory_events_spill_and_retry(tmp_path):
    """Spill and OOM-retry producers land structured records."""
    import jax.numpy as jnp

    from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                                 reset_buffer_catalog)
    from spark_rapids_tpu.memory.retry import (TpuRetryOOM, force_retry_oom,
                                               register_task,
                                               unregister_task, with_retry)
    events.enable(str(tmp_path), "MODERATE")
    cat = reset_buffer_catalog()
    h = cat.add(jnp.arange(1024))
    cat.synchronous_spill(None)
    register_task(9)
    try:
        force_retry_oom(1)
        assert list(with_retry(1, lambda x: x * 2)) == [2]
    finally:
        unregister_task()
        cat.remove(h)
        reset_buffer_catalog()
    recs = _read_log(tmp_path)
    spills = [r for r in recs if r["kind"] == "spill"]
    assert spills and spills[0]["tier"] == "device->host"
    assert spills[0]["bytes"] == jnp.arange(1024).nbytes
    retries = [r for r in recs if r["kind"] == "oom_retry"]
    assert retries and retries[0]["oom"] == "retry"
    assert retries[0]["task_id"] == 9


def test_profile_report_cli_renders_top_table(tmp_path, capsys):
    """tools/profile_report.py turns an event log into the top-N
    operator time/bytes table (acceptance criterion)."""
    sess = _enabled_session(tmp_path)
    _q1_query(sess).collect()
    (log,) = glob.glob(str(tmp_path / "events-*.jsonl"))
    assert profile_report.main([log, "--top", "3"]) == 0
    out = capsys.readouterr().out
    assert "top 3 operators by inclusive wall time" in out
    # ISSUE 14: the filter+group-by chain executes as a fused stage
    assert "CompiledStageExec" in out or "AggregateExec" in out
    assert "1 queries (1 completed)" in out
    # machine surface: the builder is also importable on raw lines
    with open(log) as f:
        report = profile_report.build_report(
            profile_report.read_events(f), top=2)
    assert "CompiledStageExec" in report or "AggregateExec" in report


def test_bus_reconfigure_reuses_and_closes(tmp_path):
    """Same dir+level keeps one file across queries; the bus is
    process-wide, so a default-conf session leaves it alone and only an
    EXPLICIT enabled=false tears it down."""
    sess = _enabled_session(tmp_path)
    q = _q1_query(sess)
    q.collect()
    q.collect()
    recs = _read_log(tmp_path)  # asserts exactly one file
    assert sum(1 for r in recs if r["kind"] == "query_end") == 2
    qids = {r["query"] for r in recs if r["kind"] == "query_end"}
    assert len(qids) == 2  # fresh id per query
    TpuSession()  # eventLog.enabled UNSET: another session's log lives on
    assert events.active_bus() is not None
    TpuSession({"spark.rapids.tpu.eventLog.enabled": False})  # explicit
    assert events.active_bus() is None


def test_write_failure_deactivates_bus(tmp_path):
    """A dead sink removes itself: producers must drop back to the
    uninstrumented fast path instead of serializing records into a
    closed bus forever."""
    events.enable(str(tmp_path / "f"), "MODERATE")
    (tmp_path / "f").write_text("not a directory")  # makedirs will fail
    events.emit("spill", bytes=1)
    assert events.active_bus() is None
