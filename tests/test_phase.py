"""Wall-clock phase attribution (ISSUE 17 tentpole piece 1): the
closed-set invariant `sum(phases) == wall_ns` exactly — unit-level on
the ledger's folding/trim rules and end-to-end on a pipelined,
spilling, task-retried governed query — plus the disabled-mode
one-pointer-check discipline and the query_phases event surface."""

import glob
import json
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.memory.budget import (memory_budget,
                                            reset_memory_budget)
from spark_rapids_tpu.memory.catalog import reset_buffer_catalog
from spark_rapids_tpu.obs import events, history, phase
from spark_rapids_tpu.obs.phase import ACCRUABLE, PHASES, PhaseLedger


@pytest.fixture(autouse=True)
def _phase_isolation():
    yield
    faults.install(None)
    phase.reset_phase_counters()
    history.reset_history()
    events.reset_event_bus()
    TpuSession()  # restore the default active conf


# ---------------------------------------------------------------------------
# the closed set
# ---------------------------------------------------------------------------

def test_phase_set_is_closed_and_other_is_derived():
    assert PHASES[-1] == "other"
    assert ACCRUABLE == PHASES[:-1]
    assert len(set(PHASES)) == len(PHASES)
    led = PhaseLedger()
    snap = led.snapshot()
    assert set(snap) == set(PHASES)


# ---------------------------------------------------------------------------
# global counters (the bench delta surface)
# ---------------------------------------------------------------------------

def test_global_counters_accrue_and_reset():
    phase.reset_phase_counters()
    base = phase.counters()
    assert set(base) == set(ACCRUABLE) and not any(base.values())
    phase.add("compile", 1234)
    phase.add("compile", 1)
    phase.add("shuffle-io", 7)
    phase.add("spill-wait", 0)      # zero/negative accruals are no-ops
    phase.add("spill-wait", -5)
    cur = phase.counters()
    assert cur["compile"] == 1235
    assert cur["shuffle-io"] == 7
    assert cur["spill-wait"] == 0
    phase.reset_phase_counters()
    assert not any(phase.counters().values())


def test_span_is_exclusive_of_nested_accruals():
    """A span's phase gets only its EXCLUSIVE elapsed: time a nested
    add() (or nested span) reports is subtracted, so arbitrary nesting
    never double-counts into the global books."""
    phase.reset_phase_counters()
    with phase.span("ici-collective"):
        assert phase.in_span()
        t0 = time.perf_counter_ns()
        while time.perf_counter_ns() - t0 < 2_000_000:
            pass
        # a nested accrual claiming (more than) the whole block so far
        phase.add("device-compute", 10_000_000_000)
    assert not phase.in_span()
    cur = phase.counters()
    assert cur["device-compute"] == 10_000_000_000
    # the child claimed more than the span elapsed -> zero exclusive
    assert cur["ici-collective"] == 0


def test_note_dispatch_routing():
    """Traced dispatches are compile wherever they happen; cached
    dispatches are device-compute only OUTSIDE a span (inside one the
    enclosing phase keeps the time)."""
    phase.reset_phase_counters()
    phase.note_dispatch(50, traced=True)
    phase.note_dispatch(70, traced=False)
    with phase.span("ici-collective"):
        phase.note_dispatch(500, traced=True)
        phase.note_dispatch(900, traced=False)  # swallowed by the span
    cur = phase.counters()
    assert cur["compile"] == 550
    assert cur["device-compute"] == 70


# ---------------------------------------------------------------------------
# ledger folding rules (unit)
# ---------------------------------------------------------------------------

def _folded_add(led, phase_name, ns):
    t = threading.Thread(target=led.add, args=(phase_name, ns))
    t.start()
    t.join()


def test_ledger_folds_producer_time_into_stall_budget():
    """Folded (producer-thread) time displaces pipeline-stall
    one-for-one: the consumer stalled exactly while producers worked."""
    led = PhaseLedger()
    led.add("pipeline-stall", 1000)
    _folded_add(led, "device-compute", 400)
    time.sleep(0.001)  # wall must dominate the synthetic accruals
    snap = led.snapshot()
    assert snap["device-compute"] == 400
    assert snap["pipeline-stall"] == 600
    assert sum(snap.values()) == led.wall_ns
    assert min(snap.values()) >= 0


def test_ledger_scales_folded_surplus_down():
    """Producers reporting MORE than the consumer stalled (deep
    overlap): shares scale down so attribution never exceeds the
    measured stall budget."""
    led = PhaseLedger()
    led.add("pipeline-stall", 1000)
    _folded_add(led, "device-compute", 3000)
    _folded_add(led, "host-pack-serialize", 1000)
    time.sleep(0.001)
    snap = led.snapshot()
    assert snap["device-compute"] == 3000 * 1000 // 4000
    assert snap["host-pack-serialize"] == 1000 * 1000 // 4000
    assert snap["pipeline-stall"] == 1000 - (750 + 250)
    assert sum(snap.values()) == led.wall_ns


def test_ledger_trims_rather_than_exceeding_wall():
    """Defensive seam: even a ledger fed absurd direct accruals
    reports sum == wall with nothing negative."""
    led = PhaseLedger()
    led.add("compile", 10**15)
    led.add("shuffle-io", 500)
    snap = led.snapshot()
    assert sum(snap.values()) == led.wall_ns
    assert min(snap.values()) >= 0
    assert snap["other"] == 0  # trim leaves no remainder to derive


def test_ledger_finish_is_idempotent():
    led = PhaseLedger()
    led.add("compile", 10)
    w1 = led.finish()
    time.sleep(0.002)
    assert led.finish() == w1 == led.wall_ns
    s1, s2 = led.snapshot(), led.snapshot()
    assert s1 == s2 and sum(s1.values()) == w1


# ---------------------------------------------------------------------------
# end-to-end: pipelined + spilling + retried governed query
# ---------------------------------------------------------------------------

def _storm_parquet(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(5)
    n_l, n_o = 2000, 500
    l_key = rng.integers(0, n_o, n_l)
    l_val = rng.random(n_l) * 100.0
    l_flag = rng.integers(0, 4, n_l)
    o_flag = rng.integers(0, 10, n_o)
    lp, op = str(tmp_path / "lines.parquet"), str(tmp_path / "orders.parquet")
    pq.write_table(pa.table({
        "l_key": pa.array(l_key, pa.int64()),
        "l_val": pa.array(l_val, pa.float64()),
        "l_flag": pa.array(l_flag, pa.int64())}), lp, row_group_size=512)
    pq.write_table(pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(o_flag, pa.int64())}), op, row_group_size=128)
    return lp, op


def _storm_query(sess, lp, op):
    lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
    orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    return (j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                    (F.count(), "cnt"))
             .sort(("rev", False)))


STRESS = {
    "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
    "spark.rapids.sql.broadcastSizeThreshold": "-1",
    "spark.rapids.sql.retry.maxAttempts": "50",
    "spark.rapids.tpu.retry.backoffMs": "1",
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
}


def test_phase_invariant_on_pipelined_spilling_retried_query(tmp_path):
    """THE acceptance criterion: a governed query that pipelines,
    spills under a forced budget, AND task-retries a mid-flight device
    fault still closes its phase books exactly — sum(phases) ==
    wall_ns, nothing negative — and the query_phases ESSENTIAL event
    carries the same ledger with correct attribution fields."""
    lp, op = _storm_parquet(tmp_path)
    settings = dict(STRESS, **{
        "spark.rapids.tpu.eventLog.enabled": "true",
        "spark.rapids.tpu.eventLog.dir": str(tmp_path / "ev"),
        "spark.rapids.tpu.eventLog.level": "ESSENTIAL",
        "spark.rapids.tpu.test.faults":
            "device.dispatch:prob=1,seed=3,kind=device,max=1",
    })
    reset_buffer_catalog()
    reset_memory_budget(80 * 1024)  # force spill on a single lane
    try:
        sess = TpuSession(settings)
        rows = _storm_query(sess, lp, op).collect()
        assert rows
        assert memory_budget().spill_requests > 0, \
            "the forced-spill budget lost its teeth"
        prof = sess.last_query_profile()
        ph = prof.phases()
        wall = prof.phases_wall_ns()
        assert ph is not None and wall > 0
        assert set(ph) == set(PHASES)
        assert sum(ph.values()) == wall        # the exact invariant
        assert min(ph.values()) >= 0
        assert ph["compile"] > 0               # dispatches traced
        m = sess.last_query_metrics()
        assert m["retryCount"] + m["splitAndRetryCount"] >= 0
        # the ESSENTIAL event carries the same closed books
        (ev_file,) = glob.glob(str(tmp_path / "ev" / "events-*.jsonl"))
        with open(ev_file) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        (qp,) = [r for r in recs if r["kind"] == "query_phases"]
        assert qp["ok"] is True
        assert qp["attempts"] >= 2, "the injected fault never retried"
        assert qp["query"] is not None
        # the ledger joins the FINAL attempt's begin/end records on the
        # events-plane id — the lifecycle ctx_id drifts from it as soon
        # as a query retries (one events id per attempt)
        ends = [r for r in recs if r["kind"] == "query_end"]
        assert qp["query"] == ends[-1]["query"]
        assert set(qp["phases"]) == set(PHASES)
        assert sum(qp["phases"].values()) == qp["wall_ns"]
        assert qp["phases"]["retry-backoff"] > 0
    finally:
        reset_buffer_catalog()
        reset_memory_budget()


def test_phases_disabled_is_one_pointer_check_and_byte_identical(tmp_path):
    """Explicitly false: no ledger rides the query (profile.phases()
    is None), the history store stays a single None pointer check, and
    results are identical to the enabled run."""
    lp, op = _storm_parquet(tmp_path)
    on = TpuSession({"spark.rapids.tpu.phases.enabled": "true",
                     "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    rows_on = _storm_query(on, lp, op).collect()
    assert on.last_query_profile().phases() is not None

    off = TpuSession({"spark.rapids.tpu.phases.enabled": "false",
                      "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    assert history.active_store() is None  # history off = one pointer
    rows_off = _storm_query(off, lp, op).collect()
    assert rows_off == rows_on
    prof = off.last_query_profile()
    assert prof.phases() is None
    assert prof.phases_wall_ns() is None
    assert "phases" not in prof.to_dict()
    # the process-cumulative counters stay live either way (bench lane)
    assert isinstance(phase.counters(), dict)
