"""Round-5 de-hosted collection/string kernels vs the host-tier oracle
(VERDICT r4 item 4; reference collectionOperations.scala,
stringFunctions.scala GpuFormatNumber/GpuEncode/GpuDecode)."""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DOUBLE, LONG, STRING, ArrayType, Schema, StructField,
)

ARRS = [[1, 2, 3, 2], [], None, [5], [7, None, 3, 7, None], [10, 10],
        [None], [-4, 0, -4]]
BRRS = [[2, 9], [1], [3, None], None, [7], [None], [], [0]]


@pytest.fixture(scope="module")
def df():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(LONG)),
                  StructField("b", ArrayType(LONG)),
                  StructField("v", LONG)))
    return s.from_pydict(
        {"a": ARRS, "b": BRRS, "v": [2, 1, 3, 5, 7, 10, None, -4]}, sch)


def run1(df, expr):
    return [r[0] for r in df.select(expr.alias("r")).collect()]


def _plan_is_device(df, expr):
    tree = df.select(expr.alias("r"))._exec().tree_string()
    return "Fallback" not in tree and "HostRow" not in tree


def test_array_position_device(df):
    e = F.array_position(col("a"), col("v"))
    got = run1(df, e)
    exp = []
    for a, v in zip(ARRS, [2, 1, 3, 5, 7, 10, None, -4]):
        if a is None or v is None:
            exp.append(None)
        else:
            pos = 0
            for i, x in enumerate(a):
                if x is not None and x == v:
                    pos = i + 1
                    break
            exp.append(pos)
    assert got == exp
    assert _plan_is_device(df, e)


def test_array_remove_device(df):
    e = F.array_remove(col("a"), col("v"))
    assert _plan_is_device(df, e)
    got = run1(df, e)
    exp = []
    for a, v in zip(ARRS, [2, 1, 3, 5, 7, 10, None, -4]):
        exp.append(None if a is None or v is None
                   else [x for x in a if x is None or x != v])
    assert got == exp


def test_array_distinct_device(df):
    got = run1(df, F.array_distinct(col("a")))
    exp = []
    for a in ARRS:
        if a is None:
            exp.append(None)
            continue
        out, saw = [], False
        for x in a:
            if x is None:
                if not saw:
                    out.append(None)
                    saw = True
            elif x not in out:
                out.append(x)
        exp.append(out)
    assert got == exp


def test_slice_device(df):
    assert _plan_is_device(df, F.slice(col("a"), 2, 2))
    got = run1(df, F.slice(col("a"), 2, 2))
    exp = [None if a is None else a[1:3] for a in ARRS]
    assert got == exp
    got_neg = run1(df, F.slice(col("a"), -2, 2))
    exp_neg = []
    for a in ARRS:
        if a is None:
            exp_neg.append(None)
        else:
            i = len(a) - 2
            exp_neg.append([] if i < 0 else a[i:i + 2])
    assert got_neg == exp_neg


def test_arrays_overlap_device(df):
    got = run1(df, F.arrays_overlap(col("a"), col("b")))
    exp = []
    for a, b in zip(ARRS, BRRS):
        if a is None or b is None:
            exp.append(None)
            continue
        bs = {x for x in b if x is not None}
        if any(x in bs for x in a if x is not None):
            exp.append(True)
        elif a and b and (None in a or None in b):
            exp.append(None)
        else:
            exp.append(False)
    assert got == exp


def test_flatten_device():
    s = TpuSession()
    NEST = [[[1, 2], [3]], [[], [4, None]], None, [None, [5]], [[]]]
    sch = Schema((StructField("n", ArrayType(ArrayType(LONG))),))
    ndf = s.from_pydict({"n": NEST}, sch)
    got = run1(ndf, F.flatten(col("n")))
    exp = []
    for arr in NEST:
        if arr is None or any(x is None for x in arr):
            exp.append(None)
        else:
            exp.append([y for sub in arr for y in sub])
    assert got == exp


def test_sequence_literal_device(df):
    got = run1(df, F.sequence(lit(1), lit(7), lit(2)))
    assert got == [[1, 3, 5, 7]] * len(ARRS)
    got_desc = run1(df, F.sequence(lit(5), lit(1), lit(-2)))
    assert got_desc == [[5, 3, 1]] * len(ARRS)


def test_array_repeat_literal_device(df):
    got = run1(df, F.array_repeat(col("v"), 3))
    exp = [[v] * 3 for v in [2, 1, 3, 5, 7, 10, None, -4]]
    assert got == exp


def test_format_number_device():
    s = TpuSession()
    vals = [1234567.891, -0.004, 0.0, None, -98765.5, 1e12]
    sch = Schema((StructField("x", DOUBLE),))
    fdf = s.from_pydict({"x": vals}, sch)
    got = run1(fdf, F.format_number(col("x"), 2))
    assert got == [None if v is None else f"{v:,.2f}" for v in vals]
    ldf = s.from_pydict({"x": [0, -5, 1234567, None]},
                        Schema((StructField("x", LONG),)))
    assert run1(ldf, F.format_number(col("x"), 0)) == \
        ["0", "-5", "1,234,567", None]


def test_encode_decode_device_roundtrip():
    s = TpuSession()
    vals = ["héllo", "abc", "ü¢", None, "", "mixed é ascii"]
    sch = Schema((StructField("s", STRING),))
    sdf = s.from_pydict({"s": vals}, sch)
    dec = run1(sdf, F.decode(F.encode(col("s"), "ISO-8859-1"),
                             "ISO-8859-1"))
    assert dec == vals
    utf = run1(sdf, F.decode(F.encode(col("s"), "UTF-8"), "UTF-8"))
    assert utf == vals
    asc = run1(sdf, F.decode(F.encode(col("s"), "US-ASCII"), "US-ASCII"))
    assert asc == [None if v is None else
                   v.encode("ascii", "replace").decode("ascii")
                   for v in vals]


def test_string_elements_fall_back_to_host():
    # string-element arrays keep the host tier but stay CORRECT
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(STRING)),))
    sdf = s.from_pydict({"a": [["x", "y", "x"], None, ["z"]]}, sch)
    got = run1(sdf, F.array_distinct(col("a")))
    assert got == [["x", "y"], None, ["z"]]


def test_slice_negative_start_past_front_is_empty(df):
    # slice([1,2], -5, 4) -> [] (Spark; host tier agrees)
    got = run1(df, F.slice(col("a"), -5, 4))
    exp = []
    for a in ARRS:
        if a is None:
            exp.append(None)
        else:
            i = len(a) - 5
            exp.append([] if i < 0 else a[i:i + 4])
    assert got == exp


def test_slice_zero_start_and_negative_length_null_deviation(df):
    # data-dependent start 0 / length < 0 -> NULL on device (documented
    # deviation; Spark raises)
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(LONG)),
                  StructField("st", LONG), StructField("ln", LONG)))
    sdf = s.from_pydict({"a": [[1, 2, 3]] * 3, "st": [0, 1, 2],
                         "ln": [2, -1, 2]}, sch)
    got = [r[0] for r in sdf.select(
        F.slice(col("a"), col("st"), col("ln")).alias("r")).collect()]
    assert got == [None, None, [2, 3]]


def test_format_number_large_decimals_host_tier():
    s = TpuSession()
    sch = Schema((StructField("x", DOUBLE),))
    fdf = s.from_pydict({"x": [1.5, None]}, sch)
    got = run1(fdf, F.format_number(col("x"), 19))  # host tier (d > 18)
    assert got == [f"{1.5:,.19f}", None]


def test_format_number_int_overflow_saturates():
    s = TpuSession()
    sch = Schema((StructField("x", LONG),))
    fdf = s.from_pydict({"x": [10 ** 18]}, sch)
    # |x|*10^2 exceeds int64: device saturates (documented deviation):
    # scaled pins to 2^63-1 -> int part 92,233,720,368,547,758
    got = run1(fdf, F.format_number(col("x"), 2))
    assert got[0] == "92,233,720,368,547,758.07"


def test_array_position_nan_and_negzero_spark_equality():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(DOUBLE)),
                  StructField("v", DOUBLE)))
    nan = float("nan")
    sdf = s.from_pydict(
        {"a": [[1.0, nan, 3.0], [0.0, 2.0], [-0.0, 5.0]],
         "v": [nan, -0.0, -0.0]}, sch)
    got = run1(sdf, F.array_position(col("a"), col("v")))
    # NaN matches NaN (pos 2); -0.0 does NOT match 0.0; -0.0 matches -0.0
    assert got == [2, 0, 1]
    rem = run1(sdf, F.array_remove(col("a"), col("v")))
    assert rem[0] == [1.0, 3.0]
    assert rem[1] == [0.0, 2.0]
    assert rem[2] == [5.0]
