"""Tracing/profiling surface (reference NVTX/profile.* integration)."""

import glob
import os

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import LONG, Schema, StructField
from spark_rapids_tpu.utils import profile_trace


def _df(sess):
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    return sess.from_pydict({"k": [1, 1, 2], "v": [10, 20, 30]}, sch)


def test_profile_disabled_is_noop():
    TpuSession()
    with profile_trace():  # conf off -> no trace, no error
        assert _df(TpuSession()).group_by("k").agg(
            (F.sum("v"), "s")).count() == 2


@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_profile_captures_trace(tmp_path):
    out = str(tmp_path / "trace")
    sess = TpuSession({"spark.rapids.tpu.profile.enabled": True,
                       "spark.rapids.tpu.profile.dir": out})
    try:
        with profile_trace():
            _df(sess).group_by("k").agg((F.sum("v"), "s")).collect()
        files = glob.glob(os.path.join(out, "**", "*"), recursive=True)
        assert any(os.path.isfile(f) for f in files), files
    finally:
        TpuSession()


def test_annotations_wrap_execution():
    # annotation must not perturb results
    sess = TpuSession()
    got = sorted(_df(sess).group_by("k").agg((F.sum("v"), "s")).collect())
    assert got == [(1, 30), (2, 30)]
