"""Decimal128 columns as first-class sort/group/join keys (VERDICT r4
item 9; reference DecimalUtil.scala / decimalExpressions.scala): two-limb
order lanes, limb-equality join verify, recursive limb hashing."""

import decimal as dec

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import DecimalType, LONG, Schema, StructField

T = DecimalType(30, 2)  # two-limb (precision > 18)

VALS = [dec.Decimal("12345678901234567890.50"),
        dec.Decimal("-99999999999999999999.99"),
        dec.Decimal("0.01"),
        dec.Decimal("12345678901234567890.50"),
        None,
        dec.Decimal("-0.01"),
        dec.Decimal("99999999999999999999.99"),
        None,
        dec.Decimal("0.01")]


def _df(sess, extra=None):
    data = {"d": VALS, "v": list(range(len(VALS)))}
    sch = Schema((StructField("d", T), StructField("v", LONG)))
    return sess.from_pydict(data, sch)


def _u(v):
    # engine collect() convention: decimals come back as UNSCALED ints
    return None if v is None else int(v.scaleb(2))


def test_sort_by_decimal128_key():
    sess = TpuSession()
    rows = _df(sess).sort("d").collect()
    got = [r[0] for r in rows]
    non_null = [_u(v) for v in sorted(v for v in VALS if v is not None)]
    # Spark default: nulls first ascending
    assert got == [None, None] + non_null


def test_group_by_decimal128_key():
    sess = TpuSession()
    rows = _df(sess).group_by("d").agg(
        (F.count(), "n"), (F.sum(F.col("v")), "sv")).collect()
    got = {r[0]: (r[1], r[2]) for r in rows}
    exp = {}
    for d, v in zip(VALS, range(len(VALS))):
        n, sv = exp.get(_u(d), (0, 0))
        exp[_u(d)] = (n + 1, sv + v)
    assert got == exp


def test_join_on_decimal128_key():
    sess = TpuSession()
    left = _df(sess)
    rdata = {"d": [dec.Decimal("12345678901234567890.50"),
                   dec.Decimal("0.01"), dec.Decimal("5.00"), None],
             "w": [100, 200, 300, 400]}
    rsch = Schema((StructField("d", T), StructField("w", LONG)))
    right = sess.from_pydict(rdata, rsch)
    rows = left.join(right, on="d", how="inner").collect()
    got = sorted((r[0], r[1], r[2]) for r in rows)
    exp = []
    for d, v in zip(VALS, range(len(VALS))):
        if d is None:
            continue
        for rd, w in zip(rdata["d"], rdata["w"]):
            if rd is not None and rd == d:
                exp.append((_u(d), v, w))
    assert got == sorted(exp)
    # two-limb discrimination: values differing ONLY in the low limb
    # must not cross-match
    a = dec.Decimal("18446744073709551616.00")   # hi=1, lo=0 region
    b = dec.Decimal("18446744073709551617.00")
    l2 = sess.from_pydict({"d": [a], "x": [1]},
                          Schema((StructField("d", T),
                                  StructField("x", LONG))))
    r2 = sess.from_pydict({"d": [b], "y": [2]},
                          Schema((StructField("d", T),
                                  StructField("y", LONG))))
    assert l2.join(r2, on="d", how="inner").collect() == []


def test_window_partition_by_decimal128():
    from spark_rapids_tpu.expr.windowexprs import WindowAgg, window
    from spark_rapids_tpu.exec.window import WindowExec
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    sch = Schema((StructField("d", T), StructField("v", LONG)))
    b = ColumnarBatch.from_pydict({"d": VALS, "v": list(range(len(VALS)))},
                                  sch)
    spec = window(partition_by=["d"])
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                      InMemoryScanExec([b], sch))
    rows = plan.collect()
    exp = {}
    for d, v in zip(VALS, range(len(VALS))):
        exp[_u(d)] = exp.get(_u(d), 0) + v
    for d, v, s in rows:
        assert s == exp[d], (d, s, exp[d])
