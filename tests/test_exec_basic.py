"""Exec-layer tests: project/filter/range/limit/union/expand + coalesce —
modeled on the reference's SparkQueryCompareTestSuite pattern (every case
states expected rows explicitly or compares against a numpy oracle)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.basic import (
    ExpandExec, FilterExec, GlobalLimitExec, InMemoryScanExec, LocalLimitExec,
    ProjectExec, RangeExec, UnionExec,
)
from spark_rapids_tpu.exec.coalesce import CoalesceBatchesExec
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)


def make_scan(data: dict, schema: Schema, split: int = 0):
    """Build a scan; split>0 chunks rows into multiple batches."""
    n = len(next(iter(data.values())))
    if split and n > split:
        batches = []
        for s in range(0, n, split):
            chunk = {k: v[s:s + split] for k, v in data.items()}
            batches.append(ColumnarBatch.from_pydict(chunk, schema))
        return InMemoryScanExec(batches, schema)
    return InMemoryScanExec([ColumnarBatch.from_pydict(data, schema)], schema)


SCHEMA = Schema((StructField("a", INT), StructField("b", LONG),
                 StructField("s", STRING)))
DATA = {
    "a": [1, 2, None, 4, 5, None, 7, 8],
    "b": [10, None, 30, 40, 50, 60, None, 80],
    "s": ["x", "yy", None, "zzz", "w", "v", "u", "tt"],
}


def test_project_arithmetic():
    scan = make_scan(DATA, SCHEMA)
    plan = ProjectExec([(col("a") + col("b")).alias("ab"),
                        (col("a") * lit(2)).alias("a2")], scan)
    rows = plan.collect()
    expect = [(11, 2), (None, 4), (None, None), (44, 8), (55, 10),
              (None, None), (None, 14), (88, 16)]
    assert rows == expect


def test_filter_basic():
    scan = make_scan(DATA, SCHEMA)
    plan = FilterExec(col("a") > lit(3), scan)
    rows = plan.collect()
    assert rows == [(4, 40, "zzz"), (5, 50, "w"), (7, None, "u"),
                    (8, 80, "tt")]


def test_filter_null_predicate_dropped():
    # a > 3 is null for null a -> dropped (Spark semantics)
    scan = make_scan(DATA, SCHEMA, split=3)
    plan = FilterExec(col("a") > lit(0), scan)
    assert len(plan.collect()) == 6


def test_project_filter_chain_multibatch():
    scan = make_scan(DATA, SCHEMA, split=3)
    plan = ProjectExec([(col("a") + lit(1)).alias("a1"), col("s")],
                       FilterExec(col("a") > lit(1), scan))
    assert plan.collect() == [(3, "yy"), (5, "zzz"), (6, "w"), (8, "u"),
                              (9, "tt")]


def test_range_exec():
    plan = RangeExec(0, 1000, 7, batch_rows=128)
    rows = [r[0] for r in plan.collect()]
    assert rows == list(range(0, 1000, 7))


def test_local_and_global_limit():
    scan = make_scan(DATA, SCHEMA, split=3)
    assert len(LocalLimitExec(5, scan).collect()) == 5
    scan2 = make_scan(DATA, SCHEMA, split=3)
    got = GlobalLimitExec(3, scan2, offset=2).collect()
    assert got == [(None, 30, None), (4, 40, "zzz"), (5, 50, "w")]


def test_union():
    s1 = make_scan(DATA, SCHEMA)
    s2 = make_scan(DATA, SCHEMA)
    assert len(UnionExec(s1, s2).collect()) == 16


def test_expand_grouping_sets():
    scan = make_scan(DATA, SCHEMA)
    plan = ExpandExec([[col("a"), lit(0).alias("g")],
                       [col("a"), lit(1).alias("g")]], scan)
    rows = plan.collect()
    assert len(rows) == 16
    assert {r[1] for r in rows} == {0, 1}


def test_coalesce_merges_batches():
    scan = make_scan(DATA, SCHEMA, split=2)  # 4 input batches
    plan = CoalesceBatchesExec(scan)
    batches = list(plan.execute())
    assert len(batches) == 1
    assert batches[0].num_rows_host == 8
    # row content preserved in order
    assert batches[0].to_pydict()["a"] == DATA["a"]
    assert batches[0].to_pydict()["s"] == DATA["s"]


def test_coalesce_respects_target_bytes():
    scan = make_scan(DATA, SCHEMA, split=2)
    plan = CoalesceBatchesExec(scan, target_bytes=1)  # force no merging
    batches = list(plan.execute())
    assert len(batches) == 4


def test_metrics_populated():
    scan = make_scan(DATA, SCHEMA)
    plan = FilterExec(col("a") > lit(3), scan)
    _ = plan.collect()
    assert plan.metrics["numOutputRows"].value == 4
    assert plan.metrics["numOutputBatches"].value == 1
