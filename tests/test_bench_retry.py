"""bench.py backend-init hardening (ISSUE 1 satellite, VERDICT r5 Weak
#1): bounded exponential-backoff retry around backend init, and a
structured {"error_kind": "backend_init"} record — not a raw rc=1
traceback — when every attempt fails."""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
import bench  # noqa: E402


def test_backoff_survives_one_injected_failure():
    """One transient init failure recovers on the retry; the backoff
    sleep between attempts is exponential."""
    attempts = []
    sleeps = []

    def flaky():
        attempts.append(1)
        if len(attempts) == 1:
            raise ConnectionError("injected: axon relay dropped")
        return "backend"

    out = bench.with_backend_retry(flaky, attempts=3, base_sleep=0.5,
                                   sleep=sleeps.append)
    assert out == "backend"
    assert len(attempts) == 2
    assert sleeps == [0.5]


def test_exponential_backoff_schedule():
    sleeps = []

    def flaky_twice(state=[0]):
        state[0] += 1
        if state[0] <= 2:
            raise RuntimeError("injected")
        return 42

    assert bench.with_backend_retry(flaky_twice, attempts=3,
                                    base_sleep=1.0,
                                    sleep=sleeps.append) == 42
    assert sleeps == [1.0, 2.0]


def test_structured_record_instead_of_rc1(capsys):
    """All attempts failing must emit one machine-readable JSON record
    and exit 0 — the driver logs an outage, not a zeroed perf round."""
    sleeps = []

    def dead():
        raise ConnectionError("injected: relay stdin closed")

    with pytest.raises(SystemExit) as exc:
        bench.with_backend_retry(dead, attempts=3, base_sleep=0.25,
                                 sleep=sleeps.append)
    assert exc.value.code == 0
    assert sleeps == [0.25, 0.5]  # 3 attempts -> 2 backoff sleeps
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["error_kind"] == "backend_init"
    assert rec["attempts"] == 3
    assert "relay stdin closed" in rec["error"]


def test_backend_probe_dispatches_a_real_program(monkeypatch):
    """BENCH_r05 regression: jax.devices() can succeed while the FIRST
    dispatched cast still dies with a backend setup/compile error
    (`lax._convert_element_type` -> 'Unable to initialize backend').
    The probe must therefore dispatch + block on a real program, so the
    failure lands INSIDE with_backend_retry instead of crashing the run
    at the data upload with rc=1."""
    import jax
    blocked = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda x: blocked.append(x) or real(x))
    out = bench.backend_probe()
    assert out is jax
    assert blocked  # a computation was forced, not just a device listing


def test_init_backend_retries_first_dispatch_failure(monkeypatch):
    """A transient backend failure raised by the probe's dispatched
    program (not by jax.devices()) is retried and recovers — the exact
    r05 failure mode, now covered by the retry machinery."""
    import jax
    calls = []
    real = jax.block_until_ready

    def flaky(x):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError(
                "Unable to initialize backend 'axon': UNAVAILABLE")
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", flaky)
    slept = []
    assert bench.init_backend(sleep=slept.append) is jax
    assert len(calls) == 2 and len(slept) == 1  # one retry, one backoff
