"""Profiling advisor + history report CLI (ISSUE 17 tentpole piece 3)
and the tier-1 suite-budget tool (satellite): per-fingerprint
aggregation, phase-ranked --diff regressions, the closed ADVISOR_RULES
registry on crafted golden scenarios, the profile_report phase
roll-up, and suite_budget's durations parsing."""

import json
import sys
from pathlib import Path

import pytest

from spark_rapids_tpu.obs import history
from spark_rapids_tpu.obs import phase as obs_phase

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT / "tools"))
import history_report  # noqa: E402
import profile_report  # noqa: E402
import suite_budget  # noqa: E402


def test_report_phase_tuple_is_the_registry():
    """The stdlib-only tool mirrors obs.phase.PHASES by value — drift
    between the two is a silent misattribution bug."""
    assert history_report.PHASES == obs_phase.PHASES


# ---------------------------------------------------------------------------
# capsule factory (golden scenarios)
# ---------------------------------------------------------------------------

def _capsule(fp, wall_ns, ts=0, ok=True, phases=None, mesh=1, **families):
    ph = {p: 0 for p in history_report.PHASES}
    ph.update(phases or {})
    measured = sum(v for k, v in ph.items() if k != "other")
    ph["other"] = max(0, wall_ns - measured)
    cap = {"ts_ms": ts, "query": 1, "fingerprint": fp, "ok": ok,
           "priority": "interactive", "attempts": 1, "wall_ns": wall_ns,
           "mesh_devices": mesh, "phases": ph, "rows": 100, "batches": 2,
           "sem_wait_ns": 0, "spill_bytes": 0, "skew": None,
           "dispatch": {}, "shuffle": {}, "ici": {}, "upload": {},
           "workload": {}}
    cap.update(families)
    return cap


def _write_dir(d, capsules):
    d.mkdir(parents=True, exist_ok=True)
    with open(d / "history-1-1.jsonl", "w") as f:
        for c in capsules:
            f.write(json.dumps(c) + "\n")


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def test_aggregate_per_fingerprint():
    caps = [
        _capsule("aaa", 1000, ts=1, phases={"compile": 600}),
        _capsule("aaa", 3000, ts=2, phases={"compile": 1800}),
        _capsule("aaa", 2000, ts=3, ok=False, phases={"compile": 1200}),
        _capsule("bbb", 500, ts=4),
        {"wall_ns": 42, "ok": True},   # fingerprint-less -> "(none)"
    ]
    agg = history_report.aggregate(caps)
    assert set(agg) == {"aaa", "bbb", "(none)"}
    a = agg["aaa"]
    assert a["count"] == 3 and a["ok"] == 2
    assert a["p50_wall_ns"] == 2000       # nearest-rank of [1000,2000,3000]
    assert a["p95_wall_ns"] == 3000
    assert a["phase_mean_ns"]["compile"] == (600 + 1800 + 1200) // 3
    assert agg["bbb"]["count"] == 1
    assert agg["(none)"]["count"] == 1


def test_read_capsules_skips_bad_lines(tmp_path, capsys):
    d = tmp_path / "hist"
    d.mkdir()
    good = _capsule("aaa", 100, ts=5)
    (d / "history-9-1.jsonl").write_text(
        json.dumps(good) + "\n{not json\n\n")
    caps = history_report.read_capsules(str(d))
    assert len(caps) == 1 and caps[0]["fingerprint"] == "aaa"
    assert "skipped 1" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# diff: regressions ranked by the phase that moved
# ---------------------------------------------------------------------------

def test_diff_ranks_regression_by_moved_phase(tmp_path):
    base = [_capsule("slowplan", 1_000_000, ts=i,
                     phases={"device-compute": 800_000})
            for i in range(3)]
    base += [_capsule("okplan", 500_000, ts=i + 10,
                      phases={"device-compute": 400_000})
             for i in range(3)]
    # the induced regression: slowplan's wall doubles and the growth is
    # all compile (a recompile regression)
    cur = [_capsule("slowplan", 2_000_000, ts=i,
                    phases={"device-compute": 800_000,
                            "compile": 1_000_000})
           for i in range(3)]
    cur += [_capsule("okplan", 490_000, ts=i + 10,
                     phases={"device-compute": 390_000})
            for i in range(3)]
    cur += [_capsule("newplan", 100, ts=20)]  # no base -> not joined
    rows = history_report.diff_report(history_report.aggregate(base),
                                      history_report.aggregate(cur))
    assert [r["fingerprint"] for r in rows] == ["slowplan", "okplan"]
    top = rows[0]
    assert top["delta_ns"] == 1_000_000
    assert top["pct"] == 100.0
    assert top["phase"] == "compile"          # the mover, named
    assert top["phase_delta_ns"] == 1_000_000
    assert rows[1]["delta_ns"] < 0            # improvement at the bottom


# ---------------------------------------------------------------------------
# advisor goldens
# ---------------------------------------------------------------------------

def _findings(caps):
    return history_report.advise(history_report.aggregate(caps))


def test_advisor_silent_on_healthy_corpus():
    # traced once on the first run, program-cache hits thereafter
    caps = [_capsule("good", 1000, ts=i,
                     phases={"device-compute": 900},
                     dispatch={"dispatches": 50,
                               "traces": 1 if i == 0 else 0, "storms": 0},
                     upload={"uploads": 10, "per_buffer": 0})
            for i in range(3)]
    assert _findings(caps) == []


def test_advisor_recompile_storm_golden():
    # scenario A: an explicit storm event fired
    caps = [_capsule("stormy", 1000, ts=1,
                     dispatch={"dispatches": 9, "traces": 9, "storms": 2})]
    (f,) = _findings(caps)
    assert f["rule"] == "recompile-storm" and f["fingerprint"] == "stormy"
    assert f["evidence"]["storms"] == 2
    assert "advice" in f and f["advice"]
    # scenario B: no storm, but every repeat of the plan re-traced
    caps = [_capsule("churny", 1000, ts=i,
                     dispatch={"dispatches": 4, "traces": 2})
            for i in range(3)]
    (f,) = _findings(caps)
    assert f["rule"] == "recompile-storm"
    assert f["evidence"]["traces"] == 6 and f["evidence"]["runs"] == 3


def test_advisor_ici_eligible_golden():
    """Multi-device mesh + host shuffle bytes + zero ICI rounds/
    fallbacks: the lane never even tried — the one-conf fix."""
    caps = [_capsule("podplan", 1000, ts=1, mesh=8,
                     shuffle={"bytes": 1 << 20},
                     ici={"rounds": 0, "fallbacks": 0})]
    (f,) = _findings(caps)
    assert f["rule"] == "ici-eligible"
    assert f["evidence"]["mesh_devices"] == 8
    assert f["evidence"]["host_shuffle_bytes"] == 1 << 20
    assert "shuffle.ici.enabled" in f["advice"]
    # negatives: single device / lane already tried / lane degraded
    assert _findings([_capsule("x", 1000, mesh=1,
                               shuffle={"bytes": 1 << 20})]) == []
    assert _findings([_capsule("x", 1000, mesh=8,
                               shuffle={"bytes": 1 << 20},
                               ici={"rounds": 3})]) == []
    assert _findings([_capsule("x", 1000, mesh=8,
                               shuffle={"bytes": 1 << 20},
                               ici={"fallbacks": 1})]) == []


def test_advisor_skew_stall_upload_quota():
    caps = [_capsule("skewed", 1000, ts=1,
                     skew={"op": "HostShuffleExchangeExec#3",
                           "ratio": 9.5, "basis": "bytes",
                           "partitions": 16})]
    (f,) = _findings(caps)
    assert f["rule"] == "partition-skew" and f["evidence"]["ratio"] == 9.5

    caps = [_capsule("stally", 1_000_000, ts=1,
                     phases={"pipeline-stall": 400_000})]
    (f,) = _findings(caps)
    assert f["rule"] == "pipeline-stall"
    assert f["evidence"]["share"] == 0.4

    caps = [_capsule("buffery", 1000, ts=1,
                     upload={"uploads": 10, "per_buffer": 8})]
    (f,) = _findings(caps)
    assert f["rule"] == "per-buffer-upload"
    assert f["evidence"]["share"] == 0.8

    # quota-spill dominance is CROSS-plan: one plan owns the majority
    caps = [_capsule("hog", 1000, ts=1,
                     workload={"quota_spills": 9}),
            _capsule("meek", 1000, ts=2,
                     workload={"quota_spills": 1})]
    (f,) = _findings(caps)
    assert f["rule"] == "quota-spill-dominance"
    assert f["fingerprint"] == "hog"
    assert f["evidence"] == {"quota_spills": 9, "all_plans": 10,
                             "spill_bytes": 0}


def test_advisor_partition_skew_sharpens_to_adaptive_one_conf():
    """Closed loop (ISSUE 19): a skewed capsule whose adaptive family
    shows ZERO consults (the lane was off) gets the one-conf remedy —
    enable adaptive.enabled — instead of the manual repartition advice;
    a capsule where the lane DID consult keeps the static advice."""
    skew = {"op": "HostShuffleExchangeExec#3", "ratio": 9.5,
            "basis": "bytes", "partitions": 16}
    caps = [_capsule("skewed", 1000, ts=1, skew=skew)]
    (f,) = _findings(caps)
    assert f["rule"] == "partition-skew"
    assert f["evidence"]["adaptive_consults"] == 0
    assert "spark.rapids.tpu.adaptive.enabled" in f["advice"]
    assert "_advice" not in f["evidence"]
    caps = [_capsule("skewed", 1000, ts=1, skew=skew,
                     adaptive={"consults": 4, "skew_splits": 2})]
    (f,) = _findings(caps)
    assert f["rule"] == "partition-skew"
    assert f["evidence"]["skew_splits"] == 2
    assert "spark.rapids.tpu.adaptive.enabled" not in f["advice"]


def test_advisor_adaptive_demotion_storm_golden():
    """The replan lane repeatedly stood down behind an open `adaptive`
    breaker: the advisor names the misfiring lane."""
    caps = [_capsule("flappy", 1000, ts=1,
                     adaptive={"breaker_demotions": 5, "errors": 3,
                               "consults": 2, "skew_splits": 1})]
    (f,) = _findings(caps)
    assert f["rule"] == "adaptive-demotion-storm"
    assert f["evidence"] == {"breaker_demotions": 5, "errors": 3,
                             "skew_splits": 1, "consults": 2}
    assert "skewedPartitionFactor" in f["advice"]


# ---------------------------------------------------------------------------
# the CLI end-to-end: two history dirs, --diff, advisor, both formats
# ---------------------------------------------------------------------------

def test_cli_diff_end_to_end(tmp_path, capsys):
    """The acceptance flow: two capsule dirs (base vs current), --diff
    joins on fingerprint and ranks the induced regression by the phase
    that moved; the advisor section rides along; text and json agree."""
    base_d, cur_d = tmp_path / "base", tmp_path / "cur"
    _write_dir(base_d, [
        _capsule("deadbeef" * 5, 1_000_000, ts=i,
                 phases={"device-compute": 900_000}) for i in range(2)])
    _write_dir(cur_d, [
        _capsule("deadbeef" * 5, 1_600_000, ts=i, mesh=4,
                 phases={"device-compute": 900_000,
                         "host-pack-serialize": 600_000},
                 shuffle={"bytes": 1 << 22}) for i in range(2)])
    rc = history_report.main([str(cur_d), "--diff", str(base_d),
                              "--format", "json"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["capsules"] == 2
    (row,) = summary["diff"]
    assert row["fingerprint"] == "deadbeef" * 5
    assert row["delta_ns"] == 600_000
    assert row["phase"] == "host-pack-serialize"
    # the regression also made the plan ici-eligible -> advisor fires
    assert [f["rule"] for f in summary["advisor"]] == ["ici-eligible"]
    # text rendering carries the same story
    assert history_report.main([str(cur_d), "--diff", str(base_d)]) == 0
    text = capsys.readouterr().out
    assert "host-pack-serialize" in text
    assert "[ici-eligible]" in text
    # an empty dir exits 1 (nothing to report on)
    empty = tmp_path / "empty"
    empty.mkdir()
    assert history_report.main([str(empty)]) == 1


def test_real_session_capsules_join_across_dirs(tmp_path):
    """Fingerprint stability end-to-end: the SAME query shape run into
    two different history dirs (two 'bench runs') joins on fingerprint
    in --diff — no crafted capsules, the real store + real plans."""
    import numpy as np
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.aggexprs import Sum
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.types import LONG, Schema

    def run_into(d):
        history.enable(str(d))
        try:
            sess = TpuSession()
            rng = np.random.default_rng(1)
            df = sess.from_pydict(
                {"k": rng.integers(0, 5, 1000).tolist(),
                 "v": rng.integers(0, 100, 1000).tolist()},
                Schema.of(k=LONG, v=LONG))
            out = (df.filter(col("v") > lit(10)).group_by("k")
                     .agg((Sum(col("v")), "s")).collect())
            assert out
        finally:
            history.reset_history()

    run_into(tmp_path / "a")
    run_into(tmp_path / "b")
    base = history_report.aggregate(
        history_report.read_capsules(str(tmp_path / "a")))
    cur = history_report.aggregate(
        history_report.read_capsules(str(tmp_path / "b")))
    rows = history_report.diff_report(base, cur)
    assert len(rows) == 1, "the same plan did not join on fingerprint"
    assert rows[0]["fingerprint"] != "(none)"


# ---------------------------------------------------------------------------
# profile_report: the phase roll-up block (satellite)
# ---------------------------------------------------------------------------

def test_profile_report_phase_rollup(tmp_path, capsys):
    log = tmp_path / "events-1-1.jsonl"
    recs = [
        {"ts_ns": 1, "kind": "query_start", "query": 1, "root": "AggregateExec"},
        {"ts_ns": 2, "kind": "query_phases", "query": 1, "ok": True,
         "wall_ns": 1000, "attempts": 1, "priority": "interactive",
         "phases": {"compile": 600, "device-compute": 300, "other": 100}},
        {"ts_ns": 3, "kind": "query_end", "query": 1, "ok": True,
         "root": "AggregateExec", "wall_ns": 1000},
        {"ts_ns": 4, "kind": "query_phases", "query": 2, "ok": True,
         "wall_ns": 500, "attempts": 1, "priority": "batch",
         "phases": {"compile": 100, "shuffle-io": 400}},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert profile_report.main([str(log), "--format", "json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    ph = summary["phases"]
    assert ph["queries"] == 2
    assert ph["wall_ns"] == 1500
    assert ph["by_phase"]["compile"] == 700
    assert ph["by_phase"]["shuffle-io"] == 400
    assert ph["by_phase"]["other"] == 100
    text = profile_report.build_report(
        profile_report.read_event_files(str(log)))
    assert "wall-clock phases" in text
    assert "compile" in text


# ---------------------------------------------------------------------------
# suite_budget (satellite): the tier-1 time-budget table
# ---------------------------------------------------------------------------

SAMPLE_LOG = """\
============================= slowest durations ==============================
12.50s call     tests/test_big.py::test_storm
2.00s setup    tests/test_big.py::test_storm
1.25s call     tests/test_small.py::TestC::test_y[param-1]
0.30s teardown tests/test_small.py::TestC::test_y[param-1]
(12 durations < 0.005s hidden.)
========================== 3 passed in 16.05s ================================
"""


def test_suite_budget_parse_and_build():
    rows = suite_budget.parse_durations(SAMPLE_LOG.splitlines())
    assert len(rows) == 4
    b = suite_budget.build_budget(rows, budget_s=870.0, top=20)
    assert b["measured_s"] == pytest.approx(16.05)
    assert b["headroom_s"] == pytest.approx(870.0 - 16.05)
    # per-test totals merge call+setup+teardown; worst first
    assert b["top_tests"][0]["test"] == "tests/test_big.py::test_storm"
    assert b["top_tests"][0]["seconds"] == pytest.approx(14.5)
    assert b["top_files"][0]["file"] == "tests/test_big.py"
    assert b["top_files"][1]["seconds"] == pytest.approx(1.55)


def test_suite_budget_cli_and_warn_gate(tmp_path, capsys):
    log = tmp_path / "run.log"
    log.write_text(SAMPLE_LOG)
    assert suite_budget.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "tier-1 time budget" in out and "test_storm" in out
    # the early-warning gate: measured 16.05s > 80% of a 20s budget
    assert suite_budget.main([str(log), "--budget", "20"]) == 1
    capsys.readouterr()
    assert suite_budget.main([str(log), "--budget", "20",
                              "--format", "json"]) == 1
    b = json.loads(capsys.readouterr().out)
    assert b["budget_s"] == 20.0 and b["budget_share"] > 0.8
    # a log with no durations section is an error, not a silent pass
    empty = tmp_path / "empty.log"
    empty.write_text("=== 3 passed in 1.00s ===\n")
    assert suite_budget.main([str(empty)]) == 1
