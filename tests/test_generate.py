"""GenerateExec (explode/posexplode, outer variants) vs Python oracle
(reference GpuGenerateExec.scala:829; integration analog
generate_expr_test.py)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    LONG, STRING, ArrayType, Schema, StructField,
)

ARRS = [[1, 2], [], None, [5], [7, None], [10, 20, 30]]
NS = list(range(len(ARRS)))


@pytest.fixture(scope="module")
def df():
    s = TpuSession()
    sch = Schema((StructField("n", LONG),
                  StructField("a", ArrayType(LONG))))
    return s.from_pydict({"n": NS, "a": ARRS}, sch)


def test_explode(df):
    got = df.explode("a", alias="e").collect()
    exp = [(n, a, x) for n, a in zip(NS, ARRS) if a for x in a]
    assert sorted(got, key=str) == sorted(exp, key=str)


def test_explode_outer(df):
    got = df.explode("a", alias="e", outer=True).collect()
    exp = []
    for n, a in zip(NS, ARRS):
        if a:
            exp.extend((n, a, x) for x in a)
        else:
            exp.append((n, a, None))
    assert sorted(got, key=str) == sorted(exp, key=str)


def test_posexplode(df):
    got = df.posexplode("a", alias="e").collect()
    exp = [(n, a, i, x) for n, a in zip(NS, ARRS) if a
           for i, x in enumerate(a)]
    assert sorted(got, key=str) == sorted(exp, key=str)


def test_posexplode_outer(df):
    got = df.posexplode("a", alias="e", outer=True).collect()
    exp = []
    for n, a in zip(NS, ARRS):
        if a:
            exp.extend((n, a, i, x) for i, x in enumerate(a))
        else:
            exp.append((n, a, None, None))
    assert sorted(got, key=str) == sorted(exp, key=str)


def test_explode_strings():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(STRING)),))
    arrs = [["x", "yy"], None, ["zzz"]]
    df = s.from_pydict({"a": arrs}, sch)
    got = df.explode("a", alias="e").select("e").collect()
    assert sorted(r[0] for r in got) == ["x", "yy", "zzz"]


def test_explode_then_aggregate(df):
    got = df.explode("a", alias="e").group_by().agg(
        (F.sum("e"), "s"), (F.count("e"), "c")).collect()
    flat = [x for a in ARRS if a for x in a if x is not None]
    assert got == [(sum(flat), len(flat))]


def test_explode_of_create_array():
    s = TpuSession()
    sch = Schema((StructField("x", LONG), StructField("y", LONG)))
    df = s.from_pydict({"x": [1, 2], "y": [10, 20]}, sch)
    df2 = df.explode(F.array(col("x"), col("y")), alias="v")
    got = sorted(r[-1] for r in df2.collect())
    assert got == [1, 2, 10, 20]


def test_explode_in_plan_explain(df):
    tree = df.explode("a")._exec().tree_string()
    assert "GenerateExec[Explode" in tree


def test_explode_duplicates_string_payload():
    """Duplicating variable-size payload columns must size output buckets
    from measured needs (review regression: long strings truncated)."""
    s = TpuSession()
    sch = Schema((StructField("s", STRING),
                  StructField("t", ArrayType(STRING)),
                  StructField("a", ArrayType(LONG))))
    big = "x" * 500
    tags = ["tag_" + "y" * 60, "q"]
    df = s.from_pydict({"s": [big, "z"], "t": [tags, []],
                        "a": [[1, 2, 3, 4, 5, 6], [7]]}, sch)
    out = df.explode("a", alias="e").collect()
    assert len(out) == 7
    for s_val, t_val, a_val, e in out:
        if a_val == [7]:
            assert (s_val, t_val) == ("z", [])
        else:
            assert s_val == big and t_val == tags
