"""Hash parity tests. Device murmur3/xxhash64 is cross-checked against an
independent pure-python implementation of Spark's Murmur3_x86_32 /
XXH64 (written from the xxHash spec + Spark's hashUnsafeBytes layout)."""

import numpy as np
import pytest

from spark_rapids_tpu.types import DOUBLE, INT, LONG, STRING, Schema
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.ops.hashing import murmur3_batch, pmod, xxhash64_batch

M32 = 0xFFFFFFFF
M64 = 0xFFFFFFFFFFFFFFFF


# --- reference murmur3 (Spark Murmur3_x86_32) -----------------------------

def rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & M32


def mix_k1(k1):
    k1 = (k1 * 0xCC9E2D51) & M32
    k1 = rotl32(k1, 15)
    return (k1 * 0x1B873593) & M32


def mix_h1(h1, k1):
    h1 ^= k1
    h1 = rotl32(h1, 13)
    return (h1 * 5 + 0xE6546B64) & M32


def fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & M32
    h1 ^= h1 >> 16
    return h1


def py_murmur3_int(v, seed):
    return fmix(mix_h1(seed, mix_k1(v & M32)), 4)


def py_murmur3_long(v, seed):
    v &= M64
    h1 = mix_h1(seed, mix_k1(v & M32))
    h1 = mix_h1(h1, mix_k1(v >> 32))
    return fmix(h1, 8)


def py_murmur3_bytes(data: bytes, seed):
    h1 = seed
    n = len(data)
    for i in range(0, n - n % 4, 4):
        word = int.from_bytes(data[i : i + 4], "little")
        h1 = mix_h1(h1, mix_k1(word))
    for i in range(n - n % 4, n):
        b = data[i]
        if b >= 128:
            b -= 256  # sign extension like Java's getByte
        h1 = mix_h1(h1, mix_k1(b & M32))
    return fmix(h1, n)


def to_i32(x):
    return x - 2**32 if x >= 2**31 else x


# --- reference xxh64 ------------------------------------------------------

P1, P2, P3, P4, P5 = (0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F,
                      0x165667B19E3779F9, 0x85EBCA77C2B2AE63,
                      0x27D4EB2F165667C5)


def rotl64(x, r):
    return ((x << r) | (x >> (64 - r))) & M64


def xx_fmix(h):
    h ^= h >> 33
    h = (h * P2) & M64
    h ^= h >> 29
    h = (h * P3) & M64
    h ^= h >> 32
    return h


def py_xx_long(v, seed):
    h = (seed + P5 + 8) & M64
    k = rotl64((v * P2) & M64, 31) * P1 & M64
    h = (rotl64(h ^ k, 27) * P1 + P4) & M64
    return xx_fmix(h)


def py_xx_int(v, seed):
    h = (seed + P5 + 4) & M64
    h ^= ((v & M32) * P1) & M64
    h = (rotl64(h, 23) * P2 + P3) & M64
    return xx_fmix(h)


def py_xx_bytes(data: bytes, seed):
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + P1 + P2) & M64
        v2 = (seed + P2) & M64
        v3 = seed & M64
        v4 = (seed - P1) & M64
        while i + 32 <= n:
            for k, v in enumerate((v1, v2, v3, v4)):
                w = int.from_bytes(data[i + 8 * k : i + 8 * k + 8], "little")
                nv = (rotl64((v + w * P2) & M64, 31) * P1) & M64
                if k == 0:
                    v1 = nv
                elif k == 1:
                    v2 = nv
                elif k == 2:
                    v3 = nv
                else:
                    v4 = nv
            i += 32
        h = (rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18)) & M64
        for v in (v1, v2, v3, v4):
            h ^= (rotl64((v * P2) & M64, 31) * P1) & M64
            h = (h * P1 + P4) & M64
    else:
        h = (seed + P5) & M64
    h = (h + n) & M64
    while i + 8 <= n:
        w = int.from_bytes(data[i : i + 8], "little")
        k = (rotl64((w * P2) & M64, 31) * P1) & M64
        h = (rotl64(h ^ k, 27) * P1 + P4) & M64
        i += 8
    if i + 4 <= n:
        w = int.from_bytes(data[i : i + 4], "little")
        h = (rotl64(h ^ ((w * P1) & M64), 23) * P2 + P3) & M64
        i += 4
    while i < n:
        h = (rotl64(h ^ ((data[i] * P5) & M64), 11) * P1) & M64
        i += 1
    return xx_fmix(h)


def to_i64(x):
    return x - 2**64 if x >= 2**63 else x


# --- tests ----------------------------------------------------------------

def test_murmur3_ints():
    vals = [0, 1, -1, 42, 2**31 - 1, -(2**31), 123456789]
    b = ColumnarBatch.from_pydict({"i": vals}, Schema.of(i=INT))
    out = np.asarray(murmur3_batch(b.columns))[: len(vals)]
    exp = [to_i32(py_murmur3_int(v, 42)) for v in vals]
    assert out.tolist() == exp


def test_murmur3_longs():
    vals = [0, 1, -1, 42, 2**63 - 1, -(2**63), 987654321012345]
    b = ColumnarBatch.from_pydict({"l": vals}, Schema.of(l=LONG))
    out = np.asarray(murmur3_batch(b.columns))[: len(vals)]
    exp = [to_i32(py_murmur3_long(v, 42)) for v in vals]
    assert out.tolist() == exp


def test_murmur3_multi_column_null_passthrough():
    b = ColumnarBatch.from_pydict(
        {"i": [1, None, 3], "l": [None, 5, 6]}, Schema.of(i=INT, l=LONG))
    out = np.asarray(murmur3_batch(b.columns))[:3]
    exp = [
        to_i32(py_murmur3_int(1, 42)),             # null long leaves hash
        to_i32(py_murmur3_long(5, 42)),            # null int leaves seed
        to_i32(py_murmur3_long(6, py_murmur3_int(3, 42))),
    ]
    assert out.tolist() == exp


def test_murmur3_strings():
    vals = ["", "a", "ab", "abc", "abcd", "abcde", "Hello TPU world!", "日本語",
            "0123456789abcdef0123456789abcdef!"]
    b = ColumnarBatch.from_pydict({"s": vals}, Schema.of(s=STRING))
    out = np.asarray(murmur3_batch(b.columns))[: len(vals)]
    exp = [to_i32(py_murmur3_bytes(v.encode("utf-8"), 42)) for v in vals]
    assert out.tolist() == exp


def test_murmur3_double_negzero():
    b = ColumnarBatch.from_pydict({"x": [-0.0, 0.0]}, Schema.of(x=DOUBLE))
    out = np.asarray(murmur3_batch(b.columns))[:2]
    assert out[0] == out[1]  # -0.0 normalized


def test_xxhash64_fixed():
    vals = [0, 1, -1, 42, 2**63 - 1, -(2**63)]
    b = ColumnarBatch.from_pydict({"l": vals}, Schema.of(l=LONG))
    out = np.asarray(xxhash64_batch(b.columns))[: len(vals)]
    exp = [to_i64(py_xx_long(v & M64, 42)) for v in vals]
    assert out.tolist() == exp

    ivals = [0, 5, -5, 2**31 - 1]
    bi = ColumnarBatch.from_pydict({"i": ivals}, Schema.of(i=INT))
    outi = np.asarray(xxhash64_batch(bi.columns))[: len(ivals)]
    expi = [to_i64(py_xx_int(v, 42)) for v in ivals]
    assert outi.tolist() == expi


def test_xxhash64_strings():
    vals = ["", "a", "abcd", "abcdefgh", "0123456789abcdef",
            "0123456789abcdef0123456789abcdef",  # exactly 32
            "0123456789abcdef0123456789abcdefXYZ",  # 32 + tail
            "x" * 100]
    b = ColumnarBatch.from_pydict({"s": vals}, Schema.of(s=STRING))
    out = np.asarray(xxhash64_batch(b.columns))[: len(vals)]
    exp = [to_i64(py_xx_bytes(v.encode(), 42)) for v in vals]
    assert out.tolist() == exp


def test_pmod():
    import jax.numpy as jnp
    h = jnp.asarray([-5, 5, -1, 0], jnp.int32)
    assert np.asarray(pmod(h, 4)).tolist() == [3, 1, 3, 0]
