"""Multi-host topology tests (SURVEY §2.10: ICI intra-slice + DCN
inter-slice mapping). Real multi-process isn't available in CI; the
topology math runs against the 8 virtual devices and fake device
objects."""

import jax
import pytest

from spark_rapids_tpu.parallel.multihost import (build_query_mesh,
                                                 dcn_axis_size,
                                                 group_devices_by_host,
                                                 ici_axis_size,
                                                 initialize_distributed,
                                                 topology_shape)


class _FakeDev:
    def __init__(self, pid, i):
        self.process_index = pid
        self.id = i

    def __repr__(self):
        return f"dev({self.process_index},{self.id})"


def test_single_process_initialize_is_noop():
    assert initialize_distributed() is False  # no env, no pod metadata


def test_group_and_shape_virtual_devices():
    devs = jax.devices()
    n_hosts, per_host = topology_shape(devs)
    assert n_hosts == 1 and per_host == len(devs)


def test_mesh_axes_single_host():
    mesh = build_query_mesh(jax.devices())
    assert dcn_axis_size(mesh) == 1
    assert ici_axis_size(mesh) == len(jax.devices())


def test_fake_multihost_grid():
    devs = [_FakeDev(pid, i) for pid in (1, 0, 2) for i in range(4)]
    groups = group_devices_by_host(devs)
    assert [g[0].process_index for g in groups] == [0, 1, 2]
    assert topology_shape(devs) == (3, 4)


def test_ragged_topology_rejected():
    devs = [_FakeDev(0, 0), _FakeDev(0, 1), _FakeDev(1, 0)]
    with pytest.raises(RuntimeError, match="ragged"):
        topology_shape(devs)
