"""WindowExec tests against hand oracles: running/unbounded/bounded frames,
rank family, lag/lead, ties under the default RANGE frame."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.exec.window import WindowExec
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.expr.windowexprs import (
    DenseRank, FirstValue, Lag, LastValue, Lead, Rank, RowNumber, WindowAgg,
    WindowFrame, window,
)
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)

SCHEMA = Schema((StructField("p", STRING), StructField("o", INT),
                 StructField("v", INT)))
DATA = {
    "p": ["a", "a", "a", "b", "b", "a", "b"],
    "o": [1, 2, 2, 1, 3, 3, 2],
    "v": [10, 20, 30, 5, 15, 40, None],
}


def scan(data=DATA, schema=SCHEMA, split=0):
    n = len(next(iter(data.values())))
    if split:
        batches = [ColumnarBatch.from_pydict(
            {k: v[s:s + split] for k, v in data.items()}, schema)
            for s in range(0, n, split)]
    else:
        batches = [ColumnarBatch.from_pydict(data, schema)]
    return InMemoryScanExec(batches, schema)


def rows_by_key(rows):
    return {(r[0], r[1], r[2]): r[3:] for r in rows}


def test_row_number_and_ranks():
    spec = window(partition_by=["p"], order_by=["o"])
    plan = WindowExec([(RowNumber().over(spec), "rn"),
                       (Rank().over(spec), "rk"),
                       (DenseRank().over(spec), "dr")], scan())
    got = plan.collect()
    # partition a sorted by o: (1,10) (2,20) (2,30) (3,40)
    a = [r for r in got if r[0] == "a"]
    assert [(r[1], r[3], r[4], r[5]) for r in a] == [
        (1, 1, 1, 1), (2, 2, 2, 2), (2, 3, 2, 2), (3, 4, 4, 3)]
    b = [r for r in got if r[0] == "b"]
    assert [(r[1], r[3], r[4], r[5]) for r in b] == [
        (1, 1, 1, 1), (2, 2, 2, 2), (3, 3, 3, 3)]


def test_running_sum_with_ties():
    # default frame: RANGE UNBOUNDED..CURRENT ROW -> ties share the value
    spec = window(partition_by=["p"], order_by=["o"])
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "rs")],
                      scan(split=3))
    got = [r for r in plan.collect() if r[0] == "a"]
    assert [r[3] for r in got] == [10, 60, 60, 100]


@pytest.mark.slow  # ~10s; running-sum semantics kept tier-1 via the with-ties variant: nightly tier (round-7 budget move, redundant tier-1 coverage)
def test_rows_running_sum_no_ties_semantics():
    spec = window(partition_by=["p"], order_by=["o"],
                  frame=WindowFrame.rows(None, 0))
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "rs")],
                      scan())
    got = [r for r in plan.collect() if r[0] == "a"]
    assert [r[3] for r in got] == [10, 30, 60, 100]


@pytest.mark.slow  # ~7s; unbounded-frame agg nightly (round-7 budget move)
def test_whole_partition_agg():
    spec = window(partition_by=["p"])
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "t"),
                       (WindowAgg("count", col("v")).over(spec), "c"),
                       (WindowAgg("max", col("v")).over(spec), "mx")],
                      scan())
    for r in plan.collect():
        if r[0] == "a":
            assert r[3:] == (100, 4, 40)
        else:
            assert r[3:] == (20, 2, 15)  # 5+15, None excluded


@pytest.mark.slow  # ~6s; bounded-rows sum nightly, min/max frame kept tier-1 (round-7 budget move)
def test_bounded_rows_frame_sum():
    spec = window(partition_by=["p"], order_by=["o"],
                  frame=WindowFrame.rows(1, 1))
    plan = WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                      scan())
    a = [r[3] for r in plan.collect() if r[0] == "a"]
    # sorted a rows: 10,20,30,40 -> windows: 30,60,90,70
    assert a == [30, 60, 90, 70]


# moved to the slow tier by ISSUE 13 budget relief (6s: overlaps the
# bounded min/max frame tests kept tier-1)
@pytest.mark.slow
def test_running_min_max():
    spec = window(partition_by=["p"], order_by=["o"],
                  frame=WindowFrame.rows(None, 0))
    data = {"p": ["x"] * 5, "o": [1, 2, 3, 4, 5], "v": [3, 1, None, 5, 2]}
    plan = WindowExec([(WindowAgg("min", col("v")).over(spec), "mn"),
                       (WindowAgg("max", col("v")).over(spec), "mx")],
                      scan(data))
    got = plan.collect()
    assert [r[3] for r in got] == [3, 1, 1, 1, 1]
    assert [r[4] for r in got] == [3, 3, 3, 5, 5]


def test_lag_lead():
    spec = window(partition_by=["p"], order_by=["o"])
    plan = WindowExec([(Lag(col("v"), 1).over(spec), "lg"),
                       (Lead(col("v"), 1).over(spec), "ld")], scan())
    a = [r for r in plan.collect() if r[0] == "a"]
    assert [r[3] for r in a] == [None, 10, 20, 30]
    assert [r[4] for r in a] == [20, 30, 40, None]


@pytest.mark.slow  # ~9s; lag/lead defaults also covered by test_lag_lead: nightly tier (round-7 budget move, redundant tier-1 coverage)
def test_lag_default_value():
    spec = window(partition_by=["p"], order_by=["o"])
    data = {"p": ["x", "x"], "o": [1, 2], "v": [7, 8]}
    plan = WindowExec([(Lag(col("v"), 1, default=-1).over(spec), "lg")],
                      scan(data))
    assert [r[3] for r in plan.collect()] == [-1, 7]


def test_first_last_value():
    spec = window(partition_by=["p"], order_by=["o"])
    plan = WindowExec([(FirstValue(col("v")).over(spec), "fv"),
                       (LastValue(col("v")).over(spec), "lv")], scan())
    a = [r for r in plan.collect() if r[0] == "a"]
    assert [r[3] for r in a] == [10, 10, 10, 10]
    # default frame last_value = end of current order group (ties)
    assert [r[4] for r in a] == [10, 30, 30, 40]


def test_no_partition_window():
    spec = window(order_by=["o"])
    data = {"p": ["x", "y", "z"], "o": [3, 1, 2], "v": [1, 2, 3]}
    plan = WindowExec([(RowNumber().over(spec), "rn")], scan(data))
    got = {r[1]: r[3] for r in plan.collect()}
    assert got == {1: 1, 2: 2, 3: 3}


def test_avg_window():
    spec = window(partition_by=["p"])
    plan = WindowExec([(WindowAgg("avg", col("v")).over(spec), "av")],
                      scan())
    for r in plan.collect():
        if r[0] == "a":
            assert r[3] == pytest.approx(25.0)
        else:
            assert r[3] == pytest.approx(10.0)


def test_window_via_dataframe_api():
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.expr.windowexprs import window
    s = TpuSession()
    d = s.from_pydict(DATA, SCHEMA)
    w = window(partition_by=["p"], order_by=["o"])
    out = d.with_windows((F.row_number().over(w), "rn"),
                         (F.window_sum("v").over(w), "rs"))
    report = out.explain()
    assert "* Window" in report
    a = [r for r in out.collect() if r[0] == "a"]
    assert [r[3] for r in a] == [1, 2, 3, 4]
    assert [r[4] for r in a] == [10, 60, 60, 100]


def _minmax_oracle(vals, p, f, want_max):
    """Python oracle for ROWS [i-p, i+f] min/max, None = unbounded."""
    n = len(vals)
    out = []
    for i in range(n):
        a = 0 if p is None else max(i - p, 0)
        b = n - 1 if f is None else min(i + f, n - 1)
        window_vals = [v for v in vals[a:b + 1] if v is not None]
        out.append((max(window_vals) if want_max else min(window_vals))
                   if window_vals else None)
    return out


@pytest.mark.parametrize("p,f", [
    (1, 1), (0, 2),
    # the remaining frame shapes cover the same kernel paths with other
    # bound mixes (~35s on the single-core box): nightly tier (ISSUE 3
    # budget move, same policy as PR 1/2)
    pytest.param(2, 0, marks=pytest.mark.slow),
    pytest.param(2, 1, marks=pytest.mark.slow),
    pytest.param(None, 2, marks=pytest.mark.slow),
    pytest.param(3, None, marks=pytest.mark.slow),
])
def test_bounded_min_max_frames(p, f):
    """The sparse-table sliding extrema kernel vs a Python oracle
    (reference GpuBatchedBoundedWindowExec.scala:220)."""
    rng = np.random.default_rng(17)
    n = 60
    parts = sorted(["x", "y", "z"][i] for i in rng.integers(0, 3, n))
    vals = [None if rng.random() < 0.2 else int(v)
            for v in rng.integers(-50, 50, n)]
    data = {"p": parts, "o": list(range(n)), "v": vals}
    spec = window(partition_by=["p"], order_by=["o"],
                  frame=WindowFrame.rows(p, f))
    plan = WindowExec([(WindowAgg("min", col("v")).over(spec), "mn"),
                       (WindowAgg("max", col("v")).over(spec), "mx")],
                      scan(data, split=16))
    got = sorted(plan.collect(), key=lambda r: r[1])
    by_part = {}
    for part, o, v in zip(parts, data["o"], vals):
        by_part.setdefault(part, []).append((o, v))
    exp_mn, exp_mx = {}, {}
    for part, items in by_part.items():
        items.sort()
        vs = [v for _, v in items]
        mns = _minmax_oracle(vs, p, f, False)
        mxs = _minmax_oracle(vs, p, f, True)
        for (o, _), mn, mx in zip(items, mns, mxs):
            exp_mn[o], exp_mx[o] = mn, mx
    for part, o, v, mn, mx in got:
        assert mn == exp_mn[o], (o, mn, exp_mn[o])
        assert mx == exp_mx[o], (o, mx, exp_mx[o])


@pytest.mark.slow  # ~8s; empty-frame semantics nightly, bounded frames stay tier-1 (round-7 budget move)
def test_bounded_min_max_empty_frame():
    """Frame entirely outside (2 PRECEDING .. 1 PRECEDING at row 0)."""
    data = {"p": ["x"] * 4, "o": [1, 2, 3, 4], "v": [7, 3, 9, 1]}
    spec = window(partition_by=["p"], order_by=["o"],
                  frame=WindowFrame.rows(2, -1))
    plan = WindowExec([(WindowAgg("min", col("v")).over(spec), "mn")],
                      scan(data))
    assert [r[3] for r in plan.collect()] == [None, 7, 3, 3]


def _chunked_vs_reference(ks, vs, sch, num_batches=12):
    """Shared harness: run the same windowed sum chunked vs single-batch
    and return (chunked outputs, sorted rows, sorted reference rows)."""
    n_rows = len(ks)

    def mk_plan(nb):
        per = n_rows // nb
        batches = [ColumnarBatch.from_pydict(
            {"k": ks[i * per:(i + 1) * per],
             "v": vs[i * per:(i + 1) * per]}, sch)
            for i in range(nb)]
        spec = window(partition_by=["k"], order_by=["v"],
                      frame=WindowFrame.rows(None, 0))
        return WindowExec([(WindowAgg("sum", col("v")).over(spec), "s")],
                          InMemoryScanExec(batches, sch))

    def skey(r):
        return (r[0] is None, str(r[0]) if r[0] is not None else "",
                r[1], r[2])

    outs = list(mk_plan(num_batches).execute())
    got = sorted((r for b in outs for r in b.to_pylist()), key=skey)
    ref = sorted((r for b in mk_plan(1).execute() for r in b.to_pylist()),
                 key=skey)
    return outs, got, ref


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_partition_aligned_chunked_window():
    # >MERGE_FAN_IN child batches engage the out-of-core sorted stream:
    # the window must emit MULTIPLE batches (concat-all is gone) with
    # partitions never split across outputs, and results must equal the
    # single-batch reference run
    import random
    rng = random.Random(13)
    ks = [rng.randint(0, 40) for _ in range(600)]
    vs = [rng.randint(-100, 100) for _ in range(600)]
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    outs, got, ref = _chunked_vs_reference(ks, vs, sch)
    assert len(outs) > 1, "expected multiple output batches"
    assert got == ref


@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_partition_aligned_chunks_string_keys_with_nulls():
    # string partition keys incl. NULLs across chunk boundaries: the
    # boundary detector compares null rows by validity, not stale bytes
    import random
    rng = random.Random(29)
    ks = [None if rng.random() < 0.2 else f"key{rng.randint(0, 20):03d}"
          for _ in range(480)]
    vs = [rng.randint(-50, 50) for _ in range(480)]
    sch = Schema((StructField("k", STRING), StructField("v", LONG)))
    outs, got, ref = _chunked_vs_reference(ks, vs, sch)
    assert len(outs) > 1
    assert got == ref
