"""Host shuffle (MULTITHREADED mode) tests: native LZ4 codec roundtrip,
batch serializer framing for every column shape, writer/reader file
contract, and the planner-integrated host-shuffled aggregate and join
(reference analogs: RapidsShuffleThreadedWriterBase/ReaderBase unit suites
and the shuffle integration tests; SURVEY §2.5/§4)."""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.native import (lz4_available, lz4_compress,
                                     lz4_decompress, xxh64)
from spark_rapids_tpu.shuffle import deserialize_batch, serialize_batch
from spark_rapids_tpu.shuffle.manager import (HostShuffleReader,
                                              HostShuffleWriter,
                                              partition_batch_host,
                                              shuffle_manager)
from spark_rapids_tpu.types import (DOUBLE, INT, LONG, STRING, ArrayType,
                                    Schema, StructField)


def _sorted(rows):
    return sorted(rows, key=lambda r: tuple(
        (x is None, tuple(x) if isinstance(x, list) else x) for x in r))


# ---------------------------------------------------------------------------
# native codec
# ---------------------------------------------------------------------------

def test_native_codec_builds():
    assert lz4_available(), "g++ toolchain is baked into the image"


def test_lz4_roundtrip_shapes():
    rng = np.random.default_rng(0)
    for n in (0, 1, 4, 11, 64, 1000, 1 << 16):
        for data in (bytes(rng.integers(0, 256, n, dtype=np.uint8)),
                     b"x" * n,
                     (b"spark" * (n // 5 + 1))[:n]):
            c = lz4_compress(data)
            assert lz4_decompress(c, len(data)) == data


def test_lz4_rejects_corrupt():
    data = b"hello shuffle world " * 100
    c = bytearray(lz4_compress(data))
    c[len(c) // 2] ^= 0xFF
    with pytest.raises((ValueError, RuntimeError)):
        if lz4_decompress(bytes(c), len(data)) != data:
            raise ValueError("corrupt")


def test_xxh64_canonical_vectors():
    assert xxh64(b"") == 0xEF46DB3751D8E999
    assert xxh64(b"a") == 0xD24EC4F1A98C6E5B


# ---------------------------------------------------------------------------
# serializer
# ---------------------------------------------------------------------------

def _rich_schema():
    return Schema((
        StructField("i", INT), StructField("l", LONG),
        StructField("d", DOUBLE), StructField("s", STRING),
        StructField("a", ArrayType(LONG)),
    ))


def _rich_batch(n=97):
    rng = np.random.default_rng(7)
    data = {
        "i": [None if x % 11 == 0 else int(x) for x in range(n)],
        "l": [int(x) for x in rng.integers(-10**12, 10**12, n)],
        "d": [None if x % 7 == 0 else float(rng.standard_normal())
              for x in range(n)],
        "s": [None if x % 5 == 0 else ("värde-%d" % x) * (x % 4)
              for x in range(n)],
        "a": [None if x % 9 == 0 else [int(v) for v in range(x % 5)]
              for x in range(n)],
    }
    return ColumnarBatch.from_pydict(data, _rich_schema()), data


def test_serializer_roundtrip_rich_types():
    batch, _ = _rich_batch()
    frame = serialize_batch(batch)
    out = deserialize_batch(frame, batch.schema)
    assert out.to_pylist() == batch.to_pylist()


def test_serializer_trims_padding():
    # a nearly-empty batch in a big capacity bucket must serialize small
    b = ColumnarBatch.from_pydict(
        {"l": [1, 2, 3]}, Schema((StructField("l", LONG),)),
        capacity=1 << 16)
    assert len(serialize_batch(b)) < 1024


def test_serializer_schema_mismatch_detected():
    batch, _ = _rich_batch()
    frame = serialize_batch(batch)
    other = Schema((StructField("x", LONG),))
    with pytest.raises(ValueError, match="schema"):
        deserialize_batch(frame, other)


def test_serializer_checksum_detects_corruption():
    batch, _ = _rich_batch()
    frame = bytearray(serialize_batch(batch))
    frame[-3] ^= 0x55
    with pytest.raises(ValueError, match="checksum|corrupt"):
        deserialize_batch(bytes(frame), batch.schema)


def test_empty_batch_roundtrip():
    sch = Schema((StructField("s", STRING), StructField("l", LONG)))
    b = ColumnarBatch.from_pydict({"s": [], "l": []}, sch)
    out = deserialize_batch(serialize_batch(b), sch)
    assert out.num_rows_host == 0
    assert out.to_pylist() == []


# ---------------------------------------------------------------------------
# partition split + writer/reader file contract
# ---------------------------------------------------------------------------

def test_partition_split_and_file_roundtrip():
    batch, data = _rich_batch(200)
    n_parts = 4
    rng = np.random.default_rng(1)
    pid = rng.integers(0, n_parts, 200)
    parts = partition_batch_host(batch, pid, n_parts)
    assert sum(p.num_rows_host for p in parts) == 200
    # every partition holds exactly its rows, in stable order
    rows = batch.to_pylist()
    for p in range(n_parts):
        expect = [rows[i] for i in range(200) if pid[i] == p]
        assert parts[p].to_pylist() == expect

    mgr = shuffle_manager()
    handle = mgr.register(n_parts, batch.schema)
    try:
        w = HostShuffleWriter(handle, map_id=0, manager=mgr)
        w.write([[p] if p.num_rows_host else [] for p in parts])
        assert w.bytes_written > 0
        r = HostShuffleReader(handle, mgr)
        for p in range(n_parts):
            got = [row for b in r.read_partition(p)
                   for row in b.to_pylist()]
            expect = [rows[i] for i in range(200) if pid[i] == p]
            assert got == expect
    finally:
        mgr.unregister(handle)


def test_multi_map_reader_merges_all_outputs():
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    mgr = shuffle_manager()
    handle = mgr.register(2, sch)
    try:
        for map_id in range(3):
            b = ColumnarBatch.from_pydict(
                {"k": [0, 1], "v": [map_id * 10, map_id * 10 + 1]}, sch)
            parts = partition_batch_host(b, np.array([0, 1]), 2)
            HostShuffleWriter(handle, map_id, mgr).write(
                [[p] for p in parts])
        r = HostShuffleReader(handle, mgr)
        got0 = [row for b in r.read_partition(0) for row in b.to_pylist()]
        got1 = [row for b in r.read_partition(1) for row in b.to_pylist()]
        assert sorted(got0) == [(0, 0), (0, 10), (0, 20)]
        assert sorted(got1) == [(1, 1), (1, 11), (1, 21)]
    finally:
        mgr.unregister(handle)


def test_unregister_removes_files():
    import os
    sch = Schema((StructField("v", LONG),))
    mgr = shuffle_manager()
    handle = mgr.register(1, sch)
    b = ColumnarBatch.from_pydict({"v": [1, 2]}, sch)
    HostShuffleWriter(handle, 0, mgr).write([[b]])
    paths = list(handle.map_outputs)
    assert all(os.path.exists(p) for p in paths)
    mgr.unregister(handle)
    assert not any(os.path.exists(p) for p in paths)


# ---------------------------------------------------------------------------
# planner integration: host-shuffled aggregate and join
# ---------------------------------------------------------------------------

def _host_shuffle_session(parts=4):
    return TpuSession({
        "spark.rapids.sql.shuffle.partitions": str(parts),
        "spark.rapids.sql.broadcastSizeThreshold": "-1",
    })


@pytest.mark.slow  # ~11s; host-shuffle equality kept tier-1 via the join variant (round-7 budget move)
def test_host_shuffled_aggregate_matches_single():
    rng = np.random.default_rng(3)
    n = 500
    data = {"k": [int(x) for x in rng.integers(0, 13, n)],
            "v": [None if x % 17 == 0 else int(x)
                  for x in rng.integers(-100, 100, n)]}
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))

    def q(sess):
        df = sess.from_pydict(data, sch, batch_rows=64)
        return df.group_by("k").agg((F.sum(col("v")), "sv"),
                                    (F.count(), "c")).collect()

    shuffled_sess = _host_shuffle_session()
    df = shuffled_sess.from_pydict(data, sch, batch_rows=64)
    tree = df.group_by("k").agg((F.sum(col("v")), "sv"),
                                (F.count(), "c"))._exec().tree_string()
    assert "HostShuffleExchangeExec" in tree
    assert _sorted(q(shuffled_sess)) == _sorted(q(TpuSession()))


def test_host_shuffled_join_matches_single():
    rng = np.random.default_rng(4)
    ldata = {"k": [int(x) for x in rng.integers(0, 20, 300)],
             "v": [int(x) for x in rng.integers(0, 50, 300)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 20, 200)],
             "w": [["a", "bb", None, "dddd"][int(x)]
                   for x in rng.integers(0, 4, 200)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", STRING)))

    def q(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=64)
        r = sess.from_pydict(rdata, rsch, batch_rows=64)
        return l.join(r, on="k").collect()

    shuffled = _host_shuffle_session()
    l = shuffled.from_pydict(ldata, lsch, batch_rows=64)
    r = shuffled.from_pydict(rdata, rsch, batch_rows=64)
    tree = l.join(r, on="k")._exec().tree_string()
    assert "HostShuffleExchangeExec" in tree
    assert "ShuffledHashJoinExec" in tree
    assert _sorted(q(shuffled)) == _sorted(q(TpuSession()))


@pytest.mark.parametrize("jt", ["left_outer", "full_outer", "left_anti"])
def test_host_shuffled_outer_joins(jt):
    rng = np.random.default_rng(5)
    ldata = {"k": [int(x) for x in rng.integers(0, 30, 200)],
             "v": [int(x) for x in rng.integers(0, 9, 200)]}
    rdata = {"k": [int(x) for x in rng.integers(15, 45, 150)],
             "w": [int(x) for x in rng.integers(0, 9, 150)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", LONG)))

    def q(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=64)
        r = sess.from_pydict(rdata, rsch, batch_rows=64)
        return l.join(r, on="k", how=jt).collect()

    assert _sorted(q(_host_shuffle_session(3))) == _sorted(q(TpuSession()))
