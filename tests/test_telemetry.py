"""Runtime statistics & live telemetry plane (ISSUE 11): log2-histogram
units vs numpy oracles, exchange statistics exactness + the
QueryProfile.statistics() golden surface, disabled-mode zero-emission
(the PR 2 cost discipline), live active_queries() introspection against
an in-flight governed query (the PR 5 stalled-producer recipe), event
log rotation, the profile_report JSON format, the Prometheus exporter,
and the 8-lane workload storm reconciling per-owner HBM attribution
with the catalog/budget counters (the PR 6 storm recipe)."""

import json
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.exec import lifecycle, workload
from spark_rapids_tpu.memory.budget import (memory_budget,
                                            reset_memory_budget)
from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                             reset_buffer_catalog)
from spark_rapids_tpu.obs import events
from spark_rapids_tpu.obs import stats as runtime_stats
from spark_rapids_tpu.obs import telemetry
from spark_rapids_tpu.types import DOUBLE, LONG, Schema

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


@pytest.fixture(autouse=True)
def _isolation():
    prev_conf = C.active_conf()
    telemetry.reset_telemetry()
    runtime_stats.reset_stats()
    lifecycle.reset_lifecycle()
    workload.reset_workload()
    yield
    telemetry.reset_telemetry()
    runtime_stats.reset_stats()
    lifecycle.reset_lifecycle()
    workload.reset_workload()
    events.reset_event_bus()
    C.set_active_conf(prev_conf)


@pytest.fixture
def spy(monkeypatch):
    rows = []
    real = events.emit

    def spy_emit(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy_emit)
    return rows


def _kinds(rows, kind):
    return [e for e in rows if e["kind"] == kind]


# ---------------------------------------------------------------------------
# Log2Hist units vs numpy oracles
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 7, 23])
def test_log2_hist_percentiles_vs_numpy_oracle(seed):
    """The histogram's exact fields match numpy exactly; its p50/p95
    are bucket-quantized UPPER bounds within 2x of the true percentile
    (the documented contract an AQE consumer sizes against)."""
    rng = np.random.default_rng(seed)
    data = (rng.lognormal(mean=8.0, sigma=2.0, size=500)
            .astype(np.int64))
    h = runtime_stats.Log2Hist()
    for v in data:
        h.add(int(v))
    assert h.count == len(data)
    assert h.sum == int(data.sum())
    assert h.min == int(data.min()) and h.max == int(data.max())
    for q in (50, 95):
        true = int(np.percentile(data, q, method="inverted_cdf"))
        est = h.percentile(q)
        assert true <= est, (q, true, est)
        assert est <= max(2 * true - 1, true), (q, true, est)
        assert est <= int(data.max())


def test_log2_hist_edges_and_merge():
    h = runtime_stats.Log2Hist()
    assert h.summary() == {"count": 0, "sum": 0, "min": 0, "max": 0,
                           "p50": 0, "p95": 0}
    assert h.percentile(95) == 0
    h.add(0)
    h.add(0)
    assert h.percentile(50) == 0 and h.summary()["count"] == 2
    h2 = runtime_stats.Log2Hist()
    h2.add(1024)
    h.merge(h2)
    assert h.count == 3 and h.max == 1024 and h.min == 0
    assert h.percentile(99) == 1024  # clamped to the observed max
    single = runtime_stats.Log2Hist()
    single.add(37)
    # one sample: every percentile answers within [min, max] == {37}
    assert single.percentile(1) == 37 and single.percentile(99) == 37


def test_exchange_stats_skew_and_exact_sums():
    st = runtime_stats.ExchangeStats("X", 1, 4)
    st.record_map([10, 0, 0, 2], [100, 0, 0, 20], 120)
    st.record_map([10, 0, 0, 0], [100, 0, 0, 0], 100)
    s = st.summary()
    assert s["maps"] == 2 and s["rows"] == 22 and s["bytes"] == 220
    assert s["per_partition_rows"] == [20, 0, 0, 2]
    assert s["per_partition_bytes"] == [200, 0, 0, 20]
    assert sum(s["per_partition_bytes"]) == s["bytes"]
    # median over [0, 0, 20, 200] is 10 -> ratio 20: heavy skew reads
    # as a large finite ratio
    sk = s["skew"]
    assert sk["basis"] == "bytes" and sk["max"] == 200
    assert sk["ratio"] == pytest.approx(20.0, abs=1e-3)
    # distributions sampled per (map, partition), empties included
    assert s["partition_rows"]["count"] == 8
    assert s["partition_bytes"]["count"] == 8
    # all-in-one-partition: the all-partitions median is 0, so the
    # ratio falls back to the non-empty median — finite, never inf
    lone = runtime_stats.ExchangeStats("X", 2, 4)
    lone.record_map([7, 0, 0, 0], [700, 0, 0, 0], 700)
    sk2 = lone.skew()
    assert sk2["ratio"] == pytest.approx(1.0)
    empty = runtime_stats.ExchangeStats("X", 3, 2)
    empty.record_map([0, 0], [0, 0], 0)
    assert empty.skew()["ratio"] == 0.0


# ---------------------------------------------------------------------------
# QueryProfile.statistics() golden (host-shuffled join)
# ---------------------------------------------------------------------------

def _golden_join_session(extra=None):
    # adaptive OFF: these goldens pin the STATIC plan's telemetry shape
    # (3 exchanges); the replanner's single-build conversion would
    # delete the tiny build side's partitioned read from under them
    settings = {"spark.rapids.sql.shuffle.partitions": "3",
                "spark.rapids.sql.broadcastSizeThreshold": "-1",
                "spark.rapids.tpu.adaptive.enabled": "false"}
    settings.update(extra or {})
    sess = TpuSession(settings)
    n_l, n_o = 240, 16
    lines = sess.from_pydict(
        {"l_key": [i % n_o for i in range(n_l)],
         "l_val": [float(i) for i in range(n_l)]},
        Schema.of(l_key=LONG, l_val=DOUBLE), batch_rows=100)
    orders = sess.from_pydict({"o_key": list(range(n_o))},
                              Schema.of(o_key=LONG))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    return sess, j.group_by("l_key").agg((F.sum("l_val"), "s"))


def test_statistics_golden_host_shuffled_join(spy):
    """Acceptance criterion: statistics() exposes per-exchange
    partition histograms + skew for a host-shuffled join. The murmur3
    partition assignment is deterministic (Spark-exact), so the
    per-partition ROW totals are golden; the byte totals are asserted
    EXACTLY equal to the serializer's written bytes (the shuffle_write
    events), the acceptance-criterion reconciliation."""
    sess, q = _golden_join_session()
    out = q.collect()
    assert len(out) == 16
    st = sess.last_query_profile().statistics()
    assert len(st["exchanges"]) == 3  # lines, orders, agg repartition
    # the lines-side exchange wrote all 240 rows: golden partition split
    lines_x = [v for v in st["exchanges"].values() if v["rows"] == 240]
    assert len(lines_x) == 1
    v = lines_x[0]
    assert v["partitions"] == 3 and v["maps"] == 1
    assert v["per_partition_rows"] == [60, 60, 120]
    sk = v["skew"]
    assert sk["basis"] == "bytes"
    assert sk["max"] == max(v["per_partition_bytes"])
    med = sorted(v["per_partition_bytes"])[1]
    assert sk["ratio"] == pytest.approx(sk["max"] / med, abs=1e-3)
    # histogram fields: one sample per (map, partition); percentile
    # upper bounds bracket the true values
    prow = v["partition_rows"]
    assert prow["count"] == 3 and prow["min"] == 60 \
        and prow["max"] == 120
    assert 60 <= prow["p50"] < 120 and prow["p95"] == 120
    # EXACT byte reconciliation across every exchange in the plan:
    # sum(per_partition_bytes) == bytes == the serializer's written
    # bytes (shuffle_write events), per acceptance criterion (c)
    writes = _kinds(spy, "shuffle_write")
    assert sum(e["bytes"] for e in writes) \
        == sum(x["bytes"] for x in st["exchanges"].values())
    for x in st["exchanges"].values():
        assert sum(x["per_partition_bytes"]) == x["bytes"]
    # per-op cardinality derived from the metric tree
    ops = {(o["op"], o["op_id"]): o for o in st["operators"]}
    assert any(o["selectivity"] is not None for o in ops.values())
    # one exchange_stats event per exchange execution, skew included
    evs = _kinds(spy, "exchange_stats")
    assert len(evs) == 3
    for e in evs:
        assert e["skew_ratio"] >= 1.0 and e["skew_basis"] == "bytes"
        assert e["maps"] >= 1 and e["partitions"] == 3


def test_statistics_reachable_during_execution():
    """The tentpole contract: RuntimeStats is reachable from the
    governing QueryContext DURING execution — an operator (here a
    pandas UDF running mid-plan) sees the upstream exchange's recorded
    maps before the query finishes."""
    sess, _ = _golden_join_session()
    seen = {}

    def probe(it):
        for pdf in it:
            rs = runtime_stats.current()
            if rs is not None:
                seen["exchanges"] = len(rs.exchanges())
                seen["maps"] = sum(x.maps for x in rs.exchanges())
            yield pdf

    n_l, n_o = 240, 16
    lines = sess.from_pydict(
        {"l_key": [i % n_o for i in range(n_l)],
         "l_val": [float(i) for i in range(n_l)]},
        Schema.of(l_key=LONG, l_val=DOUBLE), batch_rows=100)
    orders = sess.from_pydict({"o_key": list(range(n_o))},
                              Schema.of(o_key=LONG))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    out_schema = Schema.of(l_key=LONG, l_val=DOUBLE, o_key=LONG)
    j.map_in_pandas(probe, out_schema).collect()
    assert seen.get("exchanges", 0) >= 1, \
        "mid-flight probe never saw the RuntimeStats"
    assert seen.get("maps", 0) >= 1


def test_statistics_multiple_maps(spy):
    """Small batchSizeBytes forces several map tasks per exchange: the
    map-output histogram sees one sample per map and distributions
    accumulate across maps."""
    sess, q = _golden_join_session(
        {"spark.rapids.sql.batchSizeBytes": "4k"})
    q.collect()
    st = sess.last_query_profile().statistics()
    lines_x = [v for v in st["exchanges"].values() if v["rows"] == 240]
    assert len(lines_x) == 1 and lines_x[0]["maps"] >= 2
    assert lines_x[0]["map_output_bytes"]["count"] == lines_x[0]["maps"]
    assert sum(lines_x[0]["per_partition_rows"]) == 240


# ---------------------------------------------------------------------------
# disabled-mode discipline (PR 2 pattern)
# ---------------------------------------------------------------------------

def test_disabled_mode_zero_emission_and_single_pointer_check(spy):
    """Telemetry off (the default): no registry, no sampler thread,
    push sites cost one pointer check and write nothing, results are
    byte-identical, and zero telemetry_sample/registry writes happen —
    acceptance criterion (d)."""
    assert telemetry.active_registry() is None
    telemetry.add("anything", 5)  # the entire disabled-mode cost
    assert telemetry.active_registry() is None
    sess, q = _golden_join_session()
    out_off = q.collect()
    assert not any(t.name.startswith("telemetry-")
                   for t in threading.enumerate())
    assert telemetry.counters() == {"samples": 0, "registry_writes": 0}
    assert not _kinds(spy, "telemetry_sample")
    # the same query with telemetry ON returns identical results
    sess2, q2 = _golden_join_session(
        {"spark.rapids.tpu.telemetry.enabled": "true",
         "spark.rapids.tpu.telemetry.intervalMs": "50"})
    assert sorted(q2.collect()) == sorted(out_off)
    assert telemetry.active_registry() is not None
    assert telemetry.counters()["registry_writes"] > 0


def test_configure_semantics_match_event_bus():
    """Process-wide conf semantics: unset keeps another session's
    registry, explicit false tears it down, unchanged params keep the
    instance (ring-buffer history survives)."""
    r1 = telemetry.configure(C.RapidsConf(
        {"spark.rapids.tpu.telemetry.enabled": "true"}))
    assert r1 is not None
    # unset: keeps it
    assert telemetry.configure(C.RapidsConf({})) is r1
    # unchanged params: same instance
    assert telemetry.configure(C.RapidsConf(
        {"spark.rapids.tpu.telemetry.enabled": "true"})) is r1
    # explicit false: torn down, thread gone
    assert telemetry.configure(C.RapidsConf(
        {"spark.rapids.tpu.telemetry.enabled": "false"})) is None
    time.sleep(0.05)
    assert not any(t.name.startswith("telemetry-") and t.is_alive()
                   for t in threading.enumerate())


def test_sample_series_and_owner_attribution_sum():
    """Every registered series appears in a sample, and the per-owner
    HBM attribution sums to the tier totals exactly (one lock pass)."""
    r = telemetry.enable(interval_ms=100000)  # manual sampling only
    import jax.numpy as jnp
    cat = buffer_catalog()
    h = cat.add(jnp.arange(1024, dtype=jnp.int32))
    try:
        snap = r.sample()
        for name in telemetry.SERIES:
            assert name in snap, name
        by_owner = snap["hbm_by_owner"]
        assert sum(by_owner["device"].values()) \
            == snap["hbm.device_bytes"]
        assert sum(by_owner["host"].values()) == snap["hbm.host_bytes"]
        assert snap["hbm.device_bytes"] == cat.device_bytes()
        assert by_owner["device"].get("unowned", 0) > 0
        assert r.series("hbm.device_bytes")[-1][1] \
            == snap["hbm.device_bytes"]
    finally:
        cat.remove(h)


# ---------------------------------------------------------------------------
# live introspection: active_queries()
# ---------------------------------------------------------------------------

class _StallingSource:
    """batches() parks on an event after the first batch — the PR 5
    stalled-producer recipe, released by the test driver."""

    def __init__(self, batches, schema, gate):
        self._batches = batches
        self.schema = schema
        self.gate = gate

    def batches(self):
        for i, b in enumerate(self._batches):
            if i >= 1:
                assert self.gate.wait(60), "driver never released"
            yield b

    def estimated_size_bytes(self):
        return sum(b.device_size_bytes() for b in self._batches)

    def estimated_num_rows(self):
        return sum(b.num_rows_host for b in self._batches)


def test_active_queries_during_inflight_governed_query():
    """Acceptance criterion (a): active_queries() observed non-empty
    mid-run with correct phase/fields, and empty again at quiesce."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.plan import logical as L
    schema = Schema.of(a=LONG)
    # each batch alone exceeds batchSizeBytes, so CoalesceBatches
    # passes the first one through to the root BEFORE the stall —
    # otherwise no root output exists "mid" the run at all
    batches = [ColumnarBatch.from_pydict({"a": [i] * 1024}, schema)
               for i in range(3)]
    gate = threading.Event()
    sess = TpuSession({"spark.rapids.sql.batchSizeBytes": "4k"})
    df = sess._df(L.LogicalScan(_StallingSource(batches, schema, gate)))
    # pandas tail: the ROOT's output batches are host-built, so the
    # live rows counter sees them (device-resident root output counts
    # batches only — progress never pays a device sync)
    q = df.filter(col("a") >= lit(0)).map_in_pandas(
        lambda it: it, schema)
    done = {}

    def drive():
        done["rows"] = q.collect()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    rows = []
    while time.monotonic() < deadline:
        rows = sess.active_queries()
        if rows and rows[0]["batches"] >= 1:
            break
        time.sleep(0.01)
    assert rows, "active_queries never saw the in-flight query"
    r = rows[0]
    assert r["phase"] == "executing"
    assert r["mine"] is True
    assert r["attempt"] == 1
    assert r["cancelled"] is False
    assert r["current_op"] is not None
    assert r["rows"] >= 1024  # root output observed mid-run
    assert r["elapsed_ms"] >= 0
    assert r["deadline_remaining_ms"] is None  # no timeoutMs set
    gate.set()
    t.join(timeout=30)
    assert not t.is_alive() and len(done["rows"]) == 3072
    assert sess.active_queries() == []


def test_attempt_restart_resets_live_progress():
    """A task re-execution starts its root output from zero — the live
    progress counters must not double-count across attempts (review
    finding; mirrors the fresh-RuntimeStats-per-attempt rule)."""
    with lifecycle.governed(C.RapidsConf({})) as ctx:
        lifecycle.begin_attempt(1)
        ctx.root_op_id = 42
        ctx.note_batch("RootExec", 42, 100)
        ctx.note_batch("RootExec", 42, 100)
        assert ctx.batches_produced == 2 and ctx.rows_produced == 200
        lifecycle.begin_attempt(2)
        assert ctx.attempt_no == 2 and ctx.phase == "executing"
        assert ctx.batches_produced == 0 and ctx.rows_produced == 0
        assert ctx.current_op is None


# ---------------------------------------------------------------------------
# event-log rotation + report tooling
# ---------------------------------------------------------------------------

def test_event_log_rotation_and_rotated_report(tmp_path):
    """eventLog.maxBytes rotates the sink to events-<n>.<rot>.jsonl;
    profile_report reads the set in order and still tolerates a
    truncated final line."""
    import profile_report
    bus = events.enable(str(tmp_path), max_bytes=512)
    for i in range(50):
        bus.emit("op_close", op="FakeExec", op_id=1, wall_ns=1000,
                 batches=1, rows=10)
    bus.emit("query_end", root="FakeExec", ok=True, wall_ns=1)
    events.reset_event_bus()
    files = sorted(tmp_path.glob("*.jsonl"))
    assert len(files) >= 3, "rotation never engaged"
    members = profile_report.rotated_set(str(files[0]))
    assert len(members) == len(files)
    # rotation order: base first, then .1, .2, ... (numeric, not lex)
    assert members[0].endswith("-1.jsonl") \
        or ".1.jsonl" not in members[0]
    evs = profile_report.read_event_files(str(members[0]))
    assert sum(1 for e in evs if e["kind"] == "op_close") == 50
    # truncated final line in the newest member: parseable prefix kept
    with open(members[-1], "a") as f:
        f.write('{"kind": "op_close", "op": "Trunc')
    evs2 = profile_report.read_event_files(str(members[0]))
    assert len(evs2) == len(evs)
    report = profile_report.build_report(evs2)
    assert "51 events" in report and "FakeExec" in report


def test_rotation_respects_unrotated_default(tmp_path):
    bus = events.enable(str(tmp_path))
    for _ in range(200):
        bus.emit("op_close", op="E", op_id=1, wall_ns=1, batches=1,
                 rows=1)
    events.reset_event_bus()
    assert len(list(tmp_path.glob("*.jsonl"))) == 1


def test_profile_report_json_format(tmp_path, capsys, spy):
    """--format json: the same roll-ups as the text report, as fields
    (the AQE/CI assertion surface), including the statistics block."""
    import profile_report
    d = tmp_path / "ev"
    sess, q = _golden_join_session(
        {"spark.rapids.tpu.eventLog.enabled": "true",
         "spark.rapids.tpu.eventLog.dir": str(d)})
    q.collect()
    events.reset_event_bus()
    log = sorted(d.glob("*.jsonl"))[0]
    assert profile_report.main([str(log), "--format", "json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["completed"] == 1
    assert summary["top_ops"] and summary["top_ops"][0]["wall_ns"] > 0
    st = summary["statistics"]
    assert st["exchanges"] == 3 and st["max_skew_ratio"] >= 1.0
    assert st["p95_map_output_bytes"] > 0
    assert len(st["per_exchange"]) == 3
    assert summary["shuffle_writes"]["maps"] >= 3
    # the text renderer prints the same data as a statistics line
    text = profile_report.build_report(
        profile_report.read_event_files(str(log)))
    assert "statistics: 3 exchange(s)" in text
    assert "max partition skew ratio" in text


def test_telemetry_export_prometheus(tmp_path, capsys):
    """tools/telemetry_export.py renders telemetry_sample records as
    Prometheus text format, per-owner HBM labels included."""
    import telemetry_export
    sample = {
        "kind": "telemetry_sample", "ts_ms": 1700000000000,
        "hbm.device_bytes": 4096, "hbm.host_bytes": 0,
        "hbm_by_owner": {"device": {"q3": 4096, "unowned": 0},
                         "host": {}},
        "counters": {"exchange.write_bytes": 99},
    }
    text = telemetry_export.to_prometheus(sample)
    assert "# TYPE spark_rapids_tpu_hbm_device_bytes gauge" in text
    assert "spark_rapids_tpu_hbm_device_bytes 4096 1700000000000" \
        in text
    assert ('spark_rapids_tpu_hbm_owner_bytes{tier="device",'
            'owner="q3"} 4096') in text
    assert "spark_rapids_tpu_counter_exchange_write_bytes 99" in text
    # CLI over a real log (rotated-set reading included)
    log = tmp_path / "events-1-1.jsonl"
    sample2 = dict(sample, **{"ts_ms": 1700000001000,
                              "hbm.device_bytes": 2048})
    log.write_text(json.dumps(sample) + "\n"
                   + json.dumps(sample2) + "\n")
    assert telemetry_export.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "spark_rapids_tpu_hbm_device_bytes 2048" in out  # newest
    # --all: valid exposition — ONE TYPE line per metric, one
    # timestamped line per sample under it (no duplicate TYPE lines)
    assert telemetry_export.main([str(log), "--all"]) == 0
    out_all = capsys.readouterr().out
    assert out_all.count(
        "# TYPE spark_rapids_tpu_hbm_device_bytes gauge") == 1
    assert "spark_rapids_tpu_hbm_device_bytes 4096 1700000000000" \
        in out_all
    assert "spark_rapids_tpu_hbm_device_bytes 2048 1700000001000" \
        in out_all
    empty = tmp_path / "events-1-2.jsonl"
    empty.write_text("")
    assert telemetry_export.main([str(empty)]) == 1


# ---------------------------------------------------------------------------
# bench attribution blocks
# ---------------------------------------------------------------------------

def test_bench_telemetry_and_statistics_blocks():
    import bench
    bench._attr_prev.clear()
    base = bench.telemetry_attribution()
    assert base == {"samples": 0, "registry_writes": 0}
    r = telemetry.enable(interval_ms=100000)
    r.sample()
    delta = bench.telemetry_attribution()
    assert delta["samples"] == 1 and delta["registry_writes"] >= 1
    runtime_stats.reset_stats()
    bench._attr_prev.pop("statistics", None)
    s0 = bench.statistics_attribution()
    assert s0["maps"] == 0 and s0["skew_ratio"] == 0.0
    rec = runtime_stats.ExchangeRecorder("X", 1, 2)
    rec.record_map([5, 1], [500, 100], 600)
    rec.finish()
    s1 = bench.statistics_attribution()
    assert s1["maps"] == 1 and s1["bytes"] == 600
    assert s1["p95_map_output_bytes"] >= 600 \
        and s1["p95_map_output_bytes"] < 1200
    assert s1["skew_ratio"] == pytest.approx(500 / 300, abs=1e-3)


# ---------------------------------------------------------------------------
# the 8-lane storm: per-owner attribution reconciles (PR 6 recipe)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def storm_files(tmp_path_factory):
    """The PR 6 proven forced-spill storm shape, verbatim scale."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    d = tmp_path_factory.mktemp("telemetry_storm")
    lanes = []
    for seed in range(8):
        rng = np.random.default_rng(seed)
        n_l, n_o = 2000, 500
        l_key = rng.integers(0, n_o, n_l)
        l_val = rng.random(n_l) * 100.0
        l_flag = rng.integers(0, 4, n_l)
        o_flag = rng.integers(0, 10, n_o)
        lp = str(d / f"lines-{seed}.parquet")
        op = str(d / f"orders-{seed}.parquet")
        pq.write_table(pa.table({
            "l_key": pa.array(l_key, pa.int64()),
            "l_val": pa.array(l_val, pa.float64()),
            "l_flag": pa.array(l_flag, pa.int64())}), lp,
            row_group_size=512)
        pq.write_table(pa.table({
            "o_key": pa.array(np.arange(n_o), pa.int64()),
            "o_flag": pa.array(o_flag, pa.int64())}), op,
            row_group_size=128)
        keep = (l_flag != 0) & (o_flag[l_key] < 5)
        oracle = {}
        for k, v in zip(l_key[keep], l_val[keep]):
            s, c = oracle.get(int(k), (0.0, 0))
            oracle[int(k)] = (s + float(v), c + 1)
        lanes.append((lp, op, oracle))
    return lanes


STORM = {
    "spark.rapids.tpu.workload.enabled": "true",
    "spark.rapids.tpu.workload.maxConcurrentQueries": "2",
    "spark.rapids.tpu.workload.queueDepth": "8",
    "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
    "spark.rapids.sql.broadcastSizeThreshold": "-1",
    "spark.rapids.sql.retry.maxAttempts": "50",
    "spark.rapids.tpu.retry.backoffMs": "5",
    "spark.rapids.tpu.io.retryBackoffMs": "1",
    "spark.rapids.tpu.task.retryBackoffMs": "1",
}


def _run_storm_query(settings, lane):
    lp, op, _ = lane
    sess = TpuSession(settings)
    lines = sess.read_parquet(lp).filter(col("l_flag") != lit(0))
    orders = sess.read_parquet(op).filter(col("o_flag") < lit(5))
    j = lines.join(orders, left_on=["l_key"], right_on=["o_key"])
    agg = j.group_by("l_key").agg((F.sum("l_val"), "rev"),
                                  (F.count(), "cnt"))
    return agg.sort(("rev", False)).collect()


# moved to the slow tier by ISSUE 13 budget relief (91s: 8-lane storm
# reconciliation; per-owner attribution equality stays tier-1 on the
# single-query drive)
@pytest.mark.slow
def test_storm_hbm_attribution_reconciles(storm_files):
    """Acceptance criterion: 8 governed lanes under a forced-spill
    budget with telemetry ON — (a) active_queries() snapshots observed
    non-empty mid-run with correct phases, (b) per-owner HBM
    attribution sums to the catalog totals at every sampled tick and
    owner-keyed attribution actually engaged, (c) results match the
    per-lane oracles, and everything reconciles at quiesce."""
    pre = {t for t in threading.enumerate()
           if t.name.startswith(("pipeline-", "spill-writer",
                                 "telemetry-"))}
    try:
        reset_buffer_catalog()
        reset_memory_budget(112 * 1024)  # the PR 6 probed-stable point
        used_before = memory_budget().used
        reg = telemetry.enable(interval_ms=100000)  # sampled by driver
        results = [None] * 8
        settings = dict(STORM, **{
            "spark.rapids.tpu.telemetry.enabled": "true"})

        def lane(i):
            try:
                results[i] = _run_storm_query(settings, storm_files[i])
            except BaseException as e:  # noqa: BLE001 — asserted below
                results[i] = e

        threads = [threading.Thread(target=lane, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        samples = []
        snapshots = []
        while any(t.is_alive() for t in threads):
            samples.append(reg.sample())
            snapshots.append(lifecycle.active_queries())
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=240)
            assert not t.is_alive(), "a lane wedged"
        for i in range(8):
            assert not isinstance(results[i], BaseException), results[i]
            got = {int(k): (rev, int(cnt))
                   for k, rev, cnt in results[i]}
            oracle = storm_files[i][2]
            assert set(got) == set(oracle), f"lane {i}"
            for k, (rev, cnt) in got.items():
                o_rev, o_cnt = oracle[k]
                assert cnt == o_cnt, (i, k)
                assert abs(rev - o_rev) <= 1e-9 * max(abs(o_rev), 1.0)
        # (b) attribution reconciles at EVERY sampled tick: per-owner
        # sums equal the same-pass tier totals
        assert samples, "storm finished before the first sample"
        for s in samples:
            assert sum(s["hbm_by_owner"]["device"].values()) \
                == s["hbm.device_bytes"]
            assert sum(s["hbm_by_owner"]["host"].values()) \
                == s["hbm.host_bytes"]
        # owner-keyed attribution engaged: some tick saw bytes charged
        # to an admitted ticket (q<id>), not just "unowned"
        assert any(k.startswith("q") and v > 0
                   for s in samples
                   for k, v in s["hbm_by_owner"]["device"].items()), \
            "no sampled tick attributed device bytes to a ticket owner"
        assert memory_budget().spill_requests > 0, \
            "the forced-spill drive lost its teeth"
        # (a) live snapshots: non-empty mid-run, phases valid, and the
        # admission queue actually held queries at some tick
        flat = [r for snap in snapshots for r in snap]
        assert flat, "active_queries never saw the storm"
        valid = {"queued", "admitted", "executing", "retrying"}
        assert all(r["phase"] in valid for r in flat)
        assert any(r["phase"] == "executing" for r in flat)
        assert any(s["workload.queue_depth"] > 0 for s in samples) \
            or any(r["phase"] == "queued" for r in flat), \
            "no queue residency observed: no contention"
        # quiesce: budget restored, no lingering queries, totals zero
        buffer_catalog().drain_writeback()
        assert memory_budget().used == used_before, "leaked budget"
        final = reg.sample()
        assert final["hbm.device_bytes"] == buffer_catalog().device_bytes()
        assert lifecycle.active_queries() == []
        assert workload.snapshot()["admitted"] == 0
        buffer_catalog().shutdown_writer()
        telemetry.reset_telemetry()
        post = {t for t in threading.enumerate()
                if t.name.startswith(("pipeline-", "spill-writer",
                                      "telemetry-"))}
        assert post <= pre, "storm leaked threads"
    finally:
        reset_buffer_catalog()
        reset_memory_budget()


# ---------------------------------------------------------------------------
# ICI lane counters + SLO latency ring (ISSUE 17 satellite)
# ---------------------------------------------------------------------------

def test_ici_counters_sampled_and_exported(capsys):
    """The ICI shuffle lane's cumulative counters ride every telemetry
    sample and round-trip through the Prometheus exporter as
    spark_rapids_tpu_ici_* gauges."""
    import telemetry_export
    telemetry.enable(interval_ms=100000)
    sample = telemetry.collect_sample()
    for key in ("ici.rounds", "ici.bytes", "ici.fallbacks"):
        assert key in sample and isinstance(sample[key], int)
    # every documented series is sampled, and vice versa (no series can
    # silently fall out of the export again, the way ici.* did)
    numeric = {k for k, v in sample.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)
               and k not in ("ts_ms", "ts_ns")}
    assert numeric == set(telemetry.SERIES)
    text = telemetry_export.to_prometheus(
        dict(sample, kind="telemetry_sample", ts_ms=1700000002000))
    for key in numeric:   # ...and every one round-trips as a gauge
        m = telemetry_export._metric(key)
        assert f"# TYPE {m} gauge" in text
        assert f"{m} {sample[key]} 1700000002000" in text
    assert "spark_rapids_tpu_ici_rounds" in text


def test_slo_latency_ring_percentiles():
    """note_query_latency feeds per-priority-class nearest-rank
    percentiles; health()['slo'] carries them; disabled telemetry is
    one pointer check ({'enabled': False})."""
    telemetry.reset_telemetry()
    assert telemetry.slo_section() == {"enabled": False}
    telemetry.note_query_latency("interactive", 123)  # no-op when off

    reg = telemetry.enable(interval_ms=100000)
    assert telemetry.slo_section()["classes"] == {}  # nothing finished
    for i in range(1, 101):
        telemetry.note_query_latency("interactive", i * 1000)
    telemetry.note_query_latency("batch", 7_000_000)
    snap = reg.slo_snapshot()
    inter = snap["interactive"]
    assert inter["p50_ns"] == 50_000     # nearest-rank over 1k..100k
    assert inter["p95_ns"] == 95_000
    assert inter["p99_ns"] == 99_000
    assert inter["window"] == 100 and inter["queries"] == 100
    assert snap["batch"]["p50_ns"] == 7_000_000
    assert snap["batch"]["window"] == 1

    sess = TpuSession()
    slo = sess.health()["slo"]
    assert slo["enabled"] is True
    assert slo["classes"]["interactive"]["p95_ns"] == 95_000


def test_slo_ring_fed_only_by_completed_queries(tmp_path):
    """End-to-end: a successful governed collect lands in the ring
    under its priority class; a failed one does not (it would drag the
    percentiles toward shed-fast microseconds)."""
    from spark_rapids_tpu import faults
    telemetry.enable(interval_ms=100000)
    sess = TpuSession({"spark.rapids.tpu.task.maxAttempts": "1"})
    df = sess.from_pydict(
        {"k": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]},
        Schema.of(k=LONG, v=DOUBLE))
    df.filter(col("v") > lit(0.5)).collect()
    snap = telemetry.active_registry().slo_snapshot()
    assert snap["interactive"]["queries"] == 1
    assert snap["interactive"]["p50_ns"] > 0
    try:
        faults.install("device.dispatch:prob=1,seed=2,kind=device,max=9")
        with pytest.raises(Exception):
            df.filter(col("v") > lit(0.5)).select(col("k")).collect()
    finally:
        faults.install(None)
    snap = telemetry.active_registry().slo_snapshot()
    assert snap["interactive"]["queries"] == 1, \
        "a failed query leaked into the SLO ring"


def test_bench_phases_block_and_history_env(tmp_path, monkeypatch):
    """bench records carry process-cumulative phase deltas, and
    SPARK_RAPIDS_TPU_HISTORY_DIR arms the capsule store for a bench
    run (the two-dirs --diff workflow)."""
    import bench
    from spark_rapids_tpu.obs import history, phase
    phase.reset_phase_counters()
    bench._attr_prev.pop("phases", None)
    base = bench.phases_attribution()
    assert set(base) == set(phase.ACCRUABLE) and not any(base.values())
    phase.add("compile", 1000)
    phase.add("shuffle-io", 250)
    delta = bench.phases_attribution()
    assert delta["compile"] == 1000 and delta["shuffle-io"] == 250
    assert not any(bench.phases_attribution().values())  # consumed

    monkeypatch.setenv("SPARK_RAPIDS_TPU_HISTORY_DIR", str(tmp_path))
    try:
        bench.maybe_enable_history()
        store = history.active_store()
        assert store is not None
        store.append({"i": 1})
        assert store.records == 1
    finally:
        history.reset_history()
