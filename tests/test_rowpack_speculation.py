"""Round-4 machinery tests (VERDICT r4 item 2): packed-row gather
roundtrips over the full dtype matrix, the join's speculative sizing
trip -> exact re-run contract, sizing-cap decay, prefix-difference
aggregation edges, and device-side TopN."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.exec.joins import HashJoinExec
from spark_rapids_tpu.exec.sort import TopNExec
from spark_rapids_tpu.exec.speculation import speculation_scope
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.ops.aggregate import groupby_aggregate
from spark_rapids_tpu.ops.rowpack import (
    gather_rows, is_packable, pack_rows, split_packable, unpack_rows,
)
from spark_rapids_tpu.types import (
    BOOLEAN, BYTE, DOUBLE, FLOAT, INT, LONG, SHORT, STRING, Schema,
    StructField,
)


# ---------------------------------------------------------------- rowpack
DTYPE_COLS = [
    ("i8", BYTE, [1, -2, None, 127, -128, 0]),
    ("i16", SHORT, [300, None, -32768, 32767, 5, -1]),
    ("i32", INT, [2 ** 31 - 1, -2 ** 31, None, 0, 42, -7]),
    ("i64", LONG, [2 ** 62, -2 ** 62, None, -1, 2 ** 40 + 3, 0]),
    ("bool", BOOLEAN, [True, False, None, True, False, True]),
    ("f32", FLOAT, [1.5, -0.0, None, 3.25e8, -2.0, 0.0]),
    ("f64", DOUBLE, [1e300, -1e-300, None, 0.0, -0.0, 2.5]),
]


def _mk_cols():
    return [Column.from_pylist(vals, dt) for _, dt, vals in DTYPE_COLS]


def test_rowpack_roundtrip_full_dtype_matrix():
    cols = _mk_cols()
    assert all(is_packable(c) for c in cols)
    plan, imat, fmat = pack_rows(cols)
    out = unpack_rows(plan, imat, fmat)
    n = 6
    for (name, dt, vals), c_out in zip(DTYPE_COLS, out):
        got = c_out.to_pylist(n)
        assert got == vals, (name, got, vals)


def test_rowpack_gather_permutation_and_out_of_range():
    cols = _mk_cols()
    plan, imat, fmat = pack_rows(cols)
    cap = cols[0].capacity
    # reversal of the 6 real rows, plus out-of-range slots: -1 and cap+5
    idx = jnp.asarray([5, 4, 3, 2, 1, 0, -1, cap + 5] +
                      [0] * (cap - 8), jnp.int32)
    gi, gf = gather_rows(plan, imat, fmat, idx)
    out = unpack_rows(plan, gi, gf)
    for (name, dt, vals), c_out in zip(DTYPE_COLS, out):
        got = c_out.to_pylist(8)
        assert got[:6] == vals[::-1], (name, got)
        # out-of-range -> invalid rows, never resurrected data
        assert got[6] is None and got[7] is None, (name, got)


def test_rowpack_many_columns_multi_validity_lane():
    # >32 columns forces a second validity lane
    cols = [Column.from_pylist([i, None, i * 3], INT) for i in range(40)]
    plan, imat, fmat = pack_rows(cols)
    assert plan.n_valid_lanes == 2
    out = unpack_rows(plan, imat, fmat)
    for i, c in enumerate(out):
        assert c.to_pylist(3) == [i, None, i * 3]


def test_split_packable_keeps_order():
    from spark_rapids_tpu.columnar.column import StringColumn
    cols = [Column.from_pylist([1], INT),
            StringColumn.from_pylist(["x"]),
            Column.from_pylist([2.0], DOUBLE)]
    p, o = split_packable(cols)
    assert p == [0, 2] and o == [1]


# ------------------------------------------------- speculative join sizing
L_SCHEMA = Schema((StructField("lk", LONG), StructField("lv", STRING)))
R_SCHEMA = Schema((StructField("rk", LONG), StructField("rv", STRING)))


def _join_plan(n_stream_batches=3):
    rng = np.random.default_rng(11)
    r = {"rk": list(range(20)), "rv": [f"b{i}" for i in range(20)]}
    batches = []
    for bi in range(n_stream_batches):
        lk = rng.integers(0, 20, 64).tolist()
        batches.append(ColumnarBatch.from_pydict(
            {"lk": lk, "lv": [f"s{bi}_{k}" for k in lk]}, L_SCHEMA))
    plan = HashJoinExec(
        InMemoryScanExec(batches, L_SCHEMA),
        InMemoryScanExec([ColumnarBatch.from_pydict(r, R_SCHEMA)], R_SCHEMA),
        [col("lk")], [col("rk")], "inner", build_side="right")
    oracle = []
    rv = dict(zip(r["rk"], r["rv"]))
    for b in batches:
        ks = b.columns[0].to_pylist(64)
        vs = b.columns[1].to_pylist(64)
        oracle.extend((k, v, k, rv[k]) for k, v in zip(ks, vs))
    return plan, sorted(oracle)


def test_speculative_sizing_trip_reruns_exact():
    plan, oracle = _join_plan()
    assert sorted(plan.collect()) == oracle  # populates the size cache
    assert plan._size_cache
    # sabotage: shrink every cached cap so the speculative probe MUST
    # overflow (candidate bucket of 1, 1-byte string buckets)
    for k, (cand, s_caps, b_caps) in plan._size_cache.items():
        plan._size_cache[k] = (
            1, tuple(None if c is None else 8 for c in s_caps),
            tuple(None if c is None else 8 for c in b_caps))
        plan._spec_uses[k] = 0
    # collect() speculates with the broken caps, sees the tripped flag,
    # and re-runs exact: results must still be correct
    assert sorted(plan.collect()) == oracle


def test_speculative_flag_actually_trips():
    plan, oracle = _join_plan()
    plan.collect()
    for k, (cand, s_caps, b_caps) in plan._size_cache.items():
        plan._size_cache[k] = (1, s_caps, b_caps)
        plan._spec_uses[k] = 0
    with speculation_scope() as scope:
        list(plan.execute())
        assert scope.tripped()  # a deliberately-broken cap must flag


def test_speculative_cap_decay():
    plan, oracle = _join_plan()
    plan.SPEC_REFRESH = 4  # instance override
    assert sorted(plan.collect()) == oracle
    key = next(iter(plan._size_cache))
    cand0, s0, b0 = plan._size_cache[key]
    # a pathological batch inflated the caps way past need
    plan._size_cache[key] = (
        cand0 * 64, tuple(None if c is None else c * 64 for c in s0),
        tuple(None if c is None else c * 64 for c in b0))
    for _ in range(4):
        assert sorted(plan.collect()) == oracle
    # the entry must have expired and been re-measured back down
    cand_now = plan._size_cache[key][0]
    assert cand_now <= cand0, (cand_now, cand0)


# ------------------------------------------------ prefix-difference edges
def _sums(keys, vals, dtype):
    k = Column.from_pylist(keys, LONG)
    v = Column.from_pylist(vals, dtype, capacity=k.capacity)
    out_keys, results, num_groups = groupby_aggregate(
        [k], [("sum", v), ("count", v)], jnp.int32(len(keys)),
        k.capacity, 0)
    ng = int(num_groups)
    ks = out_keys[0].to_pylist(ng)
    _, (sdata, svalid) = results[0]
    _, (cdata, _) = results[1]
    sums = [d if bool(v) else None for d, v in
            zip(np.asarray(sdata)[:ng].tolist(),
                np.asarray(svalid)[:ng].tolist())]
    counts = np.asarray(cdata)[:ng].tolist()
    return dict(zip(ks, zip(sums, counts)))


@pytest.mark.slow  # ~9s: nightly tier (round-7 budget move, redundant tier-1 coverage)
def test_prefix_tier_null_and_all_null_groups():
    keys = [1, 1, 2, 2, 2, 3]
    vals = [10, None, None, None, 7, None]
    got = _sums(keys, vals, LONG)
    assert got[1] == (10, 1)
    assert got[2] == (7, 1)
    assert got[3] == (None, 0)  # all-null group: NULL sum, count 0


# moved to the slow tier by ISSUE 13 budget relief (4s: prefix-tier
# single; the trip/decay/exact-rerun contracts stay tier-1)
@pytest.mark.slow
def test_prefix_tier_single_group_and_negatives():
    got = _sums([5] * 7, [-(2 ** 50), 2 ** 50, -1, 2, -3, 4, -5], LONG)
    assert got[5] == (-3, 7)


# ----------------------------------------------------------------- TopN
def _topn(vals, limit):
    sch = Schema((StructField("v", LONG),))
    b = ColumnarBatch.from_pydict({"v": vals}, sch)
    plan = TopNExec(limit, [(col("v"), False)],
                    InMemoryScanExec([b], sch))
    return [r[0] for r in plan.collect()]


def test_topn_rows_exceed_limit():
    vals = [5, 1, 9, 7, 3, 8, 2]
    assert _topn(vals, 3) == [9, 8, 7]


def test_topn_rows_below_limit():
    vals = [4, 2, 6]
    assert _topn(vals, 10) == [6, 4, 2]
