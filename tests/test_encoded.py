"""Dictionary-encoded execution (ISSUE 18): structural acceptance for
the encoded lane — byte-identical collects with the conf on vs off, the
>= 2x packed-upload byte shrink on a string-dictionary-heavy scan,
code-space predicate / dictionary-hash-table engagement, late
materialization ONLY at output-level seams, the PR 3 forced-spill
recipe flowing encoded batches through the spill lane, seeded
`device.dispatch` chaos over the materialize seam, and the
`dict_gather` kern_bench family."""

import os
import sys
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col, lit
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar import encoded, upload
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.encoded import DictionaryColumn

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import kern_bench  # noqa: E402

OFF = {"spark.rapids.tpu.scan.encoded.enabled": "false"}

#: distinct values long enough that the decoded (offsets, bytes) layout
#: dominates the i32 code lane — the shrink the tentpole claims
CATS = ["alpha-category-00000000000000", "beta-category-111111111111111",
        "gamma-category-22222222222222", "delta-category-3333333333333"]


@pytest.fixture(autouse=True)
def _isolation():
    prev = C.active_conf()
    faults.install(None)
    yield
    faults.install(None)
    C.set_active_conf(prev)


def _delta(before, after):
    return {k: after[k] - before[k] for k in after}


def _write_strings(tmp_path, n=4000, name="strings.parquet", seed=18):
    rng = np.random.default_rng(seed)
    path = os.path.join(str(tmp_path), name)
    # parquet writes string columns dictionary-encoded BY DEFAULT —
    # no writer flags needed for the scan to see the encoded layout
    pq.write_table(pa.table({
        "s": pa.array([CATS[i] for i in rng.integers(0, len(CATS), n)]),
        "v": pa.array(rng.integers(0, 1000, n), pa.int64()),
    }), path)
    return path


# ---------------------------------------------------------------------------
# acceptance: byte-identical collects + the >= 2x upload byte shrink
# ---------------------------------------------------------------------------

def test_scan_collect_byte_identical_and_upload_shrink(tmp_path):
    """The headline structural claim: the same scan->collect returns
    IDENTICAL rows with the encoded lane on vs off, while the packed
    host->device upload ships <= half the bytes (codes + one dictionary
    instead of the decoded string buffers)."""
    path = _write_strings(tmp_path)
    results, up, enc = {}, {}, {}
    for mode, settings in (("on", {}), ("off", dict(OFF))):
        sess = TpuSession(dict(settings))
        df = sess.read_parquet(path)
        ub, eb = upload.counters(), encoded.counters()
        results[mode] = df.collect()
        up[mode] = _delta(ub, upload.counters())
        enc[mode] = _delta(eb, encoded.counters())
    assert results["on"] == results["off"]
    assert enc["on"]["cols_encoded"] >= 1
    assert enc["off"]["cols_encoded"] == 0
    assert enc["on"]["decoded_bytes_avoided"] > 0
    # the tentpole's transfer claim: >= 2x fewer H2D bytes encoded
    assert up["on"]["bytes"] * 2 <= up["off"]["bytes"], (up["on"],
                                                         up["off"])


def test_materializations_only_at_output_seam(tmp_path, monkeypatch):
    """scan -> filter(code-space equality) -> collect must decode each
    encoded column exactly once, at the OUTPUT seam — any `boundary`
    seam means an exec's consumes_encoded walk regressed."""
    path = _write_strings(tmp_path)
    seams = []
    real = encoded.materialize_column

    def rec(c, fault_key=None, seam="boundary"):
        seams.append(seam)
        return real(c, fault_key=fault_key, seam=seam)

    monkeypatch.setattr(encoded, "materialize_column", rec)
    sess = TpuSession()
    eb = encoded.counters()
    got = sess.read_parquet(path).filter(col("s") == lit(CATS[1])) \
        .collect()
    d = _delta(eb, encoded.counters())
    sess_off = TpuSession(dict(OFF))
    want = sess_off.read_parquet(path) \
        .filter(col("s") == lit(CATS[1])).collect()
    assert got == want and len(got) > 0
    assert d["code_space_predicates"] >= 1
    assert d["decoded_bytes_avoided"] > 0
    assert seams and set(seams) == {"output"}, seams


def test_dictionary_hash_precompute_matches_per_row_hash():
    """Ops-level pin of the join-hash precompute (the fast tier-1 face
    of the slow join drive below): murmur3 over an encoded key — one
    dictionary-table hash + a code-indexed take — equals the per-row
    string hash of the decoded column, nulls included."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.hashing import murmur3_batch
    C.set_active_conf(C.RapidsConf({}))
    vals = [CATS[i % len(CATS)] for i in range(37)] + [None, CATS[0]]
    enc = ColumnarBatch.from_arrow(
        pa.table({"s": pa.array(vals).dictionary_encode()}))
    assert isinstance(enc.columns[0], DictionaryColumn)
    plain = ColumnarBatch.from_arrow(pa.table({"s": pa.array(vals)}))
    eb = encoded.counters()
    h_enc = murmur3_batch(enc.columns)[:len(vals)]
    h_plain = murmur3_batch(plain.columns)[:len(vals)]
    d = _delta(eb, encoded.counters())
    assert d["dict_hash_tables"] >= 1
    assert jnp.array_equal(h_enc, h_plain)


@pytest.mark.slow  # ~11s: two fresh sessions compile the join+agg pipeline
def test_string_key_join_agg_identical_and_dict_hashed(tmp_path):
    """String-key hash join + aggregate: identical results on vs off,
    with the join's per-row hashes served by the once-per-dictionary
    murmur3 precompute (dict_hash_tables) instead of a per-row byte
    hash."""
    rng = np.random.default_rng(7)
    n = 3000
    lp = os.path.join(str(tmp_path), "facts.parquet")
    dp = os.path.join(str(tmp_path), "dim.parquet")
    pq.write_table(pa.table({
        "s": pa.array([CATS[i] for i in rng.integers(0, len(CATS), n)]),
        "v": pa.array(np.arange(n), pa.int64()),
    }), lp)
    pq.write_table(pa.table({
        "s2": pa.array(CATS[1:3]),
        "w": pa.array([10, 20], pa.int64()),
    }), dp)
    results, enc = {}, {}
    for mode, settings in (("on", {}), ("off", dict(OFF))):
        sess = TpuSession(dict(settings))
        facts = sess.read_parquet(lp)
        dim = sess.read_parquet(dp)
        q = facts.join(dim, left_on=["s"], right_on=["s2"]) \
            .group_by("s").agg((F.sum("v"), "sv"), (F.count(), "c"))
        eb = encoded.counters()
        results[mode] = sorted(q.collect())
        enc[mode] = _delta(eb, encoded.counters())
    assert results["on"] == results["off"] and len(results["on"]) == 2
    assert enc["on"]["dict_hash_tables"] >= 1
    assert enc["off"]["dict_hash_tables"] == 0


# ---------------------------------------------------------------------------
# the spill lane: encoded batches survive the PR 3 forced-spill recipe
# ---------------------------------------------------------------------------

def test_encoded_batch_spill_unspill_roundtrip(tmp_path):
    """Catalog-level pin of the spill lane (the fast tier-1 face of the
    slow forced-spill drive below): an encoded batch spills device ->
    host -> disk and unspills back with the DictionaryColumn pytree —
    not a decoded copy — and identical rows."""
    from spark_rapids_tpu.memory import (SpillableBatch, StorageTier,
                                         buffer_catalog,
                                         reset_buffer_catalog)
    C.set_active_conf(C.RapidsConf({
        "spark.rapids.memory.host.spillStorageSize": "1k",
        "spark.rapids.memory.spillDirectory": str(tmp_path),
    }))
    reset_buffer_catalog()
    try:
        vals = [CATS[i % len(CATS)] for i in range(200)] + [None]
        batch = ColumnarBatch.from_arrow(
            pa.table({"s": pa.array(vals).dictionary_encode()}))
        assert isinstance(batch.columns[0], DictionaryColumn)
        want = encoded.materialize_batch(batch).to_pydict()
        sb = SpillableBatch.from_batch(batch)
        cat = buffer_catalog()
        cat.synchronous_spill(None)  # device -> host -> (1k limit) -> disk
        assert cat.tier_of(sb._handle) == StorageTier.DISK
        got = sb.get_batch()
        assert isinstance(got.columns[0], DictionaryColumn)
        assert encoded.materialize_batch(got).to_pydict() == want
        sb.release()
        sb.close()
    finally:
        reset_buffer_catalog()


@pytest.mark.slow  # ~11s: two fresh sessions compile filter+join+agg
def test_forced_spill_through_encoded_lane(tmp_path):
    """The PR 3 forced-spill recipe (string-keyed scan->filter->join->
    agg under a 640 KiB budget): the catalog really spills batches that
    carry DictionaryColumns, and unspill restores the encoded pytree —
    results identical to the conf-off run."""
    from spark_rapids_tpu.memory.budget import reset_memory_budget
    from spark_rapids_tpu.memory.catalog import (buffer_catalog,
                                                 reset_buffer_catalog)
    rng = np.random.default_rng(3)
    n_l, n_o = 4000, 500
    lp = os.path.join(str(tmp_path), "lines.parquet")
    op = os.path.join(str(tmp_path), "orders.parquet")
    pq.write_table(pa.table({
        "l_key": pa.array(rng.integers(0, n_o, n_l), pa.int64()),
        "l_cat": pa.array([CATS[i]
                           for i in rng.integers(0, len(CATS), n_l)]),
        "l_val": pa.array(rng.random(n_l) * 100.0, pa.float64()),
    }), lp, row_group_size=512)
    pq.write_table(pa.table({
        "o_key": pa.array(np.arange(n_o), pa.int64()),
        "o_flag": pa.array(rng.integers(0, 10, n_o), pa.int64()),
    }), op, row_group_size=128)
    results, spilled, enc = {}, {}, {}
    try:
        for mode, settings in (("on", {}), ("off", dict(OFF))):
            reset_buffer_catalog()
            reset_memory_budget(640 * 1024)
            settings = dict(settings, **{
                "spark.rapids.memory.spillDirectory": str(tmp_path)})
            sess = TpuSession(settings)
            lines = sess.read_parquet(lp).filter(
                col("l_cat") != lit(CATS[0]))
            orders = sess.read_parquet(op).filter(
                col("o_flag") < lit(5))
            j = lines.join(orders, left_on=["l_key"],
                           right_on=["o_key"])
            agg = j.group_by("l_cat").agg((F.count(), "cnt"))
            eb = encoded.counters()
            results[mode] = sorted(agg.collect())
            enc[mode] = _delta(eb, encoded.counters())
            spilled[mode] = buffer_catalog().spilled_device_bytes
    finally:
        reset_buffer_catalog()
        reset_memory_budget()
    assert spilled["on"] > 0 and spilled["off"] > 0  # the budget bit
    assert enc["on"]["cols_encoded"] >= 1  # encoded batches in play
    assert results["on"] == results["off"] and len(results["on"]) == 3


# ---------------------------------------------------------------------------
# chaos: the materialize seam is a recoverable device-dispatch site
# ---------------------------------------------------------------------------

def test_chaos_inject_once_at_materialize_seam_recovers():
    """A seeded device fault at the materialize seam's device.dispatch
    check raises on the first decode and, with its max=1 budget spent,
    the retry decodes correctly — the inject-once -> recover contract
    every task-retry site obeys."""
    C.set_active_conf(C.RapidsConf({}))
    vals = ["a", "b", None, "a", "c"]
    batch = ColumnarBatch.from_arrow(
        pa.table({"s": pa.array(vals).dictionary_encode()}))
    assert isinstance(batch.columns[0], DictionaryColumn)
    faults.install("device.dispatch:prob=1,seed=0,kind=device,max=1")
    with pytest.raises(faults.InjectedDeviceError):
        encoded.materialize_batch(batch)
    out = encoded.materialize_batch(batch)  # budget spent -> clean
    injected = faults.stats().get("device.dispatch")
    faults.install(None)
    assert out.to_pydict() == {"s": vals}
    assert injected == 1


def test_chaos_e2e_encoded_query_recovers(tmp_path):
    """End to end: an encoded scan->filter->collect under a seeded
    inject-once device fault returns the fault-free result through the
    session's task-retry lane."""
    path = _write_strings(tmp_path, n=800)
    want = TpuSession().read_parquet(path) \
        .filter(col("s") == lit(CATS[2])).collect()
    sess = TpuSession({
        "spark.rapids.tpu.test.faults":
            "device.dispatch:prob=1,seed=0,kind=device,max=1",
        "spark.rapids.tpu.task.retryBackoffMs": "1",
    })
    got = sess.read_parquet(path).filter(col("s") == lit(CATS[2])) \
        .collect()
    assert got == want and len(got) > 0
    assert faults.stats().get("device.dispatch", 0) >= 1


# ---------------------------------------------------------------------------
# the dict_gather kern_bench family
# ---------------------------------------------------------------------------

def test_kern_bench_dict_gather_family():
    """Both lanes of the `dict_gather` family run (interpret mode) and
    report positive medians — the harness half of the measured-tier
    contract; the registries themselves are lint-pinned."""
    xla_ms, pallas_ms = kern_bench.bench_dict_gather(
        (256, 64), iters=2, reps=1, interpret=True)
    assert xla_ms > 0 and pallas_ms > 0
