"""Device HOF kernels (ops/array_hof.py) — differential vs the host
row-tier evaluators."""
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.types import (ArrayType, LONG, STRING, Schema,
                                    StructField)

SCH = Schema((StructField("a", ArrayType(LONG)),))
SSCH = Schema((StructField("s", ArrayType(STRING)),))


def _run(data, schema, expr):
    sess = TpuSession()
    q = sess.from_pydict(data, schema).select(expr.alias("o"))
    assert "HostProjectExec" not in q._exec().tree_string()
    return [r[0] for r in q.collect()]


def test_transform_device():
    got = _run({"a": [[1, 2, None], [], None, [5]]}, SCH,
               F.transform(F.col("a"), lambda x: x * F.lit(3)))
    assert got == [[3, 6, None], [], None, [15]]


def test_filter_device_compacts():
    got = _run({"a": [[1, 5, None, 7], [2], None, []]}, SCH,
               F.filter_(F.col("a"), lambda x: x > F.lit(2)))
    assert got == [[5, 7], [], None, []]


def test_exists_forall_three_valued():
    data = {"a": [[1, None], [5, None], [5], [], None, [1]]}
    got = _run(data, SCH, F.exists(F.col("a"), lambda x: x > F.lit(4)))
    assert got == [None, True, True, False, None, False]
    got = _run(data, SCH, F.forall(F.col("a"), lambda x: x > F.lit(0)))
    assert got == [None, None, True, True, None, True]


def test_filter_string_elements():
    got = _run({"s": [["aa", "b", None, "ccc"], [], None]}, SSCH,
               F.filter_(F.col("s"),
                         lambda x: F.length(x) > F.lit(1)))
    assert got == [["aa", "ccc"], [], None]


def test_transform_string_elements():
    got = _run({"s": [["ab", None, "c"], None]}, SSCH,
               F.transform(F.col("s"), lambda x: F.upper(x)))
    assert got == [["AB", None, "C"], None]


def test_host_tier_op_inside_lambda_falls_back():
    # an operator without a device kernel inside the lambda body must
    # route the whole projection to the host tier at PLAN time, not
    # crash inside the compiled projection
    sess = TpuSession()
    df = sess.from_pydict({"s": [["ab", "c"]]}, SSCH)
    q = df.select(F.transform(
        F.col("s"), lambda x: F.levenshtein(x, F.lit("a"))).alias("o"))
    assert "HostProjectExec" in q._exec().tree_string()
    assert q.collect() == [([1, 1],)]
