"""API validation — the reference's api_validation module
(ApiValidation.scala: reflection-diff of Gpu exec signatures against each
Spark version, catching registry drift; SURVEY §2.11). This engine's
analog validates the rule registries against the expression classes by
reflection, so a rule pointing at a renamed/missing surface fails CI
instead of exploding at plan time."""

import inspect

import pytest

from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.plan.overrides import expression_rules


def test_every_rule_class_is_an_expression():
    for cls in expression_rules():
        assert issubclass(cls, Expression), cls


def test_every_rule_class_has_eval_surface():
    """Each registered expression must be evaluable SOMEWHERE: a real
    columnar_eval override (device tier) or a host interpreter hook
    (host tier / _EVALS / _SPECIAL)."""
    from spark_rapids_tpu.exec.fallback import _EVALS, _SPECIAL
    from spark_rapids_tpu.expr.core import (Alias, BoundReference, Literal,
                                            UnresolvedAttribute)
    leaves = (Alias, BoundReference, Literal, UnresolvedAttribute)
    for cls in expression_rules():
        if issubclass(cls, leaves):
            continue
        has_device = cls.columnar_eval is not Expression.columnar_eval \
            and "NotImplementedError" not in (
                inspect.getsource(cls.columnar_eval)
                if cls.columnar_eval.__qualname__.startswith(cls.__name__)
                else "x")
        has_host = (cls in _EVALS or cls in _SPECIAL
                    or hasattr(cls, "host_eval_row")
                    or hasattr(cls, "host_eval_with_row"))
        assert has_device or has_host, \
            f"{cls.__name__} registered but not evaluable on any tier"


def test_rule_descriptions_and_signatures_present():
    for cls, rule in expression_rules().items():
        assert rule.desc, cls
        assert rule.input_sig.tags and rule.output_sig.tags, cls


def test_with_children_reconstructs():
    """Every non-leaf expression's with_children must round-trip its
    children (the transform_up contract that resolution and the UDF
    rewriter rely on)."""
    from spark_rapids_tpu.expr.arithmetic import Add
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.expr.predicates import And, EqualTo
    from spark_rapids_tpu.expr.stringexprs import RegExpReplace, Upper
    for e in (Add(col("a"), lit(1)),
              And(EqualTo(col("a"), lit(1)), EqualTo(col("b"), lit(2))),
              Upper(col("s")),
              RegExpReplace(col("s"), "a", "b")):
        rebuilt = e.with_children(list(e.children))
        assert type(rebuilt) is type(e)
        assert len(rebuilt.children) == len(e.children)


def test_exec_conversion_covers_all_logical_nodes():
    """Every LogicalPlan node class must have a conversion in
    PlanMeta.convert (the analog of 'every Spark exec has a Gpu
    replacement or an explicit fallback')."""
    import inspect as _i

    from spark_rapids_tpu.plan import logical as L
    from spark_rapids_tpu.plan.overrides import PlanMeta
    src = _i.getsource(PlanMeta.convert) \
        + _i.getsource(PlanMeta._convert_join)
    for name, cls in vars(L).items():
        if (_i.isclass(cls) and issubclass(cls, L.LogicalPlan)
                and cls is not L.LogicalPlan):
            assert f"L.{name}" in src, \
                f"{name} has no conversion in PlanMeta.convert"
