"""Sort + aggregate exec tests with numpy/python oracles (the reference's
CPU-vs-GPU comparison pattern, SparkQueryCompareTestSuite:194)."""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.exec.sort import SortExec, TopNExec
from spark_rapids_tpu.expr.aggexprs import (
    Average, Count, First, Last, Max, Min, StddevSamp, Sum,
)
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.ops.sort import SortOrder
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)

SCHEMA = Schema((StructField("k", STRING), StructField("v", INT),
                 StructField("d", DOUBLE)))
DATA = {
    "k": ["b", "a", None, "b", "a", "c", None, "b", "a", "c"],
    "v": [3, 1, 7, None, 5, 2, 9, 4, None, 6],
    "d": [1.5, 2.5, 0.5, 3.5, None, 4.5, 5.5, 6.5, 7.5, 8.5],
}


def make_scan(data=DATA, schema=SCHEMA, split=0):
    n = len(next(iter(data.values())))
    if split:
        batches = [ColumnarBatch.from_pydict(
            {k: v[s:s + split] for k, v in data.items()}, schema)
            for s in range(0, n, split)]
    else:
        batches = [ColumnarBatch.from_pydict(data, schema)]
    return InMemoryScanExec(batches, schema)


# ---------- sort ----------

def test_sort_int_asc_nulls_first():
    plan = SortExec([(col("v"), True)], make_scan())
    got = [r[1] for r in plan.collect()]
    assert got == [None, None, 1, 2, 3, 4, 5, 6, 7, 9]


def test_sort_int_desc_nulls_last():
    plan = SortExec([(col("v"), False)], make_scan(split=4))
    got = [r[1] for r in plan.collect()]
    assert got == [9, 7, 6, 5, 4, 3, 2, 1, None, None]


def test_sort_string_then_int():
    plan = SortExec([(col("k"), True), (col("v"), True)], make_scan())
    got = [(r[0], r[1]) for r in plan.collect()]
    expect = [(None, 7), (None, 9), ("a", None), ("a", 1), ("a", 5),
              ("b", None), ("b", 3), ("b", 4), ("c", 2), ("c", 6)]
    assert got == expect


def test_sort_doubles_with_nan():
    data = {"k": ["x"] * 6, "v": [1] * 6,
            "d": [float("nan"), -0.0, 1.0, float("-inf"), None, float("inf")]}
    plan = SortExec([(col("d"), True)], make_scan(data))
    got = [r[2] for r in plan.collect()]
    assert got[0] is None
    assert got[1] == float("-inf")
    assert got[2] == 0.0
    assert got[3] == 1.0
    assert got[4] == float("inf")
    assert math.isnan(got[5])  # NaN greatest (Spark)


def test_sort_long_strings_exact():
    # strings sharing a 32-byte prefix force the exact-width lane path
    base = "p" * 40
    data = {"k": [base + "b", base + "a", base + "c", "q"],
            "v": [1, 2, 3, 4], "d": [1.0, 2.0, 3.0, 4.0]}
    plan = SortExec([(col("k"), True)], make_scan(data))
    got = [r[0] for r in plan.collect()]
    assert got == [base + "a", base + "b", base + "c", "q"]


def test_topn():
    plan = TopNExec(3, [(col("v"), False)], make_scan(split=3))
    got = [r[1] for r in plan.collect()]
    assert got == [9, 7, 6]


# ---------- aggregate ----------

def test_groupby_sum_count_multibatch():
    plan = AggregateExec(
        [col("k")],
        [(Sum(col("v")), "sv"), (Count(col("v")), "cv"), (Count(), "c")],
        make_scan(split=3))
    got = {r[0]: r[1:] for r in plan.collect()}
    assert got == {
        None: (16, 2, 2),
        "a": (6, 2, 3),
        "b": (7, 2, 3),
        "c": (8, 2, 2),
    }


def test_groupby_min_max_avg():
    plan = AggregateExec(
        [col("k")],
        [(Min(col("v")), "mn"), (Max(col("v")), "mx"),
         (Average(col("d")), "av")],
        make_scan(split=4))
    got = {r[0]: r[1:] for r in plan.collect()}
    assert got[None] == (7, 9, 3.0)
    assert got["a"] == (1, 5, 5.0)
    assert got["b"] == (3, 4, pytest.approx(11.5 / 3))
    assert got["c"] == (2, 6, 6.5)


def test_groupby_string_min_max():
    plan = AggregateExec(
        [col("v") % lit(2)],
        [(Min(col("k")), "mn"), (Max(col("k")), "mx")],
        make_scan())
    got = {r[0]: r[1:] for r in plan.collect()}
    # v%2==1: rows v=1,3,5,7,9 -> k in {a,b,a,None,None}; min 'a' max 'b'
    assert got[1] == ("a", "b")
    # v%2==0: v=2,4,6 -> k in {c,b,c}
    assert got[0] == ("b", "c")
    # v null -> key null: k in {b,a}
    assert got[None] == ("a", "b")


def test_grand_aggregate_no_keys():
    plan = AggregateExec(
        [],
        [(Sum(col("v")), "s"), (Count(), "c"), (Min(col("d")), "mn")],
        make_scan(split=3))
    rows = plan.collect()
    assert rows == [(37, 10, 0.5)]


def test_grand_aggregate_empty_input():
    schema = SCHEMA
    scan = InMemoryScanExec([], schema)
    plan = AggregateExec([], [(Count(), "c"), (Sum(col("v")), "s")], scan)
    rows = plan.collect()
    assert rows == [(0, None)]


def test_sum_all_null_group_is_null():
    data = {"k": ["a", "a"], "v": [None, None], "d": [1.0, 2.0]}
    plan = AggregateExec([col("k")], [(Sum(col("v")), "s"),
                                      (Count(col("v")), "c")],
                         make_scan(data))
    assert plan.collect() == [("a", None, 0)]


def test_stddev():
    data = {"k": ["a", "a", "a", "b"], "v": [1, 2, 3, 4],
            "d": [2.0, 4.0, 6.0, 5.0]}
    plan = AggregateExec([col("k")], [(StddevSamp(col("d")), "sd")],
                         make_scan(data))
    got = {r[0]: r[1] for r in plan.collect()}
    assert got["a"] == pytest.approx(2.0)
    assert math.isnan(got["b"])  # n==1 -> NaN


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_partial_final_split():
    """partial -> (simulated shuffle) -> final gives same answer."""
    partial = AggregateExec([col("k")], [(Sum(col("v")), "s"),
                                         (Average(col("d")), "a")],
                            make_scan(split=3), mode="partial")
    bufs = list(partial.execute())
    final_scan = InMemoryScanExec(bufs, partial.output_schema)
    final = AggregateExec([col("k")], [(Sum(col("v")), "s"),
                                       (Average(col("d")), "a")],
                          final_scan, mode="final")
    got = {r[0]: r[1:] for r in final.collect()}
    complete = AggregateExec([col("k")], [(Sum(col("v")), "s"),
                                          (Average(col("d")), "a")],
                             make_scan())
    want = {r[0]: r[1:] for r in complete.collect()}
    for k in want:
        assert got[k][0] == want[k][0]
        assert got[k][1] == pytest.approx(want[k][1])


def test_first_last_after_sort():
    plan = AggregateExec(
        [col("k")],
        [(First(col("v"), ignore_nulls=True), "f"),
         (Last(col("v"), ignore_nulls=True), "l")],
        SortExec([(col("k"), True), (col("v"), True)], make_scan()))
    got = {r[0]: r[1:] for r in plan.collect()}
    assert got["a"] == (1, 5)
    assert got["c"] == (2, 6)


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_out_of_core_sort_streams_bounded_chunks():
    """>MERGE_FAN_IN runs: the streamed merge must emit multiple bounded
    batches whose concatenation is exactly the global sort (reference
    GpuOutOfCoreSortIterator, GpuSortExec.scala:281)."""
    import numpy as np
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.exec.sort import SortExec
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.types import INT, STRING, Schema, StructField

    rng = np.random.default_rng(23)
    sch = Schema((StructField("k", INT), StructField("s", STRING)))
    n_batches, rows = 20, 64
    batches, all_rows = [], []
    for _ in range(n_batches):
        ks = [None if rng.random() < 0.1 else int(x)
              for x in rng.integers(-1000, 1000, rows)]
        ss = [f"s{int(x):03d}" for x in rng.integers(0, 500, rows)]
        all_rows += list(zip(ks, ss))
        batches.append(ColumnarBatch.from_pydict({"k": ks, "s": ss}, sch))
    plan = SortExec([(col("k"), True), (col("s"), True)],
                    InMemoryScanExec(batches, sch))
    out_batches = list(plan.execute())
    assert len(out_batches) > 1, "streamed merge must emit multiple chunks"
    # bounded device footprint: no emitted chunk anywhere near the total
    total = n_batches * rows
    assert all(b.capacity < total for b in out_batches)
    got = [r for b in out_batches for r in b.to_pylist()]
    exp = sorted(all_rows, key=lambda r: (r[0] is not None, r[0] or 0, r[1]))
    assert got == exp


def test_out_of_core_sort_disabled_conf():
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.exec.sort import SortExec
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.types import INT, Schema, StructField

    TpuSession({"spark.rapids.sql.sort.outOfCore.enabled": False})
    sch = Schema((StructField("k", INT),))
    batches = [ColumnarBatch.from_pydict({"k": [i, 100 - i]}, sch)
               for i in range(12)]
    plan = SortExec([(col("k"), True)], InMemoryScanExec(batches, sch))
    out = list(plan.execute())
    assert len(out) == 1  # concat-all path
    got = [r[0] for b in out for r in b.to_pylist()]
    assert got == sorted(got)
    TpuSession()  # reset active conf
