"""Engine contract analyzer (ISSUE 12): per-rule fixture corpus (each
rule fires on its fixture, a justified suppression silences it), the
suppression-justification and baseline lints, the CLI JSON surface, and
THE tier-1 gate — the whole package analyzes clean against the
checked-in baseline."""

import json
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parents[1]
FIXTURES = ROOT / "tests" / "fixtures" / "contract"

from spark_rapids_tpu import analysis  # noqa: E402
from spark_rapids_tpu.analysis import core as acore  # noqa: E402
from spark_rapids_tpu.analysis import registry as reg_mod  # noqa: E402
from spark_rapids_tpu.analysis.registry import (  # noqa: E402
    ContractRegistry, EntrySpec, LockSpec, PairSpec)

sys.path.insert(0, str(ROOT / "tools"))
try:
    import contract_check
finally:
    sys.path.pop(0)


def fixture_registry(fname: str) -> ContractRegistry:
    """Fixture twin of DEFAULT_REGISTRY scoped to one fixture module
    (module matching is suffix-based, so each fixture file gets its own
    specs)."""
    return ContractRegistry(
        locks=[
            LockSpec("fx-outer", fname, "Engine", "self._outer",
                     reentrant=False, note="fixture outer lock"),
            LockSpec("fx-lock", fname, "Engine", "self._lock",
                     reentrant=False, note="fixture lock"),
        ],
        lock_order=["fx-outer", "fx-lock"],
        cross_query_entries=[
            EntrySpec(fname, None, "writer_loop", "fixture producer")],
        pairs=[PairSpec("fx-budget", "reserve", "release", "budget",
                        (fname,),
                        {"escrowed": "fixture: ownership transfers"})],
        adopt_helpers=reg_mod.ADOPT_HELPERS,
        extra_blocking_calls={},
        scope_prefix="",  # fixtures live under tests/, not the package
    )


def run_fixture(fname: str, rules=None):
    return analysis.analyze_paths([FIXTURES / fname], ROOT,
                                  registry=fixture_registry(fname),
                                  rules=rules)


def rules_fired(report):
    return sorted({f.rule for f in report.findings})


# -- per-rule: fixture fires ------------------------------------------------

def test_lock_rules_fire():
    rep = run_fixture("fx_locks.py")
    by_rule = {}
    for f in rep.findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"lock-blocking-call", "lock-reacquire",
                            "lock-order"}
    # the direct sleep AND the one reached through the module-local walk
    blocking_scopes = {f.scope for f in by_rule["lock-blocking-call"]}
    assert "Engine.bad_blocking" in blocking_scopes
    assert "Engine._do_io" in blocking_scopes  # via bad_blocking_via_call
    assert by_rule["lock-reacquire"][0].key == "fx-lock"
    assert by_rule["lock-order"][0].key == "fx-lock->fx-outer"


def test_bounded_wait_rule_fires():
    """ISSUE 20 satellite: provably unbounded waits (zero positional
    args, no timeout= kwarg) on wait/get/result/sleep fire; bounded,
    positional-arg (dict.get) and splat forms stay clean."""
    rep = run_fixture("fx_bounded_wait.py")
    assert rules_fired(rep) == ["bounded-wait"]
    got = {(f.scope, f.key) for f in rep.findings}
    assert got == {("parked_on_event", "ev.wait"),
                   ("parked_on_queue", "q.get"),
                   ("parked_on_future", "fut.result")}, got


def test_thread_rule_fires_and_resolves_adoption():
    rep = run_fixture("fx_threads.py")
    assert rules_fired(rep) == ["thread-adopt"]
    scopes = {f.scope for f in rep.findings}
    assert scopes == {"spawn_bad", "submit_bad"}  # spawn_good is clean


def test_trace_rules_fire():
    rep = run_fixture("fx_trace.py")
    by_rule = {}
    for f in rep.findings:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"trace-module-jnp", "trace-host-sync"}
    assert [f.key for f in by_rule["trace-module-jnp"]] == ["_BAD"]
    assert {f.scope for f in by_rule["trace-host-sync"]} == \
        {"traced", "add_kernel"}  # `untraced` stays clean


def test_conf_rule_fires_only_from_entry():
    rep = run_fixture("fx_conf.py")
    assert rules_fired(rep) == ["conf-provenance"]
    assert len(rep.findings) == 1
    assert rep.findings[0].scope == "_helper"  # via writer_loop;
    # consumer_side's read is NOT reachable from the entry


def test_accounting_rule_shapes():
    rep = run_fixture("fx_accounting.py")
    assert rules_fired(rep) == ["accounting-symmetry"]
    keys = {f.scope: f.key for f in rep.findings}
    assert keys == {"one_sided": "fx-budget:one-sided",
                    "exception_edge": "fx-budget:exception-edge"}
    # guarded (finally) and escrowed (registry-declared) stay clean


def test_dispatch_rule_fires():
    """ISSUE 13 satellite: every jax.jit / pallas_call site must route
    through the dispatch-ledger chokepoint (obs.dispatch.instrument)
    or carry a justified suppression — a bare site's dispatches and
    compiles are invisible to the observability plane."""
    rep = run_fixture("fx_dispatch.py")
    assert rules_fired(rep) == ["dispatch-ledger"]
    keys = sorted(f.key for f in rep.findings)
    assert keys == ["jax.jit", "jax.jit", "pallas_call"], keys


def test_stage_governance_rule_fires():
    """ISSUE 14 satellite: per-batch governance hooks (lifecycle tick,
    chaos fault points, metric timers, event emits, gather observes)
    are forbidden inside traced stage bodies handed to the dispatch
    chokepoint — they run once per TRACE, not per batch. The rule
    resolves local defs, self._method references, lambdas, partial
    wrappers and @partial(instrument, ...) decorators, and walks one
    hop into module-local helpers."""
    rep = run_fixture("fx_stage.py")
    assert rules_fired(rep) == ["stage-governance"]
    keys = sorted(f.key for f in rep.findings)
    assert keys == ["emit", "faults.check", "ns_timer", "observe",
                    "tick"], keys
    scopes = {f.key: f.scope for f in rep.findings}
    assert scopes["ns_timer"] == "_kernel"      # self._site(self._m)
    assert scopes["emit"] == "decorated_body"   # @partial(instrument)
    assert scopes["observe"] == "<lambda>"      # one-hop via helper


def test_registry_rules_fire():
    rep = run_fixture("fx_registry.py")
    assert rules_fired(rep) == ["conf-key-registered",
                                "event-kind-registered"]
    assert {f.key for f in rep.findings} == \
        {"spark.rapids.tpu.fixture.not.registered",
         "fixture_unregistered_kind"}


# -- per-rule: suppression silences -----------------------------------------

@pytest.mark.parametrize("fname,n_suppressed", [
    ("fx_locks_ok.py", 4),
    ("fx_bounded_wait_ok.py", 3),
    ("fx_threads_ok.py", 2),
    ("fx_trace_ok.py", 4),
    ("fx_conf_ok.py", 1),
    ("fx_accounting_ok.py", 2),
    ("fx_registry_ok.py", 2),
    ("fx_dispatch_ok.py", 2),
    ("fx_stage_ok.py", 1),
])
def test_suppressions_silence(fname, n_suppressed):
    rep = run_fixture(fname)
    assert rep.findings == [], [f.render() for f in rep.findings]
    assert len(rep.suppressed) == n_suppressed
    for _f, why, _line in rep.suppressed:
        assert why.strip(), "suppression accepted without justification"


def test_empty_justification_is_its_own_finding():
    rep = run_fixture("fx_suppress_empty.py")
    meta = [f for f in rep.findings if f.rule == "suppression-empty"]
    # one empty why + one typo'd rule id
    assert len(meta) == 2
    assert {f.key for f in meta} == {"lock-blocking-call",
                                     "lock-blocking-cal"}
    # the empty-why suppression still silenced its base finding (CI
    # fails on the meta finding, not on double noise) while the typo'd
    # one silenced NOTHING
    real = [f for f in rep.findings if f.rule == "lock-blocking-call"]
    assert len(real) == 1 and real[0].scope == "Engine.typo"


# -- baseline ---------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    rep = run_fixture("fx_locks.py")
    findings = rep.sorted_findings()
    bl_path = tmp_path / "baseline.json"
    entries = acore.write_baseline(bl_path, findings)
    # write mode stamps new entries UNREVIEWED...
    assert all(e["why"] == acore.UNREVIEWED_WHY for e in entries.values())
    loaded = acore.load_baseline(bl_path)
    new, stale, lint = acore.apply_baseline(findings, loaded)
    assert new == [] and stale == []
    # ...and the lint rejects the UNREVIEWED stamp until justified
    assert lint and all(f.rule == "baseline-invalid" for f in lint)
    for e in loaded.values():
        e["why"] = "fixture: accepted"
    new, stale, lint = acore.apply_baseline(findings, loaded)
    assert (new, stale, lint) == ([], [], [])
    # a fixed finding leaves its entry STALE (the file must shrink)
    new, stale, lint = acore.apply_baseline(findings[1:], loaded)
    assert len(stale) == 1 and new == []
    # count semantics: two identical findings, one baselined slot
    dup = [findings[0], findings[0]]
    one = {findings[0].fingerprint: {"count": 1, "why": "x"}}
    new, _stale, _lint = acore.apply_baseline(dup, one)
    assert len(new) == 1


def test_partially_fixed_baseline_entry_is_stale():
    """A count=2 entry with only one finding left must fail as stale —
    the leftover slot would otherwise silently absorb a future
    regression of the same fingerprint (review round fix)."""
    rep = run_fixture("fx_locks.py")
    f = rep.sorted_findings()[0]
    baseline = {f.fingerprint: {"count": 2, "why": "accepted debt"}}
    new, stale, lint = acore.apply_baseline([f], baseline)
    assert new == [] and lint == []
    assert stale == [f.fingerprint]
    # both slots consumed -> clean
    new, stale, _ = acore.apply_baseline([f, f], baseline)
    assert new == [] and stale == []


def test_baseline_write_refuses_scoped_runs(tmp_path, monkeypatch, capsys):
    """`--baseline write` on a path- or rule-scoped run would rewrite
    the whole file from a slice of the findings, destroying every
    out-of-scope entry and its justification — it must refuse."""
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps(
        {"version": 1,
         "findings": {"keep::me::alive::slot":
                      {"count": 1, "why": "precious"}}}))
    monkeypatch.setattr(contract_check, "DEFAULT_BASELINE", bl)
    assert contract_check.main(
        [str(FIXTURES / "fx_registry.py"), "--baseline", "write"]) == 2
    assert contract_check.main(
        ["--rules", "conf-key-registered", "--baseline", "write"]) == 2
    capsys.readouterr()
    assert "precious" in bl.read_text()  # untouched


def test_baseline_write_preserves_existing_whys(tmp_path, monkeypatch):
    monkeypatch.setattr(contract_check, "DEFAULT_BASELINE",
                        tmp_path / "bl.json")
    rep = run_fixture("fx_registry.py")
    prev = {rep.sorted_findings()[0].fingerprint:
            {"count": 1, "why": "kept justification"}}
    entries = acore.write_baseline(tmp_path / "bl.json",
                                   rep.sorted_findings(), prev)
    kept = entries[rep.sorted_findings()[0].fingerprint]
    assert kept["why"] == "kept justification"


# -- CLI --------------------------------------------------------------------

def test_cli_json_golden(tmp_path, capsys):
    """`--format json` on a firing fixture: nonzero exit + the stable
    record shape downstream tooling parses."""
    rc = contract_check.main([
        str(FIXTURES / "fx_registry.py"), "--format", "json",
        "--baseline", str(tmp_path / "missing.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["exit"] == 1
    assert out["files_scanned"] == 1
    assert out["stale_baseline"] == [] and out["baseline_lint"] == []
    got = {(f["rule"], f["key"], f["scope"]) for f in out["findings"]}
    assert got == {
        ("conf-key-registered",
         "spark.rapids.tpu.fixture.not.registered", "<module>"),
        ("event-kind-registered", "fixture_unregistered_kind",
         "<module>"),
    }
    for f in out["findings"]:
        assert set(f) == {"rule", "path", "line", "scope", "key",
                          "message", "fingerprint"}


def test_cli_clean_exit_zero(tmp_path, capsys):
    rc = contract_check.main([
        str(FIXTURES / "fx_registry_ok.py"), "--format", "json",
        "--baseline", str(tmp_path / "missing.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["findings"] == [] and out["suppressed"] == 2


def test_cli_rule_filter(tmp_path, capsys):
    rc = contract_check.main([
        str(FIXTURES / "fx_registry.py"), "--format", "json",
        "--rules", "conf-key-registered",
        "--baseline", str(tmp_path / "missing.json")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in out["findings"]} == {"conf-key-registered"}


# -- registry/docs coherence ------------------------------------------------

def test_registry_specs_name_real_modules():
    """Every lock/entry/pair spec in DEFAULT_REGISTRY must point at an
    existing package module — a refactor that moves a file must move
    its contract data too."""
    reg = reg_mod.DEFAULT_REGISTRY
    pkg = ROOT / "spark_rapids_tpu"
    modules = {p.relative_to(ROOT).as_posix()
               for p in pkg.rglob("*.py")}

    def exists(suffix):
        return any(m.endswith(suffix) for m in modules)

    for spec in reg.locks:
        assert exists(spec.module), f"lock {spec.name}: {spec.module}"
    for e in reg.cross_query_entries:
        assert exists(e.module), f"entry {e.func}: {e.module}"
    for p in reg.pairs:
        for m in p.modules:
            assert exists(m), f"pair {p.name}: {m}"
    # every ordered lock is a registered lock
    names = {s.name for s in reg.locks}
    for n in reg.lock_order:
        assert n in names, n


def test_docs_rule_table_matches_registry():
    """docs/static_analysis.md's rule table lists exactly RULES — the
    EVENT_LEVELS/CANONICAL_METRICS drift-lint pattern."""
    import re
    docs = (ROOT / "docs" / "static_analysis.md").read_text()
    rows = set(re.findall(r"^\|\s*`([a-z0-9-]+)`\s*\|", docs,
                          re.MULTILINE))
    expected = set(reg_mod.RULES)
    assert rows == expected, (
        f"docs/static_analysis.md rule table drifted: "
        f"missing={sorted(expected - rows)} "
        f"stale={sorted(rows - expected)}")


def test_every_rule_family_is_fixture_proven():
    """Acceptance guard: each non-meta rule family has at least one
    fixture where it fires (the per-rule tests above pin the details —
    this keeps a NEW rule from landing without a fixture)."""
    fired = set()
    for fname in ("fx_locks.py", "fx_bounded_wait.py", "fx_threads.py",
                  "fx_trace.py", "fx_conf.py", "fx_accounting.py",
                  "fx_registry.py", "fx_dispatch.py", "fx_stage.py"):
        for f in run_fixture(fname).findings:
            fired.add(f.rule)
    non_meta = {rid for rid, m in reg_mod.RULES.items()
                if m.checker is not None}
    assert non_meta <= fired, sorted(non_meta - fired)


# -- THE tier-1 gate ---------------------------------------------------------

def test_whole_package_is_clean_or_baselined():
    """The CI gate (ISSUE 12 acceptance): the analyzer runs over the
    full package scan set in-process; every finding is either inline-
    suppressed with a justification or covered by a justified baseline
    entry; no stale baseline entries (fixes must shrink the file); no
    UNREVIEWED/empty baseline justifications."""
    report = contract_check.build_report()
    baseline = acore.load_baseline(contract_check.DEFAULT_BASELINE)
    new, stale, lint = acore.apply_baseline(report.sorted_findings(),
                                            baseline)
    assert new == [], "new contract findings:\n" + "\n".join(
        f.render() for f in new)
    assert stale == [], (
        "stale baseline entries (finding fixed — delete them): "
        f"{stale}")
    assert lint == [], "\n".join(f.render() for f in lint)
    # the escape hatches stay justified
    for _f, why, _line in report.suppressed:
        assert why.strip()
