"""Iceberg + Hive text integration tests (reference iceberg_test.py /
hive text suites; SURVEY §2.7 #48)."""

import os

import numpy as np
import pytest

from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.types import (BOOLEAN, DOUBLE, LONG, STRING, Schema,
                                    StructField)


def _sorted(rows):
    return sorted(rows, key=repr)


SCH = Schema((StructField("k", LONG), StructField("v", DOUBLE),
              StructField("s", STRING), StructField("b", BOOLEAN)))


def _df(sess, n=50, seed=0):
    rng = np.random.default_rng(seed)
    return sess.from_pydict({
        "k": [int(x) for x in rng.integers(0, 100, n)],
        "v": [None if x % 9 == 0 else float(x) / 3
              for x in rng.integers(0, 100, n)],
        "s": [None if x % 7 == 0 else f"röw-{x}"
              for x in rng.integers(0, 100, n)],
        "b": [None if x % 5 == 0 else bool(x % 2)
              for x in rng.integers(0, 100, n)],
    }, SCH)


# ---------------------------------------------------------------------------
# iceberg
# ---------------------------------------------------------------------------

def test_iceberg_write_read_roundtrip(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "ice")
    df = _df(sess)
    df.write_iceberg(path)
    got = sess.read_iceberg(path).collect()
    assert _sorted(got) == _sorted(df.collect())
    # the metadata chain exists: metadata.json + manifest list + manifest
    names = os.listdir(os.path.join(path, "metadata"))
    assert any(n.endswith(".metadata.json") for n in names)
    assert any(n.startswith("snap-") for n in names)
    assert any(n.endswith("-m0.avro") for n in names)


def test_iceberg_append_and_snapshot_isolation(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "ice")
    _df(sess, 10, seed=1).write_iceberg(path)
    from spark_rapids_tpu.io.iceberg import IcebergTable
    snap1 = IcebergTable(path).metadata()["current-snapshot-id"]
    _df(sess, 5, seed=2).write_iceberg(path, mode="append")
    assert len(sess.read_iceberg(path).collect()) == 15
    # time travel to the first snapshot
    assert len(sess.read_iceberg(path, snapshot_id=snap1).collect()) == 10


def test_iceberg_overwrite(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "ice")
    _df(sess, 10).write_iceberg(path)
    _df(sess, 3, seed=9).write_iceberg(path, mode="overwrite")
    assert len(sess.read_iceberg(path).collect()) == 3


def test_iceberg_filter_pushdown_through_planner(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "ice")
    df = _df(sess, 100)
    df.write_iceberg(path)
    got = sess.read_iceberg(path).filter(col("k") < lit(50)).collect()
    expect = [r for r in df.collect() if r[0] < 50]
    assert _sorted(got) == _sorted(expect)


# ---------------------------------------------------------------------------
# hive text
# ---------------------------------------------------------------------------

def test_hive_text_roundtrip(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "t.hivetxt")
    df = _df(sess, 40)
    df.write_hive_text(path)
    got = sess.read_hive_text(path, SCH).collect()
    assert _sorted(got) == _sorted(df.collect())
    # ^A delimiter + \N null sentinel on disk (LazySimpleSerDe defaults)
    raw = open(path, encoding="utf-8").read()
    assert "\x01" in raw and r"\N" in raw


def test_hive_text_malformed_numeric_reads_null(tmp_path):
    path = str(tmp_path / "bad.hivetxt")
    with open(path, "w") as f:
        f.write("12\x01notanumber\n\\N\x013.5\n")
    sess = TpuSession()
    sch = Schema((StructField("a", LONG), StructField("b", DOUBLE)))
    got = sess.read_hive_text(path, sch).collect()
    assert got == [(12, None), (None, 3.5)]


def test_hive_text_custom_delimiter(tmp_path):
    sess = TpuSession()
    path = str(tmp_path / "csvish.txt")
    df = _df(sess, 10)
    df.write_hive_text(path, field_delim="|")
    got = sess.read_hive_text(path, SCH, field_delim="|").collect()
    assert _sorted(got) == _sorted(df.collect())
