"""Pallas murmur3 kernel parity tests (SURVEY §2.9 #40). Off-TPU the
kernel runs under the Pallas interpreter; results must be BIT-EXACT
against the engine's fused-XLA murmur3 (itself parity-tested against an
independent host oracle in test_hashing.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops.pallas_kernels import (murmur3_int_lanes,
                                                 murmur3_long_lanes)


@pytest.mark.parametrize("n", [1, 7, 128, 1000, 256 * 128 + 3])
def test_long_lanes_match_xla(n):
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(-(2**62), 2**62, n), jnp.int64)
    seeds = jnp.full((n,), jnp.uint32(42))
    xla = H.murmur3_long(data, seeds)
    pal = murmur3_long_lanes(data, seeds, interpret=True)
    assert (np.asarray(xla, np.uint32) == np.asarray(pal)).all()


def test_long_lanes_edge_values():
    vals = jnp.asarray([0, -1, 1, 2**63 - 1, -(2**63), 42], jnp.int64)
    seeds = jnp.full((6,), jnp.uint32(42))
    xla = H.murmur3_long(vals, seeds)
    pal = murmur3_long_lanes(vals, seeds, interpret=True)
    assert (np.asarray(xla, np.uint32) == np.asarray(pal)).all()


def test_int_lanes_match_xla():
    rng = np.random.default_rng(1)
    data = jnp.asarray(rng.integers(-(2**31), 2**31, 5000), jnp.int32)
    seeds = jnp.full((5000,), jnp.uint32(42))
    xla = H.murmur3_int(data, seeds)
    pal = murmur3_int_lanes(data, seeds, interpret=True)
    assert (np.asarray(xla, np.uint32) == np.asarray(pal)).all()


def test_chained_seeds_match_multi_column_hash():
    """Column chaining: col2's seed is col1's hash — the per-row seed
    vector path must stay exact."""
    rng = np.random.default_rng(2)
    c1 = jnp.asarray(rng.integers(-(2**62), 2**62, 777), jnp.int64)
    c2 = jnp.asarray(rng.integers(-(2**31), 2**31, 777), jnp.int32)
    seeds = jnp.full((777,), jnp.uint32(42))
    xla = H.murmur3_int(c2, H.murmur3_long(c1, seeds))
    h1 = murmur3_long_lanes(c1, seeds, interpret=True)
    pal = murmur3_int_lanes(c2, h1, interpret=True)
    assert (np.asarray(xla, np.uint32) == np.asarray(pal)).all()
