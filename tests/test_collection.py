"""Collection expression tests vs Python oracles (reference
collectionOperations.scala; integration analog collection_ops_test.py)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import (
    DOUBLE, LONG, STRING, ArrayType, Schema, StructField,
)

ARRS = [[1, 2, 3], [], None, [5], [7, None, 3], [10, 10], [None], [-4, 0]]


@pytest.fixture(scope="module")
def df():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(LONG)),
                  StructField("n", LONG)))
    return s.from_pydict({"a": ARRS, "n": list(range(len(ARRS)))}, sch)


def run1(df, expr):
    return [r[0] for r in df.select(expr.alias("r")).collect()]


def test_roundtrip(df):
    assert run1(df, col("a")) == ARRS


def test_size(df):
    assert run1(df, F.size(col("a"))) == [
        None if a is None else len(a) for a in ARRS]


def test_array_contains(df):
    got = run1(df, F.array_contains(col("a"), 3))
    exp = []
    for a in ARRS:
        if a is None:
            exp.append(None)
        elif 3 in a:
            exp.append(True)
        elif None in a:
            exp.append(None)
        else:
            exp.append(False)
    assert got == exp


def test_element_at(df):
    assert run1(df, F.element_at(col("a"), 2)) == [
        None if a is None or len(a) < 2 else a[1] for a in ARRS]
    assert run1(df, F.element_at(col("a"), -1)) == [
        None if a is None or not a else a[-1] for a in ARRS]
    assert run1(df, F.get_array_item(col("a"), 0)) == [
        None if a is None or not a else a[0] for a in ARRS]


def test_sort_array(df):
    def srt(a, asc):
        if a is None:
            return None
        nulls = [x for x in a if x is None]
        vals = sorted(x for x in a if x is not None)
        return nulls + vals if asc else vals[::-1] + nulls
    assert run1(df, F.sort_array(col("a"))) == [srt(a, True) for a in ARRS]
    assert run1(df, F.sort_array(col("a"), False)) == [
        srt(a, False) for a in ARRS]


def test_array_min_max(df):
    assert run1(df, F.array_min(col("a"))) == [
        None if a is None or all(x is None for x in a)
        else min(x for x in a if x is not None) for a in ARRS]
    assert run1(df, F.array_max(col("a"))) == [
        None if a is None or all(x is None for x in a)
        else max(x for x in a if x is not None) for a in ARRS]


def test_create_array(df):
    got = run1(df, F.array(col("n"), col("n") + 100, F.lit(7).cast(LONG)))
    assert got == [[n, n + 100, 7] for n in range(len(ARRS))]


def test_filter_preserves_arrays(df):
    got = df.filter(col("n") < 4).select("a", "n").collect()
    assert got == [(a, i) for i, a in enumerate(ARRS[:4])]


def test_string_element_arrays():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(STRING)),))
    arrs = [["x", "yy"], None, [], ["z", None, "abc"]]
    df = s.from_pydict({"a": arrs}, sch)
    assert run1(df, col("a")) == arrs
    assert run1(df, F.size(col("a"))) == [2, None, 0, 3]
    assert run1(df, F.array_contains(col("a"), "abc")) == [
        False, None, False, True]
    assert run1(df, F.element_at(col("a"), 2)) == ["yy", None, None, None]


def test_sort_array_doubles():
    s = TpuSession()
    sch = Schema((StructField("a", ArrayType(DOUBLE)),))
    arrs = [[3.5, -1.0, float("inf")], [float("-inf"), 0.0], None]
    df = s.from_pydict({"a": arrs}, sch)
    assert run1(df, F.sort_array(col("a"))) == [
        [-1.0, 3.5, float("inf")], [float("-inf"), 0.0], None]


def test_nvl_family():
    s = TpuSession()
    sch = Schema((StructField("x", LONG), StructField("y", LONG)))
    df = s.from_pydict({"x": [1, None, 3, None], "y": [9, 8, None, None]},
                       sch)
    assert [r[0] for r in df.select(F.nvl(col("x"), col("y")).alias("r"))
            .collect()] == [1, 8, 3, None]
    assert [r[0] for r in df.select(
        F.nvl2(col("x"), col("y"), F.lit(0).cast(LONG)).alias("r"))
        .collect()] == [9, 0, None, 0]
    assert [r[0] for r in df.select(F.nullif(col("x"), F.lit(3).cast(LONG))
                                    .alias("r")).collect()] == [1, None,
                                                                None, None]
