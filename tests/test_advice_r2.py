"""Regression tests for the round-2 advisor findings (ADVICE.md)."""
import datetime as dt
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar import ArrayColumn, ColumnarBatch
from spark_rapids_tpu.delta.log import DeltaLog
from spark_rapids_tpu.exec.fallback import _java_double_str
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.ops.timezone import local_to_utc
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)
from spark_rapids_tpu.types import (ArrayType, IntegerType, Schema,
                                    StructField)
from spark_rapids_tpu.udf_compiler import compile_udf


def test_udf_branch_locals_do_not_leak():
    # STORE_FAST in the then-branch must not leak into the else-branch
    def f(a):
        b = 1
        if a > 0:
            b = 2
        return b

    e = compile_udf(f, [col("a")])
    s = str(e)
    assert "lit(2)" in s and "lit(1)" in s


def test_udf_nested_branch_locals():
    def f(a):
        x = 10
        if a > 0:
            x = 20
            if a > 5:
                x = 30
        return x

    e = compile_udf(f, [col("a")])
    s = str(e)
    assert "lit(10)" in s and "lit(20)" in s and "lit(30)" in s


@pytest.mark.parametrize("v,expect", [
    (0.0001, "1.0E-4"),
    (1e16, "1.0E16"),
    (1.0, "1.0"),
    (0.001, "0.001"),
    (1234.5, "1234.5"),
    (100.0, "100.0"),
    (1e7, "1.0E7"),
    (9999999.0, "9999999.0"),
    (-0.5, "-0.5"),
    (0.0, "0.0"),
    (-0.0, "-0.0"),
    (1.5e-5, "1.5E-5"),
    (123456789.0, "1.23456789E8"),
    (float("nan"), "NaN"),
    (float("inf"), "Infinity"),
    (float("-inf"), "-Infinity"),
])
def test_java_double_to_string(v, expect):
    assert _java_double_str(v) == expect


def test_java_float_to_string():
    from spark_rapids_tpu.exec.fallback import _java_float_str
    assert _java_float_str(0.10000000149011612) == "0.1"
    assert _java_float_str(12345678.0) == "1.2345678E7"
    assert _java_float_str(1.401298464324817e-45) == "1.4E-45"


def test_double_min_value_java_digits():
    assert _java_double_str(5e-324) == "4.9E-324"


def test_cast_double_to_string_routes_to_host_tier():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import DOUBLE, STRING
    sess = TpuSession()
    df = sess.from_pydict({"d": [0.0001, 1e16, 1.5, None]},
                          schema=Schema((StructField("d", DOUBLE),)))
    q = df.select(F.col("d").cast(STRING).alias("s"))
    assert "host" in q.explain()
    assert [r[0] for r in q.collect()] == ["1.0E-4", "1.0E16", "1.5", None]


def _us(d: dt.datetime) -> int:
    return int((d - dt.datetime(1970, 1, 1)).total_seconds() * 1e6)


def test_dst_gap_uses_pre_transition_offset():
    # 2025-03-09 02:30 America/Los_Angeles does not exist; Java resolves
    # it with the offset before the transition → 10:30 UTC
    out = int(np.asarray(local_to_utc(
        np.array([_us(dt.datetime(2025, 3, 9, 2, 30))], np.int64),
        "America/Los_Angeles"))[0])
    assert dt.datetime(1970, 1, 1) + dt.timedelta(microseconds=out) == \
        dt.datetime(2025, 3, 9, 10, 30)


def test_dst_overlap_still_earlier_offset():
    out = int(np.asarray(local_to_utc(
        np.array([_us(dt.datetime(2025, 11, 2, 1, 30))], np.int64),
        "America/Los_Angeles"))[0])
    assert dt.datetime(1970, 1, 1) + dt.timedelta(microseconds=out) == \
        dt.datetime(2025, 11, 2, 8, 30)


def test_serialize_non_compacted_array_column():
    at = ArrayType(IntegerType())
    base = ArrayColumn.from_pylist(
        [[1, 2], [3], [4, 5, 6], [7], None, [8, 9]], at)
    off = np.asarray(base.offsets)
    n = 4
    sl_off = np.zeros(len(off), np.int32)
    sl_off[:n + 1] = off[2:2 + n + 1]
    sl_off[n + 1:] = sl_off[n]
    val = np.zeros(base.capacity, np.bool_)
    val[:n] = [True, True, False, True]
    sliced = ArrayColumn(base.child, jnp.asarray(sl_off),
                         jnp.asarray(val), at)
    assert int(sl_off[0]) != 0  # genuinely non-compacted
    sch = Schema([StructField("a", at)])
    rt = deserialize_batch(
        serialize_batch(ColumnarBatch([sliced], n, sch)), sch)
    assert rt.columns[0].to_pylist(n) == [[4, 5, 6], [7], None, [8, 9]]


def test_delta_checkpoint_struct_typed(tmp_path):
    d = str(tmp_path / "tbl")
    log = DeltaLog(d)
    sch = Schema([StructField("x", IntegerType())])
    log.commit([log.protocol_action(),
                log.metadata_action(sch, [], "tid-1")], 0)
    for v in range(1, 13):
        log.commit([{"add": {
            "path": f"f{v}.parquet", "partitionValues": {"p": str(v)},
            "size": 10, "dataChange": True, "modificationTime": 123,
            "stats": '{"numRecords": 1}'}}], v)
    import pyarrow.parquet as pq
    cp = log.last_checkpoint()
    assert cp == 10
    t = pq.read_table(
        os.path.join(d, "_delta_log", f"{cp:020d}.checkpoint.parquet"))
    # protocol-required struct columns, not the old JSON-blob layout
    assert {"protocol", "metaData", "add"} <= set(t.column_names)
    assert "action" not in t.column_names
    acts = list(log._read_checkpoint(cp))
    kinds = sorted({list(a)[0] for a in acts})
    assert kinds == ["add", "metaData", "protocol"]
    with open(os.path.join(d, "_delta_log", "_last_checkpoint")) as f:
        lc = json.load(f)
    assert lc["size"] == len(acts)
    # replay from checkpoint in a fresh log object
    snap = DeltaLog(d).snapshot()
    assert len(snap.files) == 12
    assert snap.files[0].partition_values == {"p": "1"}
