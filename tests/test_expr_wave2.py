"""Second expression wave: device bitwise/shifts; host-tier JSON, URL,
and string long tail routed through CPU fallback (reference families:
bitwise rules, GpuGetJsonObject/JSONUtils, GpuParseUrl/ParseURI,
GpuStringSplit/GpuSubstringIndex/GpuRegExpExtract/GpuRegExpReplace)."""

import numpy as np
import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.core import lit
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField


def _run1(sess, data, sch, expr):
    df = sess.from_pydict(data, sch)
    return [r[0] for r in df.select(expr.alias("out")).collect()]


# ---------------------------------------------------------------------------
# device bitwise / shifts
# ---------------------------------------------------------------------------

def test_bitwise_ops_match_python():
    from spark_rapids_tpu.expr.bitwise import (BitwiseAnd, BitwiseNot,
                                               BitwiseOr, BitwiseXor)
    sess = TpuSession()
    sch = Schema((StructField("a", LONG), StructField("b", LONG)))
    data = {"a": [0b1100, -7, None, 2**40], "b": [0b1010, 3, 5, -1]}
    for cls, op in ((BitwiseAnd, lambda a, b: a & b),
                    (BitwiseOr, lambda a, b: a | b),
                    (BitwiseXor, lambda a, b: a ^ b)):
        got = _run1(sess, data, sch, cls(col("a"), col("b")))
        expect = [None if a is None or b is None else op(a, b)
                  for a, b in zip(data["a"], data["b"])]
        assert got == expect, cls.__name__
    got = _run1(sess, data, sch, BitwiseNot(col("a")))
    assert got == [~a if a is not None else None for a in data["a"]]


def test_shifts_java_semantics():
    sess = TpuSession()
    sch = Schema((StructField("a", LONG), StructField("n", LONG)))
    data = {"a": [1, -8, 2**62, 5], "n": [3, 1, 65, 70]}
    got = _run1(sess, data, sch, F.shiftleft(col("a"), col("n")))
    # Java: distance masked to 63 for longs
    expect = []
    for a, n in zip(data["a"], data["n"]):
        v = (a << (n & 63)) & ((1 << 64) - 1)
        expect.append(v - (1 << 64) if v >= (1 << 63) else v)
    assert got == expect
    got = _run1(sess, data, sch, F.shiftright(col("a"), col("n")))
    assert got == [a >> (n & 63) for a, n in zip(data["a"], data["n"])]
    got = _run1(sess, data, sch,
                F.shiftrightunsigned(col("a"), col("n")))
    assert got == [(a & ((1 << 64) - 1)) >> (n & 63)
                   for a, n in zip(data["a"], data["n"])]


# ---------------------------------------------------------------------------
# host-tier JSON / URL
# ---------------------------------------------------------------------------

STR_SCH = Schema((StructField("s", STRING),))


@pytest.mark.slow  # ~7s; device json parity kept tier-1 in test_json_device (round-7 budget move)
def test_get_json_object():
    sess = TpuSession()
    data = {"s": ['{"a":{"b":[1,2,3]},"x":"y"}', '{"a":1}',
                  "not json", None]}
    q = sess.from_pydict(data, STR_SCH).select(
        F.get_json_object(col("s"), "$.a.b[1]").alias("o"))
    # literal wildcard-free paths run the device scanner since round 3
    assert "HostProjectExec" not in q._exec().tree_string()
    assert [r[0] for r in q.collect()] == ["2", None, None, None]
    # string scalar renders bare; object renders as JSON
    got = _run1(sess, data, STR_SCH,
                F.get_json_object(col("s"), "$.x"))
    assert got == ["y", None, None, None]
    got = _run1(sess, data, STR_SCH, F.get_json_object(col("s"), "$.a"))
    assert got == ['{"b":[1,2,3]}', "1", None, None]


def test_get_json_object_wildcard():
    sess = TpuSession()
    data = {"s": ['{"a":[{"b":1},{"b":2}]}']}
    got = _run1(sess, data, STR_SCH,
                F.get_json_object(col("s"), "$.a[*].b"))
    assert got == ["[1,2]"]


def test_parse_url_parts():
    sess = TpuSession()
    url = "https://user:pw@example.com:8443/p/a?x=1&y=2#frag"
    data = {"s": [url, None]}
    cases = {"HOST": "example.com", "PATH": "/p/a", "QUERY": "x=1&y=2",
             "REF": "frag", "PROTOCOL": "https",
             "FILE": "/p/a?x=1&y=2", "AUTHORITY": "user:pw@example.com:8443",
             "USERINFO": "user:pw"}
    for part, expect in cases.items():
        got = _run1(sess, data, STR_SCH, F.parse_url(col("s"), part))
        assert got == [expect, None], part
    got = _run1(sess, data, STR_SCH, F.parse_url(col("s"), "QUERY", "y"))
    assert got == ["2", None]


# ---------------------------------------------------------------------------
# host-tier string long tail
# ---------------------------------------------------------------------------

def test_split_and_substring_index():
    sess = TpuSession()
    data = {"s": ["a,b,,c,,", "nodelim", None]}
    # default limit -1 KEEPS trailing empties (Java split semantics)
    got = _run1(sess, data, STR_SCH, F.split(col("s"), ","))
    assert got == [["a", "b", "", "c", "", ""], ["nodelim"], None]
    # limit 0 strips them
    got = _run1(sess, data, STR_SCH, F.split(col("s"), ",", 0))
    assert got == [["a", "b", "", "c"], ["nodelim"], None]
    got = _run1(sess, data, STR_SCH,
                F.substring_index(col("s"), ",", 2))
    assert got == ["a,b", "nodelim", None]
    got = _run1(sess, data, STR_SCH,
                F.substring_index(col("s"), ",", -2))
    assert got == [",", "nodelim", None]


def test_regexp_extract_and_replace():
    sess = TpuSession()
    data = {"s": ["ab123cd", "xyz", None]}
    got = _run1(sess, data, STR_SCH,
                F.regexp_extract(col("s"), r"([a-z]+)(\d+)", 2))
    assert got == ["123", "", None]
    got = _run1(sess, data, STR_SCH,
                F.regexp_replace(col("s"), r"(\d+)", r"<$1>"))
    assert got == ["ab<123>cd", "xyz", None]


def test_find_in_set_format_number_levenshtein():
    sess = TpuSession()
    sch2 = Schema((StructField("a", STRING), StructField("b", STRING)))
    data = {"a": ["b", "x", "a,b", None],
            "b": ["a,b,c", "a,b,c", "a,b,c", "a"]}
    df = sess.from_pydict(data, sch2)
    got = [r[0] for r in df.select(
        F.find_in_set(col("a"), col("b")).alias("o")).collect()]
    assert got == [2, 0, 0, None]

    num_sch = Schema((StructField("v", LONG),))
    got = _run1(sess, {"v": [1234567, -42, None]}, num_sch,
                F.format_number(col("v"), 2))
    assert got == ["1,234,567.00", "-42.00", None]

    got = [r[0] for r in df.select(
        F.levenshtein(col("a"), col("b")).alias("o")).collect()]
    assert got == [4, 5, 2, None]  # lev("a,b","a,b,c") = 2


def test_bad_regex_pattern_fails_plan_not_midquery():
    sess = TpuSession({"spark.rapids.sql.cpuFallback.enabled": "false"})
    df = sess.from_pydict({"s": ["x"]}, STR_SCH)
    from spark_rapids_tpu.plan.overrides import PlanNotSupported
    with pytest.raises(PlanNotSupported):
        df.select(F.regexp_extract(col("s"), r"(", 1).alias("o"))._exec()
    # even with fallback on: unparseable pattern cannot run anywhere
    relaxed = TpuSession()
    df2 = relaxed.from_pydict({"s": ["x"]}, STR_SCH)
    with pytest.raises(PlanNotSupported):
        df2.select(F.regexp_extract(col("s"), r"(", 1).alias("o"))._exec()


def test_split_limit_one_and_dollar_digit_replacement():
    """Java semantics edge cases: limit=1 means NO split; '$1' followed
    by a digit in the replacement stays group-1 + literal digit."""
    sess = TpuSession()
    got = _run1(sess, {"s": ["a,b,c"]}, STR_SCH, F.split(col("s"), ",", 1))
    assert got == [["a,b,c"]]
    got = _run1(sess, {"s": ["x42y"]}, STR_SCH,
                F.regexp_replace(col("s"), r"(\d+)", "<$10>"))
    assert got == ["x<420>y"]


def test_parse_url_part_is_case_sensitive():
    sess = TpuSession()
    got = _run1(sess, {"s": ["https://e.com/p"]}, STR_SCH,
                F.parse_url(col("s"), "host"))
    assert got == [None]  # Spark: unknown (lowercase) part -> NULL


def test_base64_hex_encode_family():
    sess = TpuSession()
    data = {"s": ["hello", "", None]}
    assert _run1(sess, data, STR_SCH, F.base64(col("s"))) == \
        ["aGVsbG8=", "", None]
    assert _run1(sess, data, STR_SCH,
                 F.decode(F.unbase64(F.base64(col("s"))), "UTF-8")) == \
        ["hello", "", None]
    assert _run1(sess, data, STR_SCH, F.hex(col("s"))) == \
        ["68656C6C6F", "", None]
    num_sch = Schema((StructField("v", LONG),))
    assert _run1(sess, {"v": [255, -1, None]}, num_sch,
                 F.hex(col("v"))) == ["FF", "FFFFFFFFFFFFFFFF", None]
    assert _run1(sess, {"s": ["4A4B", "XYZ", None]}, STR_SCH,
                 F.decode(F.unhex(col("s")), "UTF-8")) == \
        ["JK", None, None]


def test_base64_hex_spark_edge_semantics():
    """Review-driven edge cases: unpadded base64 decodes leniently,
    whitespace in hex is rejected (NULL), unmappable chars encode as
    '?', bad bytes decode as U+FFFD, unknown charsets fail loudly."""
    sess = TpuSession()
    assert _run1(sess, {"s": ["YWJj", "YWJjZA", None]}, STR_SCH,
                 F.decode(F.unbase64(col("s")), "UTF-8")) == \
        ["abc", "abcd", None]                    # no-padding accepted
    assert _run1(sess, {"s": ["4A 4B"]}, STR_SCH,
                 F.unhex(col("s"))) == [None]    # whitespace -> NULL
    assert _run1(sess, {"s": ["héllo"]}, STR_SCH,
                 F.decode(F.encode(col("s"), "US-ASCII"), "US-ASCII")) \
        == ["h?llo"]                             # '?' substitution
    with pytest.raises(ValueError, match="charset"):
        F.encode(col("s"), "KOI8-R")             # analysis-time error
