"""Device base64/hex codecs vs Python reference implementations."""
import base64
import binascii
import random

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops.codecs import (base64_decode, base64_encode,
                                         hex_decode, hex_encode,
                                         hex_encode_long)
from spark_rapids_tpu.types import LONG, STRING, Schema, StructField


def test_base64_roundtrip_and_malformed():
    rng = random.Random(4)
    rows = ["", "a", "ab", "abc", "abcd", "hello world", None] + [
        "".join(chr(rng.randint(32, 126))
                for _ in range(rng.randint(0, 24))) for _ in range(30)]
    sc = StringColumn.from_pylist(rows)
    n = len(rows)
    exp = [None if r is None else base64.b64encode(r.encode()).decode()
           for r in rows]
    assert base64_encode(sc).to_pylist(n) == exp
    encs = exp + ["!!!!", "AB", "A===", "QQ==", "=AAA"]
    got = base64_decode(StringColumn.from_pylist(encs)).to_pylist(
        len(encs))
    ref = []
    for e in encs:
        if e is None:
            ref.append(None)
            continue
        try:
            # lenient tail like Spark's UnBase64 (and the host tier): a
            # trailing group of 2-3 data chars decodes without padding;
            # 1 leftover char is malformed
            stripped = e.rstrip("=")
            if len(stripped) % 4 == 1:
                raise binascii.Error("len")
            pad = "=" * (-len(stripped) % 4)
            ref.append(base64.b64decode(stripped + pad, validate=True))
        except binascii.Error:
            ref.append(None)
    assert got == ref


def test_hex_string_long_and_unhex():
    rows = ["", "a", "hi!", None]
    sc = StringColumn.from_pylist(rows)
    assert hex_encode(sc).to_pylist(4) == [
        None if r is None else r.encode().hex().upper() for r in rows]
    vals = [0, 1, 255, -1, 2 ** 62, None, 17]
    lc = Column.from_pylist(vals, LONG)
    assert hex_encode_long(lc).to_pylist(len(vals)) == [
        None if v is None else format(v & ((1 << 64) - 1), "X")
        for v in vals]
    hexes = ["", "A", "FF", "0aF", "xyz", None, "1234AB"]
    got = hex_decode(StringColumn.from_pylist(hexes)).to_pylist(
        len(hexes))

    def h(e):
        if e is None:
            return None
        if any(c not in "0123456789abcdefABCDEF" for c in e):
            return None
        return bytes.fromhex("0" + e if len(e) % 2 else e)

    assert got == [h(e) for e in hexes]


def test_planner_routes_codecs_to_device():
    sess = TpuSession()
    df = sess.from_pydict(
        {"s": ["hi", "", None], "n": [255, -1, 0]},
        schema=Schema((StructField("s", STRING), StructField("n", LONG))))
    q = df.select(F.base64(F.col("s")).alias("b"),
                  F.unbase64(F.base64(F.col("s"))).alias("rt"),
                  F.hex(F.col("n")).alias("h"),
                  F.unhex(F.hex(F.col("s"))).alias("hrt"))
    assert "host" not in q.explain()
    out = q.collect()
    assert out[0] == ("aGk=", b"hi", "FF", b"hi")
    assert out[1] == ("", b"", "FFFFFFFFFFFFFFFF", b"")
    assert out[2] == (None, None, "0", None)


def test_base64_many_tiny_rows_capacity():
    # 300 one-byte rows expand 4x: the output bucket must hold them all
    import base64 as b64
    rows = [chr(65 + (i % 26)) for i in range(300)]
    got = base64_encode(StringColumn.from_pylist(rows)).to_pylist(300)
    assert got == [b64.b64encode(r.encode()).decode() for r in rows]
