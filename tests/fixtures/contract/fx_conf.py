"""Contract-analyzer fixture: conf-provenance FIRES on the declared
producer entry (`writer_loop`), including through a module-local call,
and NOT on functions outside the entry's reach."""

from spark_rapids_tpu.config import active_conf


def writer_loop():
    _helper()


def _helper():
    return active_conf()  # conf-provenance: reachable from writer_loop


def consumer_side():
    return active_conf()  # NOT flagged: not reachable from the entry
