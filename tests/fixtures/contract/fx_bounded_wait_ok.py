"""Contract-analyzer fixture (never imported): the same unbounded
rendezvous sites as fx_bounded_wait.py, each silenced by a justified
bounded-wait suppression in the standard grammar."""


def worker_loop(q):
    while True:
        # contract: ok bounded-wait — fixture: daemon feed queue,
        # parked-on-empty is its idle state; a sentinel unparks it
        job = q.get()
        if job is None:
            return


def drain(fut):
    # contract: ok bounded-wait — fixture: producer owns the deadline
    return fut.result()


def rendezvous(ev):
    ev.wait()  # contract: ok bounded-wait — fixture: signaled in finally
