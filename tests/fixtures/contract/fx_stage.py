"""Contract-analyzer fixture: the stage-governance rule FIRES here —
per-batch governance hooks inside traced stage bodies handed to the
dispatch chokepoint (ISSUE 14: they run once per TRACE, not per batch,
so cancellation latency / fault coverage / metric totals all lie)."""

from functools import partial

from spark_rapids_tpu import faults
from spark_rapids_tpu.obs.dispatch import instrument


def make_site(self, qctx):
    def traced_body(batch):
        qctx.tick()                      # stage-governance
        faults.check("device.dispatch")  # stage-governance
        return batch
    return instrument(traced_body, label="fx.stage")


class Op:
    def _kernel(self, batch):
        with self.metrics["opTime"].ns_timer():  # stage-governance
            return batch

    def build(self):
        self._jit = self._site(self._kernel, label="Op.kernel")


@partial(instrument, label="fx.decorated")
def decorated_body(batch, bus):
    bus.emit("op_batch", rows=1)  # stage-governance
    return batch


def helper_hook(tracker, batch):
    # flagged via the one-hop walk from hooked_site below
    with tracker.observe((batch,)):
        return batch


def hooked_site():
    return instrument(lambda b: helper_hook(None, b), label="fx.hop")
