"""Contract-analyzer fixture: thread-adopt FIRES on the bare spawn and
stays silent on the adopting one."""

import threading


def _worker():
    pass  # no capture/adopt helper anywhere in reach


def _adopting_worker():
    from spark_rapids_tpu.obs.events import adopt_query_id
    adopt_query_id(None)


def spawn_bad():
    t = threading.Thread(target=_worker)  # thread-adopt fires
    t.start()


def spawn_good():
    t = threading.Thread(target=_adopting_worker)  # clean
    t.start()


def submit_bad(pool):
    return pool.submit(_worker)  # thread-adopt fires
