"""Contract-analyzer fixture: the fx_conf.py read, suppressed."""

from spark_rapids_tpu.config import active_conf


def writer_loop():
    _helper()


def _helper():
    # contract: ok conf-provenance — fixture: value is invariant across
    # queries in this scenario
    return active_conf()
