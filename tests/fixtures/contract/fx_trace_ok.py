"""Contract-analyzer fixture: the fx_trace.py violations, suppressed."""

import jax
import jax.numpy as jnp
import numpy as np

# contract: ok trace-module-jnp — fixture: module imported only at top
# level, never inside a trace
_BAD = jnp.uint32(7)


# contract: ok dispatch-ledger — fixture: exercising the trace rules,
# not the ledger chokepoint
@jax.jit
def traced(x):
    # contract: ok trace-host-sync — fixture: x is statically concrete
    return np.asarray(x)


def add_kernel(x_ref, o_ref):
    # contract: ok trace-host-sync — fixture: demonstrates suppression
    o_ref[...] = x_ref[...].item()
