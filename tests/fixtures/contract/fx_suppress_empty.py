"""Contract-analyzer fixture: a justification-less suppression and a
typo'd rule id — both must surface as `suppression-empty` findings (the
empty one still silences its base finding, so CI fails on the meta
finding, not on noise)."""

import threading
import time


class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self):
        with self._lock:
            # contract: ok lock-blocking-call —
            time.sleep(0.1)

    def typo(self):
        with self._lock:
            # contract: ok lock-blocking-cal — the rule id is misspelled
            time.sleep(0.1)
