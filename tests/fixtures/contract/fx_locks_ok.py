"""Contract-analyzer fixture: the fx_locks.py violations with justified
suppressions — the analyzer must report ZERO findings here and count
the suppressions."""

import threading
import time


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._outer = threading.Lock()

    def bad_blocking(self):
        with self._lock:
            # contract: ok lock-blocking-call — fixture: bounded 100ms
            # sleep, lock is test-local
            time.sleep(0.1)

    def bad_blocking_via_call(self):
        with self._lock:
            self._do_io()

    def _do_io(self):
        # contract: ok lock-blocking-call — fixture: tmpfile probe only
        open("/tmp/fx", "rb")

    def bad_reacquire(self):
        with self._lock:
            self._helper()

    def _helper(self):
        # contract: ok lock-reacquire — fixture: demonstrates suppression
        with self._lock:
            pass

    def bad_order(self):
        with self._lock:
            # contract: ok lock-order — fixture: demonstrates suppression
            with self._outer:
                pass
