"""Contract-analyzer fixture: the fx_accounting.py shapes, suppressed."""


class _Budget:
    def reserve(self, n):
        pass

    def release(self, n):
        pass


budget = _Budget()


def _work(n):
    pass


def one_sided(n):
    # contract: ok accounting-symmetry — fixture: ownership transfers to
    # the caller's handle
    budget.reserve(n)


def exception_edge(n):
    # contract: ok accounting-symmetry — fixture: _work cannot raise here
    budget.reserve(n)
    _work(n)
    budget.release(n)
