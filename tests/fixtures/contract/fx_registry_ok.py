"""Contract-analyzer fixture: the fx_registry.py literals, suppressed."""

# contract: ok conf-key-registered — fixture: deliberately fake key
BAD_KEY = "spark.rapids.tpu.fixture.not.registered"


def report(emit):
    # contract: ok event-kind-registered — fixture: deliberately fake kind
    emit("fixture_unregistered_kind", x=1)
