"""Contract-analyzer fixture: both registry-drift rules FIRE here."""

BAD_KEY = "spark.rapids.tpu.fixture.not.registered"  # conf-key-registered


def report(emit):
    emit("fixture_unregistered_kind", x=1)  # event-kind-registered
    emit("query_start")  # registered kind: NOT flagged
