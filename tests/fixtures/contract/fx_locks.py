"""Contract-analyzer fixture (never imported): every lock-discipline
rule FIRES here. tests/test_contract_check.py registers Engine._lock /
Engine._outer as fixture locks with declared order [fx-outer, fx-lock]
and asserts one finding per bad_* method."""

import threading
import time


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._outer = threading.Lock()

    def bad_blocking(self):
        with self._lock:
            time.sleep(0.1)  # lock-blocking-call: sleep under fx-lock

    def bad_blocking_via_call(self):
        with self._lock:
            self._do_io()  # the module-local walk follows this

    def _do_io(self):
        open("/tmp/fx", "rb")  # lock-blocking-call via bad_blocking_via_call

    def bad_reacquire(self):
        with self._lock:
            self._helper()

    def _helper(self):
        with self._lock:  # lock-reacquire (non-reentrant, via bad_reacquire)
            pass

    def bad_order(self):
        with self._lock:
            with self._outer:  # lock-order: fx-outer must be taken first
                pass
