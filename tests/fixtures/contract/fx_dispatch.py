"""Contract-analyzer fixture: the dispatch-ledger rule FIRES here —
bare jit/pallas sites the observability plane cannot see (ISSUE 13)."""

import jax
from jax.experimental import pallas as pl


def bare_jit(fn):
    return jax.jit(fn)  # dispatch-ledger


def bare_jit_decorator_arg(fn, partial):
    return partial(jax.jit, static_argnums=(1,))(fn)  # dispatch-ledger


def bare_pallas(kernel, out_shape):
    return pl.pallas_call(kernel, out_shape=out_shape)  # dispatch-ledger
