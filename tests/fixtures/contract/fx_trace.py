"""Contract-analyzer fixture: both trace-purity rules FIRE here."""

import jax
import jax.numpy as jnp
import numpy as np

_BAD = jnp.uint32(7)  # trace-module-jnp: jax array built at import
_OK_REF = jnp.sqrt    # bare attribute reference: NOT flagged
_OK_NP = np.uint32(7)  # numpy scalar: NOT flagged


# contract: ok dispatch-ledger — fixture: exercising the trace rules,
# not the ledger chokepoint
@jax.jit
def traced(x):
    return np.asarray(x)  # trace-host-sync: materializes a tracer


def add_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...].item()  # trace-host-sync in a Pallas body


def untraced(x):
    return np.asarray(x)  # host helper: NOT flagged
