"""Contract-analyzer fixture twin: dispatch-ledger stays SILENT —
chokepoint-routed programs are clean, accepted bare sites carry a
justified suppression."""

import jax
from jax.experimental import pallas as pl

from spark_rapids_tpu.obs.dispatch import instrument


def routed(fn):
    # the chokepoint itself: not flagged
    return instrument(fn, label="fixture.routed")


def inline_pallas(kernel, out_shape):
    # contract: ok dispatch-ledger — fixture: traced inline into an
    # instrumented enclosing program (not a separate device dispatch)
    return pl.pallas_call(kernel, out_shape=out_shape)


def accepted_bare(fn):
    # contract: ok dispatch-ledger — fixture: measured elsewhere
    return jax.jit(fn)
