"""Contract-analyzer fixture: accounting-symmetry FIRES (one-sided and
exception-edge shapes), stays silent on the guarded and the
registry-escrowed shapes."""


class _Budget:
    def reserve(self, n):
        pass

    def release(self, n):
        pass


budget = _Budget()


def _work(n):
    pass


def one_sided(n):
    budget.reserve(n)  # accounting-symmetry: no release anywhere


def exception_edge(n):
    budget.reserve(n)
    _work(n)  # may raise: the release below is skipped on unwind
    budget.release(n)


def guarded(n):
    budget.reserve(n)
    try:
        _work(n)
    finally:
        budget.release(n)  # close on every edge: NOT flagged


def escrowed(n):
    budget.reserve(n)  # registry escrow declares the transfer: silent
