"""Contract-analyzer fixture twin: stage-governance stays SILENT —
pure traced bodies are clean, harness-side hooks live outside the
traced function, and an accepted in-body hook carries a justified
suppression."""

from spark_rapids_tpu import faults
from spark_rapids_tpu.obs.dispatch import instrument


def pure_site():
    # pure dataflow: nothing to flag
    return instrument(lambda b: b * 2, label="fx.pure")


class Op:
    def _kernel(self, batch):
        return batch  # pure

    def build(self):
        self._jit = self._site(self._kernel, label="Op.kernel")

    def drive(self, batch):
        # harness-side governance (the correct shape): hooks bind
        # AROUND the program call, never inside the traced body
        faults.check("device.dispatch", key="stage:1")
        with self.batch_harness(gather_shape=(batch,)):
            return self._jit(batch)


def accepted_site(qctx):
    def body(batch):
        # contract: ok stage-governance — fixture: trace-time consult
        # deliberately baked per compiled shape, documented
        qctx.tick()
        return batch
    return instrument(body, label="fx.accepted")
