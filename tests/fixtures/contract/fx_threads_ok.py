"""Contract-analyzer fixture: the fx_threads.py spawns, suppressed."""

import threading


def _worker():
    pass


def spawn_bad():
    # contract: ok thread-adopt — fixture: daemon carries no per-query
    # context by design
    t = threading.Thread(target=_worker)
    t.start()


def submit_bad(pool):
    # contract: ok thread-adopt — fixture: pure function of its args
    return pool.submit(_worker)
