"""Contract-analyzer fixture (never imported): the `bounded-wait` rule
FIRES on each provably unbounded rendezvous here, and stays quiet on
every bounded / non-blocking form. Waitables arrive as arguments so no
other rule (thread-adopt, lock-discipline) has anything to say."""

import time


def parked_on_event(ev):
    ev.wait()  # bounded-wait: no timeout


def parked_on_queue(q):
    return q.get()  # bounded-wait: queue get with no timeout


def parked_on_future(fut):
    return fut.result()  # bounded-wait: result with no timeout


def bounded_forms(ev, fut, d, q):
    ev.wait(5)                  # positional bound — clean
    fut.result(timeout=2)       # keyword bound — clean
    d.get("key")                # dict lookup, positional args — clean
    q.get(timeout=0.1)          # bounded queue get — clean
    time.sleep(0.01)            # duration IS the positional — clean


def splat_forms(ev, args, kwargs):
    ev.wait(*args)      # bound may ride the splat — unprovable, clean
    ev.wait(**kwargs)   # same for keyword splat
