"""Datagen-driven fuzz suite: random data through every engine tier
(speculative/exact/fused/unfused/distributed) must agree, and core
pipelines must match independent Python oracles (reference analog:
integration_tests data_gen.py + asserts.py cross-engine runs).

Marked `slow`: fuzz sweeps are multi-minute on a single-core host and
belong to the nightly tier; the 870s tier-1 gate excludes them
(-m 'not slow', ROADMAP)."""

import collections
import math

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.testing import (
    BooleanGen, DateGen, DecimalGen, DoubleGen, IntegerGen, LongGen,
    SetValuesGen, StringGen, assert_consistent_across_configs,
    assert_rows_equal, gen_df, gen_pydict,
)
from spark_rapids_tpu.types import LONG, STRING


def test_datagen_reproducible():
    gens = [("a", LongGen()), ("s", StringGen()), ("d", DoubleGen())]
    d1, sch1 = gen_pydict(gens, 100, seed=7)
    d2, sch2 = gen_pydict(gens, 100, seed=7)
    assert d1 == d2 or (str(d1) == str(d2))  # NaN-safe compare via repr
    assert sch1 == sch2
    d3, _ = gen_pydict(gens, 100, seed=8)
    assert str(d3) != str(d1)


def test_datagen_specials_present():
    data, _ = gen_pydict([("a", IntegerGen())], 2000, seed=1)
    vals = [v for v in data["a"] if v is not None]
    assert (1 << 31) - 1 in vals or -(1 << 31) in vals
    assert any(v is None for v in data["a"])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_groupby_sum_count(seed):
    gens = [("k", SetValuesGen(LONG, [0, 1, 2, 3, 4, None])),
            ("v", LongGen(min_val=-1 << 40, max_val=1 << 40))]
    data, sch = gen_pydict(gens, 300, seed=seed)

    acc = collections.defaultdict(lambda: [0, 0])
    for k, v in zip(data["k"], data["v"]):
        if v is not None:
            acc[k][0] += v
            acc[k][1] += 1
        else:
            acc[k]  # group still exists
    oracle = [(k, (s if c else None), c) for k, (s, c) in acc.items()]

    def build(sess):
        df = sess.from_pydict(data, sch, batch_rows=64)
        return df.group_by("k").agg((F.sum("v"), "s"), (F.count("v"), "c"))

    assert_consistent_across_configs(build, expected=oracle)


@pytest.mark.parametrize("seed", [3, 4])
def test_fuzz_groupby_string_keys_minmax(seed):
    gens = [("k", SetValuesGen(STRING, ["a", "bb", "ccc", None])),
            ("v", DoubleGen(no_nans=True))]
    data, sch = gen_pydict(gens, 200, seed=seed)

    acc = collections.defaultdict(list)
    for k, v in zip(data["k"], data["v"]):
        if v is not None:
            acc[k].append(v)
        else:
            acc[k]
    oracle = [(k, (min(vs) if vs else None), (max(vs) if vs else None))
              for k, vs in acc.items()]

    def build(sess):
        df = sess.from_pydict(data, sch, batch_rows=50)
        return df.group_by("k").agg((F.min("v"), "mn"), (F.max("v"), "mx"))

    assert_consistent_across_configs(build, expected=oracle)


@pytest.mark.parametrize("seed", [5, 6])
def test_fuzz_filter_project(seed):
    gens = [("a", IntegerGen()), ("b", LongGen()),
            ("s", StringGen(max_length=12))]
    data, sch = gen_pydict(gens, 400, seed=seed)

    def wrap64(x):  # Spark non-ANSI long arithmetic wraps two's-complement
        return (x + (1 << 63)) % (1 << 64) - (1 << 63)

    oracle = []
    for a, b, s in zip(data["a"], data["b"], data["s"]):
        if a is not None and a > 0:
            oracle.append((a, None if b is None else wrap64(b + 1), s))

    def build(sess):
        df = sess.from_pydict(data, sch, batch_rows=128)
        return df.filter(col("a") > 0).select(
            col("a"), (col("b") + 1).alias("b1"), col("s"))

    assert_consistent_across_configs(build, expected=oracle)


@pytest.mark.parametrize("seed", [7, 8])
def test_fuzz_join(seed):
    lgens = [("k", SetValuesGen(LONG, list(range(20)) + [None])),
             ("lv", LongGen())]
    rgens = [("k", SetValuesGen(LONG, list(range(10, 30)) + [None])),
             ("rv", StringGen(max_length=30))]
    ldata, lsch = gen_pydict(lgens, 150, seed=seed)
    rdata, rsch = gen_pydict(rgens, 100, seed=seed + 100)

    rmap = collections.defaultdict(list)
    for k, v in zip(rdata["k"], rdata["rv"]):
        if k is not None:
            rmap[k].append(v)
    oracle = []
    for k, lv in zip(ldata["k"], ldata["lv"]):
        matches = rmap.get(k, []) if k is not None else []
        if matches:
            oracle.extend((k, lv, rv) for rv in matches)
        else:
            oracle.append((k, lv, None))

    def build(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=64)
        r = sess.from_pydict(rdata, rsch, batch_rows=64)
        return l.join(r, on="k", how="left_outer")

    assert_consistent_across_configs(build, expected=oracle)


def test_fuzz_sort_limit():
    gens = [("a", IntegerGen()), ("s", StringGen(max_length=8))]
    data, sch = gen_pydict(gens, 300, seed=9)

    # Spark ascending default is NULLS FIRST
    key = [(a is not None, a if a is not None else 0, s is not None, s or "")
           for a, s in zip(data["a"], data["s"])]
    order = sorted(range(300), key=lambda i: key[i])
    oracle = [(data["a"][i], data["s"][i]) for i in order[:25]]

    def build(sess):
        df = sess.from_pydict(data, sch, batch_rows=100)
        return df.sort("a", "s").limit(25)

    got = assert_consistent_across_configs(build)
    assert_rows_equal(got, oracle, ordered=True)


def test_fuzz_boolean_date_decimal_roundtrip():
    """Logical-value ingestion: bool/date/decimal generators feed the
    engine and round-trip through a projection."""
    from spark_rapids_tpu.api.session import TpuSession
    gens = [("b", BooleanGen()), ("d", DateGen()),
            ("x", DecimalGen(precision=10, scale=2))]
    data, sch = gen_pydict(gens, 100, seed=10)
    sess = TpuSession()
    df = sess.from_pydict(data, sch)
    out = df.select("b", "d", "x").collect()
    assert len(out) == 100
    import datetime
    epoch = datetime.date(1970, 1, 1)
    for (b, d, x), (eb, ed, ex) in zip(out, zip(*data.values())):
        assert b == eb
        assert d == (None if ed is None else (ed - epoch).days)
        assert x == (None if ex is None else int(ex.scaleb(2)))


def test_fuzz_double_specials_groupby():
    """NaN/inf/-0.0 group keys: Spark groups NaN together and 0.0==-0.0."""
    data = {"k": [float("nan"), float("nan"), 0.0, -0.0, 1.0, None],
            "v": [1, 2, 3, 4, 5, 6]}
    from spark_rapids_tpu.types import DOUBLE, Schema, StructField
    sch = Schema((StructField("k", DOUBLE), StructField("v", LONG)))

    def build(sess):
        return sess.from_pydict(data, sch).group_by("k").agg(
            (F.sum("v"), "s"))

    got = assert_consistent_across_configs(build)
    as_map = {("nan" if (k is not None and math.isnan(k)) else k): s
              for k, s in got}
    assert as_map == {"nan": 3, 0.0: 7, 1.0: 5, None: 6}
