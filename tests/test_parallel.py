"""Distributed-path tests on the virtual 8-device CPU mesh (the driver's
dryrun environment; reference analog NUM_LOCAL_EXECS pseudo-cluster)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.parallel.distributed import (
    make_distributed_groupby, stack_batches, unstack_batches,
)
from spark_rapids_tpu.parallel.exchange import (
    exchange_columns, partition_ids, partition_slots,
)
from spark_rapids_tpu.parallel.mesh import DATA_AXIS, device_mesh
from spark_rapids_tpu.types import (
    DOUBLE, INT, LONG, STRING, Schema, StructField,
)

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def test_partition_slots_roundtrip():
    # every active row must land in exactly one slot of its partition
    from spark_rapids_tpu.columnar.column import Column
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 1000, 100)
    col = Column.from_numpy(vals, LONG)
    pid = partition_ids([col], jnp.int32(100), col.capacity, 4)
    send_idx = partition_slots(pid, jnp.int32(100), col.capacity, 4,
                               col.capacity)
    si = np.asarray(send_idx)
    placed = si[si >= 0]
    assert sorted(placed.tolist()) == list(range(100))
    # slot partition must match row partition
    pids = np.asarray(pid)
    slot_cap = col.capacity
    for slot, row in enumerate(si):
        if row >= 0:
            assert pids[row] == slot // slot_cap


@needs_8
@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_distributed_groupby_ints_and_strings():
    mesh = device_mesh(8)
    rng = np.random.default_rng(7)
    sch = Schema((StructField("k", STRING), StructField("v", LONG)))
    keys = ["alpha", "bravo", "charlie", "delta", None]
    batches, oracle = [], {}
    for d in range(8):
        ks = [keys[i] for i in rng.integers(0, len(keys), 64)]
        vs = rng.integers(0, 50, 64).tolist()
        for k, v in zip(ks, vs):
            oracle[k] = oracle.get(k, 0) + v
        batches.append(ColumnarBatch.from_pydict({"k": ks, "v": vs}, sch))
    out_sch = Schema((StructField("k", STRING), StructField("s", LONG)))
    step = make_distributed_groupby(
        mesh, key_count=1, update_inputs=[("sum", 1)], merge_ops=["sum"],
        buffer_types=[LONG], out_schema=out_sch)
    out = step(stack_batches(batches))
    got = {}
    for shard in unstack_batches(out, 8):
        for k, s in shard.to_pylist():
            assert k not in got, f"group {k!r} split across shards"
            got[k] = s
    assert got == oracle


@needs_8
def test_exchange_preserves_all_rows():
    """Every row emitted exactly once, landing on pmod(hash(key), n)."""
    from jax.sharding import PartitionSpec as P
    from spark_rapids_tpu.ops.hashing import murmur3_batch, pmod

    mesh = device_mesh(8)
    rng = np.random.default_rng(1)
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    batches = []
    all_rows = []
    for d in range(8):
        ks = rng.integers(0, 100, 128).tolist()
        vs = (rng.integers(0, 1000, 128) * 8 + d).tolist()  # tag origin
        all_rows += list(zip(ks, vs))
        batches.append(ColumnarBatch.from_pydict({"k": ks, "v": vs}, sch))
    stacked = stack_batches(batches)

    def spmd(stacked_b):
        local = jax.tree_util.tree_map(lambda x: x[0], stacked_b)
        cols, n = exchange_columns(list(local.columns), [0], local.num_rows,
                                   local.capacity, DATA_AXIS, 8)
        out = ColumnarBatch(cols, n, sch)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    from spark_rapids_tpu.parallel.mesh import shard_map_compat
    step = jax.jit(shard_map_compat(spmd, mesh=mesh, in_specs=P(DATA_AXIS),
                                    out_specs=P(DATA_AXIS)))
    out = step(stacked)
    received = []
    for i, shard in enumerate(unstack_batches(out, 8)):
        rows = shard.to_pylist()
        received += rows
        # rows must be on the right partition
        for k, v in rows:
            kcol = ColumnarBatch.from_pydict({"k": [k], "v": [0]}, sch)
            h = murmur3_batch([kcol.columns[0]], seed=42)
            expect_p = int(np.asarray(pmod(h, 8))[0])
            assert expect_p == i, (k, expect_p, i)
    assert sorted(received) == sorted(all_rows)


@needs_8
@pytest.mark.slow  # minute-scale on a single-core host; nightly tier
def test_distributed_groupby_long_string_keys():
    """Review regression: keys longer than the default 64-byte exchange
    width must group exactly when string_width is sized to the data."""
    from spark_rapids_tpu.parallel.distributed import required_string_width
    mesh = device_mesh(8)
    base = "x" * 64
    keys = [base + "AAAAAA", base + "BBBBBB"]
    sch = Schema((StructField("k", STRING), StructField("v", LONG)))
    batches, oracle = [], {}
    rng = np.random.default_rng(5)
    for d in range(8):
        ks = [keys[i] for i in rng.integers(0, 2, 32)]
        vs = rng.integers(0, 9, 32).tolist()
        for k, v in zip(ks, vs):
            oracle[k] = oracle.get(k, 0) + v
        batches.append(ColumnarBatch.from_pydict({"k": ks, "v": vs}, sch))
    width = required_string_width(batches)
    assert width >= 72
    out_sch = Schema((StructField("k", STRING), StructField("s", LONG)))
    step = make_distributed_groupby(
        mesh, key_count=1, update_inputs=[("sum", 1)], merge_ops=["sum"],
        buffer_types=[LONG], out_schema=out_sch, string_width=width)
    out = step(stack_batches(batches))
    got = {}
    for shard in unstack_batches(out, 8):
        for k, sm in shard.to_pylist():
            assert k not in got
            got[k] = sm
    assert got == oracle
