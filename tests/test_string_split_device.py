"""Device split/substring_index/find_in_set vs host-tier semantics."""
import random
import re

import pytest

from spark_rapids_tpu.columnar.column import StringColumn
from spark_rapids_tpu.ops.string_split import (find_in_set,
                                               split_literal,
                                               substring_index)


def host_fis(needle, s):
    if needle is None or s is None:
        return None
    if "," in needle:
        return 0
    items = s.split(",")
    return items.index(needle) + 1 if needle in items else 0


def host_ssi(s, d, c):
    if s is None:
        return None
    if not d or c == 0:
        return ""
    parts = s.split(d)
    if c > 0:
        return d.join(parts[:c]) if len(parts) > c else s
    return d.join(parts[c:]) if len(parts) > -c else s


def host_split(s, d, limit):
    if s is None:
        return None
    if limit == 1:
        return [s]
    parts = re.split(re.escape(d), s,
                     maxsplit=limit - 1 if limit > 0 else 0)
    if limit == 0:
        while parts and parts[-1] == "":
            parts.pop()
    return parts


def test_find_in_set_battery():
    needles = ["b", "", "a", "x,y", "ab", None, "c", "", "a", "ég"]
    sets_ = ["a,b,c", "a,,b", "", "x,y", "ab", "a", None, "a,", "a,a",
             "x,ég,z"]
    got = find_in_set(StringColumn.from_pylist(needles),
                      StringColumn.from_pylist(sets_)).to_pylist(
        len(needles))
    assert got == [host_fis(n, s) for n, s in zip(needles, sets_)]


@pytest.mark.parametrize("d,c", [
    (".", 2), (".", 1), (".", -2), (".", -1), (".", 3), (".", -5),
    (".", 0), ("aa", 1), ("aa", -1), ("", 2),
])
def test_substring_index_battery(d, c):
    rows = ["www.apache.org", "a.b", "abc", "", "a..b", None, "aaaa",
            ".x.", "aaaa.aaaa"]
    got = substring_index(StringColumn.from_pylist(rows), d.encode(),
                          c).to_pylist(len(rows))
    assert got == [host_ssi(s, d, c) for s in rows]


@pytest.mark.parametrize("d,lim", [
    (",", -1), (",", 0), (",", 2), (",", 1), (",", 4), ("a", -1),
    ("a", 0), ("ab", -1),
])
def test_split_battery(d, lim):
    rows = ["a,b,c", "a,,", ",,", "", "abc", None, ",a", "aa",
            "a,b,c,d,e", "abab"]
    got = split_literal(StringColumn.from_pylist(rows), d.encode(),
                        lim).to_pylist(len(rows))
    assert got == [host_split(s, d, lim) for s in rows]


def test_fuzz_differential():
    rng = random.Random(11)
    alphabet = "ab,.x "
    rows = [None if rng.random() < 0.1 else
            "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 12)))
            for _ in range(80)]
    col = StringColumn.from_pylist(rows)
    n = len(rows)
    for d in (",", ".", "ab", " "):
        for lim in (-1, 0, 2, 3):
            got = split_literal(col, d.encode(), lim).to_pylist(n)
            assert got == [host_split(s, d, lim) for s in rows], (d, lim)
        for c in (-3, -1, 1, 2):
            got = substring_index(col, d.encode(), c).to_pylist(n)
            assert got == [host_ssi(s, d, c) for s in rows], (d, c)
    needles = [None if rng.random() < 0.1 else
               "".join(rng.choice("abx") for _ in range(rng.randint(0, 3)))
               for _ in range(n)]
    got = find_in_set(StringColumn.from_pylist(needles), col).to_pylist(n)
    assert got == [host_fis(a, b) for a, b in zip(needles, rows)]


def test_planner_routes_to_device():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import STRING, Schema, StructField
    sess = TpuSession()
    df = sess.from_pydict(
        {"s": ["a,b,c", "x", None]},
        schema=Schema((StructField("s", STRING),)))
    q = df.select(F.split(F.col("s"), ",").alias("p"),
                  F.substring_index(F.col("s"), ",", 2).alias("i"))
    assert "host" not in q.explain()
    rows = q.collect()
    assert rows[0] == (["a", "b", "c"], "a,b")
    assert rows[1] == (["x"], "x")
    assert rows[2] == (None, None)


def test_planner_keeps_regex_split_on_host():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import STRING, Schema, StructField
    sess = TpuSession()
    df = sess.from_pydict(
        {"s": ["a1b22c"]}, schema=Schema((StructField("s", STRING),)))
    q = df.select(F.split(F.col("s"), "[0-9]+").alias("p"))
    assert "host" in q.explain()
    assert q.collect()[0][0] == ["a", "b", "c"]
