"""Datetime expression tests vs Python's datetime oracle."""

import datetime as pydt

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import DATE, INT, Schema, StructField

DATES = [pydt.date(2024, 2, 29), pydt.date(1999, 12, 31),
         pydt.date(1970, 1, 1), None, pydt.date(2025, 7, 4),
         pydt.date(1969, 3, 15)]


@pytest.fixture
def df():
    s = TpuSession()
    return s.from_pydict(
        {"d": [None if d is None else (d - pydt.date(1970, 1, 1)).days
               for d in DATES],
         "n": [1, 2, 3, 4, 5, 6]},
        Schema((StructField("d", DATE), StructField("n", INT))))


def test_extract_parts(df):
    got = df.select(F.year("d"), F.month("d"), F.dayofmonth("d"),
                    F.quarter("d"), F.dayofyear("d")).collect()
    for row, d in zip(got, DATES):
        if d is None:
            assert row == (None,) * 5
        else:
            assert row == (d.year, d.month, d.day, (d.month - 1) // 3 + 1,
                           d.timetuple().tm_yday)


def test_dayofweek_spark_semantics(df):
    # Spark dayofweek: 1=Sunday..7=Saturday
    got = [r[0] for r in df.select(F.dayofweek("d")).collect()]
    for g, d in zip(got, DATES):
        if d is None:
            assert g is None
        else:
            assert g == (d.isoweekday() % 7) + 1


def test_date_add_sub_diff(df):
    got = df.select(F.date_add("d", 10), F.date_sub("d", 10),
                    F.datediff("d", F.lit(0).cast(DATE))).collect()
    for row, d in zip(got, DATES):
        if d is None:
            assert row == (None, None, None)
        else:
            epoch = pydt.date(1970, 1, 1)
            assert row[0] == (d - epoch).days + 10
            assert row[1] == (d - epoch).days - 10
            assert row[2] == (d - epoch).days


def test_add_months_and_last_day(df):
    got = df.select(F.add_months("d", 1), F.last_day("d")).collect()
    epoch = pydt.date(1970, 1, 1)
    for row, d in zip(got, DATES):
        if d is None:
            assert row == (None, None)
            continue
        y, m = (d.year, d.month + 1) if d.month < 12 else (d.year + 1, 1)
        import calendar
        day = min(d.day, calendar.monthrange(y, m)[1])
        assert row[0] == (pydt.date(y, m, day) - epoch).days
        last = pydt.date(d.year, d.month,
                         calendar.monthrange(d.year, d.month)[1])
        assert row[1] == (last - epoch).days


def test_trunc(df):
    got = df.select(F.trunc("d", "year"), F.trunc("d", "month")).collect()
    epoch = pydt.date(1970, 1, 1)
    for row, d in zip(got, DATES):
        if d is None:
            assert row == (None, None)
            continue
        assert row[0] == (pydt.date(d.year, 1, 1) - epoch).days
        assert row[1] == (pydt.date(d.year, d.month, 1) - epoch).days
