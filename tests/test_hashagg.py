"""Hash-path group-by kernel tests: correctness vs the sort path, collision
resolution across rounds, leftover fallback signaling, null keys, strings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.exec.aggregate import AggregateExec
from spark_rapids_tpu.exec.basic import InMemoryScanExec
from spark_rapids_tpu.expr.aggexprs import Count, Max, Min, Sum
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.ops.aggregate import (
    groupby_aggregate, groupby_aggregate_hash,
)
from spark_rapids_tpu.types import INT, LONG, STRING, Schema, StructField


def _run_hash(keys, vals, rounds=2):
    n = len(vals)
    k = Column.from_pylist(keys, LONG) if not isinstance(keys[0], (str, type(None))) \
        else StringColumn.from_pylist(keys)
    v = Column.from_pylist(vals, LONG, capacity=k.capacity)
    out_keys, results, num_groups, leftover = groupby_aggregate_hash(
        [k], [("sum", v), ("count", v)], jnp.int32(n), k.capacity,
        rounds=rounds)
    if bool(leftover):
        return None
    ng = int(num_groups)
    ks = out_keys[0].to_pylist(ng)
    sums = [int(x) for x in np.asarray(results[0][1][0])[:ng]]
    return dict(zip(ks, sums))


def oracle(keys, vals):
    out = {}
    for k, v in zip(keys, vals):
        out[k] = out.get(k, 0) + (v or 0)
    return out


def test_low_cardinality_ints():
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 5, 500).tolist()
    vals = rng.integers(0, 100, 500).tolist()
    assert _run_hash(keys, vals) == oracle(keys, vals)


def test_null_keys_group_together():
    keys = [1, None, 2, None, 1]
    vals = [10, 20, 30, 40, 50]
    got = _run_hash(keys, vals)
    assert got == {1: 60, None: 60, 2: 30}


def test_string_keys():
    keys = ["aa", "bb", None, "aa", "cc", "bb"]
    vals = [1, 2, 3, 4, 5, 6]
    got = _run_hash(keys, vals)
    assert got == {"aa": 5, "bb": 8, None: 3, "cc": 5}


def test_mid_cardinality_resolves_or_flags():
    # 120 distinct keys in a 128 bucket: heavy collisions; either all
    # resolve within the rounds or leftover must be flagged (never silent
    # wrong answers)
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 120, 128).tolist()
    vals = rng.integers(0, 10, 128).tolist()
    got = _run_hash(keys, vals, rounds=6)
    if got is not None:
        assert got == oracle(keys, vals)


def test_hash_matches_sort_path_random():
    rng = np.random.default_rng(11)
    for trial in range(5):
        card = [3, 17, 40][trial % 3]
        keys = rng.integers(0, card, 300).tolist()
        vals = rng.integers(0, 50, 300).tolist()
        got = _run_hash(keys, vals, rounds=6)
        assert got is not None and got == oracle(keys, vals)


def test_exec_uses_hash_then_falls_back():
    """High-cardinality through the exec must still be exact (fallback)."""
    rng = np.random.default_rng(7)
    n = 2000
    keys = rng.integers(0, n, n).tolist()  # ~unique keys: forces fallback
    vals = rng.integers(0, 100, n).tolist()
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    scan = InMemoryScanExec(
        [ColumnarBatch.from_pydict({"k": keys, "v": vals}, sch)], sch)
    plan = AggregateExec([col("k")], [(Sum(col("v")), "s"),
                                      (Count(), "c")], scan)
    got = {r[0]: r[1] for r in plan.collect()}
    assert got == oracle(keys, vals)


def test_exec_string_minmax_routes_to_sort():
    sch = Schema((StructField("k", LONG), StructField("s", STRING)))
    data = {"k": [1, 1, 2, 2], "s": ["b", "a", "z", "y"]}
    scan = InMemoryScanExec([ColumnarBatch.from_pydict(data, sch)], sch)
    plan = AggregateExec([col("k")], [(Min(col("s")), "mn"),
                                      (Max(col("s")), "mx")], scan)
    assert not plan._hash_path_ok
    got = {r[0]: r[1:] for r in plan.collect()}
    assert got == {1: ("a", "b"), 2: ("y", "z")}


def test_first_last_ignore_nulls_semantics():
    """Spark default ignoreNulls=False: first/last return the first/last
    ROW's value even when null (review finding r1: the kernels silently
    modeled ignoreNulls=True)."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import LONG, Schema, StructField
    s = TpuSession()
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    data = {"k": [1, 1, 1, 2, 2], "v": [None, 10, None, 7, None]}
    df = s.from_pydict(data, sch)

    def run(**kw):
        rows = df.group_by("k").agg(
            (F.first(F.col("v"), **kw), "f"),
            (F.last(F.col("v"), **kw), "l")).collect()
        return {k: (f, l) for k, f, l in rows}

    # default: positional first/last regardless of nulls
    assert run() == {1: (None, None), 2: (7, None)}
    # ignore_nulls=True: skip nulls
    assert run(ignore_nulls=True) == {1: (10, 10), 2: (7, 7)}


def test_decimal_disabled_conf_tags_off():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.plan.overrides import PlanNotSupported
    from spark_rapids_tpu.types import DecimalType, Schema, StructField
    import pytest
    try:
        s = TpuSession({"spark.rapids.sql.decimalType.enabled": False})
        sch = Schema((StructField("x", DecimalType(10, 2)),))
        df = s.from_pydict({"x": [100]}, sch)
        with pytest.raises(PlanNotSupported):
            df.select((col("x") + col("x")).alias("y")).collect()
    finally:
        TpuSession()  # reset active conf for the rest of the process


def test_collect_list_and_set():
    """collect_list/collect_set (reference GpuCollectList/Set) vs Python
    oracle, across batches so the merge path runs."""
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import LONG, Schema, StructField
    s = TpuSession()
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    data = {"k": [1, 1, 2, 1, 2, 2, 1, None],
            "v": [5, None, 7, 5, 8, 7, 3, 9]}
    df = s.from_pydict(data, sch, batch_rows=3)
    got = {k: (sorted(lst), sorted(st)) for k, lst, st in
           df.group_by("k").agg((F.collect_list(F.col("v")), "lst"),
                                (F.collect_set(F.col("v")), "st")).collect()}
    import collections
    exp_list = collections.defaultdict(list)
    for k, v in zip(data["k"], data["v"]):
        if v is not None:
            exp_list[k].append(v)
        else:
            exp_list[k]
    exp = {k: (sorted(vs), sorted(set(vs))) for k, vs in exp_list.items()}
    assert got == exp, (got, exp)


def test_collect_list_grand_aggregate():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import LONG, Schema, StructField
    s = TpuSession()
    sch = Schema((StructField("v", LONG),))
    df = s.from_pydict({"v": [3, None, 1, 3, 2]}, sch)
    rows = df.agg((F.collect_list(F.col("v")), "lst"),
                  (F.collect_set(F.col("v")), "st"),
                  (F.sum(F.col("v")), "s")).collect()
    lst, st, total = rows[0]
    assert sorted(lst) == [1, 2, 3, 3] and sorted(st) == [1, 2, 3]
    assert total == 9


def test_to_jax_handoff():
    """ML handoff: device-resident arrays out of a query (reference
    ColumnarRdd / spark-rapids-ml bridge)."""
    import jax.numpy as jnp
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import DOUBLE, LONG, Schema, StructField
    s = TpuSession()
    sch = Schema((StructField("x", DOUBLE), StructField("y", LONG)))
    df = s.from_pydict({"x": [1.0, 2.0, None, 4.0],
                        "y": [1, 2, 3, 4]}, sch, batch_rows=2)
    out = df.filter(col("y") > 1).to_jax()
    assert set(out) == {"x", "y"}
    data, valid = out["x"]
    assert data.shape == (3,) and not bool(valid[1])
    assert [float(v) for v in out["y"][0]] == [2.0, 3.0, 4.0]


def test_collect_set_doubles_and_string_gate():
    """collect_set: float dedup without 64-bit bitcasts (TPU X64 rewrite),
    -0.0==0.0, and a plan-time gate for string inputs."""
    import pytest
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.plan.overrides import PlanNotSupported
    from spark_rapids_tpu.types import DOUBLE, LONG, STRING, Schema, \
        StructField
    s = TpuSession()
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    df = s.from_pydict({"k": [1, 1, 1, 1, 2, 2],
                        "v": [1.5, -0.0, 0.0, 1.5, 3.25, 3.25]}, sch)
    got = {k: sorted(st) for k, st in df.group_by("k").agg(
        (F.collect_set(col("v")), "st")).collect()}
    assert got == {1: [0.0, 1.5], 2: [3.25]}
    ssch = Schema((StructField("k", LONG), StructField("v", STRING)))
    sdf = s.from_pydict({"k": [1, 1], "v": ["a", "a"]}, ssch)
    with pytest.raises(PlanNotSupported):
        sdf.group_by("k").agg((F.collect_set(col("v")), "st")).collect()
    # collect_LIST over strings stays supported
    lst = sdf.group_by("k").agg((F.collect_list(col("v")), "l")).collect()
    assert lst == [(1, ["a", "a"])]


def test_collect_list_nested_arrays():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.functions import col
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import ArrayType, LONG, Schema, StructField
    s = TpuSession()
    sch = Schema((StructField("k", LONG), StructField("v", ArrayType(LONG))))
    df = s.from_pydict({"k": [1, 1], "v": [[1, 2], [3]]}, sch)
    agg = df.group_by("k").agg((F.collect_list(col("v")), "l"))
    assert agg.schema.fields[1].data_type.simple_name() \
        == "array<array<bigint>>"
    assert sorted(agg.collect()[0][1]) == [[1, 2], [3]]
