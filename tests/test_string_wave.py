"""String function wave + device regex, each vs a Python-semantics oracle
(reference: stringFunctions.scala operators, RegexParser.scala transpiler;
integration test analog string_test.py / regexp_test.py)."""

import re as pyre

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.testing import StringGen, gen_pydict
from spark_rapids_tpu.types import INT, LONG, STRING, Schema, StructField

STRS = ["hello world", "  padded  ", "", "a", "aaa bbb", "MiXeD CaSe",
        None, "tab\there", "x,y,z", "abcabcabc", "trailing   ",
        "   leading", "one two  three"]


@pytest.fixture(scope="module")
def df():
    s = TpuSession()
    sch = Schema((StructField("s", STRING), StructField("n", INT)))
    return s.from_pydict({"s": STRS, "n": list(range(len(STRS)))}, sch)


def run1(df, expr):
    return [r[0] for r in df.select(expr.alias("r")).collect()]


def oracle(fn):
    return [None if s is None else fn(s) for s in STRS]


def test_trim_family(df):
    assert run1(df, F.trim(col("s"))) == oracle(str.strip)
    assert run1(df, F.ltrim(col("s"))) == oracle(str.lstrip)
    assert run1(df, F.rtrim(col("s"))) == oracle(str.rstrip)
    assert run1(df, F.trim(col("s"), "ag ")) == oracle(
        lambda s: s.strip("ag "))


def test_pad(df):
    assert run1(df, F.lpad(col("s"), 8, "*")) == oracle(
        lambda s: s.rjust(8, "*") if len(s) < 8 else s[:8])
    assert run1(df, F.rpad(col("s"), 8, "xy")) == oracle(
        lambda s: (s + "xyxyxyxy")[:8] if len(s) < 8 else s[:8])
    # empty pad keeps short strings (Spark semantics)
    assert run1(df, F.lpad(col("s"), 6, "")) == oracle(lambda s: s[:6])


def test_repeat_reverse(df):
    assert run1(df, F.repeat(col("s"), 3)) == oracle(lambda s: s * 3)
    assert run1(df, F.repeat(col("s"), 0)) == oracle(lambda s: "")
    assert run1(df, F.reverse(col("s"))) == oracle(lambda s: s[::-1])


def test_initcap(df):
    def ic(s):
        out, prev_space = [], True
        for ch in s:
            out.append(ch.upper() if prev_space else ch.lower())
            prev_space = ch in " \t\n\r"
        return "".join(out)
    assert run1(df, F.initcap(col("s"))) == oracle(ic)


def test_locate_instr(df):
    assert run1(df, F.locate("l", col("s"))) == oracle(
        lambda s: s.find("l") + 1)
    assert run1(df, F.locate("l", col("s"), 4)) == oracle(
        lambda s: s.find("l", 3) + 1)
    assert run1(df, F.instr(col("s"), "ab")) == oracle(
        lambda s: s.find("ab") + 1)
    # empty needle: Java indexOf("") semantics
    assert run1(df, F.locate("", col("s"), 3)) == oracle(
        lambda s: min(2, len(s)) + 1)


def test_replace(df):
    assert run1(df, F.replace(col("s"), "ab", "QQ")) == oracle(
        lambda s: s.replace("ab", "QQ"))
    assert run1(df, F.replace(col("s"), "a", "")) == oracle(
        lambda s: s.replace("a", ""))
    assert run1(df, F.replace(col("s"), " ", "__")) == oracle(
        lambda s: s.replace(" ", "__"))


def test_replace_bordered_needle():
    """Self-overlapping needles need greedy non-overlapping selection."""
    s = TpuSession()
    vals = ["aaaa", "aaa", "aa", "a", "", "baaab", "aabaa", None]
    sch = Schema((StructField("s", STRING),))
    df = s.from_pydict({"s": vals}, sch)
    got = [r[0] for r in df.select(
        F.replace(col("s"), "aa", "X").alias("r")).collect()]
    assert got == [None if v is None else v.replace("aa", "X")
                   for v in vals]


def test_concat_and_ws(df):
    got = run1(df, F.concat(col("s"), F.lit("!"), col("s")))
    assert got == oracle(lambda s: s + "!" + s)
    # concat is null-intolerant
    assert got[STRS.index(None)] is None
    # concat_ws skips nulls and never returns null
    got_ws = run1(df, F.concat_ws("-", col("s"), F.lit("A"), col("s")))
    exp = ["-".join(x for x in (s, "A", s) if x is not None)
           for s in STRS]
    assert got_ws == exp


def test_translate(df):
    assert run1(df, F.translate(col("s"), "abc", "xy")) == oracle(
        lambda s: s.translate(str.maketrans("ab", "xy", "c")))


def test_ascii_chr():
    s = TpuSession()
    sch = Schema((StructField("s", STRING), StructField("n", LONG)))
    df = s.from_pydict({"s": ["Abc", "", "zz", None],
                        "n": [65, 0, 256 + 66, None]}, sch)
    assert [r[0] for r in df.select(F.ascii(col("s")).alias("r")).collect()] \
        == [65, 0, 122, None]
    assert [r[0] for r in df.select(F.chr(col("n")).alias("r")).collect()] \
        == ["A", "", "B", None]


def test_left_right(df):
    assert run1(df, F.left(col("s"), 3)) == oracle(lambda s: s[:3])
    assert run1(df, F.right(col("s"), 3)) == oracle(
        lambda s: s[-3:] if len(s) >= 3 else s)
    assert run1(df, F.left(col("s"), 0)) == oracle(lambda s: "")


def test_lengths(df):
    assert run1(df, F.octet_length(col("s"))) == oracle(
        lambda s: len(s.encode()))
    assert run1(df, F.bit_length(col("s"))) == oracle(
        lambda s: len(s.encode()) * 8)


@pytest.mark.parametrize("pattern", [
    r"^hello", r"world$", r"a+", r"[a-m]+", r"\s\s", r"^\s*[a-z]+",
    r"(one|two) ", r"a{3}", r".b.", r"^[^aeiou]+$", r"x,y,z",
])
def test_rlike_vs_python(df, pattern):
    got = run1(df, F.rlike(col("s"), pattern))
    assert got == oracle(lambda s: bool(pyre.search(pattern, s))), pattern


@pytest.mark.parametrize("pattern", [
    "hello%", "%world", "%a%", "___", "", "%", "a", "%  %", "x,y,z",
])
def test_like_vs_python(df, pattern):
    rx = "^" + "".join(
        ".*" if c == "%" else "." if c == "_" else pyre.escape(c)
        for c in pattern) + "$"
    got = run1(df, F.like(col("s"), pattern))
    assert got == oracle(lambda s: bool(pyre.search(rx, s))), pattern


def test_rlike_unsupported_tags_off_tpu():
    """Unsupported regex constructs tag the expression off the DEVICE at
    PLAN time (the reference's transpile-or-fallback), not at expression
    construction. With CPU fallback disabled the plan fails; with it on
    (default) Python-re-compatible patterns run on the host row engine
    instead."""
    from spark_rapids_tpu.plan.overrides import PlanNotSupported
    strict = TpuSession({"spark.rapids.sql.cpuFallback.enabled": "false"})
    sch = Schema((StructField("s", STRING),))
    df = strict.from_pydict({"s": ["x"]}, sch)
    for bad in (r"(?=x)", r"a*?", r"\1", r"\bw", r"\p{L}", r"x{1,200}"):
        plan = df.select(F.rlike(col("s"), bad).alias("r"))  # no throw
        with pytest.raises(PlanNotSupported):
            plan.collect()
    # default session: lookahead runs on the host engine (same answers
    # Java regex would give for this construct)
    relaxed = TpuSession()
    df2 = relaxed.from_pydict({"s": ["xy", "zy", None]}, sch)
    q = df2.select(F.rlike(col("s"), r"(?=x)x").alias("r"))
    assert "HostProjectExec" in q._exec().tree_string()
    assert q.collect() == [(True,), (False,), (None,)]


def test_string_wave_fuzz():
    """Random strings through the whole wave vs Python oracles."""
    data, sch = gen_pydict(
        [("s", StringGen(max_length=24, ascii_only=True))], 500, seed=42)
    sess = TpuSession()
    df = sess.from_pydict(data, sch, batch_rows=128)
    vals = data["s"]

    def run(expr):
        return [r[0] for r in df.select(expr.alias("r")).collect()]

    checks = [
        (F.trim(col("s")), str.strip),
        (F.reverse(col("s")), lambda s: s[::-1]),
        (F.lpad(col("s"), 10, "#"),
         lambda s: s.rjust(10, "#") if len(s) < 10 else s[:10]),
        (F.replace(col("s"), "a", "zz"), lambda s: s.replace("a", "zz")),
        (F.locate("e", col("s")), lambda s: s.find("e") + 1),
        (F.rlike(col("s"), r"[0-9][a-z]"),
         lambda s: bool(pyre.search(r"[0-9][a-z]", s))),
        (F.like(col("s"), "%a%"), lambda s: "a" in s),
    ]
    for expr, fn in checks:
        got = run(expr)
        exp = [None if s is None else fn(s) for s in vals]
        assert got == exp, f"{expr!r}"


def test_rule_count_grew():
    from spark_rapids_tpu.plan.overrides import expression_rules
    assert len(expression_rules()) >= 80
