"""Memory-runtime suites — the engine's analog of the reference's
OOM-injection chaos tests (RmmSparkRetrySuiteBase.scala + WithRetrySuite /
RapidsBufferCatalogSuite / Rapids*StoreSuite, SURVEY §4 tier 1): a tiny
budget, spill stores installed, then forced TpuRetryOOM / split-retry."""

import numpy as np
import pytest

from spark_rapids_tpu.types import INT, LONG, Schema
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.memory import (
    SpillableBatch, StorageTier, TpuRetryOOM, TpuSplitAndRetryOOM,
    buffer_catalog, force_retry_oom, force_split_and_retry_oom,
    memory_budget, register_task, reset_buffer_catalog, reset_memory_budget,
    reset_tpu_semaphore, split_in_half_by_rows, task_retry_counts,
    tpu_semaphore, with_retry, with_retry_no_split,
)


@pytest.fixture(autouse=True)
def small_pool():
    """512 KiB budget + fresh catalog per test (the reference's tiny RMM)."""
    reset_buffer_catalog()
    reset_memory_budget(512 * 1024)
    register_task(1)
    yield
    reset_buffer_catalog()
    reset_memory_budget()


def batch_of(n, start=0):
    return ColumnarBatch.from_pydict(
        {"a": list(range(start, start + n)),
         "b": [i * 10 for i in range(start, start + n)]},
        Schema.of(a=LONG, b=LONG))


def test_spillable_roundtrip():
    sb = SpillableBatch.from_batch(batch_of(100))
    got = sb.get_batch()
    assert got.to_pydict()["a"][:3] == [0, 1, 2]
    sb.release()
    sb.close()
    assert buffer_catalog().num_entries() == 0


def test_spill_to_host_and_back():
    sb = SpillableBatch.from_batch(batch_of(64))
    cat = buffer_catalog()
    freed = cat.synchronous_spill(None)
    assert freed > 0
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    # acquire unspills transparently
    got = sb.get_batch()
    assert got.to_pydict()["b"][3] == 30
    assert cat.tier_of(sb._handle) == StorageTier.DEVICE
    sb.release()
    sb.close()


def test_spill_to_disk(tmp_path):
    from spark_rapids_tpu import config as C
    C.set_active_conf(C.RapidsConf({
        "spark.rapids.memory.host.spillStorageSize": "1k",
        "spark.rapids.memory.spillDirectory": str(tmp_path),
    }))
    try:
        reset_buffer_catalog()
        sb = SpillableBatch.from_batch(batch_of(64))
        cat = buffer_catalog()
        cat.synchronous_spill(None)  # device -> host -> (limit 1k) -> disk
        assert cat.tier_of(sb._handle) == StorageTier.DISK
        # spill.asyncWrite (default on) hands the write to the
        # background writer; drain before asserting the file landed
        cat.drain_writeback()
        assert list(tmp_path.glob("spill-*.npz"))
        got = sb.get_batch()
        assert got.to_pydict()["a"][5] == 5
        sb.release()
        sb.close()
    finally:
        C.set_active_conf(C.RapidsConf())


def test_in_use_entries_are_not_spilled():
    sb = SpillableBatch.from_batch(batch_of(32))
    sb.get_batch()  # pinned
    cat = buffer_catalog()
    cat.synchronous_spill(None)
    assert cat.tier_of(sb._handle) == StorageTier.DEVICE
    sb.release()
    cat.synchronous_spill(None)
    assert cat.tier_of(sb._handle) == StorageTier.HOST
    sb.close()


def test_budget_pressure_triggers_spill():
    """Reserving past the limit spills idle spillables instead of failing."""
    budget = memory_budget()
    sb = SpillableBatch.from_batch(batch_of(1000))  # big-ish resident batch
    used_before = budget.used
    assert used_before > 0
    budget.reserve(budget.limit - budget.used + 1)  # forces a spill
    assert buffer_catalog().tier_of(sb._handle) == StorageTier.HOST
    sb.close()


def test_budget_oom_when_nothing_spillable():
    budget = memory_budget()
    with pytest.raises(TpuRetryOOM):
        budget.reserve(budget.limit + 1)


def test_with_retry_recovers_from_injected_oom():
    """Reference WithRetrySuite: first attempt throws, retry succeeds."""
    attempts = []

    def body(b):
        attempts.append(1)
        return b.num_rows_host

    force_retry_oom()
    sb = batch_of(10)
    out = list(with_retry(sb, body))
    assert out == [10]
    retries, splits = task_retry_counts()
    assert retries == 1 and splits == 0


def test_with_retry_split_halves_batch():
    """Reference split-retry: the batch is halved and both halves run."""
    force_split_and_retry_oom()
    out = list(with_retry(batch_of(10), lambda b: b.num_rows_host,
                          split_policy=split_in_half_by_rows))
    assert out == [5, 5]
    retries, splits = task_retry_counts()
    assert splits == 1


def test_with_retry_split_preserves_rows():
    force_split_and_retry_oom()
    seen = []
    for b in with_retry(batch_of(9), lambda b: b.to_pydict()["a"],
                        split_policy=split_in_half_by_rows):
        seen.extend(b)
    assert seen == list(range(9))


def test_with_retry_no_split_escalates():
    force_split_and_retry_oom()
    with pytest.raises(TpuSplitAndRetryOOM):
        with_retry_no_split(batch_of(4), lambda b: b)


def test_retry_gives_up_after_max_attempts():
    from spark_rapids_tpu import config as C
    C.set_active_conf(C.RapidsConf({
        "spark.rapids.sql.retry.maxAttempts": "3"}))
    try:
        register_task(2)

        def always_oom(b):
            raise TpuRetryOOM("persistent")

        with pytest.raises(TpuRetryOOM):
            list(with_retry(batch_of(4), always_oom))
    finally:
        C.set_active_conf(C.RapidsConf())


def test_semaphore_admission():
    sem = reset_tpu_semaphore(2)
    try:
        sem.acquire_if_necessary(1)
        sem.acquire_if_necessary(1)  # reentrant, no deadlock
        sem.acquire_if_necessary(2)
        assert sem.available == 0
        sem.release_if_necessary(1)
        assert sem.available == 1
        sem.release_if_necessary(2)
        assert sem.available == 2
    finally:
        reset_tpu_semaphore()  # don't leak a 2-permit sem to later tests


def test_semaphore_blocks_third_task():
    import threading
    sem = reset_tpu_semaphore(1)
    try:
        sem.acquire_if_necessary(1)
        acquired = threading.Event()

        def worker():
            sem.acquire_if_necessary(2)
            acquired.set()
            sem.release_if_necessary(2)

        t = threading.Thread(target=worker)
        t.start()
        assert not acquired.wait(0.1)
        sem.release_if_necessary(1)
        assert acquired.wait(2.0)
        t.join()
    finally:
        reset_tpu_semaphore()  # don't leak a 1-permit sem to later tests


def test_config_docs_generation():
    from spark_rapids_tpu.config import generate_docs
    docs = generate_docs()
    assert "spark.rapids.sql.batchSizeBytes" in docs
    assert "spark.rapids.memory.tpu.allocFraction" in docs


def test_unknown_config_rejected():
    from spark_rapids_tpu import config as C
    with pytest.raises(KeyError):
        C.RapidsConf({"spark.rapids.sql.typoKey": "1"})
