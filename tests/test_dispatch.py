"""Dispatch & compile observability plane (ISSUE 13): the ledger
chokepoint (counting, first-trace vs cache-hit discrimination, nested
passthrough, off-path), the recompile-storm detector, the per-exec
numDispatches/compileTimeNs metrics and QueryProfile.dispatch_summary()
replay stability, the profile_report dispatch roll-up, bench deltas,
health section, and the Chrome trace exporter (structural: thread
tracks, nested operator spans, compile instants)."""

from __future__ import annotations

import json
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs import dispatch, events
from spark_rapids_tpu.types import (DoubleType, IntegerType, LongType,
                                    Schema, StructField)

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import profile_report  # noqa: E402
import trace_export  # noqa: E402

INT, LONG, DOUBLE = IntegerType(), LongType(), DoubleType()


@pytest.fixture(autouse=True)
def _fresh_ledger():
    dispatch.reset_dispatch_ledger()
    events.reset_event_bus()
    yield
    dispatch.reset_dispatch_ledger()
    events.reset_event_bus()


# -- ledger unit behavior ----------------------------------------------------

def test_dispatch_counts_and_cache_hit_discrimination():
    site = dispatch.instrument(lambda x: x * 2, label="t.double")
    a = jnp.arange(100, dtype=jnp.int32)
    assert int(site(a)[3]) == 6
    c = dispatch.counters()
    assert (c["dispatches"], c["traces"], c["cache_hits"]) == (1, 1, 0)
    site(a)  # same exact shape: jit cache hit, still a dispatch
    c = dispatch.counters()
    assert (c["dispatches"], c["traces"], c["cache_hits"]) == (2, 1, 1)
    progs = dispatch.programs()
    assert len(progs) == 1 and progs[0]["label"] == "t.double"
    assert progs[0]["dispatches"] == 2 and progs[0]["traces"] == 1
    assert progs[0]["compile_ns"] > 0 and progs[0]["trace_ns"] > 0
    # a new shape in a DIFFERENT log2 bucket is a new program key
    site(jnp.arange(300, dtype=jnp.int32))
    assert dispatch.counters()["programs"] == 2


def test_same_bucket_retrace_is_one_program_key():
    """Distinct exact shapes inside one log2 bucket re-trace the SAME
    key — the churn signal the storm detector watches."""
    site = dispatch.instrument(lambda x: x + 1, label="t.churn")
    for n in (130, 140, 150):  # all bucket to 8 (129..256)
        site(jnp.arange(n, dtype=jnp.int32))
    progs = dispatch.programs()
    assert len(progs) == 1
    assert progs[0]["traces"] == 3 and progs[0]["cache_hits"] == 0


def test_nested_instrumented_call_is_not_a_second_dispatch():
    inner = dispatch.instrument(lambda x: x + 1, label="t.inner")
    outer = dispatch.instrument(lambda x: inner(x) * 2, label="t.outer")
    outer(jnp.arange(64, dtype=jnp.int32))
    labels = {p["label"] for p in dispatch.programs()}
    assert labels == {"t.outer"}
    assert dispatch.counters()["dispatches"] == 1


def test_eval_shape_is_not_a_dispatch():
    site = dispatch.instrument(lambda x: x * 2, label="t.abstract")
    out = jax.eval_shape(site, jax.ShapeDtypeStruct((16,), jnp.int32))
    assert out.shape == (16,)
    assert dispatch.counters()["dispatches"] == 0


def test_donated_vs_retained_bytes():
    site = dispatch.instrument(lambda x, y: x + y, label="t.donate",
                               donate_argnums=(0,))
    x = jnp.arange(256, dtype=jnp.int32)
    site(x, x + 1)
    p = dispatch.programs()[0]
    assert p["donated_bytes"] == 256 * 4
    assert p["retained_bytes"] == 256 * 4


def test_off_path_is_pointer_check_and_results_identical():
    site = dispatch.instrument(lambda x: x * 3, label="t.off")
    a = jnp.arange(50, dtype=jnp.int32)
    on = np.asarray(site(a))
    dispatch.configure(__import__(
        "spark_rapids_tpu.config", fromlist=["RapidsConf"]).RapidsConf(
        {"spark.rapids.tpu.dispatch.ledger.enabled": "false"}))
    assert dispatch.active_ledger() is None
    off = np.asarray(site(a))
    np.testing.assert_array_equal(on, off)
    assert dispatch.counters() == {
        "programs": 0, "dispatches": 0, "traces": 0, "cache_hits": 0,
        "compile_ns": 0, "trace_ns": 0, "storms": 0}
    # a default conf re-enables (the conf defaults ON)
    dispatch.configure(__import__(
        "spark_rapids_tpu.config", fromlist=["RapidsConf"]).RapidsConf({}))
    assert dispatch.active_ledger() is not None


def test_recompile_storm_fires_once_per_window(tmp_path):
    from spark_rapids_tpu.config import RapidsConf
    bus = events.enable(str(tmp_path), level="ESSENTIAL")
    dispatch.configure(RapidsConf({
        "spark.rapids.tpu.dispatch.storm.traces": "3",
        "spark.rapids.tpu.dispatch.storm.windowMs": "60000"}))
    site = dispatch.instrument(lambda x: x + 1, label="t.storm")
    for n in range(130, 138):  # 8 exact shapes, one bucket: 8 traces
        site(jnp.arange(n, dtype=jnp.int32))
    assert dispatch.counters()["storms"] == 1  # quiet until the
    bus.close()                                # window rolls past
    recs = [json.loads(ln) for ln in open(bus.path)]
    storms = [r for r in recs if r["kind"] == "recompile_storm"]
    assert len(storms) == 1
    s = storms[0]
    assert s["label"] == "t.storm" and s["threshold"] == 3
    assert s["traces_in_window"] >= 3 and s["window_ms"] == 60000
    # recompile_storm is ESSENTIAL: it survived the ESSENTIAL cut
    assert all(r["kind"] in ("recompile_storm",) for r in recs)


def test_many_sites_one_label_is_not_a_storm(tmp_path):
    """Review fix: distinct program sites legitimately share a ledger
    key (ExpandExec's per-projection jits, fresh exec instances per
    collect). Each site's FIRST trace of a bucket is a new program —
    first=True on its compile event, and never a storm contribution;
    only a re-trace within one site's own jit cache is churn."""
    from spark_rapids_tpu.config import RapidsConf
    bus = events.enable(str(tmp_path), level="MODERATE")
    dispatch.configure(RapidsConf({
        "spark.rapids.tpu.dispatch.storm.traces": "3"}))
    sites = [dispatch.instrument(lambda x, i=i: x + i, label="t.fan")
             for i in range(6)]
    a = jnp.arange(64, dtype=jnp.int32)
    for s in sites:  # 6 fresh traces of ONE ledger key, zero churn
        s(a)
    assert dispatch.counters()["storms"] == 0
    bus.close()
    recs = [json.loads(ln) for ln in open(bus.path)]
    comps = [r for r in recs if r["kind"] == "program_compile"]
    assert len(comps) == 6 and all(r["first"] for r in comps)
    assert not any(r["kind"] == "recompile_storm" for r in recs)
    # genuine churn on ONE of the sites still fires
    for n in (65, 66, 67, 68):  # same bucket, new exact shapes
        sites[0](jnp.arange(n, dtype=jnp.int32))
    assert dispatch.counters()["storms"] == 1


def test_dispatch_summary_claims_inherited_site_labels():
    """Review fix: TopNExec inherits SortExec.__init__'s jit site
    (label "SortExec.sort") — its stage row must still report the
    program, joined by the exec's own site labels, not its class
    name."""
    sess = TpuSession()
    q = _q3_query(sess)  # ends in sort+limit? ensure a TopN via limit
    q.limit(5).collect()
    summary = sess.last_query_profile().dispatch_summary()
    rows = {r["op"]: r for r in summary["stages"]}
    top = rows.get("TopNExec") or rows.get("SortExec")
    assert top is not None and top["dispatches"] > 0
    assert top["programs"] > 0, summary


def test_program_compile_event_fields(tmp_path):
    bus = events.enable(str(tmp_path), level="MODERATE")
    site = dispatch.instrument(lambda x: x * 2, label="t.ev")
    site(jnp.arange(64, dtype=jnp.int32))
    site(jnp.arange(64, dtype=jnp.int32))  # cache hit: no second event
    bus.close()
    recs = [json.loads(ln) for ln in open(bus.path)]
    comps = [r for r in recs if r["kind"] == "program_compile"]
    assert len(comps) == 1
    c = comps[0]
    assert c["label"] == "t.ev" and c["first"] is True
    assert c["compile_ns"] > 0 and c["trace_ns"] > 0
    assert c["platform"] == jax.default_backend()
    assert "thread" in c  # ISSUE 13 satellite: track assignment field


# -- engine integration ------------------------------------------------------

def _q1_query(sess, n=3000):
    rng = np.random.default_rng(0)
    schema = Schema((StructField("k", INT), StructField("q", LONG),
                     StructField("p", DOUBLE)))
    df = sess.from_pydict({"k": rng.integers(0, 6, n).tolist(),
                           "q": rng.integers(1, 50, n).tolist(),
                           "p": (rng.random(n) * 10).tolist()},
                          schema, batch_rows=1024)
    return (df.filter(col("q") <= lit(40))
              .group_by("k").agg((Sum(col("p")), "s"), (Count(), "c")))


def _q3_query(sess, n=800):
    rng = np.random.default_rng(1)
    osch = Schema((StructField("o", LONG), StructField("d", LONG)))
    lsch = Schema((StructField("o", LONG), StructField("x", DOUBLE)))
    orders = sess.from_pydict(
        {"o": list(range(n)),
         "d": rng.integers(0, 100, n).tolist()}, osch, batch_rows=256)
    lines = sess.from_pydict(
        {"o": [int(v) for v in rng.integers(0, n, 2 * n)],
         "x": (rng.random(2 * n) * 5).tolist()}, lsch, batch_rows=256)
    return (orders.filter(col("d") < lit(50))
                  .join(lines, on="o")
                  .group_by("o").agg((Sum(col("x")), "rev"))
                  .sort((col("rev"), False)))


def _summary_key(summary):
    """The replay-stable projection of a dispatch summary: per stage,
    (dispatches, batches, dispatches/batch)."""
    return [(r["op"], r["dispatches"], r["batches"],
             r["dispatches_per_batch"]) for r in summary["stages"]]


@pytest.mark.parametrize("build", [_q1_query, _q3_query],
                         ids=["q1", "q3"])
def test_dispatch_summary_exact_and_replayed_across_collects(build):
    """Acceptance (ISSUE 13): per-stage dispatches/batch is exact and
    identical across 3 repeated collects — jit cache hits must not
    zero the counts (dispatches are counted at call time)."""
    sess = TpuSession()
    q = build(sess)
    keys, results = [], []
    for _ in range(3):
        results.append(sorted(q.collect()))
        keys.append(_summary_key(
            sess.last_query_profile().dispatch_summary()))
    assert results[0] == results[1] == results[2]
    assert keys[0] == keys[1] == keys[2], keys
    # the plan actually dispatched programs, and some stage reports an
    # exact per-batch rate
    total = sum(r[1] for r in keys[0])
    assert total > 0
    assert any(r[3] for r in keys[0])


def test_cache_hits_do_not_zero_counts_on_one_plan():
    """Drive ONE exec tree twice (the bench shape: one plan, many
    iterations): the second execution is all jit cache hits, yet its
    dispatch delta equals the first's and the per-batch rate holds."""
    from spark_rapids_tpu.obs.profile import QueryProfile
    sess = TpuSession()
    plan = _q1_query(sess)._exec()
    r1 = sorted(plan.collect())
    s1 = QueryProfile(plan).dispatch_summary()
    hits1 = dispatch.counters()["cache_hits"]
    r2 = sorted(plan.collect())
    s2 = QueryProfile(plan).dispatch_summary()
    hits2 = dispatch.counters()["cache_hits"]
    assert r1 == r2
    assert hits2 > hits1  # second run really rode the jit cache
    for a, b in zip(s1["stages"], s2["stages"]):
        assert b["dispatches"] == 2 * a["dispatches"]
        assert b["batches"] == 2 * a["batches"]
        assert b["dispatches_per_batch"] == a["dispatches_per_batch"]


def test_results_byte_identical_with_plane_on_and_off():
    on = sorted(_q1_query(TpuSession()).collect())
    off_sess = TpuSession(
        {"spark.rapids.tpu.dispatch.ledger.enabled": "false"})
    assert dispatch.active_ledger() is None
    off = sorted(_q1_query(off_sess).collect())
    assert on == off
    dispatch.reset_dispatch_ledger()


def test_health_section():
    sess = TpuSession()
    _q1_query(sess).collect()
    h = sess.health()["dispatch"]
    assert h["enabled"] is True
    assert h["dispatches"] > 0 and h["programs"] > 0
    assert h["top_programs"][0]["compile_ns"] >= \
        h["top_programs"][-1]["compile_ns"]


def test_dispatch_stats_event_and_report_rollup(tmp_path):
    sess = TpuSession({"spark.rapids.tpu.eventLog.enabled": "true",
                       "spark.rapids.tpu.eventLog.dir": str(tmp_path)})
    _q1_query(sess).collect()
    log = events.active_bus().path
    events.reset_event_bus()
    evs = profile_report.read_event_files(log)
    kinds = {e["kind"] for e in evs}
    assert "program_compile" in kinds and "dispatch_stats" in kinds
    s = profile_report.build_summary(evs)
    dp = s["dispatch"]
    assert dp["programs_compiled"] > 0 and dp["compile_ns"] > 0
    assert dp["top_by_compile_ns"][0]["compile_ns"] > 0
    assert any(r["dispatches_per_batch"]
               for r in dp["top_by_dispatches_per_batch"])
    text = profile_report.build_report(evs)
    assert "program compiles:" in text
    assert "dispatches/batch" in text


def test_report_tolerates_pre_dispatch_logs(tmp_path):
    """A log from a build without dispatch events still renders — the
    roll-up reports zeros and prints nothing."""
    log = tmp_path / "old.jsonl"
    log.write_text(json.dumps(
        {"ts_ns": 1, "kind": "op_close", "query": 1, "op": "X",
         "op_id": 1, "wall_ns": 5, "batches": 1, "rows": 1}) + "\n")
    evs = profile_report.read_event_files(str(log))
    s = profile_report.build_summary(evs)
    assert s["dispatch"]["programs_compiled"] == 0
    assert s["dispatch"]["storms"] == []
    assert "program compiles" not in profile_report.build_report(evs)


def test_bench_dispatch_attribution_deltas():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", Path(__file__).resolve().parents[1] / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    bench._attr_prev.pop("dispatch", None)
    first = bench.dispatch_attribution()
    assert set(first) == {"programs", "dispatches", "compile_ns",
                          "cache_hits", "storms"}
    site = dispatch.instrument(lambda x: x + 1, label="t.bench")
    site(jnp.arange(32, dtype=jnp.int32))
    delta = bench.dispatch_attribution()
    assert delta["dispatches"] == 1 and delta["programs"] == 1


# -- trace exporter ----------------------------------------------------------

def _mk(ts_ns, kind, thread, **f):
    return dict(ts_ns=ts_ns, kind=kind, query=1, thread=thread, **f)


def test_trace_export_structure_handcrafted():
    """Structural acceptance on a deterministic log: >=3 thread tracks,
    NESTED operator spans (parent op_close encloses the child's), and
    compile instants."""
    us = 1_000
    evs = [
        _mk(100 * us, "program_compile", "MainThread", label="A.k",
            compile_ns=5, trace_ns=2, first=True),
        # child closes at 900us after 500us; parent at 1000us after
        # 800us: parent span [200..1000] strictly encloses [400..900]
        _mk(900 * us, "op_close", "MainThread", op="ChildExec", op_id=2,
            wall_ns=500 * us, batches=3, rows=9),
        _mk(1000 * us, "op_close", "MainThread", op="RootExec", op_id=1,
            wall_ns=800 * us, batches=3, rows=9),
        _mk(300 * us, "semaphore_acquire", "pipeline-scan-1",
            task_id=1, wait_ns=10),
        _mk(350 * us, "spill", "spill-writer", tier="device->host",
            bytes=123),
        _mk(400 * us, "telemetry_sample", "telemetry-sampler",
            **{"hbm.device_bytes": 42, "workload.queue_depth": 1}),
    ]
    trace = trace_export.build_trace(evs)
    te = trace["traceEvents"]
    tracks = {t["args"]["name"]: t["tid"] for t in te
              if t.get("ph") == "M" and t["name"] == "thread_name"}
    assert len(tracks) >= 3
    assert tracks["MainThread"] == 1
    spans = {t["name"]: t for t in te if t.get("ph") == "X"}
    root, child = spans["RootExec"], spans["ChildExec"]
    assert root["ts"] <= child["ts"]
    assert root["ts"] + root["dur"] >= child["ts"] + child["dur"]
    assert root["tid"] == child["tid"] == 1
    instants = {t["name"] for t in te if t.get("ph") == "i"}
    assert "program_compile" in instants and "spill" in instants
    counters = [t for t in te if t.get("ph") == "C"]
    assert {c["name"] for c in counters} == {"hbm.device_bytes",
                                             "workload.queue_depth"}


def test_trace_export_live_query_perfetto_shape(tmp_path):
    """Acceptance (ISSUE 13): a real host-shuffled run with eventLog on
    produces a Chrome trace with >=3 thread tracks (consumer +
    pipeline producers), nested op spans, and compile instants; the
    JSON is structurally Perfetto-loadable (traceEvents list, M/X/i
    phases only from the known set)."""
    sess = TpuSession({
        "spark.rapids.tpu.eventLog.enabled": "true",
        "spark.rapids.tpu.eventLog.dir": str(tmp_path),
        "spark.rapids.sql.shuffle.partitions": "2",
        "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    _q3_query(sess).collect()
    log = events.active_bus().path
    events.reset_event_bus()
    out = str(tmp_path / "trace.json")
    assert trace_export.main([log, "-o", out]) == 0
    trace = json.load(open(out))
    te = trace["traceEvents"]
    assert trace["displayTimeUnit"] == "ms"
    assert all(t["ph"] in ("M", "X", "i", "C") for t in te)
    tracks = [t["args"]["name"] for t in te
              if t["ph"] == "M" and t["name"] == "thread_name"]
    assert len(tracks) >= 3, tracks
    assert "MainThread" in tracks
    assert any(t.startswith("pipeline-") for t in tracks)
    spans = [t for t in te if t["ph"] == "X"]
    # nested operator spans on the consumer track: some span strictly
    # inside another (the pull model's inclusive wall time)
    main_spans = sorted((t for t in spans if t["tid"] == 1),
                        key=lambda t: t["dur"], reverse=True)
    outer = main_spans[0]
    assert any(outer["ts"] <= s["ts"] and
               s["ts"] + s["dur"] <= outer["ts"] + outer["dur"]
               for s in main_spans[1:])
    assert any(t["name"] == "program_compile" for t in te
               if t["ph"] == "i")
