"""Expression semantics tests — the engine-side analog of the reference's
CPU-vs-GPU equality harness (integration_tests asserts.py:579): every case
states the exact Spark answer and asserts the TPU columnar eval matches."""

import math

import numpy as np
import pytest

from spark_rapids_tpu.types import (
    BOOLEAN, DOUBLE, FLOAT, INT, LONG, STRING, Schema,
)
from spark_rapids_tpu.columnar import ColumnarBatch
from spark_rapids_tpu.expr import (
    Abs, Add, And, BRound, CaseWhen, Cast, Coalesce, Contains, Divide,
    EndsWith, EqualNullSafe, EqualTo, Greatest, If, In, IntegralDivide, IsNaN,
    IsNotNull, IsNull, Least, Length, LessThan, Lower, Murmur3Hash, NaNvl, Not,
    Or, Pmod, Remainder, Round, Sqrt, StartsWith, Substring, Upper, XxHash64,
    col, lit, resolve,
)


def ev(expr, batch):
    bound = resolve(expr, batch.schema)
    c = bound.columnar_eval(batch)
    return c.to_pylist(batch.num_rows_host)


@pytest.fixture
def batch():
    return ColumnarBatch.from_pydict(
        {
            "i": [1, None, 3, -4, 0],
            "j": [10, 20, None, 2, 0],
            "x": [1.0, 2.5, None, -8.0, float("nan")],
            "s": ["Apple", "banana", None, "", "Cherry pie"],
            "b": [True, False, None, True, False],
        },
        Schema.of(i=INT, j=LONG, x=DOUBLE, s=STRING, b=BOOLEAN),
    )


def test_add_nulls(batch):
    assert ev(col("i") + col("j"), batch) == [11, None, None, -2, 0]


def test_subtract_multiply(batch):
    assert ev(col("j") - col("i"), batch) == [9, None, None, 6, 0]
    assert ev(col("i") * lit(3), batch) == [3, None, 9, -12, 0]


def test_divide_by_zero_is_null(batch):
    # Spark: 1/0 -> NULL (non-ANSI), fractional division
    out = ev(col("i") / col("j"), batch)
    assert out[0] == pytest.approx(0.1)
    assert out[1] is None and out[2] is None
    assert out[3] == pytest.approx(-2.0)
    assert out[4] is None  # 0/0 -> NULL


def test_integral_divide(batch):
    assert ev(IntegralDivide(col("j"), col("i")), batch) == [10, None, None, 0, None]
    # truncation toward zero: -7 div 2 = -3 (Java), not -4
    b = ColumnarBatch.from_pydict({"a": [-7], "b": [2]}, Schema.of(a=INT, b=INT))
    assert ev(IntegralDivide(col("a"), col("b")), b) == [-3]


def test_remainder_sign(batch):
    b = ColumnarBatch.from_pydict({"a": [-7, 7, -7, 7], "b": [2, -2, -2, 2]},
                                  Schema.of(a=INT, b=INT))
    # Java %: sign of dividend
    assert ev(col("a") % col("b"), b) == [-1, 1, -1, 1]
    # Spark Pmod formula (arithmetic.scala): r = a % n; r<0 ? (r+n)%n : r
    # — for n<0 the result can stay negative: pmod(-7,-2) = -1 in Spark
    assert ev(Pmod(col("a"), col("b")), b) == [1, 1, -1, 1]


def test_comparisons(batch):
    assert ev(col("i") < col("j"), batch) == [True, None, None, True, False]
    assert ev(EqualNullSafe(col("i"), col("j")), batch) == \
        [False, False, False, False, True]


def test_three_valued_logic():
    b = ColumnarBatch.from_pydict(
        {"p": [True, True, True, False, False, False, None, None, None],
         "q": [True, False, None, True, False, None, True, False, None]},
        Schema.of(p=BOOLEAN, q=BOOLEAN))
    assert ev(And(col("p"), col("q")), b) == \
        [True, False, None, False, False, False, None, False, None]
    assert ev(Or(col("p"), col("q")), b) == \
        [True, True, True, True, False, None, True, None, None]
    assert ev(Not(col("p")), b) == \
        [False, False, False, True, True, True, None, None, None]


def test_null_predicates(batch):
    assert ev(IsNull(col("i")), batch) == [False, True, False, False, False]
    assert ev(IsNotNull(col("i")), batch) == [True, False, True, True, True]


def test_in(batch):
    assert ev(In(col("i"), [1, 3]), batch) == [True, None, True, False, False]
    # IN with null element: misses become NULL
    assert ev(In(col("i"), [1, None]), batch) == [True, None, None, None, None]


def test_if_casewhen(batch):
    e = If(col("i") > lit(0), lit("pos"), lit("neg"))
    assert ev(e, batch) == ["pos", "neg", "pos", "neg", "neg"]
    cw = CaseWhen([(col("i") > lit(1), lit(100)), (col("i") > lit(-10), lit(200))])
    assert ev(cw, batch) == [200, None, 100, 200, 200]


def test_coalesce(batch):
    assert ev(Coalesce(col("i"), col("j")), batch) == [1, 20, 3, -4, 0]


def test_nan(batch):
    assert ev(IsNaN(col("x")), batch) == [False, False, False, False, True]
    out = ev(NaNvl(col("x"), lit(9.0)), batch)
    assert out == [1.0, 2.5, None, -8.0, 9.0]


def test_least_greatest(batch):
    assert ev(Least(col("i"), col("j")), batch) == [1, 20, 3, -4, 0]
    assert ev(Greatest(col("i"), col("j")), batch) == [10, 20, 3, 2, 0]


def test_math(batch):
    out = ev(Sqrt(col("x")), batch)
    assert out[0] == 1.0 and out[1] == pytest.approx(math.sqrt(2.5))
    assert ev(Abs(col("i")), batch) == [1, None, 3, 4, 0]


def test_round():
    b = ColumnarBatch.from_pydict(
        {"x": [2.5, 3.5, -2.5, 1.25, 1.35]}, Schema.of(x=DOUBLE))
    # Spark round = HALF_UP (away from zero). Float rounding is approximate
    # on accelerators — the reference documents the same divergence for GPU
    # round (reference docs/compatibility.md, floating point section).
    assert ev(Round(col("x"), 0), b) == [3.0, 4.0, -3.0, 1.0, 1.0]
    assert ev(Round(col("x"), 1), b) == pytest.approx([2.5, 3.5, -2.5, 1.3, 1.4])
    # bround = HALF_EVEN
    assert ev(BRound(col("x"), 0), b) == [2.0, 4.0, -2.0, 1.0, 1.0]


def test_string_funcs(batch):
    assert ev(Upper(col("s")), batch) == ["APPLE", "BANANA", None, "", "CHERRY PIE"]
    assert ev(Lower(col("s")), batch) == ["apple", "banana", None, "", "cherry pie"]
    assert ev(Length(col("s")), batch) == [5, 6, None, 0, 10]
    assert ev(StartsWith(col("s"), "Ch"), batch) == [False, False, None, False, True]
    assert ev(EndsWith(col("s"), "e"), batch) == [True, False, None, False, True]
    assert ev(Contains(col("s"), "an"), batch) == [False, True, None, False, False]
    assert ev(Substring(col("s"), 2, 3), batch) == ["ppl", "ana", None, "", "her"]
    assert ev(Substring(col("s"), -3, None), batch) == ["ple", "ana", None, "", "pie"]


def test_string_compare(batch):
    assert ev(col("s") == lit("banana"), batch) == [False, True, None, False, False]
    assert ev(col("s") < lit("b"), batch) == [True, False, None, True, True]


def test_length_utf8():
    b = ColumnarBatch.from_pydict({"s": ["héllo", "日本語", "a"]},
                                  Schema.of(s=STRING))
    assert ev(Length(col("s")), b) == [5, 3, 1]


def test_cast_numeric():
    b = ColumnarBatch.from_pydict(
        {"x": [1.9, -1.9, float("nan"), 1e20]}, Schema.of(x=DOUBLE))
    # Spark double->int: truncate, NaN->0, saturate
    assert ev(Cast(col("x"), INT), b) == [1, -1, 0, 2**31 - 1]


def test_cast_string_to_int():
    b = ColumnarBatch.from_pydict(
        {"s": ["42", " -7 ", "3.5", "abc", "", None, "99999999999999999999"]},
        Schema.of(s=STRING))
    assert ev(Cast(col("s"), INT), b) == [42, -7, None, None, None, None, None]


def test_cast_string_to_double():
    b = ColumnarBatch.from_pydict(
        {"s": ["1.5", "-2e3", "NaN", "Infinity", "x", None]},
        Schema.of(s=STRING))
    out = ev(Cast(col("s"), DOUBLE), b)
    assert out[0] == 1.5 and out[1] == -2000.0
    assert math.isnan(out[2]) and out[3] == math.inf
    assert out[4] is None and out[5] is None


def test_cast_int_to_string():
    b = ColumnarBatch.from_pydict(
        {"i": [0, 7, -123, 2**31 - 1, None]}, Schema.of(i=INT))
    assert ev(Cast(col("i"), STRING), b) == ["0", "7", "-123", "2147483647", None]


def test_cast_bool_string():
    b = ColumnarBatch.from_pydict({"b": [True, False, None]}, Schema.of(b=BOOLEAN))
    assert ev(Cast(col("b"), STRING), b) == ["true", "false", None]
    s = ColumnarBatch.from_pydict({"s": ["true", "NO", "1", "zz", None]},
                                  Schema.of(s=STRING))
    assert ev(Cast(col("s"), BOOLEAN), s) == [True, False, True, None, None]
