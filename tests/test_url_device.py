"""Device parse_url (ops/url.py) vs the host urlparse tier."""
import random

import pytest

from spark_rapids_tpu.columnar.column import StringColumn
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.expr.urlexprs import ParseUrl
from spark_rapids_tpu.ops.url import parse_url

URLS = [
    "https://user:pw@example.com:8443/p/a?x=1&y=2#frag",
    "http://spark.apache.org/path",
    "http://example.com",
    "ftp://host/file.txt",
    "https://Example.COM/UP?a=b",
    "http://example.com/?",
    "http://example.com/#",
    "no-scheme-just-text",
    "/relative/path?q=v",
    "http://[::1]:8080/x",
    "http://user@h.io/",
    None,
    "",
    "HTTPS://U:P@H.COM/Q?k=v+w%21#z",
    "http://h/p?a=1&a=2&b=",
    "http://h/p?key",
    "mailto:someone@example.com",
]


@pytest.mark.parametrize("part", ["HOST", "PATH", "QUERY", "REF",
                                  "PROTOCOL", "FILE", "AUTHORITY",
                                  "USERINFO", "host"])
def test_parts_match_host_tier(part):
    sc = StringColumn.from_pylist(URLS)
    expr = ParseUrl(col("u"), part)
    host = [expr.host_eval_row(u) for u in URLS]
    assert parse_url(sc, part).to_pylist(len(URLS)) == host


@pytest.mark.parametrize("key", ["x", "a", "b", "key", "k", "missing"])
def test_query_key_match_host_tier(key):
    sc = StringColumn.from_pylist(URLS)
    expr = ParseUrl(col("u"), "QUERY", key)
    host = [expr.host_eval_row(u) for u in URLS]
    assert parse_url(sc, "QUERY", key).to_pylist(len(URLS)) == host


def test_fuzz_realistic_urls():
    rng = random.Random(8)
    urls = []
    for _ in range(80):
        u = rng.choice(["http", "https", "ftp", "s3a"]) + "://"
        if rng.random() < 0.3:
            u += f"user{rng.randint(0, 9)}@"
        u += rng.choice(["host.example.com", "h", "a.b.c.d"])
        if rng.random() < 0.4:
            u += f":{rng.randint(1, 65000)}"
        u += "/" + "/".join(f"p{i}" for i in range(rng.randint(0, 3)))
        if rng.random() < 0.5:
            u += "?" + "&".join(f"k{i}={rng.randint(0, 99)}"
                                for i in range(rng.randint(1, 3)))
        if rng.random() < 0.3:
            u += "#sec" + str(rng.randint(0, 9))
        urls.append(u)
    sc = StringColumn.from_pylist(urls)
    for part in ("HOST", "PATH", "QUERY", "PROTOCOL", "AUTHORITY",
                 "USERINFO", "FILE", "REF"):
        expr = ParseUrl(col("u"), part)
        host = [expr.host_eval_row(u) for u in urls]
        assert parse_url(sc, part).to_pylist(len(urls)) == host, part
    for key in ("k0", "k2", "zz"):
        expr = ParseUrl(col("u"), "QUERY", key)
        host = [expr.host_eval_row(u) for u in urls]
        assert parse_url(sc, "QUERY", key).to_pylist(len(urls)) == host


def test_planner_routes_parse_url_to_device():
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.types import STRING, Schema, StructField
    sess = TpuSession()
    df = sess.from_pydict(
        {"u": ["https://h.io/p?a=1", None]},
        schema=Schema((StructField("u", STRING),)))
    q = df.select(F.parse_url(F.col("u"), "HOST").alias("h"),
                  F.parse_url(F.col("u"), "QUERY", "a").alias("a"))
    assert "host row engine" not in q.explain()
    assert q.collect() == [("h.io", "1"), (None, None)]
