"""Planner-integrated exchange tests: with a session mesh, group-bys plan
as partial → ShuffleExchangeExec → final and equi-joins as
exchange-both-sides → per-partition ShuffledHashJoinExec, and results match
the single-partition plan exactly (reference analog:
GpuShuffleExchangeExecBase + GpuShuffledHashJoinExec integration tests).

Marked `slow`: each case drives the 8-virtual-device mesh end to end
(minutes on one core); the fast distributed-primitive coverage stays in
tier-1 via tests/test_parallel.py."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                             reason="needs 8 virtual devices")


def _sorted(rows):
    return sorted(rows, key=lambda r: tuple((x is None, x) for x in r))


def _data(rng, n=400, n_keys=7):
    return {
        "k": [int(x) for x in rng.integers(0, n_keys, n)],
        "s": [["alpha", "bravo", "charlie", None][int(x)]
              for x in rng.integers(0, 4, n)],
        "v": [int(x) for x in rng.integers(-50, 50, n)],
        "d": [float(x) for x in rng.normal(0, 10, n)],
    }


def _schema():
    from spark_rapids_tpu.types import (
        DOUBLE, LONG, STRING, Schema, StructField,
    )
    return Schema((StructField("k", LONG), StructField("s", STRING),
                   StructField("v", LONG), StructField("d", DOUBLE)))


def _both_sessions():
    # broadcast planning off: these tests cover the shuffled-exchange path
    # (tiny inputs would all fall under the broadcast threshold otherwise);
    # broadcast planning has its own suite in test_broadcast.py
    no_bcast = {"spark.rapids.sql.broadcastSizeThreshold": "-1"}
    return TpuSession(no_bcast), TpuSession(no_bcast, mesh_devices=8)


@needs_8
def test_plan_contains_exchange():
    single, dist = _both_sessions()
    rng = np.random.default_rng(0)
    data, sch = _data(rng), _schema()
    df = dist.from_pydict(data, sch, batch_rows=64)
    tree = df.group_by("k").agg((F.sum("v"), "sv"))._exec().tree_string()
    assert "ShuffleExchangeExec" in tree
    assert "AggregateExec[partial" in tree
    assert "AggregateExec[final" in tree
    # single-partition session: no exchange nodes
    df1 = single.from_pydict(data, sch, batch_rows=64)
    tree1 = df1.group_by("k").agg((F.sum("v"), "sv"))._exec().tree_string()
    assert "ShuffleExchangeExec" not in tree1


@needs_8
def test_distributed_groupby_matches_single():
    single, dist = _both_sessions()
    rng = np.random.default_rng(1)
    data, sch = _data(rng), _schema()

    def run(sess):
        df = sess.from_pydict(data, sch, batch_rows=64)
        return _sorted(df.group_by("k").agg(
            (F.sum("v"), "sv"), (F.count(), "c"), (F.min("d"), "mn"),
            (F.max("d"), "mx"), (F.avg("v"), "av")).collect())

    assert run(dist) == run(single)


@needs_8
def test_distributed_groupby_string_keys():
    single, dist = _both_sessions()
    rng = np.random.default_rng(2)
    data, sch = _data(rng), _schema()

    def run(sess):
        df = sess.from_pydict(data, sch, batch_rows=64)
        return _sorted(df.group_by("s").agg(
            (F.sum("v"), "sv"), (F.count(), "c")).collect())

    assert run(dist) == run(single)


@needs_8
def test_distributed_groupby_long_string_keys():
    """Keys > 64 bytes: the measured exchange width must prevent the
    fixed-width codec from truncating (review finding r1)."""
    single, dist = _both_sessions()
    from spark_rapids_tpu.types import LONG, STRING, Schema, StructField
    base = "x" * 100
    rng = np.random.default_rng(3)
    ks = [base + ["AA", "BB", "CC"][int(i)] for i in rng.integers(0, 3, 96)]
    vs = [int(x) for x in rng.integers(0, 9, 96)]
    sch = Schema((StructField("k", STRING), StructField("v", LONG)))
    data = {"k": ks, "v": vs}

    def run(sess):
        df = sess.from_pydict(data, sch, batch_rows=16)
        return _sorted(df.group_by("k").agg((F.sum("v"), "sv")).collect())

    assert run(dist) == run(single)


@needs_8
def test_distributed_groupby_skewed_keys():
    """All rows in one key → one partition takes everything; the measured
    slot capacity must still fit every row."""
    single, dist = _both_sessions()
    from spark_rapids_tpu.types import LONG, Schema, StructField
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    data = {"k": [5] * 300, "v": list(range(300))}

    def run(sess):
        df = sess.from_pydict(data, sch, batch_rows=64)
        return df.group_by("k").agg((F.sum("v"), "sv"),
                                    (F.count(), "c")).collect()

    assert run(dist) == run(single) == [(5, sum(range(300)), 300)]


@needs_8
def test_distributed_distinct():
    single, dist = _both_sessions()
    rng = np.random.default_rng(4)
    data, sch = _data(rng), _schema()

    def run(sess):
        df = sess.from_pydict(data, sch, batch_rows=64)
        return _sorted(df.select("k", "s").distinct().collect())

    assert run(dist) == run(single)


@needs_8
@pytest.mark.parametrize("how", ["inner", "left_outer", "right_outer",
                                 "full_outer", "left_semi", "left_anti"])
def test_distributed_join_matches_single(how):
    single, dist = _both_sessions()
    from spark_rapids_tpu.types import LONG, STRING, Schema, StructField
    rng = np.random.default_rng(5)
    lsch = Schema((StructField("k", LONG), StructField("lv", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("rv", STRING)))
    ldata = {"k": [int(x) for x in rng.integers(0, 30, 200)],
             "lv": [int(x) for x in rng.integers(0, 1000, 200)]}
    rdata = {"k": [int(x) for x in rng.integers(10, 40, 150)],
             "rv": [f"r{int(x)}" for x in rng.integers(0, 99, 150)]}

    def run(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=64)
        r = sess.from_pydict(rdata, rsch, batch_rows=64)
        return _sorted(l.join(r, on="k", how=how).collect())

    assert run(dist) == run(single)


@needs_8
def test_distributed_join_plan_shape():
    single, dist = _both_sessions()
    from spark_rapids_tpu.types import LONG, Schema, StructField
    lsch = Schema((StructField("k", LONG), StructField("lv", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("rv", LONG)))
    l = dist.from_pydict({"k": [1, 2, 3], "lv": [10, 20, 30]}, lsch)
    r = dist.from_pydict({"k": [1, 2, 3], "rv": [10, 20, 30]}, rsch)
    tree = l.join(r, on="k")._exec().tree_string()
    assert "ShuffledHashJoinExec" in tree
    assert tree.count("ShuffleExchangeExec") == 2


@needs_8
def test_distributed_join_with_condition():
    single, dist = _both_sessions()
    from spark_rapids_tpu.types import LONG, Schema, StructField
    rng = np.random.default_rng(6)
    lsch = Schema((StructField("k", LONG), StructField("lv", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("rv", LONG)))
    ldata = {"k": [int(x) for x in rng.integers(0, 10, 80)],
             "lv": [int(x) for x in rng.integers(0, 100, 80)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 10, 80)],
             "rv": [int(x) for x in rng.integers(0, 100, 80)]}

    def run(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=32)
        r = sess.from_pydict(rdata, rsch, batch_rows=32)
        return _sorted(l.join(r, on="k",
                              condition=col("lv") > col("rv")).collect())

    assert run(dist) == run(single)


@needs_8
def test_groupby_after_join_distributed():
    """Exchange → join → exchange → aggregate, the canonical 2-stage plan."""
    single, dist = _both_sessions()
    from spark_rapids_tpu.types import LONG, Schema, StructField
    rng = np.random.default_rng(7)
    lsch = Schema((StructField("k", LONG), StructField("g", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", LONG)))
    ldata = {"k": [int(x) for x in rng.integers(0, 25, 150)],
             "g": [int(x) for x in rng.integers(0, 5, 150)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 25, 100)],
             "w": [int(x) for x in rng.integers(1, 10, 100)]}

    def run(sess):
        l = sess.from_pydict(ldata, lsch, batch_rows=64)
        r = sess.from_pydict(rdata, rsch, batch_rows=64)
        j = l.join(r, on="k")
        return _sorted(j.group_by("g").agg((F.sum("w"), "sw"),
                                           (F.count(), "c")).collect())

    assert run(dist) == run(single)


@needs_8
def test_shuffle_plan_exchange_disabled():
    sess = TpuSession({"spark.rapids.tpu.shuffle.planExchange": False},
                      mesh_devices=8)
    from spark_rapids_tpu.types import LONG, Schema, StructField
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    df = sess.from_pydict({"k": [1, 1, 2], "v": [1, 2, 3]}, sch)
    tree = df.group_by("k").agg((F.sum("v"), "sv"))._exec().tree_string()
    assert "ShuffleExchangeExec" not in tree
    assert _sorted(df.group_by("k").agg((F.sum("v"), "sv")).collect()) \
        == [(1, 3), (2, 3)]


def _kv_schema():
    from spark_rapids_tpu.types import LONG, STRING, Schema, StructField
    return Schema((StructField("k", LONG), StructField("tag", STRING)))


@needs_8
def test_exchange_streams_in_bounded_rounds():
    # input many times the per-round budget: the exchange must run
    # MULTIPLE rounds with spillable staging, and results stay exact
    single, _ = _both_sessions()
    dist = TpuSession({"spark.rapids.sql.broadcastSizeThreshold": "-1",
                       "spark.rapids.sql.exchange.roundBytes": "16384",
                       # keep the upstream coalescer from folding the
                       # whole input into one batch before the exchange
                       "spark.rapids.sql.batchSizeBytes": "8192"},
                      mesh_devices=8)
    rng = np.random.default_rng(5)
    data, sch = _data(rng, n=1600), _schema()
    # joins exchange RAW rows (a partial aggregate would collapse to one
    # tiny state batch before the exchange)
    left = dist.from_pydict(data, sch, batch_rows=64)
    right = dist.from_pydict(
        {"k": list(range(7)), "tag": [f"t{i}" for i in range(7)]},
        _kv_schema(), batch_rows=64)
    q = left.join(right, on="k", how="inner")
    ex = q._exec()
    got = _sorted(ex.collect())
    sl = single.from_pydict(data, sch, batch_rows=64)
    sr = single.from_pydict(
        {"k": list(range(7)), "tag": [f"t{i}" for i in range(7)]},
        _kv_schema(), batch_rows=64)
    ref = _sorted(sl.join(sr, on="k", how="inner").collect())
    assert got == ref

    def find_exchanges(e, out):
        from spark_rapids_tpu.exec.exchange import ShuffleExchangeExec
        if isinstance(e, ShuffleExchangeExec):
            out.append(e)
        for c in e.children:
            find_exchanges(c, out)
        return out
    exchanges = find_exchanges(ex, [])
    assert exchanges, "no exchange planned"
    rounds = [getattr(x, "rounds", 0) for x in exchanges]
    assert max(rounds) > 1, rounds  # the big side ran multiple rounds
