"""Query-profile surface (ISSUE 2 tentpole part 3): the golden text
renderer, the session last_query_profile() API, and the
spark.rapids.sql.metrics.level visibility cut (satellite: DEBUG metrics
stay out of summaries by default, reference GpuExec.scala:36-47)."""

import json

import numpy as np
import pytest

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.basic import FilterExec, InMemoryScanExec
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.obs.profile import QueryProfile
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField


def _session_query(sess, n=3000):
    rng = np.random.default_rng(0)
    schema = Schema((StructField("k", INT), StructField("q", LONG),
                     StructField("p", DOUBLE)))
    df = sess.from_pydict({"k": rng.integers(0, 6, n).tolist(),
                           "q": rng.integers(1, 50, n).tolist(),
                           "p": (rng.random(n) * 10).tolist()}, schema)
    return (df.filter(col("q") <= lit(40))
              .group_by("k").agg((Sum(col("p")), "s"), (Count(), "c")))


def test_text_renderer_golden():
    """Exact explain-with-metrics output for a hand-built tree with
    pinned metric values — the renderer's format is a surface other
    tooling greps, so it is golden-tested."""
    schema = Schema((StructField("x", LONG),))
    batch = ColumnarBatch.from_pydict({"x": [1, 2, 3]}, schema)
    scan = InMemoryScanExec([batch], schema)
    filt = FilterExec(col("x") > lit(1), scan)
    filt.metrics["numOutputRows"].value = 2
    filt.metrics["numOutputBatches"].value = 1
    filt.metrics["opTime"].value = 2_000_000
    scan.metrics["numOutputRows"].value = 3
    scan.metrics["numOutputBatches"].value = 1
    scan.metrics["opTime"].value = 1_500
    prof = QueryProfile(filt, {"semWaitTimeNs": 1_000, "retryCount": 1,
                               "spilledDeviceBytes": 2048})
    expected = """== TPU Query Profile ==
task: semWaitTimeNs=1.0us retryCount=1 spilledDeviceBytes=2.0KB
FilterExec[(col('x') > lit(1))]
  + compileTimeNs: 0ns, gatherTimeNs: 0ns, numDispatches: 0, numGathers: 0, numOutputBatches: 1, numOutputRows: 2, opTime: 2.0ms
  InMemoryScanExec
    + numOutputBatches: 1, numOutputRows: 3, opTime: 1.5us"""
    assert prof.text() == expected
    # the JSON renderer round-trips the same tree
    doc = json.loads(prof.to_json())
    assert doc["plan"]["op"] == "FilterExec"
    assert doc["plan"]["children"][0]["metrics"]["numOutputRows"] == 3
    assert doc["summary"]["retryCount"] == 1


def test_session_profile_surface():
    sess = TpuSession()
    assert sess.last_query_profile() is None
    rows = _session_query(sess).collect()
    prof = sess.last_query_profile()
    assert prof is not None
    text = prof.text()
    assert "AggregateExec" in text and "numOutputRows" in text
    top = prof.top_operators(3)
    assert top and top[0]["time_ns"] >= top[-1]["time_ns"]
    assert {"op", "op_id", "rows", "batches"} <= set(top[0])
    # tree totals agree with the metric roll-up surface (ISSUE 14: the
    # filter+group-by chain now compiles to a CompiledStageExec whose
    # description still names the absorbed AggregateExec)
    m = sess.last_query_metrics()
    agg_rows = [n for n in _walk(prof.tree)
                if n["op"] in ("AggregateExec", "CompiledStageExec")]
    assert agg_rows[0]["metrics"]["numOutputRows"] == len(rows)
    assert m["total.numOutputRows"] >= len(rows)


def _walk(node):
    yield node
    for c in node["children"]:
        yield from _walk(c)


def test_metrics_level_filters_summaries():
    """satellite: spark.rapids.sql.metrics.level gates all_metrics() /
    last_query_metrics(). DEBUG shows per-op input counts, MODERATE
    (default) hides them, ESSENTIAL trims to row/batch counts."""
    sess = TpuSession()
    q = _session_query(sess)
    q.collect()
    m = sess.last_query_metrics()
    assert "total.computeAggTime" in m          # MODERATE visible
    assert not any(k.endswith(".numInputRows") for k in m)  # DEBUG hidden

    sess_dbg = TpuSession({"spark.rapids.sql.metrics.level": "DEBUG"})
    _session_query(sess_dbg).collect()
    m_dbg = sess_dbg.last_query_metrics()
    assert any(k.endswith(".numInputRows") for k in m_dbg)
    assert "total.numInputBatches" in m_dbg

    # ESSENTIAL: metric KEYS are the cut, so the conversion alone (no
    # re-execution/compile) exercises both the conf-driven and the
    # explicit-level paths
    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.exec.base import DEBUG as DBG, ESSENTIAL as ESS
    plan = q._exec()
    try:
        set_active_conf(RapidsConf(
            {"spark.rapids.sql.metrics.level": "ESSENTIAL"}))
        m_ess = plan.all_metrics()              # conf-driven cut
        assert any(k.endswith(".numOutputRows") for k in m_ess)
        assert not any(k.endswith((".computeAggTime", ".opTime"))
                       for k in m_ess)
        # explicit level overrides the conf
        assert any(k.endswith(".numInputRows")
                   for k in plan.all_metrics(level=DBG))
        assert all(k.endswith((".numOutputRows", ".numOutputBatches",
                               ".dataSize"))
                   for k in plan.all_metrics(level=ESS))
    finally:
        set_active_conf(sess.conf)


def test_sibling_operators_do_not_collide_in_roll_up():
    """Same-class siblings (every join has two scan-side subtrees) must
    keep distinct ops.* keys — the pre-fix walk collided them and one
    side's metrics silently vanished from the totals."""
    sess = TpuSession()
    l_schema = Schema((StructField("k", LONG), StructField("v", LONG)))
    r_schema = Schema((StructField("k2", LONG), StructField("w", LONG)))
    df_l = sess.from_pydict({"k": [1, 2, 3], "v": [10, 20, 30]}, l_schema)
    df_r = sess.from_pydict({"k2": [1, 2], "w": [7, 8]}, r_schema)
    out = df_l.join(df_r, left_on="k", right_on="k2").collect()
    assert len(out) == 2
    m = sess.last_query_metrics()
    scan_keys = [k for k in m if "SourceScanExec" in k
                 and k.endswith(".numOutputRows")]
    assert len(scan_keys) == 2, scan_keys       # both sides present
    assert sum(m[k] for k in scan_keys) == 3 + 2


def test_profile_respects_metrics_level():
    sess = TpuSession({"spark.rapids.sql.metrics.level": "ESSENTIAL"})
    _session_query(sess).collect()
    prof = sess.last_query_profile()
    for node in _walk(prof.tree):
        assert "opTime" not in node["metrics"]
        assert "numInputRows" not in node["metrics"]
