"""Device-side shuffle partitioning (ISSUE 9): serialize_slice
byte-equality against the gather formulation across every column
family, the one-pass device split (counts + stable permutation + packed
D2H), zero host-side gathers on the device lanes (structural), engine
on/off equality under the PR 3 forced-spill recipe, seeded
`shuffle.decode` injection placement invariance across lanes, the
`partition_split` kern_bench family, and the vectorized range-key
materialization."""

import decimal
import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.transfer import (fetch_batch_host,
                                                fetch_split_host)
from spark_rapids_tpu.shuffle import manager as shuffle_mgr
from spark_rapids_tpu.shuffle import serializer as ser
from spark_rapids_tpu.shuffle.manager import (HostShuffleReader,
                                              HostShuffleWriter,
                                              partition_batch_host,
                                              shuffle_manager)
from spark_rapids_tpu.types import (DOUBLE, INT, LONG, STRING, ArrayType,
                                    DecimalType, MapType, Schema,
                                    StructField, StructType)

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import kern_bench  # noqa: E402


@pytest.fixture(autouse=True)
def _isolation():
    prev = C.active_conf()
    faults.install(None)
    yield
    faults.install(None)
    C.set_active_conf(prev)


def _sorted(rows):
    return sorted(rows, key=repr)


def _rich_schema():
    return Schema((
        StructField("i", INT), StructField("l", LONG),
        StructField("d", DOUBLE), StructField("s", STRING),
        StructField("a", ArrayType(LONG)),
        StructField("m", MapType(LONG, STRING)),
        StructField("st", StructType((StructField("x", LONG),
                                      StructField("y", STRING)))),
        StructField("dec", DecimalType(30, 2)),
    ))


def _rich_host_batch(n=97):
    rng = np.random.default_rng(7)
    data = {
        "i": [None if x % 11 == 0 else int(x) for x in range(n)],
        "l": [int(x) for x in rng.integers(-10**12, 10**12, n)],
        "d": [None if x % 7 == 0 else float(rng.standard_normal())
              for x in range(n)],
        "s": [None if x % 5 == 0 else ("värde-%d" % x) * (x % 4)
              for x in range(n)],
        "a": [None if x % 9 == 0 else [int(v) for v in range(x % 5)]
              for x in range(n)],
        "m": [None if x % 8 == 0 else {int(k): f"v{k}"
                                       for k in range(x % 3)}
              for x in range(n)],
        "st": [None if x % 13 == 0 else {"x": int(x), "y": f"s{x}"}
               for x in range(n)],
        "dec": [None if x % 6 == 0
                else decimal.Decimal(x * 123456789).scaleb(-2)
                for x in range(n)],
    }
    batch = ColumnarBatch.from_pydict(data, _rich_schema())
    cols, nn = fetch_batch_host(batch)
    return ColumnarBatch(cols, nn, batch.schema), batch


# ---------------------------------------------------------------------------
# serializer: slice vs gather byte equality
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lo,hi", [(0, 0), (0, 97), (5, 5), (3, 41),
                                   (40, 97), (0, 1), (96, 97)])
def test_serialize_slice_matches_gather_bytes(lo, hi):
    """serialize_slice over any row range is byte-identical to
    serialize_batch over the gathered rows — across string/array/map/
    struct/decimal128 offsets, null masks and empty slices."""
    hb, _dev = _rich_host_batch()
    sliced = ser.serialize_slice(hb, lo, hi)
    gathered = ser.serialize_batch(
        ser.host_gather_batch(hb, np.arange(lo, hi)))
    assert sliced == gathered
    out = ser.deserialize_batch(sliced, hb.schema)
    assert out.to_pylist() == \
        ser.host_gather_batch(hb, np.arange(lo, hi)).to_pylist()


def test_host_slice_matches_gather_arrays():
    """host_slice_column reproduces host_gather_column's buckets and
    padding exactly (the byte-identity the frame equality rides on)."""
    import jax
    hb, _dev = _rich_host_batch()
    for lo, hi in [(0, 10), (17, 64), (0, 97), (96, 96)]:
        a = ser.host_slice_batch(hb, lo, hi)
        b = ser.host_gather_batch(hb, np.arange(lo, hi))
        la = jax.tree_util.tree_leaves(a.columns)
        lb = jax.tree_util.tree_leaves(b.columns)
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            assert x.shape == y.shape and x.dtype == y.dtype
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_partition_batch_host_stable_slices():
    """The rewritten host partitioner (ONE argsort + whole-batch gather
    + slice emission) keeps the per-partition stable-order contract."""
    hb, _dev = _rich_host_batch()
    n = hb.num_rows_host
    rng = np.random.default_rng(1)
    pid = rng.integers(0, 5, n)
    parts = partition_batch_host(hb, pid, 5)
    rows = hb.to_pylist()
    for p in range(5):
        expect = [rows[i] for i in range(n) if pid[i] == p]
        assert parts[p].to_pylist() == expect


# ---------------------------------------------------------------------------
# device split: counts + permutation + packed D2H + slice write
# ---------------------------------------------------------------------------

def _device_write(handle, mgr, batch, pid, map_id=0):
    """The device lane's write, driven at the writer API level: one
    traced split, one packed D2H, slice serialization."""
    import jax.numpy as jnp

    from spark_rapids_tpu.ops.partition_split import (partition_table,
                                                      reorder_columns)
    n = batch.num_rows_host
    cap = batch.capacity
    full_pid = np.full(cap, handle.n_partitions, np.int64)
    full_pid[:n] = pid
    counts, order = partition_table(jnp.asarray(full_pid),
                                    batch.num_rows, cap,
                                    handle.n_partitions)
    cols = reorder_columns(batch.columns, order, batch.num_rows)
    host_counts, host_cols = fetch_split_host(counts, cols)
    bounds = np.concatenate([[0], np.cumsum(host_counts)])
    packed = ColumnarBatch(host_cols, n, batch.schema)
    w = HostShuffleWriter(handle, map_id, mgr)
    w.write_slices(packed, bounds)
    return w


def _host_write(handle, mgr, batch, pid, map_id=0):
    parts = partition_batch_host(batch, pid, handle.n_partitions)
    w = HostShuffleWriter(handle, map_id, mgr)
    w.write([[p] if p.num_rows_host else [] for p in parts])
    return w


def test_device_and_host_lanes_decode_identically():
    """Same batch, same pids through both lanes: identical frame
    counts, identical per-partition decoded rows."""
    _hb, dev = _rich_host_batch()
    n = dev.num_rows_host
    rng = np.random.default_rng(2)
    pid = rng.integers(0, 3, n)
    mgr = shuffle_manager()
    rows = dev.to_pylist()
    got = {}
    for lane, write in (("device", _device_write), ("host", _host_write)):
        handle = mgr.register(3, dev.schema)
        try:
            w = write(handle, mgr, dev, pid)
            got[lane] = (w.frames_written, [
                [r for b in HostShuffleReader(handle, mgr)
                 .read_partition(p) for r in b.to_pylist()]
                for p in range(3)])
        finally:
            mgr.unregister(handle)
    assert got["device"][0] == got["host"][0]
    assert got["device"][1] == got["host"][1]
    for p in range(3):
        expect = [rows[i] for i in range(n) if pid[i] == p]
        assert got["device"][1][p] == expect


def test_seeded_decode_injection_placement_unchanged_by_lane():
    """The chaos contract (PR 4/5): `shuffle.decode` verdicts key on
    (partition, global frame ordinal). The device lane preserves frame
    count and order, so a seeded corrupt plan must quarantine exactly
    the same frames as the host lane."""
    _hb, dev = _rich_host_batch()
    n = dev.num_rows_host
    rng = np.random.default_rng(3)
    pid = rng.integers(0, 4, n)
    mgr = shuffle_manager()
    spec = "shuffle.decode:prob=0.4,seed=11,kind=corrupt"
    outcomes = {}
    for lane, write in (("device", _device_write), ("host", _host_write)):
        handle = mgr.register(4, dev.schema)
        try:
            # two map tasks so global frame ordinals span map outputs
            write(handle, mgr, dev, pid, map_id=0)
            write(handle, mgr, dev, pid, map_id=1)
            faults.install(spec)
            r = HostShuffleReader(handle, mgr)
            corrupted = set()
            ok_rows = []
            for p in range(4):
                ordinal = 0
                for path in handle.map_outputs:
                    for fr in r._fetch_segment(path, p):
                        try:
                            b = r._decode(fr, f"p{p}:{ordinal}")
                            ok_rows.extend(b.to_pylist())
                        except faults.IntegrityError:
                            corrupted.add((p, ordinal))
                        ordinal += 1
            outcomes[lane] = (corrupted, _sorted(ok_rows))
        finally:
            faults.install(None)
            mgr.unregister(handle)
    assert outcomes["device"][0], "the seeded plan never fired"
    assert outcomes["device"][0] == outcomes["host"][0]
    assert outcomes["device"][1] == outcomes["host"][1]


# ---------------------------------------------------------------------------
# exchange integration: zero host gathers, on/off equality, events
# ---------------------------------------------------------------------------

def _join_query(sess, seed=4):
    from spark_rapids_tpu.api.session import TpuSession  # noqa: F401
    rng = np.random.default_rng(seed)
    ldata = {"k": [int(x) for x in rng.integers(0, 20, 300)],
             "v": [int(x) for x in rng.integers(0, 50, 300)]}
    rdata = {"k": [int(x) for x in rng.integers(0, 20, 200)],
             "w": [["a", "bb", None, "dddd"][int(x)]
                   for x in rng.integers(0, 4, 200)]}
    lsch = Schema((StructField("k", LONG), StructField("v", LONG)))
    rsch = Schema((StructField("k", LONG), StructField("w", STRING)))
    l = sess.from_pydict(ldata, lsch, batch_rows=64)
    r = sess.from_pydict(rdata, rsch, batch_rows=64)
    return l.join(r, on="k")


def test_hash_lane_pins_host_gathers_at_zero():
    """Acceptance (ISSUE 9): with devicePartition on (the default), the
    hash lane performs ZERO host-side row gathers per written batch —
    asserted structurally on the serializer's host-gather counter over
    a whole host-shuffled join."""
    from spark_rapids_tpu.api.session import TpuSession
    sess = TpuSession({"spark.rapids.sql.shuffle.partitions": "4",
                       "spark.rapids.sql.broadcastSizeThreshold": "-1"})
    q = _join_query(sess)
    before = ser.host_gather_calls()
    got = q.collect()
    assert got  # the query actually ran
    assert ser.host_gather_calls() == before, \
        "device-partition lane fell back to host gathers"


# moved to the slow tier by ISSUE 13 budget relief (18s: three full
# query runs; slice-vs-gather byte equality keeps the lane proven
# tier-1)
@pytest.mark.slow
def test_conf_off_restores_host_lane_and_results_match():
    from spark_rapids_tpu.api.session import TpuSession
    base = {"spark.rapids.sql.shuffle.partitions": "4",
            "spark.rapids.sql.broadcastSizeThreshold": "-1"}
    on = _join_query(TpuSession(base)).collect()
    off_sess = TpuSession(dict(
        base, **{"spark.rapids.tpu.shuffle.devicePartition.enabled":
                 "false"}))
    before = ser.host_gather_calls()
    off = _join_query(off_sess).collect()
    assert ser.host_gather_calls() > before  # host lane engaged
    plain = _join_query(__import__(
        "spark_rapids_tpu.api.session", fromlist=["TpuSession"]
    ).TpuSession()).collect()
    assert _sorted(on) == _sorted(off) == _sorted(plain)


def test_roundrobin_and_single_ride_device_lane():
    from spark_rapids_tpu.api.session import TpuSession
    rng = np.random.default_rng(0)
    sch = Schema((StructField("k", LONG), StructField("s", STRING)))
    data = {"k": [int(x) for x in rng.integers(-100, 100, 300)],
            "s": [None if x % 7 == 0 else f"v{x}"
                  for x in rng.integers(0, 60, 300)]}
    sess = TpuSession()
    df = sess.from_pydict(data, sch, batch_rows=64)
    before = ser.host_gather_calls()
    rr = df.repartition(4).collect()
    single = df.coalesce(1).collect()
    assert ser.host_gather_calls() == before
    assert _sorted(rr) == _sorted(single) == _sorted(df.collect())
    off = TpuSession({
        "spark.rapids.tpu.shuffle.devicePartition.enabled": "false"})
    df_off = off.from_pydict(data, sch, batch_rows=64)
    assert _sorted(df_off.repartition(4).collect()) == _sorted(rr)


def test_forced_spill_recipe_on_off_equality(tmp_path):
    """Engine-level equality under the PR 3 forced-spill recipe (tiny
    host spill limit + spill dir + small batches): the host-shuffled
    join and the range-partitioned global sort return identical rows
    with the device lane on and off."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.memory.budget import (reset_memory_budget)
    from spark_rapids_tpu.memory.catalog import reset_buffer_catalog
    base = {
        "spark.rapids.sql.shuffle.partitions": "3",
        "spark.rapids.sql.broadcastSizeThreshold": "-1",
        "spark.rapids.sql.batchSizeBytes": str(16 * 1024),
        "spark.rapids.memory.host.spillStorageSize": "1k",
        "spark.rapids.memory.spillDirectory": str(tmp_path),
    }
    off = dict(base, **{
        "spark.rapids.tpu.shuffle.devicePartition.enabled": "false"})
    try:
        reset_buffer_catalog()
        reset_memory_budget(256 * 1024)

        def drive(settings):
            sess = TpuSession(settings)
            join_rows = _join_query(sess, seed=9).collect()
            rng = np.random.default_rng(5)
            sch = Schema((StructField("k", LONG),
                          StructField("s", STRING)))
            data = {"k": [int(x) for x in rng.integers(-50, 50, 400)],
                    "s": [None if x % 7 == 0 else f"v{x}"
                          for x in rng.integers(0, 60, 400)]}
            df = sess.from_pydict(data, sch, batch_rows=64)
            sort_rows = df.sort("k").collect()
            return join_rows, sort_rows

        j_on, s_on = drive(base)
        j_off, s_off = drive(off)
        assert _sorted(j_on) == _sorted(j_off)
        assert [r[0] for r in s_on] == [r[0] for r in s_off] \
            == sorted(r[0] for r in s_on)
    finally:
        reset_buffer_catalog()
        reset_memory_budget()


def test_shuffle_write_event_and_metrics(monkeypatch, tmp_path):
    """One shuffle_write event per map task, lane=device, with the
    pack/serialize/io split, one gather_stats record per execution;
    shufflePackTimeNs and numGathers register on the exchange."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.obs import events
    rows = []
    real = events.emit

    def spy(kind, **fields):
        rows.append({"kind": kind, **fields})
        real(kind, **fields)

    monkeypatch.setattr(events, "emit", spy)
    # a live bus: GatherTracker.emit_event short-circuits without one
    events.enable(str(tmp_path), "MODERATE")
    try:
        sess = TpuSession({"spark.rapids.sql.shuffle.partitions": "3",
                           "spark.rapids.sql.broadcastSizeThreshold":
                               "-1"})
        q = _join_query(sess)
        plan = q._exec()
        out = [r for gen_b in plan.execute()
               for r in gen_b.to_pylist()]
        assert out
        writes = [r for r in rows if r["kind"] == "shuffle_write"]
        assert writes and all(w["lane"] == "device" for w in writes)
        assert all(w["frames"] >= 1 and w["bytes"] > 0 for w in writes)
        # the exchange follows the wired-exec convention: one
        # gather_stats record per execution covering the write phase
        gstats = [r for r in rows if r["kind"] == "gather_stats"
                  and r.get("op") == "HostShuffleExchangeExec"]
        assert gstats and all(g["count"] >= 1 for g in gstats)
        metrics = plan.all_metrics(level=2)
        packs = {k: v for k, v in metrics.items()
                 if k.endswith("shufflePackTimeNs")}
        assert packs and any(v > 0 for v in packs.values())
        gathers = {k: v for k, v in metrics.items()
                   if "HostShuffleExchangeExec" in k
                   and k.endswith("numGathers")}
        assert gathers and any(v > 0 for v in gathers.values())
    finally:
        events.reset_event_bus()


def test_empty_batch_stays_on_device_lane():
    """An empty batch with devicePartition on writes zero frames, does
    zero host gathers, and attributes to the DEVICE lane in both the
    shuffle counters and the shuffle_write event."""
    from spark_rapids_tpu.columnar.batch import empty_batch
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.exec.basic import InMemoryScanExec
    from spark_rapids_tpu.exec.exchange import HostShuffleExchangeExec
    from spark_rapids_tpu.expr.core import col
    sch = Schema((StructField("k", LONG), StructField("v", LONG)))
    batches = [empty_batch(sch),
               ColumnarBatch.from_pydict({"k": [1, 2, 3],
                                          "v": [4, 5, 6]}, sch)]
    ex = HostShuffleExchangeExec([col("k")],
                                 InMemoryScanExec(batches, sch), 3,
                                 RapidsConf({}))
    g0 = ser.host_gather_calls()
    c0 = shuffle_mgr.counters()
    rows = [r for gen in ex.execute_partitions()
            for b in gen for r in b.to_pylist()]
    assert sorted(rows) == [(1, 4), (2, 5), (3, 6)]
    assert ser.host_gather_calls() == g0
    c1 = shuffle_mgr.counters()
    assert c1["batches"] - c0["batches"] == 2
    assert c1["device_batches"] - c0["device_batches"] == 2
    assert c1["host_batches"] == c0["host_batches"]


def test_profile_report_shuffle_rollup():
    import profile_report
    evs = [
        {"kind": "shuffle_write", "lane": "device", "bytes": 2048,
         "frames": 3, "pack_ns": 1000, "serialize_ns": 2000,
         "io_ns": 500},
        {"kind": "shuffle_write", "lane": "host", "bytes": 1024,
         "frames": 2, "pack_ns": 0, "serialize_ns": 700, "io_ns": 300},
    ]
    report = profile_report.build_report(evs)
    assert "shuffle writes: 2 maps" in report
    assert "5 frames" in report
    assert "1 device-partitioned" in report


def test_bench_shuffle_attribution_delta():
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    try:
        import bench
    finally:
        sys.path.pop(0)
    first = bench.shuffle_attribution()
    for key in ("batches", "device_batches", "host_batches", "frames",
                "bytes", "pack_ns", "serialize_ns", "io_ns",
                "host_gathers"):
        assert key in first
    _hb, dev = _rich_host_batch(40)
    mgr = shuffle_manager()
    handle = mgr.register(2, dev.schema)
    try:
        _device_write(handle, mgr, dev, np.arange(40) % 2)
    finally:
        mgr.unregister(handle)
    delta = bench.shuffle_attribution()
    assert delta["batches"] == 1 and delta["device_batches"] == 1
    assert delta["frames"] == 2 and delta["bytes"] > 0
    assert delta["host_gathers"] == 0


# ---------------------------------------------------------------------------
# kern_bench family + range-key vectorization
# ---------------------------------------------------------------------------

def test_kern_bench_partition_split_quick(tmp_path):
    """Acceptance: the partition_split family runs on CPU via --quick
    and produces a well-formed versioned record."""
    from spark_rapids_tpu.ops.pallas_tier import KERN_BENCH_SCHEMA
    out = tmp_path / "kb.json"
    kern_bench.main(["--quick", "--families", "partition_split",
                     "--out", str(out)])
    doc = json.loads(out.read_text())
    assert doc["schema"] == KERN_BENCH_SCHEMA
    (rec,) = doc["records"]
    assert rec["family"] == "partition_split"
    assert rec["winner"] in ("xla", "pallas")
    assert rec["shape"] == [1 << 11, 4]


def test_host_key_array_matches_object_path():
    """The vectorized numeric/string range-key materialization returns
    exactly what the to_pylist object path returned (None for nulls,
    python floats for f32/f64 incl NaN, utf-8 strings), with and
    without a sampling stride."""
    from spark_rapids_tpu.columnar.column import (Column, StringColumn,
                                                  build_column)
    from spark_rapids_tpu.exec.exchange import _host_key_array
    from spark_rapids_tpu.types import FLOAT

    n = 60
    vals = [None if x % 7 == 0 else float(x) * 1.5 for x in range(n)]
    vals[3] = float("nan")
    fcol = build_column(vals, FLOAT)
    cols, _ = fetch_batch_host(ColumnarBatch(
        [fcol], n, Schema((StructField("f", FLOAT),))))
    got = _host_key_array(cols[0], n)
    expect = np.array(cols[0].to_pylist(n), dtype=object)
    assert len(got) == n
    for g, e in zip(got, expect):
        if e is None or e != e:  # null / NaN
            assert g is None or g != g
            assert (g is None) == (e is None)
        else:
            assert type(g) is type(e) and g == e

    svals = [None if x % 5 == 0 else f"s{x}-å" for x in range(n)]
    scol = build_column(svals, STRING)
    cols, _ = fetch_batch_host(ColumnarBatch(
        [scol], n, Schema((StructField("s", STRING),))))
    got = _host_key_array(cols[0], n)
    assert list(got) == scol.to_pylist(n)

    idx = np.arange(0, n, 7, dtype=np.int64)
    got = _host_key_array(cols[0], n, idx)
    assert list(got) == [scol.to_pylist(n)[i] for i in idx]

    # nested types decline the fast path (caller falls back)
    acol = build_column([[1], None, [2, 3]], ArrayType(LONG))
    assert _host_key_array(acol, 3) is None


def test_range_sort_unaffected_by_device_conf():
    """Range partitioning keeps the host lane (sampled bounds are host
    objects) and still sorts globally with the conf on or off."""
    from spark_rapids_tpu.api.session import TpuSession
    rng = np.random.default_rng(6)
    sch = Schema((StructField("k", DOUBLE), StructField("s", STRING)))
    data = {"k": [None if x % 11 == 0 else float(v) for x, v in
                  enumerate(rng.standard_normal(250))],
            "s": [f"v{x}" for x in range(250)]}
    for extra in ({}, {"spark.rapids.tpu.shuffle.devicePartition.enabled":
                       "false"}):
        sess = TpuSession(dict(
            {"spark.rapids.sql.shuffle.partitions": "3"}, **extra))
        df = sess.from_pydict(data, sch, batch_rows=64)
        got = [r[0] for r in df.sort("k").collect()]
        expect = sorted(data["k"], key=lambda v: (v is not None, v))
        assert got == expect
