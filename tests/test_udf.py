"""Python UDFs via pure_callback (reference GpuPythonUDF /
GpuArrowEvalPythonExec: columnar host round trip)."""

import pytest

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.functions import col
from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.types import DOUBLE, LONG, STRING, Schema, StructField


def test_udf_fixed_width():
    s = TpuSession()
    sch = Schema((StructField("a", LONG), StructField("b", LONG)))
    df = s.from_pydict({"a": [1, 2, None, 4], "b": [10, 20, 30, 40]}, sch)
    f = F.udf(lambda a, b: None if a is None else a * 100 + b,
              return_type=LONG)
    got = [r[0] for r in df.select(f(col("a"), col("b")).alias("r"))
           .collect()]
    assert got == [110, 220, None, 440]


def test_udf_string_input():
    s = TpuSession()
    sch = Schema((StructField("s", STRING),))
    df = s.from_pydict({"s": ["abc", "", None, "héllo"]}, sch)
    f = F.udf(lambda x: None if x is None else len(x), return_type=LONG)
    got = [r[0] for r in df.select(f(col("s")).alias("n")).collect()]
    assert got == [3, 0, None, 5]


def test_udf_composes_with_engine_exprs():
    """UDF output feeds native expressions and aggregates."""
    s = TpuSession()
    sch = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    df = s.from_pydict({"k": [1, 1, 2], "v": [1.5, 2.5, 10.0]}, sch)
    f = F.udf(lambda v: v * 2, return_type=DOUBLE)
    got = sorted(df.with_column("d", f(col("v")))
                 .filter(col("d") > 3.0)
                 .group_by("k").agg((F.sum("d"), "s")).collect())
    assert got == [(1, 5.0), (2, 20.0)]


def test_udf_decorator_form():
    s = TpuSession()
    sch = Schema((StructField("a", LONG),))
    df = s.from_pydict({"a": [3, 4]}, sch)

    @F.udf(return_type=LONG)
    def square(x):
        return x * x

    assert [r[0] for r in df.select(square(col("a")).alias("r"))
            .collect()] == [9, 16]


def test_udf_string_output_rejected():
    f = F.udf(lambda x: "no", return_type=STRING)
    with pytest.raises(AssertionError):
        f(col("a"))  # PythonUDF constructs (and rejects) at call time


def test_udf_string_arg_means_column():
    s = TpuSession()
    sch = Schema((StructField("a", LONG),))
    df = s.from_pydict({"a": [5, 7]}, sch)
    f = F.udf(lambda x: x + 1, return_type=LONG)
    assert [r[0] for r in df.select(f("a").alias("r")).collect()] == [6, 8]


def test_udf_requires_return_type():
    with pytest.raises(TypeError):
        F.udf(lambda x: x)
