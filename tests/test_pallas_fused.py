"""Interpret-mode property tests for the fused Pallas kernel tier
(ISSUE 1): the probe-verify-emit join kernel and the
scan-filter-project-partial-aggregate kernel must match the existing XLA
formulations on randomized inputs — including null masks and
capacity-bucket padding — on every PR, not just TPU rounds.

Bit-exactness contract: everything integer (verified masks, emitted
indices, counts, min/max, integer sums) compares bitwise; float SUMS
compare to 1e-9 relative tolerance because the kernel accumulates
lane-wise then reduces, a different (but per-group-bounded) reduction
order than the XLA masked sweep.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_rapids_tpu.columnar.column import Column, bucket_capacity
from spark_rapids_tpu.ops.join import (
    BuildTable, expand_candidates, int_key_lanes, probe_counts,
    verify_pairs,
)
from spark_rapids_tpu.ops.pallas_join import fused_probe_verify
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField


def _col(np_arr, dtype, null_every=0, capacity=None):
    c = Column.from_numpy(np_arr, dtype,
                          capacity=capacity or bucket_capacity(len(np_arr)))
    if null_every:
        v = np.asarray(c.validity).copy()
        v[::null_every] = False
        c = Column(c.data, jnp.asarray(v), dtype)
    return c


def _xla_probe(build, skey_cols, lo, counts, cand_cap):
    s_idx, b_pos, _ = expand_candidates(lo, counts, cand_cap)
    pair_valid = s_idx >= 0
    b_pos_m = jnp.where(pair_valid, b_pos, -1)
    ver, b_row = verify_pairs(build, skey_cols,
                              jnp.where(pair_valid, s_idx, -1),
                              b_pos_m, pair_valid)
    return ver, s_idx, b_pos, b_row


def _fused_probe(build, skey_cols, lo, counts, cand_cap):
    bk_lanes, bvalid = build.key_lanes
    sk_lanes, svalid = int_key_lanes(skey_cols)
    return fused_probe_verify(lo, counts, bk_lanes, bvalid, sk_lanes,
                              svalid, build.perm, cand_cap,
                              interpret=True)


@pytest.mark.parametrize("seed,nb,ns,dom,null_every", [
    (0, 500, 1500, 200, 7),     # duplicates + nulls
    (1, 64, 200, 1000, 0),      # sparse matches, no nulls
    (2, 300, 300, 5, 3),        # heavy duplication (long bucket ranges)
    (3, 1, 100, 2, 0),          # single-row build
])
def test_fused_probe_long_keys_bit_exact(seed, nb, ns, dom, null_every):
    rng = np.random.default_rng(seed)
    bk = _col(rng.integers(-dom, dom, nb).astype(np.int64), LONG,
              null_every)
    sk = _col(rng.integers(-dom, dom, ns).astype(np.int64), LONG,
              max(0, null_every - 2))
    build = BuildTable.build([bk], [bk], jnp.int32(nb), bk.capacity)
    lo, counts, _ = probe_counts(build, [sk], jnp.int32(ns), sk.capacity)
    cand_cap = bucket_capacity(max(int(jnp.sum(counts)), 1))

    ver_x, s_x, p_x, row_x = _xla_probe(build, [sk], lo, counts, cand_cap)
    ver_p, s_p, p_p, row_p = _fused_probe(build, [sk], lo, counts,
                                          cand_cap)
    assert (np.asarray(ver_x) == np.asarray(ver_p)).all()
    assert (np.asarray(s_x) == np.asarray(s_p)).all()
    pv = np.asarray(s_x) >= 0
    assert (np.asarray(p_x)[pv] == np.asarray(p_p)[pv]).all()
    assert (np.asarray(row_x) == np.asarray(row_p)).all()


def test_fused_probe_multi_column_int_keys():
    """Two-column (LONG, INT) keys: 3 u32 lanes, combined validity."""
    rng = np.random.default_rng(4)
    nb, ns = 400, 900
    bk1 = _col(rng.integers(0, 50, nb).astype(np.int64), LONG, 5)
    bk2 = _col(rng.integers(0, 7, nb).astype(np.int32), INT, 0)
    sk1 = _col(rng.integers(0, 50, ns).astype(np.int64), LONG, 0)
    sk2 = _col(rng.integers(0, 7, ns).astype(np.int32), INT, 9)
    build = BuildTable.build([bk1, bk2], [bk1], jnp.int32(nb),
                             bk1.capacity)
    lo, counts, _ = probe_counts(build, [sk1, sk2], jnp.int32(ns),
                                 sk1.capacity)
    cand_cap = bucket_capacity(max(int(jnp.sum(counts)), 1))
    ver_x, s_x, _, row_x = _xla_probe(build, [sk1, sk2], lo, counts,
                                      cand_cap)
    ver_p, s_p, _, row_p = _fused_probe(build, [sk1, sk2], lo, counts,
                                        cand_cap)
    assert (np.asarray(ver_x) == np.asarray(ver_p)).all()
    assert (np.asarray(s_x) == np.asarray(s_p)).all()
    assert (np.asarray(row_x) == np.asarray(row_p)).all()
    assert int(np.asarray(ver_p).sum()) > 0  # the case exercises matches


def test_fused_probe_no_matches_and_overflowed_bucket():
    """Zero matches; and a cand_cap smaller than the true total (the
    speculative cached-bucket overflow shape) must truncate identically
    to the XLA expand."""
    rng = np.random.default_rng(5)
    bk = _col(np.arange(100, dtype=np.int64), LONG)
    sk = _col((np.arange(300) + 1000).astype(np.int64), LONG)
    build = BuildTable.build([bk], [bk], jnp.int32(100), bk.capacity)
    lo, counts, _ = probe_counts(build, [sk], jnp.int32(300), sk.capacity)
    for cand_cap in (128, 256):
        ver_x, s_x, _, row_x = _xla_probe(build, [sk], lo, counts,
                                          cand_cap)
        ver_p, s_p, _, row_p = _fused_probe(build, [sk], lo, counts,
                                            cand_cap)
        assert (np.asarray(ver_x) == np.asarray(ver_p)).all()
        assert (np.asarray(s_x) == np.asarray(s_p)).all()
        assert (np.asarray(row_x) == np.asarray(row_p)).all()

    # overflow: duplicate-heavy keys, cap below the true candidate count
    bk = _col(np.zeros(64, np.int64), LONG)
    sk = _col(np.zeros(64, np.int64), LONG)
    build = BuildTable.build([bk], [bk], jnp.int32(64), bk.capacity)
    lo, counts, _ = probe_counts(build, [sk], jnp.int32(64), sk.capacity)
    assert int(jnp.sum(counts)) == 64 * 64
    cand_cap = 1024  # < 4096 true candidates
    ver_x, s_x, p_x, _ = _xla_probe(build, [sk], lo, counts, cand_cap)
    ver_p, s_p, p_p, _ = _fused_probe(build, [sk], lo, counts, cand_cap)
    assert (np.asarray(ver_x) == np.asarray(ver_p)).all()
    assert (np.asarray(s_x) == np.asarray(s_p)).all()


def _join_engine(tier, how, null_every=6):
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.core import col
    sess = TpuSession({"spark.rapids.tpu.pallas.fusedTier": tier})
    rng = np.random.default_rng(11)
    no, nl = 180, 500
    o = {"o_key": rng.integers(0, 150, no).tolist(),
         "o_flag": rng.integers(0, 10, no).tolist(),
         "o_name": [f"o{i % 17}" for i in range(no)]}
    l = {"l_key": [int(k) if i % null_every else None
                   for i, k in enumerate(rng.integers(0, 150, nl))],
         "l_val": (rng.random(nl) * 100).round(6).tolist()}
    from spark_rapids_tpu.types import STRING
    o_schema = Schema((StructField("o_key", LONG),
                       StructField("o_flag", INT),
                       StructField("o_name", STRING)))
    l_schema = Schema((StructField("l_key", LONG, True),
                       StructField("l_val", DOUBLE)))
    df_o = sess.from_pydict(o, o_schema)
    df_l = sess.from_pydict(l, l_schema)
    j = df_l.join(df_o, left_on="l_key", right_on="o_key", how=how)
    return sorted(map(tuple, j.collect()),
                  key=lambda r: tuple((x is None, x) for x in r))


@pytest.mark.parametrize("how", ["inner", "left_outer", "left_semi",
                                 "left_anti"])
def test_fused_join_engine_level_matches_xla(how):
    """Whole-join equality with string payload and null keys: fusedTier
    'on' vs 'off' produce identical row multisets."""
    assert _join_engine("off", how) == _join_engine("on", how)


# --- scan-filter-project-partial-aggregate family ----------------------


def _scan_agg_kernel_pair(seed, n, dom, G, null_every=4,
                          float_vals=True):
    """Kernel-level: fused_scan_agg_update vs masked_groupby with ONE
    round and the same bucket count — identical round-0 bucketization,
    so resolved groups and the leftover flag must agree."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.expr.core import BoundReference
    from spark_rapids_tpu.ops.maskedagg import masked_groupby
    from spark_rapids_tpu.ops.pallas_fused import (
        compile_scan_agg_spec, fused_scan_agg_update)

    rng = np.random.default_rng(seed)
    key = _col(rng.integers(0, dom, n).astype(np.int64), LONG, null_every)
    val = _col((rng.random(n) * 100) if float_vals
               else rng.integers(-50, 50, n).astype(np.int64),
               DOUBLE if float_vals else LONG, 3)
    schema = Schema((StructField("k", LONG, True),
                     StructField("v", DOUBLE if float_vals else LONG,
                                 True)))
    batch = ColumnarBatch([key, val], n, schema)
    pre = [BoundReference(0, schema.fields[0].data_type, "k"),
           BoundReference(1, schema.fields[1].data_type, "v")]
    agg_ops = [("sum", 1), ("count", 1), ("min", 1), ("max", 1),
               ("count_star", None)]
    spec = compile_scan_agg_spec([], pre, schema, 1, agg_ops, schema)
    assert spec is not None
    out_cap = bucket_capacity(G)

    fk, fres, fng, fleft = fused_scan_agg_update(spec, batch, G, out_cap,
                                                 interpret=True)
    xk, xres, xng, xleft = masked_groupby(
        [key], [(op, None if s is None else [key, val][s])
                for op, s in agg_ops],
        batch.num_rows, batch.capacity, None, group_slots=G, rounds=1)
    return (fk, fres, int(fng), bool(fleft),
            xk, xres, int(xng), bool(xleft))


@pytest.mark.parametrize("seed,dom,floats", [
    (22, 1, True),
    # same kernel, other domain/float mixes (~24s): nightly tier
    pytest.param(20, 4, True, marks=pytest.mark.slow),
    pytest.param(21, 8, False, marks=pytest.mark.slow),
])
def test_fused_scan_agg_kernel_matches_masked_groupby(seed, dom, floats):
    fk, fres, fng, fleft, xk, xres, xng, xleft = _scan_agg_kernel_pair(
        seed, 1500, dom, G=16, float_vals=floats)
    assert fleft == xleft
    assert fng == xng

    def groups(keys, res, ng):
        kd = np.asarray(keys[0].data)
        kv = np.asarray(keys[0].validity)
        out = {}
        for i in range(ng):
            kval = (int(kd[i]) if kv[i] else None)
            row = []
            for _, (d, v) in res:
                row.append((None if not np.asarray(v)[i]
                            else np.asarray(d)[i]))
            out[kval] = row
        return out

    fg = groups(fk, fres, fng)
    xg = groups(xk, xres, xng)
    assert set(fg) == set(xg)
    for k in fg:
        for a, b in zip(fg[k], xg[k]):
            if a is None or b is None:
                assert a is None and b is None, (k, fg[k], xg[k])
            elif isinstance(a, np.floating) or isinstance(b, np.floating):
                assert abs(float(a) - float(b)) <= \
                    1e-9 * max(abs(float(b)), 1.0), (k, a, b)
            else:
                assert a == b, (k, fg[k], xg[k])  # bitwise for integers


@pytest.mark.slow  # ~6s; high-cardinality fallback nightly like the PR 2 maskedagg move (round-7 budget move)
def test_fused_scan_agg_leftover_on_high_cardinality():
    _, _, _, fleft, _, _, _, xleft = _scan_agg_kernel_pair(
        23, 1200, 300, G=8, float_vals=False)
    assert fleft and xleft


def _agg_engine(tier, n=1500, nkeys=5):
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.aggexprs import Count, Max, Min, Sum
    from spark_rapids_tpu.expr.core import col, lit
    sess = TpuSession({"spark.rapids.tpu.pallas.fusedTier": tier})
    rng = np.random.default_rng(31)
    data = {"flag": rng.integers(0, nkeys, n).tolist(),
            "qty": rng.integers(1, 51, n).tolist(),
            "price": (rng.random(n) * 1000).tolist(),
            "disc": (rng.random(n) * 0.1).tolist()}
    schema = Schema((StructField("flag", INT), StructField("qty", LONG),
                     StructField("price", DOUBLE),
                     StructField("disc", DOUBLE)))
    df = sess.from_pydict(data, schema)
    q = (df.filter(col("qty") <= lit(45))
           .select(col("flag"), col("qty"),
                   (col("price") * (lit(1.0) - col("disc"))).alias("dp"))
           .group_by("flag")
           .agg((Sum(col("qty")), "sq"), (Sum(col("dp")), "sd"),
                (Count(), "cnt"), (Min(col("qty")), "mn"),
                (Max(col("qty")), "mx")))
    return sorted(q.collect())


@pytest.mark.slow  # minute-scale single-core; nightly tier (-m slow)
def test_fused_scan_agg_engine_level_q1_shape():
    """The headline q1 shape (filter -> derived projection -> group-by)
    through the full exec layer: fused tier == XLA tier."""
    a = _agg_engine("off")
    b = _agg_engine("on")
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra[0] == rb[0] and ra[1] == rb[1] and ra[3] == rb[3] \
            and ra[4] == rb[4] and ra[5] == rb[5]
        assert abs(ra[2] - rb[2]) <= 1e-9 * max(abs(ra[2]), 1.0)


def test_fused_scan_agg_unreferenced_varlen_column_falls_back():
    """A STRING source column — even one no expression touches — makes
    the shape ineligible (every source column rides the kernel as row
    tiles); the aggregate must silently keep the XLA tier and stay
    correct with fusedTier=on."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.aggexprs import Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.types import STRING
    sess = TpuSession({"spark.rapids.tpu.pallas.fusedTier": "on"})
    rng = np.random.default_rng(41)
    n = 600
    data = {"k": rng.integers(0, 4, n).tolist(),
            "v": rng.integers(0, 100, n).tolist(),
            "name": [f"s{i % 13}" for i in range(n)]}
    schema = Schema((StructField("k", INT), StructField("v", LONG),
                     StructField("name", STRING)))
    df = sess.from_pydict(data, schema)
    got = dict(df.group_by("k").agg((Sum(col("v")), "s")).collect())
    exp = {}
    for k, v in zip(data["k"], data["v"]):
        exp[k] = exp.get(k, 0) + v
    assert got == exp


def test_fused_scan_agg_short_key_falls_back():
    """BYTE/SHORT group keys are structurally ineligible (their u8/u16
    order lanes don't round-trip the u32 accumulator); the tier must
    fall back to XLA silently, not crash at trace time."""
    from spark_rapids_tpu.api.session import TpuSession
    from spark_rapids_tpu.expr.aggexprs import Sum
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.types import SHORT
    sess = TpuSession({"spark.rapids.tpu.pallas.fusedTier": "on"})
    rng = np.random.default_rng(43)
    n = 500
    data = {"k": rng.integers(0, 5, n).tolist(),
            "v": rng.integers(0, 100, n).tolist()}
    schema = Schema((StructField("k", SHORT), StructField("v", LONG)))
    df = sess.from_pydict(data, schema)
    got = dict(df.group_by("k").agg((Sum(col("v")), "s")).collect())
    exp = {}
    for k, v in zip(data["k"], data["v"]):
        exp[k] = exp.get(k, 0) + v
    assert got == exp


def test_fused_tier_auto_requires_a_measurement(tmp_path):
    """auto + no record -> XLA; auto + recorded Pallas win -> fused."""
    import json

    import jax

    from spark_rapids_tpu.config import RapidsConf, set_active_conf
    from spark_rapids_tpu.ops.pallas_tier import (
        KERN_BENCH_SCHEMA, fused_tier_enabled, shape_bucket)
    set_active_conf(RapidsConf({
        "spark.rapids.tpu.pallas.fusedTier": "auto",
        "spark.rapids.tpu.pallas.fusedTier.benchFile":
            str(tmp_path / "none.json")}))
    assert not fused_tier_enabled("join_probe", (1024, 512))

    rec = {"schema": KERN_BENCH_SCHEMA, "records": [{
        "family": "join_probe", "platform": jax.default_backend(),
        "shape_bucket": list(shape_bucket((1024, 512))),
        "xla_ms": 10.0, "pallas_ms": 2.0}]}
    p = tmp_path / "kern_bench.json"
    p.write_text(json.dumps(rec))
    set_active_conf(RapidsConf({
        "spark.rapids.tpu.pallas.fusedTier": "auto",
        "spark.rapids.tpu.pallas.fusedTier.benchFile": str(p)}))
    assert fused_tier_enabled("join_probe", (1024, 512))
    assert not fused_tier_enabled("join_probe", (4096, 512))
    assert not fused_tier_enabled("scan_agg", (1024, 512))
    set_active_conf(RapidsConf())
