"""Per-query task-metrics roll-up (ISSUE 1 satellite, VERDICT Missing
#8): existing per-exec metrics (semaphore wait, spill, retry counts,
operator times) aggregate into a session-reachable per-query summary —
the standalone analog of GpuTaskMetrics.scala:81-103."""

import numpy as np

from spark_rapids_tpu.api.session import TpuSession
from spark_rapids_tpu.expr.aggexprs import Count, Sum
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.types import DOUBLE, INT, LONG, Schema, StructField


def _session_query(sess):
    rng = np.random.default_rng(0)
    n = 2000
    data = {"k": rng.integers(0, 6, n).tolist(),
            "q": rng.integers(1, 50, n).tolist(),
            "p": (rng.random(n) * 10).tolist()}
    schema = Schema((StructField("k", INT), StructField("q", LONG),
                     StructField("p", DOUBLE)))
    df = sess.from_pydict(data, schema)
    return (df.filter(col("q") <= lit(40))
              .group_by("k").agg((Sum(col("p")), "s"), (Count(), "c")))


def test_summary_reachable_from_session_api():
    sess = TpuSession()
    assert sess.last_query_metrics() is None
    q = _session_query(sess)
    rows = q.collect()
    assert rows
    m = sess.last_query_metrics()
    assert m is not None
    # GpuTaskMetrics-mirrored task globals are present and sane
    for key in ("semWaitTimeNs", "retryCount", "splitAndRetryCount",
                "spilledDeviceBytes", "spilledHostBytes"):
        assert key in m and m[key] >= 0, (key, m.get(key))
    # per-metric roll-ups across the operator tree
    assert m["total.numOutputRows"] >= len(rows)
    assert m["total.numOutputBatches"] >= 1
    assert m["total.computeAggTime"] >= 0
    # per-operator breakdown uses the all_metrics addressing
    # ISSUE 14: the filter+group-by chain executes as a fused stage
    assert any(k.startswith("ops.") and ("AggregateExec" in k
                                         or "CompiledStageExec" in k)
               for k in m)


def test_summary_reports_per_query_deltas():
    """Two queries on one session: each collect's summary reflects ITS
    run, not a lifetime accumulation of retry counters."""
    from spark_rapids_tpu.memory.retry import (
        force_retry_oom, register_task, unregister_task)
    sess = TpuSession()
    q = _session_query(sess)
    register_task(1)
    try:
        force_retry_oom(1)  # inject ONE retryable OOM into query 1
        q.collect()
        m1 = sess.last_query_metrics()
        q.collect()
        m2 = sess.last_query_metrics()
    finally:
        unregister_task()
    assert m1["retryCount"] >= 1
    assert m2["retryCount"] == 0  # the delta resets per query


def test_join_query_rolls_up_join_metrics():
    sess = TpuSession()
    rng = np.random.default_rng(1)
    l_schema = Schema((StructField("k", LONG), StructField("v", DOUBLE)))
    r_schema = Schema((StructField("k2", LONG), StructField("w", LONG)))
    df_l = sess.from_pydict(
        {"k": rng.integers(0, 50, 500).tolist(),
         "v": rng.random(500).tolist()}, l_schema)
    df_r = sess.from_pydict(
        {"k2": rng.integers(0, 50, 200).tolist(),
         "w": rng.integers(0, 9, 200).tolist()}, r_schema)
    out = df_l.join(df_r, left_on="k", right_on="k2").collect()
    m = sess.last_query_metrics()
    assert m["total.numOutputRows"] >= len(out)
    assert "total.joinTime" in m
    assert "total.buildTime" in m
    assert any("HashJoinExec" in k for k in m)
